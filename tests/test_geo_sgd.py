"""Geo-SGD (reference ``distribute_transpiler.py:131`` geo fields + the
geo ``Communicator`` mode): k-step local training with periodic
delta-averaging, redesigned as a gated delta-allreduce
(``transpiler/collective.py`` GeoSGD).

Two oracles:
1. shard_map 2-worker run of the transpiled op tail with a REAL psum —
   diverged workers must converge to the delta-average exactly on sync
   steps and stay untouched on local steps.
2. executor-level config-driven parity: under GSPMD (identity
   collectives) a geo-transpiled program must train bit-identically to
   the untranspiled baseline.
"""

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.executor import Scope, scope_guard, _run_ops_into_env
from paddle_tpu.ops import registry as op_registry


def _build_geo_program(k, nranks):
    fluid.unique_name.switch()
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        w = fluid.layers.create_parameter([4], "float32", name="w")
    cfg = fluid.DistributeTranspilerConfig()
    cfg.geo_sgd_mode = True
    cfg.geo_sgd_need_push_nums = k
    t = fluid.DistributeTranspiler(config=cfg)
    t.transpile(trainer_id=0, program=main, startup_program=startup,
                trainers=nranks)
    return main, startup


class TestGeoDeltaAverageUnderPsum:
    def _run_tail(self, main, w_vals, snap_vals, step_val):
        """Run the transpiled block ops under shard_map(2 workers) with a
        real psum (ctx.collective_axis)."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import Mesh, PartitionSpec as P
        from jax.experimental.shard_map import shard_map

        mesh = Mesh(np.array(jax.devices()[:2]), ("workers",))
        block = main.global_block()

        def per_worker(w, snap, step):
            ctx = op_registry.LoweringContext(mode="train")
            ctx.collective_axis = "workers"
            env = {"w": w[0], "w@GEO_SNAPSHOT": snap[0],
                   "geo_sgd@STEP": step[0]}
            _run_ops_into_env(block, env, ctx)
            return (env["w"][None], env["w@GEO_SNAPSHOT"][None],
                    env["geo_sgd@STEP"][None])

        f = shard_map(
            per_worker, mesh=mesh,
            in_specs=(P("workers"), P("workers"), P("workers")),
            out_specs=(P("workers"), P("workers"), P("workers")))
        return [np.asarray(v) for v in f(
            jnp.asarray(w_vals), jnp.asarray(snap_vals),
            jnp.asarray(step_val))]

    def test_sync_and_local_steps(self):
        main, _ = _build_geo_program(k=2, nranks=2)
        snap = np.tile(np.arange(4, dtype="float32"), (2, 1))  # both [0,1,2,3]
        w = snap + np.array([[1.0], [3.0]], "float32")  # deltas -1 and -3

        # counter 0 → increments to 1 → 1 % 2 != 0 → LOCAL step: untouched
        w1, s1, st1 = self._run_tail(main, w, snap, np.zeros((2, 1), "f4"))
        np.testing.assert_allclose(w1, w)
        np.testing.assert_allclose(s1, snap)

        # counter 1 → increments to 2 → sync: delta=snap-w per worker
        # (-1, -3), mean -2 → w = snap + 2 on BOTH; snapshot = new w
        w2, s2, st2 = self._run_tail(main, w1, s1, st1)
        np.testing.assert_allclose(w2, snap + 2.0)
        np.testing.assert_allclose(s2, w2)


class TestGeoConfigParity:
    def _train(self, geo, steps=5):
        fluid.unique_name.switch()
        rng = np.random.RandomState(0)
        xs = rng.randn(steps, 8, 4).astype("float32")
        ys = rng.randn(steps, 8, 1).astype("float32")
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.layers.data("x", shape=[8, 4], dtype="float32",
                                  append_batch_size=False)
            y = fluid.layers.data("y", shape=[8, 1], dtype="float32",
                                  append_batch_size=False)
            pred = fluid.layers.fc(x, size=1,
                                   param_attr=fluid.ParamAttr(name="fc.w"))
            loss = fluid.layers.mean(
                fluid.layers.square_error_cost(pred, y))
            fluid.optimizer.SGD(0.1).minimize(loss)
        if geo:
            cfg = fluid.DistributeTranspilerConfig()
            cfg.geo_sgd_mode = True
            cfg.geo_sgd_need_push_nums = 2
            t = fluid.DistributeTranspiler(config=cfg)
            t.transpile(trainer_id=0, program=main,
                        startup_program=startup, trainers=2)
        exe = fluid.Executor(fluid.CPUPlace())
        losses = []
        with scope_guard(Scope()):
            exe.run(startup)
            for i in range(steps):
                (lv,) = exe.run(main, feed={"x": xs[i], "y": ys[i]},
                                fetch_list=[loss])
                losses.append(float(np.asarray(lv).reshape(())))
        return losses

    def test_identity_collective_parity(self):
        """Single-process GSPMD: the allreduce is identity, so geo must
        reproduce baseline training exactly (gated ops must not perturb
        params on either local or sync steps)."""
        base = self._train(geo=False)
        geo = self._train(geo=True)
        np.testing.assert_allclose(geo, base, rtol=1e-6)
