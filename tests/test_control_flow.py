"""Control flow: While → lax.while_loop, ConditionalBlock → lax.cond,
StaticRNN → lax.scan, tensor arrays (reference tests:
unittests/test_while_op.py, test_conditional_block.py, test_recurrent_op.py,
test_lod_tensor_array_ops.py)."""

import numpy as np

import paddle_tpu as fluid
from paddle_tpu.executor import Scope, scope_guard


def test_while_sum_of_squares():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        i = fluid.layers.fill_constant([1], "float32", 0.0)
        acc = fluid.layers.fill_constant([1], "float32", 0.0)
        limit = fluid.layers.fill_constant([1], "float32", 10.0)
        cond = fluid.layers.less_than(i, limit)
        w = fluid.layers.While(cond)
        with w.block():
            sq = fluid.layers.elementwise_mul(i, i)
            fluid.layers.assign(
                fluid.layers.elementwise_add(acc, sq), output=acc
            )
            fluid.layers.increment(i, value=1.0, in_place=True)
            fluid.layers.less_than(i, limit, cond=cond)
    exe = fluid.Executor(fluid.CPUPlace())
    out = exe.run(main, fetch_list=[acc, i])
    assert float(out[0][0]) == sum(k * k for k in range(10))
    assert float(out[1][0]) == 10.0


def test_while_with_tensor_array():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        i = fluid.layers.fill_constant([1], "int32", 0)
        limit = fluid.layers.fill_constant([1], "int32", 5)
        x = fluid.layers.fill_constant([3], "float32", 2.0)
        arr = fluid.layers.array_write(x, i, capacity=8)
        cond = fluid.layers.less_than(i, limit)
        w = fluid.layers.While(cond)
        with w.block():
            val = fluid.layers.array_read(arr, i)
            doubled = fluid.layers.scale(val, scale=2.0)
            fluid.layers.increment(i, value=1, in_place=True)
            fluid.layers.array_write(doubled, i, array=arr)
            fluid.layers.less_than(i, limit, cond=cond)
        final = fluid.layers.array_read(arr, i)
        n = fluid.layers.array_length(arr)
    exe = fluid.Executor(fluid.CPUPlace())
    out = exe.run(main, fetch_list=[final, n])
    np.testing.assert_allclose(out[0], 2.0 * 2 ** 5)
    assert int(out[1][0]) == 6


def test_conditional_block_true_false():
    for flag, expect in ((1.0, 5.0), (-1.0, 0.0)):
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.layers.data("x", shape=[1], dtype="float32",
                                  append_batch_size=False)
            zero = fluid.layers.fill_constant([1], "float32", 0.0)
            out = fluid.layers.fill_constant([1], "float32", 0.0)
            pred = fluid.layers.greater_than(x, zero)
            cb = fluid.layers.ConditionalBlock([pred])
            with cb.block():
                fluid.layers.assign(
                    fluid.layers.fill_constant([1], "float32", 5.0),
                    output=out,
                )
        exe = fluid.Executor(fluid.CPUPlace())
        res = exe.run(main, feed={"x": np.array([flag], "float32")},
                      fetch_list=[out])[0]
        assert float(res[0]) == expect, (flag, res)


def test_switch_lr_band():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        step = fluid.layers.data("step", shape=[1], dtype="float32",
                                 append_batch_size=False)
        lr = fluid.layers.fill_constant([1], "float32", 0.0)
        b1 = fluid.layers.fill_constant([1], "float32", 10.0)
        with fluid.layers.Switch() as switch:
            with switch.case(fluid.layers.less_than(step, b1)):
                fluid.layers.assign(
                    fluid.layers.fill_constant([1], "float32", 0.1),
                    output=lr)
            with switch.default():
                fluid.layers.assign(
                    fluid.layers.fill_constant([1], "float32", 0.01),
                    output=lr)
    exe = fluid.Executor(fluid.CPUPlace())
    lo = exe.run(main, feed={"step": np.array([5.0], "float32")},
                 fetch_list=[lr])[0]
    hi = exe.run(main, feed={"step": np.array([50.0], "float32")},
                 fetch_list=[lr])[0]
    assert abs(float(lo[0]) - 0.1) < 1e-6
    assert abs(float(hi[0]) - 0.01) < 1e-6


def test_static_rnn_cumsum():
    """StaticRNN accumulating inputs = running sum over time."""
    T, B, D = 4, 2, 3
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[T, B, D], dtype="float32",
                              append_batch_size=False)
        h0 = fluid.layers.fill_constant([B, D], "float32", 0.0)
        rnn = fluid.layers.StaticRNN()
        with rnn.step():
            xt = rnn.step_input(x)
            mem = rnn.memory(init=h0)
            s = fluid.layers.elementwise_add(mem, xt)
            rnn.update_memory(mem, s)
            rnn.step_output(s)
        out = rnn()
    exe = fluid.Executor(fluid.CPUPlace())
    xv = np.random.RandomState(0).rand(T, B, D).astype("float32")
    res = exe.run(main, feed={"x": xv}, fetch_list=[out])[0]
    np.testing.assert_allclose(res, np.cumsum(xv, axis=0), rtol=1e-5)


def test_while_inside_jit_is_compiled_loop():
    """A 1000-iteration while must execute fast (compiled, not
    op-by-op host dispatch)."""
    import time

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        i = fluid.layers.fill_constant([1], "float32", 0.0)
        limit = fluid.layers.fill_constant([1], "float32", 1000.0)
        cond = fluid.layers.less_than(i, limit)
        w = fluid.layers.While(cond)
        with w.block():
            fluid.layers.increment(i, value=1.0, in_place=True)
            fluid.layers.less_than(i, limit, cond=cond)
    exe = fluid.Executor(fluid.CPUPlace())
    out = exe.run(main, fetch_list=[i])  # includes compile
    t0 = time.perf_counter()
    out = exe.run(main, fetch_list=[i])
    dt = time.perf_counter() - t0
    assert float(out[0][0]) == 1000.0
    assert dt < 0.5, "while loop appears to be interpreted (%.3fs)" % dt


def test_ifelse_per_row_branches():
    """IfElse (reference control_flow.py:1564): per-row branch selection;
    TPU-static masked-merge semantics (both branches on the full batch,
    per-row select at merge)."""
    import paddle_tpu as fluid
    from paddle_tpu.executor import Scope, scope_guard
    import numpy as np

    fluid.unique_name.switch()
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[3], dtype="float32")
        zero = fluid.layers.fill_constant([1], "float32", 0.0)
        row_sum = fluid.layers.reduce_sum(x, dim=[1], keep_dim=True)
        cond = fluid.layers.greater_than(row_sum, zero)  # [B,1] bool
        ie = fluid.layers.IfElse(cond)
        with ie.true_block():
            xt = ie.input(x)
            ie.output(fluid.layers.scale(xt, 10.0))
        with ie.false_block():
            xf = ie.input(x)
            ie.output(fluid.layers.scale(xf, -1.0))
        (merged,) = ie()
        total = fluid.layers.reduce_sum(merged)
    exe = fluid.Executor(fluid.CPUPlace())
    xv = np.array([[1, 1, 1], [-1, -1, -1], [2, 0, 0]], "float32")
    with scope_guard(Scope()):
        out, tot = exe.run(main, feed={"x": xv}, fetch_list=[merged, total])
    exp = np.where(xv.sum(1, keepdims=True) > 0, xv * 10.0, -xv)
    np.testing.assert_allclose(out, exp, rtol=1e-6)
    # grads flow through the select
    fluid.unique_name.switch()
    main2, startup2 = fluid.Program(), fluid.Program()
    with fluid.program_guard(main2, startup2):
        x = fluid.layers.data("x", shape=[3], dtype="float32",
                              stop_gradient=False)
        zero = fluid.layers.fill_constant([1], "float32", 0.0)
        cond = fluid.layers.greater_than(
            fluid.layers.reduce_sum(x, dim=[1], keep_dim=True), zero)
        ie = fluid.layers.IfElse(cond)
        with ie.true_block():
            ie.output(fluid.layers.scale(ie.input(x), 10.0))
        with ie.false_block():
            ie.output(fluid.layers.scale(ie.input(x), -1.0))
        (merged,) = ie()
        loss = fluid.layers.reduce_sum(merged)
        (gx,) = fluid.backward.gradients(loss, x)
    with scope_guard(Scope()):
        gv = exe.run(main2, feed={"x": xv}, fetch_list=[gx])[0]
    exp_g = np.where(xv.sum(1, keepdims=True) > 0, 10.0, -1.0)
    np.testing.assert_allclose(gv, np.broadcast_to(exp_g, xv.shape),
                               rtol=1e-6)
