"""CTR models + MultiSlot dataset pipeline + train_from_dataset
(reference tests: unittests/test_dataset.py, dist_ctr.py model)."""

import os
import tempfile

import numpy as np

import paddle_tpu as fluid
from paddle_tpu.dataset import DatasetFactory
from paddle_tpu.executor import Scope, scope_guard
from paddle_tpu.models import ctr


def _write_multislot_file(path, n_lines, num_slots, slot_len, dense_dim,
                          rng):
    """label(1) + slots + dense, MultiSlot text format."""
    with open(path, "w") as f:
        for _ in range(n_lines):
            parts = []
            y = rng.randint(0, 2)
            parts.append("1 %d" % y)
            for _ in range(num_slots):
                n = rng.randint(1, slot_len + 1)
                ids = rng.randint(1, 1000, n)
                parts.append(str(n) + " " + " ".join(map(str, ids)))
            dense = rng.rand(dense_dim)
            parts.append(
                str(dense_dim) + " " + " ".join("%.4f" % v for v in dense)
            )
            f.write(" ".join(parts) + "\n")


def test_wide_deep_trains():
    main, startup, feeds, loss, prob = ctr.build(
        "wide_deep", num_slots=4, slot_len=3, vocab=1000, lr=3e-3
    )
    rng = np.random.RandomState(0)
    exe = fluid.Executor(fluid.CPUPlace())
    with scope_guard(Scope()):
        exe.run(startup)
        losses = []
        for _ in range(40):
            feed = {
                "slot_%d" % i: rng.randint(1, 1000, (16, 3)).astype("int64")
                for i in range(4)
            }
            # learnable signal: label depends on slot_0's first id parity
            feed["label"] = (feed["slot_0"][:, :1] % 2).astype("int64")
            feed["dense"] = rng.rand(16, 13).astype("float32")
            lv = exe.run(main, feed=feed, fetch_list=[loss])[0]
            losses.append(float(lv[0]))
    assert np.isfinite(losses).all()
    assert np.mean(losses[-10:]) < np.mean(losses[:10])


def test_deepfm_trains():
    main, startup, feeds, loss, prob = ctr.build(
        "deepfm", num_slots=4, slot_len=3, vocab=1000, lr=3e-3
    )
    rng = np.random.RandomState(1)
    exe = fluid.Executor(fluid.CPUPlace())
    with scope_guard(Scope()):
        exe.run(startup)
        losses = []
        for _ in range(30):
            feed = {
                "slot_%d" % i: rng.randint(1, 1000, (16, 3)).astype("int64")
                for i in range(4)
            }
            feed["label"] = (feed["slot_0"][:, :1] % 2).astype("int64")
            lv = exe.run(main, feed=feed, fetch_list=[loss])[0]
            losses.append(float(lv[0]))
    assert np.isfinite(losses).all()
    assert np.mean(losses[-10:]) < np.mean(losses[:10])


def test_multislot_dataset_parse_and_train_from_dataset():
    rng = np.random.RandomState(2)
    tmpd = tempfile.mkdtemp()
    files = []
    for k in range(2):
        p = os.path.join(tmpd, "part-%d" % k)
        _write_multislot_file(p, 40, num_slots=2, slot_len=3, dense_dim=4,
                              rng=rng)
        files.append(p)

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        label = fluid.layers.data("label", shape=[1], dtype="int64")
        slots = [
            fluid.layers.data("slot_%d" % i, shape=[3], dtype="int64")
            for i in range(2)
        ]
        dense = fluid.layers.data("dense", shape=[4], dtype="float32")
        embs = [
            fluid.layers.reduce_sum(
                fluid.layers.embedding(s, size=[1000, 8], padding_idx=0),
                dim=1,
            )
            for s in slots
        ]
        x = fluid.layers.concat(embs + [dense], axis=1)
        logit = fluid.layers.fc(x, size=1)
        loss = fluid.layers.mean(
            fluid.layers.sigmoid_cross_entropy_with_logits(
                logit, fluid.layers.cast(label, "float32")
            )
        )
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)

    dataset = DatasetFactory().create_dataset("InMemoryDataset")
    dataset.set_use_var([label] + slots + [dense])
    dataset.set_batch_size(8)
    dataset.set_filelist(files)
    dataset.load_into_memory()
    assert dataset.get_memory_data_size() == 80
    dataset.local_shuffle()

    exe = fluid.Executor(fluid.CPUPlace())
    with scope_guard(Scope()):
        exe.run(startup)
        results = exe.train_from_dataset(
            program=main, dataset=dataset, fetch_list=[loss],
            print_period=100,
        )
    assert len(results) == 10  # 80 examples / batch 8
    assert all(np.isfinite(r[0]).all() for r in results)


def test_queue_dataset_streams():
    rng = np.random.RandomState(3)
    tmpd = tempfile.mkdtemp()
    p = os.path.join(tmpd, "part-0")
    _write_multislot_file(p, 10, num_slots=1, slot_len=2, dense_dim=2,
                          rng=rng)
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        label = fluid.layers.data("label", shape=[1], dtype="int64")
        slot = fluid.layers.data("slot", shape=[2], dtype="int64")
        dense = fluid.layers.data("dense", shape=[2], dtype="float32")
    ds = DatasetFactory().create_dataset("QueueDataset")
    ds.set_use_var([label, slot, dense])
    ds.set_batch_size(4)
    ds.set_filelist([p])
    batches = list(ds.batch_iterator())
    assert len(batches) == 3  # 4+4+2
    assert batches[0]["slot"].shape == (4, 2)
    assert batches[-1]["dense"].shape == (2, 2)


def test_trainer_factory_and_desc_wiring():
    """TrainerDesc/DeviceWorker config surface (reference trainer_desc.py
    + device_worker.py + trainer_factory.py), recorded by
    run_from_dataset."""
    from paddle_tpu.trainer_desc import TrainerFactory, MultiTrainer
    from paddle_tpu.device_worker import Hogwild, Section

    t = TrainerFactory()._create_trainer({})
    assert isinstance(t, MultiTrainer)
    assert isinstance(t._device_worker, Hogwild)
    t2 = TrainerFactory()._create_trainer(
        {"trainer": "PipelineTrainer", "device_worker": "Section"})
    assert isinstance(t2._device_worker, Section)
    t2._set_fetch_var_and_info(["loss"], ["l"], 5)
    assert t2._print_period == 5 and t2._fetch_info == ["l"]
