"""Program/Block/Operator/Variable construction and shape inference
(reference tests: unittests/test_program.py, test_variable.py,
test_operator_desc.py)."""

import numpy as np
import pytest

import paddle_tpu as fluid


def test_program_blocks():
    prog = fluid.Program()
    assert prog.num_blocks == 1
    b = prog.global_block()
    v = b.create_var(name="x", shape=[2, 3], dtype="float32")
    assert b.var("x") is v
    assert v.shape == (2, 3)
    assert v.dtype == "float32"


def test_program_guard_switches_default():
    prog = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(prog, startup):
        assert fluid.default_main_program() is prog
        assert fluid.default_startup_program() is startup
    assert fluid.default_main_program() is not prog


def test_shape_inference_static():
    prog = fluid.Program()
    with fluid.program_guard(prog, fluid.Program()):
        x = fluid.layers.data("x", shape=[8, 16], dtype="float32",
                              append_batch_size=False)
        y = fluid.layers.fc(x, size=4)
        assert y.shape == (8, 4)
        s = fluid.layers.softmax(y)
        assert s.shape == (8, 4)


def test_shape_inference_batch_dim():
    prog = fluid.Program()
    with fluid.program_guard(prog, fluid.Program()):
        x = fluid.layers.data("x", shape=[16], dtype="float32")
        assert x.shape == (-1, 16)
        y = fluid.layers.fc(x, size=4)
        # -1 batch dim propagates through mul/elementwise_add
        assert y.shape == (-1, 4)


def test_clone_for_test_flips_is_test():
    prog = fluid.Program()
    with fluid.program_guard(prog, fluid.Program()):
        x = fluid.layers.data("x", shape=[4], dtype="float32")
        y = fluid.layers.dropout(x, dropout_prob=0.5)
    test_prog = prog.clone(for_test=True)
    dropout_ops = [op for op in test_prog.global_block().ops
                   if op.type == "dropout"]
    assert dropout_ops and dropout_ops[0].attrs["is_test"] is True
    # original untouched
    assert not prog.global_block().ops[-1].attrs.get("is_test", False)


def test_prune():
    prog = fluid.Program()
    with fluid.program_guard(prog, fluid.Program()):
        x = fluid.layers.data("x", shape=[4], dtype="float32")
        y1 = fluid.layers.fc(x, size=3)
        y2 = fluid.layers.fc(x, size=5)
    pruned = prog._prune(["x"], [y1])
    kept_outputs = {
        n for op in pruned.global_block().ops for n in op.output_arg_names
    }
    assert y1.name in kept_outputs
    assert y2.name not in kept_outputs


def test_operator_io_lists():
    prog = fluid.Program()
    b = prog.global_block()
    b.create_var(name="a", shape=[2], dtype="float32")
    b.create_var(name="c", shape=[2], dtype="float32")
    op = b.append_op(
        type="sum", inputs={"X": ["a", "a"]}, outputs={"Out": ["c"]}
    )
    assert op.input("X") == ["a", "a"]
    assert op.output("Out") == ["c"]
    assert set(op.input_arg_names) == {"a"}


def test_serialization_roundtrip():
    prog = fluid.Program()
    with fluid.program_guard(prog, fluid.Program()):
        x = fluid.layers.data("x", shape=[4], dtype="float32")
        y = fluid.layers.fc(x, size=3, act="relu")
    d = prog.to_proto_dict()
    prog2 = fluid.Program.parse_from_proto_dict(d)
    assert [op.type for op in prog2.global_block().ops] == [
        op.type for op in prog.global_block().ops
    ]
    assert prog2.global_block().var(y.name).shape == y.shape
