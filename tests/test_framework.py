"""Program/Block/Operator/Variable construction and shape inference
(reference tests: unittests/test_program.py, test_variable.py,
test_operator_desc.py)."""

import numpy as np
import pytest

import paddle_tpu as fluid


def test_program_blocks():
    prog = fluid.Program()
    assert prog.num_blocks == 1
    b = prog.global_block()
    v = b.create_var(name="x", shape=[2, 3], dtype="float32")
    assert b.var("x") is v
    assert v.shape == (2, 3)
    assert v.dtype == "float32"


def test_program_guard_switches_default():
    prog = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(prog, startup):
        assert fluid.default_main_program() is prog
        assert fluid.default_startup_program() is startup
    assert fluid.default_main_program() is not prog


def test_shape_inference_static():
    prog = fluid.Program()
    with fluid.program_guard(prog, fluid.Program()):
        x = fluid.layers.data("x", shape=[8, 16], dtype="float32",
                              append_batch_size=False)
        y = fluid.layers.fc(x, size=4)
        assert y.shape == (8, 4)
        s = fluid.layers.softmax(y)
        assert s.shape == (8, 4)


def test_shape_inference_batch_dim():
    prog = fluid.Program()
    with fluid.program_guard(prog, fluid.Program()):
        x = fluid.layers.data("x", shape=[16], dtype="float32")
        assert x.shape == (-1, 16)
        y = fluid.layers.fc(x, size=4)
        # -1 batch dim propagates through mul/elementwise_add
        assert y.shape == (-1, 4)


def test_clone_for_test_flips_is_test():
    prog = fluid.Program()
    with fluid.program_guard(prog, fluid.Program()):
        x = fluid.layers.data("x", shape=[4], dtype="float32")
        y = fluid.layers.dropout(x, dropout_prob=0.5)
    test_prog = prog.clone(for_test=True)
    dropout_ops = [op for op in test_prog.global_block().ops
                   if op.type == "dropout"]
    assert dropout_ops and dropout_ops[0].attrs["is_test"] is True
    # original untouched
    assert not prog.global_block().ops[-1].attrs.get("is_test", False)


def test_prune():
    prog = fluid.Program()
    with fluid.program_guard(prog, fluid.Program()):
        x = fluid.layers.data("x", shape=[4], dtype="float32")
        y1 = fluid.layers.fc(x, size=3)
        y2 = fluid.layers.fc(x, size=5)
    pruned = prog._prune(["x"], [y1])
    kept_outputs = {
        n for op in pruned.global_block().ops for n in op.output_arg_names
    }
    assert y1.name in kept_outputs
    assert y2.name not in kept_outputs


def test_operator_io_lists():
    prog = fluid.Program()
    b = prog.global_block()
    b.create_var(name="a", shape=[2], dtype="float32")
    b.create_var(name="c", shape=[2], dtype="float32")
    op = b.append_op(
        type="sum", inputs={"X": ["a", "a"]}, outputs={"Out": ["c"]}
    )
    assert op.input("X") == ["a", "a"]
    assert op.output("Out") == ["c"]
    assert set(op.input_arg_names) == {"a"}


def test_serialization_roundtrip():
    prog = fluid.Program()
    with fluid.program_guard(prog, fluid.Program()):
        x = fluid.layers.data("x", shape=[4], dtype="float32")
        y = fluid.layers.fc(x, size=3, act="relu")
    d = prog.to_proto_dict()
    prog2 = fluid.Program.parse_from_proto_dict(d)
    assert [op.type for op in prog2.global_block().ops] == [
        op.type for op in prog.global_block().ops
    ]
    assert prog2.global_block().var(y.name).shape == y.shape


def test_clone_for_test_after_minimize_prunes_training_ops():
    """Reference clone(for_test=True) drops ops carrying the Backward/
    Optimize role (framework.py clone -> _inference_optimize), so a
    POST-minimize clone is a pure eval program — without the prune an
    'eval' run would keep training and donate the parameter buffers
    (found via examples/slim_compress.py)."""
    import numpy as np

    from paddle_tpu.executor import Scope, scope_guard

    fluid.unique_name.switch()
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[8], dtype="float32")
        y = fluid.layers.data("y", shape=[1], dtype="int64")
        h = fluid.layers.fc(x, size=16, act="relu")
        logits = fluid.layers.fc(h, size=3)
        loss = fluid.layers.mean(
            fluid.layers.softmax_with_cross_entropy(logits, y))
        fluid.optimizer.Adam(learning_rate=1e-3).minimize(loss)
    test = main.clone(for_test=True)
    types = [op.type for op in test.global_block().ops]
    assert not any(t.endswith("_grad") for t in types), types
    assert "adam" not in types
    # the train program is untouched
    assert any(op.type == "adam" for op in main.global_block().ops)
    # eval really evaluates: params identical before/after, loss equal
    # across two runs on the same batch
    sc = Scope()
    with scope_guard(sc):
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        feed = {"x": np.random.RandomState(0).randn(4, 8).astype(
            "float32"), "y": np.zeros((4, 1), "int64")}
        l1 = exe.run(test, feed=feed, fetch_list=[loss])[0]
        l2 = exe.run(test, feed=feed, fetch_list=[loss])[0]
    np.testing.assert_allclose(np.asarray(l1), np.asarray(l2))


def test_clone_for_test_does_not_advance_lr_counter():
    """Eval batches must not advance @LR_DECAY_COUNTER@: the scheduler's
    increment op carries the LRSched role and is pruned by
    clone(for_test) — otherwise interleaved eval decays the training lr
    faster the more eval batches run."""
    import numpy as np

    from paddle_tpu.executor import Scope, scope_guard

    fluid.unique_name.switch()
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[4], dtype="float32")
        y = fluid.layers.data("y", shape=[1], dtype="float32")
        h = fluid.layers.fc(x, size=4)
        loss = fluid.layers.reduce_mean(fluid.layers.square(h - y))
        lr = fluid.layers.exponential_decay(
            learning_rate=0.1, decay_steps=1, decay_rate=0.5)
        fluid.optimizer.SGD(learning_rate=lr).minimize(loss)
    test = main.clone(for_test=True)
    assert not any(op.type == "increment"
                   for op in test.global_block().ops)
    sc = Scope()
    with scope_guard(sc):
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        feed = {"x": np.zeros((2, 4), "float32"),
                "y": np.zeros((2, 1), "float32")}
        exe.run(main, feed=feed, fetch_list=[])  # 1 train step
        c1 = float(np.asarray(sc.get("@LR_DECAY_COUNTER@")).reshape(-1)[0])
        for _ in range(3):  # eval must not move the counter
            exe.run(test, feed=feed, fetch_list=[loss])
        c2 = float(np.asarray(sc.get("@LR_DECAY_COUNTER@")).reshape(-1)[0])
    assert c1 == c2 == 1.0, (c1, c2)
