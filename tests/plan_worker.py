"""Subprocess helper for the planner determinism/acceptance tests:
build a named program set, run ``auto_transpile``, and print one JSON
line — the canonical plan bytes' sha256, the chosen plan, the search
wall time, and the hand-written DP baseline's priced step time (so the
parent asserts planner <= hand without a second build).

    python plan_worker.py {mlp|bert|bert_base} CHIPS
"""

import hashlib
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
for p in (REPO, os.path.dirname(os.path.abspath(__file__))):
    if p not in sys.path:
        sys.path.insert(0, p)


def main():
    which, chips = sys.argv[1], int(sys.argv[2])
    import paddle_tpu as fluid
    from paddle_tpu.parallel.planner import (ClusterSpec, auto_transpile,
                                             price_worker_set)
    from paddle_tpu.transpiler.collective import GradAllReduce

    fluid.unique_name.switch()
    if which == "mlp":
        import dist_model

        main_prog, startup, loss, _feeds = dist_model.build_model()
        loss_name = loss.name
    elif which == "bert":
        import dist_model

        main_prog, startup, loss_name = dist_model.build_example_program(
            "bert")
    elif which == "bert_base":
        from paddle_tpu.models import bert

        main_prog, startup, _feeds, loss = bert.build_pretrain(
            bert.BERT_BASE, seq_len=128, train=True)
        loss_name = loss.name
    else:
        raise SystemExit("unknown program %r" % which)

    spec = ClusterSpec(chips=chips)
    t0 = time.time()
    result = auto_transpile(main_prog, spec, startup_program=startup,
                            targets=[loss_name])
    search_s = time.time() - t0

    # the hand-written DP baseline, priced by the same meter
    fluid.unique_name.switch()
    if which == "mlp":
        import dist_model

        hand, hstartup, hloss, _ = dist_model.build_model()
    elif which == "bert":
        import dist_model

        hand, hstartup, _ = dist_model.build_example_program("bert")
    else:
        from paddle_tpu.models import bert

        hand, hstartup, _feeds, hloss = bert.build_pretrain(
            bert.BERT_BASE, seq_len=128, train=True)
    GradAllReduce().transpile(program=hand, startup_program=hstartup,
                              rank=0, nranks=chips)
    hand._num_trainers = chips
    _, hand_price = price_worker_set([hand], spec, targets=[loss_name])

    js = result.to_json()
    print(json.dumps({
        "sha": hashlib.sha256(js.encode()).hexdigest(),
        "plan": result.plan.candidate.describe(),
        "step_ms": result.plan.price.step_ms,
        "hand_dp_step_ms": hand_price.step_ms,
        "deadlock_free": result.deadlock_free,
        "search_s": round(search_s, 2),
    }))


if __name__ == "__main__":
    main()
