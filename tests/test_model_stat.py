"""contrib.model_stat.summary (reference
``contrib/model_stat.py``: per-op TYPE/INPUT/OUTPUT/PARAMs/FLOPs table
+ totals)."""

import numpy as np

import paddle_tpu as fluid
from paddle_tpu.contrib.model_stat import summary


def test_summary_counts_params_and_flops(capsys):
    fluid.unique_name.switch()
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        img = fluid.layers.data("img", shape=[3, 16, 16], dtype="float32")
        conv = fluid.layers.conv2d(img, num_filters=8, filter_size=3,
                                   padding=1, act="relu")
        pool = fluid.layers.pool2d(conv, pool_size=2, pool_stride=2)
        fc = fluid.layers.fc(pool, size=10)
    rows = summary(main)
    out = capsys.readouterr().out
    types = [r[1] for r in rows]
    assert "conv2d" in types or "depthwise_conv2d" in types
    assert "relu" in types
    assert "pool2d" in types
    assert "mul" in types
    # the layer decomposes conv into conv + elementwise_add(bias), so
    # the conv op carries the filter only (8*3*3*3); the bias param (8)
    # rides the elementwise_add row
    conv_row = next(r for r in rows
                    if r[1] in ("conv2d", "depthwise_conv2d"))
    assert conv_row[4] == 8 * 3 * 3 * 3
    add_rows = [r for r in rows if r[1] == "elementwise_add"]
    assert any(r[4] == 8 for r in add_rows)
    mul_row = next(r for r in rows if r[1] == "mul")
    assert mul_row[4] == 8 * 8 * 8 * 10
    # conv FLOPs: 2*Hout*Wout*Cout*(Cin*kh*kw)
    assert conv_row[5] == 2 * 16 * 16 * 8 * (3 * 3 * 3)
    total_params = sum(r[4] for r in rows)
    assert "Total PARAMs: %d" % total_params in out
    assert "Total FLOPs:" in out
    assert "| conv2d |" in out.replace("  ", " ") or "conv2d" in out
