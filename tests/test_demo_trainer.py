"""C++-only train demo (reference: ``paddle/fluid/train/demo/
demo_trainer.cc`` + its run.sh build): serialize a fit-a-line training
program, compile the C++ driver against libpython, run it with NO Python
script, and check it trains."""

import os
import subprocess
import sys
import sysconfig

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import proto

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SRC = os.path.join(REPO, "paddle_tpu", "native", "src", "demo_trainer.cc")


def _build_binary(out_path):
    import shutil

    if shutil.which("g++") is None:
        pytest.skip("g++ unavailable")
    inc = sysconfig.get_paths()["include"]
    libdir = sysconfig.get_config_var("LIBDIR")
    ver = "python%d.%d" % sys.version_info[:2]
    if not os.path.exists(os.path.join(inc, "Python.h")):
        pytest.skip("Python.h unavailable")
    cmd = ["g++", "-O2", "-std=c++14", SRC, "-I", inc,
           "-L", libdir, "-l" + ver, "-Wl,-rpath," + libdir,
           "-o", out_path]
    res = subprocess.run(cmd, capture_output=True, text=True, timeout=180)
    # toolchain present → a compile failure is a REGRESSION, not a skip
    assert res.returncode == 0, res.stderr[-600:]
    return out_path


class TestDemoTrainer:
    def test_cpp_binary_trains_serialized_program(self, tmp_path):
        # 1. build + serialize fit-a-line (the reference demo's model)
        fluid.unique_name.switch()
        main, startup = fluid.Program(), fluid.Program()
        main.random_seed = startup.random_seed = 1
        with fluid.program_guard(main, startup):
            x = fluid.layers.data("x", shape=[13], dtype="float32")
            y = fluid.layers.data("y", shape=[1], dtype="float32")
            pred = fluid.layers.fc(x, size=1)
            loss = fluid.layers.mean(
                fluid.layers.square_error_cost(pred, y))
            fluid.optimizer.SGD(learning_rate=0.01).minimize(loss)
        proto.save_program(main, str(tmp_path / "main_program"))
        proto.save_program(startup, str(tmp_path / "startup_program"))

        # 2. compile the C++ driver
        binary = _build_binary(str(tmp_path / "demo_trainer"))

        # 3. run it — no Python script involved; the env must let the
        # embedded interpreter find the repo and force the CPU backend
        env = dict(os.environ)
        env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
        env["PADDLE_TPU_DEMO_FORCE_CPU"] = "1"
        res = subprocess.run(
            [binary, str(tmp_path), "10"], capture_output=True,
            text=True, timeout=300, env=env)
        assert res.returncode == 0, (res.stdout[-400:], res.stderr[-400:])
        lines = [l for l in res.stdout.splitlines()
                 if l.startswith("step:")]
        assert len(lines) == 10, res.stdout
        assert "demo_trainer ok" in res.stdout
        first = float(lines[0].split("loss:")[1])
        last = float(lines[-1].split("loss:")[1])
        assert last < first
