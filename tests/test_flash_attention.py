"""Flash-attention Pallas kernel tests (interpret mode on CPU) and the
fused_multihead_attention op/layer, mirroring the reference OpTest pattern
(`python/paddle/fluid/tests/unittests/op_test.py`): kernel vs XLA-reference
oracle for forward and grads."""

import os

import numpy as np
import pytest

os.environ.setdefault("PADDLE_TPU_PALLAS", "interpret")

import jax
import jax.numpy as jnp

import importlib

# the package __init__ re-exports the flash_attention *function* under the
# same name as the module, so resolve the module explicitly
FA = importlib.import_module("paddle_tpu.ops.pallas.flash_attention")


def _rand(rng, *shape):
    return jnp.asarray(rng.randn(*shape).astype("float32"))


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("with_bias", [False, True])
def test_kernel_fwd_bwd_single_block(causal, with_bias):
    rng = np.random.RandomState(0)
    B, H, T, D = 2, 2, 128, 64
    q, k, v = (_rand(rng, B, H, T, D) for _ in range(3))
    bias = None
    if with_bias:
        bias = jnp.asarray(
            np.where(rng.rand(B, T) < 0.2, -1e4, 0).astype("float32")
        )

    o1 = FA.flash_attention(q, k, v, bias=bias, causal=causal)
    o2 = FA.mha_reference(q, k, v, bias=bias, causal=causal)
    np.testing.assert_allclose(o1, o2, atol=2e-5, rtol=2e-5)

    def loss(fn):
        return lambda q, k, v: jnp.sum(
            fn(q, k, v, bias=bias, causal=causal) * v
        )

    g1 = jax.grad(loss(FA.flash_attention), argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss(FA.mha_reference), argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(a, b, atol=5e-4, rtol=1e-3)


def test_kernel_multiblock(monkeypatch):
    """Multiple KV/Q blocks exercise the online-softmax accumulation and
    the bwd sweep accumulators (plus the big-|bias| precision path that
    motivated saving (m, l) instead of lse)."""
    monkeypatch.setattr(FA, "_pick_blocks", lambda tq, tk: (64, 128))
    rng = np.random.RandomState(1)
    B, H, T, D = 2, 2, 256, 64
    q, k, v = (_rand(rng, B, H, T, D) for _ in range(3))
    bias = jnp.asarray(
        np.where(rng.rand(B, T) < 0.2, -1e4, 0).astype("float32")
    )
    for causal in (False, True):
        o1 = FA.flash_attention(q, k, v, bias=bias, causal=causal)
        o2 = FA.mha_reference(q, k, v, bias=bias, causal=causal)
        np.testing.assert_allclose(o1, o2, atol=2e-5, rtol=2e-5)
    g1 = jax.grad(
        lambda q, k, v: jnp.sum(
            FA.flash_attention(q, k, v, bias=bias, causal=True) * v
        ),
        argnums=(0, 1, 2),
    )(q, k, v)
    g2 = jax.grad(
        lambda q, k, v: jnp.sum(
            FA.mha_reference(q, k, v, bias=bias, causal=True) * v
        ),
        argnums=(0, 1, 2),
    )(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(a, b, atol=5e-4, rtol=1e-3)


def test_fused_op_in_program():
    """Program-level: fused_multihead_attention layer vs the unfused op
    chain, both through the Executor, gradients included.  T=128 so the
    Pallas kernel path (interpret mode) actually engages — this covers the
    registry's generic jax.vjp grad over the kernel's custom_vjp."""
    import paddle_tpu as fluid
    from paddle_tpu.ops.pallas import flash_attention as _fa_fn  # noqa: F401

    assert FA._kernel_applicable(
        jnp.zeros((4, 128, 16)), jnp.zeros((4, 128, 16)), None
    ), "test shapes must exercise the kernel path"

    B, H, T, D = 1, 2, 128, 16
    rng = np.random.RandomState(2)
    qv = rng.randn(B, H, T, D).astype("float32")
    kv = rng.randn(B, H, T, D).astype("float32")
    vv = rng.randn(B, H, T, D).astype("float32")

    def build(fused):
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            q = fluid.layers.data("q", shape=[H, T, D])
            k = fluid.layers.data("k", shape=[H, T, D])
            v = fluid.layers.data("v", shape=[H, T, D])
            q.stop_gradient = False
            if fused:
                out = fluid.layers.fused_multihead_attention(q, k, v)
            else:
                s = fluid.layers.matmul(
                    q, k, transpose_y=True, alpha=1.0 / np.sqrt(D)
                )
                p = fluid.layers.softmax(s)
                out = fluid.layers.matmul(p, v)
            loss = fluid.layers.reduce_sum(out)
            grads = fluid.backward.gradients([loss], [q])
        exe = fluid.Executor(fluid.TPUPlace())
        exe.run(startup)
        return exe.run(
            main, feed={"q": qv, "k": kv, "v": vv},
            fetch_list=[out, grads[0]],
        )

    o_f, gq_f = build(True)
    o_u, gq_u = build(False)
    np.testing.assert_allclose(o_f, o_u, atol=1e-5, rtol=1e-5)
    np.testing.assert_allclose(gq_f, gq_u, atol=1e-4, rtol=1e-4)
