"""Flash-attention Pallas kernel tests (interpret mode on CPU) and the
fused_multihead_attention op/layer, mirroring the reference OpTest pattern
(`python/paddle/fluid/tests/unittests/op_test.py`): kernel vs XLA-reference
oracle for forward and grads."""

import os

import numpy as np
import pytest

os.environ.setdefault("PADDLE_TPU_PALLAS", "interpret")

import jax
import jax.numpy as jnp

import importlib

# the package __init__ re-exports the flash_attention *function* under the
# same name as the module, so resolve the module explicitly
FA = importlib.import_module("paddle_tpu.ops.pallas.flash_attention")


def _rand(rng, *shape):
    return jnp.asarray(rng.randn(*shape).astype("float32"))


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("with_bias", [False, True])
def test_kernel_fwd_bwd_single_block(causal, with_bias):
    rng = np.random.RandomState(0)
    B, H, T, D = 2, 2, 128, 64
    q, k, v = (_rand(rng, B, H, T, D) for _ in range(3))
    bias = None
    if with_bias:
        bias = jnp.asarray(
            np.where(rng.rand(B, T) < 0.2, -1e4, 0).astype("float32")
        )

    o1 = FA.flash_attention(q, k, v, bias=bias, causal=causal)
    o2 = FA.mha_reference(q, k, v, bias=bias, causal=causal)
    np.testing.assert_allclose(o1, o2, atol=2e-5, rtol=2e-5)

    def loss(fn):
        return lambda q, k, v: jnp.sum(
            fn(q, k, v, bias=bias, causal=causal) * v
        )

    g1 = jax.grad(loss(FA.flash_attention), argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss(FA.mha_reference), argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(a, b, atol=5e-4, rtol=1e-3)


def test_kernel_multiblock(monkeypatch):
    """Multiple KV/Q blocks exercise the online-softmax accumulation and
    the bwd sweep accumulators (plus the big-|bias| precision path that
    motivated saving (m, l) instead of lse)."""
    monkeypatch.setattr(FA, "_pick_blocks", lambda tq, tk: (64, 128))
    rng = np.random.RandomState(1)
    B, H, T, D = 2, 2, 256, 64
    q, k, v = (_rand(rng, B, H, T, D) for _ in range(3))
    bias = jnp.asarray(
        np.where(rng.rand(B, T) < 0.2, -1e4, 0).astype("float32")
    )
    for causal in (False, True):
        o1 = FA.flash_attention(q, k, v, bias=bias, causal=causal)
        o2 = FA.mha_reference(q, k, v, bias=bias, causal=causal)
        np.testing.assert_allclose(o1, o2, atol=2e-5, rtol=2e-5)
    g1 = jax.grad(
        lambda q, k, v: jnp.sum(
            FA.flash_attention(q, k, v, bias=bias, causal=True) * v
        ),
        argnums=(0, 1, 2),
    )(q, k, v)
    g2 = jax.grad(
        lambda q, k, v: jnp.sum(
            FA.mha_reference(q, k, v, bias=bias, causal=True) * v
        ),
        argnums=(0, 1, 2),
    )(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(a, b, atol=5e-4, rtol=1e-3)


def test_kernel_bf16_inputs_match_reference():
    """bf16 (AMP) inputs: the kernel now feeds the MXU input-dtype
    operands with f32 accumulation — QK^T is bit-identical to the old
    upcast form (bf16 casts are exact, 8-bit-mantissa products fit
    f32), and the PV/backward downcasts match mha_reference's own
    (bf16-scaled tolerances)."""
    rng = np.random.RandomState(5)
    B, H, T, D = 2, 2, 256, 64
    q, k, v = (jnp.asarray(rng.randn(B, H, T, D), jnp.bfloat16)
               for _ in range(3))
    o1 = FA.flash_attention(q, k, v)
    o2 = FA.mha_reference(q, k, v)
    np.testing.assert_allclose(
        np.asarray(o1, np.float32), np.asarray(o2, np.float32),
        atol=2e-2, rtol=2e-2)

    def loss(fn):
        return lambda q, k, v: jnp.sum(
            fn(q, k, v).astype(jnp.float32) ** 2)

    g1 = jax.grad(loss(FA.flash_attention), argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss(FA.mha_reference), argnums=(0, 1, 2))(q, k, v)
    for a, b, nm in zip(g1, g2, "qkv"):
        np.testing.assert_allclose(
            np.asarray(a, np.float32), np.asarray(b, np.float32),
            atol=0.5, rtol=6e-2, err_msg="d%s" % nm)


def test_fused_op_in_program():
    """Program-level: fused_multihead_attention layer vs the unfused op
    chain, both through the Executor, gradients included.  T=128 so the
    Pallas kernel path (interpret mode) actually engages — this covers the
    registry's generic jax.vjp grad over the kernel's custom_vjp."""
    import paddle_tpu as fluid
    # the package must expose the SUBMODULE under this name (a
    # function re-export here once shadowed it and broke every
    # module-path import — see ops/pallas/__init__.py)
    from paddle_tpu.ops.pallas import flash_attention as _fa_mod

    assert _fa_mod is FA and callable(_fa_mod.flash_attention)

    assert FA._kernel_applicable(
        jnp.zeros((4, 128, 16)), jnp.zeros((4, 128, 16)), None
    ), "test shapes must exercise the kernel path"

    B, H, T, D = 1, 2, 128, 16
    rng = np.random.RandomState(2)
    qv = rng.randn(B, H, T, D).astype("float32")
    kv = rng.randn(B, H, T, D).astype("float32")
    vv = rng.randn(B, H, T, D).astype("float32")

    def build(fused):
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            q = fluid.layers.data("q", shape=[H, T, D])
            k = fluid.layers.data("k", shape=[H, T, D])
            v = fluid.layers.data("v", shape=[H, T, D])
            q.stop_gradient = False
            if fused:
                out = fluid.layers.fused_multihead_attention(q, k, v)
            else:
                s = fluid.layers.matmul(
                    q, k, transpose_y=True, alpha=1.0 / np.sqrt(D)
                )
                p = fluid.layers.softmax(s)
                out = fluid.layers.matmul(p, v)
            loss = fluid.layers.reduce_sum(out)
            grads = fluid.backward.gradients([loss], [q])
        exe = fluid.Executor(fluid.TPUPlace())
        exe.run(startup)
        return exe.run(
            main, feed={"q": qv, "k": kv, "v": vv},
            fetch_list=[out, grads[0]],
        )

    o_f, gq_f = build(True)
    o_u, gq_u = build(False)
    np.testing.assert_allclose(o_f, o_u, atol=1e-5, rtol=1e-5)
    np.testing.assert_allclose(gq_f, gq_u, atol=1e-4, rtol=1e-4)


class TestInKernelDropout:
    """Debug-hash mode (PADDLE_TPU_FLASH_DROPOUT_DEBUG=iota): the kernel
    and the XLA reference draw the IDENTICAL mask, so fwd outputs and all
    grads must match to float tolerance — verifying the FA2 dropout math
    (l from undropped p, masked numerator, mask-scaled dP in backward)
    independently of the hardware PRNG."""

    def setup_method(self):
        os.environ["PADDLE_TPU_FLASH_DROPOUT_DEBUG"] = "iota"

    def teardown_method(self):
        os.environ.pop("PADDLE_TPU_FLASH_DROPOUT_DEBUG", None)

    @pytest.mark.parametrize("rate", [0.1, 0.5])
    @pytest.mark.parametrize("multiblock", [False, True])
    def test_fwd_bwd_match_reference(self, rate, multiblock):
        rng = np.random.RandomState(0)
        B, H, D = 2, 2, 64
        T = 512 if multiblock else 128
        q = _rand(rng, B, H, T, D)
        k = _rand(rng, B, H, T, D)
        v = _rand(rng, B, H, T, D)
        seed = 1234

        if multiblock:
            bq, bk = 128, 256
        else:
            bq, bk = T, max(128, T)

        def flash_loss(q, k, v):
            qf = q.reshape(B * H, T, D)
            kf = k.reshape(B * H, T, D)
            vf = v.reshape(B * H, T, D)
            o = FA._flash(qf, kf, vf, None,
                          jnp.asarray([seed], jnp.int32), False,
                          1.0 / np.sqrt(D), bq, bk, True, rate, True)
            return jnp.sum(o.astype(jnp.float32) ** 2)

        def ref_loss(q, k, v):
            o = FA.mha_reference(q, k, v, sm_scale=1.0 / np.sqrt(D),
                                 dropout_rate=rate,
                                 seed=jnp.asarray([seed], jnp.int32),
                                 debug=True)
            return jnp.sum(o.astype(jnp.float32) ** 2)

        lf, gf = jax.value_and_grad(flash_loss, argnums=(0, 1, 2))(q, k, v)
        lr_, gr = jax.value_and_grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
        # Tolerance rationale: inputs here are f32, so both paths compute
        # f32 apart from kernel-vs-XLA reduction-order differences —
        # 2e-5/2e-4 bounds those.  mha_reference downcasts the dropout-
        # scaled probabilities to q.dtype before the PV matmul (the MXU-
        # rate tradeoff); under bf16 AMP that widens the gap, which the
        # program-level AMP tests cover with bf16-scaled bounds instead.
        np.testing.assert_allclose(float(lf), float(lr_), rtol=2e-5)
        for a, b, nm in zip(gf, gr, "qkv"):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=2e-4, rtol=2e-4,
                err_msg="d%s mismatch" % nm)

    def test_mask_actually_drops(self):
        """Dropout changes the output vs rate=0 and zero cells appear at
        the hash-predicted positions."""
        rng = np.random.RandomState(1)
        B, H, T, D = 1, 1, 128, 64
        q = _rand(rng, B, H, T, D)
        k = _rand(rng, B, H, T, D)
        v = _rand(rng, B, H, T, D)
        seed = jnp.asarray([7], jnp.int32)
        o_drop = FA._flash(q.reshape(1, T, D), k.reshape(1, T, D),
                           v.reshape(1, T, D), None, seed, False,
                           1.0 / np.sqrt(D), T, 128, True, 0.5, True)
        o_plain = FA._flash(q.reshape(1, T, D), k.reshape(1, T, D),
                            v.reshape(1, T, D), None, seed, False,
                            1.0 / np.sqrt(D), T, 128, True, 0.0, True)
        assert not np.allclose(np.asarray(o_drop), np.asarray(o_plain))
        # keep fraction of the debug hash is ~1-rate
        keep = np.asarray(FA.debug_keep_mask(1, T, T, 0.5, 7))
        assert abs(keep.mean() - 0.5) < 0.05

    def test_dropout_through_program(self):
        """attn_dropout>0 BERT config now takes the fused path and trains
        (loss finite and decreasing)."""
        import paddle_tpu as fluid
        from paddle_tpu.models import bert

        cfg = bert.BertConfig(vocab_size=256, hidden=64, layers=1,
                              heads=2, ffn=128, max_seq=128, dropout=0.1,
                              fuse_attn=True)
        assert cfg.attn_dropout == 0.1
        main, startup, feeds, loss = bert.build_pretrain(
            cfg, seq_len=128, lr=1e-3, train=True)
        fused_ops = [op for op in main.global_block().ops
                     if op.type == "fused_multihead_attention"]
        assert fused_ops and fused_ops[0].attr("dropout_rate") == 0.1
        exe = fluid.Executor(fluid.TPUPlace())
        exe.run(startup)
        rng = np.random.RandomState(0)
        feed = bert.make_fake_batch(4, 128, cfg, rng)
        l0 = float(np.asarray(exe.run(
            main, feed=feed, fetch_list=[loss])[0]).reshape(()))
        for _ in range(6):
            exe.run(main, feed=feed, fetch_list=[])
        l1 = float(np.asarray(exe.run(
            main, feed=feed, fetch_list=[loss])[0]).reshape(()))
        assert np.isfinite(l0) and np.isfinite(l1)
        assert l1 < l0


    def test_clone_for_test_disables_kernel_dropout(self):
        """clone(for_test=True) must switch in-kernel dropout off — the
        serving path has no other off-switch for the fused op."""
        import paddle_tpu as fluid
        from paddle_tpu.models import bert

        cfg = bert.BertConfig(vocab_size=256, hidden=64, layers=1,
                              heads=2, ffn=128, max_seq=128, dropout=0.1,
                              fuse_attn=True)
        fluid.unique_name.switch()
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            ids = fluid.layers.data("input_ids", shape=[128],
                                    dtype="int64")
            tt = fluid.layers.data("token_type_ids", shape=[128],
                                   dtype="int64")
            mb = fluid.layers.data("attn_mask_bias", shape=[1, 1, 128],
                                   dtype="float32")
            x = bert.encoder(ids, tt, mb, cfg, 128)
            out = fluid.layers.reduce_mean(x)
        test_prog = main.clone(for_test=True)
        fused = [op for op in test_prog.global_block().ops
                 if op.type == "fused_multihead_attention"]
        assert fused and all(op.attr("is_test") for op in fused)
        exe = fluid.Executor(fluid.TPUPlace())
        exe.run(startup)
        rng = np.random.RandomState(0)
        feed = {k: v for k, v in bert.make_fake_batch(
            2, 128, cfg, rng).items()
            if k in ("input_ids", "token_type_ids", "attn_mask_bias",
                     "pos_ids")}
        o1 = exe.run(test_prog, feed=feed, fetch_list=[out])[0]
        o2 = exe.run(test_prog, feed=feed, fetch_list=[out])[0]
        np.testing.assert_array_equal(np.asarray(o1), np.asarray(o2))

    def test_rate_validation(self):
        rng = np.random.RandomState(0)
        q = _rand(rng, 1, 1, 128, 64)
        with pytest.raises(ValueError, match="dropout_rate"):
            FA.flash_attention(q, q, q, dropout_rate=1.0, dropout_seed=1)
