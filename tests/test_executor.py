"""Executor lowering + jit-cache behavior (reference tests:
unittests/test_executor_and_mul.py, test_exe caching)."""

import numpy as np

import paddle_tpu as fluid
from paddle_tpu.executor import Scope, global_scope, scope_guard


def _fresh():
    return fluid.Program(), fluid.Program()


def test_fc_matches_numpy():
    prog, startup = _fresh()
    with fluid.program_guard(prog, startup):
        x = fluid.layers.data("x", shape=[4], dtype="float32")
        y = fluid.layers.fc(x, size=3)
    exe = fluid.Executor(fluid.CPUPlace())
    with scope_guard(Scope()):
        exe.run(startup)
        sc = global_scope()
        w = np.asarray(sc.get("fc_0.w_0") if sc.has("fc_0.w_0") else None)
        # param names are unique per test session; find them from program
        params = prog.all_parameters()
        w = np.asarray(sc.get(params[0].name))
        b = np.asarray(sc.get(params[1].name))
        xv = np.random.RandomState(0).rand(2, 4).astype("float32")
        out = exe.run(prog, feed={"x": xv}, fetch_list=[y])[0]
        np.testing.assert_allclose(out, xv @ w + b, rtol=1e-5, atol=1e-5)


def test_feed_fetch_roundtrip():
    prog, startup = _fresh()
    with fluid.program_guard(prog, startup):
        x = fluid.layers.data("x", shape=[3], dtype="float32")
        y = fluid.layers.scale(x, scale=2.0, bias=1.0)
    exe = fluid.Executor(fluid.CPUPlace())
    xv = np.arange(6, dtype="float32").reshape(2, 3)
    out = exe.run(prog, feed={"x": xv}, fetch_list=[y])[0]
    np.testing.assert_allclose(out, xv * 2 + 1)


def test_persistable_update_across_runs():
    """An op writing a persistable var must persist it (the in-place SGD
    pattern)."""
    prog, startup = _fresh()
    with fluid.program_guard(prog, startup):
        counter = fluid.layers.create_global_var(
            [1], 0.0, "float32", persistable=True
        )
        fluid.layers.increment(counter, value=1.0, in_place=True)
    exe = fluid.Executor(fluid.CPUPlace())
    with scope_guard(Scope()):
        exe.run(startup)
        for expected in (1.0, 2.0, 3.0):
            out = exe.run(prog, fetch_list=[counter])[0]
            assert float(out[0]) == expected


def test_uninitialized_var_raises():
    prog, startup = _fresh()
    with fluid.program_guard(prog, startup):
        x = fluid.layers.data("x", shape=[4], dtype="float32")
        y = fluid.layers.fc(x, size=3)
    exe = fluid.Executor(fluid.CPUPlace())
    with scope_guard(Scope()):
        try:
            exe.run(prog, feed={"x": np.zeros((1, 4), "float32")},
                    fetch_list=[y])
        except RuntimeError as e:
            assert "not initialized" in str(e)
        else:
            raise AssertionError("expected RuntimeError")


def test_shape_bucketing_recompiles():
    """Different feed shapes hit different cache entries, same program."""
    prog, startup = _fresh()
    with fluid.program_guard(prog, startup):
        x = fluid.layers.data("x", shape=[4], dtype="float32")
        y = fluid.layers.scale(x, scale=3.0)
    exe = fluid.Executor(fluid.CPUPlace())
    for n in (1, 2, 5):
        xv = np.ones((n, 4), "float32")
        out = exe.run(prog, feed={"x": xv}, fetch_list=[y])[0]
        assert out.shape == (n, 4)
        np.testing.assert_allclose(out, 3.0)


def test_fetch_parameter_directly():
    prog, startup = _fresh()
    with fluid.program_guard(prog, startup):
        x = fluid.layers.data("x", shape=[4], dtype="float32")
        y = fluid.layers.fc(x, size=3)
    p = prog.all_parameters()[0]
    exe = fluid.Executor(fluid.CPUPlace())
    with scope_guard(Scope()):
        exe.run(startup)
        out = exe.run(prog, feed={"x": np.zeros((1, 4), "float32")},
                      fetch_list=[p])[0]
        assert out.shape == tuple(p.shape)


def test_random_ops_vary_per_step_and_respect_seed():
    prog, startup = _fresh()
    with fluid.program_guard(prog, startup):
        r = fluid.layers.io.data  # noqa: F841  (no feeds needed)
        from paddle_tpu.layer_helper import LayerHelper

        helper = LayerHelper("rand")
        out = helper.create_variable_for_type_inference("float32")
        helper.append_op(
            type="uniform_random", outputs={"Out": [out]},
            attrs={"shape": [4], "min": 0.0, "max": 1.0, "seed": 0},
        )
    exe = fluid.Executor(fluid.CPUPlace())
    a = exe.run(prog, fetch_list=[out])[0]
    b = exe.run(prog, fetch_list=[out])[0]
    assert not np.allclose(a, b), "per-step RNG should differ"
