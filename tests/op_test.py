"""Golden op-test harness (reference:
``python/paddle/fluid/tests/unittests/op_test.py`` — OpTest builds a one-op
program from inputs/attrs/outputs, checks outputs against a numpy oracle
(check_output_with_place, op_test.py:368) and analytic grads against numeric
finite differences (check_grad, op_test.py:532)).

Same oracles here: numpy forward reference supplied by each test;
grad check compares the program-level grad ops produced by append_backward
against central finite differences of the op's own lowering.

Backend-flag rerun (reference ``unittests/mkldnn/`` pattern, SURVEY §4):
with ``PADDLE_TPU_TESTS_ON_TPU=1`` (conftest leaves the real backend on)
``check_output`` runs every one-op program on the chip against the same
numpy oracle with bf16-MXU-tolerant bounds, and ``check_grad`` skips —
central finite differences at delta 1e-3 are noise under bf16 matmul
rounding (grad correctness is CPU-proven; the chip run validates the
forward lowerings on real silicon)."""

import os

import numpy as np

import paddle_tpu as fluid
from paddle_tpu.executor import Scope, scope_guard, global_scope
from paddle_tpu.ops import registry as op_registry

ON_TPU = bool(os.environ.get("PADDLE_TPU_TESTS_ON_TPU"))


class OpTest:
    """Subclass and set: op_type, inputs {slot: np.ndarray | [(name, arr)]},
    attrs, outputs {slot: expected np.ndarray | [(name, arr)]}."""

    op_type = None
    inputs = {}
    attrs = {}
    outputs = {}

    def _build_program(self):
        main, startup = fluid.Program(), fluid.Program()
        feed = {}
        with fluid.program_guard(main, startup):
            block = main.global_block()
            in_names = {}
            for slot, value in self.inputs.items():
                if isinstance(value, list):
                    names = []
                    for name, arr in value:
                        arr = np.asarray(arr)
                        block.create_var(
                            name=name, shape=arr.shape, dtype=str(arr.dtype),
                            is_data=True, stop_gradient=False,
                        )
                        feed[name] = arr
                        names.append(name)
                    in_names[slot] = names
                else:
                    arr = np.asarray(value)
                    name = "in_%s" % slot
                    block.create_var(
                        name=name, shape=arr.shape, dtype=str(arr.dtype),
                        is_data=True, stop_gradient=False,
                    )
                    feed[name] = arr
                    in_names[slot] = [name]
            out_names = {}
            for slot, value in self.outputs.items():
                if isinstance(value, list):
                    out_names[slot] = [n for n, _ in value]
                else:
                    out_names[slot] = ["out_%s" % slot]
                for n in out_names[slot]:
                    block.create_var(name=n, dtype="float32")
            block.append_op(
                type=self.op_type, inputs=in_names, outputs=out_names,
                attrs=dict(self.attrs),
            )
        return main, startup, feed, in_names, out_names

    def check_output(self, atol=1e-5, rtol=1e-5):
        if ON_TPU:
            # f32 matmuls run at bf16 MXU precision on the chip
            atol, rtol = max(atol, 2e-2), max(rtol, 2e-2)
        main, startup, feed, _, out_names = self._build_program()
        exe = fluid.Executor(
            fluid.TPUPlace() if ON_TPU else fluid.CPUPlace())
        with scope_guard(Scope()):
            fetch = [n for slot in self.outputs for n in out_names[slot]]
            outs = exe.run(main, feed=feed, fetch_list=fetch)
            i = 0
            for slot, value in self.outputs.items():
                expect = (
                    [a for _, a in value] if isinstance(value, list)
                    else [value]
                )
                for e in expect:
                    np.testing.assert_allclose(
                        outs[i], np.asarray(e), atol=atol, rtol=rtol,
                        err_msg="output %s of %s" % (slot, self.op_type),
                    )
                    i += 1

    def check_grad(self, inputs_to_check, output_name, max_relative_error=5e-3,
                   numeric_delta=1e-3):
        """Analytic (program grad-op) vs numeric (finite difference) grads
        w.r.t. each named input, using sum(output) as the scalar loss."""
        if ON_TPU:
            import pytest

            pytest.skip("finite-difference grads are noise under bf16 "
                        "MXU rounding; grad oracle runs on CPU")
        main, startup, feed, in_names, out_names = self._build_program()
        with fluid.program_guard(main, startup):
            block = main.global_block()
            out_var = block.var(
                "out_%s" % output_name
                if not isinstance(self.outputs[output_name], list)
                else self.outputs[output_name][0][0]
            )
            # loss = sum(out * R) with fixed random R — a plain sum is
            # degenerate for ops like softmax whose outputs sum to a
            # constant (numeric grad would be pure float noise)
            expect = self.outputs[output_name]
            expect_arr = np.asarray(
                expect[0][1] if isinstance(expect, list) else expect
            )
            proj = np.random.RandomState(1234).uniform(
                0.5, 1.5, expect_arr.shape
            ).astype("float32")
            block.create_var(
                name="__proj__", shape=proj.shape, dtype="float32",
                is_data=True, stop_gradient=True,
            )
            feed["__proj__"] = proj
            weighted = fluid.layers.elementwise_mul(
                out_var, block.var("__proj__")
            )
            loss = fluid.layers.reduce_sum(weighted)
            check_vars = [block.var(n) for n in inputs_to_check]
            grads = fluid.gradients(loss, check_vars)
        exe = fluid.Executor(fluid.CPUPlace())
        with scope_guard(Scope()):
            analytic = exe.run(main, feed=feed, fetch_list=grads)

            def loss_at(feed_override):
                with scope_guard(Scope()):
                    return float(
                        exe.run(main, feed=feed_override,
                                fetch_list=[loss])[0].reshape(-1)[0]
                    )

            for name, g in zip(inputs_to_check, analytic):
                base = feed[name].astype(np.float64)
                num = np.zeros_like(base)
                flat = base.reshape(-1)
                numf = num.reshape(-1)
                for i in range(flat.size):
                    for sgn in (+1, -1):
                        pert = flat.copy()
                        pert[i] += sgn * numeric_delta
                        f2 = dict(feed)
                        f2[name] = pert.reshape(base.shape).astype(
                            feed[name].dtype
                        )
                        numf[i] += sgn * loss_at(f2)
                    numf[i] /= 2 * numeric_delta
                abs_max = max(np.abs(num).max(), np.abs(g).max(), 1e-3)
                rel_err = np.abs(g - num).max() / abs_max
                assert rel_err < max_relative_error, (
                    "grad of %s wrt %s: rel err %.3g (analytic %s vs "
                    "numeric %s)" % (
                        self.op_type, name, rel_err,
                        np.asarray(g).reshape(-1)[:5], num.reshape(-1)[:5],
                    )
                )
