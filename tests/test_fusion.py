"""Cost-guided fusion pass pipeline (ISSUE 5): pattern-match/rewrite
goldens on the example builders, fusion-on vs fusion-off bit-exactness
(train + infer; documented tolerance where fused softmax-xent differs),
bucketed-allreduce deadlock proof, jit-cache-key separation, the kill
switch + fusion_report introspection, and the two new lint checks."""

import copy
import os

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.static_analysis import (FusionConfig, fusion,
                                        prove_deadlock_free,
                                        verify_program)
from paddle_tpu.executor import Scope, scope_guard
from paddle_tpu.transpiler.collective import GradAllReduce


def build_mnist_mlp(act="relu", train=True, lr=1e-3, optimizer="adam",
                    width=24, in_dim=32):
    """fc(relu) x2 -> fc(softmax) -> cross_entropy: exercises the
    bias_act, softmax_xent, and optimizer families."""
    fluid.unique_name.switch()
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        img = fluid.layers.data(name="img", shape=[in_dim],
                                dtype="float32")
        label = fluid.layers.data(name="label", shape=[1], dtype="int64")
        h = fluid.layers.fc(input=img, size=width, act=act)
        h = fluid.layers.fc(input=h, size=width, act=act)
        pred = fluid.layers.fc(input=h, size=10, act="softmax")
        loss = fluid.layers.reduce_mean(
            fluid.layers.cross_entropy(input=pred, label=label))
        acc = fluid.layers.accuracy(input=pred, label=label)
        if train:
            opt = (fluid.optimizer.Adam(learning_rate=lr)
                   if optimizer == "adam"
                   else fluid.optimizer.SGD(learning_rate=lr))
            opt.minimize(loss)
    return main, startup, loss, acc, pred


def build_bert_tiny(seq_len=32, train=True, dropout=None):
    """BERT_TINY with the UNfused attention chain so the pipeline (not
    the model builder) performs the rewrite."""
    from paddle_tpu.models import bert

    cfg = copy.copy(bert.BERT_TINY)
    cfg.fuse_attn = False
    if dropout is not None:
        cfg.dropout = dropout
        cfg.attn_dropout = dropout
    fluid.unique_name.switch()
    main, startup, feeds, loss = bert.build_pretrain(
        cfg, seq_len=seq_len, train=train)
    return main, startup, feeds, loss, cfg


def mlp_feed(rng, bs=8):
    return {"img": rng.rand(bs, 32).astype("float32"),
            "label": rng.randint(0, 10, (bs, 1)).astype("int64")}


def run_steps(main, startup, feed, fetch, steps=4):
    exe = fluid.Executor()
    scope = Scope()
    with scope_guard(scope):
        exe.run(startup)
        outs = [np.asarray(exe.run(main, feed=feed, fetch_list=fetch)[0])
                for _ in range(steps)]
    return np.array(outs), scope


def op_types(program):
    return [op.type for op in program.global_block().ops]


# ---------------------------------------------------------------------------
# pattern-match / rewrite goldens
# ---------------------------------------------------------------------------

OPT_FUSE_ON = ("PADDLE_TPU_FUSE_OPT_OVERHEAD_BYTES", str(8 << 20))


class TestRewriteGoldens:
    def test_mnist_mlp_families(self, monkeypatch):
        # credit the TPU launch overhead so the optimizer gate passes
        # (the CPU default refuses — see test_optimizer_gate_*)
        monkeypatch.setenv(*OPT_FUSE_ON)
        main, startup, loss, acc, pred = build_mnist_mlp()
        fused, report = fusion.resolve_fused_program(
            main, targets=[loss.name, acc.name])
        counts = report.counts()
        assert counts.get("bias_act") == 2          # two relu fcs
        assert counts.get("softmax_xent") == 1
        assert counts.get("optimizer") == 1         # one adam group
        types = op_types(fused)
        assert types.count("fused_bias_act") == 2
        assert types.count("fused_bias_act_grad") == 2
        assert types.count("softmax_with_cross_entropy") == 1
        assert types.count("softmax_with_cross_entropy_grad") == 1
        assert types.count("fused_adam") == 1
        assert types.count("adam") == 0
        # the rewritten program is strictly smaller and still verifies
        assert len(types) < len(op_types(main))
        verify_program(fused, targets=[loss.name, acc.name])

    def test_bert_tiny_all_families_fire(self, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_FLASH_MIN_T", "16")
        monkeypatch.setenv(*OPT_FUSE_ON)
        main, startup, feeds, loss, cfg = build_bert_tiny()
        fused, report = fusion.resolve_fused_program(
            main, targets=[loss.name])
        counts = report.counts()
        assert counts.get("attention") == cfg.layers == 2
        # 2 sublayer closes per layer + the embedding add+LN
        assert counts.get("dropout_add_ln") == 2 * cfg.layers + 1
        assert counts.get("bias_act") == cfg.layers  # gelu ffn1 per layer
        assert counts.get("optimizer") == 1
        types = op_types(fused)
        assert types.count("fused_multihead_attention") == 2
        assert types.count("fused_multihead_attention_grad") == 2
        assert types.count("fused_dropout_add_ln") == 5
        assert types.count("fused_dropout_add_ln_grad") == 5
        assert "softmax" not in types  # every attention softmax fused
        verify_program(fused, targets=[loss.name])

    def test_bert_train_program_strictly_fewer_ops(self, monkeypatch):
        """Acceptance: with fusion enabled (default) the BERT train step
        lowers to strictly fewer ops than unfused — program-level op
        count, which maps 1:1 onto fewer HLO computations entering XLA."""
        monkeypatch.setenv("PADDLE_TPU_FLASH_MIN_T", "16")
        main, startup, feeds, loss, cfg = build_bert_tiny()
        fused, report = fusion.resolve_fused_program(
            main, targets=[loss.name])
        assert len(op_types(fused)) < len(op_types(main))
        assert report.ops_removed > 0

    @pytest.mark.slow
    def test_bert_base_train_program_strictly_fewer_ops(self, monkeypatch):
        """The BERT-base acceptance criterion at its real scale (IR-only;
        nothing is executed)."""
        from paddle_tpu.models import bert

        monkeypatch.setenv("PADDLE_TPU_FLASH_MIN_T", "128")
        cfg = copy.copy(bert.BERT_BASE)
        cfg.fuse_attn = False
        fluid.unique_name.switch()
        main, _, _, loss = bert.build_pretrain(cfg, seq_len=128,
                                               train=True)
        fused, report = fusion.resolve_fused_program(
            main, targets=[loss.name])
        counts = report.counts()
        assert counts.get("attention") == 12
        assert counts.get("dropout_add_ln") == 25
        assert len(op_types(fused)) < len(op_types(main))

    def test_infer_program_rewrites(self):
        """Inference programs (no grad twins) rewrite forward-only."""
        main, startup, feeds, loss, cfg = build_bert_tiny(train=False)
        fused, report = fusion.resolve_fused_program(
            main, targets=[loss.name])
        counts = report.counts()
        assert counts.get("dropout_add_ln") == 5
        types = op_types(fused)
        assert types.count("fused_dropout_add_ln") == 5
        assert not any(t.endswith("_grad") for t in types)

    def test_fetched_intermediate_is_never_fused_away(self):
        """A fetch of the pre-activation bias-add output must keep the
        unfused chain (the fused op would leave the fetch unproduced)."""
        fluid.unique_name.switch()
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.layers.data(name="x", shape=[8], dtype="float32")
            h = fluid.layers.fc(input=x, size=4, act="relu")
            loss = fluid.layers.reduce_mean(h)
        # find the elementwise_add output (the intermediate)
        add_out = next(op.outputs["Out"][0]
                       for op in main.global_block().ops
                       if op.type == "elementwise_add")
        fused, report = fusion.resolve_fused_program(
            main, targets=[loss.name, add_out])
        assert report.counts().get("bias_act") is None
        fused2, report2 = fusion.resolve_fused_program(
            main, targets=[loss.name])
        assert report2.counts().get("bias_act") == 1


# ---------------------------------------------------------------------------
# cost gates
# ---------------------------------------------------------------------------

class TestCostGates:
    def test_attention_below_flash_threshold_skips_with_reason(
            self, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_FLASH_MIN_T", "512")
        main, startup, feeds, loss, cfg = build_bert_tiny(seq_len=32)
        fused, report = fusion.resolve_fused_program(
            main, targets=[loss.name])
        assert report.counts().get("attention") is None
        skips = [s for s in report.skipped if s.family == "attention"]
        assert len(skips) == cfg.layers
        assert "flash engagement threshold" in skips[0].reason

    def test_attention_dynamic_seq_dim_skips_not_crashes(
            self, monkeypatch):
        """Regression: dynamic Tq with static Tk above the threshold
        passed the cost gate and hit int(None) — must skip instead."""
        import math

        monkeypatch.setenv("PADDLE_TPU_FLASH_MIN_T", "32")
        fluid.unique_name.switch()
        main, startup = fluid.Program(), fluid.Program()
        H, DH = 2, 8
        with fluid.program_guard(main, startup):
            q = fluid.layers.data(name="q", shape=[H, None, DH],
                                  dtype="float32")
            k = fluid.layers.data(name="k", shape=[H, 64, DH],
                                  dtype="float32")
            v = fluid.layers.data(name="v", shape=[H, 64, DH],
                                  dtype="float32")
            scores = fluid.layers.matmul(q, k, transpose_y=True,
                                         alpha=1.0 / math.sqrt(DH))
            probs = fluid.layers.softmax(scores)
            loss = fluid.layers.reduce_mean(
                fluid.layers.matmul(probs, v))
        _, report = fusion.resolve_fused_program(main,
                                                 targets=[loss.name])
        assert report.counts().get("attention") is None
        skips = [s for s in report.skipped if s.family == "attention"]
        assert skips and "dynamic" in skips[0].reason

    def test_skips_not_duplicated_by_applied_rewrites(self, monkeypatch):
        """Regression: the family loop re-scans after every applied
        rewrite, and each scan used to re-record every still-gated
        site — one below-threshold attention next to one fused
        attention listed the same skip twice (quadratic on BERT)."""
        import math

        monkeypatch.setenv("PADDLE_TPU_FLASH_MIN_T", "32")
        fluid.unique_name.switch()
        main, startup = fluid.Program(), fluid.Program()
        H, DH = 2, 8
        with fluid.program_guard(main, startup):
            outs = []
            for T in (64, 16):  # first fuses, second is below threshold
                q = fluid.layers.data(name="q%d" % T, shape=[H, T, DH],
                                      dtype="float32")
                k = fluid.layers.data(name="k%d" % T, shape=[H, T, DH],
                                      dtype="float32")
                v = fluid.layers.data(name="v%d" % T, shape=[H, T, DH],
                                      dtype="float32")
                scores = fluid.layers.matmul(q, k, transpose_y=True,
                                             alpha=1.0 / math.sqrt(DH))
                probs = fluid.layers.softmax(scores)
                outs.append(fluid.layers.reduce_mean(
                    fluid.layers.matmul(probs, v)))
            loss = fluid.layers.elementwise_add(outs[0], outs[1])
        _, report = fusion.resolve_fused_program(main,
                                                 targets=[loss.name])
        assert report.counts().get("attention") == 1
        skips = [s for s in report.skipped if s.family == "attention"]
        assert len(skips) == 1
        assert "flash engagement threshold" in skips[0].reason
        # recorded coordinates must be valid in the reported program
        seen = {(s.family, s.block_idx, s.op_idx) for s in report.skipped}
        assert len(seen) == len(report.skipped)

    def test_optimizer_gate_rejects_large_groups(self, monkeypatch):
        """The r04 lesson encoded: a BERT-scale flat stream costs more
        in concat/split traffic than it saves in launches."""
        monkeypatch.setenv("PADDLE_TPU_FUSE_OPT_OVERHEAD_BYTES", "1024")
        main, startup, loss, acc, pred = build_mnist_mlp()
        fused, report = fusion.resolve_fused_program(
            main, targets=[loss.name])
        assert report.counts().get("optimizer") is None
        skips = [s for s in report.skipped if s.family == "optimizer"]
        assert skips and "cost model" in skips[0].reason

    def test_optimizer_gate_default_is_backend_aware(self, monkeypatch):
        """On the CPU backend the default launch-overhead credit is
        small enough that the real mnist-scale group (784->200->200->10,
        ~200k params) is refused — the fused arm measured 1.7x SLOWER
        there — while tiny groups still pass.  The TPU-scale credit
        (env override here; automatic on a tpu backend) flips it."""
        monkeypatch.delenv("PADDLE_TPU_FUSE_OPT_OVERHEAD_BYTES",
                           raising=False)
        main, startup, loss, acc, pred = build_mnist_mlp(
            width=200, in_dim=784)
        fused, report = fusion.resolve_fused_program(
            main, targets=[loss.name])
        assert report.counts().get("optimizer") is None
        skips = [s for s in report.skipped if s.family == "optimizer"]
        assert skips and "cost model" in skips[0].reason
        monkeypatch.setenv(*OPT_FUSE_ON)
        fused2, report2 = fusion.resolve_fused_program(
            main, targets=[loss.name])
        assert report2.counts().get("optimizer") == 1

    def test_attention_rank2_per_row_bias_stays_unfused(self, monkeypatch):
        """Regression: a rank-2 bias trailing-aligns to the (Tq,Tk)
        score dims under the unfused elementwise_add — a per-QUERY-ROW
        bias.  The fused op would reinterpret it per batch, so the
        matcher must refuse it (only [B,1,1,Tk] / [1,Tk] fuse)."""
        import math

        monkeypatch.setenv("PADDLE_TPU_FLASH_MIN_T", "16")
        fluid.unique_name.switch()
        main, startup = fluid.Program(), fluid.Program()
        T, H, DH = 32, 2, 8
        with fluid.program_guard(main, startup):
            q = fluid.layers.data(name="q", shape=[H, T, DH],
                                  dtype="float32")
            k = fluid.layers.data(name="k", shape=[H, T, DH],
                                  dtype="float32")
            v = fluid.layers.data(name="v", shape=[H, T, DH],
                                  dtype="float32")
            rowbias = fluid.layers.data(name="rowbias", shape=[T],
                                        dtype="float32")  # [B,T]: per-row
            scores = fluid.layers.matmul(q, k, transpose_y=True,
                                         alpha=1.0 / math.sqrt(DH))
            scores = fluid.layers.elementwise_add(scores, rowbias)
            probs = fluid.layers.softmax(scores)
            out = fluid.layers.matmul(probs, v)
            loss = fluid.layers.reduce_mean(out)
        _, report = fusion.resolve_fused_program(main,
                                                 targets=[loss.name])
        assert report.counts().get("attention") is None

    def test_differentiable_soft_label_stays_unfused(self):
        """Regression: distillation-style soft label produced by a
        differentiable teacher path.  The fused op emits Logits@GRAD
        only, so fusing would leave the teacher's softmax_grad reading
        a never-produced Label@GRAD — the matcher must refuse."""
        fluid.unique_name.switch()
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.layers.data(name="x", shape=[8], dtype="float32")
            teacher = fluid.layers.softmax(
                fluid.layers.fc(input=x, size=4, act=None))
            student = fluid.layers.softmax(
                fluid.layers.fc(input=x, size=4, act=None))
            loss = fluid.layers.reduce_mean(fluid.layers.cross_entropy(
                student, teacher, soft_label=True))
            fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
        fused, report = fusion.resolve_fused_program(
            main, targets=[loss.name])
        assert report.counts().get("softmax_xent") is None
        skips = [s for s in report.skipped if s.family == "softmax_xent"]
        assert skips and "differentiable" in skips[0].reason
        # the program must still run with fusion on
        rng = np.random.RandomState(3)
        feed = {"x": rng.rand(4, 8).astype("float32")}
        run_steps(main, startup, feed, [loss.name], steps=1)

    def test_ops_removed_matches_actual_program_shrink(self):
        main, startup, loss, acc, pred = build_mnist_mlp()
        n_before = len(main.global_block().ops)
        fused, report = fusion.resolve_fused_program(
            main, targets=[loss.name])
        n_after = len(fused.global_block().ops)
        assert report.ops_removed == n_before - n_after > 0

    def test_rewrite_records_coordinates_and_deltas(self):
        main, startup, loss, acc, pred = build_mnist_mlp()
        fused, report = fusion.resolve_fused_program(
            main, targets=[loss.name])
        for r in report.applied:
            assert r.block_idx == 0
            assert len(r.op_idxs) >= 2
            assert r.predicted  # every rewrite carries a predicted delta
        d = report.to_dict()
        assert d["counts"] == report.counts()


# ---------------------------------------------------------------------------
# bit-exactness / documented tolerance
# ---------------------------------------------------------------------------

class TestNumerics:
    def test_bias_act_and_optimizer_train_bit_exact(self, monkeypatch):
        """Families documented bit-exact (bias_act composite, fused_sgd
        multi-tensor): identical losses and identical final params.  The
        model avoids the softmax-xent family so the whole program is in
        the bit-exact class."""
        def build():
            fluid.unique_name.switch()
            main, startup = fluid.Program(), fluid.Program()
            with fluid.program_guard(main, startup):
                x = fluid.layers.data(name="img", shape=[32],
                                      dtype="float32")
                y = fluid.layers.data(name="label", shape=[1],
                                      dtype="float32")
                h = fluid.layers.fc(input=x, size=16, act="relu")
                h = fluid.layers.fc(input=h, size=16, act="tanh")
                out = fluid.layers.fc(input=h, size=1)
                loss = fluid.layers.reduce_mean(
                    fluid.layers.square(
                        fluid.layers.elementwise_sub(out, y)))
                fluid.optimizer.SGD(learning_rate=0.05).minimize(loss)
            return main, startup, loss
        rng = np.random.RandomState(3)
        feed = {"img": rng.rand(8, 32).astype("float32"),
                "label": rng.rand(8, 1).astype("float32")}
        monkeypatch.setenv(*OPT_FUSE_ON)
        monkeypatch.setenv("PADDLE_TPU_FUSION", "0")
        m0, s0, loss0 = build()
        off, sc_off = run_steps(m0, s0, feed, [loss0.name])
        monkeypatch.setenv("PADDLE_TPU_FUSION", "1")
        m1, s1, loss1 = build()
        # prove the rewrites actually fired on the fusion-on arm
        rep = fusion.resolve_fused_program(m1, targets=[loss1.name])[1]
        assert rep.counts().get("bias_act") == 2
        assert rep.counts().get("optimizer") == 1
        on, sc_on = run_steps(m1, s1, feed, [loss1.name])
        np.testing.assert_array_equal(off, on)
        w_off = np.asarray(sc_off.get("fc_0.w_0"))
        w_on = np.asarray(sc_on.get("fc_0.w_0"))
        np.testing.assert_array_equal(w_off, w_on)

    def test_softmax_xent_train_documented_tolerance(self, monkeypatch):
        """The softmax-xent family is NOT bit-exact (logsumexp form vs
        the eps-guarded log(softmax)+pick) — documented tolerance 1e-5
        relative over a few steps."""
        rng = np.random.RandomState(0)
        feed = mlp_feed(rng)
        monkeypatch.setenv("PADDLE_TPU_FUSION", "0")
        m, s, loss, acc, _ = build_mnist_mlp()
        off, _ = run_steps(m, s, feed, [loss.name])
        monkeypatch.setenv("PADDLE_TPU_FUSION", "1")
        m, s, loss, acc, _ = build_mnist_mlp()
        on, _ = run_steps(m, s, feed, [loss.name])
        np.testing.assert_allclose(on, off, rtol=1e-5)
        assert on[-1] < on[0]  # still trains

    def test_bert_infer_dropout0_bit_exact_ln_family(self, monkeypatch):
        """Rate-0 fused_dropout_add_ln is bit-exact in f32: the bert
        eval program (all dropout off) produces the identical loss with
        fusion on and off."""
        monkeypatch.setenv("PADDLE_TPU_FLASH_MIN_T", "512")
        rng = np.random.RandomState(1)
        from paddle_tpu.models import bert

        main, startup, feeds, loss, cfg = build_bert_tiny(train=False)
        batch = bert.make_fake_batch(4, 32, cfg, rng)
        monkeypatch.setenv("PADDLE_TPU_FUSION", "0")
        off, _ = run_steps(main, startup, batch, [loss.name], steps=2)
        monkeypatch.setenv("PADDLE_TPU_FUSION", "1")
        on, _ = run_steps(main, startup, batch, [loss.name], steps=2)
        np.testing.assert_array_equal(off, on)

    def test_bert_train_with_attention_fusion_converges(self, monkeypatch):
        """Attention + LN fusion in train mode: dropout mask streams
        differ (documented), so assert convergence parity, not
        bit-exactness."""
        monkeypatch.setenv("PADDLE_TPU_FLASH_MIN_T", "16")
        rng = np.random.RandomState(2)
        from paddle_tpu.models import bert

        main, startup, feeds, loss, cfg = build_bert_tiny()
        batch = bert.make_fake_batch(4, 32, cfg, rng)
        monkeypatch.setenv("PADDLE_TPU_FUSION", "1")
        on, _ = run_steps(main, startup, batch, [loss.name], steps=4)
        assert np.isfinite(on).all()
        assert on[-1] < on[0]


# ---------------------------------------------------------------------------
# bucketed allreduce
# ---------------------------------------------------------------------------

def build_dp_mlp(rank=0, nranks=2, lr=0.1):
    fluid.unique_name.switch()
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[16], dtype="float32")
        label = fluid.layers.data(name="label", shape=[1], dtype="int64")
        h = fluid.layers.fc(input=x, size=32, act="relu")
        h = fluid.layers.fc(input=h, size=32, act="relu")
        pred = fluid.layers.fc(input=h, size=4, act="softmax")
        loss = fluid.layers.reduce_mean(
            fluid.layers.cross_entropy(input=pred, label=label))
        fluid.optimizer.SGD(learning_rate=lr).minimize(loss)
    GradAllReduce().transpile(program=main, startup_program=startup,
                              rank=rank, nranks=nranks)
    main._num_trainers = nranks
    return main, startup, loss


class TestBucketedAllreduce:
    def test_coalesces_into_buckets(self):
        main, startup, loss = build_dp_mlp()
        n_before = op_types(main).count("c_allreduce_sum")
        assert n_before == 6
        fused, report = fusion.resolve_fused_program(
            main, targets=[loss.name])
        types = op_types(fused)
        assert types.count("c_fused_allreduce_sum") == 1
        assert types.count("c_allreduce_sum") == 0
        (rw,) = [r for r in report.applied if r.family == "allreduce"]
        assert rw.predicted["collectives_removed"] == 5

    def test_bucket_cap_splits(self, monkeypatch):
        # grads total ~6.9KB; a 4KB cap must split into >=2 buckets
        monkeypatch.setenv("PADDLE_TPU_ALLREDUCE_BUCKET_MB", "0.004")
        main, startup, loss = build_dp_mlp()
        fused, report = fusion.resolve_fused_program(
            main, targets=[loss.name])
        types = op_types(fused)
        # a bucket surfaces as the fused op, or as a start/wait pair
        # once the overlap scheduler (PR 16) hoists it
        n_buckets = (types.count("c_fused_allreduce_sum")
                     + types.count("c_allreduce_start"))
        assert n_buckets >= 2

    def test_sub_block_closure_read_blocks_coalescing(self):
        """A conditional body reading a grad by closure (no input slot)
        between its allreduce and the flush site would see the
        un-reduced local value — that member must stay unfused."""
        main, startup, loss = build_dp_mlp()
        block = main.global_block()
        idxs = [i for i, op in enumerate(block.ops)
                if op.type == "c_allreduce_sum"]
        g = block.ops[idxs[0]].inputs["X"][0]
        sub = main._create_block()
        sub.create_var(name="peek", shape=[1], dtype="float32")
        sub.append_op(type="scale", inputs={"X": [g]},
                      outputs={"Out": ["peek"]}, attrs={"scale": 1.0})
        from paddle_tpu.framework import Operator
        cf = Operator(block, "conditional_block", inputs={}, outputs={},
                      attrs={"sub_block": sub.idx})
        block.ops.insert(idxs[0] + 1, cf)
        fused, report = fusion.resolve_fused_program(
            main, targets=[loss.name])
        skips = [s for s in report.skipped if s.family == "allreduce"]
        assert any(g in s.reason for s in skips), [s.reason for s in skips]
        types = op_types(fused)
        assert types.count("c_allreduce_sum") == 1  # the guarded member
        assert types.count("c_fused_allreduce_sum") == 1  # the rest

    def test_schedule_passes_deadlock_proof(self):
        w = []
        for rank in range(2):
            main, _, loss = build_dp_mlp(rank=rank)
            fused, _ = fusion.resolve_fused_program(
                main, targets=[loss.name])
            w.append(fused)
        schedules, diags = prove_deadlock_free(w, nranks=2)
        assert diags == []
        evs = schedules[0].get(0, [])
        assert [e.op_type for e in evs] == ["c_fused_allreduce_sum"]
        # ICI payload is the SUM of the coalesced members
        assert evs[0].numel == 16 * 32 + 32 + 32 * 32 + 32 + 32 * 4 + 4

    def test_gspmd_identity_bit_exact(self, monkeypatch):
        """Under the GSPMD (no shard_map) path the bucketed collective
        is an identity like the scalar one: training is bit-exact with
        the unfused program."""
        rng = np.random.RandomState(5)
        feed = {"x": rng.rand(8, 16).astype("float32"),
                "label": rng.randint(0, 4, (8, 1)).astype("int64")}
        monkeypatch.setenv("PADDLE_TPU_FUSION", "0")
        m, s, loss = build_dp_mlp()
        off, _ = run_steps(m, s, feed, [loss.name])
        monkeypatch.setenv("PADDLE_TPU_FUSION", "1")
        m, s, loss = build_dp_mlp()
        rep = fusion.resolve_fused_program(m, targets=[loss.name])[1]
        assert rep.counts().get("allreduce") == 1
        on, _ = run_steps(m, s, feed, [loss.name])
        # softmax_xent also fires on both arms? no: fusion-off arm is
        # fully unfused; compare within the documented tolerance
        np.testing.assert_allclose(on, off, rtol=1e-5)


# ---------------------------------------------------------------------------
# kill switch, report, cache-key separation
# ---------------------------------------------------------------------------

class TestIntrospectionAndCaching:
    def test_kill_switch_disables_everything(self, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_FUSION", "0")
        main, startup, loss, acc, pred = build_mnist_mlp()
        fused, report = fusion.resolve_fused_program(
            main, targets=[loss.name])
        assert fused is main
        assert report.applied == []
        assert not report.config.enabled

    def test_compiled_program_fusion_report(self):
        main, startup, loss, acc, pred = build_mnist_mlp()
        cp = fluid.CompiledProgram(main)
        report = cp.fusion_report()
        assert report.counts().get("softmax_xent") == 1
        assert "softmax_with_cross_entropy" in report.format()

    def test_build_strategy_flags_gate_families(self):
        main, startup, loss, acc, pred = build_mnist_mlp()
        bs = fluid.BuildStrategy()
        bs.fuse_all_optimizer_ops = False
        bs.fuse_elewise_add_act_ops = False
        config = FusionConfig.from_build_strategy(bs)
        fused, report = fusion.resolve_fused_program(
            main, config=config, targets=[loss.name])
        counts = report.counts()
        assert counts.get("optimizer") is None
        assert counts.get("bias_act") is None
        assert counts.get("softmax_xent") == 1  # its own flag, still on

    def test_plain_compiled_program_honors_disabled_flags(self):
        """Regression: with a BuildStrategy that disables a family, the
        plain (non-DP) CompiledProgram path must NOT fall back to the
        default config in Executor.run — even when the strategy's own
        resolve applies zero rewrites."""
        rng = np.random.RandomState(0)
        feed = mlp_feed(rng)
        fluid.unique_name.switch()
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.layers.data(name="img", shape=[32],
                                  dtype="float32")
            h = fluid.layers.fc(input=x, size=8, act="relu")
            loss = fluid.layers.reduce_mean(h)
        bs = fluid.BuildStrategy()
        bs.fuse_elewise_add_act_ops = False  # the ONLY matching family
        cp = fluid.CompiledProgram(main, build_strategy=bs)
        exe = fluid.Executor()
        with scope_guard(Scope()):
            exe.run(startup)
            exe.run(cp, feed={"img": feed["img"]},
                    fetch_list=[loss.name])
            assert cp.fusion_report().counts() == {}
            # the executor must have compiled the UNfused program: no
            # fusion signature in any cache key
            assert all(k[-1] is None for k in exe._cache)

    def test_jit_cache_key_separates_fusion_configs(self, monkeypatch):
        """The same source program under fusion on/off compiles into
        DIFFERENT executor cache entries (fusion config is part of the
        compilation identity)."""
        rng = np.random.RandomState(0)
        feed = mlp_feed(rng)
        main, startup, loss, acc, pred = build_mnist_mlp()
        exe = fluid.Executor()
        with scope_guard(Scope()):
            exe.run(startup)
            monkeypatch.setenv("PADDLE_TPU_FUSION", "1")
            exe.run(main, feed=feed, fetch_list=[loss.name])
            n_on = len(exe._cache)
            monkeypatch.setenv("PADDLE_TPU_FUSION", "0")
            exe.run(main, feed=feed, fetch_list=[loss.name])
            assert len(exe._cache) > n_on
            keys = list(exe._cache)
            sigs = {k[-1] for k in keys if len(k) >= 8}
            assert None in sigs and len(sigs) >= 2

    def test_resolution_is_cached(self):
        main, startup, loss, acc, pred = build_mnist_mlp()
        f1, r1 = fusion.resolve_fused_program(main, targets=[loss.name])
        f2, r2 = fusion.resolve_fused_program(main, targets=[loss.name])
        assert f1 is f2 and r1 is r2

    def test_resolve_cache_is_bounded(self):
        """A serving loop fetching distinct var subsets must not
        accumulate unbounded program clones on the source program."""
        main, startup, loss, acc, pred = build_mnist_mlp()
        names = [loss.name, acc.name, pred.name]
        for i in range(fusion._FUSION_CACHE_CAP + 8):
            fusion.resolve_fused_program(
                main, targets=names[:1 + i % 3] + ["dummy_%d" % i])
        assert len(main.__dict__["_fusion_cache"]) \
            <= fusion._FUSION_CACHE_CAP

    def test_scan_is_side_effect_free(self):
        main, startup, loss, acc, pred = build_mnist_mlp()
        before = op_types(main)
        report = fusion.scan_fusible_patterns(main, targets=[loss.name])
        assert op_types(main) == before
        assert report.counts().get("softmax_xent") == 1


# ---------------------------------------------------------------------------
# lint checks
# ---------------------------------------------------------------------------

class TestLintChecks:
    def test_fused_op_missing_grad_fires(self):
        from paddle_tpu.ops.registry import register_op

        register_op("fused_test_nograd", inputs=["X"], outputs=["Out"],
                    no_grad=True)(lambda ctx, attrs, X: X)
        fluid.unique_name.switch()
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.layers.data(name="x", shape=[4], dtype="float32")
            w = fluid.layers.create_parameter([4], "float32", name="w")
            h = fluid.layers.elementwise_mul(x, w)
            block = main.global_block()
            out = block.create_var(name="ftng_out", shape=[-1, 4],
                                   dtype="float32")
            block.append_op(type="fused_test_nograd",
                            inputs={"X": [h]}, outputs={"Out": [out]})
            # the loss DEMANDS a gradient through the fused op (the
            # parallel h path keeps minimize able to produce w@GRAD)
            loss = fluid.layers.reduce_mean(
                fluid.layers.elementwise_add(out, h))
            fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
        diags = verify_program(main, targets=[loss.name])
        hits = [d for d in diags if d.check == "fused-op-missing-grad"]
        assert hits, [str(d) for d in diags]
        from paddle_tpu.static_analysis import Severity

        assert hits[0].severity == Severity.ERROR

    def test_metrics_only_fused_op_does_not_fire_missing_grad(self):
        """A no_grad fused op on a fetch/metrics-only branch demands no
        gradient — training is correct, so no ERROR."""
        from paddle_tpu.ops.registry import register_op

        register_op("fused_test_nograd2", inputs=["X"], outputs=["Out"],
                    no_grad=True)(lambda ctx, attrs, X: X)
        fluid.unique_name.switch()
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.layers.data(name="x", shape=[4], dtype="float32")
            w = fluid.layers.create_parameter([4], "float32", name="w2")
            h = fluid.layers.elementwise_mul(x, w)
            block = main.global_block()
            metric = block.create_var(name="ftng2_out", shape=[-1, 4],
                                      dtype="float32")
            block.append_op(type="fused_test_nograd2",
                            inputs={"X": [h]}, outputs={"Out": [metric]})
            loss = fluid.layers.reduce_mean(h)
            fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
        diags = verify_program(main, targets=[loss.name, metric.name])
        hits = [d for d in diags if d.check == "fused-op-missing-grad"]
        assert not hits, [str(d) for d in hits]

    def test_pipeline_fused_ops_do_not_trip_missing_grad(self):
        main, startup, loss, acc, pred = build_mnist_mlp()
        fused, _ = fusion.resolve_fused_program(main, targets=[loss.name])
        diags = verify_program(fused, targets=[loss.name])
        assert not [d for d in diags
                    if d.check == "fused-op-missing-grad"]

    def test_fusible_pattern_not_fused_advisory(self, monkeypatch):
        """A matched-but-cost-gated pattern surfaces as an INFO
        advisory naming the cost-model reason."""
        monkeypatch.setenv("PADDLE_TPU_FLASH_MIN_T", "512")
        main, startup, feeds, loss, cfg = build_bert_tiny(seq_len=32)
        diags = verify_program(main, targets=[loss.name])
        hits = [d for d in diags
                if d.check == "fusible-pattern-not-fused"]
        assert hits
        assert any("flash engagement threshold" in d.message
                   for d in hits)

    def test_kill_switch_surfaces_disabled_patterns(self, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_FUSION", "0")
        main, startup, loss, acc, pred = build_mnist_mlp()
        diags = verify_program(main, targets=[loss.name])
        hits = [d for d in diags
                if d.check == "fusible-pattern-not-fused"
                and "PADDLE_TPU_FUSION=0" in d.message]
        assert hits


# ---------------------------------------------------------------------------
# pallas fallback plumbing (satellite)
# ---------------------------------------------------------------------------

class TestPallasFallback:
    def test_pallas_supported_flag_exists(self):
        from paddle_tpu.ops.pallas.flash_attention import pallas_supported

        assert isinstance(pallas_supported(), bool)

    def test_rewritten_attention_runs_on_cpu_without_pallas(
            self, monkeypatch):
        """The fused attention op reached by the REWRITE (not the model
        builder) must execute on CPU via the XLA composite — the tier-1
        guarantee that the fusion plumbing is exercised without Pallas."""
        monkeypatch.setenv("PADDLE_TPU_FLASH_MIN_T", "16")
        monkeypatch.delenv("PADDLE_TPU_PALLAS", raising=False)
        rng = np.random.RandomState(7)
        from paddle_tpu.models import bert

        main, startup, feeds, loss, cfg = build_bert_tiny(dropout=0.0)
        fused, report = fusion.resolve_fused_program(
            main, targets=[loss.name])
        assert report.counts().get("attention") == 2
        batch = bert.make_fake_batch(2, 32, cfg, rng)
        out, _ = run_steps(main, startup, batch, [loss.name], steps=2)
        assert np.isfinite(out).all()


# ---------------------------------------------------------------------------
# conv + batch_norm + act family (ISSUE 6)
# ---------------------------------------------------------------------------

def build_conv_bn(act="relu", train=True, width=8, hw=16):
    """conv(bias-free) -> batch_norm(act) x2 -> pool -> fc: two
    conv_bn_act sites (one with act, one without)."""
    fluid.unique_name.switch()
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        img = fluid.layers.data(name="img", shape=[width, hw, hw],
                                dtype="float32")
        label = fluid.layers.data(name="label", shape=[1], dtype="int64")
        c = fluid.layers.conv2d(img, num_filters=width, filter_size=3,
                                padding=1, bias_attr=False)
        h = fluid.layers.batch_norm(c, act=act)
        c2 = fluid.layers.conv2d(h, num_filters=width, filter_size=3,
                                 padding=1, bias_attr=False)
        h2 = fluid.layers.batch_norm(c2, act=None)
        pool = fluid.layers.pool2d(h2, pool_size=hw, pool_type="avg")
        pred = fluid.layers.fc(pool, size=10, act="softmax")
        loss = fluid.layers.reduce_mean(
            fluid.layers.cross_entropy(input=pred, label=label))
        if train:
            fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    return main, startup, loss


def conv_feed(rng, bs=4, width=8, hw=16):
    return {"img": rng.randn(bs, width, hw, hw).astype("float32"),
            "label": rng.randint(0, 10, (bs, 1)).astype("int64")}


class TestConvBnActFamily:
    def test_rewrite_golden_with_and_without_act(self):
        main, startup, loss = build_conv_bn()
        fused, report = fusion.resolve_fused_program(
            main, targets=[loss.name])
        assert report.counts().get("conv_bn_act") == 2
        types = op_types(fused)
        assert types.count("fused_conv_bn_act") == 2
        assert types.count("fused_conv_bn_act_grad") == 2
        assert types.count("batch_norm") == 0
        assert types.count("conv2d") == 0
        # one site carries the act, the other is the bare conv+bn close
        acts = [op.attrs.get("act_type")
                for op in fused.global_block().ops
                if op.type == "fused_conv_bn_act"]
        assert sorted(acts) == ["", "relu"]
        verify_program(fused, targets=[loss.name])

    def test_resnet_builder_fuses_every_conv_bn_site(self):
        from paddle_tpu.models import resnet

        fluid.unique_name.switch()
        main, startup, feeds, loss, acc = resnet.build(dataset="cifar10")
        fused, report = fusion.resolve_fused_program(
            main, targets=[loss.name])
        # depth-20 cifar resnet: 1 stem + 18 block convs + 2 shortcut
        # projections, every one behind a batch_norm
        assert report.counts().get("conv_bn_act") == 21
        assert op_types(fused).count("batch_norm") == 0

    def test_train_bit_exact_family_isolated(self, monkeypatch):
        """Fusion-on vs conv-family-gated-off over real train steps is
        BIT-EXACT on the XLA composite path (the acceptance bar)."""
        rng = np.random.RandomState(0)
        feed = conv_feed(rng)

        def arm(gate):
            if gate is not None:
                monkeypatch.setenv("PADDLE_TPU_CONV_BN_MIN_BYTES", gate)
            else:
                monkeypatch.delenv("PADDLE_TPU_CONV_BN_MIN_BYTES",
                                   raising=False)
            main, startup, loss = build_conv_bn()
            out, _ = run_steps(main, startup, feed, [loss.name], steps=4)
            return out

        on = arm(None)
        off = arm("1000000000000")
        assert np.array_equal(on, off)

    def test_infer_program_rewrites_forward_only(self):
        main, startup, loss = build_conv_bn(train=False)
        fused, report = fusion.resolve_fused_program(
            main, targets=[loss.name])
        assert report.counts().get("conv_bn_act") == 2
        assert not any(t.endswith("_grad") for t in op_types(fused))

    def test_fetched_conv_out_is_never_fused_away(self):
        main, startup, loss = build_conv_bn()
        conv_out = next(op.outputs["Output"][0]
                        for op in main.global_block().ops
                        if op.type == "conv2d")
        fused, report = fusion.resolve_fused_program(
            main, targets=[loss.name, conv_out])
        assert report.counts().get("conv_bn_act", 0) <= 1  # site 1 kept
        assert conv_out in {n for op in fused.global_block().ops
                            for n in op.output_arg_names}

    def test_running_stats_update_identically(self, monkeypatch):
        """MeanOut/VarianceOut ride the fused op: after N steps the
        running stats in scope match the unfused run bit-for-bit."""
        rng = np.random.RandomState(1)
        feed = conv_feed(rng)

        def arm(gate):
            if gate is not None:
                monkeypatch.setenv("PADDLE_TPU_CONV_BN_MIN_BYTES", gate)
            else:
                monkeypatch.delenv("PADDLE_TPU_CONV_BN_MIN_BYTES",
                                   raising=False)
            main, startup, loss = build_conv_bn()
            mean_name = next(
                op.outputs["MeanOut"][0]
                for op in main.global_block().ops
                if op.type == "batch_norm")
            exe = fluid.Executor()
            scope = Scope()
            with scope_guard(scope):
                exe.run(startup)
                for _ in range(3):
                    exe.run(main, feed=feed, fetch_list=[loss.name])
                return np.asarray(scope.get(mean_name))

        on = arm(None)
        off = arm("1000000000000")
        assert np.array_equal(on, off)

    def test_cost_gate_skip_names_uncalibrated_autotune(
            self, monkeypatch, tmp_path):
        """Satellite: the advisory reason carries the autotune state —
        an empty cache reads 'uncalibrated' with the signature to sweep."""
        monkeypatch.setenv("PADDLE_TPU_AUTOTUNE_CACHE",
                           str(tmp_path / "at.json"))
        monkeypatch.setenv("PADDLE_TPU_CONV_BN_MIN_BYTES", "1000000000000")
        from paddle_tpu import autotune
        autotune.reset()
        main, startup, loss = build_conv_bn()
        fused, report = fusion.resolve_fused_program(
            main, targets=[loss.name])
        assert report.counts().get("conv_bn_act") is None
        skips = [s for s in report.skipped if s.family == "conv_bn_act"]
        assert skips
        assert "uncalibrated" in skips[0].reason
        assert "conv_bn_act|" in skips[0].reason  # the signature to sweep
        autotune.reset()

    def test_calibration_flips_the_gate(self, monkeypatch, tmp_path):
        """The measure-and-learn loop closed: a recorded calibration
        factor scales the predicted delta past the gate."""
        from paddle_tpu import autotune

        monkeypatch.setenv("PADDLE_TPU_AUTOTUNE_CACHE",
                           str(tmp_path / "at.json"))
        autotune.reset()
        main, startup, loss = build_conv_bn()
        # gate sits just above the un-calibrated predicted saving
        conv_out_bytes = 8 * 16 * 16 * 4  # batch=1 resolution
        monkeypatch.setenv("PADDLE_TPU_CONV_BN_MIN_BYTES",
                           str(conv_out_bytes * 2))
        _, rep_uncal = fusion.resolve_fused_program(
            main, targets=[loss.name])
        assert rep_uncal.counts().get("conv_bn_act") is None
        # a silicon sweep measured 4x the predicted gain -> gate opens
        ov = next(op for op in main.global_block().ops
                  if op.type == "conv2d").outputs["Output"][0]
        shape = tuple(main.global_block()._find_var_recursive(ov).shape)
        for act in ("relu", "identity"):
            autotune.record(
                autotune.sweep_signature(
                    "conv_bn_act", {"shape": shape, "dtype": "float32",
                                    "act": act}),
                {"params": {}, "calibration": 4.0})
        _, rep_cal = fusion.resolve_fused_program(main,
                                                  targets=[loss.name])
        assert rep_cal.counts().get("conv_bn_act") == 2
        autotune.reset()

    def test_pallas_epilogue_interpret_close_to_xla(self, monkeypatch):
        """PADDLE_TPU_PALLAS=interpret routes the NHWC lane-aligned
        epilogue through the kernel; tolerance documented ~1e-6."""
        monkeypatch.setenv("PADDLE_TPU_PALLAS", "interpret")
        import jax.numpy as jnp
        from paddle_tpu.ops.registry import (LoweringContext, call_op,
                                             get_op_def)

        rng = np.random.RandomState(0)
        x = jnp.asarray(rng.randn(2, 8, 8, 128).astype("float32"))
        w = jnp.asarray(rng.randn(128, 128, 1, 1).astype("float32") * .1)
        g = jnp.asarray(rng.rand(128).astype("float32") + 0.5)
        b = jnp.asarray(rng.randn(128).astype("float32"))
        attrs = {"strides": [1, 1], "paddings": [0, 0],
                 "dilations": [1, 1], "groups": 1,
                 "data_format": "NHWC", "data_layout": "NHWC",
                 "epsilon": 1e-5, "momentum": 0.9, "is_test": False,
                 "act_type": "relu"}
        ins = {"Input": [x], "Filter": [w], "Scale": [g], "Bias": [b],
               "Mean": [jnp.zeros(128)], "Variance": [jnp.ones(128)]}
        fused = get_op_def("fused_conv_bn_act")
        pal = call_op(fused, LoweringContext(), ins, attrs, 1)["Out"][0]
        monkeypatch.setenv("PADDLE_TPU_PALLAS", "off")
        xla = call_op(fused, LoweringContext(), ins, attrs, 1)["Out"][0]
        np.testing.assert_allclose(np.asarray(pal), np.asarray(xla),
                                   rtol=2e-5, atol=2e-5)


# ---------------------------------------------------------------------------
# embedding gather family (ISSUE 6)
# ---------------------------------------------------------------------------

def build_embedding(dim=128, vocab=100, slot_len=16, train=True):
    fluid.unique_name.switch()
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        ids = fluid.layers.data(name="ids", shape=[slot_len],
                                dtype="int64")
        label = fluid.layers.data(name="label", shape=[1], dtype="int64")
        emb = fluid.layers.embedding(
            ids, size=[vocab, dim], padding_idx=0,
            param_attr=fluid.ParamAttr(name="fused_emb_tab"))
        s = fluid.layers.reduce_sum(emb, dim=1)
        pred = fluid.layers.fc(s, size=10, act="softmax")
        loss = fluid.layers.reduce_mean(
            fluid.layers.cross_entropy(input=pred, label=label))
        if train:
            fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    return main, startup, loss


class TestEmbeddingGatherFamily:
    def test_rewrite_golden(self):
        main, startup, loss = build_embedding()
        fused, report = fusion.resolve_fused_program(
            main, targets=[loss.name])
        assert report.counts().get("embedding_gather") == 1
        types = op_types(fused)
        assert types.count("fused_embedding_gather") == 1
        assert types.count("fused_embedding_gather_grad") == 1
        assert "lookup_table" not in types
        assert "lookup_table_grad" not in types
        verify_program(fused, targets=[loss.name])

    def test_train_bit_exact_family_isolated(self, monkeypatch):
        rng = np.random.RandomState(0)
        feed = {"ids": rng.randint(0, 100, (4, 16)).astype("int64"),
                "label": rng.randint(0, 10, (4, 1)).astype("int64")}

        def arm(gate):
            if gate is not None:
                monkeypatch.setenv("PADDLE_TPU_EMBED_FUSE_MIN_BYTES",
                                   gate)
            else:
                monkeypatch.delenv("PADDLE_TPU_EMBED_FUSE_MIN_BYTES",
                                   raising=False)
            main, startup, loss = build_embedding()
            out, _ = run_steps(main, startup, feed, [loss.name], steps=4)
            return out

        on = arm(None)
        off = arm("1000000000000")
        assert np.array_equal(on, off)

    def test_unaligned_dim_skips_with_reason(self):
        main, startup, loss = build_embedding(dim=48)
        fused, report = fusion.resolve_fused_program(
            main, targets=[loss.name])
        assert report.counts().get("embedding_gather") is None
        skips = [s for s in report.skipped
                 if s.family == "embedding_gather"]
        assert skips and "lane-aligned" in skips[0].reason

    def test_deepfm_device_table_path_fuses(self):
        """The DeepFM device-table migration: lane-aligned tables fuse,
        the dim-1 first-order tables are correctly refused, and the
        model trains to finite losses through the fused gather."""
        from paddle_tpu.models import ctr

        losses, report = ctr.run_deepfm_device_table_steps(
            steps=3, num_slots=2, slot_len=3, vocab=200, batch=8,
            embed_dim=128)
        assert report.counts().get("embedding_gather") == 2
        assert all(np.isfinite(l) for l in losses)
        assert losses[0] != losses[-1]  # it actually trains

    def test_lint_advisory_covers_new_families(self, monkeypatch,
                                               tmp_path):
        """Satellite: fusible-pattern-not-fused surfaces the gated-out
        conv+bn+act and embedding-gather sites with the autotune
        cost-gate reason."""
        from paddle_tpu import autotune

        monkeypatch.setenv("PADDLE_TPU_AUTOTUNE_CACHE",
                           str(tmp_path / "at.json"))
        monkeypatch.setenv("PADDLE_TPU_EMBED_FUSE_MIN_BYTES",
                           "1000000000000")
        autotune.reset()
        main, startup, loss = build_embedding()
        diags = verify_program(main, targets=[loss.name])
        hits = [d for d in diags
                if d.check == "fusible-pattern-not-fused"
                and "embedding_gather" in d.message]
        assert hits
        assert any("uncalibrated" in d.message for d in hits)
        autotune.reset()


class TestConvBnActAmp:
    def test_amp_cast_sandwich_is_absorbed(self):
        """The bf16 AMP rewrite cast-sandwiches BN (conv -> cast f32 ->
        bn -> cast bf16 -> act); the matcher absorbs the pair — every
        resnet conv+bn site still fuses under AMP (the bench config)."""
        from paddle_tpu.models import resnet

        fluid.unique_name.switch()
        main, startup, feeds, loss, acc = resnet.build(
            dataset="cifar10", amp=True)
        fused, report = fusion.resolve_fused_program(
            main, targets=[loss.name])
        assert report.counts().get("conv_bn_act") == 21
        assert op_types(fused).count("batch_norm") == 0
        # the rewrite note documents the AMP tolerance exception
        conv_rewrites = [r for r in report.applied
                         if r.family == "conv_bn_act"]
        assert any("AMP cast sandwich" in r.note for r in conv_rewrites)

    def test_amp_train_within_documented_tolerance(self, monkeypatch):
        """AMP A/B: losses track within float-noise tolerance.  NOT
        bit-exact by design — absorbing the cast sandwich lets XLA
        reassociate the BN scale/bias gradient reductions (f32-stored
        grads show ~1e-4 relative noise; bf16-stored conv grads round
        identically) — the documented exception, mirroring the
        softmax_xent ~1e-6 precedent."""
        import jax.numpy as jnp
        from paddle_tpu.models import resnet

        rng = np.random.RandomState(0)
        feed = {"img": jnp.asarray(
                    rng.randn(4, 3, 32, 32).astype("float32")),
                "label": jnp.asarray(
                    rng.randint(0, 10, (4, 1)).astype("int64"))}

        def arm(gate):
            if gate is not None:
                monkeypatch.setenv("PADDLE_TPU_CONV_BN_MIN_BYTES", gate)
            else:
                monkeypatch.delenv("PADDLE_TPU_CONV_BN_MIN_BYTES",
                                   raising=False)
            fluid.unique_name.switch()
            main, startup, feeds, loss, acc = resnet.build(
                dataset="cifar10", amp=True)
            exe = fluid.Executor()
            with scope_guard(Scope()):
                exe.run(startup)
                return [float(np.asarray(exe.run(
                    main, feed=feed, fetch_list=[loss])[0]).reshape(()))
                    for _ in range(3)]

        on = arm(None)
        off = arm("1000000000000")
        assert np.isfinite(on).all() and np.isfinite(off).all()
        np.testing.assert_allclose(on, off, rtol=2e-2, atol=1e-2)
