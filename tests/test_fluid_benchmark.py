"""benchmark/fluid_benchmark.py — the reference harness CLI: model
builders wire up and one bench pass produces the reference's
``examples/sed`` report (reference ``benchmark/fluid/
fluid_benchmark.py:296-300``)."""

import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_cli_mnist_cpu_pass():
    """One mnist pass on CPU through the real CLI prints the per-pass
    and total examples/sed lines and exits 0."""
    res = subprocess.run(
        [sys.executable, os.path.join(REPO, "benchmark",
                                      "fluid_benchmark.py"),
         "--model", "mnist", "--device", "CPU", "--iterations", "4",
         "--batch_size", "16"],
        capture_output=True, text=True, timeout=300, cwd=REPO)
    assert res.returncode == 0, res.stderr[-800:]
    assert "examples/sed" in res.stdout
    assert "Pass: 0" in res.stdout
    assert "Total examples: 64" in res.stdout


def test_build_model_covers_all_workloads():
    """Every --model choice builds a program with a loss var (no
    execution — builder wiring only)."""
    sys.path.insert(0, os.path.join(REPO, "benchmark"))
    import importlib

    import jax

    jax.config.update("jax_platforms", "cpu")
    fb = importlib.import_module("fluid_benchmark")

    class A:
        batch_size = 4
        learning_rate = 1e-3
        no_amp = True

    for m in fb.MODELS:
        A.model = m
        main, startup, feed_fn, loss = fb.build_model(A, on_tpu=False)
        assert loss.name in main.global_block().vars
        feed = feed_fn(4)
        assert isinstance(feed, dict) and feed


def test_require_device_refuses_cpu_fallback(monkeypatch):
    """--require_device turns the dead-tunnel CPU fallback into a
    nonzero exit, so the hardware-capture suite can never record a CPU
    run as a silicon artifact (hw_suite fb_* steps pass this flag)."""
    sys.path.insert(0, os.path.join(REPO, "benchmark"))
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import importlib

    import pytest

    fb = importlib.import_module("fluid_benchmark")
    import hw_suite

    monkeypatch.setattr(hw_suite, "probe",
                        lambda timeout_s=60: (False, "probe down"))
    monkeypatch.setattr(
        sys, "argv",
        ["fluid_benchmark.py", "--model", "mnist", "--device", "TPU",
         "--iterations", "1", "--require_device"])
    with pytest.raises(SystemExit) as ei:
        fb.main()
    assert "refusing the CPU fallback" in str(ei.value)
