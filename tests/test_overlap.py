"""Overlap scheduler (ISSUE 16): hoist/sink goldens on the example
builders, proof-gated revert negatives (in-flight write, asymmetric
ring), the PADDLE_TPU_OVERLAP=0 kill-switch schedule identity, the
FusionConfig.signature overlap-knob fold, quant-bucket pairs, the
planner's three-axis pricing, the new pairing lint checks, and a
prog_gen property sweep (every rewritten schedule re-proves or the
bucket reverts)."""

import json
import os

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.framework import Operator
from paddle_tpu.static_analysis import (FusionConfig,
                                        apply_overlap_pass,
                                        check_schedule_consistency,
                                        extract_collective_schedule,
                                        find_overlap_window_races,
                                        overlap_enabled,
                                        verify_program)
from paddle_tpu.static_analysis import fusion, overlap
from paddle_tpu.static_analysis.cost import (estimate_cost,
                                             overlap_window_table,
                                             price_plan)
from paddle_tpu.transpiler.collective import GradAllReduce

from test_fusion import build_bert_tiny, build_mnist_mlp, op_types

# mnist grads are a few KB: this cap splits them into multi-member
# buckets that close before the optimizer, opening a real window
BUCKET_SMALL = ("PADDLE_TPU_ALLREDUCE_BUCKET_MB", "0.004")


def transpiled_mnist(nranks=2):
    main, startup, loss, acc, pred = build_mnist_mlp()
    GradAllReduce().transpile(program=main, startup_program=startup,
                              rank=0, nranks=nranks)
    main._num_trainers = nranks
    return main, startup, loss


def transpiled_bert(nranks=2):
    main, startup, feeds, loss, cfg = build_bert_tiny()
    GradAllReduce().transpile(program=main, startup_program=startup,
                              rank=0, nranks=nranks)
    main._num_trainers = nranks
    return main, startup, loss


def fused_clone(program, targets):
    """The synchronous-fusion-only rewrite (what resolve produced
    before ISSUE 16): clone + fusion passes, overlap pass not run."""
    clone = program.clone()
    fusion.apply_fusion_passes(clone, FusionConfig(),
                               targets=tuple(targets))
    return clone


def pair_sites(program):
    block = program.global_block()
    starts = [(i, op) for i, op in enumerate(block.ops)
              if op.type == "c_allreduce_start"]
    waits = [(i, op) for i, op in enumerate(block.ops)
             if op.type == "c_allreduce_wait"]
    return starts, waits


class TestHoistSink:
    def test_mnist_hoist_sink_golden(self, monkeypatch):
        monkeypatch.setenv(*BUCKET_SMALL)
        monkeypatch.delenv("PADDLE_TPU_OVERLAP", raising=False)
        main, startup, loss = transpiled_mnist()
        resolved, _ = fusion.resolve_fused_program(
            main, targets=[loss.name])
        report = resolved._overlap_report
        assert len(report.applied) == 1
        (dec,) = report.applied
        starts, waits = pair_sites(resolved)
        assert len(starts) == 1 and len(waits) == 1
        (si, start_op), (wi, wait_op) = starts[0], waits[0]
        # the decision's final coordinates are the real op indices
        assert dec.start_idx == (0, si)
        assert dec.wait_idx == (0, wi)
        assert dec.window_ops == wi - si - 1 >= 1
        members = set(start_op.inputs["X"])
        assert members == set(dec.vars)
        block = resolved.global_block()
        # hoist golden: the op right before the start defines (or
        # reads) a member — the start sits at the earliest legal point
        prev = block.ops[si - 1]
        assert members & (set(prev.output_arg_names)
                          | set(prev.input_arg_names))
        # sink golden: the op right after the wait is the first
        # consumer of a member (the optimizer reads the reduced grad)
        nxt = block.ops[wi + 1]
        assert members & set(nxt.input_arg_names)
        # nothing in the window touches a member
        for j in range(si + 1, wi):
            op = block.ops[j]
            assert not members & set(op.output_arg_names)
            assert not members & set(op.input_arg_names)

    def test_bert_multi_bucket(self, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_ALLREDUCE_BUCKET_MB", "0.2")
        monkeypatch.delenv("PADDLE_TPU_OVERLAP", raising=False)
        main, startup, loss = transpiled_bert()
        resolved, _ = fusion.resolve_fused_program(
            main, targets=[loss.name])
        report = resolved._overlap_report
        assert len(report.applied) >= 2
        starts, waits = pair_sites(resolved)
        assert len(starts) == len(waits) == len(report.applied)
        # twins pair 1:1 by overlap_bucket, start strictly before wait
        for dec in report.applied:
            s = [i for i, op in starts
                 if op.attrs["overlap_bucket"] == dec.bucket]
            w = [i for i, op in waits
                 if op.attrs["overlap_bucket"] == dec.bucket]
            assert len(s) == 1 and len(w) == 1 and s[0] < w[0]
        # the rewritten program is still a valid program (pairing
        # checks included) with no new ERRORs
        diags = verify_program(resolved, targets=[loss.name])
        assert not [d for d in diags if d.severity.name == "ERROR"]

    def test_overlap_windows_priced(self, monkeypatch):
        monkeypatch.setenv(*BUCKET_SMALL)
        monkeypatch.delenv("PADDLE_TPU_OVERLAP", raising=False)
        main, startup, loss = transpiled_mnist()
        resolved, _ = fusion.resolve_fused_program(
            main, targets=[loss.name])
        rep = estimate_cost(resolved, nranks=2, targets=[loss.name])
        assert len(rep.overlap_windows) == 1
        (w,) = rep.overlap_windows
        assert w.wire_bytes > 0 and w.window_flops >= 0
        price = price_plan(rep, ici_gbps=0.001)
        assert price.exposed_wire_ms < price.ici_ms
        assert 0.0 < price.overlap_fraction <= 1.0
        rows = overlap_window_table(rep, ici_gbps=0.001)
        assert len(rows) == 1
        assert rows[0]["verdict"] in ("hidden", "partial")
        # bench_json carries the static overlap numbers
        bench = rep.bench_json()
        assert "static_exposed_wire_ms" in bench
        assert "static_overlap_fraction" in bench

    def test_price_plan_degenerates_without_windows(self, monkeypatch):
        monkeypatch.setenv(*BUCKET_SMALL)
        monkeypatch.setenv("PADDLE_TPU_OVERLAP", "0")
        main, startup, loss = transpiled_mnist()
        resolved, _ = fusion.resolve_fused_program(
            main, targets=[loss.name])
        rep = estimate_cost(resolved, nranks=2, targets=[loss.name])
        assert rep.overlap_windows == []
        price = price_plan(rep, ici_gbps=0.001)
        # no windows: exposed wire IS the wire, fraction 0 — the old
        # additive formula exactly
        assert price.exposed_wire_ms == price.ici_ms
        assert price.overlap_fraction == 0.0
        assert "static_exposed_wire_ms" not in rep.bench_json()


class TestProofsAndRevert:
    def test_inflight_write_reverts(self, monkeypatch):
        """A start misplaced above a member's last def puts that def
        INSIDE the window — the race prover must reject and the pass
        must revert the bucket to its fused synchronous form."""
        monkeypatch.setenv(*BUCKET_SMALL)
        monkeypatch.delenv("PADDLE_TPU_OVERLAP", raising=False)
        main, startup, loss = transpiled_mnist()
        clone = fused_clone(main, [loss.name])
        monkeypatch.setattr(overlap, "_start_position",
                            lambda program, block, members, fi: 0)
        report = apply_overlap_pass(clone, targets=(loss.name,),
                                    nranks=2)
        assert not report.applied
        assert any(d.status == "reverted-race" for d in report.decisions)
        # reverted means the fused op is back and no pair ops remain
        types = op_types(clone)
        assert "c_fused_allreduce_sum" in types
        assert "c_allreduce_start" not in types
        assert "c_allreduce_wait" not in types

    def test_race_prover_flags_window_write(self, monkeypatch):
        monkeypatch.setenv(*BUCKET_SMALL)
        monkeypatch.delenv("PADDLE_TPU_OVERLAP", raising=False)
        main, startup, loss = transpiled_mnist()
        resolved, _ = fusion.resolve_fused_program(
            main, targets=[loss.name])
        assert find_overlap_window_races(resolved) == []
        block = resolved.global_block()
        (si, start_op), _ = pair_sites(resolved)[0][0], None
        g = start_op.inputs["X"][0]
        block.ops.insert(si + 1, Operator(
            block, "scale", {"X": [g]}, {"Out": [g]}, {"scale": 2.0}))
        resolved._bump_version()
        diags = find_overlap_window_races(resolved)
        assert len(diags) == 1
        assert diags[0].check == "race-inflight-write"
        assert diags[0].severity.name == "ERROR"
        assert g in diags[0].var_names

    def test_asymmetric_ring_rejected(self, monkeypatch):
        """Two workers starting the same ring's buckets in different
        orders is the classic collective deadlock — the prover must
        reject the hand-built asymmetric schedule."""
        monkeypatch.setenv("PADDLE_TPU_ALLREDUCE_BUCKET_MB", "0.002")
        monkeypatch.delenv("PADDLE_TPU_OVERLAP", raising=False)
        main, startup, loss = transpiled_mnist()
        resolved, _ = fusion.resolve_fused_program(
            main, targets=[loss.name])
        s0 = extract_collective_schedule(resolved, worker=0, nranks=2)
        assert any(e.op_type == "c_allreduce_start"
                   for e in s0.get(0, ()))
        assert len(s0[0]) >= 2
        assert check_schedule_consistency([s0, s0]) == []
        # worker 1 launches ring 0's first two collectives (the hoisted
        # start among them) in the opposite order — asymmetric ring
        s1 = {r: list(evs) for r, evs in s0.items()}
        s1[0][0], s1[0][1] = s1[0][1], s1[0][0]
        diags = check_schedule_consistency([s0, s1])
        assert any(d.severity.name == "ERROR" for d in diags)

    def test_rewritten_schedule_proves_deadlock_free(self, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_ALLREDUCE_BUCKET_MB", "0.2")
        monkeypatch.delenv("PADDLE_TPU_OVERLAP", raising=False)
        main, startup, loss = transpiled_bert()
        resolved, _ = fusion.resolve_fused_program(
            main, targets=[loss.name])
        assert resolved._overlap_report.applied
        s0 = extract_collective_schedule(resolved, worker=0, nranks=2)
        assert check_schedule_consistency([s0, s0]) == []

    def test_prog_gen_property_sweep(self, monkeypatch):
        """Random programs: the overlap resolve either applies with
        both proofs clean or reverts — never ships an unproven
        schedule, never crashes."""
        from prog_gen import gen_program

        monkeypatch.setenv(*BUCKET_SMALL)
        monkeypatch.delenv("PADDLE_TPU_OVERLAP", raising=False)
        for seed in range(8):
            main, startup, fetches = gen_program(seed, train=True)
            GradAllReduce().transpile(program=main,
                                      startup_program=startup,
                                      rank=0, nranks=2)
            main._num_trainers = 2
            resolved, _ = fusion.resolve_fused_program(
                main, targets=list(fetches))
            assert find_overlap_window_races(resolved) == []
            report = getattr(resolved, "_overlap_report", None)
            if report is not None and report.applied:
                s0 = extract_collective_schedule(resolved, worker=0,
                                                 nranks=2)
                assert check_schedule_consistency([s0, s0]) == []


class TestKillSwitchAndSignature:
    def test_kill_switch_restores_schedule_bit_exactly(self,
                                                       monkeypatch):
        monkeypatch.setenv(*BUCKET_SMALL)
        monkeypatch.setenv("PADDLE_TPU_OVERLAP", "0")
        main, startup, loss = transpiled_mnist()
        resolved, _ = fusion.resolve_fused_program(
            main, targets=[loss.name])
        baseline = fused_clone(main, [loss.name])

        def sig(program):
            return [(op.type, sorted(op.inputs.items()),
                     sorted(op.outputs.items()))
                    for op in program.global_block().ops]

        assert sig(resolved) == sig(baseline)
        assert "c_allreduce_start" not in op_types(resolved)

    def test_overlap_enabled_precedence(self, monkeypatch):
        main, _, _ = transpiled_mnist()
        # default: on
        monkeypatch.delenv("PADDLE_TPU_OVERLAP", raising=False)
        assert overlap_enabled() and overlap_enabled(main)
        # env beats default
        monkeypatch.setenv("PADDLE_TPU_OVERLAP", "0")
        assert not overlap_enabled(main)
        # mark beats env, in BOTH directions
        main._overlap = True
        assert overlap_enabled(main)
        monkeypatch.setenv("PADDLE_TPU_OVERLAP", "1")
        main._overlap = False
        assert not overlap_enabled(main)
        assert overlap_enabled()  # no mark -> env still wins

    def test_signature_folds_overlap_knob(self, monkeypatch):
        """The PR-15 bucket-cap lesson, replayed for overlap: the
        resolved-clone cache and the jit cache key both derive from
        FusionConfig.signature, so the knob MUST be in it — stamping
        ``_overlap`` after a resolve must invalidate the cached
        clone."""
        monkeypatch.setenv(*BUCKET_SMALL)
        monkeypatch.delenv("PADDLE_TPU_OVERLAP", raising=False)
        cfg = FusionConfig()
        main, startup, loss = transpiled_mnist()
        s_default = cfg.signature(main)
        monkeypatch.setenv("PADDLE_TPU_OVERLAP", "0")
        assert cfg.signature(main) != s_default
        monkeypatch.delenv("PADDLE_TPU_OVERLAP", raising=False)

        resolved, _ = fusion.resolve_fused_program(
            main, targets=[loss.name])
        assert "c_allreduce_start" in op_types(resolved)
        # stamp the mark AFTER the resolve: the next resolve must miss
        # the cached overlapped clone and return the fused form
        main._overlap = False
        assert cfg.signature(main) != s_default
        resolved2, _ = fusion.resolve_fused_program(
            main, targets=[loss.name])
        types2 = op_types(resolved2)
        assert "c_allreduce_start" not in types2
        assert "c_fused_allreduce_sum" in types2


class TestQuantInteraction:
    def test_quant_bucket_splits_into_quant_start(self, monkeypatch):
        monkeypatch.setenv(*BUCKET_SMALL)
        monkeypatch.setenv("PADDLE_TPU_QUANT", "1")
        monkeypatch.setenv("PADDLE_TPU_QUANT_MIN_BYTES", "1")
        monkeypatch.delenv("PADDLE_TPU_OVERLAP", raising=False)
        main, startup, loss = transpiled_mnist()
        resolved, _ = fusion.resolve_fused_program(
            main, targets=[loss.name])
        starts, waits = pair_sites(resolved)
        assert starts and waits
        quant_starts = [op for _, op in starts
                        if op.attrs.get("quant")]
        assert quant_starts, "quant bucket should split into a " \
                             "quant-carrying start"
        report = resolved._overlap_report
        assert any(d.quant and d.status == "applied"
                   for d in report.decisions)
        # the quantized window's wire bytes use the int8+sidecar model
        rep = estimate_cost(resolved, nranks=2, targets=[loss.name])
        qw = [w for w in rep.overlap_windows if w.quant]
        assert qw and all(w.wire_bytes > 0 for w in qw)


class TestPlannerThirdAxis:
    SPEC = {"chips": 4, "peak_tflops": 0.05, "ici_gbps": 0.2,
            "launch_us": 1.0}

    def test_axis_enumerated_and_prices_lower(self, monkeypatch):
        from paddle_tpu.parallel.planner import (ClusterSpec,
                                                 auto_transpile)

        monkeypatch.setenv("PADDLE_TPU_PLAN_BUCKETS_MB", "1")
        monkeypatch.delenv("PADDLE_TPU_OVERLAP", raising=False)
        main, startup, feeds, loss, cfg = build_bert_tiny()
        res = auto_transpile(main, ClusterSpec(**self.SPEC),
                             startup_program=startup,
                             targets=[loss.name])
        dp = {(c.candidate.zero1, c.candidate.quant,
               c.candidate.overlap): c
              for c in res.candidates if c.candidate.kind == "dp"}
        # three axes: overlap twin exists for every (zero1, quant) combo
        for (z, q, ov) in list(dp):
            assert (z, q, not ov) in dp
        sync = dp[(False, False, False)].price
        over = dp[(False, False, True)].price
        assert over.exposed_wire_ms < sync.exposed_wire_ms
        assert over.step_ms < sync.step_ms
        assert over.overlap_fraction > 0.0
        # to_dict carries the axis; describe names it
        c = dp[(False, False, True)].candidate
        assert c.to_dict()["overlap"] is True
        assert "+overlap" in c.describe()

    def test_kill_switch_removes_axis(self, monkeypatch):
        from paddle_tpu.parallel.planner import (ClusterSpec,
                                                 auto_transpile)

        monkeypatch.setenv("PADDLE_TPU_OVERLAP", "0")
        main, startup, loss = transpiled_mnist(nranks=1)
        res = auto_transpile(main, ClusterSpec(**self.SPEC),
                             targets=[loss.name])
        assert not any(getattr(c.candidate, "overlap", False)
                       for c in res.candidates)

    def test_apply_plan_stamps_mark_and_runtime_config(self,
                                                       monkeypatch):
        from paddle_tpu.parallel.planner import (ClusterSpec,
                                                 auto_transpile,
                                                 apply_plan,
                                                 select_dp_standin)

        monkeypatch.setenv("PADDLE_TPU_PLAN_BUCKETS_MB", "1")
        monkeypatch.delenv("PADDLE_TPU_OVERLAP", raising=False)
        main, startup, feeds, loss, cfg = build_bert_tiny()
        res = auto_transpile(main, ClusterSpec(**self.SPEC),
                             startup_program=startup,
                             targets=[loss.name])
        applied = apply_plan(main, res, startup_program=startup)
        # axis searched -> verdict realized on the program either way
        assert main._overlap == applied.overlap
        dp_pc = select_dp_standin(res)
        bs, env = res.runtime_config()
        assert env["PADDLE_TPU_OVERLAP"] in ("0", "1")
        expected = "1" if getattr(res.plan.candidate, "overlap",
                                  False) else "0"
        assert env["PADDLE_TPU_OVERLAP"] == expected


class TestPairingLintChecks:
    def _rewritten(self, monkeypatch):
        monkeypatch.setenv(*BUCKET_SMALL)
        monkeypatch.delenv("PADDLE_TPU_OVERLAP", raising=False)
        main, startup, loss = transpiled_mnist()
        resolved, _ = fusion.resolve_fused_program(
            main, targets=[loss.name])
        return resolved, loss

    @staticmethod
    def _checks(diags):
        return [d.check for d in diags
                if d.check in ("collective-start-without-wait",
                               "wait-without-start", "double-wait")]

    def test_clean_pair_is_silent(self, monkeypatch):
        resolved, loss = self._rewritten(monkeypatch)
        assert self._checks(
            verify_program(resolved, targets=[loss.name])) == []

    def test_start_without_wait(self, monkeypatch):
        resolved, loss = self._rewritten(monkeypatch)
        block = resolved.global_block()
        wi = next(i for i, op in enumerate(block.ops)
                  if op.type == "c_allreduce_wait")
        del block.ops[wi]
        resolved._bump_version()
        assert self._checks(
            verify_program(resolved, targets=[loss.name])) \
            == ["collective-start-without-wait"]

    def test_wait_without_start(self, monkeypatch):
        resolved, loss = self._rewritten(monkeypatch)
        block = resolved.global_block()
        si = next(i for i, op in enumerate(block.ops)
                  if op.type == "c_allreduce_start")
        del block.ops[si]
        resolved._bump_version()
        assert self._checks(
            verify_program(resolved, targets=[loss.name])) \
            == ["wait-without-start"]

    def test_double_wait(self, monkeypatch):
        resolved, loss = self._rewritten(monkeypatch)
        block = resolved.global_block()
        wi = next(i for i, op in enumerate(block.ops)
                  if op.type == "c_allreduce_wait")
        block.ops.insert(wi + 1, block.ops[wi])
        resolved._bump_version()
        assert self._checks(
            verify_program(resolved, targets=[loss.name])) \
            == ["double-wait"]

    def test_advisory_on_kill_switch(self, monkeypatch):
        monkeypatch.setenv(*BUCKET_SMALL)
        monkeypatch.setenv("PADDLE_TPU_OVERLAP", "0")
        main, startup, loss = transpiled_mnist()
        resolved, _ = fusion.resolve_fused_program(
            main, targets=[loss.name])
        diags = [d for d in verify_program(resolved,
                                           targets=[loss.name])
                 if d.check == "overlap-opportunity-unexploited"]
        assert diags
        assert all(d.severity.name == "INFO" for d in diags)
        assert any("PADDLE_TPU_OVERLAP=0" in d.message for d in diags)


class TestExecutionParity:
    def test_single_device_losses_identical(self, monkeypatch):
        """GSPMD path: collectives are identity, so overlap on/off must
        produce bit-identical training (the pair really is a pure
        schedule change)."""
        from test_fusion import mlp_feed, run_steps

        monkeypatch.setenv(*BUCKET_SMALL)

        feed = mlp_feed(np.random.RandomState(7))

        def losses(ov):
            monkeypatch.setenv("PADDLE_TPU_OVERLAP", ov)
            main, startup, loss = transpiled_mnist(nranks=1)
            out, _ = run_steps(main, startup, feed, [loss.name],
                               steps=3)
            return out

        np.testing.assert_array_equal(losses("1"), losses("0"))


class TestAnalyzeCLI:
    def test_overlap_flag_json(self, tmp_path, monkeypatch, capsys):
        from paddle_tpu.proto import save_program
        from paddle_tpu.tools import analyze_program as cli

        monkeypatch.setenv(*BUCKET_SMALL)
        monkeypatch.delenv("PADDLE_TPU_OVERLAP", raising=False)
        main, startup, loss = transpiled_mnist()
        path = str(tmp_path / "prog.json")
        save_program(main, path)
        rc = cli.main(["--program-json", path, "--overlap",
                       "--nranks", "2", "--json"])
        out = json.loads(capsys.readouterr().out)
        assert rc == 0
        ov = out["overlap"]
        assert ov["windows"] and ov["report"]["enabled"]
        row = ov["windows"][0]
        for key in ("bucket", "start", "wait", "window_compute_ms",
                    "wire_ms", "exposed_ms", "verdict"):
            assert key in row

    def test_overlap_flag_table(self, tmp_path, monkeypatch, capsys):
        from paddle_tpu.proto import save_program
        from paddle_tpu.tools import analyze_program as cli

        monkeypatch.setenv(*BUCKET_SMALL)
        monkeypatch.delenv("PADDLE_TPU_OVERLAP", raising=False)
        main, startup, loss = transpiled_mnist()
        path = str(tmp_path / "prog.json")
        save_program(main, path)
        rc = cli.main(["--program-json", path, "--overlap",
                       "--nranks", "2"])
        out = capsys.readouterr().out
        assert rc == 0
        assert "overlap windows" in out
        assert "verdict" in out and "exposed ms" in out
