"""Ring attention (sequence parallelism) on the virtual 8-device CPU mesh —
the reference's "fake cluster" test pattern (test_dist_base.py) applied to
the net-new sequence-parallel capability.  Oracle: the single-device XLA
attention."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from paddle_tpu.parallel import ring_attention
from paddle_tpu.ops.pallas.flash_attention import mha_reference


@pytest.fixture(scope="module")
def data():
    rng = np.random.RandomState(0)
    B, H, T, D = 2, 2, 256, 32
    q, k, v = (
        jnp.asarray(rng.randn(B, H, T, D).astype("float32"))
        for _ in range(3)
    )
    bias = jnp.asarray(
        np.where(rng.rand(B, T) < 0.2, -1e4, 0).astype("float32")
    )
    return q, k, v, bias


@pytest.fixture(scope="module")
def sp_mesh():
    return Mesh(np.array(jax.devices()[:8]).reshape(8), ("sp",))


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("with_bias", [False, True])
def test_ring_matches_full_attention(data, sp_mesh, causal, with_bias):
    q, k, v, bias = data
    b_ = bias if with_bias else None
    o1 = ring_attention(q, k, v, sp_mesh, "sp", bias=b_, causal=causal)
    o2 = mha_reference(q, k, v, bias=b_, causal=causal)
    np.testing.assert_allclose(o1, o2, atol=3e-5, rtol=3e-5)


def test_ring_grads(data, sp_mesh):
    q, k, v, bias = data

    def loss(fn):
        return lambda q, k, v: jnp.sum(
            fn(q, k, v) * v
        )

    g1 = jax.grad(
        loss(lambda q, k, v: ring_attention(
            q, k, v, sp_mesh, "sp", bias=bias, causal=True)),
        argnums=(0, 1, 2),
    )(q, k, v)
    g2 = jax.grad(
        loss(lambda q, k, v: mha_reference(
            q, k, v, bias=bias, causal=True)),
        argnums=(0, 1, 2),
    )(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(a, b, atol=1e-3, rtol=1e-3)


def test_ring_dp_sp_mesh_under_jit(data):
    """dp x sp mesh: batch sharded over 'data', sequence ring over 'sp',
    whole thing under jit (the way a training step uses it)."""
    q, k, v, bias = data
    mesh = Mesh(np.array(jax.devices()[:8]).reshape(2, 4), ("data", "sp"))

    @jax.jit
    def f(q, k, v):
        return ring_attention(
            q, k, v, mesh, "sp", bias=bias, causal=True, batch_axis="data"
        )

    o1 = f(q, k, v)
    o2 = mha_reference(q, k, v, bias=bias, causal=True)
    np.testing.assert_allclose(o1, o2, atol=3e-5, rtol=3e-5)


def test_ring_rejects_indivisible_seq(sp_mesh):
    q = jnp.zeros((1, 1, 100, 8))
    with pytest.raises(ValueError, match="not divisible"):
        ring_attention(q, q, q, sp_mesh, "sp")
