"""append_backward + optimizer correctness (reference tests:
unittests/test_backward.py, test_optimizer.py — and regression tests for
review findings: apply_gradients no-op, Adam bias correction)."""

import numpy as np

import paddle_tpu as fluid
from paddle_tpu.executor import Scope, scope_guard, global_scope


def _linreg_program(lr=0.1, optimizer=None):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[4], dtype="float32")
        yt = fluid.layers.data("yt", shape=[1], dtype="float32")
        y = fluid.layers.fc(x, size=1)
        loss = fluid.layers.mean(fluid.layers.square_error_cost(y, yt))
    return main, startup, x, yt, loss


def test_append_backward_grads_match_numeric():
    main, startup, x, yt, loss = _linreg_program()
    with fluid.program_guard(main, startup):
        params_grads = fluid.append_backward(loss)
    assert len(params_grads) == 2  # w, b
    exe = fluid.Executor(fluid.CPUPlace())
    with scope_guard(Scope()):
        exe.run(startup)
        rng = np.random.RandomState(0)
        xv = rng.rand(8, 4).astype("float32")
        yv = rng.rand(8, 1).astype("float32")
        p, g = params_grads[0]
        w0 = np.asarray(global_scope().get(p.name))
        analytic = exe.run(
            main, feed={"x": xv, "yt": yv}, fetch_list=[g]
        )[0]
        # numeric gradient (the reference op_test.py oracle)
        eps = 1e-3
        num = np.zeros_like(w0)
        for i in range(w0.shape[0]):
            for j in range(w0.shape[1]):
                for sgn in (+1, -1):
                    w = w0.copy()
                    w[i, j] += sgn * eps
                    global_scope().set(p.name, w)
                    lv = exe.run(
                        main, feed={"x": xv, "yt": yv}, fetch_list=[loss]
                    )[0]
                    num[i, j] += sgn * float(lv[0])
                num[i, j] /= 2 * eps
        global_scope().set(p.name, w0)
        np.testing.assert_allclose(analytic, num, rtol=1e-2, atol=1e-3)


def test_sgd_converges_linear_regression():
    main, startup, x, yt, loss = _linreg_program()
    with fluid.program_guard(main, startup):
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    with scope_guard(Scope()):
        exe.run(startup)
        rng = np.random.RandomState(1)
        w_true = rng.randn(4, 1).astype("float32")
        first = last = None
        for step in range(200):
            xv = rng.randn(32, 4).astype("float32")
            yv = xv @ w_true
            lv = exe.run(main, feed={"x": xv, "yt": yv},
                         fetch_list=[loss])[0]
            if first is None:
                first = float(lv[0])
            last = float(lv[0])
        assert last < 1e-3, (first, last)


def test_backward_then_apply_gradients_trains():
    """apply_gradients alone must append the update ops (review finding:
    the split API silently trained nothing)."""
    main, startup, x, yt, loss = _linreg_program()
    opt = fluid.optimizer.SGD(learning_rate=0.1)
    with fluid.program_guard(main, startup):
        params_grads = opt.backward(loss)
        opt.apply_gradients(params_grads)
    sgd_ops = [op for op in main.global_block().ops if op.type == "sgd"]
    assert len(sgd_ops) == 2
    exe = fluid.Executor(fluid.CPUPlace())
    with scope_guard(Scope()):
        exe.run(startup)
        rng = np.random.RandomState(2)
        w_true = rng.randn(4, 1).astype("float32")
        for _ in range(100):
            xv = rng.randn(32, 4).astype("float32")
            lv = exe.run(main, feed={"x": xv, "yt": xv @ w_true},
                         fetch_list=[loss])[0]
        assert float(lv[0]) < 1e-2


def test_adam_first_step_matches_reference_formula():
    """Regression: bias correction must use beta_pow = beta^t as stored,
    not advance it an extra step (reference adam_op.h:93)."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[1], dtype="float32")
        y = fluid.layers.fc(
            x, size=1, bias_attr=False,
            param_attr=fluid.ParamAttr(
                initializer=fluid.initializer.Constant(0.5)
            ),
        )
        loss = fluid.layers.mean(y)
        fluid.optimizer.Adam(learning_rate=0.1, beta1=0.9, beta2=0.999,
                             epsilon=1e-8).minimize(loss)
    p = main.all_parameters()[0]
    exe = fluid.Executor(fluid.CPUPlace())
    with scope_guard(Scope()):
        exe.run(startup)
        xv = np.ones((1, 1), "float32")
        exe.run(main, feed={"x": xv}, fetch_list=[loss])
        w1 = float(np.asarray(global_scope().get(p.name)).reshape(()))
    # hand-computed Adam step: g=1, m=0.1, v=0.001,
    # lr_t = lr*sqrt(1-0.999)/(1-0.9) = lr*0.31623..., update ≈ -0.1
    g = 1.0
    m = 0.1 * g
    v = 0.001 * g * g
    lr_t = 0.1 * np.sqrt(1 - 0.999) / (1 - 0.9)
    expected = 0.5 - lr_t * m / (np.sqrt(v) + 1e-8)
    np.testing.assert_allclose(w1, expected, rtol=1e-5)


def test_momentum_adam_lamb_all_converge():
    rng = np.random.RandomState(3)
    w_true = rng.randn(4, 1).astype("float32")
    for make_opt in (
        lambda: fluid.optimizer.Momentum(learning_rate=0.05, momentum=0.9),
        lambda: fluid.optimizer.Adam(learning_rate=0.05),
        lambda: fluid.optimizer.Adagrad(learning_rate=0.5),
        lambda: fluid.optimizer.RMSProp(learning_rate=0.05),
        lambda: fluid.optimizer.Lamb(learning_rate=0.05),
    ):
        main, startup, x, yt, loss = _linreg_program()
        with fluid.program_guard(main, startup):
            make_opt().minimize(loss)
        exe = fluid.Executor(fluid.CPUPlace())
        with scope_guard(Scope()):
            exe.run(startup)
            # 300 steps: Lamb's trust-ratio scaling (with its default
            # weight decay) converges slowest on this tiny problem
            for _ in range(300):
                xv = rng.randn(64, 4).astype("float32")
                lv = exe.run(main, feed={"x": xv, "yt": xv @ w_true},
                             fetch_list=[loss])[0]
            assert float(lv[0]) < 0.05, make_opt


def test_init_reproducible_across_builds():
    """Two identical programs built back-to-back (with the global
    unique_name counter advanced in between) must initialize identically:
    random init is keyed on per-program op ids + program.random_seed, not
    on global build history (reference contract: fixed seed => fixed init,
    framework.py Program.random_seed)."""
    inits = []
    for _ in range(2):
        main, startup, x, yt, loss = _linreg_program()
        exe = fluid.Executor(fluid.CPUPlace())
        with scope_guard(Scope()):
            exe.run(startup)
            w = np.asarray(
                global_scope().get(main.all_parameters()[0].name)
            ).copy()
        inits.append(w)
        # perturb global name-counter state between builds
        fluid.layers.data(fluid.unique_name.generate("perturb"),
                          shape=[1], dtype="float32")
    np.testing.assert_array_equal(inits[0], inits[1])


def test_gradients_api():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[3], dtype="float32")
        x.stop_gradient = False
        y = fluid.layers.scale(x, scale=3.0)
        z = fluid.layers.reduce_sum(y)
        (gx,) = fluid.gradients(z, x)
    exe = fluid.Executor(fluid.CPUPlace())
    out = exe.run(main, feed={"x": np.ones((2, 3), "float32")},
                  fetch_list=[gx])[0]
    np.testing.assert_allclose(out, 3.0)


def test_weight_decay_and_grad_clip():
    main, startup, x, yt, loss = _linreg_program()
    with fluid.program_guard(main, startup):
        fluid.clip.set_gradient_clip(
            fluid.clip.GradientClipByGlobalNorm(1.0), program=main
        )
        fluid.optimizer.SGD(
            learning_rate=0.1,
            regularization=fluid.regularizer.L2Decay(0.01),
        ).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    with scope_guard(Scope()):
        exe.run(startup)
        rng = np.random.RandomState(4)
        w_true = rng.randn(4, 1).astype("float32")
        for _ in range(200):
            xv = rng.randn(32, 4).astype("float32")
            lv = exe.run(main, feed={"x": xv, "yt": xv @ w_true},
                         fetch_list=[loss])[0]
        assert float(lv[0]) < 0.1
