"""BuildStrategy.shard_optimizer_state (ZeRO-1): param-shaped Adam
moments partition dim 0 over the data axis under DP — per-chip optimizer
memory drops by dp_degree, training is numerically unchanged.

Reference analogue: the fleet "sharding" strategy (post-v1.5); on TPU it
is a sharding annotation — GSPMD shards the elementwise update and
all-gathers only the param result."""

import numpy as np

import paddle_tpu as fluid
from paddle_tpu.executor import Scope, scope_guard


def _build(seed=5):
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = seed
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[16], dtype="float32")
        y = fluid.layers.data(name="y", shape=[1], dtype="float32")
        h = fluid.layers.fc(input=x, size=32, act="relu")
        pred = fluid.layers.fc(input=h, size=1)
        loss = fluid.layers.reduce_mean(
            fluid.layers.square_error_cost(input=pred, label=y))
        fluid.optimizer.Adam(learning_rate=1e-2).minimize(loss)
    return main, startup, loss


def _train(shard_state, steps=5):
    import jax

    main, startup, loss = _build()
    bs = fluid.BuildStrategy()
    bs.shard_optimizer_state = shard_state
    cp = fluid.CompiledProgram(main).with_data_parallel(
        loss_name=loss.name, build_strategy=bs)
    sc = Scope()
    rng = np.random.RandomState(0)
    xb = rng.randn(16, 16).astype("float32")
    feed = {"x": xb, "y": (xb.sum(1, keepdims=True) > 0).astype("float32")}
    with scope_guard(sc):
        exe = fluid.Executor(fluid.TPUPlace())
        exe.run(startup)
        ls = [float(np.asarray(exe.run(cp, feed=feed,
                                       fetch_list=[loss])[0]).reshape(-1)[0])
              for _ in range(steps)]
        moments = {n: sc.get(n) for n in list(sc.vars)
                   if "_adam_moment1_" in n}
    return ls, moments


class TestZero1:
    def test_loss_parity_and_sharded_moments(self):
        import jax

        ls_off, m_off = _train(False)
        ls_on, m_on = _train(True)
        np.testing.assert_allclose(ls_off, ls_on, rtol=1e-5, atol=1e-6)
        assert ls_on[-1] < ls_on[0]
        # the fc weight moment [16,32] / [32,1]... dim0 divisible by 8
        # for the first fc's w: find a moment whose dim0 % ndev == 0
        import pytest

        ndev = len(jax.devices())
        if ndev == 1:
            # is_fully_replicated on a size-1 mesh axis is a jax
            # implementation detail; the sharding assertion is only
            # meaningful with real partitions (conftest forces 8 virtual
            # devices, so a skip here is VISIBLE if that forcing breaks)
            pytest.skip("moment-sharding assertion needs >1 device")
        sharded = [
            n for n, v in m_on.items()
            if v.ndim >= 1 and v.shape[0] % ndev == 0
            and not v.sharding.is_fully_replicated
        ]
        assert sharded, (
            "no divisible moment came back data-axis-sharded: %s"
            % {n: (v.shape, str(v.sharding)) for n, v in m_on.items()})
        # and the off-run's moments stay replicated
        assert all(v.sharding.is_fully_replicated for v in m_off.values())
