"""fused_adam rewrite (reference: fuse_adam_op_pass — coalesce all
per-param Adam kernels into one streamed update): OPT-IN via
PADDLE_TPU_FUSE_ADAM=1 since r04 (the concat/scatter-back structure
costs ~4.5x the step's bytes accessed); bit-parity with the per-param
path, sharded tables excluded, default-off behavior asserted."""

import os
import subprocess
import sys

import numpy as np

import paddle_tpu as fluid
from paddle_tpu.executor import Scope, scope_guard, _fuse_adam_ops


def _build(lr=1e-3):
    fluid.unique_name.switch()
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 7
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[16], dtype="float32")
        y = fluid.layers.data("y", shape=[1], dtype="int64")
        h = fluid.layers.fc(x, size=32, act="relu")
        h = fluid.layers.fc(h, size=32, act="relu")
        logits = fluid.layers.fc(h, size=4)
        loss = fluid.layers.reduce_mean(
            fluid.layers.softmax_with_cross_entropy(logits, y))
        fluid.optimizer.Adam(learning_rate=lr).minimize(loss)
    return main, startup, loss


def _losses(n_steps=8):
    main, startup, loss = _build()
    exe = fluid.Executor(fluid.CPUPlace())
    rng = np.random.RandomState(0)
    scope = Scope()
    out = []
    with scope_guard(scope):
        exe.run(startup)
        for _ in range(n_steps):
            feed = {"x": rng.randn(8, 16).astype("float32"),
                    "y": rng.randint(0, 4, (8, 1)).astype("int64")}
            (l,) = exe.run(main, feed=feed, fetch_list=[loss])
            out.append(float(np.asarray(l).reshape(())))
    return out


class TestFusedAdam:
    """The fusion is OPT-IN since r04 (XLA cost model: 664GB vs 145GB
    bytes accessed on the BERT-base step) — tests enable it explicitly."""

    def test_default_is_unfused(self, monkeypatch):
        """r04 default: without the env opt-in the ops pass through
        unchanged (XLA cost model: 664GB vs 145GB bytes accessed)."""
        monkeypatch.delenv("PADDLE_TPU_FUSE_ADAM", raising=False)
        main, startup, loss = _build()
        block = main.global_block()
        fused = _fuse_adam_ops(list(block.ops), block)
        assert not any(op.type == "fused_adam" for op in fused)
        assert [op.type for op in fused] == [op.type for op in block.ops]

    def test_rewrite_groups_adam_ops(self, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_FUSE_ADAM", "1")
        main, startup, loss = _build()
        block = main.global_block()
        ops = [op for op in block.ops]
        fused = _fuse_adam_ops(ops, block)
        adam_before = sum(1 for op in ops if op.type == "adam")
        fused_ops = [op for op in fused if op.type == "fused_adam"]
        assert adam_before >= 6  # 3 fc layers x (w, b)
        assert len(fused_ops) == 1
        assert not any(op.type == "adam" for op in fused)
        assert len(fused_ops[0].inputs["Param"]) == adam_before

    def test_loss_parity_fused_vs_unfused(self, monkeypatch):
        """The fused path must reproduce the per-param losses exactly
        (same fp32 math, just concatenated).  The unfused control runs
        in a subprocess because the switch is read at lowering."""
        monkeypatch.setenv("PADDLE_TPU_FUSE_ADAM", "1")
        fused = _losses()
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        code = (
            "import sys; sys.path.insert(0, %r); "
            "import jax; jax.config.update('jax_platforms', 'cpu'); "
            "import importlib.util as iu; "
            "spec = iu.spec_from_file_location('tfa', %r); "
            "m = iu.module_from_spec(spec); spec.loader.exec_module(m); "
            "print('LOSSES', m._losses())"
            % (repo, os.path.abspath(__file__)))
        env = dict(os.environ)
        env["PADDLE_TPU_FUSE_ADAM"] = "0"
        res = subprocess.run([sys.executable, "-c", code],
                             capture_output=True, text=True, timeout=300,
                             env=env)
        assert res.returncode == 0, res.stderr[-600:]
        line = next(l for l in res.stdout.splitlines()
                    if l.startswith("LOSSES"))
        unfused = eval(line[len("LOSSES "):])
        np.testing.assert_allclose(fused, unfused, rtol=1e-6, atol=1e-7)
        assert fused[-1] < fused[0]

    def test_sharded_table_stays_unfused(self, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_FUSE_ADAM", "1")
        fluid.unique_name.switch()
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            ids = fluid.layers.data("ids", shape=[4], dtype="int64")
            emb = fluid.layers.embedding(
                ids, size=[64, 8], is_distributed=True,
                param_attr=fluid.ParamAttr(name="dist_table"))
            pooled = fluid.layers.reduce_sum(emb, dim=1)
            logits = fluid.layers.fc(pooled, size=2)
            label = fluid.layers.data("label", shape=[1], dtype="int64")
            loss = fluid.layers.reduce_mean(
                fluid.layers.softmax_with_cross_entropy(logits, label))
            fluid.optimizer.Adam(learning_rate=1e-3).minimize(loss)
        block = main.global_block()
        fused = _fuse_adam_ops(list(block.ops), block)
        plain = [op for op in fused if op.type == "adam"]
        assert len(plain) == 1
        assert plain[0].inputs["Param"][0] == "dist_table"
        assert any(op.type == "fused_adam" for op in fused)
