"""Quantization-aware training tests (reference:
unittests/test_fake_quantize_op.py, test_fake_dequantize_op.py, and
slim/tests/test_quantization_pass.py)."""

import numpy as np

import paddle_tpu as fluid
from paddle_tpu.executor import Scope, scope_guard
from paddle_tpu.contrib.slim.quantization import (
    QuantizationTranspiler, TransformForTraining)
from op_test import OpTest

rng = np.random.RandomState(0)


class TestFakeQuantizeAbsMax(OpTest):
    op_type = "fake_quantize_abs_max"

    def test_output(self):
        x = rng.randn(8, 6).astype("float32")
        scale = np.max(np.abs(x))
        bin_cnt = 127.0
        out = np.round(np.clip(x, -scale, scale) * bin_cnt / scale)
        self.inputs = {"X": x}
        self.attrs = {"bit_length": 8}
        self.outputs = {"Out": out, "OutScale": np.array([scale], "float32")}
        self.check_output(atol=1e-5)


class TestFakeDequantize(OpTest):
    op_type = "fake_dequantize_max_abs"

    def test_output(self):
        x = rng.randint(-127, 128, size=(4, 5)).astype("float32")
        scale = np.array([3.7], "float32")
        self.inputs = {"X": x, "Scale": scale}
        self.attrs = {"max_range": 127.0}
        self.outputs = {"Out": x * scale[0] / 127.0}
        self.check_output(atol=1e-5)


class TestChannelWise(OpTest):
    op_type = "fake_channel_wise_quantize_abs_max"

    def test_output(self):
        x = rng.randn(4, 3, 2, 2).astype("float32")
        scale = np.abs(x.reshape(4, -1)).max(axis=1)
        out = np.zeros_like(x)
        for c in range(4):
            out[c] = np.round(
                np.clip(x[c], -scale[c], scale[c]) * 127.0 / scale[c])
        self.inputs = {"X": x}
        self.attrs = {"bit_length": 8}
        self.outputs = {"Out": out, "OutScale": scale.astype("float32")}
        self.check_output(atol=1e-4)


class TestQuantDequantRoundTrip:
    def test_error_bounded(self):
        """quant-dequant error is bounded by scale/bin_cnt per element."""
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.layers.data("x", shape=[16], dtype="float32")
            block = main.current_block()
            out = block.create_var(name="qdq", dtype="float32")
            sc = block.create_var(name="qdq_s", dtype="float32")
            block.append_op(
                type="fake_quantize_dequantize_abs_max",
                inputs={"X": [x]},
                outputs={"Out": [out], "OutScale": [sc]},
                attrs={"bit_length": 8})
        exe = fluid.Executor(fluid.CPUPlace())
        xv = rng.randn(4, 16).astype("float32")
        with scope_guard(Scope()):
            o, s = exe.run(main, feed={"x": xv}, fetch_list=[out, sc])
        assert np.abs(o - xv).max() <= s[0] / 127.0 + 1e-6


class TestQATTransform:
    def _build(self):
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            img = fluid.layers.data("img", shape=[1, 8, 8], dtype="float32")
            label = fluid.layers.data("label", shape=[1], dtype="int64")
            conv = fluid.layers.conv2d(img, num_filters=4, filter_size=3,
                                       padding=1, act="relu")
            pool = fluid.layers.pool2d(conv, pool_size=8, pool_type="avg")
            logits = fluid.layers.fc(pool, size=3)
            loss = fluid.layers.mean(
                fluid.layers.softmax_with_cross_entropy(logits, label))
        return main, startup, loss

    def test_transform_inserts_ops(self):
        main, startup, loss = self._build()
        n = TransformForTraining().apply(main, startup)
        # conv (Input+Filter) + fc's mul (X+Y) = 4 quantized slots
        assert n == 4
        types = [op.type for op in main.global_block().ops]
        assert types.count("fake_quantize_dequantize_moving_average_abs_max") == 2
        assert types.count("fake_quantize_dequantize_abs_max") == 2
        # quantizable ops now read the dequantized vars
        for op in main.global_block().ops:
            if op.type == "conv2d":
                assert op.inputs["Input"][0].endswith(".quant_dequant")
                assert op.inputs["Filter"][0].endswith(".quant_dequant")

    def test_qat_trains(self):
        main, startup, loss = self._build()
        with fluid.program_guard(main, startup):
            QuantizationTranspiler().training_transpile(main, startup)
            fluid.optimizer.Adam(learning_rate=5e-3).minimize(loss)
        exe = fluid.Executor(fluid.CPUPlace())
        r = np.random.RandomState(1)
        W = r.randn(64, 3)
        def batch(n=16):
            xv = r.rand(n, 1, 8, 8).astype("float32")
            yv = np.argmax(xv.reshape(n, -1) @ W, axis=1)[:, None]
            return xv, yv.astype("int64")
        with scope_guard(Scope()):
            exe.run(startup)
            losses = []
            for _ in range(60):
                xv, yv = batch()
                (l,) = exe.run(main, feed={"img": xv, "label": yv},
                               fetch_list=[loss])
                losses.append(float(np.asarray(l).reshape(())))
            scale = exe.run(main, feed={"img": xv, "label": yv},
                            fetch_list=["img.quant_scale"])[0]
        # training ran and the activation scale accumulated something real
        assert scale[0] > 0.1
        assert losses[-1] < 1.5

    def _train_curve(self, transform, steps=120):
        main, startup, loss = self._build()
        with fluid.program_guard(main, startup):
            if transform:
                TransformForTraining(
                    activation_quantize_type="abs_max").apply(main, startup)
            fluid.optimizer.Adam(learning_rate=5e-3).minimize(loss)
        exe = fluid.Executor(fluid.CPUPlace())
        r = np.random.RandomState(2)
        xv = r.rand(16, 1, 8, 8).astype("float32")
        yv = r.randint(0, 3, size=(16, 1)).astype("int64")
        with scope_guard(Scope()):
            exe.run(startup)
            ls = []
            for _ in range(steps):
                (l,) = exe.run(main, feed={"img": xv, "label": yv},
                               fetch_list=[loss])
                ls.append(float(np.asarray(l).reshape(())))
        return ls

    def test_qat_loss_tracks_float_baseline(self):
        """STE grads must let QAT train essentially as well as float
        (slim/tests pattern: quantized-vs-float loss parity)."""
        plain = self._train_curve(transform=False)
        qat = self._train_curve(transform=True)
        assert qat[-1] < qat[0], (qat[0], qat[-1])
        # the meaningful bar: QAT's final loss tracks the float baseline
        assert qat[-1] < plain[-1] + 0.1, (plain[-1], qat[-1])


def _mnist_convnet():
    """Small conv net on MNIST (the book recognize_digits CNN shape):
    conv+fc covers both _QUANT_SLOTS families."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        img = fluid.layers.data("img", shape=[1, 28, 28], dtype="float32")
        label = fluid.layers.data("label", shape=[1], dtype="int64")
        conv = fluid.layers.conv2d(img, num_filters=8, filter_size=5,
                                   padding=2, act="relu")
        pool = fluid.layers.pool2d(conv, pool_size=4, pool_stride=4)
        logits = fluid.layers.fc(pool, size=10)
        prob = fluid.layers.softmax(logits)
        acc = fluid.layers.accuracy(input=prob, label=label)
        loss = fluid.layers.mean(
            fluid.layers.softmax_with_cross_entropy(logits, label))
    return main, startup, loss, acc, prob


def _mnist_batches(n_batches, batch=64, seed=0, train=True):
    from paddle_tpu import datasets

    reader = fluid.batch(
        datasets.mnist.train() if train else datasets.mnist.test(), batch)
    out = []
    for i, b in enumerate(reader()):
        if i >= n_batches:
            break
        xs = np.stack([x[0].reshape(1, 28, 28) for x in b]).astype(
            "float32")
        ys = np.array([[x[1]] for x in b], dtype="int64")
        out.append({"img": xs, "label": ys})
    return out


def _eval_acc(run_fn, batches):
    accs = []
    for feed in batches:
        accs.append(float(np.asarray(run_fn(feed)).reshape(-1)[0]))
    return float(np.mean(accs))


class TestQATRoundTrip:
    """VERDICT r5 item #5: the full reference QAT story on a real model —
    insert fake-quant ops → train to convergence → freeze (int8 weights
    + recorded activation scales) → run through AnalysisPredictor,
    accuracy within tolerance of fp32.  Reference:
    ``slim/quantization/quantization_pass.py`` insert/freeze passes."""

    def _train(self, qat, steps=120):
        main, startup, loss, acc, prob = _mnist_convnet()
        with fluid.program_guard(main, startup):
            if qat:
                QuantizationTranspiler().training_transpile(main, startup)
            test_prog = main.clone(for_test=True)
            fluid.optimizer.Adam(learning_rate=2e-3).minimize(loss)
        exe = fluid.Executor(fluid.CPUPlace())
        scope = Scope()
        batches = _mnist_batches(steps)
        with scope_guard(scope):
            exe.run(startup)
            for feed in batches:
                exe.run(main, feed=feed, fetch_list=[])
        return exe, scope, test_prog, acc, prob

    def test_insert_train_freeze_infer_roundtrip(self, tmp_path):
        from paddle_tpu import core

        eval_batches = _mnist_batches(4, train=False, batch=128)

        # fp32 twin: the accuracy bar
        exe32, scope32, test32, acc32, _ = self._train(qat=False)
        with scope_guard(scope32):
            fp32_acc = _eval_acc(
                lambda f: exe32.run(test32, feed=f, fetch_list=[acc32])[0],
                eval_batches)
        assert fp32_acc > 0.7, fp32_acc  # converged

        # QAT: train with fake-quant ops, then freeze the test clone
        exe, scope, test_prog, acc, prob = self._train(qat=True)
        with scope_guard(scope):
            qat_acc = _eval_acc(
                lambda f: exe.run(test_prog, feed=f, fetch_list=[acc])[0],
                eval_batches)
            QuantizationTranspiler().freeze_program(test_prog, scope=scope)
            block = test_prog.global_block()
            types = [op.type for op in block.ops]
            # weights now int8 + dequant; activation fake-qdq removed
            assert types.count("fake_dequantize_max_abs") == 2
            assert not any(t.startswith("fake_quantize_dequantize")
                           for t in types)
            conv = next(op for op in block.ops
                        if op.type in ("conv2d", "depthwise_conv2d"))
            w_name = conv.inputs["Filter"][0].rsplit(
                ".quant_dequant", 1)[0]
            w = block.var(w_name)
            assert w.dtype == core.convert_np_dtype_to_dtype_("int8")
            assert np.asarray(scope.get(w_name)).dtype == np.int8
            # recorded scale attr on the consumer (int8-engine record)
            assert conv.attrs.get("quantization_type") == "qat_weight_int8"
            assert conv.attrs.get("Input_scale", 0) > 0
            # frozen program still runs + scores
            frozen_acc = _eval_acc(
                lambda f: exe.run(test_prog, feed=f, fetch_list=[acc])[0],
                eval_batches)
            # export → AnalysisPredictor
            from paddle_tpu import io as fluid_io

            fluid_io.save_inference_model(
                str(tmp_path), ["img"], [prob], exe,
                main_program=test_prog)
        from paddle_tpu.inference import AnalysisConfig, AnalysisPredictor

        pred = AnalysisPredictor(AnalysisConfig(model_dir=str(tmp_path)))
        correct = total = 0
        for feed in eval_batches:
            (p,) = pred.run([feed["img"]])
            correct += int((np.argmax(p, axis=1)
                            == feed["label"].reshape(-1)).sum())
            total += len(feed["label"])
        pred_acc = correct / total
        # the int8 deploy tracks fp32 within tolerance, end to end
        assert qat_acc > fp32_acc - 0.1, (fp32_acc, qat_acc)
        assert frozen_acc > qat_acc - 0.05, (qat_acc, frozen_acc)
        assert pred_acc > fp32_acc - 0.1, (fp32_acc, pred_acc)


class TestPostTrainingQuantization:
    """VERDICT r5 item #9: int8 post-training calibration — an fp32
    model + a calibration reader → scales → int8 weights + fixed-scale
    activation QDQ + recorded attrs → export.  Reference:
    ``inference/api/mkldnn_quantizer.cc``."""

    def test_calibrate_quantize_export(self, tmp_path):
        from paddle_tpu.contrib.slim.quantization import (
            PostTrainingQuantization)

        main, startup, loss, acc, prob = _mnist_convnet()
        with fluid.program_guard(main, startup):
            test_prog = main.clone(for_test=True)
            fluid.optimizer.Adam(learning_rate=2e-3).minimize(loss)
        exe = fluid.Executor(fluid.CPUPlace())
        scope = Scope()
        eval_batches = _mnist_batches(4, train=False, batch=128)
        with scope_guard(scope):
            exe.run(startup)
            for feed in _mnist_batches(120):
                exe.run(main, feed=feed, fetch_list=[])
            fp32_acc = _eval_acc(
                lambda f: exe.run(test_prog, feed=f, fetch_list=[acc])[0],
                eval_batches)
            assert fp32_acc > 0.7, fp32_acc

            calib = [{"img": f["img"]} for f in _mnist_batches(8, seed=3)]
            ptq = PostTrainingQuantization(
                exe, program=test_prog, feed_names=["img"],
                fetch_targets=[prob], scope=scope, algo="avg",
                batch_nums=8)
            qprog = ptq.quantize(iter(calib))
            types = [op.type for op in qprog.global_block().ops]
            assert types.count("fake_dequantize_max_abs") == 2
            assert types.count("quantize_dequantize_fixed_scale") == 2
            conv = next(op for op in qprog.global_block().ops
                        if op.type in ("conv2d", "depthwise_conv2d"))
            assert conv.attrs.get("quantization_type") == \
                "post_training_int8"
            assert conv.attrs.get("Input_scale", 0) > 0
            w_name = conv.inputs["Filter"][0].rsplit(
                ".quant_dequant", 1)[0]
            assert np.asarray(scope.get(w_name)).dtype == np.int8
            ptq_acc = _eval_acc(
                lambda f: exe.run(qprog, feed=f, fetch_list=[acc])[0],
                eval_batches)
            ptq.save_quantized_model(str(tmp_path))
        assert ptq_acc > fp32_acc - 0.1, (fp32_acc, ptq_acc)

        from paddle_tpu.inference import AnalysisConfig, AnalysisPredictor

        pred = AnalysisPredictor(AnalysisConfig(model_dir=str(tmp_path)))
        (p,) = pred.run([eval_batches[0]["img"]])
        pa = float((np.argmax(p, axis=1)
                    == eval_batches[0]["label"].reshape(-1)).mean())
        assert pa > fp32_acc - 0.1, (fp32_acc, pa)


class TestQATDataParallel:
    def test_qat_dp_loss_parity(self):
        """QAT fake-quant ops under GSPMD data parallelism: the
        moving-average scale state is replicated, the abs_max reductions
        become global (all-reduce max over the sharded batch), and
        per-step losses match the single-device run (the
        test_dist_base.py parity bar, quantized edition)."""
        import jax

        def run(dp):
            fluid.unique_name.switch()
            main, startup = fluid.Program(), fluid.Program()
            main.random_seed = startup.random_seed = 21
            with fluid.program_guard(main, startup):
                img = fluid.layers.data("img", shape=[1, 8, 8],
                                        dtype="float32")
                label = fluid.layers.data("label", shape=[1],
                                          dtype="int64")
                conv = fluid.layers.conv2d(img, num_filters=4,
                                           filter_size=3, padding=1,
                                           act="relu")
                pool = fluid.layers.pool2d(conv, pool_size=8,
                                           pool_type="avg")
                logits = fluid.layers.fc(pool, size=3)
                loss = fluid.layers.mean(
                    fluid.layers.softmax_with_cross_entropy(logits,
                                                            label))
                QuantizationTranspiler().training_transpile(main, startup)
                fluid.optimizer.Adam(learning_rate=5e-3).minimize(loss)
            exe = fluid.Executor(fluid.CPUPlace())
            r = np.random.RandomState(4)
            W = r.randn(64, 3)  # learnable labeling so loss decreases
            feeds = []
            for _ in range(8):
                xv = r.rand(16, 1, 8, 8).astype("float32")
                yv = np.argmax(xv.reshape(16, -1) @ W, axis=1)[:, None]
                feeds.append({"img": xv, "label": yv.astype("int64")})
            ls = []
            with scope_guard(Scope()):
                exe.run(startup)
                prog = main
                if dp:
                    prog = fluid.CompiledProgram(main).with_data_parallel(
                        loss_name=loss.name)
                for feed in feeds:
                    (l,) = exe.run(prog, feed=feed, fetch_list=[loss])
                    ls.append(float(np.asarray(l).reshape(-1)[0]))
            return ls

        single = run(dp=False)
        sharded = run(dp=True)
        np.testing.assert_allclose(sharded, single, rtol=2e-4, atol=2e-4)
        assert single[-1] < single[0]


class TestChannelWiseQAT:
    def test_channel_wise_weight_qat_and_freeze(self):
        """weight_quantize_type='channel_wise_abs_max' (reference
        fake_channel_wise_quantize_op): per-output-channel weight scales
        through training, frozen to int8 + channel-wise dequant."""
        fluid.unique_name.switch()
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            img = fluid.layers.data("img", shape=[1, 8, 8],
                                    dtype="float32")
            label = fluid.layers.data("label", shape=[1], dtype="int64")
            conv = fluid.layers.conv2d(img, num_filters=4, filter_size=3,
                                       padding=1, act="relu")
            pool = fluid.layers.pool2d(conv, pool_size=8,
                                       pool_type="avg")
            logits = fluid.layers.fc(pool, size=3)
            loss = fluid.layers.mean(
                fluid.layers.softmax_with_cross_entropy(logits, label))
            t = QuantizationTranspiler(
                weight_quantize_type="channel_wise_abs_max")
            t.training_transpile(main, startup)
            test_prog = main.clone(for_test=True)
            fluid.optimizer.Adam(learning_rate=5e-3).minimize(loss)
        types = [op.type for op in main.global_block().ops]
        assert "fake_channel_wise_quantize_dequantize_abs_max" in types
        r = np.random.RandomState(6)
        W = r.randn(64, 3)
        exe = fluid.Executor(fluid.CPUPlace())
        scope = Scope()
        with scope_guard(scope):
            exe.run(startup)
            for _ in range(30):
                xv = r.rand(16, 1, 8, 8).astype("float32")
                yv = np.argmax(xv.reshape(16, -1) @ W, axis=1)[:, None]
                exe.run(main, feed={"img": xv,
                                    "label": yv.astype("int64")},
                        fetch_list=[])
            feed = {"img": xv, "label": yv.astype("int64")}
            (l_qat,) = exe.run(test_prog, feed=feed, fetch_list=[loss])
            t.freeze_program(test_prog, scope=scope)
            ftypes = [op.type for op in test_prog.global_block().ops]
            assert "fake_channel_wise_dequantize_max_abs" in ftypes
            conv_op = next(op for op in test_prog.global_block().ops
                           if op.type in ("conv2d", "depthwise_conv2d"))
            w_name = conv_op.inputs["Filter"][0].rsplit(
                ".quant_dequant", 1)[0]
            wq = np.asarray(scope.get(w_name))
            assert wq.dtype == np.int8
            scales = np.asarray(scope.get(w_name + ".quant_scale"))
            assert scales.shape == (wq.shape[0],)  # per output channel
            # per-channel dequant reproduces the trained fake-quant
            # weights: frozen loss == QAT-sim loss on the same batch
            (l_frozen,) = exe.run(test_prog, feed=feed,
                                  fetch_list=[loss])
        np.testing.assert_allclose(
            float(np.asarray(l_frozen).reshape(())),
            float(np.asarray(l_qat).reshape(())), rtol=2e-2, atol=2e-2)


class TestPTQChannelWise:
    def test_ptq_channel_wise_weights(self, tmp_path):
        """PostTrainingQuantization(weight_quantize_type=
        'channel_wise_abs_max'): calibrated activations + per-channel
        int8 weights through the same pipeline."""
        from paddle_tpu.contrib.slim.quantization import (
            PostTrainingQuantization)

        main, startup, loss, acc, prob = _mnist_convnet()
        with fluid.program_guard(main, startup):
            test_prog = main.clone(for_test=True)
            fluid.optimizer.Adam(learning_rate=2e-3).minimize(loss)
        exe = fluid.Executor(fluid.CPUPlace())
        scope = Scope()
        with scope_guard(scope):
            exe.run(startup)
            for feed in _mnist_batches(40):
                exe.run(main, feed=feed, fetch_list=[])
            calib = [{"img": f["img"]} for f in _mnist_batches(4, seed=5)]
            ptq = PostTrainingQuantization(
                exe, program=test_prog, feed_names=["img"],
                fetch_targets=[prob], scope=scope,
                weight_quantize_type="channel_wise_abs_max",
                batch_nums=4)
            qprog = ptq.quantize(iter(calib))
            types = [op.type for op in qprog.global_block().ops]
            assert "fake_channel_wise_dequantize_max_abs" in types
            conv = next(op for op in qprog.global_block().ops
                        if op.type in ("conv2d", "depthwise_conv2d"))
            w_name = conv.inputs["Filter"][0].rsplit(
                ".quant_dequant", 1)[0]
            wq = np.asarray(scope.get(w_name))
            assert wq.dtype == np.int8
            scales = np.asarray(scope.get(w_name + ".quant_scale"))
            assert scales.shape == (wq.shape[0],)
            # quantized program still classifies
            feed = _mnist_batches(1, train=False, batch=128)[0]
            a = float(np.asarray(exe.run(
                qprog, feed=feed, fetch_list=[acc])[0]).reshape(-1)[0])
            assert a > 0.5, a
            ptq.save_quantized_model(str(tmp_path))
        # per-channel int8 weights survive export -> AnalysisPredictor
        from paddle_tpu.inference import AnalysisConfig, AnalysisPredictor

        pred = AnalysisPredictor(AnalysisConfig(model_dir=str(tmp_path)))
        (p,) = pred.run([feed["img"]])
        pa = float((np.argmax(p, axis=1)
                    == feed["label"].reshape(-1)).mean())
        assert pa > 0.5, pa
