"""Quantization-aware training tests (reference:
unittests/test_fake_quantize_op.py, test_fake_dequantize_op.py, and
slim/tests/test_quantization_pass.py)."""

import numpy as np

import paddle_tpu as fluid
from paddle_tpu.executor import Scope, scope_guard
from paddle_tpu.contrib.slim.quantization import (
    QuantizationTranspiler, TransformForTraining)
from op_test import OpTest

rng = np.random.RandomState(0)


class TestFakeQuantizeAbsMax(OpTest):
    op_type = "fake_quantize_abs_max"

    def test_output(self):
        x = rng.randn(8, 6).astype("float32")
        scale = np.max(np.abs(x))
        bin_cnt = 127.0
        out = np.round(np.clip(x, -scale, scale) * bin_cnt / scale)
        self.inputs = {"X": x}
        self.attrs = {"bit_length": 8}
        self.outputs = {"Out": out, "OutScale": np.array([scale], "float32")}
        self.check_output(atol=1e-5)


class TestFakeDequantize(OpTest):
    op_type = "fake_dequantize_max_abs"

    def test_output(self):
        x = rng.randint(-127, 128, size=(4, 5)).astype("float32")
        scale = np.array([3.7], "float32")
        self.inputs = {"X": x, "Scale": scale}
        self.attrs = {"max_range": 127.0}
        self.outputs = {"Out": x * scale[0] / 127.0}
        self.check_output(atol=1e-5)


class TestChannelWise(OpTest):
    op_type = "fake_channel_wise_quantize_abs_max"

    def test_output(self):
        x = rng.randn(4, 3, 2, 2).astype("float32")
        scale = np.abs(x.reshape(4, -1)).max(axis=1)
        out = np.zeros_like(x)
        for c in range(4):
            out[c] = np.round(
                np.clip(x[c], -scale[c], scale[c]) * 127.0 / scale[c])
        self.inputs = {"X": x}
        self.attrs = {"bit_length": 8}
        self.outputs = {"Out": out, "OutScale": scale.astype("float32")}
        self.check_output(atol=1e-4)


class TestQuantDequantRoundTrip:
    def test_error_bounded(self):
        """quant-dequant error is bounded by scale/bin_cnt per element."""
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.layers.data("x", shape=[16], dtype="float32")
            block = main.current_block()
            out = block.create_var(name="qdq", dtype="float32")
            sc = block.create_var(name="qdq_s", dtype="float32")
            block.append_op(
                type="fake_quantize_dequantize_abs_max",
                inputs={"X": [x]},
                outputs={"Out": [out], "OutScale": [sc]},
                attrs={"bit_length": 8})
        exe = fluid.Executor(fluid.CPUPlace())
        xv = rng.randn(4, 16).astype("float32")
        with scope_guard(Scope()):
            o, s = exe.run(main, feed={"x": xv}, fetch_list=[out, sc])
        assert np.abs(o - xv).max() <= s[0] / 127.0 + 1e-6


class TestQATTransform:
    def _build(self):
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            img = fluid.layers.data("img", shape=[1, 8, 8], dtype="float32")
            label = fluid.layers.data("label", shape=[1], dtype="int64")
            conv = fluid.layers.conv2d(img, num_filters=4, filter_size=3,
                                       padding=1, act="relu")
            pool = fluid.layers.pool2d(conv, pool_size=8, pool_type="avg")
            logits = fluid.layers.fc(pool, size=3)
            loss = fluid.layers.mean(
                fluid.layers.softmax_with_cross_entropy(logits, label))
        return main, startup, loss

    def test_transform_inserts_ops(self):
        main, startup, loss = self._build()
        n = TransformForTraining().apply(main, startup)
        # conv (Input+Filter) + fc's mul (X+Y) = 4 quantized slots
        assert n == 4
        types = [op.type for op in main.global_block().ops]
        assert types.count("fake_quantize_dequantize_moving_average_abs_max") == 2
        assert types.count("fake_quantize_dequantize_abs_max") == 2
        # quantizable ops now read the dequantized vars
        for op in main.global_block().ops:
            if op.type == "conv2d":
                assert op.inputs["Input"][0].endswith(".quant_dequant")
                assert op.inputs["Filter"][0].endswith(".quant_dequant")

    def test_qat_trains(self):
        main, startup, loss = self._build()
        with fluid.program_guard(main, startup):
            QuantizationTranspiler().training_transpile(main, startup)
            fluid.optimizer.Adam(learning_rate=5e-3).minimize(loss)
        exe = fluid.Executor(fluid.CPUPlace())
        r = np.random.RandomState(1)
        W = r.randn(64, 3)
        def batch(n=16):
            xv = r.rand(n, 1, 8, 8).astype("float32")
            yv = np.argmax(xv.reshape(n, -1) @ W, axis=1)[:, None]
            return xv, yv.astype("int64")
        with scope_guard(Scope()):
            exe.run(startup)
            losses = []
            for _ in range(60):
                xv, yv = batch()
                (l,) = exe.run(main, feed={"img": xv, "label": yv},
                               fetch_list=[loss])
                losses.append(float(np.asarray(l).reshape(())))
            scale = exe.run(main, feed={"img": xv, "label": yv},
                            fetch_list=["img.quant_scale"])[0]
        # training ran and the activation scale accumulated something real
        assert scale[0] > 0.1
        assert losses[-1] < 1.5

    def _train_curve(self, transform, steps=120):
        main, startup, loss = self._build()
        with fluid.program_guard(main, startup):
            if transform:
                TransformForTraining(
                    activation_quantize_type="abs_max").apply(main, startup)
            fluid.optimizer.Adam(learning_rate=5e-3).minimize(loss)
        exe = fluid.Executor(fluid.CPUPlace())
        r = np.random.RandomState(2)
        xv = r.rand(16, 1, 8, 8).astype("float32")
        yv = r.randint(0, 3, size=(16, 1)).astype("int64")
        with scope_guard(Scope()):
            exe.run(startup)
            ls = []
            for _ in range(steps):
                (l,) = exe.run(main, feed={"img": xv, "label": yv},
                               fetch_list=[loss])
                ls.append(float(np.asarray(l).reshape(())))
        return ls

    def test_qat_loss_tracks_float_baseline(self):
        """STE grads must let QAT train essentially as well as float
        (slim/tests pattern: quantized-vs-float loss parity)."""
        plain = self._train_curve(transform=False)
        qat = self._train_curve(transform=True)
        assert qat[-1] < qat[0], (qat[0], qat[-1])
        # the meaningful bar: QAT's final loss tracks the float baseline
        assert qat[-1] < plain[-1] + 0.1, (plain[-1], qat[-1])
