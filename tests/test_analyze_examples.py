"""CI sweep (ISSUE 3 satellite): run the whole-program static analyzer
over every program built in ``examples/`` and require zero ERROR
diagnostics — analyzer regressions and example rot both fail fast,
and every example gets a static cost baseline for free.

Each example module exposes a ``build_program()``-style builder (the
``main()`` entry uses the same builder, so the analyzed program IS the
example's program).  ``long_context_ring.py`` is pure-jax (no Program)
and ``deepfm_ctr.py`` builds via dataset-file readers; they have no
static program to sweep.
"""

import os
import sys

import pytest

import paddle_tpu as fluid

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
EXAMPLES = os.path.join(REPO, "examples")
if EXAMPLES not in sys.path:
    sys.path.insert(0, EXAMPLES)


def _mnist():
    import mnist_train

    main, startup, test_prog, loss, acc = mnist_train.build_program()
    return [(main, [loss.name, acc.name]), (test_prog, [acc.name]),
            (startup, None)]


def _bert_tiny():
    import bert_pretrain

    main, startup, feeds, loss = bert_pretrain.build_program(
        tiny=True, seq_len=32)
    return [(main, [loss.name]), (startup, None)]


def _ctr():
    import ps_migration

    main, startup, loss = ps_migration.build_ctr(vocab=512)
    return [(main, [loss.name]), (startup, None)]


def _resnet_eval():
    import resnet_infer

    main, startup, prob = resnet_infer.build_program()
    return [(main, [prob.name]), (startup, None)]



def _gpt_small():
    import gpt_small

    main, startup, feeds, tokens, gen_len = gpt_small.build_program(
        batch=2, prompt_len=8, max_new_tokens=4)
    return [(main, [tokens.name, gen_len.name]), (startup, None)]


def _slim():
    import slim_compress

    main, startup, loss, acc, prob = slim_compress.build_program()
    return [(main, [loss.name, acc.name]), (startup, None)]


@pytest.mark.parametrize("builder", [
    _mnist, _bert_tiny, _ctr, _resnet_eval, _slim, _gpt_small,
], ids=["mnist", "bert-tiny", "ctr", "resnet-eval", "slim",
        "gpt-small"])
def test_every_example_program_analyzes_clean(builder):
    fluid.unique_name.switch()
    for program, targets in builder():
        report = program.analyze(targets=targets)
        assert report.ok, "\n".join(str(d) for d in report.errors)


def test_example_cost_baselines_are_nonzero():
    """The BENCH-style static baseline a perf PR would cite: the mnist
    training program has real FLOP/byte totals and a peak estimate."""
    import mnist_train

    fluid.unique_name.switch()
    main, startup, test_prog, loss, acc = mnist_train.build_program()
    report = main.analyze(targets=[loss.name], batch_size=64)
    assert report.cost.total_flops > 1_000_000  # 784->200->200->10 MLP
    assert report.cost.peak_memory_bytes > report.cost.persistent_bytes
    assert report.cost.persistent_bytes > 0
    lines = report.cost.bench_json().splitlines()
    assert len(lines) == 7
    import json as _json

    metrics = {_json.loads(l)["metric"] for l in lines}
    # the async-dispatch additions ride in the same BENCH stream
    assert "static_host_sync_points" in metrics
    assert "static_dispatch_overhead_ms" in metrics

@pytest.mark.parametrize("builder", [
    _mnist, _bert_tiny, _ctr, _resnet_eval, _slim, _gpt_small,
], ids=["mnist", "bert-tiny", "ctr", "resnet-eval", "slim",
        "gpt-small"])
def test_every_example_fuses_and_analyzes_clean(builder):
    """ISSUE 5 CI sweep: the fusion pipeline (on, default config) over
    every example program must introduce ZERO new ERROR diagnostics —
    the fused ops are first-class citizens of the analyzer (cost rules,
    sharding transfers, schedule extraction) and every rewrite is
    verify_pass-bracketed."""
    from paddle_tpu.static_analysis import fusion

    fluid.unique_name.switch()
    for program, targets in builder():
        fused, report = fusion.resolve_fused_program(
            program, targets=targets or ())
        analysis = fused.analyze(targets=targets)
        assert analysis.ok, "\n".join(str(d) for d in analysis.errors)


@pytest.mark.parametrize("builder", [
    _mnist, _bert_tiny, _ctr, _resnet_eval, _slim, _gpt_small,
], ids=["mnist", "bert-tiny", "ctr", "resnet-eval", "slim",
        "gpt-small"])
def test_every_example_program_concurrency_clean(builder):
    """ISSUE 10 CI sweep: the concurrency battery at max_in_flight=2
    finds ZERO races across every example program — training programs
    fetch temporaries (loss/acc), never the donated parameter buffers,
    so the corpus is the precision baseline for the race rules."""
    fluid.unique_name.switch()
    for program, targets in builder():
        report = program.analyze(targets=targets, concurrency=True,
                                 max_in_flight=2)
        assert report.ok, "\n".join(str(d) for d in report.errors)
        assert report.concurrency is not None
        assert report.concurrency.race_free, "\n".join(
            str(d) for d in report.concurrency.races)


def test_dist_worker_sets_concurrency_clean():
    """Every transpiled multi-worker program set (pipeline, DP at 2 and
    8 ranks, MoE) stays race-free at depth 2 — collective rewrites must
    not put a fetched var into a donated buffer."""
    TESTS = os.path.dirname(os.path.abspath(__file__))
    if TESTS not in sys.path:
        sys.path.insert(0, TESTS)
    import dist_model

    sets = []
    workers, _, loss = dist_model.build_pipeline_workers()
    sets.append((workers, loss))
    workers, _, loss = dist_model.build_dp_workers(nranks=2)
    sets.append((workers, loss))
    w0, _, loss = dist_model.build_example_dp_workers("bert", nranks=8)
    sets.append(([w0], loss))
    workers, _, out = dist_model.build_moe_workers(nranks=2)
    sets.append((workers, out))
    for workers, fetch in sets:
        for w in workers:
            # pipeline stages that don't produce the fetch var analyze
            # without it (the split keeps the var declaration in every
            # stage, but only one stage's ops define it)
            has = any(fetch in op.output_arg_names
                      for b in w.blocks for op in b.ops)
            report = w.analyze(targets=[fetch] if has else None,
                               concurrency=True, max_in_flight=2)
            assert report.ok, "\n".join(str(d) for d in report.errors)
            assert report.concurrency.race_free, "\n".join(
                str(d) for d in report.concurrency.races)


def test_fusion_families_fire_across_example_corpus(monkeypatch):
    """The rewrite families all fire somewhere in the examples: mnist
    carries bias_act + softmax_xent + optimizer, bert carries the
    dropout_add_ln sites (and attention once T reaches the flash
    threshold — exercised in test_fusion.py with the env override).
    The optimizer gate gets the TPU-scale launch credit — the CPU
    default refuses mnist-scale groups (measured slower there)."""
    from paddle_tpu.static_analysis import fusion

    monkeypatch.setenv("PADDLE_TPU_FUSE_OPT_OVERHEAD_BYTES",
                       str(8 << 20))
    seen = {}
    fluid.unique_name.switch()
    for build in (_mnist, _bert_tiny):
        for program, targets in build():
            _, report = fusion.resolve_fused_program(
                program, targets=targets or ())
            for fam, n in report.counts().items():
                seen[fam] = seen.get(fam, 0) + n
    assert seen.get("bias_act", 0) >= 2
    assert seen.get("softmax_xent", 0) >= 1
    assert seen.get("optimizer", 0) >= 1
    assert seen.get("dropout_add_ln", 0) >= 5
