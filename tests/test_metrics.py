"""Metric op tests (reference: unittests/test_auc_op.py,
test_precision_recall_op.py — numpy-oracle style)."""

import numpy as np

import paddle_tpu as fluid
from paddle_tpu.executor import Scope, scope_guard


def np_auc(pos_hist, neg_hist):
    """Trapezoid AUC from bucket histograms (auc_op.h calcAuc)."""
    tot_pos = tot_neg = 0.0
    tot_pos_prev = tot_neg_prev = 0.0
    area = 0.0
    for idx in range(len(pos_hist) - 1, -1, -1):
        tot_pos_prev, tot_neg_prev = tot_pos, tot_neg
        tot_pos += pos_hist[idx]
        tot_neg += neg_hist[idx]
        area += abs(tot_neg - tot_neg_prev) * (tot_pos + tot_pos_prev) / 2.0
    if tot_pos > 0 and tot_neg > 0:
        return area / tot_pos / tot_neg
    return 0.0


class TestAuc:
    def _run(self, num_thresholds, batches, slide_steps=1):
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            pred = fluid.layers.data("pred", shape=[4, 2],
                                     append_batch_size=False)
            label = fluid.layers.data("label", shape=[4, 1], dtype="int32",
                                      append_batch_size=False)
            g_auc, b_auc, _ = fluid.layers.auc(
                pred, label, num_thresholds=num_thresholds,
                slide_steps=slide_steps)
        exe = fluid.Executor(fluid.CPUPlace())
        outs = []
        with scope_guard(Scope()):
            exe.run(startup)
            for p, l in batches:
                outs.append(exe.run(main, feed={"pred": p, "label": l},
                                    fetch_list=[g_auc, b_auc]))
        return outs

    def test_global_accumulates(self):
        rng = np.random.RandomState(0)
        T = 63
        batches = []
        for _ in range(3):
            p = rng.rand(4).astype("float32")
            pred = np.stack([1 - p, p], axis=1)
            lab = rng.randint(0, 2, size=(4, 1)).astype("int32")
            batches.append((pred, lab))
        outs = self._run(T, batches, slide_steps=1)

        # numpy oracle: global AUC over all seen batches
        pos = np.zeros(T + 1)
        neg = np.zeros(T + 1)
        for i, (pred, lab) in enumerate(batches):
            for j in range(4):
                b = min(int(pred[j, 1] * T), T)
                if lab[j, 0]:
                    pos[b] += 1
                else:
                    neg[b] += 1
            np.testing.assert_allclose(
                outs[i][0][0], np_auc(pos, neg), atol=1e-5,
                err_msg="global auc batch %d" % i)

    def test_batch_auc_is_windowed(self):
        rng = np.random.RandomState(1)
        T = 31
        batches = []
        for _ in range(4):
            p = rng.rand(4).astype("float32")
            pred = np.stack([1 - p, p], axis=1)
            lab = rng.randint(0, 2, size=(4, 1)).astype("int32")
            batches.append((pred, lab))
        # slide_steps=1 → batch AUC computed from the current batch only
        outs = self._run(T, batches, slide_steps=1)
        for i, (pred, lab) in enumerate(batches):
            pos = np.zeros(T + 1)
            neg = np.zeros(T + 1)
            for j in range(4):
                b = min(int(pred[j, 1] * T), T)
                if lab[j, 0]:
                    pos[b] += 1
                else:
                    neg[b] += 1
            np.testing.assert_allclose(
                outs[i][1][0], np_auc(pos, neg), atol=1e-5,
                err_msg="batch auc %d" % i)

    def test_slide_zero_batch_equals_global(self):
        rng = np.random.RandomState(2)
        batches = []
        for _ in range(3):
            p = rng.rand(4).astype("float32")
            pred = np.stack([1 - p, p], axis=1)
            lab = rng.randint(0, 2, size=(4, 1)).astype("int32")
            batches.append((pred, lab))
        outs = self._run(31, batches, slide_steps=0)
        for g, b in outs:
            np.testing.assert_allclose(np.asarray(g), np.asarray(b),
                                       atol=1e-7)

    def test_perfect_separation(self):
        pred = np.array([[0.9, 0.1], [0.8, 0.2], [0.2, 0.8], [0.1, 0.9]],
                        "float32")
        lab = np.array([[0], [0], [1], [1]], "int32")
        outs = self._run(255, [(pred, lab)])
        np.testing.assert_allclose(outs[0][0][0], 1.0, atol=1e-6)


class TestPrecisionRecall:
    def _build_and_run(self, C, ids, labels, weights=None, states=None):
        N = len(ids)
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            block = main.current_block()
            probs = fluid.layers.data("probs", shape=[N, 1],
                                      append_batch_size=False)
            idx = fluid.layers.data("idx", shape=[N, 1], dtype="int32",
                                    append_batch_size=False)
            lab = fluid.layers.data("lab", shape=[N, 1], dtype="int32",
                                    append_batch_size=False)
            ins = {"MaxProbs": [probs], "Indices": [idx], "Labels": [lab]}
            feed = {
                "probs": np.ones((N, 1), "float32"),
                "idx": np.asarray(ids, "int32").reshape(N, 1),
                "lab": np.asarray(labels, "int32").reshape(N, 1),
            }
            if weights is not None:
                w = fluid.layers.data("w", shape=[N, 1],
                                      append_batch_size=False)
                ins["Weights"] = [w]
                feed["w"] = np.asarray(weights, "float32").reshape(N, 1)
            if states is not None:
                st = fluid.layers.data("st", shape=[C, 4],
                                       append_batch_size=False)
                ins["StatesInfo"] = [st]
                feed["st"] = np.asarray(states, "float32")
            bm = block.create_var(name="bm", dtype="float32")
            am = block.create_var(name="am", dtype="float32")
            ast = block.create_var(name="ast", dtype="float32")
            block.append_op(
                type="precision_recall", inputs=ins,
                outputs={"BatchMetrics": [bm], "AccumMetrics": [am],
                         "AccumStatesInfo": [ast]},
                attrs={"class_number": C},
            )
        exe = fluid.Executor(fluid.CPUPlace())
        with scope_guard(Scope()):
            return exe.run(main, feed=feed, fetch_list=[bm, am, ast])

    @staticmethod
    def np_metrics(states):
        C = states.shape[0]
        precs, recs = [], []
        for c in range(C):
            tp, fp, tn, fn = states[c]
            precs.append(tp / (tp + fp) if (tp > 0 or fp > 0) else 1.0)
            recs.append(tp / (tp + fn) if (tp > 0 or fn > 0) else 1.0)
        mp, mr = np.mean(precs), np.mean(recs)
        mf1 = 2 * mp * mr / (mp + mr) if (mp > 0 or mr > 0) else 0.0
        ttp, tfp, tfn = states[:, 0].sum(), states[:, 1].sum(), states[:, 3].sum()
        up = ttp / (ttp + tfp) if (ttp > 0 or tfp > 0) else 1.0
        ur = ttp / (ttp + tfn) if (ttp > 0 or tfn > 0) else 1.0
        uf1 = 2 * up * ur / (up + ur) if (up > 0 or ur > 0) else 0.0
        return np.array([mp, mr, mf1, up, ur, uf1])

    @staticmethod
    def np_states(C, ids, labels, weights=None):
        states = np.zeros((C, 4))
        w = weights if weights is not None else [1.0] * len(ids)
        for i, (p, l) in enumerate(zip(ids, labels)):
            if p == l:
                states[p, 0] += w[i]
                states[:, 2] += w[i]
                states[p, 2] -= w[i]
            else:
                states[l, 3] += w[i]
                states[p, 1] += w[i]
                states[:, 2] += w[i]
                states[p, 2] -= w[i]
                states[l, 2] -= w[i]
        return states

    def test_batch_metrics(self):
        C = 3
        ids = [0, 1, 2, 1, 0]
        labels = [0, 1, 1, 2, 0]
        bm, am, ast = self._build_and_run(C, ids, labels)
        expect_states = self.np_states(C, ids, labels)
        np.testing.assert_allclose(ast, expect_states, atol=1e-5)
        np.testing.assert_allclose(bm, self.np_metrics(expect_states),
                                   atol=1e-5)
        np.testing.assert_allclose(am, bm, atol=1e-6)  # no prior states

    def test_weighted_with_accum(self):
        C = 2
        ids = [0, 1, 1]
        labels = [0, 0, 1]
        weights = [0.5, 2.0, 1.0]
        prior = np.array([[1.0, 0.0, 2.0, 0.0], [0.5, 0.5, 1.0, 1.0]],
                         "float32")
        bm, am, ast = self._build_and_run(C, ids, labels, weights, prior)
        batch_states = self.np_states(C, ids, labels, weights)
        np.testing.assert_allclose(bm, self.np_metrics(batch_states),
                                   atol=1e-5)
        np.testing.assert_allclose(ast, batch_states + prior, atol=1e-5)
        np.testing.assert_allclose(am, self.np_metrics(batch_states + prior),
                                   atol=1e-5)
