"""Numpy-oracle tests for the second-wave layers.nn surface
(ops/{vision,losses}.py + nn extras).  Harness pattern: op_test.py golden
oracles (reference unittests/test_*_op.py equivalents)."""

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.executor import Scope, scope_guard


def run_layer(build, feeds, n_out=1):
    fluid.unique_name.switch()
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        outs = build()
        if not isinstance(outs, (list, tuple)):
            outs = [outs]
    exe = fluid.Executor(fluid.CPUPlace())
    with scope_guard(Scope()):
        exe.run(startup)
        vals = exe.run(main, feed=feeds, fetch_list=list(outs))
    return vals[0] if n_out == 1 else vals


def _data(name, arr, stop_gradient=True):
    return fluid.layers.data(name, shape=list(arr.shape), dtype=str(arr.dtype),
                             append_batch_size=False,
                             stop_gradient=stop_gradient)


def test_selu():
    x = np.random.RandomState(0).randn(4, 5).astype("float32")
    got = run_layer(lambda: fluid.layers.selu(_data("x", x)), {"x": x})
    scale, alpha = 1.0507009873554805, 1.6732632423543772
    exp = scale * np.where(x > 0, x, alpha * (np.exp(x) - 1))
    np.testing.assert_allclose(got, exp, rtol=1e-6)


def test_maxout():
    x = np.random.RandomState(1).randn(2, 6, 3, 3).astype("float32")
    got = run_layer(lambda: fluid.layers.maxout(_data("x", x), groups=3),
                    {"x": x})
    exp = x.reshape(2, 2, 3, 3, 3).max(axis=2)
    np.testing.assert_allclose(got, exp)


def test_multiplex():
    rng = np.random.RandomState(2)
    xs = [rng.randn(4, 3).astype("float32") for _ in range(3)]
    ids = np.array([[2], [0], [1], [2]], "int32")
    got = run_layer(
        lambda: fluid.layers.multiplex(
            [_data("x%d" % i, x) for i, x in enumerate(xs)],
            _data("ids", ids)),
        {"x%d" % i: x for i, x in enumerate(xs)} | {"ids": ids})
    exp = np.stack([xs[ids[i, 0]][i] for i in range(4)])
    np.testing.assert_allclose(got, exp)


def test_crop_and_pad_constant_like():
    x = np.arange(24, dtype="float32").reshape(2, 3, 4)
    got = run_layer(
        lambda: fluid.layers.crop(_data("x", x), shape=[1, 2, 2],
                                  offsets=[1, 0, 1]), {"x": x})
    np.testing.assert_allclose(got, x[1:2, 0:2, 1:3])

    big = np.zeros((3, 5), "float32")
    small = np.ones((2, 3), "float32")
    got = run_layer(
        lambda: fluid.layers.pad_constant_like(
            _data("b", big), _data("s", small), pad_value=7.0),
        {"b": big, "s": small})
    exp = np.full((3, 5), 7.0, "float32")
    exp[:2, :3] = 1.0
    np.testing.assert_allclose(got, exp)


def test_pixel_shuffle_shuffle_channel_space_to_depth():
    rng = np.random.RandomState(3)
    x = rng.randn(2, 8, 3, 3).astype("float32")
    got = run_layer(lambda: fluid.layers.pixel_shuffle(_data("x", x), 2),
                    {"x": x})
    exp = x.reshape(2, 2, 2, 2, 3, 3).transpose(0, 1, 4, 2, 5, 3) \
        .reshape(2, 2, 6, 6)
    np.testing.assert_allclose(got, exp)

    got = run_layer(lambda: fluid.layers.shuffle_channel(_data("x", x), 4),
                    {"x": x})
    exp = x.reshape(2, 4, 2, 3, 3).transpose(0, 2, 1, 3, 4).reshape(x.shape)
    np.testing.assert_allclose(got, exp)

    y = rng.randn(2, 3, 4, 4).astype("float32")
    got = run_layer(lambda: fluid.layers.space_to_depth(_data("y", y), 2),
                    {"y": y})
    exp = y.reshape(2, 3, 2, 2, 2, 2).transpose(0, 3, 5, 1, 2, 4) \
        .reshape(2, 12, 2, 2)
    np.testing.assert_allclose(got, exp)


def test_temporal_shift():
    rng = np.random.RandomState(4)
    t, ratio = 3, 0.25
    x = rng.randn(6, 4, 2, 2).astype("float32")  # N=2, T=3
    got = run_layer(
        lambda: fluid.layers.temporal_shift(_data("x", x), t, ratio),
        {"x": x})
    xr = x.reshape(2, 3, 4, 2, 2)
    exp = np.zeros_like(xr)
    exp[:, :-1, :1] = xr[:, 1:, :1]    # backward shift
    exp[:, 1:, 1:2] = xr[:, :-1, 1:2]  # forward shift
    exp[:, :, 2:] = xr[:, :, 2:]
    np.testing.assert_allclose(got, exp.reshape(x.shape))


def test_affine_channel_and_fsp():
    rng = np.random.RandomState(5)
    x = rng.randn(2, 3, 4, 4).astype("float32")
    s = rng.randn(3).astype("float32")
    b = rng.randn(3).astype("float32")
    got = run_layer(
        lambda: fluid.layers.affine_channel(
            _data("x", x), _data("s", s), _data("b", b)),
        {"x": x, "s": s, "b": b})
    np.testing.assert_allclose(
        got, x * s[None, :, None, None] + b[None, :, None, None],
        rtol=1e-5, atol=1e-6)

    y = rng.randn(2, 5, 4, 4).astype("float32")
    got = run_layer(
        lambda: fluid.layers.fsp_matrix(_data("x", x), _data("y", y)),
        {"x": x, "y": y})
    exp = np.einsum("bchw,bdhw->bcd", x, y) / 16.0
    np.testing.assert_allclose(got, exp, rtol=1e-5)


def test_lrn():
    rng = np.random.RandomState(6)
    x = rng.rand(2, 7, 3, 3).astype("float32")
    got = run_layer(lambda: fluid.layers.lrn(_data("x", x), n=5, k=2.0,
                                             alpha=1e-4, beta=0.75),
                    {"x": x})
    sq = x ** 2
    mid = np.zeros_like(x) + 2.0
    for c in range(7):
        lo, hi = max(0, c - 2), min(7, c + 3)
        mid[:, c] += 1e-4 * sq[:, lo:hi].sum(axis=1)
    np.testing.assert_allclose(got, x * mid ** -0.75, rtol=1e-5)


def test_unfold():
    rng = np.random.RandomState(7)
    x = rng.randn(2, 3, 5, 5).astype("float32")
    got = run_layer(
        lambda: fluid.layers.unfold(_data("x", x), [2, 2], 1, 0, 1),
        {"x": x})
    # numpy im2col oracle
    cols = []
    for i in range(2):
        for j in range(2):
            cols.append(x[:, :, i:i + 4, j:j + 4])
    exp = np.stack(cols, 2).reshape(2, 3 * 4, 16)
    np.testing.assert_allclose(got, exp)


def test_grid_sampler_identity():
    rng = np.random.RandomState(8)
    x = rng.randn(2, 3, 4, 4).astype("float32")
    ys, xs = np.meshgrid(np.linspace(-1, 1, 4), np.linspace(-1, 1, 4),
                         indexing="ij")
    grid = np.stack([xs, ys], -1)[None].repeat(2, 0).astype("float32")
    got = run_layer(
        lambda: fluid.layers.grid_sampler(_data("x", x), _data("g", grid)),
        {"x": x, "g": grid})
    np.testing.assert_allclose(got, x, rtol=1e-5, atol=1e-5)


def test_affine_grid_identity_transform():
    theta = np.tile(np.array([[[1, 0, 0], [0, 1, 0]]], "float32"), (2, 1, 1))
    got = run_layer(
        lambda: fluid.layers.affine_grid(_data("t", theta), [2, 3, 4, 5]),
        {"t": theta})
    ys, xs = np.meshgrid(np.linspace(-1, 1, 4), np.linspace(-1, 1, 5),
                         indexing="ij")
    exp = np.stack([xs, ys], -1)[None].repeat(2, 0).astype("float32")
    np.testing.assert_allclose(got, exp, rtol=1e-5, atol=1e-6)


def test_roi_pool():
    x = np.arange(16, dtype="float32").reshape(1, 1, 4, 4)
    rois = np.array([[0, 0, 0, 3, 3]], "float32")  # whole image
    got = run_layer(
        lambda: fluid.layers.roi_pool(_data("x", x), _data("r", rois),
                                      pooled_height=2, pooled_width=2),
        {"x": x, "r": rois})
    exp = np.array([[[[5, 7], [13, 15]]]], "float32")
    np.testing.assert_allclose(got, exp)


def test_psroi_pool():
    # C = out_c(1) * 2*2; each bin reads its own channel group
    x = np.stack([np.full((3, 3), i, "float32") for i in range(4)])[None]
    rois = np.array([[0, 0, 0, 3, 3]], "float32")
    got = run_layer(
        lambda: fluid.layers.psroi_pool(
            _data("x", x), _data("r", rois), 1, 1.0, 2, 2),
        {"x": x, "r": rois})
    np.testing.assert_allclose(got.reshape(-1), [0, 1, 2, 3], atol=1e-6)


def test_losses_against_formulas():
    rng = np.random.RandomState(9)
    p = rng.rand(6, 1).astype("float32") * 0.9 + 0.05
    y = (rng.rand(6, 1) > 0.5).astype("float32")
    got = run_layer(
        lambda: fluid.layers.log_loss(_data("p", p), _data("y", y)),
        {"p": p, "y": y})
    exp = -y * np.log(p + 1e-4) - (1 - y) * np.log(1 - p + 1e-4)
    np.testing.assert_allclose(got, exp, rtol=1e-5)

    x = rng.randn(4, 5).astype("float32")
    t = rng.rand(4, 5).astype("float32")
    got = run_layer(
        lambda: fluid.layers.kldiv_loss(_data("x", x), _data("t", t),
                                        reduction="none"),
        {"x": x, "t": t})
    np.testing.assert_allclose(got, t * (np.log(t) - x), rtol=1e-4)

    l = rng.randn(5, 1).astype("float32")
    r = rng.randn(5, 1).astype("float32")
    lab = (rng.rand(5, 1) > 0.5).astype("float32")
    got = run_layer(
        lambda: fluid.layers.rank_loss(
            _data("lab", lab), _data("l", l), _data("r", r)),
        {"lab": lab, "l": l, "r": r})
    o = l - r
    np.testing.assert_allclose(got, np.log1p(np.exp(o)) - lab * o, rtol=1e-5)

    got = run_layer(
        lambda: fluid.layers.margin_rank_loss(
            _data("lab", lab), _data("l", l), _data("r", r), margin=0.1),
        {"lab": lab, "l": l, "r": r})
    np.testing.assert_allclose(
        got, np.maximum(0, -lab * (l - r) + 0.1), rtol=1e-5)


def test_bpr_loss_oracle():
    rng = np.random.RandomState(10)
    x = rng.randn(4, 6).astype("float32")
    lab = rng.randint(0, 6, (4, 1)).astype("int64")
    got = run_layer(
        lambda: fluid.layers.bpr_loss(_data("x", x), _data("y", lab)),
        {"x": x, "y": lab})
    exp = np.zeros((4, 1), "float32")
    for i in range(4):
        s = 0.0
        for j in range(6):
            if j == lab[i, 0]:
                continue
            s += -np.log(1.0 + np.exp(x[i, j] - x[i, lab[i, 0]]))
        exp[i, 0] = -s / 5.0
    np.testing.assert_allclose(got, exp, rtol=1e-4)


def test_teacher_student_loss_oracle():
    x = np.array([0.5, -0.3, 1.2, -0.8], "float32")[:, None]
    lab = np.array([-2.0, -1.0, 0.7, 1.4], "float32")[:, None]
    got = run_layer(
        lambda: fluid.layers.teacher_student_sigmoid_loss(
            _data("x", x), _data("y", lab)),
        {"x": x, "y": lab})
    exp = np.zeros_like(x)
    for i in range(4):
        xi, li = x[i, 0], lab[i, 0]
        sce = max(xi, 0) + np.log1p(np.exp(-abs(xi)))
        if li < -1.0:
            exp[i, 0] = sce
        elif li < 0.0:
            exp[i, 0] = sce - xi
        elif li < 1.0:
            exp[i, 0] = sce + max(xi, 0) - xi * li \
                + np.log1p(np.exp(-abs(xi)))
        else:
            exp[i, 0] = sce - xi + max(xi, 0) - xi * (li - 1.0) \
                + np.log1p(np.exp(-abs(xi)))
    np.testing.assert_allclose(got, exp, rtol=1e-5)


def test_mean_iou():
    pred = np.array([0, 1, 1, 2, 2, 2], "int32")
    lab = np.array([0, 1, 2, 2, 2, 1], "int32")
    miou, wrong, correct = run_layer(
        lambda: fluid.layers.mean_iou(_data("p", pred), _data("l", lab), 4),
        {"p": pred, "l": lab}, n_out=3)
    # class0: 1/1, class1: 1/3, class2: 2/4; class3 absent
    np.testing.assert_allclose(miou, (1.0 + 1 / 3 + 0.5) / 3, rtol=1e-5)
    np.testing.assert_allclose(correct, [1, 1, 2, 0])


def test_bilinear_tensor_product_shape_and_value():
    rng = np.random.RandomState(11)
    x = rng.randn(3, 4).astype("float32")
    y = rng.randn(3, 5).astype("float32")

    def build():
        return fluid.layers.bilinear_tensor_product(
            _data("x", x), _data("y", y), size=2,
            param_attr=fluid.ParamAttr(
                name="btp.w",
                initializer=fluid.initializer.Constant(0.1)),
            bias_attr=fluid.ParamAttr(
                name="btp.b",
                initializer=fluid.initializer.Constant(0.5)))

    got = run_layer(build, {"x": x, "y": y})
    w = np.full((2, 4, 5), 0.1, "float32")
    exp = np.einsum("bi,kij,bj->bk", x, w, y) + 0.5
    np.testing.assert_allclose(got, exp, rtol=1e-5)


def test_add_position_encoding():
    rng = np.random.RandomState(12)
    x = rng.randn(2, 3, 6).astype("float32")
    got = run_layer(
        lambda: fluid.layers.add_position_encoding(_data("x", x), 0.7, 0.3),
        {"x": x})
    half = 3
    pe = np.zeros((3, 6), "float32")
    for j in range(3):
        for k in range(half):
            v = j / np.power(10000.0, k / (half - 1))
            pe[j, k] = np.sin(v)
            pe[j, half + k] = np.cos(v)
    np.testing.assert_allclose(got, 0.7 * x + 0.3 * pe[None], rtol=1e-4,
                               atol=1e-5)


def test_row_conv():
    rng = np.random.RandomState(13)
    x = rng.randn(2, 5, 3).astype("float32")

    def build():
        return fluid.layers.row_conv(
            _data("x", x), future_context_size=2,
            param_attr=fluid.ParamAttr(
                name="rc.w",
                initializer=fluid.initializer.Constant(0.5)))

    got = run_layer(build, {"x": x})
    w = np.full((3, 3), 0.5, "float32")
    exp = np.zeros_like(x)
    for t in range(5):
        for i in range(3):
            if t + i < 5:
                exp[:, t] += x[:, t + i] * w[i]
    np.testing.assert_allclose(got, exp, rtol=1e-5)


def test_spectral_norm_unit_sigma():
    rng = np.random.RandomState(14)
    w = rng.randn(6, 4).astype("float32")

    def build():
        return fluid.layers.spectral_norm(_data("w", w, False),
                                          power_iters=50)

    got = run_layer(build, {"w": w})
    # after normalization the top singular value is ~1
    s = np.linalg.svd(got, compute_uv=False)
    np.testing.assert_allclose(s[0], 1.0, rtol=1e-3)


def test_data_norm():
    rng = np.random.RandomState(15)
    x = rng.randn(8, 4).astype("float32")
    got = run_layer(lambda: fluid.layers.data_norm(_data("x", x)), {"x": x})
    # fresh stats: size=1e4, sum=0, sqsum=1e4 -> means 0, scales 1
    np.testing.assert_allclose(got, x, rtol=1e-5)


def test_hash_deterministic_in_range():
    ids = np.array([[1, 2], [3, 4], [1, 2]], "int64")
    a = run_layer(lambda: fluid.layers.hash(_data("i", ids), 1000, 2),
                  {"i": ids})
    b = run_layer(lambda: fluid.layers.hash(_data("i", ids), 1000, 2),
                  {"i": ids})
    np.testing.assert_array_equal(a, b)
    assert a.min() >= 0 and a.max() < 1000
    np.testing.assert_array_equal(a[0], a[2])  # same row -> same hash
    assert not np.array_equal(a[0], a[1])


def test_sampling_id_and_randoms():
    probs = np.array([[0, 0, 1, 0], [1, 0, 0, 0]], "float32")
    got = run_layer(lambda: fluid.layers.sampling_id(_data("p", probs)),
                    {"p": probs})
    np.testing.assert_array_equal(got, [2, 0])

    x = np.zeros((5, 3), "float32")
    got = run_layer(
        lambda: fluid.layers.uniform_random_batch_size_like(
            _data("x", x), shape=[-1, 7], min=2.0, max=3.0),
        {"x": x})
    assert got.shape == (5, 7) and got.min() >= 2.0 and got.max() <= 3.0
    got = run_layer(
        lambda: fluid.layers.gaussian_random_batch_size_like(
            _data("x", x), shape=[-1, 9], mean=10.0, std=0.1),
        {"x": x})
    assert got.shape == (5, 9) and abs(got.mean() - 10.0) < 0.5


def test_random_crop():
    x = np.arange(64, dtype="float32").reshape(1, 8, 8)
    got = run_layer(
        lambda: fluid.layers.random_crop(_data("x", x), shape=[4, 4]),
        {"x": x})
    assert got.shape == (1, 4, 4)
    # crop is a contiguous window: row deltas are 1, col deltas are 8
    np.testing.assert_allclose(np.diff(got[0], axis=1), 1.0)
    np.testing.assert_allclose(np.diff(got[0], axis=0), 8.0)


def test_compositions_and_misc():
    rng = np.random.RandomState(16)
    probs = rng.rand(4, 3).astype("float32")
    probs /= probs.sum(1, keepdims=True)
    lab = rng.randint(0, 3, (4, 1)).astype("int64")
    got = run_layer(
        lambda: fluid.layers.dice_loss(_data("p", probs), _data("l", lab)),
        {"p": probs, "l": lab})
    assert got.shape in ((), (1,)) and 0.0 <= float(np.ravel(got)[0]) <= 1.0

    a = rng.randn(4, 8).astype("float32")
    p = rng.randn(4, 8).astype("float32")
    labels = np.arange(4).astype("int64")
    got = run_layer(
        lambda: fluid.layers.npair_loss(
            _data("a", a), _data("p", p), _data("l", labels)),
        {"a": a, "p": p, "l": labels})
    assert np.isfinite(got).all()

    x = np.zeros((2, 3, 4), "float32")
    got = run_layer(lambda: fluid.layers.rank(_data("x", x)), {"x": x})
    assert int(np.ravel(got)[0]) == 3

    xs = [rng.randn(3, 2).astype("float32") for _ in range(3)]
    got = run_layer(
        lambda: fluid.layers.sum(
            [_data("s%d" % i, x) for i, x in enumerate(xs)]),
        {"s%d" % i: x for i, x in enumerate(xs)})
    np.testing.assert_allclose(got, np.sum(xs, axis=0), rtol=1e-5)

    b = np.array([[True, False], [True, True]])
    got = run_layer(lambda: fluid.layers.reduce_all(_data("b", b)), {"b": b})
    assert not bool(np.ravel(got)[0])
    got = run_layer(lambda: fluid.layers.reduce_any(_data("b", b)), {"b": b})
    assert bool(np.ravel(got)[0])

    x = np.array([7.0, -7.0], "float32")
    y = np.array([3.0, 3.0], "float32")
    got = run_layer(
        lambda: fluid.layers.elementwise_mod(
            _data("x", np.array([7, -7], "int64")),
            _data("y", np.array([3, 3], "int64"))),
        {"x": np.array([7, -7], "int64"), "y": np.array([3, 3], "int64")})
    np.testing.assert_array_equal(got, [1, 2])  # python-style mod
    got = run_layer(
        lambda: fluid.layers.elementwise_floordiv(
            _data("x", np.array([7, -7], "int64")),
            _data("y", np.array([3, 3], "int64"))),
        {"x": np.array([7, -7], "int64"), "y": np.array([3, 3], "int64")})
    np.testing.assert_array_equal(got, [2, -3])


def test_step_counter():
    fluid.unique_name.switch()
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        c = fluid.layers.autoincreased_step_counter()
    exe = fluid.Executor(fluid.CPUPlace())
    with scope_guard(Scope()):
        exe.run(startup)
        vals = [int(exe.run(main, fetch_list=[c])[0][0]) for _ in range(3)]
    assert vals == [1, 2, 3]


def test_grads_flow_through_new_ops():
    """Spot grad-check: losses and samplers backprop into inputs."""
    rng = np.random.RandomState(17)
    x = rng.randn(3, 4).astype("float32")
    fluid.unique_name.switch()
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        xv = _data("x", x, stop_gradient=False)
        out = fluid.layers.selu(xv)
        out = fluid.layers.reduce_sum(out)
        (gx,) = fluid.backward.gradients(out, xv)
    exe = fluid.Executor(fluid.CPUPlace())
    with scope_guard(Scope()):
        gv = exe.run(main, feed={"x": x}, fetch_list=[gx])[0]
    scale, alpha = 1.0507009873554805, 1.6732632423543772
    exp = np.where(x > 0, scale, scale * alpha * np.exp(x))
    np.testing.assert_allclose(gv, exp, rtol=1e-4)
