"""Dygraph grad_clip (round-4 advisor fix): minimize(grad_clip=...)
must clip on the eager path with the same math as the graph-path clip
classes, instead of silently training unclipped."""

import numpy as np

import paddle_tpu as fluid
from paddle_tpu.dygraph import guard, to_variable, Linear


def _one_step(grad_clip):
    """One SGD step on y = sum(w*x) with huge grads; returns the weight
    delta actually applied."""
    rng = np.random.RandomState(0)
    with guard():
        model = Linear(4, 1, bias_attr=False)
        w0 = np.asarray(model.weight.value).copy()
        opt = fluid.optimizer.SGD(learning_rate=1.0)
        x = to_variable(np.full((2, 4), 100.0, "float32"))
        loss_v = model(x)
        from paddle_tpu.dygraph.varbase import eager_op

        loss = eager_op("mean", {"X": [loss_v]})[0]
        loss.backward()
        grad = np.asarray(model.weight._grad).copy()
        opt.minimize(loss, parameter_list=model.parameters(),
                     grad_clip=grad_clip)
        w1 = np.asarray(model.weight.value)
    return w0, w1, grad


def test_clip_by_global_norm_applied():
    clip = fluid.clip.GradientClipByGlobalNorm(1.0)
    w0, w1, grad = _one_step(clip)
    gnorm = np.sqrt((grad ** 2).sum())
    assert gnorm > 1.0  # the scenario actually exercises the clip
    expected = grad * (1.0 / gnorm)
    np.testing.assert_allclose(w0 - w1, expected, rtol=1e-5)


def test_clip_by_value_applied():
    clip = fluid.clip.GradientClipByValue(max=0.5)
    w0, w1, grad = _one_step(clip)
    np.testing.assert_allclose(w0 - w1, np.clip(grad, -0.5, 0.5),
                               rtol=1e-5)


def test_clip_by_norm_applied():
    clip = fluid.clip.GradientClipByNorm(2.0)
    w0, w1, grad = _one_step(clip)
    n = np.sqrt((grad ** 2).sum())
    np.testing.assert_allclose(w0 - w1, grad * (2.0 / max(n, 2.0)),
                               rtol=1e-5)
