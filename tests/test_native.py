"""Native runtime tests: recordio round-trip, MultiSlot parser (native vs
pure-Python equivalence — the reference's C++-vs-oracle test pattern, e.g.
recordio/scanner_test.cc, and the MultiSlot parse semantics of
data_feed.cc:525)."""

import os

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import native, recordio_writer
from paddle_tpu.executor import Scope, scope_guard
from paddle_tpu.dataset import DatasetFactory


needs_native = pytest.mark.skipif(not native.is_native(),
                                  reason="native lib unavailable")


class TestRecordIO:
    def test_roundtrip_native(self, tmp_path):
        path = str(tmp_path / "a.recordio")
        records = [b"hello", b"", b"x" * 5000, "unicode \xe9".encode()]
        with native.RecordIOWriter(path, max_chunk_records=2) as w:
            for r in records:
                w.write(r)
        with native.RecordIOScanner(path) as s:
            got = list(s)
        assert got == records

    def test_python_reads_native_and_vice_versa(self, tmp_path):
        """The fallback writer/scanner and the C++ ones share the format."""
        path = str(tmp_path / "b.recordio")
        records = [os.urandom(n) for n in (1, 100, 4096)]
        with native.RecordIOWriter(path, max_chunk_records=2) as w:
            for r in records:
                w.write(r)

        # force the python fallback scanner on the natively written file
        sc = native.RecordIOScanner.__new__(native.RecordIOScanner)
        sc._lib = None
        sc._f = open(path, "rb")
        sc._chunk, sc._cursor = [], 0
        assert list(sc) == records
        sc.close()

        # python writer → native scanner
        path2 = str(tmp_path / "c.recordio")
        w = native.RecordIOWriter.__new__(native.RecordIOWriter)
        w._lib = None
        w._path = path2
        w._max_records = 2
        w._max_bytes = 1 << 20
        w._f = open(path2, "wb")
        w._records, w._pending = [], 0
        for r in records:
            w.write(r)
        w.close()
        with native.RecordIOScanner(path2) as s:
            assert list(s) == records

    def test_corruption_detected(self, tmp_path):
        path = str(tmp_path / "d.recordio")
        with native.RecordIOWriter(path) as w:
            w.write(b"payload-payload")
        raw = bytearray(open(path, "rb").read())
        raw[-3] ^= 0xFF  # flip a payload byte → CRC mismatch
        open(path, "wb").write(bytes(raw))
        with native.RecordIOScanner(path) as s:
            with pytest.raises((IOError, StopIteration)) as ei:
                next(s)
            assert ei.type is not StopIteration

    def test_convert_reader(self, tmp_path):
        path = str(tmp_path / "e.recordio")
        rng = np.random.RandomState(0)
        samples = [(rng.rand(3, 2).astype("float32"),
                    np.array([i], "int64")) for i in range(7)]
        n = recordio_writer.convert_reader_to_recordio_file(
            path, lambda: iter(samples))
        assert n == 7
        back = list(recordio_writer.recordio_reader(path)())
        assert len(back) == 7
        for (a, b), (a2, b2) in zip(samples, back):
            np.testing.assert_array_equal(a, a2)
            np.testing.assert_array_equal(b, b2)


class TestMultiSlotParser:
    def _write_file(self, tmp_path, lines):
        p = str(tmp_path / "part-0.txt")
        with open(p, "w") as f:
            f.write("\n".join(lines) + "\n")
        return p

    def test_parse_matches_python(self, tmp_path):
        rng = np.random.RandomState(1)
        lines = []
        for _ in range(50):
            ids = rng.randint(0, 1000, size=rng.randint(1, 5))
            dense = rng.rand(3)
            label = rng.randint(0, 2)
            lines.append(
                "%d %s %d %s 1 %d" % (
                    len(ids), " ".join(map(str, ids)),
                    len(dense), " ".join("%.4f" % v for v in dense),
                    label))
        path = self._write_file(tmp_path, lines)
        types = ["uint64", "float", "uint64"]
        lens = [5, 3, 1]
        got = native.parse_multislot_file(path, types, lens)

        # pure-python oracle (same function with lib forced off)
        import unittest.mock as mock

        with mock.patch.object(native, "get_lib", return_value=None):
            expect = native.parse_multislot_file(path, types, lens)
        assert len(got) == 3
        for g, e in zip(got, expect):
            assert g.dtype == e.dtype
            np.testing.assert_allclose(g, e, atol=1e-6)

    def test_malformed_lines_skipped_consistently(self, tmp_path):
        """Comment/garbage/short lines are skipped, not parsed as zeros or
        crashed on — native and fallback agree (data_feed.cc enforces
        nonzero counts and skips unparseable instances)."""
        lines = [
            "1 5 2 0.5 0.5",        # valid
            "# comment line",        # non-numeric → skip
            "0 1 0.1 0.1",           # zero count → skip
            "1 7 2 0.25",            # short value list → skip
            "1 9 2 0.125 0.25",      # valid
        ]
        path = self._write_file(tmp_path, lines)
        types, lens = ["uint64", "float"], [1, 2]
        got = native.parse_multislot_file(path, types, lens)

        import unittest.mock as mock

        with mock.patch.object(native, "get_lib", return_value=None):
            expect = native.parse_multislot_file(path, types, lens)
        for g, e in zip(got, expect):
            np.testing.assert_allclose(g, e, atol=1e-6)
        assert got[0].shape[0] == 2
        np.testing.assert_array_equal(got[0].ravel(), [5, 9])

    @needs_native
    def test_multithreaded_consistent(self, tmp_path):
        rng = np.random.RandomState(2)
        lines = ["1 %d 2 %.3f %.3f" % (rng.randint(100), rng.rand(),
                                       rng.rand())
                 for _ in range(1000)]
        path = self._write_file(tmp_path, lines)
        one = native.parse_multislot_file(path, ["uint64", "float"], [1, 2],
                                          threads=1)
        many = native.parse_multislot_file(path, ["uint64", "float"], [1, 2],
                                           threads=8)
        for a, b in zip(one, many):
            np.testing.assert_array_equal(a, b)

    def test_dataset_uses_native(self, tmp_path):
        """QueueDataset batch_iterator over a MultiSlot file (the CTR ingest
        path, Executor.train_from_dataset upstream)."""
        lines = ["2 7 9 1 0.5 1 1", "1 3 1 0.25 1 0"]
        path = self._write_file(tmp_path, lines)
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            ids = fluid.layers.data("ids", shape=[-1, 2], dtype="int64",
                                    append_batch_size=False)
            dense = fluid.layers.data("dense", shape=[-1, 1],
                                      append_batch_size=False)
            label = fluid.layers.data("lbl", shape=[-1, 1], dtype="int64",
                                      append_batch_size=False)
        ds = DatasetFactory().create_dataset("QueueDataset")
        ds.set_batch_size(2)
        ds.set_filelist([path])
        ds.set_use_var([ids, dense, label])
        batches = list(ds.batch_iterator())
        assert len(batches) == 1
        np.testing.assert_array_equal(batches[0]["ids"],
                                      [[7, 9], [3, 0]])
        np.testing.assert_allclose(batches[0]["dense"], [[0.5], [0.25]])
        np.testing.assert_array_equal(batches[0]["lbl"], [[1], [0]])


def test_native_blocking_queue_mpmc_and_close():
    """native blocking queue (reference framework/blocking_queue.h +
    LoDTensorBlockingQueue, pybind.cc:591): bounded, blocking, ordered
    per-producer, drains after close."""
    import threading
    from paddle_tpu import native

    q = native.BlockingQueue(capacity=2)
    got = []

    def producer():
        for i in range(20):
            assert q.push({"i": i, "a": np.arange(3) * i})
        q.close()

    t = threading.Thread(target=producer)
    t.start()
    while True:
        item = q.pop()
        if item is None:
            break
        got.append(item["i"])
    t.join()
    assert got == list(range(20))
    # push after close is rejected on both native and fallback paths
    assert q.push({"i": 99}) is False


def test_pyreader_uses_bounded_queue():
    import paddle_tpu as fluid
    from paddle_tpu.reader import _Prefetcher

    def gen():
        for i in range(7):
            yield {"x": np.full((2, 2), i, "float32")}

    p = _Prefetcher(gen, capacity=3)
    p.start()
    items = list(p)
    assert len(items) == 7
    np.testing.assert_allclose(items[-1]["x"], 6.0)
