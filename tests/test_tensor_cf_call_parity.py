"""Call parity for the reference ``layers.tensor`` (23 names) and
``layers.control_flow`` (19 names) surfaces — every ``__all__`` name
called with reference-default arguments (companion to
test_nn_call_parity.py; round-3 verdict asked for the tensor/
control-flow surfaces too)."""

import numpy as np
import pytest

import paddle_tpu as fluid

L = fluid.layers


def _d(name, shape, dtype="float32"):
    return L.data(name, shape=shape, dtype=dtype, append_batch_size=False)


def _while_loop():
    i = L.fill_constant([1], "float32", 0.0)
    limit = L.fill_constant([1], "float32", 2.0)
    cond = L.less_than(i, limit)
    w = L.While(cond)
    with w.block():
        L.increment(i, in_place=True)
        L.less_than(i, limit, cond=cond)
    return i


def _switch():
    lr = L.create_global_var([1], 0.0, "float32", persistable=True)
    step = L.fill_constant([1], "float32", 5.0)
    b1 = L.fill_constant([1], "float32", 1.0)
    with L.Switch() as switch:
        with switch.case(L.less_than(step, b1)):
            L.assign(L.fill_constant([1], "float32", 0.1), lr)
        with switch.default():
            L.assign(L.fill_constant([1], "float32", 0.2), lr)
    return lr


def _ifelse():
    x = _d("x", [2, 1])
    y = L.fill_constant([2, 1], "float32", 0.0)
    ie = L.IfElse(L.less_than(x, y))
    with ie.true_block():
        ie.output(ie.input(x) * (-1.0))
    with ie.false_block():
        ie.output(ie.input(x))
    (out,) = ie()
    return out


def _dynamic_rnn():
    x = _d("x", [2, 3, 4])
    sl = _d("sl", [2], "int64")
    rnn = L.DynamicRNN()
    with rnn.block():
        xt = rnn.step_input(x, lengths=sl)
        h = rnn.memory(shape=[4], value=0.0)
        nh = L.elementwise_add(xt, h)
        rnn.update_memory(h, nh)
        rnn.output(nh)
    return rnn()


def _static_rnn():
    x = _d("x", [3, 2, 4])  # [T, B, D] step-major
    h0 = L.fill_constant([2, 4], "float32", 0.0)
    rnn = L.StaticRNN()
    with rnn.step():
        xt = rnn.step_input(x)
        h = rnn.memory(init=h0)
        nh = L.elementwise_add(xt, h)
        rnn.update_memory(h, nh)
        rnn.step_output(nh)
    return rnn()


def _array_ops():
    i = L.fill_constant([1], "int32", 0)
    arr = L.array_write(L.fill_constant([2], "float32", 1.0), i,
                        capacity=4)
    back = L.array_read(arr, i)
    n = L.array_length(arr)
    return back, n


TENSOR_BUILDERS = {
    "create_tensor": lambda: L.create_tensor("float32"),
    "create_parameter": lambda: L.create_parameter([2, 3], "float32"),
    "create_global_var": lambda: L.create_global_var([1], 1.0, "float32"),
    "cast": lambda: L.cast(_d("x", [2, 2]), "int64"),
    "tensor_array_to_tensor": lambda: L.tensor_array_to_tensor(
        L.array_write(L.fill_constant([2, 1], "float32", 1.0),
                      L.fill_constant([1], "int32", 0), capacity=2)),
    "concat": lambda: L.concat([_d("a", [2, 2]), _d("b", [2, 2])]),
    "sums": lambda: L.sums([_d("a", [2, 2]), _d("b", [2, 2])]),
    "assign": lambda: L.assign(_d("x", [2, 2])),
    "fill_constant_batch_size_like": lambda:
        L.fill_constant_batch_size_like(_d("x", [2, 2]), [2, 5],
                                        "float32", 0.0),
    "fill_constant": lambda: L.fill_constant([2, 2], "float32", 1.5),
    "argmin": lambda: L.tensor.argmin(_d("x", [2, 3])),
    "argmax": lambda: L.tensor.argmax(_d("x", [2, 3])),
    "argsort": lambda: L.argsort(_d("x", [2, 3])),
    "ones": lambda: L.ones([2, 2], "float32"),
    "zeros": lambda: L.zeros([2, 2], "float32"),
    "reverse": lambda: L.reverse(_d("x", [2, 3]), axis=0),
    "has_inf": lambda: L.has_inf(_d("x", [2, 2])),
    "has_nan": lambda: L.has_nan(_d("x", [2, 2])),
    "isfinite": lambda: L.isfinite(_d("x", [2, 2])),
    "range": lambda: L.range(0, 10, 2, "int64"),
    "linspace": lambda: L.linspace(0.0, 1.0, 5, "float32"),
    "zeros_like": lambda: L.zeros_like(_d("x", [2, 2])),
    "diag": lambda: L.tensor.diag(_d("d", [3])),
}

CF_BUILDERS = {
    "While": _while_loop,
    "Switch": _switch,
    "increment": lambda: L.increment(L.fill_constant([1], "float32", 0.0)),
    "array_write": lambda: _array_ops()[0],
    "create_array": lambda: L.create_array("float32"),
    "less_than": lambda: L.less_than(_d("a", [2]), _d("b", [2])),
    "less_equal": lambda: L.less_equal(_d("a", [2]), _d("b", [2])),
    "greater_than": lambda: L.greater_than(_d("a", [2]), _d("b", [2])),
    "greater_equal": lambda: L.greater_equal(_d("a", [2]), _d("b", [2])),
    "equal": lambda: L.equal(_d("a", [2]), _d("b", [2])),
    "not_equal": lambda: L.not_equal(_d("a", [2]), _d("b", [2])),
    "array_read": lambda: _array_ops()[0],
    "array_length": lambda: _array_ops()[1],
    "IfElse": _ifelse,
    "DynamicRNN": _dynamic_rnn,
    "StaticRNN": _static_rnn,
    "reorder_lod_tensor_by_rank": lambda: L.reorder_lod_tensor_by_rank(
        _d("x", [3, 2]), _d("rt", [3], "int64")),
    "Print": lambda: L.Print(_d("x", [2, 2])),
    "is_empty": lambda: L.is_empty(_d("x", [2, 2])),
}

REFERENCE_TENSOR_ALL = list(TENSOR_BUILDERS)
REFERENCE_CF_ALL = list(CF_BUILDERS)


def test_surface_counts_match_reference():
    assert len(REFERENCE_TENSOR_ALL) == 23
    assert len(REFERENCE_CF_ALL) == 19


@pytest.mark.parametrize("name", REFERENCE_TENSOR_ALL)
def test_tensor_call(name):
    fluid.unique_name.switch()
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        out = TENSOR_BUILDERS[name]()
    assert out is not None


@pytest.mark.parametrize("name", REFERENCE_CF_ALL)
def test_control_flow_call(name):
    fluid.unique_name.switch()
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        out = CF_BUILDERS[name]()
    assert out is not None
