"""EMA apply/restore + ModelAverage (reference optimizer.py:2244,2434)."""

import numpy as np

import paddle_tpu as fluid
from paddle_tpu.executor import Scope, scope_guard


def _param_value(scope, name):
    return np.asarray(scope.get(name))


def test_ema_apply_restore_bias_corrected():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[4], dtype="float32")
        y = fluid.layers.fc(
            x, size=3, bias_attr=False,
            param_attr=fluid.ParamAttr(name="w"))
        loss = fluid.layers.mean(y)
        fluid.optimizer.SGD(0.1).minimize(loss)
        ema = fluid.optimizer.ExponentialMovingAverage(decay=0.9)
        ema.update()

    exe = fluid.Executor(fluid.CPUPlace())
    scope = Scope()
    rng = np.random.RandomState(0)
    with scope_guard(scope):
        exe.run(startup)
        steps = 4
        param_hist = []
        for _ in range(steps):
            exe.run(main, feed={"x": rng.randn(8, 4).astype("float32")},
                    fetch_list=[])
            param_hist.append(_param_value(scope, "w"))
        raw = _param_value(scope, "w")
        # numpy EMA oracle with bias correction
        ema_np = np.zeros_like(param_hist[0])
        for p in param_hist:
            ema_np = 0.9 * ema_np + 0.1 * p
        ema_np = ema_np / (1.0 - 0.9 ** steps)
        with ema.apply(exe):
            applied = _param_value(scope, "w")
            np.testing.assert_allclose(applied, ema_np, rtol=1e-5)
            assert not np.allclose(applied, raw)
        restored = _param_value(scope, "w")
        np.testing.assert_allclose(restored, raw, rtol=1e-6)


def test_ema_apply_no_restore():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[4], dtype="float32")
        y = fluid.layers.fc(x, size=3, bias_attr=False,
                            param_attr=fluid.ParamAttr(name="w2"))
        loss = fluid.layers.mean(y)
        fluid.optimizer.SGD(0.1).minimize(loss)
        ema = fluid.optimizer.ExponentialMovingAverage(decay=0.5)
        ema.update()
    exe = fluid.Executor(fluid.CPUPlace())
    scope = Scope()
    rng = np.random.RandomState(1)
    with scope_guard(scope):
        exe.run(startup)
        exe.run(main, feed={"x": rng.randn(8, 4).astype("float32")},
                fetch_list=[])
        with ema.apply(exe, need_restore=False):
            applied = _param_value(scope, "w2")
        after = _param_value(scope, "w2")
        np.testing.assert_allclose(after, applied)
        ema.restore(exe)  # explicit restore still works


def test_model_average_window():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[4], dtype="float32")
        y = fluid.layers.fc(x, size=3, bias_attr=False,
                            param_attr=fluid.ParamAttr(name="wa", do_model_average=True))
        loss = fluid.layers.mean(y)
        fluid.optimizer.SGD(0.1).minimize(loss)
        # window never restarts in this short run: average over ALL steps
        avg = fluid.optimizer.ModelAverage(
            0.15, min_average_window=10000, max_average_window=20000)

    exe = fluid.Executor(fluid.CPUPlace())
    scope = Scope()
    rng = np.random.RandomState(2)
    with scope_guard(scope):
        exe.run(startup)
        hist = []
        for _ in range(6):
            exe.run(main, feed={"x": rng.randn(8, 4).astype("float32")},
                    fetch_list=[])
            hist.append(_param_value(scope, "wa"))
        raw = _param_value(scope, "wa")
        with avg.apply(exe):
            applied = _param_value(scope, "wa")
            np.testing.assert_allclose(
                applied, np.mean(hist, axis=0), rtol=1e-5)
        np.testing.assert_allclose(_param_value(scope, "wa"), raw, rtol=1e-6)


def test_model_average_window_restart():
    """With a tiny max window the accumulator restarts: the average covers
    only the steps since the last restart (old window kept via
    old_num_accumulates until the next fold)."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[2], dtype="float32")
        y = fluid.layers.fc(x, size=1, bias_attr=False,
                            param_attr=fluid.ParamAttr(name="wr", do_model_average=True))
        loss = fluid.layers.mean(y)
        fluid.optimizer.SGD(0.5).minimize(loss)
        avg = fluid.optimizer.ModelAverage(
            1.0, min_average_window=2, max_average_window=3)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = Scope()
    rng = np.random.RandomState(3)
    with scope_guard(scope):
        exe.run(startup)
        hist = []
        for _ in range(7):
            exe.run(main, feed={"x": rng.randn(4, 2).astype("float32")},
                    fetch_list=[])
            hist.append(_param_value(scope, "wr"))
        # numpy oracle of the reference accumulator
        s1 = s2 = s3 = np.zeros_like(hist[0])
        na = ona = nu = 0
        for p in hist:
            nu += 1
            na += 1
            s1 = s1 + p
            if na >= 2 and na >= min(3, nu * 1.0):
                s3 = s1 + s2
                s1 = np.zeros_like(s1)
                s2 = np.zeros_like(s2)
                ona, na = na, 0
        expect = (s1 + s2 + s3) / float(na + ona)
        with avg.apply(exe):
            np.testing.assert_allclose(
                _param_value(scope, "wr"), expect, rtol=1e-5)
