"""Resilient cluster trainer for the kill-and-resume test
(``dist_cluster_worker.py`` style, plus the full resilience runtime):
heartbeat writer + peer watchdog, per-step atomic checkpoints (rank 0),
auto-resume from the latest intact version, and fault injection from
``PADDLE_TPU_FAULT_SPEC`` — so an injected ``worker_kill`` surfaces to
the parent within a bounded time and the relaunched cluster continues
the SAME loss trajectory."""

import os
import sys

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_cpu_collectives_implementation", "gloo")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

import paddle_tpu as fluid  # noqa: E402
from paddle_tpu.incubate.fleet.base import role_maker  # noqa: E402
from paddle_tpu.incubate.fleet.collective import fleet  # noqa: E402
from paddle_tpu.resilience import checkpoint, faults, watchdog  # noqa: E402
from tests.dist_model import build_model  # noqa: E402

GLOBAL_BATCH = 16


def make_batches(n):
    rng = np.random.RandomState(42)
    for _ in range(n):
        xb = rng.randn(GLOBAL_BATCH, 8).astype("float32")
        yb = (xb.sum(axis=1, keepdims=True) * 0.3
              + rng.randn(GLOBAL_BATCH, 1) * 0.01).astype("float32")
        yield xb, yb


def main():
    n_steps = int(os.environ.get("RESIL_STEPS", "6"))
    ckpt_dir = os.environ["PADDLE_TPU_CKPT_DIR"]

    fleet.init(role_maker.PaddleCloudRoleMaker())
    rank = fleet.worker_index()
    nworkers = fleet.worker_num()

    # heartbeat + peer watchdog: if a peer dies mid-collective this
    # process would hang in gloo forever — the monitor's default on_lost
    # hard-exits with LOST_EXIT_CODE instead, within ~timeout seconds
    writer = monitor = None
    hb_dir = os.environ.get("PADDLE_TPU_HEARTBEAT_DIR")
    if hb_dir:
        writer = watchdog.HeartbeatWriter(hb_dir, rank,
                                          interval=0.2).start()
        hb_timeout = float(os.environ.get(
            "PADDLE_TPU_HEARTBEAT_TIMEOUT_S", "5"))
        monitor = watchdog.HeartbeatMonitor(
            hb_dir, [r for r in range(nworkers) if r != rank],
            timeout=hb_timeout, interval=0.2).start()

    main_prog, startup, loss, feeds = build_model(
        optimizer_factory=lambda opt: fleet.distributed_optimizer(opt))
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)

    start_step = 0
    info = checkpoint.try_load_latest_checkpoint(exe, ckpt_dir,
                                                 main_program=main_prog)
    if info is not None:
        start_step = int(info.state.get("next_step", info.step + 1))
        print("RESIL_RESUME rank=%d step=%d from=%s"
              % (rank, start_step, os.path.basename(info.path)),
              flush=True)

    cp = fluid.CompiledProgram(main_prog).with_data_parallel(
        loss_name=loss.name)
    per = GLOBAL_BATCH // nworkers
    for k, (xb, yb) in enumerate(make_batches(n_steps)):
        if k < start_step:
            continue
        faults.set_step(k)
        half = slice(rank * per, (rank + 1) * per)
        (lv,) = exe.run(cp, feed={feeds[0]: xb[half], feeds[1]: yb[half]},
                        fetch_list=[loss])
        print("RESIL_STEP rank=%d step=%d loss=%.8f"
              % (rank, k, float(np.asarray(lv).reshape(()))), flush=True)
        # atomic versioned save every step (rank 0 writes; the version
        # rename means a kill mid-save can never leave a loadable torn
        # checkpoint for the resumed cluster)
        checkpoint.save_checkpoint(exe, ckpt_dir, main_program=main_prog,
                                   step=k, state={"next_step": k + 1},
                                   retain=3)
    print("RESIL_OK rank=%d" % rank, flush=True)
    if monitor is not None:
        monitor.stop()
    if writer is not None:
        writer.stop()


if __name__ == "__main__":
    main()
