"""Multi-process cluster loss parity through the REAL user API
(reference bar: ``unittests/test_dist_base.py:414-575`` — subprocess
trainers on localhost, per-step loss parity ≤ 1e-5 vs the single-process
run).

Cluster: 2 ``jax.distributed`` processes × 4 virtual CPU devices each,
driving ``fleet.distributed_optimizer`` +
``CompiledProgram.with_data_parallel`` (NOT a hand-rolled MLP — the whole
executor/GSPMD path).  Oracle: the identical model trained single-process
on the full global batch."""

import os
import socket
import subprocess
import sys

import numpy as np

import paddle_tpu as fluid
from paddle_tpu.executor import Scope, scope_guard

from dist_model import build_model, make_batches


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _single_process_losses():
    fluid.unique_name.switch()
    main, startup, loss, feeds = build_model()
    exe = fluid.Executor(fluid.CPUPlace())
    losses = []
    with scope_guard(Scope()):
        exe.run(startup)
        for xb, yb in make_batches():
            (lv,) = exe.run(main, feed={feeds[0]: xb, feeds[1]: yb},
                            fetch_list=[loss])
            losses.append(float(np.asarray(lv).reshape(())))
    return losses


def test_cluster_loss_parity():
    port = _free_port()
    coord = "127.0.0.1:%d" % port
    worker = os.path.join(os.path.dirname(__file__),
                          "dist_cluster_worker.py")
    procs = []
    for rank in (0, 1):
        env = dict(os.environ)
        env.pop("XLA_FLAGS", None)  # worker sets its own 4-device flag
        env.update({
            "PADDLE_TRAINER_ID": str(rank),
            "PADDLE_TRAINERS_NUM": "2",
            "PADDLE_TRAINER_ENDPOINTS": "%s,127.0.0.1:%d"
                                        % (coord, port + 1),
            "PADDLE_COORDINATOR_ADDRESS": coord,
            "JAX_PLATFORMS": "cpu",
        })
        procs.append(subprocess.Popen(
            [sys.executable, worker], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True))
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=300)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outs.append(out)
    for rank, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, "rank %d failed:\n%s" % (rank, out[-4000:])
        assert "CLUSTER_OK rank=%d" % rank in out

    ref = _single_process_losses()
    for rank, out in enumerate(outs):
        line = [ln for ln in out.splitlines()
                if ln.startswith("CLUSTER_LOSSES")][0]
        got = [float(v) for v in line.split()[-1].split(",")]
        assert len(got) == len(ref)
        # reference bar: delta <= 1e-5 per step (test_dist_base.py)
        np.testing.assert_allclose(got, ref, atol=1e-5, err_msg=(
            "rank %d cluster losses diverged from single-process oracle"
            % rank))
