"""Fused dropout+add+layer_norm Pallas kernel (ops/pallas/fused_ln.py):
interpret-mode parity against the pure-XLA expression of the same math,
forward and all gradients, with and without dropout (debug hash mask —
the same escape the flash kernel tests use, since pltpu PRNG has no CPU
lowering)."""

import importlib
import os

import numpy as np
import pytest

os.environ.setdefault("PADDLE_TPU_PALLAS", "interpret")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

FL = importlib.import_module("paddle_tpu.ops.pallas.fused_ln")


@pytest.fixture(autouse=True)
def _interpret_debug_env(monkeypatch):
    """Per-test env (NOT module-level setdefault): earlier test modules
    — test_flash_attention's debug-hash test — pop the DEBUG var in
    their finally, which wiped a module-level default when the full
    suite ran and sent the dropout tests down the CPU-unsupported
    pltpu PRNG path."""
    monkeypatch.setenv("PADDLE_TPU_PALLAS", "interpret")
    monkeypatch.setenv("PADDLE_TPU_FLASH_DROPOUT_DEBUG", "iota")

N, D = 64, 256


def _inputs(dtype=jnp.float32):
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(N, D), dtype)
    res = jnp.asarray(rng.randn(N, D), dtype)
    g = jnp.asarray(rng.rand(D) + 0.5, dtype)
    b = jnp.asarray(rng.randn(D) * 0.1, dtype)
    return x, res, g, b


@pytest.mark.parametrize("rate", [0.0, 0.1])
def test_forward_matches_reference(rate):
    x, res, g, b = _inputs()
    seed = jnp.asarray([7], jnp.int32)
    out_k = FL._fused_core(x, res, g, b, rate, 1e-5, seed)
    out_r = FL._xla_reference(x, res, g, b, rate, 1e-5, seed, True)
    np.testing.assert_allclose(np.asarray(out_k), np.asarray(out_r),
                               atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("rate", [0.0, 0.1])
def test_grads_match_reference(rate):
    x, res, g, b = _inputs()
    seed = jnp.asarray([3], jnp.int32)

    def loss_k(x, res, g, b):
        return jnp.sum(
            FL._fused_core(x, res, g, b, rate, 1e-5, seed) ** 2)

    def loss_r(x, res, g, b):
        return jnp.sum(
            FL._xla_reference(x, res, g, b, rate, 1e-5, seed, True) ** 2)

    gk = jax.grad(loss_k, argnums=(0, 1, 2, 3))(x, res, g, b)
    gr = jax.grad(loss_r, argnums=(0, 1, 2, 3))(x, res, g, b)
    for a, e, nm in zip(gk, gr, ["dx", "dres", "dgamma", "dbeta"]):
        np.testing.assert_allclose(np.asarray(a), np.asarray(e),
                                   atol=5e-4, rtol=5e-4, err_msg=nm)


def test_bf16_inputs():
    """bf16 (AMP regime): f32 compute inside, bf16 in/out; saved y is
    bf16 but stats are the forward's own f32 mean/rstd, so grads stay
    within bf16-scaled tolerance."""
    x, res, g, b = _inputs(jnp.bfloat16)
    seed = jnp.asarray([5], jnp.int32)
    out_k = FL._fused_core(x, res, g, b, 0.1, 1e-5, seed)
    out_r = FL._xla_reference(x, res, g, b, 0.1, 1e-5, seed, True)
    np.testing.assert_allclose(
        np.asarray(out_k, np.float32), np.asarray(out_r, np.float32),
        atol=3e-2, rtol=3e-2)

    def loss(fn):
        return lambda *a: jnp.sum(
            fn(*a).astype(jnp.float32) ** 2)

    gk = jax.grad(loss(lambda x, r, g, b: FL._fused_core(
        x, r, g, b, 0.1, 1e-5, seed)), argnums=(0, 1, 2, 3))(x, res, g, b)
    gr = jax.grad(loss(lambda x, r, g, b: FL._xla_reference(
        x, r, g, b, 0.1, 1e-5, seed, True)),
        argnums=(0, 1, 2, 3))(x, res, g, b)
    for a, e, nm in zip(gk, gr, ["dx", "dres", "dgamma", "dbeta"]):
        np.testing.assert_allclose(
            np.asarray(a, np.float32), np.asarray(e, np.float32),
            atol=0.25, rtol=6e-2, err_msg=nm)


def test_rate_zero_equals_plain_add_ln():
    """rate=0 is exactly layer_norm(x + residual)."""
    x, res, g, b = _inputs()
    out = FL.fused_dropout_add_ln(x, res, g, b, 0.0)
    y = (x + res).astype(jnp.float32)
    mean = y.mean(axis=1, keepdims=True)
    var = ((y - mean) ** 2).mean(axis=1, keepdims=True)
    ref = ((y - mean) * jax.lax.rsqrt(var + 1e-5)) * g + b
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_bert_fused_ln_parity_with_op_chain():
    """cfg.fused_ln=True swaps the encoder glue for the fused op with
    the SAME LN parameter names/shapes: with dropout off, loss must
    match the op-chain graph exactly (same params, same feed)."""
    import paddle_tpu as fluid
    from paddle_tpu.executor import Scope, scope_guard
    from paddle_tpu.models import bert

    feeds = None
    params = None  # captured from the first (unfused) graph, then
    losses = {}    # injected into the fused one — order-independent
    for fused in (False, True):
        fluid.unique_name.switch()
        cfg = bert.BertConfig(vocab_size=128, hidden=128, layers=2,
                              heads=2, ffn=256, max_seq=32, dropout=0.0,
                              fused_ln=fused)
        main, startup, _, loss = bert.build_pretrain(
            cfg, seq_len=32, lr=1e-3, train=True)
        rng = np.random.RandomState(0)
        if feeds is None:
            feeds = bert.make_fake_batch(2, 32, cfg, rng)
        exe = fluid.Executor(fluid.CPUPlace())
        sc = Scope()
        with scope_guard(sc):
            exe.run(startup)
            if params is None:
                params = {p.name: np.asarray(sc.get(p.name))
                          for p in main.all_parameters()}
            else:
                for p in main.all_parameters():
                    sc.set(p.name, params[p.name])
            (lv,) = exe.run(main, feed=feeds, fetch_list=[loss])
        losses[fused] = float(np.asarray(lv).reshape(-1)[0])
    assert abs(losses[True] - losses[False]) < 2e-4, losses


def test_bert_fused_ln_trains_with_dropout():
    import paddle_tpu as fluid
    from paddle_tpu.executor import Scope, scope_guard
    from paddle_tpu.models import bert

    fluid.unique_name.switch()
    cfg = bert.BertConfig(vocab_size=128, hidden=128, layers=1, heads=2,
                          ffn=256, max_seq=32, dropout=0.1, fused_ln=True)
    main, startup, _, loss = bert.build_pretrain(
        cfg, seq_len=32, lr=1e-3, train=True)
    rng = np.random.RandomState(1)
    feed = bert.make_fake_batch(2, 32, cfg, rng)
    exe = fluid.Executor(fluid.CPUPlace())
    with scope_guard(Scope()):
        exe.run(startup)
        vals = []
        for _ in range(6):
            lv = exe.run(main, feed=feed, fetch_list=[loss])[0]
            vals.append(float(np.asarray(lv).reshape(-1)[0]))
    assert np.isfinite(vals).all()
    assert vals[-1] < vals[0]
    # eval clone flips the fused op to is_test (dropout off): loss is
    # deterministic across runs
    fluid.unique_name.switch()
    cfg2 = bert.BertConfig(vocab_size=128, hidden=128, layers=1, heads=2,
                           ffn=256, max_seq=32, dropout=0.1,
                           fused_ln=True)
    main2, startup2, _, loss2 = bert.build_pretrain(
        cfg2, seq_len=32, lr=1e-3, train=False)
    with scope_guard(Scope()):
        exe.run(startup2)
        a = exe.run(main2, feed=feed, fetch_list=[loss2])[0]
        b = exe.run(main2, feed=feed, fetch_list=[loss2])[0]
    np.testing.assert_allclose(np.asarray(a), np.asarray(b))


def test_bert_fused_ln_under_recompute():
    """cfg.recompute wraps each encoder layer in fluid.layers.recompute
    (backward re-runs the forward): the fused op's dropout seed comes
    from the deterministic ctx key chain, so the replay must draw the
    IDENTICAL mask — trains finite and decreasing."""
    import paddle_tpu as fluid
    from paddle_tpu.executor import Scope, scope_guard
    from paddle_tpu.models import bert

    fluid.unique_name.switch()
    cfg = bert.BertConfig(vocab_size=128, hidden=128, layers=2, heads=2,
                          ffn=256, max_seq=32, dropout=0.1,
                          fused_ln=True, recompute=True)
    main, startup, _, loss = bert.build_pretrain(
        cfg, seq_len=32, lr=1e-3, train=True)
    rng = np.random.RandomState(2)
    feed = bert.make_fake_batch(2, 32, cfg, rng)
    exe = fluid.Executor(fluid.CPUPlace())
    with scope_guard(Scope()):
        exe.run(startup)
        vals = []
        for _ in range(6):
            lv = exe.run(main, feed=feed, fetch_list=[loss])[0]
            vals.append(float(np.asarray(lv).reshape(-1)[0]))
    assert np.isfinite(vals).all()
    assert vals[-1] < vals[0]


def test_fused_ln_model_inference_export_roundtrip(tmp_path):
    """A model using layers.fused_dropout_add_ln survives
    save_inference_model → AnalysisPredictor (the analysis passes must
    pass the op through; the exported eval graph runs it with
    is_test → dropout off, deterministically)."""
    import paddle_tpu as fluid
    from paddle_tpu.executor import Scope, scope_guard

    fluid.unique_name.switch()
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[2, 256], dtype="float32")
        h = fluid.layers.fc(x, size=256, num_flatten_dims=2, act="relu")
        out = fluid.layers.fused_dropout_add_ln(h, x, dropout_prob=0.1)
        logits = fluid.layers.fc(out, size=4, num_flatten_dims=2)
    exe = fluid.Executor(fluid.CPUPlace())
    path = str(tmp_path / "m")
    with scope_guard(Scope()):
        exe.run(startup)
        fluid.io.save_inference_model(path, ["x"], [logits], exe,
                                      main_program=main)
    pred = fluid.inference.create_paddle_predictor(
        fluid.inference.AnalysisConfig(model_dir=path))
    feed = {"x": np.random.RandomState(0)
            .randn(3, 2, 256).astype("float32")}
    o1 = np.asarray(pred.run(feed)[0])
    o2 = np.asarray(pred.run(feed)[0])
    assert o1.shape == (3, 2, 4)
    np.testing.assert_allclose(o1, o2)  # dropout off in the export
    assert np.isfinite(o1).all()
