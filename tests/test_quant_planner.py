"""Quant as a priced planner axis (ISSUE 15): candidate enumeration,
per-bucket pricing, the winning plan's ``_quant_buckets`` stamp through
``apply_plan``, the fusion rewrite it engages, the kill-switch
bit-exactness contract, the bucket-cap precedence bugfix, and the
``quantizable-bucket-not-quantized`` advisory."""

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import autotune
from paddle_tpu.parallel.planner import (ClusterSpec, apply_plan,
                                         auto_transpile,
                                         enumerate_candidates,
                                         quant_bucket_mark)
from paddle_tpu.quant.blockwise import quant_block
from paddle_tpu.quant.collective import quant_min_bytes
from paddle_tpu.static_analysis import verify_program
from paddle_tpu.static_analysis import fusion
from paddle_tpu.static_analysis.fusion import (FusionConfig,
                                               allreduce_bucket_mb)
from paddle_tpu.transpiler.collective import GradAllReduce

import dist_model


def _fresh_mlp():
    fluid.unique_name.switch()
    return dist_model.build_model()


def _wide_mlp():
    """Gradient-heavy builder (one ~1MB fc) so a starved interconnect
    prices the int8 exchange as the outright winner."""
    fluid.unique_name.switch()
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[64], dtype="float32")
        y = fluid.layers.data(name="y", shape=[1], dtype="float32")
        h = fluid.layers.fc(input=x, size=4096, act="relu")
        p = fluid.layers.fc(input=h, size=1)
        loss = fluid.layers.reduce_mean(fluid.layers.square(p - y))
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    return main, startup, loss


def _dp_mlp(rank=0, nranks=2):
    fluid.unique_name.switch()
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[16], dtype="float32")
        label = fluid.layers.data(name="label", shape=[1], dtype="int64")
        h = fluid.layers.fc(input=x, size=32, act="relu")
        pred = fluid.layers.fc(input=h, size=4, act="softmax")
        loss = fluid.layers.reduce_mean(
            fluid.layers.cross_entropy(input=pred, label=label))
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    GradAllReduce().transpile(program=main, startup_program=startup,
                              rank=rank, nranks=nranks)
    main._num_trainers = nranks
    return main, startup, loss


def _non_quant_key(cand):
    """plan_key with the quant dimension dropped."""
    return cand.plan_key()[:-1]


class TestCandidateEnumeration:
    def test_quant_doubles_the_trainable_dp_family(self):
        main, startup, loss, _ = _fresh_mlp()
        cands = enumerate_candidates(main, ClusterSpec(4))
        quant = [c for c in cands if c.quant]
        assert quant, "no quant candidates for a trainable program"
        assert all(c.kind == "dp" for c in quant)
        # every quant candidate shadows a dense twin of the same knobs
        dense_keys = {_non_quant_key(c) for c in cands if not c.quant}
        for c in quant:
            assert _non_quant_key(c) in dense_keys

    def test_kill_switch_removes_the_axis(self, monkeypatch):
        main, startup, loss, _ = _fresh_mlp()
        with_axis = enumerate_candidates(main, ClusterSpec(4))
        monkeypatch.setenv("PADDLE_TPU_QUANT", "0")
        fluid.unique_name.switch()
        main2, _, _, _ = dist_model.build_model()
        without = enumerate_candidates(main2, ClusterSpec(4))
        assert not any(c.quant for c in without)
        # exactly the pre-quant candidate list: the dense keys match
        assert [c.plan_key() for c in without] == \
            [c.plan_key() for c in with_axis if not c.quant]

    def test_inference_program_has_no_quant_candidates(self):
        fluid.unique_name.switch()
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.layers.data(name="x", shape=[16], dtype="float32")
            h = fluid.layers.fc(input=x, size=32, act="relu")
            fluid.layers.fc(input=h, size=4, act="softmax")
        cands = enumerate_candidates(main, ClusterSpec(4))
        assert not any(getattr(c, "quant", False) for c in cands)


class TestPricing:
    def _priced_pair(self, res):
        """(quant, dense) PricedCandidate pairs sharing all other
        knobs, keyed for deterministic comparison."""
        dense = {_non_quant_key(pc.candidate): pc
                 for pc in res.candidates if not pc.candidate.quant}
        pairs = []
        for pc in res.candidates:
            if pc.candidate.quant:
                twin = dense.get(_non_quant_key(pc.candidate))
                if twin is not None:
                    pairs.append((pc, twin))
        return pairs

    def test_quant_wins_on_starved_ici(self):
        main, startup, loss, _ = _fresh_mlp()
        res = auto_transpile(
            main, ClusterSpec(chips=2, ici_gbps=0.0001, launch_us=0.1),
            startup_program=startup, targets=[loss.name])
        pairs = self._priced_pair(res)
        assert pairs
        # bandwidth-bound: int8 wire cut beats the extra phase/launches
        assert all(q.price.step_ms < d.price.step_ms for q, d in pairs)

    def test_dense_not_worse_on_rich_ici(self):
        """Tiny gradients on a fat interconnect: the quant launch tax
        dominates, the dense twin prices at or below the quant one —
        the axis must never be a free lunch in the table."""
        main, startup, loss, _ = _fresh_mlp()
        res = auto_transpile(main, ClusterSpec(chips=2),
                             startup_program=startup,
                             targets=[loss.name])
        pairs = self._priced_pair(res)
        assert pairs
        assert all(d.price.step_ms <= q.price.step_ms for q, d in pairs)
        assert not res.plan.candidate.quant


class TestWinnerApplyAndStamp:
    SPEC = dict(chips=2, ici_gbps=0.01, launch_us=1)

    def _win(self):
        main, startup, loss = _wide_mlp()
        res = auto_transpile(main, ClusterSpec(**self.SPEC),
                             startup_program=startup,
                             targets=[loss.name], batch_size=256)
        return main, startup, loss, res

    def test_quant_dp_wins_outright(self):
        _, _, _, res = self._win()
        assert res.plan.candidate.quant
        assert res.plan.candidate.kind == "dp"
        assert "+int8" in res.plan.candidate.describe()
        assert res.deadlock_free

    def test_apply_stamps_quant_buckets_mark(self):
        main, startup, loss, res = self._win()
        cand = apply_plan(main, res, startup_program=startup)
        assert cand.quant
        mark = main._quant_buckets
        assert mark == quant_bucket_mark(res.cluster, cand.degree)
        assert mark["block"] == quant_block()
        assert mark["min_bytes"] >= 1
        # the mark IS the engagement: quant_min_bytes reads it with no
        # env set, and the fusion rewrite emits the quant op
        assert quant_min_bytes(main) == mark["min_bytes"]
        fused, _ = fusion.resolve_fused_program(main,
                                                targets=[loss.name])
        types = [op.type for blk in fused.blocks for op in blk.ops]
        assert "c_allreduce_quant" in types

    def test_clone_preserves_the_mark(self):
        main, startup, loss, res = self._win()
        apply_plan(main, res, startup_program=startup)
        clone = main.clone()
        assert getattr(clone, "_quant_buckets", None) \
            == main._quant_buckets

    def test_runtime_config_emits_quant_env(self):
        _, _, _, res = self._win()
        _, env = res.runtime_config()
        mark = quant_bucket_mark(res.cluster, res.plan.candidate.degree)
        assert env["PADDLE_TPU_QUANT_MIN_BYTES"] \
            == str(mark["min_bytes"])
        assert env["PADDLE_TPU_QUANT_BLOCK"] == str(mark["block"])

    def test_format_table_has_quant_column(self):
        _, _, _, res = self._win()
        table = res.format_table()
        header = table.splitlines()[1]
        assert "quant" in header
        assert "int8" in table
        chosen = [ln for ln in table.splitlines() if "+int8" in ln]
        assert chosen


class TestKillSwitchBitExact:
    def test_disabled_resolve_is_op_for_op_dense(self, monkeypatch):
        """PADDLE_TPU_QUANT=0 with the threshold still exported: the
        resolved program is op-for-op the no-quant-env baseline — the
        acceptance criterion's bit-exact escape hatch."""
        main, _, loss = _dp_mlp()
        baseline, _ = fusion.resolve_fused_program(main,
                                                   targets=[loss.name])
        monkeypatch.setenv("PADDLE_TPU_QUANT_MIN_BYTES", "1")
        monkeypatch.setenv("PADDLE_TPU_QUANT", "0")
        killed, _ = fusion.resolve_fused_program(main,
                                                 targets=[loss.name])

        def flat(p):
            return [(op.type, dict(op.inputs), dict(op.outputs))
                    for blk in p.blocks for op in blk.ops]

        assert flat(killed) == flat(baseline)


class TestFusionQuantRewrite:
    def test_single_member_bucket_engages(self, monkeypatch):
        """A lone large gradient is below the dense fuser's interest
        (nothing to coalesce) but still a quant win — the rewrite must
        take single-member buckets when quant is on."""
        fluid.unique_name.switch()
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.layers.data(name="x", shape=[64],
                                  dtype="float32")
            y = fluid.layers.data(name="y", shape=[1],
                                  dtype="float32")
            p = fluid.layers.fc(input=x, size=1, bias_attr=False)
            loss = fluid.layers.reduce_mean(fluid.layers.square(p - y))
            fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
        GradAllReduce().transpile(program=main,
                                  startup_program=startup,
                                  rank=0, nranks=2)
        main._num_trainers = 2
        dense, _ = fusion.resolve_fused_program(main,
                                                targets=[loss.name])
        dtypes = [op.type for blk in dense.blocks for op in blk.ops]
        assert "c_allreduce_sum" in dtypes  # single grad: left alone
        monkeypatch.setenv("PADDLE_TPU_QUANT_MIN_BYTES", "1")
        fused, _ = fusion.resolve_fused_program(main,
                                                targets=[loss.name])
        qops = [op for blk in fused.blocks for op in blk.ops
                if op.type == "c_allreduce_quant"]
        assert len(qops) == 1
        assert qops[0].attrs["quant_block"] == quant_block()


class TestBucketCapPrecedence:
    def test_mark_beats_env_beats_default(self, monkeypatch):
        monkeypatch.delenv("PADDLE_TPU_ALLREDUCE_BUCKET_MB",
                           raising=False)
        assert allreduce_bucket_mb(None) == 32.0
        monkeypatch.setenv("PADDLE_TPU_ALLREDUCE_BUCKET_MB", "8")
        assert allreduce_bucket_mb(None) == 8.0
        main, _, _ = _dp_mlp()
        assert allreduce_bucket_mb(main) == 8.0
        main._allreduce_bucket_mb = 2
        assert allreduce_bucket_mb(main) == 2.0
        monkeypatch.setenv("PADDLE_TPU_ALLREDUCE_BUCKET_MB",
                           "not-a-number")
        assert allreduce_bucket_mb(None) == 32.0

    def test_signature_sees_the_program_mark(self):
        """The bugfix: ``FusionConfig.signature()`` used to hash the
        env-only bucket cap, so stamping ``_allreduce_bucket_mb`` after
        a resolve served the STALE fused clone from cache.  The
        signature now threads the program through."""
        main, _, loss = _dp_mlp()
        cfg = FusionConfig()
        base_sig = cfg.signature(main)
        fused1, _ = fusion.resolve_fused_program(main,
                                                 targets=[loss.name])
        n1 = sum(op.type == "c_fused_allreduce_sum"
                 for blk in fused1.blocks for op in blk.ops)
        assert n1 == 1  # all four grads (~2.7KB) in one 32MB bucket
        # 2KB cap splits the 2KB w1 grad from the rest
        main._allreduce_bucket_mb = 0.002
        assert cfg.signature(main) != base_sig
        fused2, _ = fusion.resolve_fused_program(main,
                                                 targets=[loss.name])
        # a bucket surfaces as the fused op, a bare allreduce, or a
        # start/wait pair once the overlap scheduler (PR 16) hoists it
        n2 = sum(op.type in ("c_fused_allreduce_sum",
                             "c_allreduce_sum", "c_allreduce_start")
                 for blk in fused2.blocks for op in blk.ops)
        assert n2 >= 2, "stale cached clone served after re-mark"


class TestAdvisory:
    # a starved link drops the break-even below this MLP's ~2.7KB of
    # gradients (the default ~2MB threshold would mute the advisory)
    SPEC = {"chips": 2, "ici_gbps": 0.001}

    def _lint(self, monkeypatch, tmp_path, **env):
        monkeypatch.setenv("PADDLE_TPU_AUTOTUNE_CACHE",
                           str(tmp_path / "at.json"))
        for k, v in env.items():
            monkeypatch.setenv(k, v)
        autotune.reset()
        main, _, loss = _dp_mlp()
        main._cluster_spec = dict(self.SPEC)
        diags = verify_program(main, targets=[loss.name])
        autotune.reset()
        return [d for d in diags
                if d.check == "quantizable-bucket-not-quantized"]

    def test_fires_with_uncalibrated_reason(self, monkeypatch,
                                            tmp_path):
        hits = self._lint(monkeypatch, tmp_path)
        assert hits
        from paddle_tpu.static_analysis import Severity
        assert all(d.severity == Severity.INFO for d in hits)
        msg = hits[0].message
        assert "no _quant_buckets plan mark" in msg
        assert "uncalibrated" in msg
        assert "auto_transpile" in hits[0].hint

    def test_fires_with_kill_switch_reason(self, monkeypatch,
                                           tmp_path):
        hits = self._lint(monkeypatch, tmp_path, PADDLE_TPU_QUANT="0")
        assert hits
        assert "disabled by PADDLE_TPU_QUANT=0" in hits[0].message

    def test_silent_when_engaged(self, monkeypatch, tmp_path):
        hits = self._lint(monkeypatch, tmp_path,
                          PADDLE_TPU_QUANT_MIN_BYTES="1")
        assert hits == []

    def test_silent_below_break_even(self, monkeypatch, tmp_path):
        monkeypatch.setenv("PADDLE_TPU_AUTOTUNE_CACHE",
                           str(tmp_path / "at.json"))
        autotune.reset()
        main, _, loss = _dp_mlp()
        # the default spec's break-even (~2MB) dwarfs 2.7KB of grads
        diags = verify_program(main, targets=[loss.name])
        autotune.reset()
        assert [d for d in diags
                if d.check == "quantizable-bucket-not-quantized"] == []
