"""Flag system: FLAGS_check_nan_inf → jax_debug_nans (reference:
FLAGS_check_nan_inf / nan-inf printers, SURVEY §5 race/NaN aids) and
BuildStrategy inert-knob warnings."""

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.executor import Scope, scope_guard


def test_check_nan_inf_flag_catches_nan():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[2], dtype="float32")
        y = fluid.layers.log(x)  # log(-1) = nan
        loss = fluid.layers.mean(y)
    exe = fluid.Executor(fluid.CPUPlace())
    fluid.set_flags({"FLAGS_check_nan_inf": True})
    try:
        with scope_guard(Scope()):
            with pytest.raises(Exception, match="[Nn]a[Nn]"):
                exe.run(main, feed={"x": np.array([[-1.0, 2.0]], "float32")},
                        fetch_list=[loss])
    finally:
        fluid.set_flags({"FLAGS_check_nan_inf": False})
    # and with the flag off the same program runs (nan propagates silently)
    with scope_guard(Scope()):
        out = exe.run(main, feed={"x": np.array([[-1.0, 2.0]], "float32")},
                      fetch_list=[loss])[0]
    assert np.isnan(out).any()


def test_unknown_flag_rejected():
    with pytest.raises(KeyError):
        fluid.set_flags({"FLAGS_not_a_flag": 1})
    assert fluid.get_flags("FLAGS_benchmark") is not None


def test_build_strategy_inert_knob_warns():
    bs = fluid.BuildStrategy()
    bs.reduce_strategy = fluid.BuildStrategy.ReduceStrategy.Reduce
    prog = fluid.Program()
    with pytest.warns(UserWarning, match="reduce_strategy"):
        fluid.CompiledProgram(prog).with_data_parallel(
            loss_name="x", build_strategy=bs)
    bs2 = fluid.BuildStrategy()
    bs2.gradient_scale_strategy = (
        fluid.BuildStrategy.GradientScaleStrategy.Customized)
    with pytest.warns(UserWarning, match="Customized"):
        fluid.CompiledProgram(prog).with_data_parallel(
            loss_name="x", build_strategy=bs2)
