"""slim pruning / distillation / NAS (reference contrib/slim/{prune,
distillation,searcher,nas})."""

import numpy as np

import paddle_tpu as fluid
from paddle_tpu.executor import Scope, scope_guard
from paddle_tpu.contrib.slim import prune, distillation, nas


def test_structure_pruner_and_magnitude():
    p = np.array([[1.0, -5.0, 0.1], [2.0, 6.0, 0.2]], "float32")
    sp = prune.StructurePruner({"*": 1})
    idx = sp.cal_pruned_idx("w", p, 1 / 3)
    np.testing.assert_array_equal(idx, [2])  # col 2 has smallest l1
    pruned = sp.prune_tensor(p, idx, axis=1)
    assert pruned.shape == (2, 2)
    lazy = sp.prune_tensor(p, idx, axis=1, lazy=True)
    assert lazy.shape == p.shape and (lazy[:, 2] == 0).all()

    mp = prune.MagnitudePruner(0.5)
    out = mp.prune(np.array([1.0, -0.1, 3.0, 0.2], "float32"))
    assert (out == np.array([1.0, 0.0, 3.0, 0.0], "float32")).all()


def test_sensitivity_analysis_and_lazy_prune_in_scope():
    rng = np.random.RandomState(0)
    fluid.unique_name.switch()
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[8], dtype="float32")
        y = fluid.layers.data("y", shape=[1], dtype="float32")
        h = fluid.layers.fc(x, size=16, act="relu",
                            param_attr=fluid.ParamAttr(name="sens.w1"))
        p = fluid.layers.fc(h, size=1,
                            param_attr=fluid.ParamAttr(name="sens.w2"))
        loss = fluid.layers.mean(fluid.layers.square_error_cost(p, y))
    exe = fluid.Executor(fluid.CPUPlace())
    scope = Scope()
    with scope_guard(scope):
        exe.run(startup)
        feed = {"x": rng.randn(16, 8).astype("float32"),
                "y": rng.randn(16, 1).astype("float32")}
        rep = prune.sensitivity_analysis(
            exe, main, feed, loss, scope, ["sens.w1"], ratios=(0.5,))
    assert 0.0 in rep["sens.w1"] and 0.5 in rep["sens.w1"]
    # restoring happened: scope weight unchanged after analysis
    assert np.asarray(scope.get("sens.w1")).shape == (8, 16)


def test_distillation_losses_train_student_towards_teacher():
    rng = np.random.RandomState(1)
    fluid.unique_name.switch()
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[4], dtype="float32")
        teacher = fluid.layers.fc(
            x, size=3, param_attr=fluid.ParamAttr(
                name="t.w", initializer=fluid.initializer.Constant(0.7)),
            bias_attr=False)
        teacher.stop_gradient = True
        student = fluid.layers.fc(
            x, size=3, param_attr=fluid.ParamAttr(name="s.w"),
            bias_attr=False)
        l2 = distillation.l2_loss(teacher, student)
        soft = distillation.SoftLabelDistiller().distiller_loss(
            student, teacher)
        loss = fluid.layers.elementwise_add(l2, soft)
        fluid.optimizer.Adam(5e-2).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    with scope_guard(Scope()):
        exe.run(startup)
        xv = rng.randn(16, 4).astype("float32")
        losses = [float(np.asarray(exe.run(main, feed={"x": xv},
                                           fetch_list=[loss])[0]).reshape(()))
                  for _ in range(80)]
        sw = np.asarray(fluid.executor.global_scope().get("s.w"))
    # the soft-label CE term floors at the teacher's entropy, so assert
    # improvement + convergence of the student weights to the teacher's
    assert losses[-1] < 0.5 * losses[0]
    np.testing.assert_allclose(sw, 0.7, atol=0.15)


def test_fsp_distiller_builds():
    fluid.unique_name.switch()
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[3, 8, 8], dtype="float32")
        t1 = fluid.layers.conv2d(x, num_filters=4, filter_size=3, padding=1)
        t2 = fluid.layers.conv2d(t1, num_filters=6, filter_size=3, padding=1)
        s1 = fluid.layers.conv2d(x, num_filters=4, filter_size=3, padding=1)
        s2 = fluid.layers.conv2d(s1, num_filters=6, filter_size=3, padding=1)
        loss = distillation.FSPDistiller().distiller_loss(
            [(s1, s2)], [(t1, t2)])
    exe = fluid.Executor(fluid.CPUPlace())
    with scope_guard(Scope()):
        exe.run(startup)
        out = exe.run(main, feed={
            "x": np.random.RandomState(2).randn(2, 3, 8, 8).astype(
                "float32")}, fetch_list=[loss])[0]
    assert np.isfinite(out).all()


def test_sa_nas_finds_optimum_on_toy_space():
    class Toy(nas.SearchSpace):
        def init_tokens(self):
            return [0, 0, 0]

        def range_table(self):
            return [5, 5, 5]

        def create_net(self, tokens):
            return tokens

    # reward maximized at tokens == [4, 4, 4]
    best, reward = nas.light_nas_search(
        Toy(), lambda t: sum(t), search_steps=200)
    assert reward >= 10, (best, reward)


def test_sa_controller_respects_constraint():
    ctl = nas.SAController()
    ctl.reset([4, 4], [0, 0], constrain_func=lambda t: sum(t) <= 3)
    for _ in range(20):
        t = ctl.next_tokens()
        assert sum(t) <= 3
        ctl.update(t, float(sum(t)))


def test_weighted_average_and_evaluators():
    from paddle_tpu.average import WeightedAverage
    from paddle_tpu import evaluator as ev

    wa = WeightedAverage()
    wa.add(2.0, 1)
    wa.add(4.0, 3)
    np.testing.assert_allclose(wa.eval(), 3.5)

    fluid.unique_name.switch()
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        inf = fluid.layers.data("inf", shape=[5], dtype="int64")
        lab = fluid.layers.data("lab", shape=[5], dtype="int64")
        sl = fluid.layers.data("sl", shape=[], dtype="int64")
        chunk_ev = ev.ChunkEvaluator(inf, lab, "IOB", 2, seq_length=sl)
    exe = fluid.Executor(fluid.CPUPlace())
    with scope_guard(Scope()):
        exe.run(startup)
        feed = {"inf": np.array([[0, 1, 2, 3, 0]], "int64"),
                "lab": np.array([[0, 1, 2, 2, 0]], "int64"),
                "sl": np.array([5], "int64")}
        exe.run(main, feed=feed, fetch_list=[])
        exe.run(main, feed=feed, fetch_list=[])
        p, r, f1 = chunk_ev.eval(exe)
        np.testing.assert_allclose(p[0], 2 / 3, rtol=1e-6)
        np.testing.assert_allclose(r[0], 0.5, rtol=1e-6)
