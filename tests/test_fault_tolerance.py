"""Cluster-level fault tolerance: kill one worker of a 2-process
jax.distributed cluster mid-run, assert the parent surfaces
``WorkerLostError`` within a bounded time (and the surviving worker's
own watchdog gets it out of the hung collective), then relaunch and
auto-resume from the latest intact checkpoint — the stitched loss
trajectory must match an uninterrupted single-process oracle.

Two jax.distributed cluster boots, but on the localhost gloo harness the
whole scenario runs in ~10s; the single-process equivalents live in
test_resilience.py."""

import os
import socket
import subprocess
import sys
import tempfile
import time

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.executor import Scope, scope_guard
from paddle_tpu.resilience import faults, watchdog

from dist_model import build_model

STEPS = 6
KILL_STEP = 3
GLOBAL_BATCH = 16


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _make_batches(n):
    rng = np.random.RandomState(42)
    for _ in range(n):
        xb = rng.randn(GLOBAL_BATCH, 8).astype("float32")
        yb = (xb.sum(axis=1, keepdims=True) * 0.3
              + rng.randn(GLOBAL_BATCH, 1) * 0.01).astype("float32")
        yield xb, yb


def _launch_cluster(ckpt_dir, hb_dir, state_file, spec):
    port = _free_port()
    coord = "127.0.0.1:%d" % port
    worker = os.path.join(os.path.dirname(__file__),
                          "dist_resilient_worker.py")
    procs, logs = [], []
    for rank in (0, 1):
        env = dict(os.environ)
        env.pop("XLA_FLAGS", None)
        env.pop("PADDLE_TPU_NAN_GUARD", None)
        env.update({
            "PADDLE_TRAINER_ID": str(rank),
            "PADDLE_TRAINERS_NUM": "2",
            "PADDLE_TRAINER_ENDPOINTS": "%s,127.0.0.1:%d"
                                        % (coord, port + 1),
            "PADDLE_COORDINATOR_ADDRESS": coord,
            "JAX_PLATFORMS": "cpu",
            "RESIL_STEPS": str(STEPS),
            "PADDLE_TPU_CKPT_DIR": ckpt_dir,
            "PADDLE_TPU_HEARTBEAT_DIR": hb_dir,
            "PADDLE_TPU_HEARTBEAT_TIMEOUT_S": "5",
            "PADDLE_TPU_FAULT_SPEC": spec,
            "PADDLE_TPU_FAULT_STATE_FILE": state_file,
        })
        log = tempfile.NamedTemporaryFile("w+", suffix="-rank%d.log" % rank,
                                          delete=False)
        procs.append(subprocess.Popen(
            [sys.executable, worker], env=env, stdout=log,
            stderr=subprocess.STDOUT))
        logs.append(log)
    return procs, logs


def _read_logs(logs):
    outs = []
    for log in logs:
        log.flush()
        with open(log.name) as f:
            outs.append(f.read())
    return outs


def _step_losses(out, rank):
    got = {}
    for line in out.splitlines():
        if line.startswith("RESIL_STEP rank=%d" % rank):
            parts = dict(p.split("=") for p in line.split()[1:])
            got[int(parts["step"])] = float(parts["loss"])
    return got


def _single_process_losses():
    faults.set_fault_spec("")
    fluid.unique_name.switch()
    main, startup, loss, feeds = build_model()
    exe = fluid.Executor(fluid.CPUPlace())
    losses = []
    with scope_guard(Scope()):
        exe.run(startup)
        for xb, yb in _make_batches(STEPS):
            (lv,) = exe.run(main, feed={feeds[0]: xb, feeds[1]: yb},
                            fetch_list=[loss])
            losses.append(float(np.asarray(lv).reshape(())))
    return losses


def test_cluster_kill_and_resume(tmp_path):
    ckpt_dir = str(tmp_path / "ckpt")
    state_file = str(tmp_path / "fault_state.json")
    spec = "worker_kill@step=%d,rank=1" % KILL_STEP

    # ---- incarnation 1: rank 1 is killed at step 3 ----
    procs, logs = _launch_cluster(ckpt_dir, str(tmp_path / "hb1"),
                                  state_file, spec)
    t0 = time.time()
    try:
        with pytest.raises(watchdog.WorkerLostError) as ei:
            # kill_on_failure=False: let rank 0's own heartbeat watchdog
            # prove it escapes the hung collective by itself
            watchdog.wait_cluster(procs, timeout=240, poll=0.2,
                                  kill_on_failure=False)
        detect_s = time.time() - t0
        assert 1 in ei.value.ranks
        assert faults.KILL_EXIT_CODE in ei.value.returncodes
        # bounded detection: well under the 240s ceiling
        assert detect_s < 120, detect_s

        # rank 0 is stuck in the step-3 collective with a dead peer; its
        # heartbeat monitor must hard-exit it within ~timeout+slack
        deadline = time.time() + 60
        while procs[0].poll() is None and time.time() < deadline:
            time.sleep(0.2)
        assert procs[0].poll() == watchdog.LOST_EXIT_CODE, \
            "rank 0 did not self-terminate (rc=%s)" % procs[0].poll()
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()
    outs1 = _read_logs(logs)
    losses1 = _step_losses(outs1[0], rank=0)
    assert sorted(losses1) == list(range(KILL_STEP)), outs1[0][-2000:]

    # ---- incarnation 2: same spec + shared fault state (the kill is
    # spent), fresh heartbeat dir; both ranks auto-resume from the
    # latest intact checkpoint ----
    procs, logs = _launch_cluster(ckpt_dir, str(tmp_path / "hb2"),
                                  state_file, spec)
    try:
        codes = watchdog.wait_cluster(procs, timeout=240, poll=0.2)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()
    assert codes == [0, 0]
    outs2 = _read_logs(logs)
    for rank, out in enumerate(outs2):
        assert "RESIL_OK rank=%d" % rank in out, out[-2000:]
        assert ("RESIL_RESUME rank=%d step=%d" % (rank, KILL_STEP)) \
            in out, out[-2000:]
    losses2 = _step_losses(outs2[0], rank=0)
    assert sorted(losses2) == list(range(KILL_STEP, STEPS))

    # ---- stitched trajectory == uninterrupted oracle ----
    stitched = [losses1[k] for k in range(KILL_STEP)] \
        + [losses2[k] for k in range(KILL_STEP, STEPS)]
    ref = _single_process_losses()
    np.testing.assert_allclose(stitched, ref, atol=1e-5, err_msg=(
        "resumed cluster diverged from the uninterrupted trajectory"))
