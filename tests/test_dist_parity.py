"""Distributed training parity tests (reference:
``unittests/test_dist_base.py`` — per-step losses of the distributed run
must match the single-process run within a small delta; and
``test_parallel_executor_*`` — ParallelExecutor vs plain Executor loss
equivalence).

TPU translation (SURVEY.md §4): the "fake cluster" is the 8-device
virtual CPU mesh (conftest.py sets xla_force_host_platform_device_count);
DP runs through CompiledProgram.with_data_parallel → pjit/GSPMD.  A
subprocess variant reproduces the reference's real-subprocess pattern.
"""

import os
import subprocess
import sys

import numpy as np

import paddle_tpu as fluid
from paddle_tpu.executor import Scope, scope_guard

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _build_model(lr=0.1):
    # fresh name scope: initializer RNG keys on var names, so both builds
    # must produce identical names (reference tests use unique_name.guard)
    fluid.unique_name.switch()
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 7
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[16], dtype="float32")
        y = fluid.layers.data("y", shape=[1], dtype="int64")
        h = fluid.layers.fc(x, size=32, act="relu")
        logits = fluid.layers.fc(h, size=4)
        loss = fluid.layers.mean(
            fluid.layers.softmax_with_cross_entropy(logits, y))
        fluid.optimizer.SGD(learning_rate=lr).minimize(loss)
    return main, startup, loss


def _batches(n_steps, bs=32):
    rng = np.random.RandomState(0)
    W = rng.randn(16, 4)
    out = []
    for _ in range(n_steps):
        xv = rng.randn(bs, 16).astype("float32")
        yv = np.argmax(xv @ W, axis=1)[:, None].astype("int64")
        out.append((xv, yv))
    return out


def run_training(data_parallel, n_steps=8):
    main, startup, loss = _build_model()
    exe = fluid.Executor(fluid.CPUPlace())
    losses = []
    with scope_guard(Scope()):
        exe.run(startup)
        prog = main
        if data_parallel:
            prog = fluid.CompiledProgram(main).with_data_parallel(
                loss_name=loss.name)
        for xv, yv in _batches(n_steps):
            (l,) = exe.run(prog, feed={"x": xv, "y": yv},
                           fetch_list=[loss])
            losses.append(float(np.asarray(l).reshape(())))
    return losses


class TestDataParallelParity:
    def test_dp_matches_single(self):
        """8-way DP must reproduce single-device per-step losses (dist
        delta <= 1e-5 bar of test_dist_base; fp tolerance slightly wider
        because the all-reduce changes summation order)."""
        single = run_training(data_parallel=False)
        dp = run_training(data_parallel=True)
        assert len(single) == len(dp) == 8
        np.testing.assert_allclose(dp, single, rtol=2e-4, atol=2e-4)
        # training progressed
        assert single[-1] < single[0]

    def test_non_divisible_batch_raises(self):
        main, startup, loss = _build_model()
        exe = fluid.Executor(fluid.CPUPlace())
        with scope_guard(Scope()):
            exe.run(startup)
            prog = fluid.CompiledProgram(main).with_data_parallel(
                loss_name=loss.name)
            xv = np.ones((3, 16), "float32")  # 3 does not divide 8
            yv = np.zeros((3, 1), "int64")
            try:
                exe.run(prog, feed={"x": xv, "y": yv}, fetch_list=[loss])
            except Exception:
                return
            raise AssertionError("expected sharding error")


_SUBPROC_SCRIPT = r"""
import os
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=8")
import jax
jax.config.update("jax_platforms", "cpu")
import sys
sys.path.insert(0, %(repo)r)
sys.path.insert(0, %(tests)r)
from test_dist_parity import run_training

losses = run_training(data_parallel=%(dp)s)
print("LOSSES:" + ",".join("%%.8f" %% l for l in losses))
"""


class TestSubprocessCluster:
    def test_subprocess_dp_vs_local(self, tmp_path):
        """Reference test_dist_base pattern: launch real subprocesses on
        localhost, compare their printed per-step losses."""
        results = {}
        for dp in (False, True):
            script = tmp_path / ("run_%s.py" % dp)
            script.write_text(_SUBPROC_SCRIPT % {
                "repo": REPO, "tests": os.path.join(REPO, "tests"),
                "dp": dp})
            env = dict(os.environ)
            env.pop("XLA_FLAGS", None)
            r = subprocess.run(
                [sys.executable, str(script)], capture_output=True,
                text=True, timeout=300, env=env, cwd=str(tmp_path))
            assert r.returncode == 0, r.stderr[-2000:]
            line = [l for l in r.stdout.splitlines()
                    if l.startswith("LOSSES:")][0]
            results[dp] = [float(v) for v in line[7:].split(",")]
        np.testing.assert_allclose(results[True], results[False],
                                   rtol=2e-4, atol=2e-4)
