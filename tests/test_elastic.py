"""Elastic training (ISSUE 12): membership agreement, the optimizer
split + deterministic gradient reduction, the file-rendezvous exchange
as failure detector, the checkpoint topology gate, and reshard
round-trips held to a bit-exact gather-then-scatter standard.

The full kill-one-worker drill lives in ``tools/chaos --elastic``
(subprocess cluster); these tests exercise the pieces hermetically.
"""

import os

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.executor import Scope, scope_guard
from paddle_tpu.models import ctr
from paddle_tpu.resilience import checkpoint, elastic, reshard
from paddle_tpu.resilience.checkpoint import TopologyMismatchError
from paddle_tpu.resilience.watchdog import (HeartbeatMonitor,
                                            HeartbeatWriter,
                                            WorkerLostError)

IN_DIM = 4


def _build_dp_model(seed=7):
    # explicit per-param initializer seeds: two builds in ONE process
    # must produce identical params (the trajectory test rebuilds)
    fluid.unique_name.switch()
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = seed
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[IN_DIM], dtype="float32")
        y = fluid.layers.data("y", shape=[1], dtype="float32")
        h = fluid.layers.fc(
            x, size=8, act="relu",
            param_attr=fluid.ParamAttr(
                initializer=fluid.initializer.XavierInitializer(
                    seed=seed)),
            bias_attr=fluid.ParamAttr(
                initializer=fluid.initializer.ConstantInitializer(0.0)))
        p = fluid.layers.fc(
            h, size=1,
            param_attr=fluid.ParamAttr(
                initializer=fluid.initializer.XavierInitializer(
                    seed=seed + 1)),
            bias_attr=fluid.ParamAttr(
                initializer=fluid.initializer.ConstantInitializer(0.0)))
        loss = fluid.layers.reduce_mean(fluid.layers.square(p - y))
        fluid.optimizer.Adam(learning_rate=0.1).minimize(loss)
    return main, startup, loss


def _batch(rng, n):
    xb = rng.randn(n, IN_DIM).astype("float32")
    yb = (xb.sum(axis=1, keepdims=True)
          + 0.1 * rng.randn(n, 1)).astype("float32")
    return {"x": xb, "y": yb}


# ---------------------------------------------------------------------------
# membership agreement
# ---------------------------------------------------------------------------

class TestMembership:
    def test_write_once_first_wins(self, tmp_path):
        path = str(tmp_path / "member-00000001.json")
        first = elastic._write_once(path, {"epoch": 1, "writer": 0})
        second = elastic._write_once(path, {"epoch": 1, "writer": 5})
        # the loser reads the winner's record — never its own
        assert first == second and second["writer"] == 0

    def test_survivors_converge_on_one_world(self, tmp_path):
        hb = str(tmp_path)
        m1 = elastic.agree_membership(hb, 1, 1, [0, 1], [2],
                                      stale_timeout=0.2, timeout=10.0)
        m0 = elastic.agree_membership(hb, 0, 1, [0, 1], [2],
                                      stale_timeout=0.2, timeout=10.0)
        assert m0 == m1
        assert m0.members == [0, 1] and m0.world == 2 and m0.lost == [2]

    def test_takeover_when_presumptive_writer_is_dead(self, tmp_path):
        # rank 0 (lowest) has no heartbeat: rank 1 climbs the ladder
        m = elastic.agree_membership(str(tmp_path), 1, 2, [0, 1], [2],
                                     stale_timeout=0.2, timeout=10.0)
        assert m.writer == 1

    def test_waiter_never_usurps_a_live_lower_rank(self, tmp_path):
        w = HeartbeatWriter(str(tmp_path), 0, interval=0.05).start()
        try:
            with pytest.raises(elastic.ElasticError,
                               match="did not appear"):
                elastic.agree_membership(
                    str(tmp_path), 1, 3, [0, 1], [],
                    stale_timeout=5.0, timeout=0.6)
        finally:
            w.stop()

    def test_excluded_rank_evicts_itself(self, tmp_path):
        tr = elastic.ElasticTrainer(None, None, None, rank=2, world=3,
                                    workdir=str(tmp_path))
        shrunk = elastic.Membership(epoch=1, members=[0, 1], world=2,
                                    lost=[2], writer=0)
        with pytest.raises(elastic.ElasticEvictedError):
            tr._adopt_membership(shrunk)
        assert elastic.ELASTIC_EVICTED_EXIT_CODE == 45


# ---------------------------------------------------------------------------
# the optimizer-boundary split and the shared reduction
# ---------------------------------------------------------------------------

class TestSplitAndReduce:
    def test_build_split_none_without_collectives(self):
        main, _, _ = _build_dp_model()
        assert elastic.build_split(main) is None

    def test_plan_world_single_runs_whole(self):
        main, startup, _ = _build_dp_model()
        _prog, _st, split, result, _applied = elastic.plan_world(
            main, startup, 1, batch_size=8)
        assert split is None and result.deadlock_free

    def test_plan_world_proves_and_splits(self):
        main, startup, _ = _build_dp_model()
        prog, _st, split, result, _applied = elastic.plan_world(
            main, startup, 2, batch_size=8)
        assert result.deadlock_free
        assert split is not None
        # every gradient the optimizer consumes is exchanged
        assert split.grad_names \
            and all(n.endswith("@GRAD") for n in split.grad_names)
        assert split.pre_scale == pytest.approx(0.5)
        head_ops = split.head.global_block().ops
        tail_ops = split.tail.global_block().ops
        # collectives are realized by the exchange, not left in-graph
        assert not any(op.type == "c_allreduce_sum" for op in head_ops)
        assert not any(op.attrs.get("op_role") == "optimize"
                       for op in head_ops)
        assert any(op.attrs.get("op_role") == "optimize"
                   for op in tail_ops)
        # the source program was cloned, never mutated
        assert not any(op.type == "c_allreduce_sum"
                       for op in main.global_block().ops)
        assert any(op.type == "c_allreduce_sum"
                   for op in prog.global_block().ops)

    def test_reduce_gradients_deterministic_f32(self):
        rng = np.random.RandomState(0)
        a = {"g": rng.randn(4, 3).astype("float32")}
        b = {"g": rng.randn(4, 3).astype("float32")}
        out = elastic.reduce_gradients([a, b], 0.5)
        ref = ((np.zeros((4, 3), np.float32) + a["g"] + b["g"])
               * np.float32(0.5)).astype("float32")
        assert out["g"].dtype == np.float32
        np.testing.assert_array_equal(out["g"], ref)
        again = elastic.reduce_gradients([a, b], 0.5)
        np.testing.assert_array_equal(out["g"], again["g"])

    def test_split_trajectory_matches_whole_program(self):
        """The elastic decomposition (head → reduce → tail) must land on
        the plain full-batch trajectory: one global batch split over two
        members, reduced in f32, applied by the tail."""
        rng = np.random.RandomState(3)
        feed = _batch(rng, 8)

        main, startup, loss = _build_dp_model(seed=5)
        exe = fluid.Executor(fluid.CPUPlace())
        with scope_guard(Scope()):
            exe.run(startup)
            for _ in range(3):
                exe.run(main, feed=feed, fetch_list=[])
            # the fetched loss is computed pre-update: this reads the
            # loss on the params produced by the 3 completed steps
            out = exe.run(main, feed=feed, fetch_list=[loss])
            ref = float(np.asarray(out[0]).reshape(()))

        main2, startup2, loss2 = _build_dp_model(seed=5)
        with scope_guard(Scope()):
            _prog, st, sp, _res, _app = elastic.plan_world(
                main2, startup2, 2, batch_size=8)
            exe.run(program=st)
            ng = len(sp.grad_names)
            for _ in range(3):
                per_member, outs = [], []
                for idx in range(2):
                    sub = {k: v[idx * 4:(idx + 1) * 4]
                           for k, v in feed.items()}
                    out = exe.run(program=sp.head, feed=sub,
                                  fetch_list=[loss2.name]
                                  + sp.grad_names + sp.passthrough)
                    outs.append(out)
                    per_member.append(
                        dict(zip(sp.grad_names, out[1:1 + ng])))
                reduced = elastic.reduce_gradients(per_member,
                                                   sp.pre_scale)
                tail_feed = dict(zip(sp.passthrough, outs[0][1 + ng:]))
                tail_feed.update(reduced)
                exe.run(program=sp.tail, feed=tail_feed, fetch_list=[])
            out = exe.run(program=sp.head, feed=feed,
                          fetch_list=[loss2.name])
            got = float(np.asarray(out[0]).reshape(()))
        assert got == pytest.approx(ref, rel=1e-5)


# ---------------------------------------------------------------------------
# the exchange as rendezvous + failure detector
# ---------------------------------------------------------------------------

class TestGradExchange:
    def _pair(self, tmp_path, wedge_timeout=30.0):
        hb = str(tmp_path / "hb")
        ex = str(tmp_path / "ex")
        writers = [HeartbeatWriter(hb, r, interval=0.05).start()
                   for r in (0, 1)]
        mons = [HeartbeatMonitor(hb, [1 - r], timeout=5.0,
                                 boot_grace=5.0) for r in (0, 1)]
        pair = [elastic.GradExchange(ex, r, [0, 1], mons[r],
                                     wedge_timeout=wedge_timeout)
                for r in (0, 1)]
        return pair, writers

    def test_both_members_reduce_identically(self, tmp_path):
        (ex0, ex1), writers = self._pair(tmp_path)
        try:
            g0 = {"w@GRAD": np.full((2, 2), 1.0, np.float32)}
            g1 = {"w@GRAD": np.full((2, 2), 3.0, np.float32)}
            ex1._publish(0, 0, g1)
            r0 = ex0.allreduce(0, 0, g0, 0.5)
            r1 = ex1.allreduce(0, 0, g1, 0.5)
            np.testing.assert_array_equal(r0["w@GRAD"], r1["w@GRAD"])
            np.testing.assert_array_equal(
                r0["w@GRAD"], np.full((2, 2), 2.0, np.float32))
        finally:
            for w in writers:
                w.stop()

    def test_dead_peer_is_a_worker_lost_verdict(self, tmp_path):
        hb = str(tmp_path / "hb")
        ex_dir = str(tmp_path / "ex")
        w0 = HeartbeatWriter(hb, 0, interval=0.05).start()
        try:
            # peer 1 never boots: stale after boot_grace
            mon = HeartbeatMonitor(hb, [1], timeout=0.2, boot_grace=0.2)
            ex0 = elastic.GradExchange(ex_dir, 0, [0, 1], mon,
                                       wedge_timeout=30.0)
            with pytest.raises(WorkerLostError) as ei:
                ex0.allreduce(0, 0,
                              {"g": np.ones((1,), np.float32)}, 1.0)
            assert list(ei.value.ranks) == [1]
        finally:
            w0.stop()

    def test_wedged_peer_is_a_worker_lost_verdict(self, tmp_path):
        (ex0, _ex1), writers = self._pair(tmp_path, wedge_timeout=0.4)
        try:
            # peer 1 beats but never publishes: alive-but-stuck
            with pytest.raises(WorkerLostError, match="wedged"):
                ex0.allreduce(0, 0,
                              {"g": np.ones((1,), np.float32)}, 1.0)
        finally:
            for w in writers:
                w.stop()


# ---------------------------------------------------------------------------
# checkpoint topology gate (satellite 2)
# ---------------------------------------------------------------------------

class TestTopologyGate:
    def _save(self, tmp_path, topology):
        root = str(tmp_path / "ckpt")
        main, startup, _loss = _build_dp_model()
        exe = fluid.Executor(fluid.CPUPlace())
        with scope_guard(Scope()):
            exe.run(startup)
            path = checkpoint.save_checkpoint(
                exe, root, main_program=main, step=4,
                state={"step": 4}, topology=topology)
        return root, path, main, startup, exe

    def test_mismatch_is_typed_and_routed_not_skipped(self, tmp_path):
        topo = {"world": 3, "zero1": False}
        root, path, main, startup, exe = self._save(tmp_path, topo)
        assert checkpoint.read_topology(path) == topo
        with scope_guard(Scope()):
            exe.run(startup)
            # matching topology loads
            info = checkpoint.try_load_latest_checkpoint(
                exe, root, main_program=main, expected_topology=topo)
            assert info is not None and info.step == 4
            # a shrunk world is a TYPED error, not a silent skip to an
            # older version (that would resurrect stale state)
            with pytest.raises(TopologyMismatchError) as ei:
                checkpoint.try_load_latest_checkpoint(
                    exe, root, main_program=main,
                    expected_topology={"world": 2, "zero1": False})
        err = ei.value
        assert err.recorded == topo
        assert err.expected["world"] == 2
        assert not isinstance(err, checkpoint.CorruptCheckpointError)

    def test_reshard_clears_the_gate(self, tmp_path):
        root, path, main, startup, exe = self._save(
            tmp_path, {"world": 3, "zero1": False})
        new_topo = {"world": 2, "zero1": False}
        report = reshard.reshard_checkpoint(path, new_topo)
        # a replicated-only (plain DP) checkpoint reshards by metadata:
        # no shard dirs to re-slice, every var copied verbatim
        assert report == []
        manifest = checkpoint.verify_checkpoint(path)
        assert manifest["topology"] == new_topo
        assert manifest["resharded_from"] == {"world": 3, "zero1": False}
        with scope_guard(Scope()):
            exe.run(startup)
            info = checkpoint.try_load_latest_checkpoint(
                exe, root, main_program=main,
                expected_topology=new_topo)
            assert info is not None and info.step == 4

    def test_legacy_manifest_without_topology_loads(self, tmp_path):
        root, path, main, startup, exe = self._save(tmp_path, None)
        assert checkpoint.read_topology(path) is None
        with scope_guard(Scope()):
            exe.run(startup)
            info = checkpoint.try_load_latest_checkpoint(
                exe, root, main_program=main,
                expected_topology={"world": 2, "zero1": False})
            assert info is not None  # pre-ISSUE-12 checkpoints keep working


# ---------------------------------------------------------------------------
# reshard round-trips: save at N, restore at N-1 / N-2 (satellite 4)
# ---------------------------------------------------------------------------

VOCAB = 64
N_SLOTS, SLOT_LEN, DENSE = 2, 3, 4


def _build_sharded(lr=0.05):
    fluid.unique_name.switch()
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 11
    with fluid.program_guard(main, startup):
        slots = [
            fluid.layers.data("slot%d" % i, shape=[SLOT_LEN],
                              dtype="int64")
            for i in range(N_SLOTS)
        ]
        dense = fluid.layers.data("dense", shape=[DENSE],
                                  dtype="float32")
        label = fluid.layers.data("label", shape=[1], dtype="int64")
        loss, _prob = ctr.wide_deep(
            slots, dense, label, vocab=VOCAB, embed_dim=8,
            hidden=(8,), is_distributed=True)
        fluid.optimizer.Adam(learning_rate=lr).minimize(loss)
    return main, startup, loss


def _ctr_feed(rng, bs=16):
    feed = {
        "slot%d" % i: rng.randint(0, VOCAB, (bs, SLOT_LEN))
        .astype("int64") for i in range(N_SLOTS)
    }
    feed["dense"] = rng.randn(bs, DENSE).astype("float32")
    feed["label"] = rng.randint(0, 2, (bs, 1)).astype("int64")
    return feed


def _gathered_shards(path):
    """Gather reference: for every ``<var>.shards`` dir, reassemble the
    full array by concatenating the shard files in row order — reading
    the files directly, independent of the reshard code under test."""
    full = {}
    for root, dirs, _files in os.walk(path):
        for d in list(dirs):
            if not d.endswith(".shards"):
                continue
            sdir = os.path.join(root, d)
            parts = []
            for fname in os.listdir(sdir):
                if not fname.startswith("shard-"):
                    continue
                start = int(fname[len("shard-"):].split("_", 1)[0])
                parts.append((start, np.load(os.path.join(sdir, fname))))
            parts.sort(key=lambda p: p[0])
            full[d[:-len(".shards")]] = np.concatenate(
                [a for _s, a in parts], axis=0)
    return full


class TestReshardRoundTrip:
    def _save_at_8(self, tmp_path):
        root = str(tmp_path / "ckpt")
        main, startup, loss = _build_sharded()
        exe = fluid.Executor(fluid.CPUPlace())
        rng = np.random.RandomState(13)
        with scope_guard(Scope()):
            exe.run(startup)
            prog = fluid.CompiledProgram(main).with_data_parallel(
                loss_name=loss.name)
            for _ in range(2):
                exe.run(prog, feed=_ctr_feed(rng), fetch_list=[])
            path = checkpoint.save_checkpoint(
                exe, root, main_program=main, step=2,
                state={"step": 2},
                topology={"world": 8, "zero1": True})
        return root, path, main, startup, exe

    def test_restore_shrunk_bit_exact(self, tmp_path):
        root, path, main, startup, exe = self._save_at_8(tmp_path)
        before = _gathered_shards(path)
        # the table and its Adam moments saved as row shards
        assert any("emb" in n for n in before)
        assert sum("moment" in n for n in before) >= 2

        for new_world in (7, 6):   # N-1, then N-2 chained on top
            report = reshard.reshard_checkpoint(
                path, {"world": new_world, "zero1": True})
            assert sorted(e["var"] for e in report) == sorted(before)
            manifest = checkpoint.verify_checkpoint(path)
            assert manifest["topology"]["world"] == new_world
            after = _gathered_shards(path)
            for name, ref in before.items():
                # gather-then-scatter: the reassembled array is
                # bit-identical, through chained reshards
                assert after[name].dtype == ref.dtype
                np.testing.assert_array_equal(after[name], ref)
                # and the on-disk slicing is the new world's row ranges
                bounds = [b for b in reshard.shard_bounds(
                    ref.shape[0], new_world) if b[0] != b[1]]
                entry = [e for e in report if e["var"] == name][0]
                assert entry["new_files"] == len(bounds)

        # the resharded version restores on a fresh scope
        with scope_guard(Scope()):
            exe.run(startup)
            info = checkpoint.try_load_latest_checkpoint(
                exe, root, main_program=main,
                expected_topology={"world": 6, "zero1": True})
            assert info is not None and info.step == 2
        # ... and the pre-reshard topology would now be rejected
        with scope_guard(Scope()):
            exe.run(startup)
            with pytest.raises(TopologyMismatchError):
                checkpoint.try_load_latest_checkpoint(
                    exe, root, main_program=main,
                    expected_topology={"world": 8, "zero1": True})

    def test_reshard_refuses_a_torn_source(self, tmp_path):
        _root, path, _main, _startup, _exe = self._save_at_8(tmp_path)
        victim = None
        for walk_root, _dirs, files in os.walk(path):
            for f in files:
                if f.startswith("shard-"):
                    victim = os.path.join(walk_root, f)
                    break
            if victim:
                break
        with open(victim, "r+b") as f:
            f.seek(-1, os.SEEK_END)
            f.write(b"\xff")
        with pytest.raises(checkpoint.CorruptCheckpointError):
            reshard.reshard_checkpoint(path, {"world": 7, "zero1": True})
