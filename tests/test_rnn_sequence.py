"""RNN + sequence ops on padded batches (reference tests:
unittests/test_lstm_op.py, test_gru_op.py, test_seq_pool.py,
test_sequence_reverse.py, test_sequence_mask.py)."""

import numpy as np

import paddle_tpu as fluid
from paddle_tpu.executor import Scope, scope_guard

rng = np.random.RandomState(7)


def _run(main, startup, feed, fetch):
    exe = fluid.Executor(fluid.CPUPlace())
    with scope_guard(Scope()):
        exe.run(startup)
        return exe.run(main, feed=feed, fetch_list=fetch)


def _np_lstm(x, w, b, h0, c0):
    """numpy oracle, gate order i,f,g,o."""
    B, T, four_d = x.shape
    d = four_d // 4
    h, c = h0.copy(), c0.copy()
    hs = []
    sig = lambda v: 1 / (1 + np.exp(-v))  # noqa: E731
    for t in range(T):
        g = x[:, t] + h @ w + b
        i, f, gg, o = np.split(g, 4, axis=-1)
        c = sig(f) * c + sig(i) * np.tanh(gg)
        h = sig(o) * np.tanh(c)
        hs.append(h.copy())
    return np.stack(hs, 1), c


def test_dynamic_lstm_matches_numpy():
    B, T, D = 2, 5, 4
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[T, 4 * D], dtype="float32")
        h, c = fluid.layers.dynamic_lstm(x, size=4 * D)
    xv = rng.randn(B, T, 4 * D).astype("float32") * 0.5
    params = main.all_parameters()
    exe = fluid.Executor(fluid.CPUPlace())
    with scope_guard(Scope()):
        exe.run(startup)
        from paddle_tpu.executor import global_scope

        w = np.asarray(global_scope().get(params[0].name))
        b = np.asarray(global_scope().get(params[1].name))
        out = exe.run(main, feed={"x": xv}, fetch_list=[h])[0]
    ref, _ = _np_lstm(xv, w, b.reshape(1, -1)[:, :4 * D].repeat(B, 0) * 0 +
                      b.reshape(-1)[:4 * D], np.zeros((B, D), "float32"),
                      np.zeros((B, D), "float32"))
    np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)


def test_dynamic_lstm_masking():
    """Shorter sequences must freeze their state at their length."""
    B, T, D = 2, 6, 3
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[T, 4 * D], dtype="float32")
        lens = fluid.layers.data("lens", shape=[], dtype="int32")
        h, c = fluid.layers.dynamic_lstm(x, size=4 * D, seq_len=lens)
    xv = rng.randn(B, T, 4 * D).astype("float32")
    lv = np.array([3, 6], "int32")
    exe = fluid.Executor(fluid.CPUPlace())
    with scope_guard(Scope()):
        exe.run(startup)
        out = exe.run(main, feed={"x": xv, "lens": lv}, fetch_list=[h])[0]
    # row 0 frozen after t=3
    np.testing.assert_allclose(out[0, 3], out[0, 4])
    np.testing.assert_allclose(out[0, 3], out[0, 5])
    assert not np.allclose(out[1, 4], out[1, 5])


def test_dynamic_gru_runs_and_grads():
    B, T, D = 2, 4, 3
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[T, 3 * D], dtype="float32")
        h = fluid.layers.dynamic_gru(x, size=D)
        loss = fluid.layers.mean(h)
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    xv = rng.randn(B, T, 3 * D).astype("float32")
    exe = fluid.Executor(fluid.CPUPlace())
    with scope_guard(Scope()):
        exe.run(startup)
        l1 = exe.run(main, feed={"x": xv}, fetch_list=[loss])[0]
        l2 = exe.run(main, feed={"x": xv}, fetch_list=[loss])[0]
    assert np.isfinite(l1).all() and np.isfinite(l2).all()
    assert not np.allclose(l1, l2)  # params moved


def test_sequence_pool_types():
    B, T, D = 2, 4, 3
    x = rng.rand(B, T, D).astype("float32")
    lens = np.array([2, 4], "int32")
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        xv = fluid.layers.data("x", shape=[T, D], dtype="float32")
        lv = fluid.layers.data("lens", shape=[], dtype="int32")
        outs = {
            ptype: fluid.layers.sequence_pool(xv, ptype, seq_len=lv)
            for ptype in ("sum", "average", "max", "last", "first")
        }
    res = _run(main, startup, {"x": x, "lens": lens}, list(outs.values()))
    got = dict(zip(outs.keys(), res))
    m = (np.arange(T)[None, :] < lens[:, None]).astype("float32")[..., None]
    np.testing.assert_allclose(got["sum"], (x * m).sum(1), rtol=1e-5)
    np.testing.assert_allclose(
        got["average"], (x * m).sum(1) / lens[:, None], rtol=1e-5
    )
    np.testing.assert_allclose(
        got["max"], np.where(m > 0, x, -np.inf).max(1), rtol=1e-5
    )
    np.testing.assert_allclose(got["last"][0], x[0, 1])
    np.testing.assert_allclose(got["last"][1], x[1, 3])
    np.testing.assert_allclose(got["first"], x[:, 0])


def test_sequence_reverse_respects_lengths():
    B, T, D = 2, 4, 2
    x = rng.rand(B, T, D).astype("float32")
    lens = np.array([2, 4], "int32")
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        xv = fluid.layers.data("x", shape=[T, D], dtype="float32")
        lv = fluid.layers.data("lens", shape=[], dtype="int32")
        out = fluid.layers.sequence_reverse(xv, seq_len=lv)
    res = _run(main, startup, {"x": x, "lens": lens}, [out])[0]
    np.testing.assert_allclose(res[0, :2], x[0, :2][::-1])
    np.testing.assert_allclose(res[0, 2:], x[0, 2:])  # padding untouched
    np.testing.assert_allclose(res[1], x[1][::-1])


def test_sequence_mask():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        lens = fluid.layers.data("lens", shape=[], dtype="int32")
        m = fluid.layers.sequence_mask(lens, maxlen=5, dtype="float32")
    res = _run(main, startup, {"lens": np.array([2, 5], "int32")}, [m])[0]
    np.testing.assert_allclose(res, [[1, 1, 0, 0, 0], [1, 1, 1, 1, 1]])


def test_sequence_softmax_masks_padding():
    B, T = 2, 4
    x = rng.rand(B, T).astype("float32")
    lens = np.array([2, 4], "int32")
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        xv = fluid.layers.data("x", shape=[T], dtype="float32")
        lv = fluid.layers.data("lens", shape=[], dtype="int32")
        out = fluid.layers.sequence_softmax(xv, seq_len=lv)
    res = _run(main, startup, {"x": x, "lens": lens}, [out])[0]
    assert res[0, 2] == 0 and res[0, 3] == 0
    np.testing.assert_allclose(res.sum(1), 1.0, rtol=1e-5)


def test_attention_lstm_and_fused_embedding_fc_lstm():
    from paddle_tpu.ops import registry
    from paddle_tpu.ops.registry import LoweringContext
    import jax

    ctx = LoweringContext(base_key=jax.random.key(0), mode="train")
    rng = np.random.RandomState(0)
    B, T, D, H = 2, 5, 4, 3
    x = rng.randn(B, T, D).astype("float32")
    c0 = np.zeros((B, H), "float32")
    attn_w = rng.randn(D + H, 1).astype("float32")
    lstm_w = rng.randn(D + H, 4 * H).astype("float32")
    out = registry.call_op(
        registry.get_op_def("attention_lstm"), ctx,
        {"X": [x], "C0": [c0], "H0": [None],
         "AttentionWeight": [attn_w], "AttentionBias": [None],
         "AttentionScalar": [None], "AttentionScalarBias": [None],
         "LSTMWeight": [lstm_w], "LSTMBias": [None],
         "SeqLen": [np.array([5, 3], "int64")]}, {})
    hs = np.asarray(out["Hidden"][0])
    assert hs.shape == (B, T, H) and np.isfinite(hs).all()

    V = 11
    emb = rng.randn(V, 4 * H).astype("float32")
    wh = rng.randn(H, 4 * H).astype("float32")
    ids = rng.randint(0, V, (B, T)).astype("int64")
    out = registry.call_op(
        registry.get_op_def("fused_embedding_fc_lstm"), ctx,
        {"Ids": [ids], "Embeddings": [emb], "WeightH": [wh],
         "Bias": [None], "H0": [None], "C0": [None],
         "SeqLen": [np.array([5, 2], "int64")]}, {})
    hs = np.asarray(out["Hidden"][0])
    assert hs.shape == (B, T, H) and np.isfinite(hs).all()
    # masked steps carry state: rows past length equal the last valid row
    np.testing.assert_allclose(hs[1, 2], hs[1, 1], rtol=1e-6)
