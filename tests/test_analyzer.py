"""Whole-program distributed static analyzer (ISSUE 3):
abstract interpretation, the static cost model, collective schedule
extraction + the cross-worker deadlock-freedom proof, the new
analyzer-backed lint checks, and the analyze_program CLI.

Golden numbers are hand-derived from the documented conventions (README
"Static analysis / lint > Analyzer"): one multiply-add = 2 FLOPs,
mul = 2·M·K·N, ``*_grad`` = 2x forward, default = one FLOP per output
element; ring-allreduce ICI = 2·B·(n-1)/n.
"""

import json
import os
import subprocess
import sys

import paddle_tpu as fluid
from paddle_tpu.static_analysis import (
    Severity,
    Sharding,
    estimate_cost,
    interpret_program,
    prove_deadlock_free,
    verify_program,
)

import dist_model

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _errors(diags):
    return [d for d in diags if d.severity >= Severity.ERROR]


def _fresh():
    fluid.unique_name.switch()
    return fluid.Program(), fluid.Program()


# ---------------------------------------------------------------------------
# abstract interpretation
# ---------------------------------------------------------------------------

class TestInterp:
    def test_shapes_dtypes_and_batch_resolution(self):
        main, startup, loss, _ = dist_model.build_model()
        res = interpret_program(main, batch_size=16)
        x = res.val("x")
        assert x.shape == (16, 8) and x.dtype == "float32"
        assert not x.persistable
        w = res.val("mlp.w0")
        assert w.shape == (8, 16) and w.persistable
        # walk covered every op
        assert len(res.records) == len(main.global_block().ops)

    def test_sharding_seeds_and_collective_transfer(self):
        """DP transpile: feeds are batch-sharded over the data axis,
        params replicated, and a grad coming out of c_allreduce_sum is
        replicated again (the collective transfer rule)."""
        workers, _, _ = dist_model.build_dp_workers(nranks=2)
        res = interpret_program(workers[0], nranks=2, batch_size=16)
        assert res.val("x").sharding.is_sharded
        assert res.val("x").sharding.parts == 2
        assert res.val("x").local_numel == 16 * 8 // 2
        assert res.val("mlp.w0").sharding.kind == Sharding.REPLICATED
        # the allreduced grad is the LAST write to mlp.w0@GRAD
        assert res.val("mlp.w0@GRAD").sharding.kind == Sharding.REPLICATED

    def test_unreferenced_persistables_enter_env(self):
        p, _ = _fresh()
        with fluid.program_guard(p):
            fluid.layers.create_parameter([4, 4], "float32", name="orphan.w")
        res = interpret_program(p)
        assert res.val("orphan.w") is not None
        assert res.val("orphan.w").persistable

    def test_sub_block_descent(self):
        main, startup = _fresh()
        with fluid.program_guard(main, startup):
            x = fluid.layers.data("x", shape=[2, 4], dtype="float32",
                                  append_batch_size=False)
            pred = fluid.layers.fill_constant([1], "bool", True)
            out = fluid.layers.cond(
                pred, lambda: fluid.layers.scale(x, scale=2.0),
                lambda: fluid.layers.scale(x, scale=-1.0))
        res = interpret_program(main)
        types = {r.op.type for r in res.records}
        assert "scale" in types  # sub-block ops were interpreted


# ---------------------------------------------------------------------------
# cost model
# ---------------------------------------------------------------------------

class TestCostModel:
    def test_golden_mlp_flops(self):
        """Hand-derived total for the dist_model MLP at batch 16 — the
        stable-numbers contract future perf PRs cite."""
        main, startup, loss, _ = dist_model.build_model()
        rep = estimate_cost(main, targets=[loss.name], batch_size=16)
        # fwd: mul 2·16·8·16 + add 256 + relu 256 + mul 2·16·16·1 +
        #      add 16 + sec 16 + mean 16
        # bwd: seed 0 + mean_grad 36 + sec_grad 16 + add_grad 17 +
        #      mul_grad 1024 + relu_grad 256 + add_grad 272 +
        #      mul_grad 8192
        # sgd: 16 + 1 + 128 + 16
        assert rep.total_flops == 15142
        assert rep.total_bytes_read > 0
        assert rep.total_bytes_written > 0
        assert rep.total_ici_bytes == 0  # no collectives

    def test_cost_is_deterministic(self):
        main, startup, loss, _ = dist_model.build_model()
        a = estimate_cost(main, targets=[loss.name], batch_size=16)
        b = estimate_cost(main, targets=[loss.name], batch_size=16)
        assert a.total_flops == b.total_flops
        assert a.peak_memory_bytes == b.peak_memory_bytes
        assert [c.to_dict() for c in a.op_costs] == \
            [c.to_dict() for c in b.op_costs]

    def test_peak_memory_components(self):
        main, startup, loss, _ = dist_model.build_model()
        rep = estimate_cost(main, targets=[loss.name], batch_size=16)
        # persistables: (8·16 + 16 + 16·1 + 1 + lr 1) · 4 bytes
        assert rep.persistent_bytes == (8 * 16 + 16 + 16 + 1 + 1) * 4
        assert rep.peak_memory_bytes > rep.persistent_bytes

    def test_allreduce_ici_convention(self):
        """2-rank DP: each c_allreduce_sum moves 2·B·(n-1)/n = B."""
        workers, _, _ = dist_model.build_dp_workers(nranks=2)
        rep = estimate_cost(workers[0], nranks=2, batch_size=16)
        grads_bytes = (8 * 16 + 16 + 16 + 1) * 4
        assert rep.total_ici_bytes == grads_bytes
        assert rep.ici_bytes_per_ring() == {0: grads_bytes}

    def test_hbm_budget_gate(self, monkeypatch):
        main, startup, loss, _ = dist_model.build_model()
        rep = estimate_cost(main, targets=[loss.name], batch_size=16,
                            budget=100)
        assert rep.over_budget
        monkeypatch.setenv("PADDLE_TPU_HBM_BUDGET", "1G")
        rep = estimate_cost(main, targets=[loss.name], batch_size=16)
        assert rep.hbm_budget == 1 << 30 and not rep.over_budget

    def test_bench_json_lines(self):
        main, startup, loss, _ = dist_model.build_model()
        rep = estimate_cost(main, targets=[loss.name], batch_size=16)
        lines = rep.bench_json().splitlines()
        metrics = {json.loads(l)["metric"] for l in lines}
        assert "static_program_flops" in metrics
        assert "static_program_peak_memory" in metrics


# ---------------------------------------------------------------------------
# collective schedules + the deadlock-freedom proof
# ---------------------------------------------------------------------------

class TestSchedules:
    def test_pipeline_workers_prove_consistent(self):
        workers, startups, loss_name = dist_model.build_pipeline_workers()
        assert len(workers) == 2
        scheds, diags = prove_deadlock_free(workers)
        assert diags == []
        # stage 0 sends the activation down, receives the grad back
        kinds0 = [(e.kind, e.peer) for e in scheds[0][1]]
        kinds1 = [(e.kind, e.peer) for e in scheds[1][1]]
        assert kinds0 == [("send", 1), ("recv", 1)]
        assert kinds1 == [("recv", 0), ("send", 0)]

    def test_pipeline_workers_lint_clean(self, verify_clean):
        workers, startups, loss_name = dist_model.build_pipeline_workers()
        verify_clean(workers[0])
        verify_clean(workers[1], targets=[loss_name])
        for su in startups:
            verify_clean(su)

    def test_dp_workers_prove_consistent(self):
        workers, _, _ = dist_model.build_dp_workers(nranks=2)
        scheds, diags = prove_deadlock_free(workers)
        assert diags == []
        assert len(scheds[0][0]) == 4  # one allreduce per grad
        assert all(e.kind == "c_allreduce_sum" for e in scheds[0][0])

    def test_moe_workers_prove_consistent(self):
        workers, _, out_name = dist_model.build_moe_workers(nranks=2)
        scheds, diags = prove_deadlock_free(workers)
        assert diags == []
        from paddle_tpu.parallel.moe import MOE_RING_ID

        kinds = [e.kind for e in scheds[0][MOE_RING_ID]]
        assert kinds == ["all_to_all", "all_to_all"]

    def test_swapped_p2p_yields_divergence_with_coordinates(self):
        """The acceptance negative: swap two collectives in ONE
        worker's program → collective-schedule-divergence ERROR naming
        the diverging op pair with block/op indices."""
        workers, _, _ = dist_model.build_pipeline_workers()
        b = workers[1].global_block()
        idxs = [i for i, op in enumerate(b.ops)
                if op.type in ("send_v2", "recv_v2")]
        b.ops[idxs[0]], b.ops[idxs[1]] = b.ops[idxs[1]], b.ops[idxs[0]]
        _, diags = prove_deadlock_free(workers)
        assert len(diags) == 1
        d = diags[0]
        assert d.check == "collective-schedule-divergence"
        assert d.severity is Severity.ERROR
        # the diagnostic anchors an op coordinate and names both sides
        assert d.block_idx == 0 and isinstance(d.op_idx, int)
        assert "worker 0" in d.message and "worker 1" in d.message

    def test_reordered_allreduce_yields_position_divergence(self):
        workers, _, _ = dist_model.build_dp_workers(nranks=2)
        b = workers[1].global_block()
        ar = [i for i, op in enumerate(b.ops)
              if op.type == "c_allreduce_sum"]
        # swap two allreduces with different payloads
        b.ops[ar[0]], b.ops[ar[1]] = b.ops[ar[1]], b.ops[ar[0]]
        _, diags = prove_deadlock_free(workers)
        assert diags
        d = diags[0]
        assert d.check == "collective-schedule-divergence"
        assert "position" in d.message
        assert d.op_type == "c_allreduce_sum"

    def test_shared_param_fanin_grad_is_allreduced(self):
        """A parameter used by two ops gets its partials summed into
        ``w@GRAD@SUM_0`` — the grad the optimizer consumes.  The
        allreduce must land on THAT var, not on the partial (which
        would apply avg(partial1)+local(partial2), divergent per
        worker)."""
        from paddle_tpu.transpiler.collective import GradAllReduce

        fluid.unique_name.switch()
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.layers.data("x", shape=[8], dtype="float32")
            w_attr = fluid.ParamAttr(name="sharedw")
            h1 = fluid.layers.fc(x, size=8, param_attr=w_attr,
                                 bias_attr=False)
            h2 = fluid.layers.fc(h1, size=8, param_attr=w_attr,
                                 bias_attr=False)
            loss = fluid.layers.mean(h2)
            fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
        GradAllReduce().transpile(program=main, startup_program=startup,
                                  rank=0, nranks=2)
        b = main.global_block()
        ars = [op.inputs["X"][0] for op in b.ops
               if op.type == "c_allreduce_sum"]
        assert ars == ["sharedw@GRAD@SUM_0"]
        sgd = next(op for op in b.ops if op.type == "sgd")
        assert sgd.inputs["Grad"] == ["sharedw@GRAD@SUM_0"]

    def test_missing_collective_yields_length_divergence(self):
        workers, _, _ = dist_model.build_dp_workers(nranks=2)
        b = workers[1].global_block()
        # drop the LAST allreduce so every shared position still
        # matches — the length layer, not the position layer, must fire
        i = max(i for i, op in enumerate(b.ops)
                if op.type == "c_allreduce_sum")
        del b.ops[i]
        _, diags = prove_deadlock_free(workers)
        assert any("worker 0 issues" in d.message for d in diags)

    def test_mismatched_p2p_payload_flagged(self):
        workers, _, _ = dist_model.build_pipeline_workers()
        b = workers[1].global_block()
        recv = next(op for op in b.ops if op.type == "recv_v2")
        recv.attrs["out_shape"] = [4, 4]
        v = b._find_var_recursive(recv.outputs["Out"][0])
        v.shape = (4, 4)
        _, diags = prove_deadlock_free(workers, batch_size=16)
        assert any("p2p channel" in d.message for d in diags)


# ---------------------------------------------------------------------------
# Program.analyze — the acceptance flow
# ---------------------------------------------------------------------------

class TestProgramAnalyze:
    def test_pipeline_acceptance(self):
        """ISSUE 3 acceptance: analyze() on the dist_model pipeline
        program reports consistent per-worker schedules, a nonzero
        FLOP/byte/ICI breakdown, and a peak-memory estimate."""
        workers, _, loss_name = dist_model.build_pipeline_workers()
        rep = workers[1].analyze(targets=[loss_name], workers=workers,
                                 batch_size=16)
        assert rep.ok
        assert rep.schedule_consistent is True
        assert rep.cost.total_flops > 0
        assert rep.cost.total_bytes_read > 0
        assert rep.cost.total_ici_bytes > 0
        assert rep.cost.peak_memory_bytes > 0
        assert rep.worker_schedules and len(rep.worker_schedules) == 2
        text = rep.format()
        assert "deadlock-free" in text and "peak memory" in text

    def test_analyze_reports_swap_divergence(self):
        workers, _, loss_name = dist_model.build_pipeline_workers()
        b = workers[0].global_block()
        idxs = [i for i, op in enumerate(b.ops)
                if op.type in ("send_v2", "recv_v2")]
        b.ops[idxs[0]], b.ops[idxs[1]] = b.ops[idxs[1]], b.ops[idxs[0]]
        rep = workers[0].analyze(workers=workers, batch_size=16)
        assert not rep.ok
        assert rep.schedule_consistent is False
        assert any(d.check == "collective-schedule-divergence"
                   for d in rep.errors)

    def test_to_dict_round_trips_through_json(self):
        workers, _, loss_name = dist_model.build_pipeline_workers()
        rep = workers[0].analyze(workers=workers, batch_size=16)
        blob = json.loads(json.dumps(rep.to_dict()))
        assert blob["ok"] is True
        assert blob["schedule_consistent"] is True
        assert blob["cost"]["total_flops"] == rep.cost.total_flops


# ---------------------------------------------------------------------------
# analyzer-backed lint checks
# ---------------------------------------------------------------------------

class TestNewChecks:
    def test_peak_memory_over_budget(self):
        main, startup, loss, _ = dist_model.build_model()
        main._hbm_budget = "1K"
        errs = _errors(verify_program(main, targets=[loss.name]))
        assert any(d.check == "peak-memory-over-budget" for d in errs)
        main._hbm_budget = None
        assert not any(d.check == "peak-memory-over-budget"
                       for d in verify_program(main, targets=[loss.name]))

    def test_degenerate_sharding(self):
        p, _ = _fresh()
        with fluid.program_guard(p):
            fluid.layers.create_parameter([3, 4], "float32",
                                          name="tiny.w")
        p._num_trainers = 4
        p.global_block().vars["tiny.w"]._is_distributed = True
        diags = verify_program(p)
        hits = [d for d in diags if d.check == "degenerate-sharding"]
        assert hits and hits[0].var_names == ("tiny.w",)
        assert hits[0].severity is Severity.WARNING

    def test_degenerate_sharding_skips_dynamic_batch_dims(self):
        p, _ = _fresh()
        with fluid.program_guard(p):
            x = fluid.layers.data("x", shape=[8], dtype="float32")
            fluid.layers.scale(x, scale=1.0)
        p._num_trainers = 4
        assert not any(d.check == "degenerate-sharding"
                       for d in verify_program(p))

    def test_oversized_replicated_persistable(self, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_REPLICATED_BUDGET", "1M")
        p, _ = _fresh()
        with fluid.program_guard(p):
            fluid.layers.create_parameter([600, 600], "float32",
                                          name="big.w")
        p._num_trainers = 2
        diags = verify_program(p)
        hits = [d for d in diags
                if d.check == "oversized-replicated-persistable"]
        assert hits and hits[0].var_names == ("big.w",)
        # single-worker programs are exempt
        p._num_trainers = 1
        assert not any(d.check == "oversized-replicated-persistable"
                       for d in verify_program(p))

    def test_parallel_emitter_collectives_need_ring_id(self):
        """Satellite: check_collective_ring covers moe/ulysses/ring-
        attention emitted collectives, not just transpiler c_* ops."""
        workers, _, out_name = dist_model.build_moe_workers(nranks=2)
        b = workers[0].global_block()
        a2a = next(op for op in b.ops if op.type == "all_to_all")
        del a2a.attrs["ring_id"]
        errs = _errors(verify_program(workers[0], targets=[out_name]))
        assert any(d.check == "collective-ring"
                   and d.op_type == "all_to_all" for d in errs)

    def test_ppermute_needs_ring_id(self):
        from paddle_tpu.parallel.ring_attention import ring_rotate

        p, s = _fresh()
        with fluid.program_guard(p, s):
            k = fluid.layers.data("k", shape=[4, 8, 16], dtype="float32")
            kr = ring_rotate(k)
        op = next(op for op in p.global_block().ops
                  if op.type == "ppermute")
        op.attrs["ring_id"] = "not-an-int"
        errs = _errors(verify_program(p, targets=[kr.name]))
        assert any(d.check == "collective-ring"
                   and d.op_type == "ppermute" for d in errs)

    def test_collective_nrings_bootstrap_gap_fixed(self, verify_clean):
        """Collective(nrings=2) used to bootstrap ring 0 only — the
        pairing gap the satellite names.  Now every ring gets its
        c_gen_nccl_id/c_comm_init pair."""
        from paddle_tpu.transpiler.collective import GradAllReduce

        fluid.unique_name.switch()
        main, startup, loss, _ = dist_model.build_model()
        GradAllReduce(nrings=2).transpile(
            program=main, startup_program=startup, rank=0, nranks=2)
        rings = {op.attrs["ring_id"]
                 for op in startup.global_block().ops
                 if op.type == "c_gen_nccl_id"}
        assert rings == {0, 1}
        verify_clean(startup)

    def test_startup_bootstrap_covers_used_rings(self):
        """A program carrying its own bootstrap must declare every ring
        its collectives use."""
        from paddle_tpu.transpiler.collective import ensure_comm_ring

        p, _ = _fresh()
        ensure_comm_ring(p, 0, rank=0, nranks=2)
        b = p.global_block()
        b.create_var(name="g", shape=[4], dtype="float32", is_data=True)
        b.append_op(type="c_allreduce_sum", inputs={"X": ["g"]},
                    outputs={"Out": ["g"]}, attrs={"ring_id": 7})
        diags = verify_program(p)
        assert any(d.check == "collective-ring"
                   and "ring 7" in d.message
                   and d.severity is Severity.WARNING for d in diags)


# ---------------------------------------------------------------------------
# analyze_program CLI (shares the lint_program emitter)
# ---------------------------------------------------------------------------

def _save_worker_programs(tmp_path):
    from paddle_tpu.proto import save_program

    workers, _, loss_name = dist_model.build_pipeline_workers()
    paths = []
    for w, p in enumerate(workers):
        pth = str(tmp_path / ("w%d.json" % w))
        save_program(p, pth)
        paths.append(pth)
    return workers, paths, loss_name


def _run_cli(tool, *args):
    return subprocess.run(
        [sys.executable, "-m", "paddle_tpu.tools.%s" % tool, *args],
        capture_output=True, text=True, timeout=240,
        env={**os.environ,
             "PYTHONPATH": REPO + os.pathsep + os.environ.get(
                 "PYTHONPATH", ""),
             "JAX_PLATFORMS": "cpu"},
        cwd=REPO)


class TestAnalyzeCli:
    def test_table_and_proof_exit_zero(self, tmp_path):
        _, paths, _ = _save_worker_programs(tmp_path)
        res = _run_cli("analyze_program", "--program-json", paths[0],
                       "--workers", *paths, "--batch", "16")
        assert res.returncode == 0, res.stdout + res.stderr
        assert "cost model" in res.stdout
        assert "deadlock-free" in res.stdout

    def test_json_report_schema(self, tmp_path):
        _, paths, _ = _save_worker_programs(tmp_path)
        res = _run_cli("analyze_program", "--program-json", paths[0],
                       "--workers", *paths, "--batch", "16", "--json")
        assert res.returncode == 0, res.stdout + res.stderr
        blob = json.loads(res.stdout)
        assert {"cost", "schedule", "schedule_consistent",
                "diagnostics", "ok"} <= set(blob)
        assert blob["cost"]["total_ici_bytes"] > 0

    def test_divergent_workers_exit_nonzero(self, tmp_path):
        from paddle_tpu.proto import save_program

        workers, _, _ = dist_model.build_pipeline_workers()
        b = workers[1].global_block()
        idxs = [i for i, op in enumerate(b.ops)
                if op.type in ("send_v2", "recv_v2")]
        b.ops[idxs[0]], b.ops[idxs[1]] = b.ops[idxs[1]], b.ops[idxs[0]]
        paths = []
        for w, p in enumerate(workers):
            pth = str(tmp_path / ("d%d.json" % w))
            save_program(p, pth)
            paths.append(pth)
        res = _run_cli("analyze_program", "--program-json", paths[0],
                       "--workers", *paths)
        assert res.returncode == 1
        assert "collective-schedule-divergence" in res.stdout

    def test_bench_json_dump(self, tmp_path):
        _, paths, _ = _save_worker_programs(tmp_path)
        out = str(tmp_path / "bench.json")
        res = _run_cli("analyze_program", "--program-json", paths[0],
                       "--batch", "16", "--bench-json", out)
        assert res.returncode == 0
        lines = [json.loads(l) for l in open(out) if l.strip()]
        assert any(l["metric"] == "static_program_flops" for l in lines)

    def test_hbm_budget_flag_gates(self, tmp_path):
        _, paths, _ = _save_worker_programs(tmp_path)
        res = _run_cli("analyze_program", "--program-json", paths[0],
                       "--batch", "16", "--hbm-budget", "1K")
        assert res.returncode == 1
        assert "peak-memory-over-budget" in res.stdout

    def test_lint_cli_shares_emitter_flags(self, tmp_path):
        """Satellite: lint_program and analyze_program speak the same
        --json/--fail-on emitter."""
        _, paths, _ = _save_worker_programs(tmp_path)
        for tool in ("lint_program", "analyze_program"):
            res = _run_cli(tool, "--program-json", paths[0], "--json",
                           "--fail-on", "ERROR")
            assert res.returncode == 0, (tool, res.stdout, res.stderr)
            blob = json.loads(res.stdout)
            diags = blob if isinstance(blob, list) else \
                blob["diagnostics"]
            assert isinstance(diags, list)
