"""Auto-parallelism planner (ISSUE 7): candidate search over the
placement/sharding space the analyzer prices — ClusterSpec handling,
deterministic plans (in-process, cross-process, autotune on/off),
cost-tie stability, the HBM-infeasible least-memory fallback, the
planner-beats-or-matches-hand-transpiles acceptance sweep over the
bert / resnet / deepfm example builders and the dist_model DP /
pipeline / MoE worker builders, the emitted workers' lint + deadlock
proof, the ``--plan`` CLI, the ``manual-plan-suboptimal`` advisory, and
the fleet / DistributeTranspiler ``auto`` routing."""

import json
import os
import subprocess
import sys

import pytest

import paddle_tpu as fluid
from paddle_tpu.static_analysis import Severity, Sharding
from paddle_tpu.static_analysis.cost import price_plan, price_program
from paddle_tpu.static_analysis.interp import interpret_program
from paddle_tpu.parallel.planner import (ClusterSpec, auto_transpile,
                                         enumerate_candidates,
                                         price_worker_set,
                                         resolve_cluster_spec)

import dist_model

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TESTS = os.path.dirname(os.path.abspath(__file__))


def _errors(diags):
    return [d for d in diags if d.severity >= Severity.ERROR]


def _fresh_mlp():
    fluid.unique_name.switch()
    return dist_model.build_model()


def _run_worker(which, chips, extra_env=None, timeout=120):
    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           "PYTHONPATH": os.pathsep.join([REPO, TESTS]),
           **(extra_env or {})}
    res = subprocess.run(
        [sys.executable, os.path.join(TESTS, "plan_worker.py"),
         which, str(chips)],
        capture_output=True, text=True, timeout=timeout, env=env)
    assert res.returncode == 0, res.stderr[-2000:]
    return json.loads(res.stdout.strip().splitlines()[-1])


class TestClusterSpec:
    def test_coerce_forms(self, tmp_path):
        assert ClusterSpec.coerce(4).chips == 4
        assert ClusterSpec.coerce({"chips": 2, "hbm_gb": 8}).hbm_gb == 8
        assert ClusterSpec.coerce('{"chips": 3}').chips == 3
        p = tmp_path / "spec.json"
        p.write_text('{"chips": 5, "ici_gbps": 50}')
        spec = ClusterSpec.coerce(str(p))
        assert (spec.chips, spec.ici_gbps) == (5, 50)
        same = ClusterSpec.coerce(spec)
        assert same is spec

    def test_unknown_field_rejected(self):
        with pytest.raises(ValueError, match="unknown ClusterSpec"):
            ClusterSpec.coerce({"chips": 2, "warp_drive": 9})

    def test_resolve_env(self, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_CLUSTER_SPEC",
                           '{"chips": 16, "hbm_gb": 32}')
        spec = resolve_cluster_spec(chips=4)
        # the actual worker count wins over the remembered chip count
        assert (spec.chips, spec.hbm_gb) == (4, 32)
        # a bare chip count is a documented spec form
        monkeypatch.setenv("PADDLE_TPU_CLUSTER_SPEC", "8")
        assert resolve_cluster_spec().chips == 8
        monkeypatch.delenv("PADDLE_TPU_CLUSTER_SPEC")
        assert resolve_cluster_spec().chips == 1


class TestShardOverrides:
    def test_override_pins_lattice_point(self):
        main, startup, loss, _ = _fresh_mlp()
        w = "mlp.w0"
        base = interpret_program(main, nranks=4)
        assert not base.val(w).sharding.is_sharded
        over = interpret_program(
            main, nranks=4,
            shard_overrides={w: Sharding.sharded("data", 0, 4)})
        assert over.val(w).sharding.is_sharded
        assert over.val(w).local_numel == base.val(w).local_numel // 4

    def test_override_survives_producing_op(self):
        # optimizer writes the param back; the override must still pin
        # the final lattice point (candidate seeding semantics)
        main, startup, loss, _ = _fresh_mlp()
        over = interpret_program(
            main, nranks=4,
            shard_overrides={"mlp.w0": Sharding.sharded("data", 0, 4)})
        assert over.val("mlp.w0").sharding.is_sharded


class TestPricePlan:
    def test_launch_and_ici_accounting(self):
        main, startup, loss, _ = _fresh_mlp()
        report, price = price_program(main, nranks=1,
                                      targets=[loss.name])
        assert price.collective_launches == 0
        assert price.ici_ms == 0
        assert price.step_ms > 0
        # pure launch arithmetic
        p2 = price_plan(report, launch_us=1000.0,
                        collective_launches=3, calibration=1.0)
        assert p2.launch_ms == pytest.approx(3.0)

    def test_schedule_factor_scales_compute(self):
        main, startup, loss, _ = _fresh_mlp()
        report, p1 = price_program(main, nranks=1, calibration=1.0)
        _, p2 = price_program(main, nranks=1, schedule_factor=2.0,
                              calibration=1.0)
        assert p2.compute_ms == pytest.approx(2 * p1.compute_ms)


class TestPlannerMLP:
    CHIPS = 8

    def _plan(self, **kw):
        main, startup, loss, _ = _fresh_mlp()
        return main, auto_transpile(
            main, ClusterSpec(chips=self.CHIPS, **kw),
            startup_program=startup, targets=[loss.name])

    def test_winner_is_feasible_and_proven(self):
        main, res = self._plan()
        assert res.plan.feasible and not res.fallback
        assert res.deadlock_free
        assert res.plan.deadlock == "ok"
        assert len(res.worker_programs) == self.CHIPS \
            or len(res.worker_programs) == res.plan.candidate.stages
        kinds = {pc.candidate.kind for pc in res.candidates}
        assert "dp" in kinds and "pipeline" in kinds

    def test_candidate_table_has_verdicts(self):
        main, res = self._plan()
        table = res.format_table()
        assert "CHOSEN" in table
        for pc in res.candidates:
            assert pc.status  # every row explains itself
        # exactly one chosen
        assert sum(1 for pc in res.candidates if pc.chosen) == 1

    def test_emitted_workers_lint_clean(self):
        main, res = self._plan()
        base_errors = len(_errors(main.lint()))
        for w in res.worker_programs:
            assert len(_errors(w.lint())) <= base_errors

    def test_in_process_determinism_and_tie_stability(self):
        main, res1 = self._plan()
        main2, res2 = self._plan()
        assert res1.to_json() == res2.to_json()
        # the canonical bytes must survive a cached calibration factor
        # (it scales every candidate alike — the plan cannot change)
        from paddle_tpu import autotune

        autotune.record(autotune.sweep_signature("planner", {}),
                        {"calibration": 2.5})
        try:
            _, res3 = self._plan()
            assert res3.plan.price.calibration == 2.5
            assert res3.to_json() == res1.to_json()
        finally:
            autotune.record(autotune.sweep_signature("planner", {}),
                            {"calibration": 1.0})
        # the MLP's grads fit any bucket: the dp bucket variants TIE on
        # step_ms, and the plan_key tie-break must hold stable
        dp = [pc for pc in res1.candidates if pc.candidate.kind == "dp"]
        assert len({pc.price.step_ms for pc in dp}) < len(dp)
        assert res1.plan.candidate.plan_key() \
            == res2.plan.candidate.plan_key()

    def test_hbm_infeasible_falls_back_to_least_memory(self):
        main, res = self._plan(hbm_gb=1e-6)
        assert res.fallback
        assert not res.plan.feasible
        assert res.plan.deadlock == "ok"
        least = min(pc.price.peak_memory_bytes for pc in res.candidates
                    if pc.deadlock != "divergent")
        assert res.plan.price.peak_memory_bytes == least
        assert "least-memory" in res.plan.status

    def test_env_budget_overrides_cluster(self, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_HBM_BUDGET", "1")
        main, res = self._plan()
        assert res.fallback

    def test_planner_beats_hand_dp_and_pipeline_and_moe(self):
        main, res = self._plan()
        spec = ClusterSpec(chips=2)

        # hand DP (the dist_model builder journey)
        workers, _, loss_name = dist_model.build_dp_workers(nranks=2)
        _, hand_dp = price_worker_set(workers, spec,
                                      targets=[loss_name])
        fluid.unique_name.switch()
        m, s, loss, _ = dist_model.build_model()
        res2 = auto_transpile(m, spec, startup_program=s,
                              targets=[loss.name])
        assert res2.plan.price.step_ms <= hand_dp.step_ms * (1 + 1e-9)

        # hand pipeline (2 stages)
        pw, _, ploss = dist_model.build_pipeline_workers()
        _, hand_pipe = price_worker_set(pw, spec, targets=[ploss])
        assert res2.plan.price.step_ms <= hand_pipe.step_ms * (1 + 1e-9)

        # hand MoE replication
        mw, _, mout = dist_model.build_moe_workers(nranks=2)
        _, hand_moe = price_worker_set(mw, spec, targets=[mout])
        fluid.unique_name.switch()
        moe_main = mw[0]
        res3 = auto_transpile(moe_main, spec, targets=[mout])
        assert {pc.candidate.kind for pc in res3.candidates} >= {"moe"}
        assert res3.plan.price.step_ms <= hand_moe.step_ms * (1 + 1e-9)


class TestPlannerExamples:
    """The acceptance sweep: planner plan <= the hand-written DP
    transpile of the same example program, and the emitted workers pass
    lint with zero new ERRORs + the deadlock proof."""

    CHIPS = 8

    @pytest.mark.parametrize("which", ["bert", "resnet", "deepfm"])
    def test_planner_at_most_hand_dp(self, which):
        hand, _hs, loss_name = dist_model.build_example_dp_workers(
            which, nranks=self.CHIPS)
        spec = ClusterSpec(chips=self.CHIPS)
        _, hand_price = price_worker_set([hand], spec,
                                         targets=[loss_name])
        main, startup, loss_name2 = dist_model.build_example_program(
            which)
        res = auto_transpile(main, spec, startup_program=startup,
                             targets=[loss_name2])
        assert res.deadlock_free
        assert res.plan.price.step_ms <= hand_price.step_ms * (1 + 1e-9)
        base_errors = len(_errors(main.lint(targets=[loss_name2])))
        for w in res.worker_programs[:2]:
            assert len(_errors(w.lint())) <= base_errors


@pytest.mark.parametrize("which,chips,budget_s",
                         [("bert_base", 8, 120)])
def test_cross_process_determinism(which, chips, budget_s):
    """Same program + ClusterSpec → byte-identical plan across two
    FRESH processes, unchanged under PADDLE_TPU_AUTOTUNE=0, and (the
    bert_base acceptance bar) the search completes in < 30 s on CPU.
    The planner must also price <= the hand-written DP transpile."""
    import concurrent.futures as cf

    with cf.ThreadPoolExecutor(3) as pool:
        futs = [
            pool.submit(_run_worker, which, chips, env, budget_s)
            for env in (None, None, {"PADDLE_TPU_AUTOTUNE": "0"})
        ]
        a, b, c = [f.result() for f in futs]
    assert a["sha"] == b["sha"] == c["sha"], (a, b, c)
    for r in (a, b, c):
        assert r["deadlock_free"]
        assert r["step_ms"] <= r["hand_dp_step_ms"] * (1 + 1e-9)
        if which == "bert_base":
            assert r["search_s"] < 30, r


class TestPlanCLI:
    def test_plan_flag_prints_candidate_table(self, tmp_path):
        from paddle_tpu.proto import save_program

        main, startup, loss, _ = _fresh_mlp()
        prog_path = tmp_path / "prog.json"
        save_program(main, str(prog_path))
        env = {**os.environ, "JAX_PLATFORMS": "cpu",
               "PYTHONPATH": REPO}
        res = subprocess.run(
            [sys.executable, "-m", "paddle_tpu.tools.analyze_program",
             "--program-json", str(prog_path),
             "--plan", '{"chips": 2}'],
            capture_output=True, text=True, timeout=120, env=env,
            cwd=REPO)
        assert res.returncode == 0, res.stderr[-2000:]
        assert "auto-parallelism plan" in res.stdout
        assert "CHOSEN" in res.stdout

    def test_plan_flag_json(self, tmp_path):
        from paddle_tpu.proto import save_program

        main, startup, loss, _ = _fresh_mlp()
        prog_path = tmp_path / "prog.json"
        save_program(main, str(prog_path))
        env = {**os.environ, "JAX_PLATFORMS": "cpu",
               "PYTHONPATH": REPO}
        res = subprocess.run(
            [sys.executable, "-m", "paddle_tpu.tools.analyze_program",
             "--program-json", str(prog_path),
             "--plan", "2", "--json"],
            capture_output=True, text=True, timeout=120, env=env,
            cwd=REPO)
        assert res.returncode == 0, res.stderr[-2000:]
        payload = json.loads(res.stdout)
        assert payload["plan"]["plan"]["candidate"]["kind"]
        assert payload["plan"]["candidates"]

    def test_bad_spec_exits_2(self, tmp_path):
        from paddle_tpu.proto import save_program

        main, startup, loss, _ = _fresh_mlp()
        prog_path = tmp_path / "prog.json"
        save_program(main, str(prog_path))
        env = {**os.environ, "JAX_PLATFORMS": "cpu",
               "PYTHONPATH": REPO}
        res = subprocess.run(
            [sys.executable, "-m", "paddle_tpu.tools.analyze_program",
             "--program-json", str(prog_path),
             "--plan", '{"warp": 1}'],
            capture_output=True, text=True, timeout=120, env=env,
            cwd=REPO)
        assert res.returncode == 2


class TestManualPlanAdvisory:
    def _manual_dp(self):
        workers, _, loss_name = dist_model.build_dp_workers(nranks=2)
        return workers[0], loss_name

    def test_silent_without_cluster_spec(self, monkeypatch):
        monkeypatch.delenv("PADDLE_TPU_CLUSTER_SPEC", raising=False)
        prog, loss_name = self._manual_dp()
        diags = prog.lint(targets=[loss_name])
        assert not [d for d in diags
                    if d.check == "manual-plan-suboptimal"]

    def test_fires_when_manual_plan_prices_worse(self):
        prog, loss_name = self._manual_dp()
        # near-zero ICI bandwidth makes per-grad allreduce DP terrible;
        # the planner's pipeline plan wins by >15%
        prog._cluster_spec = {"chips": 2, "ici_gbps": 1e-6}
        hits = [d for d in prog.lint(targets=[loss_name])
                if d.check == "manual-plan-suboptimal"]
        assert len(hits) == 1
        assert hits[0].severity == Severity.INFO
        assert "planner's best" in hits[0].message
        assert "%" in hits[0].message

    def test_silent_on_planner_emitted_program(self):
        main, startup, loss, _ = _fresh_mlp()
        res = auto_transpile(main, ClusterSpec(chips=2),
                             startup_program=startup,
                             targets=[loss.name])
        w = res.worker_programs[0]
        w._cluster_spec = {"chips": 2, "ici_gbps": 1e-6}
        assert not [d for d in w.lint()
                    if d.check == "manual-plan-suboptimal"]

    def test_bad_spec_warns(self):
        prog, loss_name = self._manual_dp()
        prog._cluster_spec = "/nonexistent/spec.json"
        hits = [d for d in prog.lint(targets=[loss_name])
                if d.check == "manual-plan-suboptimal"]
        assert len(hits) == 1
        assert hits[0].severity == Severity.WARNING


class TestAutoRouting:
    def test_distribute_transpiler_auto_mode(self):
        from paddle_tpu.transpiler import (DistributeTranspiler,
                                           DistributeTranspilerConfig)

        fluid.unique_name.switch()
        main, startup, loss, _ = dist_model.build_model()
        cfg = DistributeTranspilerConfig()
        cfg.mode = "auto"
        DistributeTranspiler(cfg).transpile(
            trainer_id=1, program=main, trainers=4,
            startup_program=startup)
        assert main._num_trainers == 4
        res = main._auto_plan
        assert res.plan.chosen
        if res.plan.candidate.kind == "dp":
            ars = [op for op in main.global_block().ops
                   if op.type == "c_allreduce_sum"]
            assert ars, "dp winner must be applied in place"

    def test_fleet_strategy_auto_attr(self):
        from paddle_tpu.incubate.fleet.collective import (
            DistributedStrategy)

        s = DistributedStrategy()
        assert s.auto is False
        s.auto = True  # the knob exists and is assignable

    def test_apply_plan_realizes_every_priced_knob(self, monkeypatch):
        """A dp winner chosen FOR its zero1/bucket numbers must not run
        without them: apply_plan stamps _shard_optimizer_state (the
        SPMD runner honors it) and sets the allreduce bucket env the
        fusion pass reads."""
        from paddle_tpu.parallel import SPMDRunner
        from paddle_tpu.parallel.planner import apply_plan

        # setenv (not delenv) so monkeypatch restores the pre-test
        # state even though apply_plan overwrites the value mid-test
        monkeypatch.setenv("PADDLE_TPU_ALLREDUCE_BUCKET_MB", "")
        fluid.unique_name.switch()
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.layers.data("x", shape=[8], dtype="float32")
            y = fluid.layers.data("y", shape=[1], dtype="float32")
            h = fluid.layers.fc(x, size=16, act="relu")
            pred = fluid.layers.fc(h, size=1)
            loss = fluid.layers.mean(
                fluid.layers.square_error_cost(pred, y))
            fluid.optimizer.Adam(learning_rate=0.01).minimize(loss)
        res = auto_transpile(main, ClusterSpec(chips=4),
                             startup_program=startup,
                             targets=[loss.name])
        cand = res.plan.candidate
        if cand.kind != "dp":
            pytest.skip("winner is %s — in-place apply N/A"
                        % cand.kind)
        applied = apply_plan(main, res, startup_program=startup)
        assert applied
        assert main._auto_plan is res
        assert main._shard_optimizer_state == cand.zero1
        if cand.bucket_mb:
            # program-scoped, not a process-global env mutation
            assert main._allreduce_bucket_mb == cand.bucket_mb
            assert not os.environ.get("PADDLE_TPU_ALLREDUCE_BUCKET_MB")
            from paddle_tpu.static_analysis.fusion import (
                allreduce_bucket_mb)

            assert allreduce_bucket_mb(main) == cand.bucket_mb
        # the SPMD runner picks the stamp up without a BuildStrategy
        runner = SPMDRunner(main, None, data_parallel=False)
        assert runner.shard_opt_state == cand.zero1

    def test_apply_plan_non_dp_winner_still_syncs_gradients(self):
        """A pipeline winner cannot be expressed in one worker's
        program; leaving it untranspiled would train N workers with NO
        gradient exchange.  apply_plan must fall back to a dp-family
        apply (warning) so the in-place journey is never silently
        divergent."""
        import warnings

        from paddle_tpu.parallel.planner import apply_plan

        fluid.unique_name.switch()
        main, startup, loss, _ = dist_model.build_model()
        # near-zero ICI bandwidth makes the pipeline candidate win
        res = auto_transpile(main, ClusterSpec(chips=2, ici_gbps=1e-6),
                             startup_program=startup,
                             targets=[loss.name])
        assert res.plan.candidate.kind == "pipeline"
        with warnings.catch_warnings(record=True) as caught:
            warnings.simplefilter("always")
            applied = apply_plan(main, res, startup_program=startup)
        assert applied.kind in ("dp", "single")
        assert any("cannot be applied in place" in str(w.message)
                   for w in caught)
        ars = [op for op in main.global_block().ops
               if op.type == "c_allreduce_sum"]
        assert ars, "fallback apply must insert the gradient sync"
        assert main._auto_plan is res

    def test_apply_plan_fallback_prefers_feasible_dp(self):
        """When pipeline wins BECAUSE dp is over budget, the in-place
        stand-in must be the least-memory dp — applying the cheaper
        over-budget dp would OOM exactly as the table predicted."""
        import warnings

        from paddle_tpu.parallel.planner import apply_plan

        fluid.unique_name.switch()
        main, startup, loss, _ = dist_model.build_model()
        res = auto_transpile(
            main, ClusterSpec(chips=2, ici_gbps=1e-6, hbm_gb=1e-6),
            startup_program=startup, targets=[loss.name])
        assert res.fallback
        dp_pcs = [pc for pc in res.candidates
                  if pc.candidate.kind == "dp"]
        assert dp_pcs and not any(pc.feasible for pc in dp_pcs)
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            applied = apply_plan(main, res, startup_program=startup)
        if res.plan.candidate.kind in ("dp", "single"):
            assert applied is res.plan.candidate
        else:
            least = min(dp_pcs,
                        key=lambda pc: (pc.price.peak_memory_bytes,
                                        pc.candidate.plan_key()))
            assert applied is least.candidate

    def test_zero1_charged_for_param_allgather(self):
        """ZeRO-1 must not be a modeled free win: its price carries the
        param-allgather ICI plain dp does not pay."""
        fluid.unique_name.switch()
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.layers.data("x", shape=[8], dtype="float32")
            y = fluid.layers.data("y", shape=[1], dtype="float32")
            pred = fluid.layers.fc(x, size=1)
            loss = fluid.layers.mean(
                fluid.layers.square_error_cost(pred, y))
            fluid.optimizer.Adam(learning_rate=0.01).minimize(loss)
        res = auto_transpile(main, ClusterSpec(chips=4),
                             startup_program=startup,
                             targets=[loss.name])
        by_kind = {}
        for pc in res.candidates:
            c = pc.candidate
            if c.kind == "dp" and c.bucket_mb == 8:
                by_kind[c.zero1] = pc
        assert by_kind[True].price.ici_bytes \
            > by_kind[False].price.ici_bytes
        assert by_kind[True].price.peak_memory_bytes \
            < by_kind[False].price.peak_memory_bytes

    def test_emitted_workers_keep_optimizer_state_marks(self):
        """Program.clone() must preserve _is_optimizer_state — the
        executor's ZeRO-1 path gates on the mark, so an emitted
        dp+zero1 worker that lost it would silently not shard its
        optimizer state (exactly on the cluster where only zero1 fit
        the budget)."""
        fluid.unique_name.switch()
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.layers.data("x", shape=[8], dtype="float32")
            y = fluid.layers.data("y", shape=[1], dtype="float32")
            pred = fluid.layers.fc(x, size=4)
            loss = fluid.layers.mean(
                fluid.layers.square_error_cost(pred, y))
            fluid.optimizer.Adam(learning_rate=0.01).minimize(loss)

        def marked(prog):
            return {n for b in prog.blocks for n, v in b.vars.items()
                    if getattr(v, "_is_optimizer_state", False)}

        assert marked(main), "Adam must mark its accumulators"
        assert marked(main.clone()) == marked(main)
        res = auto_transpile(main, ClusterSpec(chips=4),
                             startup_program=startup,
                             targets=[loss.name])
        for w in res.worker_programs[:1]:
            assert marked(w) == marked(main)


@pytest.mark.slow
class TestPlannerAcceptanceFull:
    """The full-size acceptance arm (hw_suite / manual runs): resnet50
    imagenet and a BERT_BASE plan against their hand DP transpiles."""

    def test_resnet50_and_deepfm_full(self):
        from paddle_tpu.models import ctr, resnet
        from paddle_tpu.transpiler.collective import GradAllReduce

        spec = ClusterSpec(chips=8)
        fluid.unique_name.switch()
        main, startup, _f, loss, _a = resnet.build(dataset="imagenet",
                                                   depth=50)
        hand = main.clone()
        hstartup = startup.clone()
        GradAllReduce().transpile(program=hand,
                                  startup_program=hstartup,
                                  rank=0, nranks=8)
        hand._num_trainers = 8
        _, hand_price = price_worker_set([hand], spec,
                                         targets=[loss.name])
        res = auto_transpile(main, spec, startup_program=startup,
                             targets=[loss.name])
        assert res.deadlock_free
        assert res.plan.price.step_ms <= hand_price.step_ms * (1 + 1e-9)

        fluid.unique_name.switch()
        main, startup, _f, loss, _p = ctr.build(
            model="deepfm", num_slots=8, slot_len=4, vocab=100000)
        hand = main.clone()
        hstartup = startup.clone()
        GradAllReduce().transpile(program=hand,
                                  startup_program=hstartup,
                                  rank=0, nranks=8)
        hand._num_trainers = 8
        _, hand_price = price_worker_set([hand], spec,
                                         targets=[loss.name])
        res = auto_transpile(main, spec, startup_program=startup,
                             targets=[loss.name])
        assert res.deadlock_free
        assert res.plan.price.step_ms <= hand_price.step_ms * (1 + 1e-9)
