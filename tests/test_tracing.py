"""Distributed tracing (ISSUE 13): span round-trip + torn-write
tolerance, the ``PADDLE_TPU_TRACING=0`` kill switch, context
propagation in-thread / cross-thread (the ``run_batches`` prefetch
worker) / cross-process (traceparent env), critical-path attribution
and the ``tools.trace`` CLI contract, and the flight recorder firing on
a dispatcher crash.  The full multi-process elastic drill (ONE trace
across victim + survivors) is the slow-marked acceptance test.
"""

import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import paddle_tpu as fluid
import paddle_tpu.observability as obs
from paddle_tpu import serving
from paddle_tpu.executor import Scope, scope_guard
from paddle_tpu.inference import AnalysisConfig, AnalysisPredictor
from paddle_tpu.observability import journal as oj
from paddle_tpu.observability import tracing as tr
from paddle_tpu.tools import trace as trace_cli

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _fresh(monkeypatch):
    fluid.unique_name.switch()
    for var in ("PADDLE_TPU_TELEMETRY", "PADDLE_TPU_TELEMETRY_DIR",
                "PADDLE_TPU_TELEMETRY_FLUSH", "PADDLE_TPU_TRACING",
                "PADDLE_TPU_TRACEPARENT", "PADDLE_TPU_TRACE_RING"):
        monkeypatch.delenv(var, raising=False)
    obs.reset_telemetry()
    yield
    obs.reset_telemetry()


def _trace_dir(monkeypatch, tmp_path, flush=1):
    tdir = tmp_path / "telemetry"
    monkeypatch.setenv("PADDLE_TPU_TELEMETRY_DIR", str(tdir))
    monkeypatch.setenv("PADDLE_TPU_TELEMETRY_FLUSH", str(flush))
    obs.reset_telemetry()
    return str(tdir)


# ---------------------------------------------------------------------------
# span model: ids, round-trip, torn lines, kill switch
# ---------------------------------------------------------------------------
class TestSpanModel:
    def test_round_trip_parent_child(self, tmp_path, monkeypatch):
        tdir = _trace_dir(monkeypatch, tmp_path)
        with tr.span("outer", step=3) as outer:
            with tr.span("inner") as inner:
                inner.set_attr("rows", 8)
        tr.get_tracer().flush()
        recs = tr.read_traces(tdir)
        by_name = {r["name"]: r for r in recs}
        assert set(by_name) == {"outer", "inner"}
        o, i = by_name["outer"], by_name["inner"]
        assert i["trace"] == o["trace"] == outer.trace_id
        assert i["parent"] == o["span"]
        assert o["parent"] is None
        assert i["attrs"]["rows"] == 8 and o["attrs"]["step"] == 3
        assert o["status"] == i["status"] == "ok"
        assert o["dur_ms"] >= i["dur_ms"] >= 0
        assert o["pid"] == os.getpid()

    def test_error_status_flushes_urgently(self, tmp_path, monkeypatch):
        tdir = _trace_dir(monkeypatch, tmp_path, flush=1000)
        with pytest.raises(ValueError):
            with tr.span("doomed"):
                raise ValueError("boom")
        # no explicit flush: the error terminal must already be on disk
        recs = tr.read_traces(tdir)
        assert recs and recs[0]["status"] == "error:ValueError"

    def test_torn_trailing_line_is_skipped(self, tmp_path, monkeypatch):
        tdir = _trace_dir(monkeypatch, tmp_path)
        with tr.span("kept"):
            pass
        tr.get_tracer().flush()
        path = tr.get_tracer().path
        with open(path, "a") as f:
            f.write('{"schema": 1, "kind": "span", "trunc')  # SIGKILL
        recs = tr.read_traces(tdir)
        assert [r["name"] for r in recs] == ["kept"]
        # future-schema records are skipped too, never raised
        with open(path, "a") as f:
            f.write(json.dumps({"schema": 99, "span": "x",
                                "name": "future"}) + "\n")
        assert [r["name"] for r in tr.read_traces(tdir)] == ["kept"]

    def test_kill_switch_zero_growth(self, tmp_path, monkeypatch):
        tdir = _trace_dir(monkeypatch, tmp_path)
        monkeypatch.setenv("PADDLE_TPU_TRACING", "0")
        obs.reset_telemetry()
        s = tr.span("invisible", big=1)
        assert s is tr.NULL_SPAN and not s.recording
        with s:
            assert tr.current_span() is None
            assert tr.current_traceparent() is None
        s.end("never")
        assert len(tr.get_tracer()) == 0
        tr.get_tracer().flush()
        assert not [n for n in os.listdir(tdir)
                    if n.startswith("trace-")]
        # flight dump is a no-op when killed, never a second failure
        assert tr.flight_dump("whatever") is None

    def test_traceparent_round_trip_and_tolerance(self):
        ctx = tr.new_trace_context()
        assert tr.parse_traceparent(tr.format_traceparent(ctx)) == ctx
        for bad in (None, "", "nope", "00-zz-yy-01", "00--01", 42):
            assert tr.parse_traceparent(bad) is None

    def test_ring_is_bounded(self, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_TRACE_RING", "4")
        obs.reset_telemetry()
        for i in range(10):
            tr.span("s%d" % i).end()
        assert len(tr.get_tracer()) == 4


# ---------------------------------------------------------------------------
# context propagation: threads and processes
# ---------------------------------------------------------------------------
class TestPropagation:
    def test_capture_use_context_across_thread(self):
        got = {}
        with tr.span("root") as root:
            ctx = tr.capture_context()

            def worker():
                with tr.use_context(ctx):
                    with tr.span("child") as c:
                        got["trace"] = c.trace_id
                        got["parent"] = c.parent_id

            t = threading.Thread(target=worker)
            t.start()
            t.join()
        assert got["trace"] == root.trace_id
        assert got["parent"] == root.span_id

    def test_remote_parent_from_env(self, monkeypatch):
        ctx = tr.new_trace_context()
        monkeypatch.setenv(tr.TRACEPARENT_ENV, tr.format_traceparent(ctx))
        obs.reset_telemetry()
        with tr.span("adopted") as s:
            assert s.trace_id == ctx.trace_id
            assert s.parent_id == ctx.span_id

    def test_run_batches_prefetch_thread_joins_trace(self, tmp_path):
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.layers.data(name="x", shape=[4], dtype="float32")
            out = fluid.layers.fc(x, size=3)
        exe = fluid.Executor(fluid.CPUPlace())
        with scope_guard(Scope()):
            exe.run(startup)
            fluid.io.save_inference_model(
                str(tmp_path / "m"), ["x"], [out], exe, main_program=main)
        pred = AnalysisPredictor(
            AnalysisConfig(model_dir=str(tmp_path / "m")))
        rng = np.random.RandomState(0)
        feeds = [{"x": rng.standard_normal((2, 4)).astype("float32")}
                 for _ in range(4)]
        with tr.span("client") as root:
            results = list(pred.run_batches(feeds, max_in_flight=2))
        assert len(results) == 4
        recs = tr.get_tracer().records()
        pf = [r for r in recs if r["name"] == "pipeline.prefetch"]
        assert pf, "prefetch thread emitted no span"
        # the prefetch worker runs on its own thread yet joins the
        # caller's trace — that's the cross-thread propagation contract
        assert pf[0]["trace"] == root.trace_id
        assert pf[0]["thread"] != root.thread
        assert pf[0]["attrs"]["items"] == 4

    def test_cross_process_env_propagation(self, tmp_path, monkeypatch):
        tdir = _trace_dir(monkeypatch, tmp_path)
        with tr.span("parent-proc") as root:
            env = dict(os.environ)
            env.update({"JAX_PLATFORMS": "cpu", "PYTHONPATH": REPO,
                        "PADDLE_TPU_TELEMETRY_DIR": tdir,
                        "PADDLE_TPU_TELEMETRY_FLUSH": "1"})
            tr.inject_env(env)
            assert env[tr.TRACEPARENT_ENV] == root.traceparent
            res = subprocess.run(
                [sys.executable, "-c",
                 "from paddle_tpu.observability import tracing as t\n"
                 "t.span('child-proc').end()\n"
                 "t.get_tracer().flush()"],
                capture_output=True, text=True, timeout=120, env=env,
                cwd=REPO)
        assert res.returncode == 0, res.stderr[-800:]
        tr.get_tracer().flush()
        recs = [r for r in tr.read_traces(tdir)
                if r["trace"] == root.trace_id]
        names = {r["name"] for r in recs}
        assert names == {"parent-proc", "child-proc"}
        pids = {r["pid"] for r in recs}
        assert len(pids) == 2, "expected two processes in one trace"


# ---------------------------------------------------------------------------
# critical path + the tools.trace CLI
# ---------------------------------------------------------------------------
def _synthetic_request(trace="t" * 32, base=1000.0, rank=0):
    """A serving.request tree with a known critical path:
    2ms queue + (1ms pad inside 2ms batch) + 4ms device + 2ms sync."""

    def rec(name, span, parent, ts, dur_ms, **attrs):
        r = {"schema": 1, "kind": "span", "ts": base + ts, "rank": rank,
             "pid": 1, "thread": "main", "trace": trace, "span": span,
             "parent": parent, "name": name, "dur_ms": dur_ms,
             "status": "ok"}
        if attrs:
            r["attrs"] = attrs
        return r

    return [
        rec("serving.request", "a1", None, 0.0, 10.0),
        rec("serving.queue_wait", "a2", "a1", 0.0, 2.0),
        rec("serving.batch", "a3", "a1", 0.002, 2.0),
        rec("serving.pad", "a4", "a3", 0.002, 1.0),
        rec("serving.device", "a5", "a1", 0.004, 4.0),
        rec("serving.sync", "a6", "a1", 0.008, 2.0),
    ]


class TestCriticalPath:
    def test_attribution_sums_to_root(self):
        spans = _synthetic_request()
        segments = trace_cli.critical_path(spans)
        contrib = {rec["name"]: ms for rec, ms in segments}
        assert segments[0][0]["name"] == "serving.request"
        assert contrib["serving.queue_wait"] == pytest.approx(2.0, abs=.1)
        assert contrib["serving.pad"] == pytest.approx(1.0, abs=0.1)
        assert contrib["serving.batch"] == pytest.approx(1.0, abs=0.1)
        assert contrib["serving.device"] == pytest.approx(4.0, abs=0.1)
        assert contrib["serving.sync"] == pytest.approx(2.0, abs=0.1)
        total = sum(ms for _, ms in segments)
        assert total == pytest.approx(10.0, abs=0.2)

    def test_open_spans_excluded_and_summary(self):
        spans = _synthetic_request()
        spans.append({"schema": 1, "ts": 1000.0, "trace": "t" * 32,
                      "span": "a7", "parent": "a1", "name": "hung",
                      "dur_ms": None, "status": "ok", "open": True,
                      "rank": 2})
        assert all(rec["name"] != "hung"
                   for rec, _ in trace_cli.critical_path(spans))
        info = trace_cli.trace_summary("t" * 32, spans)
        assert info["root"] == "serving.request"
        assert info["dur_ms"] == 10.0
        assert info["ranks"] == [0, 2]

    def test_serving_stats_and_alert_exit_codes(self, tmp_path, capsys):
        path = tmp_path / "trace-r0-1.jsonl"
        lines = []
        for i in range(3):
            lines.extend(json.dumps(r) for r in _synthetic_request(
                trace=("%032x" % i), base=1000.0 + i))
        path.write_text("\n".join(lines) + "\n")
        stats = trace_cli.serving_stats(
            trace_cli.group_traces(tr.read_traces(str(path))))
        assert stats["requests"] == 3
        assert stats["queue_wait_p99_ms"] == pytest.approx(2.0, abs=0.1)
        assert stats["sync_p99_ms"] == pytest.approx(2.0, abs=0.1)

        rc = trace_cli.main([str(tmp_path), "--serving", "--json"])
        assert rc == 0
        st = json.loads(capsys.readouterr().out)
        assert st["request_p99_ms"] == pytest.approx(10.0, abs=0.1)
        assert trace_cli.main(
            [str(tmp_path), "--serving", "--alert",
             "queue_wait_p99_ms>100"]) == 0
        assert trace_cli.main(
            [str(tmp_path), "--serving", "--alert",
             "queue_wait_p99_ms>1"]) == 1
        assert trace_cli.main(
            [str(tmp_path), "--serving", "--alert",
             "no_such_field>1"]) == 2
        capsys.readouterr()

    def test_id_view_and_chrome_export(self, tmp_path, capsys):
        path = tmp_path / "trace-r0-1.jsonl"
        path.write_text("\n".join(
            json.dumps(r) for r in _synthetic_request()) + "\n")
        out_json = str(tmp_path / "chrome.json")
        rc = trace_cli.main([str(tmp_path), "--id", "tttttttt",
                             "--chrome", out_json])
        assert rc == 0
        out = capsys.readouterr().out
        assert "critical path" in out and "serving.device" in out
        with open(out_json) as f:
            ct = json.load(f)
        xs = [e for e in ct["traceEvents"] if e.get("ph") == "X"]
        assert len(xs) == 6
        assert all(e["pid"] == "rank0" for e in xs)

    def test_empty_dir_exits_2(self, tmp_path, capsys):
        assert trace_cli.main([str(tmp_path)]) == 2
        capsys.readouterr()


# ---------------------------------------------------------------------------
# flight recorder: dispatcher crash postmortem
# ---------------------------------------------------------------------------
class TestFlightRecorder:
    def _save_model(self, dirname):
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.layers.data(name="x", shape=[4], dtype="float32")
            out = fluid.layers.fc(x, size=3)
        exe = fluid.Executor(fluid.CPUPlace())
        with scope_guard(Scope()):
            exe.run(startup)
            fluid.io.save_inference_model(str(dirname), ["x"], [out],
                                          exe, main_program=main)
        return str(dirname)

    def test_dispatcher_crash_dumps_flight_record(
            self, tmp_path, monkeypatch):
        tdir = _trace_dir(monkeypatch, tmp_path)
        pred = AnalysisPredictor(
            AnalysisConfig(model_dir=self._save_model(tmp_path / "m")))
        server = serving.PredictorServer({"t": pred}, buckets=(2,),
                                         auto_start=False)

        def boom():
            raise RuntimeError("scheduler bug")

        monkeypatch.setattr(server, "_pick_batch_locked", boom)
        rng = np.random.RandomState(7)
        feed = {"x": rng.standard_normal((1, 4)).astype("float32")}
        r1 = server.submit("t", feed)
        server.submit("t", feed)
        server.start()
        with pytest.raises(serving.DispatcherCrashedError):
            r1.result(timeout=60)
        server.close()

        flights = tr.read_flight_records(tdir)
        assert flights, "dispatcher crash produced no flight record"
        rec = flights[0]
        assert "dispatcher-died" in rec["reason"]
        assert "scheduler bug" in rec["reason"]
        # the postmortem shows what was in flight WHEN it died: the
        # stranded request spans are captured still open
        open_names = {s["name"] for s in rec["open_spans"]}
        assert "serving.request" in open_names
        # satellite 3: the urgent journal kind carries the trace id so
        # `tools.trace --id` reconstructs the incident chain
        died = [e for e in oj.read_journal(tdir)
                if e["kind"] == "dispatcher-died"]
        assert died and died[0].get("trace") == r1.span.trace_id

    def test_flights_cli_view(self, tmp_path, monkeypatch, capsys):
        tdir = _trace_dir(monkeypatch, tmp_path)
        with tr.span("stuck"):
            tr.flight_dump("synthetic hang")
        rc = trace_cli.main([tdir, "--flights"])
        assert rc == 0
        out = capsys.readouterr().out
        assert "synthetic hang" in out and "OPEN stuck" in out


# ---------------------------------------------------------------------------
# the acceptance drill: ONE trace across victim + survivors (slow)
# ---------------------------------------------------------------------------
@pytest.mark.slow
class TestElasticDrillTrace:
    def test_elastic_drill_is_one_trace(self, tmp_path):
        tdir = str(tmp_path / "telemetry")
        env = dict(os.environ)
        env.update({"JAX_PLATFORMS": "cpu", "PYTHONPATH": REPO})
        for var in ("PADDLE_TPU_FAULT_SPEC", "PADDLE_TPU_TELEMETRY",
                    "PADDLE_TPU_TRACING", "PADDLE_TPU_TRACEPARENT"):
            env.pop(var, None)
        res = subprocess.run(
            [sys.executable, "-m", "paddle_tpu.tools.chaos", "--elastic",
             "--steps", "8", "--ckpt-dir", str(tmp_path / "ckpt"),
             "--telemetry-dir", tdir],
            capture_output=True, text=True, timeout=600, env=env,
            cwd=REPO)
        assert res.returncode == 0, res.stdout[-3000:] + res.stderr[-800:]
        assert "chaos[elastic]: PASS" in res.stdout
        assert "ONE trace" in res.stdout

        out = subprocess.run(
            [sys.executable, "-m", "paddle_tpu.tools.trace",
             "--elastic", tdir, "--json"],
            capture_output=True, text=True, timeout=120, env=env,
            cwd=REPO)
        assert out.returncode == 0, out.stderr[-800:]
        st = json.loads(out.stdout)
        # every rank — victim AND survivors — contributed to the trace
        assert st["ranks"] == [0, 1, 2]
        human = subprocess.run(
            [sys.executable, "-m", "paddle_tpu.tools.trace",
             "--elastic", tdir],
            capture_output=True, text=True, timeout=120, env=env,
            cwd=REPO)
        assert "replan" in human.stdout and "reshard" in human.stdout
