"""SLO-driven autoscaler (ISSUE 17): the pure policy decision function
(hysteresis band, watermark, cooldown, clamps, drift-replan), the
Autoscaler loop's journal/kill-switch/executor contracts, the decode
engine's drain-then-rebuild ``resize``, and the monitor's elastic
surface (world/epoch gauges, pending joins, last autoscale decision,
``--alert 'pending_joins>0'``)."""

import json
import os
import time

import pytest

import paddle_tpu as fluid
import paddle_tpu.observability as obs
from paddle_tpu.observability.journal import read_journal
from paddle_tpu.resilience import elastic
from paddle_tpu.resilience.autoscale import (GROW, NOOP, REPLAN, SHRINK,
                                             Autoscaler, SLOPolicy,
                                             autoscale_enabled)
from paddle_tpu.resilience.watchdog import HeartbeatWriter
from paddle_tpu.serving import DecodeEngine, GenerationConfig
from paddle_tpu.tools import monitor


@pytest.fixture(autouse=True)
def _fresh(monkeypatch):
    fluid.unique_name.switch()
    for var in ("PADDLE_TPU_TELEMETRY", "PADDLE_TPU_TELEMETRY_DIR",
                "PADDLE_TPU_TELEMETRY_FLUSH", "PADDLE_TPU_TRACING",
                "PADDLE_TPU_AUTOSCALE"):
        monkeypatch.delenv(var, raising=False)
    obs.reset_telemetry()
    yield
    obs.reset_telemetry()


def _policy(**kw):
    kw.setdefault("min_world", 1)
    kw.setdefault("max_world", 8)
    kw.setdefault("p99_step_ms", 100.0)
    kw.setdefault("p99_latency_ms", 250.0)
    kw.setdefault("shed_rate", 0.0)
    kw.setdefault("hysteresis", 0.2)
    kw.setdefault("cooldown_s", 0.0)
    return SLOPolicy(**kw)


OVERLOAD = {"p99_step_ms": 400.0, "p99_serving_latency_ms": 900.0,
            "serving_shed_rate": 0.3}
IDLE = {"p99_step_ms": 10.0, "p99_serving_latency_ms": 20.0,
        "serving_shed_rate": 0.0, "serving_queue_depth": 0}


# ---------------------------------------------------------------------------
# the pure decision function
# ---------------------------------------------------------------------------

class TestSLOPolicy:
    def test_bounds_validation(self):
        with pytest.raises(ValueError, match="world bounds"):
            SLOPolicy(min_world=4, max_world=2)
        with pytest.raises(ValueError, match="slot bounds"):
            SLOPolicy(min_slots=0)
        with pytest.raises(ValueError, match="low_watermark"):
            SLOPolicy(low_watermark=1.5)

    def test_overload_grows(self):
        d = _policy().decide(OVERLOAD, world=2)
        assert d.action == GROW and d.target_world == 3
        assert d.evidence["p99_step_ms"] == 400.0
        assert "p99_step_ms" in d.reason

    def test_idle_shrinks(self):
        d = _policy().decide(IDLE, world=3)
        assert d.action == SHRINK and d.target_world == 2

    def test_within_band_is_a_noop(self):
        # above target but inside the +20% hysteresis band: no flap
        d = _policy().decide({"p99_step_ms": 110.0}, world=2)
        assert d.action == NOOP and "within band" in d.reason
        # below target but above the idle watermark: also in-band
        d = _policy().decide({"p99_step_ms": 80.0,
                              "p99_serving_latency_ms": 200.0}, world=2)
        assert d.action == NOOP and d.target_world == 2

    def test_shrink_needs_every_signal_idle(self):
        hot_queue = dict(IDLE, serving_queue_depth=4)
        assert _policy().decide(hot_queue, world=3).action == NOOP
        shedding = dict(IDLE, serving_shed_rate=0.1)
        assert _policy().decide(shedding, world=3).action != SHRINK

    def test_cooldown_blocks_consecutive_actions(self):
        p = _policy(cooldown_s=60.0)
        now = 1000.0
        d = p.decide(OVERLOAD, world=2, now=now, last_action_ts=990.0)
        assert d.action == NOOP and "cooling down" in d.reason
        d = p.decide(OVERLOAD, world=2, now=now, last_action_ts=900.0)
        assert d.action == GROW

    def test_world_clamps(self):
        d = _policy(max_world=2).decide(OVERLOAD, world=2)
        assert d.action == NOOP and "max_world" in d.reason
        d = _policy(min_world=2).decide(IDLE, world=2)
        assert d.action == NOOP and "min_world" in d.reason

    def test_drift_triggers_replan_before_growing(self):
        p = _policy(drift_ratio=2.0)
        status = dict(OVERLOAD, drift={"step_ms": 3.5, "peak_hbm": 0.9})
        d = p.decide(status, world=2)
        assert d.action == REPLAN and d.evidence["drift"] == 3.5
        # drift inside the ratio falls through to the breach logic
        status["drift"] = {"step_ms": 1.1}
        assert p.decide(status, world=2).action == GROW

    def test_missing_signals_never_decide(self):
        # no observations at all: neither overloaded nor idle
        assert _policy().decide({}, world=2).action == NOOP


# ---------------------------------------------------------------------------
# the control loop
# ---------------------------------------------------------------------------

class TestAutoscaler:
    def test_every_decision_is_journaled_with_evidence(
            self, tmp_path, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_TELEMETRY_DIR", str(tmp_path))
        monkeypatch.setenv("PADDLE_TPU_TELEMETRY_FLUSH", "1")
        obs.reset_telemetry()
        scaler = Autoscaler(_policy(), world=2)
        for status, action in ((OVERLOAD, GROW), (IDLE, SHRINK),
                               ({"p99_step_ms": 110.0}, NOOP)):
            d = scaler.poll_once(status=status)
            assert d.action == action
        assert scaler.last_decision.action == NOOP
        events = [e for e in read_journal(str(tmp_path))
                  if e.get("kind") == "autoscale"]
        assert [e["action"] for e in events] == [GROW, SHRINK, NOOP]
        assert events[0]["evidence"]["p99_step_ms"] == 400.0
        assert events[0]["target_world"] == 3
        assert all(e.get("reason") for e in events)

    def test_kill_switch_decides_noop_and_never_actuates(
            self, tmp_path, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_TELEMETRY_DIR", str(tmp_path))
        monkeypatch.setenv("PADDLE_TPU_TELEMETRY_FLUSH", "1")
        monkeypatch.setenv("PADDLE_TPU_AUTOSCALE", "0")
        obs.reset_telemetry()
        assert not autoscale_enabled()

        def _boom(*_a):
            raise AssertionError("disabled loop must not actuate")

        scaler = Autoscaler(_policy(), world=2, launch_worker=_boom,
                            release_worker=_boom)
        assert not scaler.enabled()
        d = scaler.poll_once(status=OVERLOAD)
        assert d.action == NOOP and "disabled" in d.reason
        # a disabled loop leaves no journal trail either
        assert [e for e in read_journal(str(tmp_path))
                if e.get("kind") == "autoscale"] == []

    def test_no_policy_means_disabled(self):
        assert not Autoscaler(None, world=2).enabled()

    def test_executors_receive_count_and_target(self):
        launched, released = [], []
        scaler = Autoscaler(
            _policy(), world=2,
            launch_worker=lambda n, t: launched.append((n, t)),
            release_worker=lambda n, t: released.append((n, t)))
        assert scaler.poll_once(status=OVERLOAD).action == GROW
        assert launched == [(1, 3)] and released == []
        assert scaler.poll_once(status=IDLE).action == SHRINK
        assert released == [(1, 1)]

    def test_acting_arms_the_cooldown(self):
        launched = []
        scaler = Autoscaler(
            _policy(cooldown_s=3600.0), world=2,
            launch_worker=lambda n, t: launched.append((n, t)))
        assert scaler.poll_once(status=OVERLOAD).action == GROW
        d = scaler.poll_once(status=OVERLOAD)
        assert d.action == NOOP and "cooling down" in d.reason
        assert launched == [(1, 3)]

    def test_current_world_reads_the_membership(self, tmp_path):
        d = str(tmp_path)
        elastic._write_once(elastic._member_path(d, 2),
                            {"epoch": 2, "members": [0, 1, 3],
                             "world": 3})
        scaler = Autoscaler(_policy(), hb_dir=d, world=7)
        assert scaler.current_world() == 3  # files beat the static hint
        assert Autoscaler(_policy(), world=7).current_world() == 7


# ---------------------------------------------------------------------------
# DecodeEngine.resize: drain-to-idle, rebuild, resume
# ---------------------------------------------------------------------------

V = 16


class TinyModel:
    """Deterministic next-token = cur + 1 adapter (same contract as the
    decode serving tests) so resized programs stay verifiable."""

    def cache_spec(self):
        return 1, 1, 32, 4

    def _embed(self, ids_f, rows):
        ones = fluid.layers.fill_constant([1, 4], "float32", 1.0)
        x = fluid.layers.reshape(ids_f, [rows, 1])
        return fluid.layers.matmul(x, ones)

    def build_prefill(self, prompt, plen, slot, caches):
        L = prompt.shape[1]
        pf = fluid.layers.cast(prompt, "float32")
        emb = self._embed(fluid.layers.reshape(pf, [L]), L)
        x = fluid.layers.reshape(emb, [1, 1, L, 4])
        k, v = caches[0]
        fluid.layers.kv_cache_prefill(k, x, slot=slot)
        fluid.layers.kv_cache_prefill(v, x, slot=slot)
        idx = fluid.layers.increment(fluid.layers.assign(plen),
                                     value=-1, in_place=True)
        oh = fluid.layers.cast(fluid.layers.one_hot(
            fluid.layers.reshape(idx, [1, 1]), L), "float32")
        last = fluid.layers.reduce_sum(
            fluid.layers.elementwise_mul(pf, oh), dim=[1])
        nxt = fluid.layers.cast(
            fluid.layers.scale(last, scale=1.0, bias=1.0), "int32")
        return fluid.layers.scale(fluid.layers.cast(
            fluid.layers.one_hot(
                fluid.layers.reshape(nxt, [1, 1]), V), "float32"), 10.0)

    def build_step(self, cur, cursors, caches):
        S = cur.shape[0]
        cf = fluid.layers.cast(cur, "float32")
        emb = self._embed(cf, S)
        x = fluid.layers.reshape(emb, [S, 1, 4])
        k, v = caches[0]
        fluid.layers.kv_cache_write(k, x, cursors, per_row=True)
        fluid.layers.kv_cache_write(v, x, cursors, per_row=True)
        att = fluid.layers.flash_decode(x, k, v, cursors, per_row=True)
        zero = fluid.layers.scale(
            fluid.layers.reduce_sum(att, dim=[1, 2]), 0.0)
        nxt = fluid.layers.cast(
            fluid.layers.scale(cf, scale=1.0, bias=1.0), "int32")
        logits = fluid.layers.scale(fluid.layers.cast(
            fluid.layers.one_hot(
                fluid.layers.reshape(nxt, [S, 1]), V), "float32"), 10.0)
        return fluid.layers.elementwise_add(
            logits, fluid.layers.reshape(zero, [S, 1]), axis=0)


def _engine(name="scaler-tiny", slots=2):
    return DecodeEngine(
        TinyModel(), slots=slots, prompt_buckets=(8,),
        config=GenerationConfig(max_new_tokens=4),
        place=fluid.CPUPlace(), name=name)


class TestDecodeResize:
    def test_resize_drains_rebuilds_and_resumes(self):
        with _engine() as eng:
            toks, _ = eng.submit([3, 5]).result(timeout=60)
            assert toks == [6, 7, 8, 9]
            # grow mid-service: drains to idle, rebuilds the slot pool
            assert eng.resize(4) == 4
            assert eng.stats()["slots"] == 4
            rs = [eng.submit([i]) for i in range(1, 5)]
            for i, r in enumerate(rs, start=1):
                toks, _ = r.result(timeout=60)
                assert toks == [i + 1, i + 2, i + 3, i + 4]
            # shrink back below the burst
            assert eng.resize(1) == 1
            toks, _ = eng.submit([7]).result(timeout=60)
            assert toks == [8, 9, 10, 11]

    def test_resize_waits_for_inflight_requests(self):
        with _engine() as eng:
            r = eng.submit([2])
            eng.resize(3)   # must drain r, not strand it
            toks, _ = r.result(timeout=60)
            assert toks == [3, 4, 5, 6]
            assert eng.stats()["slots"] == 3

    def test_resize_validation(self):
        with _engine() as eng:
            with pytest.raises(ValueError):
                eng.resize(0)
            assert eng.resize(2) == 2   # same size: no drain, no-op
        with pytest.raises(RuntimeError, match="closed"):
            eng.resize(3)

    def test_autoscaler_scales_engine_slots(self):
        with _engine() as eng:
            scaler = Autoscaler(_policy(max_slots=3), world=1,
                                engines=[eng])
            d = scaler.poll_once(status=OVERLOAD)
            assert d.action == GROW
            assert eng.slots == 3
            # clamped at max_slots: a further overload can't overshoot
            scaler.poll_once(status=OVERLOAD)
            assert eng.slots == 3
            scaler.poll_once(status=IDLE)
            assert eng.slots == 2
            toks, _ = eng.submit([1]).result(timeout=60)
            assert toks == [2, 3, 4, 5]


# ---------------------------------------------------------------------------
# the monitor's elastic surface
# ---------------------------------------------------------------------------

class TestMonitorElastic:
    def _elastic_dir(self, tmp_path):
        hb = str(tmp_path / "hb")
        os.makedirs(hb)
        elastic._write_once(elastic._member_path(hb, 1),
                            {"epoch": 1, "members": [0, 1], "world": 2})
        return hb

    def test_elastic_fields_and_pending_join_alert(self, tmp_path):
        hb = self._elastic_dir(tmp_path)
        elastic.request_join(hb, 2, 1)
        HeartbeatWriter(hb, 2, interval=60.0).beat()
        status = monitor.collect_status(str(tmp_path), hb_dir=hb)
        assert status["elastic_world_size"] == 2
        assert status["membership_epoch"] == 1
        assert status["pending_joins"] == 1
        code, msg = monitor.check_alert(status, "pending_joins>0")
        assert code == 1 and "TRIPPED" in msg
        code, _msg = monitor.check_alert(status, "elastic_world_size<2")
        assert code == 0
        text = monitor.render_status(status)
        assert "elastic: world=2" in text and "pending_joins=1" in text

    def test_pending_ignores_members_and_the_dead(self, tmp_path):
        hb = self._elastic_dir(tmp_path)
        elastic.request_join(hb, 0, 1)   # already a member
        elastic.request_join(hb, 3, 1)   # posted, then died: no beat
        status = monitor.collect_status(str(tmp_path), hb_dir=hb)
        assert status["pending_joins"] == 0
        code, _ = monitor.check_alert(status, "pending_joins>0")
        assert code == 0

    def test_last_autoscale_decision_surfaces(self, tmp_path):
        hb = self._elastic_dir(tmp_path)
        with open(str(tmp_path / "journal-r0-1.jsonl"), "w") as f:
            for action, ts in (("no-op", 10.0), ("grow", 20.0)):
                f.write(json.dumps(
                    {"schema": 1, "ts": ts, "rank": 0,
                     "kind": "autoscale", "action": action,
                     "reason": "p99 breach", "world": 2,
                     "target_world": 3}) + "\n")
        status = monitor.collect_status(str(tmp_path), hb_dir=hb)
        assert status["autoscale"]["action"] == "grow"
        assert status["autoscale"]["reason"] == "p99 breach"
        text = monitor.render_status(status)
        assert "autoscale: grow (p99 breach)" in text
