"""Serving-layer tests: shape buckets, padded-batch bit-exactness,
continuous batching, SLA shedding, fairness, backpressure, the
multi-tenant placement/zero-sync gates, the bounded content-keyed
FeedCache, telemetry + monitor wiring, and the ``tools.serve`` CLI."""

import json
import time

import numpy as np
import pytest

import paddle_tpu as fluid
import paddle_tpu.observability as obs
import paddle_tpu.observability.metrics as om
from paddle_tpu import pipeline as pl
from paddle_tpu import serving
from paddle_tpu.executor import Scope, scope_guard
from paddle_tpu.framework import Operator
from paddle_tpu.inference import AnalysisConfig, AnalysisPredictor
from paddle_tpu.serving import buckets as bk
from paddle_tpu.static_analysis.verifier import VerifyError


@pytest.fixture(autouse=True)
def _fresh(monkeypatch):
    fluid.unique_name.switch()
    for var in ("PADDLE_TPU_SERVING_BUCKETS",
                "PADDLE_TPU_SERVING_BUCKET_CAP",
                "PADDLE_TPU_FEED_CACHE_CAP",
                "PADDLE_TPU_STRICT_SYNC"):
        monkeypatch.delenv(var, raising=False)
    obs.reset_telemetry()
    yield
    obs.reset_telemetry()


IN_DIM = 6


def _save_model(dirname, seed=0, out_dim=3):
    """Build + save a tiny fc inference model; returns its dir."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[IN_DIM], dtype="float32")
        h = fluid.layers.fc(x, size=8, act="relu")
        out = fluid.layers.fc(h, size=out_dim, act="softmax")
    exe = fluid.Executor(fluid.CPUPlace())
    with scope_guard(Scope()):
        np.random.seed(seed)
        exe.run(startup)
        fluid.io.save_inference_model(str(dirname), ["x"], [out], exe,
                                      main_program=main)
    return str(dirname)


def _predictor(dirname):
    return AnalysisPredictor(AnalysisConfig(model_dir=dirname))


def _rows(rng, n):
    return rng.standard_normal((n, IN_DIM)).astype("float32")


class _DummyPred:
    """Predictor-shaped stub for gate tests (never actually run)."""

    def __init__(self, program, outputs):
        self.program = program
        self._outputs = outputs

    def get_input_names(self):
        return []

    def get_output_names(self):
        return list(self._outputs)

    def run_async(self, feed):  # pragma: no cover - gates fire first
        raise AssertionError("should be gated before any run")


def _named_mlp(prefix, train=False):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(prefix + "_x", shape=[4], dtype="float32")
        h = fluid.layers.fc(
            x, size=4, param_attr=fluid.ParamAttr(name=prefix + ".w"),
            bias_attr=fluid.ParamAttr(name=prefix + ".b"))
        if train:
            loss = fluid.layers.mean(h)
            fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    return main, h.name


# ---------------------------------------------------------------------------
# buckets
# ---------------------------------------------------------------------------

class TestBuckets:
    def test_parse_and_resolve_precedence(self, monkeypatch):
        assert bk.parse_buckets("8,1,4,4") == (1, 4, 8)
        monkeypatch.setenv(bk.BUCKETS_ENV, "2,16")
        assert bk.resolve_buckets() == (2, 16)          # env wins
        assert bk.resolve_buckets(explicit="1,3") == (1, 3)  # arg wins
        monkeypatch.delenv(bk.BUCKETS_ENV)
        assert bk.resolve_buckets() == bk.DEFAULT_BUCKETS

    def test_cap_is_enforced_not_silently_truncated(self, monkeypatch):
        monkeypatch.setenv(bk.BUCKET_CAP_ENV, "2")
        with pytest.raises(ValueError, match="cap"):
            bk.resolve_buckets(explicit="1,2,4")
        assert bk.resolve_buckets(explicit="1,8") == (1, 8)

    def test_derive_pow2_rounds_and_thins_to_cap(self):
        assert bk.derive_buckets([1, 3, 3, 5], cap=8) == (1, 4, 8)
        derived = bk.derive_buckets(range(1, 200), cap=4)
        assert len(derived) == 4
        assert derived[0] == 1 and derived[-1] == 256

    def test_bucket_for_and_padding(self):
        b = bk.ShapeBuckets((2, 4))
        assert b.bucket_for(1) == 2 and b.bucket_for(3) == 4
        assert b.bucket_for(5) is None
        a = np.arange(6, dtype="float32").reshape(3, 2)
        padded = b.pad_rows(a, 3, 4)
        assert padded.shape == (4, 2)
        assert np.array_equal(padded[:3], a)
        assert np.array_equal(padded[3], a[2])  # last row repeated
        outs = b.slice_rows([padded, np.float32(7.0)], 1, 3, 4)
        assert np.array_equal(outs[0], a[1:3])
        assert outs[1] == np.float32(7.0)  # non-batch output broadcast

    def test_parse_rejects_garbage(self):
        with pytest.raises(ValueError):
            bk.parse_buckets("0,2")
        with pytest.raises(ValueError):
            bk.parse_buckets("")

    def test_seq_axis_default_behavior_unchanged(self, monkeypatch):
        monkeypatch.delenv(bk.SEQ_BUCKETS_ENV, raising=False)
        assert bk.resolve_buckets(explicit="1,4") == (1, 4)  # no pair
        b = bk.ShapeBuckets((2, 4))
        assert b.seq_sizes is None
        with pytest.raises(ValueError, match="sequence-length"):
            b.bucket_for_seq(8)

    def test_seq_axis_resolution_and_precedence(self, monkeypatch):
        got = bk.resolve_buckets(explicit="1,4", seq="128,32")
        assert got == ((1, 4), (32, 128))
        monkeypatch.setenv(bk.SEQ_BUCKETS_ENV, "64,256")
        assert bk.resolve_buckets(explicit="1,4") == ((1, 4), (64, 256))
        # explicit seq beats env; observed lengths derive when neither
        assert bk.resolve_buckets(explicit="1", seq="16") == ((1,), (16,))
        monkeypatch.delenv(bk.SEQ_BUCKETS_ENV)
        got = bk.resolve_buckets(explicit="1",
                                 seq_observed=[30, 60, 100])
        assert got == ((1,), (32, 64, 128))

    def test_seq_axis_bucket_for_and_pad(self):
        b = bk.ShapeBuckets((1, 2), seq_sizes=(32, 128))
        assert b.seq_sizes == (32, 128)
        assert b.bucket_for_seq(7) == 32
        assert b.bucket_for_seq(33) == 128
        assert b.bucket_for_seq(129) is None
        ids = np.arange(20, dtype="int32").reshape(2, 10)
        padded = b.pad_seq(ids, 10, 32)
        assert padded.shape == (2, 32)
        assert np.array_equal(padded[:, :10], ids)
        assert (padded[:, 10:] == 0).all()
        assert b.pad_seq(ids, 10, 10) is ids  # no-op when full

    def test_seq_axis_grid_cap_enforced(self, monkeypatch):
        monkeypatch.setenv(bk.BUCKET_CAP_ENV, "2")
        with pytest.raises(ValueError, match="grid"):
            bk.resolve_buckets(explicit="1,2", seq="8,16,32,64,128")


# ---------------------------------------------------------------------------
# padded-bucket bit-exactness (the satellite-3 contract)
# ---------------------------------------------------------------------------

class TestPaddedCorrectness:
    @pytest.mark.parametrize("max_in_flight", [1, 2])
    @pytest.mark.parametrize("fusion", ["0", "1"])
    def test_padded_results_bit_exact_vs_unpadded(
            self, tmp_path, monkeypatch, max_in_flight, fusion):
        monkeypatch.setenv("PADDLE_TPU_FUSION", fusion)
        pred = _predictor(_save_model(tmp_path / "m"))
        rng = np.random.RandomState(0)
        server = serving.PredictorServer(
            {"t": pred}, max_in_flight=max_in_flight, buckets=(4,),
            auto_start=False)
        xs = [_rows(rng, n) for n in (1, 3, 2, 1)]
        reqs = [server.submit("t", {"x": x}) for x in xs]
        server.start()
        for x, r in zip(xs, reqs):
            got = r.result(timeout=60)
            ref = pred.run({"x": x})
            assert got[0].shape == ref[0].shape
            assert np.array_equal(got[0], ref[0])
        server.close()
        # everything was padded into the single bucket of 4
        assert all(b == 4 for _, b, _ in server.dispatch_log)

    def test_coalesced_multi_request_batch_slices_correctly(
            self, tmp_path):
        pred = _predictor(_save_model(tmp_path / "m"))
        rng = np.random.RandomState(1)
        server = serving.PredictorServer(
            {"t": pred}, buckets=(8,), auto_start=False)
        x1, x2 = _rows(rng, 2), _rows(rng, 3)
        r1 = server.submit("t", {"x": x1})
        r2 = server.submit("t", {"x": x2})
        server.start()
        o1, o2 = r1.result(timeout=60), r2.result(timeout=60)
        server.close()
        # both rode one padded batch ...
        assert len(server.dispatch_log) == 1
        assert server.dispatch_log[0] == ("t", 8, 5)
        # ... and each got exactly its own rows back
        assert np.array_equal(o1[0], pred.run({"x": x1})[0])
        assert np.array_equal(o2[0], pred.run({"x": x2})[0])

    def test_jit_cache_bounded_by_bucket_count(self, tmp_path):
        pred = _predictor(_save_model(tmp_path / "m"))
        rng = np.random.RandomState(2)
        server = serving.PredictorServer({"t": pred}, buckets=(1, 2, 4),
                                         auto_start=False)
        server.warmup({"t": {"x": _rows(rng, 1)}})
        warm = len(pred._exe._cache)
        assert warm <= 3
        server.start()
        reqs = [server.submit("t", {"x": _rows(rng, 1 + i % 4)})
                for i in range(12)]
        for r in reqs:
            r.result(timeout=60)
        server.close()
        # mixed row counts never minted a new jit signature
        assert len(pred._exe._cache) == warm


# ---------------------------------------------------------------------------
# scheduling: fairness, SLA shedding, backpressure
# ---------------------------------------------------------------------------

class TestScheduling:
    def test_round_robin_fairness_across_tenants(self, tmp_path):
        pa = _predictor(_save_model(tmp_path / "a", seed=0))
        pb = _predictor(_save_model(tmp_path / "b", seed=1))
        server = serving.PredictorServer({"a": pa, "b": pb},
                                         buckets=(2,), auto_start=False)
        rng = np.random.RandomState(3)
        reqs = [server.submit("a", {"x": _rows(rng, 1)})
                for _ in range(6)]
        reqs += [server.submit("b", {"x": _rows(rng, 1)})
                 for _ in range(2)]
        server.start()
        for r in reqs:
            r.result(timeout=60)
        server.close()
        # b's lone batch is NOT starved behind a's three: round-robin
        # puts it second
        tenants = [t for t, _, _ in server.dispatch_log]
        assert tenants[0] == "a" and tenants[1] == "b"

    def test_sla_shed_and_survivors(self, tmp_path):
        pred = _predictor(_save_model(tmp_path / "m"))
        server = serving.PredictorServer({"t": pred}, buckets=(2,),
                                         auto_start=False)
        rng = np.random.RandomState(4)
        dead = server.submit("t", {"x": _rows(rng, 1)}, sla_ms=-5,
                             request_id="late")
        live = server.submit("t", {"x": _rows(rng, 1)})
        server.start()
        with pytest.raises(serving.DeadlineExceededError,
                           match="late"):
            dead.result(timeout=60)
        assert live.result(timeout=60)[0].shape == (1, 3)
        server.close()
        stats = server.stats()
        assert stats["shed"] == 1 and stats["completed"] == 1
        assert stats["shed_rate"] == 0.5

    def test_backpressure_bounded_queue_rejects(self, tmp_path):
        pred = _predictor(_save_model(tmp_path / "m"))
        server = serving.PredictorServer({"t": pred}, queue_cap=3,
                                         buckets=(4,), auto_start=False)
        rng = np.random.RandomState(5)
        reqs = [server.submit("t", {"x": _rows(rng, 1)})
                for _ in range(3)]
        with pytest.raises(serving.QueueFullError, match="backpressure"):
            server.submit("t", {"x": _rows(rng, 1)})
        server.start()
        for r in reqs:
            r.result(timeout=60)
        server.close()
        assert server.stats()["rejected"] == 1

    def test_submit_after_close_and_unknown_tenant(self, tmp_path):
        pred = _predictor(_save_model(tmp_path / "m"))
        server = serving.PredictorServer({"t": pred}, auto_start=False)
        rng = np.random.RandomState(6)
        with pytest.raises(KeyError):
            server.submit("nope", {"x": _rows(rng, 1)})
        server.close()
        with pytest.raises(serving.ServerClosedError):
            server.submit("t", {"x": _rows(rng, 1)})


# ---------------------------------------------------------------------------
# dispatcher-crash containment
# ---------------------------------------------------------------------------

class TestDispatcherCrash:
    def test_crash_fails_pending_journals_and_poisons_submit(
            self, tmp_path, monkeypatch):
        from paddle_tpu.observability import journal as oj

        tdir = tmp_path / "tel"
        monkeypatch.setenv("PADDLE_TPU_TELEMETRY_DIR", str(tdir))
        obs.reset_telemetry()
        pred = _predictor(_save_model(tmp_path / "m"))
        server = serving.PredictorServer({"t": pred}, buckets=(2,),
                                         auto_start=False)

        def boom():
            raise RuntimeError("scheduler bug")

        # crash OUTSIDE the per-batch guards: the thread itself dies
        monkeypatch.setattr(server, "_pick_batch_locked", boom)
        rng = np.random.RandomState(11)
        r1 = server.submit("t", {"x": _rows(rng, 1)})
        r2 = server.submit("t", {"x": _rows(rng, 1)})
        server.start()
        # blocked clients get a typed verdict, never a silent hang
        with pytest.raises(serving.DispatcherCrashedError,
                           match="scheduler bug"):
            r1.result(timeout=60)
        with pytest.raises(serving.DispatcherCrashedError):
            r2.result(timeout=60)
        # the server stays dead: submit/start raise the same error
        with pytest.raises(serving.DispatcherCrashedError):
            server.submit("t", {"x": _rows(rng, 1)})
        with pytest.raises(serving.DispatcherCrashedError):
            server.start()
        assert server.stats()["failed"] == 2
        # ... and the crash is journaled urgent as dispatcher-died
        died = [e for e in oj.read_journal(str(tdir))
                if e["kind"] == "dispatcher-died"]
        assert died and died[0]["failed_requests"] == 2
        assert "scheduler bug" in died[0]["reason"]
        server.close()

    def test_batch_failure_does_not_kill_the_dispatcher(self, tmp_path):
        pred = _predictor(_save_model(tmp_path / "m"))
        server = serving.PredictorServer({"t": pred}, buckets=(2,),
                                         auto_start=False)
        orig = pred.run_async
        calls = []

        def flaky(feed):
            calls.append(1)
            if len(calls) == 1:
                raise RuntimeError("one bad batch")
            return orig(feed)

        pred.run_async = flaky
        rng = np.random.RandomState(12)
        r1 = server.submit("t", {"x": _rows(rng, 2)})
        server.start()
        with pytest.raises(RuntimeError, match="one bad batch"):
            r1.result(timeout=60)
        # the per-batch guard contained it: the server still serves
        r2 = server.submit("t", {"x": _rows(rng, 1)})
        assert r2.result(timeout=60)[0].shape == (1, 3)
        server.close()


# ---------------------------------------------------------------------------
# enqueue-time validation (satellite 2)
# ---------------------------------------------------------------------------

class TestEnqueueValidation:
    def test_submit_attributes_bad_shape_to_request_id(self, tmp_path):
        pred = _predictor(_save_model(tmp_path / "m"))
        server = serving.PredictorServer({"t": pred}, auto_start=False)
        bad = np.zeros((1, IN_DIM + 2), dtype="float32")
        with pytest.raises(ValueError) as ei:
            server.submit("t", {"x": bad}, request_id="req-7")
        msg = str(ei.value)
        assert "req-7" in msg and "declares" in msg
        server.close()

    def test_submit_rejects_oversized_and_scalar_feeds(self, tmp_path):
        pred = _predictor(_save_model(tmp_path / "m"))
        server = serving.PredictorServer({"t": pred}, buckets=(2,),
                                         auto_start=False)
        rng = np.random.RandomState(7)
        with pytest.raises(ValueError, match="largest bucket"):
            server.submit("t", {"x": _rows(rng, 3)})
        with pytest.raises(ValueError, match="batch dim"):
            server.submit("t", {"x": np.float32(1.0)})
        server.close()

    def test_run_batches_validates_at_enqueue_with_request_ids(
            self, tmp_path):
        pred = _predictor(_save_model(tmp_path / "m"))
        good = [np.zeros((2, IN_DIM), dtype="float32")]
        bad = [np.zeros((2, IN_DIM + 1), dtype="float32")]
        with pytest.raises(ValueError) as ei:
            list(pred.run_batches([good, bad, good], max_in_flight=2,
                                  request_ids=["g1", "b2", "g3"]))
        msg = str(ei.value)
        # attributed to the offending request, with the data-layer
        # declaration — not a raw jit shape error K steps later
        assert "b2" in msg and "declares" in msg

    def test_run_batches_without_ids_names_batch_index(self, tmp_path):
        pred = _predictor(_save_model(tmp_path / "m"))
        bad = [np.zeros((2, IN_DIM + 1), dtype="float32")]
        with pytest.raises(ValueError, match="batch #0"):
            list(pred.run_batches([bad]))


# ---------------------------------------------------------------------------
# construction-time gates
# ---------------------------------------------------------------------------

class TestGates:
    def test_scope_overlap_blocks_placement(self):
        a, a_out = _named_mlp("m", train=True)   # writes m.w / m.b
        b, b_out = _named_mlp("m")               # reads m.w / m.b
        with pytest.raises(VerifyError, match="scope-overlap"):
            serving.PredictorServer(
                {"train": _DummyPred(a, [a_out]),
                 "serve": _DummyPred(b, [b_out])},
                auto_start=False)

    def test_disjoint_tenants_pass_and_record_certificates(self):
        a, a_out = _named_mlp("a")
        b, b_out = _named_mlp("b")
        server = serving.PredictorServer(
            {"a": _DummyPred(a, [a_out]), "b": _DummyPred(b, [b_out])},
            auto_start=False)
        assert server.certificates["a"].ok
        assert server.certificates["b"].ok
        assert a._serving_hot_loop and b._serving_hot_loop
        server.close()

    def test_host_sync_op_blocks_hot_loop(self):
        main, out = _named_mlp("s")
        blk = main.global_block()
        blk.ops.append(Operator(blk, "save", {"X": [out]}, {},
                                {"file_path": "/tmp/x"}))
        with pytest.raises(VerifyError, match="sync"):
            serving.PredictorServer({"s": _DummyPred(main, [out])},
                                    auto_start=False)

    def test_no_verify_skips_gates(self):
        a, a_out = _named_mlp("m", train=True)
        b, b_out = _named_mlp("m")
        server = serving.PredictorServer(
            {"train": _DummyPred(a, [a_out]),
             "serve": _DummyPred(b, [b_out])},
            verify=False, auto_start=False)
        assert server.certificates["train"] is not None
        server.close()


# ---------------------------------------------------------------------------
# FeedCache: bounded LRU + content-shape keying (satellite 1)
# ---------------------------------------------------------------------------

class TestFeedCache:
    def test_content_keyed_hit_on_equal_copy(self):
        cache = pl.FeedCache(cap=4)
        a = np.arange(12, dtype="float32").reshape(3, 4)
        cache.put("x", a, "dev")
        # a fresh array with equal content hits (the serving pattern:
        # per-request arrays are never identical objects)
        assert cache.get("x", a.copy()) == "dev"
        assert cache.get("x", a) == "dev"  # identity fast path

    def test_no_false_hit_on_different_content_or_name(self):
        cache = pl.FeedCache(cap=4)
        a = np.zeros((2, 2), dtype="float32")
        cache.put("x", a, "dev")
        assert cache.get("x", np.ones((2, 2), dtype="float32")) is None
        assert cache.get("y", a.copy()) is None
        assert cache.get("x", np.zeros((4,), dtype="float32")) is None

    def test_fingerprint_collision_cannot_corrupt(self):
        cache = pl.FeedCache(cap=4)
        a = np.zeros((256,), dtype="float32")
        cache.put("x", a, "dev")
        # mutate an element the strided 64-sample fingerprint skips:
        # same key, different content — the full compare must miss
        b = a.copy()
        b[1] = 99.0
        assert cache._key("x", a) == cache._key("x", b)
        assert cache.get("x", b) is None

    def test_lru_eviction_bounded_and_counted(self):
        obs.reset_telemetry()
        cache = pl.FeedCache(cap=2)
        arrs = [np.full((2,), i, dtype="float32") for i in range(3)]
        for i, a in enumerate(arrs):
            cache.put("x", a, "dev%d" % i)
        assert len(cache) == 2
        assert cache.get("x", arrs[0]) is None   # oldest evicted
        assert cache.get("x", arrs[2]) == "dev2"
        assert om.counter("feed_cache_evictions_total").value == 1

    def test_cap_from_env(self, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_FEED_CACHE_CAP", "1")
        cache = pl.FeedCache()
        cache.put("x", np.zeros(2, dtype="float32"), "d0")
        cache.put("x", np.ones(2, dtype="float32"), "d1")
        assert len(cache) == 1

    def test_in_place_mutation_still_misses(self):
        cache = pl.FeedCache(cap=4)
        a = np.arange(8, dtype="float32")
        cache.put("x", a, "dev")
        a += 1.0
        assert cache.get("x", a) is None


# ---------------------------------------------------------------------------
# telemetry + monitor wiring
# ---------------------------------------------------------------------------

class TestServingTelemetry:
    def test_metrics_flow_into_monitor_status_and_alerts(
            self, tmp_path):
        from paddle_tpu.observability.exporters import \
            write_metrics_snapshot
        from paddle_tpu.tools.monitor import check_alert, collect_status

        obs.reset_telemetry()
        pred = _predictor(_save_model(tmp_path / "m"))
        server = serving.PredictorServer({"t": pred}, buckets=(2,),
                                         auto_start=False)
        rng = np.random.RandomState(8)
        reqs = [server.submit("t", {"x": _rows(rng, 1)})
                for _ in range(4)]
        server.start()
        for r in reqs:
            r.result(timeout=60)
        server.close()

        tdir = tmp_path / "telemetry"
        tdir.mkdir()
        write_metrics_snapshot(str(tdir / "metrics-r0-1.json"))
        status = collect_status(str(tdir))
        assert status["serving_requests"] == 4
        assert status["p50_serving_latency_ms"] > 0
        assert status["p99_serving_latency_ms"] >= \
            status["p50_serving_latency_ms"]
        assert status["serving_throughput_qps"] > 0
        assert status["serving_shed_rate"] == 0.0
        code, _ = check_alert(status, "p99_serving_latency_ms>0")
        assert code == 1  # tripped: any positive latency
        code, _ = check_alert(status, "serving_shed_rate>0")
        assert code == 0
        code, _ = check_alert(status, "p99_serving_latency_ms>99999999")
        assert code == 0

    def test_batch_occupancy_and_padding_counters(self, tmp_path):
        obs.reset_telemetry()
        pred = _predictor(_save_model(tmp_path / "m"))
        server = serving.PredictorServer({"t": pred}, buckets=(4,),
                                         auto_start=False)
        rng = np.random.RandomState(9)
        r = server.submit("t", {"x": _rows(rng, 3)})
        server.start()
        r.result(timeout=60)
        server.close()
        assert om.counter("serving_rows_total").value == 3
        assert om.counter("serving_padded_rows_total").value == 1
        assert om.gauge("serving_batch_occupancy").value == 0.75


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------

class TestServeCLI:
    def test_loadgen_json_report(self, tmp_path, capsys):
        from paddle_tpu.tools import serve

        d = _save_model(tmp_path / "m")
        rc = serve.main([d, "--requests", "8", "--qps", "500",
                         "--max-in-flight", "2", "--buckets", "1,2",
                         "--json"])
        assert rc == 0
        report = json.loads(capsys.readouterr().out)
        assert report["completed"] == 8
        assert report["p50_ms"] > 0 and report["p99_ms"] > 0
        assert report["qps"] > 0
        assert report["zero_sync"] == {"default": True}
        assert report["jit_entries"]["default"] <= 2

    def test_certify_zero_sync_preflight(self, tmp_path, capsys):
        from paddle_tpu.tools import serve

        d = _save_model(tmp_path / "m")
        rc = serve.main([d, "--certify-zero-sync"])
        assert rc == 0
        assert "PASS" in capsys.readouterr().out

    def test_two_tenants_named(self, tmp_path, capsys):
        from paddle_tpu.tools import serve

        da = _save_model(tmp_path / "a", seed=0)
        fluid.unique_name.switch()
        db = _save_model(tmp_path / "b", seed=1)
        rc = serve.main(["--tenants", "ta=%s,tb=%s" % (da, db),
                        "--requests", "8", "--qps", "500", "--json"])
        assert rc == 0
        report = json.loads(capsys.readouterr().out)
        assert set(report["tenants"]) == {"ta", "tb"}
        assert report["zero_sync"] == {"ta": True, "tb": True}
