"""Always-on runtime telemetry (ISSUE 9): metrics registry + kill
switch, the schema-versioned step/event journal (round-trip and
torn-write tolerance), predicted-vs-measured drift math, the
Prometheus/JSON exporters against goldens, the `tools/monitor` CLI
exit-code contract, and the chaos-integration acceptance scenario
(fault -> guard-skip -> checkpoint-restore readable from the journal).
"""

import json
import os
import subprocess
import sys
import time

import pytest

import paddle_tpu as fluid
from paddle_tpu import observability as obs
from paddle_tpu.observability import drift as od
from paddle_tpu.observability import exporters as oe
from paddle_tpu.observability import journal as oj
from paddle_tpu.observability import metrics as om
from paddle_tpu.tools import monitor as mon

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_telemetry(monkeypatch):
    """Every test starts with fresh singletons and no telemetry env
    knobs leaking in (or out)."""
    for var in ("PADDLE_TPU_TELEMETRY", "PADDLE_TPU_TELEMETRY_DIR",
                "PADDLE_TPU_TELEMETRY_FLUSH", "PADDLE_TPU_TELEMETRY_RING",
                "PADDLE_TPU_TELEMETRY_STEP_EVERY",
                "PADDLE_TPU_DRIFT_RECORD", "PADDLE_TPU_DRIFT_EVERY",
                "PADDLE_TPU_DRIFT_RECORD_EVERY"):
        monkeypatch.delenv(var, raising=False)
    obs.reset_telemetry()
    yield
    obs.reset_telemetry()


# ---------------------------------------------------------------------------
# metrics registry
# ---------------------------------------------------------------------------
class TestRegistry:
    def test_counter_semantics(self):
        c = om.counter("t_steps_total")
        c.inc()
        c.inc(4)
        assert c.value == 5
        with pytest.raises(ValueError):
            c.inc(-1)
        # get-or-create returns the same instance
        assert om.counter("t_steps_total") is c

    def test_gauge_semantics(self):
        g = om.gauge("t_depth")
        g.set(3.5)
        g.inc()
        g.dec(0.5)
        assert g.value == 4.0

    def test_labels_are_distinct_series(self):
        a = om.counter("t_ring_total", ring="0")
        b = om.counter("t_ring_total", ring="1")
        assert a is not b
        a.inc(2)
        assert b.value == 0
        # label order never matters: keyed on sorted items
        assert om.gauge("t_xy", x="1", y="2") is om.gauge(
            "t_xy", y="2", x="1")

    def test_kind_conflict_is_a_bug_not_an_overwrite(self):
        om.counter("t_conflict")
        with pytest.raises(TypeError):
            om.gauge("t_conflict")

    def test_histogram_buckets_and_percentiles(self):
        h = om.histogram("t_lat_ms", buckets=(1.0, 2.0, 5.0, 10.0))
        for v in (0.5, 1.5, 3.0, 7.0, 100.0):
            h.observe(v)
        d = h.to_dict()
        assert d["count"] == 5 and d["counts"] == [1, 1, 1, 1, 1]
        assert d["min"] == 0.5 and d["max"] == 100.0
        assert abs(d["sum"] - 112.0) < 1e-9
        # percentile interpolates within the bucket, clamps to max
        assert 0.0 < h.percentile(10) <= 1.0
        assert h.percentile(99) <= 100.0
        assert h.percentile(100) == 100.0
        assert om.histogram("t_empty").percentile(50) is None

    def test_kill_switch_shares_one_null_stub(self):
        om.set_telemetry_enabled(False)
        n_before = len(om.registry())
        c = om.counter("t_dead_total")
        assert c is om.NULL_METRIC
        assert om.gauge("t_dead_g") is c is om.histogram("t_dead_h")
        c.inc()
        c.observe(1.0)
        c.set(2.0)
        assert c.value == 0
        # nothing was registered, nothing journaled
        assert len(om.registry()) == n_before
        assert oj.emit("step", step=1) is None

    def test_env_kill_switch(self, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_TELEMETRY", "0")
        om.reset_metrics()  # re-arm the lazy env read
        assert not om.telemetry_enabled()
        monkeypatch.setenv("PADDLE_TPU_TELEMETRY", "1")
        om.set_telemetry_enabled(None)
        assert om.telemetry_enabled()


# ---------------------------------------------------------------------------
# step/event journal
# ---------------------------------------------------------------------------
class TestJournal:
    def test_round_trip(self, tmp_path):
        j = oj.Journal(dirname=str(tmp_path), flush_every=2, rank=3)
        j.emit("plan-chosen", plan="dp2", score=1.5)
        j.emit("step", step=1, wall_ms=2.25)
        j.flush()
        events = oj.read_journal(str(tmp_path))
        assert [e["kind"] for e in events] == ["plan-chosen", "step"]
        assert all(e["schema"] == oj.SCHEMA_VERSION for e in events)
        assert all(e["rank"] == 3 for e in events)
        assert events[0]["plan"] == "dp2"
        assert events[1]["wall_ms"] == 2.25
        # file-or-dir reader: same result via the explicit path
        assert oj.read_journal(j.path) == events

    def test_urgent_kinds_flush_immediately(self, tmp_path):
        j = oj.Journal(dirname=str(tmp_path), flush_every=1000)
        j.emit("step", step=1)
        assert oj.read_journal(str(tmp_path)) == []  # still buffered
        j.emit("fault-injected", fault="nan_grad", step=3)
        kinds = [e["kind"] for e in oj.read_journal(str(tmp_path))]
        assert "fault-injected" in kinds  # crash-critical: on disk now

    def test_torn_and_foreign_lines_are_skipped(self, tmp_path):
        j = oj.Journal(dirname=str(tmp_path), flush_every=1)
        j.emit("checkpoint-saved", step=5)
        with open(j.path, "a") as f:
            f.write('{"kind": "torn", "ts": 9')      # killed mid-write
            f.write("\nnot json at all\n")
            f.write(json.dumps({"no_kind": True}) + "\n")
            f.write(json.dumps({"schema": 99, "kind": "future",
                                "ts": 1.0}) + "\n")  # future writer
        j.emit("resume", step=5)
        events = oj.read_journal(str(tmp_path))
        assert [e["kind"] for e in events] == ["checkpoint-saved",
                                               "resume"]

    def test_ring_is_bounded(self):
        j = oj.Journal(capacity=4)
        for i in range(10):
            j.emit("step", step=i)
        assert len(j) == 4
        assert [e["step"] for e in j.events("step")] == [6, 7, 8, 9]

    def test_read_missing_path_is_empty(self, tmp_path):
        assert oj.read_journal(str(tmp_path / "nope")) == []


# ---------------------------------------------------------------------------
# drift monitor
# ---------------------------------------------------------------------------
class TestDrift:
    def test_ratio_math_and_gauges(self):
        m = od.monitor()
        m.register("prog-a", predicted_step_ms=10.0,
                   predicted_ici_bytes=1000, predicted_peak_bytes=2048)
        m.observe_step(20.0, key="prog-a")
        state = m.get("prog-a")
        assert state.measured_ms_ema == 20.0
        assert state.step_ratio() == 2.0
        g = om.registry().get("drift_ratio", kind="step_ms")
        assert g is not None and g.value == 2.0
        # EMA folds the next sample at alpha=0.1
        m.observe_step(10.0, key="prog-a")
        assert abs(state.measured_ms_ema - 19.0) < 1e-9
        assert abs(g.value - 1.9) < 1e-9
        m.observe_scheduled_ici(500, key="prog-a")
        assert state.ici_ratio() == 0.5
        gi = om.registry().get("drift_ratio", kind="ici_bytes")
        assert gi is not None and gi.value == 0.5
        assert set(state.ratios()) == {"step_ms", "ici_bytes"}

    def test_register_report_prices_golden_program(self):
        from paddle_tpu.static_analysis import analyze_program

        main = fluid.Program()
        startup = fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.layers.data(name="x", shape=[16], dtype="float32")
            y = fluid.layers.fc(input=x, size=8, act="relu")
            loss = fluid.layers.mean(y)
        report = analyze_program(main, targets=[loss], batch_size=4)
        key = od.monitor().register_report(report)
        state = od.monitor().get(key)
        assert state is not None
        assert state.predicted_step_ms > 0
        assert state.predicted_peak_bytes \
            == report.cost.peak_memory_bytes
        m = od.monitor()
        m.observe_step(1.0, key=key)
        r = state.step_ratio()
        assert r is not None and r > 0 and r == 1.0 / state.predicted_step_ms

    def test_calibration_recorded_into_autotune_cache(self, monkeypatch):
        from paddle_tpu.autotune import lookup, sweep_signature

        monkeypatch.setenv("PADDLE_TPU_DRIFT_RECORD", "1")
        od.reset_drift()
        m = od.monitor()
        m.register("prog-cal", predicted_step_ms=10.0)
        for _ in range(od._RECORD_WARMUP_STEPS + 1):
            m.observe_step(20.0, key="prog-cal")
        sig = sweep_signature(od.DRIFT_CALIBRATION_FAMILY,
                              {"program": "prog-cal"})
        hit = lookup(sig)
        assert hit is not None
        assert abs(hit["calibration"] - 2.0) < 0.05
        c = om.registry().get("drift_calibrations_recorded_total")
        assert c is not None and c.value >= 1

    def test_recording_defaults_off_without_telemetry_dir(self):
        from paddle_tpu.autotune import lookup, sweep_signature

        m = od.monitor()
        assert not m.recording_enabled()
        m.register("prog-norec", predicted_step_ms=10.0)
        for _ in range(od._RECORD_WARMUP_STEPS + 1):
            m.observe_step(20.0, key="prog-norec")
        sig = sweep_signature(od.DRIFT_CALIBRATION_FAMILY,
                              {"program": "prog-norec"})
        assert lookup(sig) is None

    def test_drift_events_journal_periodically(self, monkeypatch,
                                               tmp_path):
        monkeypatch.setenv("PADDLE_TPU_TELEMETRY_DIR", str(tmp_path))
        monkeypatch.setenv("PADDLE_TPU_DRIFT_EVERY", "5")
        monkeypatch.setenv("PADDLE_TPU_DRIFT_RECORD", "0")
        obs.reset_telemetry()
        m = od.monitor()
        m.register("prog-j", predicted_step_ms=4.0)
        for _ in range(10):
            m.observe_step(8.0, key="prog-j")
        oj.get_journal().flush()
        drifts = [e for e in oj.read_journal(str(tmp_path))
                  if e["kind"] == "drift"]
        assert len(drifts) == 2
        assert drifts[-1]["ratios"]["step_ms"] == pytest.approx(2.0)


# ---------------------------------------------------------------------------
# exporters
# ---------------------------------------------------------------------------
class TestExporters:
    def _populate(self):
        om.counter("a_total").inc(3)
        om.gauge("g", x="1").set(2.5)
        h = om.histogram("h", buckets=(1.0, 10.0))
        for v in (0.5, 5.0, 50.0):
            h.observe(v)

    def test_prometheus_golden(self):
        self._populate()
        assert oe.export_prometheus() == (
            "# TYPE paddle_tpu_a_total counter\n"
            "paddle_tpu_a_total 3\n"
            "# TYPE paddle_tpu_g gauge\n"
            'paddle_tpu_g{x="1"} 2.5\n'
            "# TYPE paddle_tpu_h histogram\n"
            'paddle_tpu_h_bucket{le="1"} 1\n'
            'paddle_tpu_h_bucket{le="10"} 2\n'
            'paddle_tpu_h_bucket{le="+Inf"} 3\n'
            "paddle_tpu_h_sum 55.5\n"
            "paddle_tpu_h_count 3\n")

    def test_json_export_shape(self):
        self._populate()
        snap = oe.export_json()
        assert snap["schema"] == 1 and snap["pid"] == os.getpid()
        metrics = snap["metrics"]
        assert metrics["a_total"] == {"type": "counter", "value": 3}
        assert metrics['g{x="1"}']["value"] == 2.5
        hist = metrics["h"]
        assert hist["count"] == 3 and hist["counts"] == [1, 1, 1]
        assert hist["p50"] is not None and hist["p99"] <= 50.0

    def test_snapshot_write_is_atomic(self, tmp_path):
        self._populate()
        path = str(tmp_path / "metrics-r0-1.json")
        snap = oe.write_metrics_snapshot(path)
        assert snap is not None
        with open(path) as f:
            on_disk = json.load(f)
        assert on_disk["metrics"] == snap["metrics"]
        assert not [n for n in os.listdir(str(tmp_path))
                    if ".tmp." in n]


# ---------------------------------------------------------------------------
# monitor CLI
# ---------------------------------------------------------------------------
def _fake_run(dirname):
    """Synthesize one rank's telemetry dir: journal with an incident
    story, a metrics snapshot, and heartbeats."""
    j = oj.Journal(dirname=dirname, flush_every=1, rank=0)
    for s in (1, 11, 21, 31, 41):
        j.emit("step", runner="executor", step=s, wall_ms=2.0 + s / 100.0)
    j.emit("fault-injected", fault="nan_grad", step=3)
    j.emit("guard-skip", step=3, consecutive=1)
    j.emit("checkpoint-saved", step=5, duration_ms=4.0, bytes=1024,
           path="ckpt-5")
    j.emit("checkpoint-loaded", step=5, duration_ms=3.0, path="ckpt-5")
    j.emit("resume", step=5, source="ckpt-5")
    j.emit("step", runner="executor", step=50, wall_ms=2.5)
    j.flush()

    om.counter("steps_total", runner="executor").inc(50)
    om.counter("guard_steps_total").inc(50)
    om.counter("guard_skips_total").inc(1)
    h = om.histogram("step_wall_ms", runner="executor")
    for _ in range(49):
        h.observe(2.0)
    h.observe(40.0)
    om.gauge("drift_ratio", kind="step_ms").set(1.25)
    om.gauge("checkpoint_last_save_ts").set(time.time() - 5.0)
    oe.write_metrics_snapshot(
        os.path.join(dirname, "metrics-r0-%d.json" % os.getpid()))

    now = time.time()
    with open(os.path.join(dirname, "hb-0"), "w") as f:
        f.write(json.dumps({"t": now, "rank": 0, "step": 50,
                            "step_ms": 2.5, "step_ts": now}))
    with open(os.path.join(dirname, "hb-1"), "w") as f:  # wedged rank
        f.write(json.dumps({"t": now, "rank": 1, "step": 12,
                            "step_ms": 2.5, "step_ts": now - 300.0}))


class TestMonitor:
    def test_collect_status(self, tmp_path):
        _fake_run(str(tmp_path))
        st = mon.collect_status(str(tmp_path))
        assert st["steps"] == 50
        assert st["p50_step_ms"] is not None
        assert st["p99_step_ms"] > st["p50_step_ms"]
        assert st["skip_rate"] == pytest.approx(0.02)
        assert st["faults"] == 1 and st["restores"] == 1
        assert st["drift"] == {"step_ms": 1.25}
        assert 0 < st["checkpoint_age_s"] < 60
        assert [e["kind"] for e in st["sequence"]] == [
            "fault-injected", "guard-skip", "checkpoint-saved",
            "checkpoint-loaded", "resume"]
        assert st["ranks"]["0"]["alive"] and not st["ranks"]["0"]["wedged"]
        assert st["ranks"]["1"]["wedged"]
        assert st["alive_ranks"] == 2 and st["lost_ranks"] == 0
        # the human rendering mentions the incident tail + wedged rank
        text = mon.render_status(st)
        assert "fault-injected" in text and "WEDGED" in text

    def test_alert_exit_codes(self, tmp_path):
        _fake_run(str(tmp_path))
        st = mon.collect_status(str(tmp_path))
        assert mon.check_alert(st, "p99_step_ms>1000000")[0] == 0
        assert mon.check_alert(st, "faults>=1")[0] == 1
        assert mon.check_alert(st, "no_such_field>1")[0] == 2
        # dotted path and the bare-name alias into drift
        assert mon.check_alert(st, "drift.step_ms>2")[0] == 0
        assert mon.check_alert(st, "step_ms>1.2")[0] == 1
        with pytest.raises(ValueError):
            mon.check_alert(st, "p99 !! 5")

    def test_cli_subprocess_contract(self, tmp_path):
        _fake_run(str(tmp_path))
        env = dict(os.environ)
        env.update({"JAX_PLATFORMS": "cpu", "PYTHONPATH": REPO})

        def run(*extra):
            return subprocess.run(
                [sys.executable, "-m", "paddle_tpu.tools.monitor",
                 str(tmp_path), "--once", "--json"] + list(extra),
                capture_output=True, text=True, timeout=120, env=env,
                cwd=REPO)

        res = run()
        assert res.returncode == 0, res.stderr[-800:]
        st = json.loads(res.stdout)
        assert st["steps"] == 50 and st["faults"] == 1

        assert run("--alert", "p99_step_ms>1000000").returncode == 0
        assert run("--alert", "faults>=1").returncode == 1
        assert run("--alert", "no_such_field>1").returncode == 2

        empty = tmp_path / "empty"
        empty.mkdir()
        res = subprocess.run(
            [sys.executable, "-m", "paddle_tpu.tools.monitor",
             str(empty), "--once"],
            capture_output=True, text=True, timeout=120, env=env,
            cwd=REPO)
        assert res.returncode == 2
        assert "no telemetry" in res.stderr


# ---------------------------------------------------------------------------
# chaos integration — the ISSUE-9 acceptance scenario
# ---------------------------------------------------------------------------
class TestChaosTelemetry:
    def test_chaos_run_yields_readable_incident_story(self, tmp_path):
        """A chaos run with telemetry on produces a journal from which
        the monitor reports the fault -> guard-skip -> restore sequence
        and a finite drift ratio."""
        tdir = str(tmp_path / "telemetry")
        env = dict(os.environ)
        env.update({"JAX_PLATFORMS": "cpu", "PYTHONPATH": REPO})
        env.pop("PADDLE_TPU_FAULT_SPEC", None)
        env.pop("PADDLE_TPU_TELEMETRY", None)
        res = subprocess.run(
            [sys.executable, "-m", "paddle_tpu.tools.chaos",
             "--steps", "9", "--ckpt-dir", str(tmp_path / "ckpt"),
             "--telemetry-dir", tdir,
             "--spec", "nan_grad@step=3;worker_kill@step=7"],
            capture_output=True, text=True, timeout=300, env=env,
            cwd=REPO)
        assert res.returncode == 0, res.stdout[-3000:] + res.stderr[-800:]

        events = oj.read_journal(tdir)
        first = {}
        for e in events:
            first.setdefault(e["kind"], e["ts"])
        assert "fault-injected" in first and "guard-skip" in first \
            and "checkpoint-loaded" in first and "resume" in first
        # the incident reads in causal order from the merged journal
        assert first["fault-injected"] <= first["guard-skip"]
        assert first["guard-skip"] <= first["checkpoint-loaded"]

        out = subprocess.run(
            [sys.executable, "-m", "paddle_tpu.tools.monitor", tdir,
             "--once", "--json"],
            capture_output=True, text=True, timeout=120, env=env,
            cwd=REPO)
        assert out.returncode == 0, out.stderr[-800:]
        st = json.loads(out.stdout)
        assert st["faults"] >= 1
        assert st["guard_skips"] >= 1
        assert st["restores"] >= 1
        kinds = [e["kind"] for e in st["sequence"]]
        assert kinds.index("fault-injected") < kinds.index("guard-skip")
        assert kinds.index("guard-skip") \
            < kinds.index("checkpoint-loaded")
        if st["drift"]:  # registered when the cost model priced the run
            import math

            assert all(math.isfinite(v) and v > 0
                       for v in st["drift"].values())
