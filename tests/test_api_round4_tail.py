"""Round-4 API tail (reference API.spec entries previously absent):
trig/cumsum/uniform_random layers, LoDTensor helpers, Program
serialization methods, DataFeeder decorate_reader/feed_parallel,
contrib basic_lstm/basic_gru + cells, dygraph LR decay objects +
grad-clip module, install_check, recordio multi-file converter."""

import numpy as np

import paddle_tpu as fluid
from paddle_tpu.executor import Scope, scope_guard


def _run(build, feeds, n_out=1):
    fluid.unique_name.switch()
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        outs = build()
        if not isinstance(outs, (list, tuple)):
            outs = [outs]
    exe = fluid.Executor(fluid.CPUPlace())
    with scope_guard(Scope()):
        exe.run(startup)
        vals = exe.run(main, feed=feeds, fetch_list=list(outs))
    return vals[0] if n_out == 1 else vals


def _d(name, arr):
    return fluid.layers.data(name, shape=list(arr.shape),
                             dtype=str(arr.dtype),
                             append_batch_size=False)


def test_trig_and_cumsum_ops():
    x = np.random.RandomState(0).uniform(-0.9, 0.9, (2, 3)).astype(
        "float32")
    for name, ref in (("acos", np.arccos), ("asin", np.arcsin),
                      ("atan", np.arctan)):
        got = _run(lambda: getattr(fluid.layers, name)(_d("x", x)),
                   {"x": x})
        np.testing.assert_allclose(got, ref(x), atol=1e-5)
    got = _run(lambda: fluid.layers.cumsum(_d("x", x), axis=1), {"x": x})
    np.testing.assert_allclose(got, np.cumsum(x, axis=1), atol=1e-5)
    got = _run(lambda: fluid.layers.cumsum(_d("x", x), axis=0,
                                           reverse=True), {"x": x})
    np.testing.assert_allclose(got, np.cumsum(x[::-1], axis=0)[::-1],
                               atol=1e-5)
    u = _run(lambda: fluid.layers.uniform_random([4, 5], min=2.0, max=3.0),
             {})
    assert u.shape == (4, 5) and (u >= 2.0).all() and (u <= 3.0).all()


def test_lod_tensor_helpers():
    data = np.arange(12).reshape(6, 2).astype("float32")
    t = fluid.create_lod_tensor(data, [[4, 2]])
    assert t.lod() == [[0, 4, 6]]
    np.testing.assert_array_equal(np.asarray(t), data)
    pad, lens = t.to_padded()
    assert pad.shape == (2, 4, 2) and lens.tolist() == [4, 2]
    assert (pad[1, 2:] == 0).all()

    r = fluid.create_random_int_lodtensor([[3, 1]], [1], low=5, high=9)
    arr = np.asarray(r)
    assert arr.shape == (4, 1) and (arr >= 5).all() and (arr <= 9).all()

    arr2 = fluid.LoDTensorArray()
    arr2.append(np.ones((2, 2), "float32"))
    assert isinstance(arr2[0], fluid.LoDTensor)


def test_program_string_roundtrip():
    fluid.unique_name.switch()
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[2, 3], append_batch_size=False)
        fluid.layers.softmax(x)
    s = main.to_string()
    clone = fluid.Program.parse_from_string(s)
    assert [op.type for op in clone.global_block().ops] == \
        [op.type for op in main.global_block().ops]


def test_data_feeder_decorate_and_parallel():
    fluid.unique_name.switch()
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[3], dtype="float32")
        y = fluid.layers.data("y", shape=[1], dtype="int64")
    feeder = fluid.DataFeeder(feed_list=[x, y], program=main)

    def reader():
        rng = np.random.RandomState(0)
        for _ in range(3):
            yield [(rng.rand(3).astype("float32"), i) for i in range(8)]

    batches = list(feeder.decorate_reader(reader)())
    assert len(batches) == 3 and batches[0]["x"].shape == (8, 3)
    par = list(feeder.feed_parallel([next(iter(reader()))], num_places=2))
    assert len(par[0]) == 2 and par[0][0]["x"].shape == (4, 3)


def test_contrib_basic_lstm_gru():
    rng = np.random.RandomState(1)
    x = rng.randn(2, 5, 4).astype("float32")
    sl = np.array([5, 3], "int64")

    def build_lstm():
        xv = _d("x", x)
        slv = _d("sl", sl)
        out, h, c = fluid.contrib.basic_lstm(
            xv, None, None, hidden_size=6, num_layers=2,
            sequence_length=slv, bidirectional=True)
        return out

    out = _run(build_lstm, {"x": x, "sl": sl})
    assert out.shape == (2, 5, 12) and np.isfinite(out).all()

    def build_gru():
        xv = _d("x", x)
        out, h = fluid.contrib.basic_gru(xv, None, hidden_size=6)
        return out

    out = _run(build_gru, {"x": x})
    assert out.shape == (2, 5, 6) and np.isfinite(out).all()


def test_basic_lstm_init_state_and_reverse_last():
    """Round-4 review regressions: init_hidden/init_cell must seed the
    cells (not be ignored), and the reverse direction's last state is
    its t=0 output (the op flips reverse outputs back to input order)."""
    rng = np.random.RandomState(3)
    x = rng.randn(2, 4, 3).astype("float32")
    h0 = rng.randn(2, 2, 5).astype("float32")  # [layers*dirs=2, B, H]
    c0 = rng.randn(2, 2, 5).astype("float32")

    def build(with_init):
        xv = _d("x", x)
        hv = _d("h0", h0) if with_init else None
        cv = _d("c0", c0) if with_init else None
        out, lh, lc = fluid.contrib.basic_lstm(
            xv, hv, cv, hidden_size=5, bidirectional=True)
        return out, lh

    feeds = {"x": x, "h0": h0, "c0": c0}
    out_i, lh_i = _run(lambda: build(True), feeds, n_out=2)
    out_z, lh_z = _run(lambda: build(False), {"x": x}, n_out=2)
    # different initial states must change the output
    assert np.abs(out_i - out_z).max() > 1e-4
    # reverse-direction last state == its output at t=0
    np.testing.assert_allclose(lh_i[1], out_i[:, 0, 5:], atol=1e-5)
    # forward-direction last state == its output at t=T-1
    np.testing.assert_allclose(lh_i[0], out_i[:, -1, :5], atol=1e-5)


def test_contrib_cells():
    rng = np.random.RandomState(2)
    xt = rng.randn(3, 4).astype("float32")
    h0 = np.zeros((3, 6), "float32")
    c0 = np.zeros((3, 6), "float32")

    def build():
        cell = fluid.contrib.BasicLSTMUnit("cell", 6)
        h, c = cell(_d("xt", xt), _d("h0", h0), _d("c0", c0))
        gcell = fluid.contrib.BasicGRUUnit("gcell", 6)
        g = gcell(_d("xg", xt), _d("hg", h0))
        return h, c, g

    h, c, g = _run(build, {"xt": xt, "h0": h0, "c0": c0, "xg": xt,
                           "hg": h0}, n_out=3)
    assert h.shape == (3, 6) and c.shape == (3, 6) and g.shape == (3, 6)
    assert np.isfinite(h).all() and np.isfinite(g).all()


def test_dygraph_lr_decays():
    d = fluid.dygraph.PiecewiseDecay([2, 4], [0.1, 0.01, 0.001], begin=0)
    vals = [d.step() for _ in range(5)]
    assert vals == [0.1, 0.1, 0.01, 0.01, 0.001]
    n = fluid.dygraph.NoamDecay(d_model=64, warmup_steps=10)
    v1, v2 = n.step(), n.step()
    assert v2 > v1  # warming up
    p = fluid.dygraph.PolynomialDecay(0.1, 10, end_learning_rate=0.0,
                                      power=1.0)
    assert abs(p.value() - 0.1) < 1e-9
    for _ in range(10):
        p.step()
    assert p.value() < 1e-9

    # a decay drives an eager optimizer: the schedule advances ONCE per
    # minimize and every parameter sees the same step's lr
    from paddle_tpu.dygraph import Linear, guard, to_variable

    with guard():
        model = Linear(3, 1)  # weight AND bias
        decay = fluid.dygraph.ExponentialDecay(0.1, decay_steps=1,
                                               decay_rate=0.5)
        opt = fluid.optimizer.SGD(learning_rate=decay)
        from paddle_tpu.dygraph.varbase import eager_op

        for step, want_lr in ((0, 0.1), (1, 0.05)):
            xv = to_variable(np.ones((2, 3), "float32"))
            loss = eager_op("mean", {"X": [model(xv)]})[0]
            loss.backward()
            w0 = np.asarray(model.weight.value).copy()
            b0 = np.asarray(model.bias.value).copy()
            gw = np.asarray(model.weight._grad).copy()
            gb = np.asarray(model.bias._grad).copy()
            opt.minimize(loss, parameter_list=model.parameters())
            np.testing.assert_allclose(
                w0 - np.asarray(model.weight.value), want_lr * gw,
                rtol=1e-5)
            np.testing.assert_allclose(
                b0 - np.asarray(model.bias.value), want_lr * gb,
                rtol=1e-5)
            for p in model.parameters():
                p._grad = None
    # graph path rejects decay objects with a targeted error
    fluid.unique_name.switch()
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[3], dtype="float32")
        lossv = fluid.layers.mean(fluid.layers.fc(x, size=1))
        import pytest

        with pytest.raises(TypeError, match="dygraph-only"):
            fluid.optimizer.SGD(
                learning_rate=fluid.dygraph.ExponentialDecay(
                    0.1, 1, 0.5)).minimize(lossv)


def test_dygraph_grad_clip_module():
    from paddle_tpu.dygraph import Linear, guard, to_variable

    clip = fluid.dygraph_grad_clip.GradClipByGlobalNorm(1.0)
    with guard():
        model = Linear(4, 1, bias_attr=False)
        opt = fluid.optimizer.SGD(learning_rate=1.0)
        xv = to_variable(np.full((2, 4), 50.0, "float32"))
        out = model(xv)
        from paddle_tpu.dygraph.varbase import eager_op

        loss = eager_op("mean", {"X": [out]})[0]
        loss.backward()
        w0 = np.asarray(model.weight.value).copy()
        opt.minimize(loss, parameter_list=model.parameters(),
                     grad_clip=clip)
        w1 = np.asarray(model.weight.value)
    assert np.sqrt(((w0 - w1) ** 2).sum()) <= 1.0 + 1e-5


def test_install_check_and_misc():
    assert fluid.install_check.run_check() is True
    assert fluid.is_compiled_with_cuda() is False
    assert len(fluid.cuda_pinned_places(2)) == 2
    fluid.memory_optimize(fluid.Program())  # inert shims must accept
    fluid.release_memory(fluid.Program())


def test_recordio_multi_file(tmp_path):
    import paddle_tpu.recordio_writer as rw

    def reader():
        for i in range(10):
            yield (np.full((2,), i, "float32"),)

    paths = rw.convert_reader_to_recordio_files(
        str(tmp_path / "part"), batch_per_file=4, reader_creator=reader)
    assert len(paths) == 3  # 4 + 4 + 2
    back = []
    for p in paths:
        back.extend(list(rw.recordio_reader(p)()))
    assert len(back) == 10
    np.testing.assert_array_equal(back[7][0], np.full((2,), 7, "float32"))
