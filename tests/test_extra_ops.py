"""Oracles for the op-parity batch (ops/extra.py + quant/detection
stragglers)."""

import numpy as np

import paddle_tpu as fluid
from paddle_tpu.executor import Scope, scope_guard
from paddle_tpu.ops import registry
from paddle_tpu.ops.registry import LoweringContext

import jax


def call(op, ins, attrs=None):
    ctx = LoweringContext(base_key=jax.random.key(0), mode="train")
    opdef = registry.get_op_def(op)
    slots = {}
    for slot, v in ins.items():
        slots[slot] = v if isinstance(v, list) else [v]
    out = registry.call_op(opdef, ctx, slots, attrs or {})
    return {k: [np.asarray(x) if x is not None else None for x in v]
            for k, v in out.items()}


def test_simple_losses_and_math():
    rng = np.random.RandomState(0)
    x = rng.randn(4, 1).astype("float32")
    y = (rng.rand(4, 1) > 0.5).astype("float32")
    out = call("hinge_loss", {"Logits": x, "Labels": y})["Loss"][0]
    np.testing.assert_allclose(out, np.maximum(0, 1 - (2 * y - 1) * x),
                               rtol=1e-6)

    out = call("modified_huber_loss", {"X": x, "Y": y})["Out"][0]
    z = (2 * y - 1) * x
    exp = np.where(z >= -1, np.square(np.maximum(0, 1 - z)), -4 * z)
    np.testing.assert_allclose(out, exp, rtol=1e-5)

    a = rng.randn(3, 4).astype("float32")
    np.testing.assert_allclose(call("l1_norm", {"X": a})["Out"][0],
                               np.abs(a).sum(), rtol=1e-6)
    b = rng.randn(3, 4).astype("float32")
    np.testing.assert_allclose(
        call("squared_l2_distance", {"X": a, "Y": b})["Out"][0].ravel(),
        ((a - b) ** 2).sum(1), rtol=1e-5)
    np.testing.assert_allclose(call("minus", {"X": a, "Y": b})["Out"][0],
                               a - b)
    d = call("diag", {"Diagonal": np.array([1., 2., 3.], "float32")})
    np.testing.assert_allclose(d["Out"][0], np.diag([1., 2., 3.]))
    out = call("norm", {"X": a}, {"axis": 1})["Out"][0]
    np.testing.assert_allclose(out, a / np.sqrt((a**2).sum(1, keepdims=True)
                                                + 1e-10), rtol=1e-5)
    cs = call("cos_sim", {"X": a, "Y": b})["Out"][0]
    exp = (a * b).sum(1) / (np.linalg.norm(a, axis=1)
                            * np.linalg.norm(b, axis=1))
    np.testing.assert_allclose(cs.ravel(), exp, rtol=1e-5)

    ce = call("cross_entropy2",
              {"X": np.array([[0.2, 0.8], [0.5, 0.5]], "float32"),
               "Label": np.array([[1], [0]], "int64")})["Y"][0]
    np.testing.assert_allclose(ce.ravel(),
                               [-np.log(0.8), -np.log(0.5)], rtol=1e-5)


def test_conv_shift():
    x = np.array([[1., 2., 3., 4.]], "float32")
    y = np.array([[0., 1., 0.]], "float32")  # identity kernel
    out = call("conv_shift", {"X": x, "Y": y})["Out"][0]
    np.testing.assert_allclose(out, x, rtol=1e-6)


def test_max_pool_with_index_and_unpool_roundtrip():
    rng = np.random.RandomState(1)
    x = rng.randn(2, 3, 4, 4).astype("float32")
    r = call("max_pool2d_with_index", {"X": x},
             {"ksize": [2, 2], "strides": [2, 2]})
    out, mask = r["Out"][0], r["Mask"][0]
    np.testing.assert_allclose(
        out, x.reshape(2, 3, 2, 2, 2, 2).max(axis=(3, 5)))
    # unpool scatters back to original positions
    up = call("unpool", {"X": out, "Indices": mask},
              {"output_size": [4, 4]})["Out"][0]
    assert up.shape == x.shape
    np.testing.assert_allclose(up.max(axis=(2, 3)), out.max(axis=(2, 3)))
    assert (np.count_nonzero(up.reshape(2, 3, -1), axis=2) <= 4).all()


def test_spp_shapes():
    x = np.random.RandomState(2).randn(2, 3, 8, 8).astype("float32")
    out = call("spp", {"X": x}, {"pyramid_height": 2,
                                 "pooling_type": "max"})["Out"][0]
    assert out.shape == (2, 3 * (1 + 4))


def test_interp_ops():
    x = np.arange(16, dtype="float32").reshape(1, 1, 4, 4)
    out = call("nearest_interp", {"X": x},
               {"out_h": 2, "out_w": 2, "align_corners": False})["Out"][0]
    assert out.shape == (1, 1, 2, 2)
    out = call("bilinear_interp", {"X": x},
               {"out_h": 8, "out_w": 8, "align_corners": True})["Out"][0]
    assert out.shape == (1, 1, 8, 8)
    np.testing.assert_allclose(out[0, 0, 0, 0], 0.0, atol=1e-5)
    np.testing.assert_allclose(out[0, 0, -1, -1], 15.0, atol=1e-4)


def test_fused_family():
    rng = np.random.RandomState(3)
    x = rng.randn(3, 4).astype("float32")
    y = rng.randn(3, 4).astype("float32")
    # reference contract: [binary, unary] = Binary(X, Unary(Y))
    r = call("fused_elemwise_activation", {"X": x, "Y": y},
             {"functor_list": ["elementwise_add", "relu"]})
    np.testing.assert_allclose(r["Out"][0], x + np.maximum(y, 0), rtol=1e-6)
    # [unary, binary] = Unary(Binary(X, Y))
    r = call("fused_elemwise_activation", {"X": x, "Y": y},
             {"functor_list": ["relu", "elementwise_add"]})
    np.testing.assert_allclose(r["Out"][0], np.maximum(x + y, 0), rtol=1e-6)

    W = rng.randn(10, 5).astype("float32")
    ids = rng.randint(0, 10, (2, 4)).astype("int64")
    lens = np.array([4, 2], "int64")
    r = call("fused_embedding_seq_pool", {"W": W, "Ids": ids,
                                          "SeqLen": lens})
    exp = np.stack([W[ids[0]].sum(0), W[ids[1, :2]].sum(0)])
    np.testing.assert_allclose(r["Out"][0], exp, rtol=1e-5)

    ws = [rng.randn(4, 6).astype("float32"), rng.randn(6, 2).astype("float32")]
    bs = [np.zeros(6, "float32"), np.zeros(2, "float32")]
    r = call("fusion_repeated_fc_relu", {"X": x, "W": ws, "Bias": bs})
    exp = np.maximum(x @ ws[0], 0) @ ws[1]
    np.testing.assert_allclose(r["Out"][0], exp, rtol=1e-4)

    a = rng.randn(2, 3).astype("float32")
    b = rng.randn(3, 4).astype("float32")
    r = call("fusion_squared_mat_sub", {"X": a, "Y": b}, {"scalar": 0.5})
    exp = 0.5 * ((a @ b) ** 2 - (a ** 2) @ (b ** 2))
    np.testing.assert_allclose(r["Out"][0], exp, rtol=1e-4)

    seqs = [rng.randn(2, 3, 4).astype("float32"),
            rng.randn(2, 5, 4).astype("float32")]
    r = call("fusion_seqpool_concat", {"X": seqs, "SeqLen": []},
             {"pooltype": "SUM"})
    exp = np.concatenate([seqs[0].sum(1), seqs[1].sum(1)], axis=1)
    np.testing.assert_allclose(r["Out"][0], exp, rtol=1e-5)


def test_fc_and_sample_logits():
    rng = np.random.RandomState(4)
    x = rng.randn(3, 4).astype("float32")
    w = rng.randn(4, 5).astype("float32")
    b = rng.randn(5).astype("float32")
    r = call("fc", {"Input": x, "W": w, "Bias": b})
    np.testing.assert_allclose(r["Out"][0], x @ w + b, rtol=1e-5)

    logits = rng.randn(4, 20).astype("float32")
    lab = rng.randint(0, 20, (4, 1)).astype("int64")
    r = call("sample_logits", {"Logits": logits, "Labels": lab},
             {"num_samples": 6})
    assert r["SampledLogits"][0].shape == (4, 7)
    assert (r["Samples"][0][:, 0] == lab[:, 0]).all()


def test_quant_family():
    x = np.array([[0.5, -1.5, 2.0]], "float32")
    q = call("quantize", {"Input": x}, {"Scale": 10.0})["Output"][0]
    np.testing.assert_array_equal(q, [[5, 0, 20]])
    dq = call("dequantize", {"Input": q.astype("float32")},
              {"Scale": 10.0})["Output"][0]
    np.testing.assert_allclose(dq, [[0.5, 0.0, 2.0]], rtol=1e-5)
    rq = call("requantize", {"Input": q.astype("float32")},
              {"Scale_in": 10.0, "Scale_out": 20.0})["Output"][0]
    np.testing.assert_array_equal(rq, [[10, 0, 40]])

    r = call("fake_quantize_range_abs_max",
             {"X": x, "InScale": np.array([3.0], "float32")},
             {"bit_length": 8})
    assert float(r["OutScale"][0][0]) == 3.0
    r = call("moving_average_abs_max_scale",
             {"X": x, "InAccum": np.array([1.0], "float32"),
              "InState": np.array([1.0], "float32")},
             {"moving_rate": 0.9})
    np.testing.assert_allclose(r["OutAccum"][0], [0.9 + 2.0], rtol=1e-5)


def test_group_norm_and_sync_bn_ops():
    rng = np.random.RandomState(5)
    x = rng.randn(2, 4, 3, 3).astype("float32")
    r = call("group_norm", {"X": x}, {"groups": 2, "epsilon": 1e-5})
    y = r["Y"][0]
    xg = x.reshape(2, 2, 2, 3, 3)
    exp = (xg - xg.mean(axis=(2, 3, 4), keepdims=True)) / np.sqrt(
        xg.var(axis=(2, 3, 4), keepdims=True) + 1e-5)
    np.testing.assert_allclose(y, exp.reshape(x.shape), rtol=1e-4,
                               atol=1e-5)

    scale = np.ones(4, "float32")
    bias = np.zeros(4, "float32")
    mean = np.zeros(4, "float32")
    var = np.ones(4, "float32")
    r = call("sync_batch_norm",
             {"X": x, "Scale": scale, "Bias": bias, "Mean": mean,
              "Variance": var},
             {"momentum": 0.9, "epsilon": 1e-5, "is_test": False})
    assert r["Y"][0].shape == x.shape


def test_bipartite_match_and_target_assign():
    dist = np.array([[0.9, 0.1, 0.3],
                     [0.2, 0.8, 0.4]], "float32")  # 2 gt, 3 priors
    r = call("bipartite_match", {"DistMat": dist},
             {"match_type": "per_prediction", "dist_threshold": 0.35})
    idx = r["ColToRowMatchIndices"][0][0]
    np.testing.assert_array_equal(idx[:2], [0, 1])
    assert idx[2] == 1  # per-prediction fills col 2 (best row 1, 0.4>=.35)

    x = np.array([[1., 2.], [3., 4.]], "float32")  # 2 gt entities
    mi = np.array([[0, -1, 1]], "int32")
    r = call("target_assign", {"X": x, "MatchIndices": mi},
             {"mismatch_value": 0})
    out = r["Out"][0]
    np.testing.assert_allclose(out[0, 0], [1., 2.])
    np.testing.assert_allclose(out[0, 1], [0., 0.])
    np.testing.assert_allclose(out[0, 2], [3., 4.])


def test_mine_hard_examples():
    cls_loss = np.array([[0.1, 0.9, 0.5, 0.7]], "float32")
    mi = np.array([[0, -1, -1, -1]], "int32")  # 1 positive, 3 negatives
    r = call("mine_hard_examples",
             {"ClsLoss": cls_loss, "MatchIndices": mi},
             {"neg_pos_ratio": 2.0, "mining_type": "max_negative"})
    neg = r["NegIndices"][0][0]
    # 2 hardest negatives: priors 1 (0.9) and 3 (0.7)
    assert set(neg[neg >= 0].tolist()) == {1, 3}


def test_print_op_passthrough():
    x = np.ones((2, 2), "float32")
    out = call("print", {"In": x}, {"message": "dbg: "})["Out"][0]
    np.testing.assert_allclose(out, x)


def test_max_pool3d_with_index_real_indices():
    rng = np.random.RandomState(6)
    x = rng.randn(1, 2, 4, 4, 4).astype("float32")
    r = call("max_pool3d_with_index", {"X": x},
             {"ksize": [2, 2, 2], "strides": [2, 2, 2]})
    out, mask = r["Out"][0], r["Mask"][0]
    exp = x.reshape(1, 2, 2, 2, 2, 2, 2, 2).max(axis=(3, 5, 7))
    np.testing.assert_allclose(out, exp)
    flat = x.reshape(1, 2, -1)
    picked = np.take_along_axis(flat, mask.reshape(1, 2, -1), axis=2)
    np.testing.assert_allclose(picked.reshape(out.shape), out)


def test_chunk_eval_outside_tag():
    """O tag (chunk_type >= num_types) must not count as a chunk."""
    inf = np.array([[0, 4, 4, 2, 4]], "int64")  # B0, O, O, B1, O
    lab = np.array([[0, 4, 4, 2, 4]], "int64")
    from test_nn_extra_ops import run_layer, _data
    import paddle_tpu as fluid

    p, r, f1, ni, nl, nc = run_layer(
        lambda: fluid.layers.chunk_eval(
            _data("i", inf), _data("l", lab), "IOB", 2),
        {"i": inf, "l": lab}, n_out=6)
    assert int(ni[0]) == 2 and int(nl[0]) == 2 and int(nc[0]) == 2
    np.testing.assert_allclose(f1, 1.0)


def test_bipartite_match_batched():
    dist = np.stack([
        np.array([[0.9, 0.1], [0.2, 0.8]], "float32"),
        np.array([[0.1, 0.9], [0.8, 0.2]], "float32"),
    ])
    r = call("bipartite_match", {"DistMat": dist}, {})
    idx = r["ColToRowMatchIndices"][0]
    np.testing.assert_array_equal(idx[0], [0, 1])
    np.testing.assert_array_equal(idx[1], [1, 0])


def test_mine_hard_examples_quota_capped():
    cls_loss = np.array([[0.1, 0.9, 0.5, 0.7]], "float32")
    mi = np.array([[0, 1, 0, -1]], "int32")  # 3 positives, 1 negative
    r = call("mine_hard_examples",
             {"ClsLoss": cls_loss, "MatchIndices": mi},
             {"neg_pos_ratio": 3.0})
    neg = r["NegIndices"][0][0]
    assert (neg >= 0).sum() == 1 and neg[0] == 3


def test_lod_bridges_roundtrip():
    rng = np.random.RandomState(20)
    x = rng.randn(2, 3, 4).astype("float32")
    lens = np.array([3, 2], "int32")
    ctx = LoweringContext(base_key=jax.random.key(0), mode="train")
    rt = registry.call_op(
        registry.get_op_def("lod_rank_table"), ctx, {"X": [lens]}, {}
    )["Out"][0]
    assert int(np.asarray(rt["order"])[0]) == 0  # longest first
    arr = registry.call_op(
        registry.get_op_def("lod_tensor_to_array"), ctx,
        {"X": [x], "RankTable": [None]}, {})["Out"][0]
    back = registry.call_op(
        registry.get_op_def("array_to_lod_tensor"), ctx,
        {"X": [arr], "RankTable": [None]}, {})["Out"][0]
    np.testing.assert_allclose(np.asarray(back), x)
    reord = registry.call_op(
        registry.get_op_def("reorder_lod_tensor_by_rank"), ctx,
        {"X": [x], "RankTable": [rt]}, {})["Out"][0]
    np.testing.assert_allclose(np.asarray(reord), x)  # already sorted


def test_fusion_transpose_flatten_concat_and_conv2d_fusion():
    rng = np.random.RandomState(21)
    a = rng.randn(2, 3, 4).astype("float32")
    b = rng.randn(2, 5, 4).astype("float32")
    out = call("fusion_transpose_flatten_concat", {"X": [a, b]},
               {"trans_axis": [0, 2, 1], "flatten_axis": 1,
                "concat_axis": 1})["Out"][0]
    exp = np.concatenate([a.transpose(0, 2, 1).reshape(2, -1),
                          b.transpose(0, 2, 1).reshape(2, -1)], 1)
    np.testing.assert_allclose(out, exp)

    x = rng.randn(1, 2, 5, 5).astype("float32")
    f = rng.randn(3, 2, 3, 3).astype("float32")
    bias = rng.randn(3).astype("float32")
    out = call("conv2d_fusion",
               {"Input": x, "Filter": f, "Bias": bias,
                "ResidualData": None},
               {"strides": [1, 1], "paddings": [1, 1],
                "dilations": [1, 1], "activation": "relu"})["Output"][0]
    assert out.shape == (1, 3, 5, 5) and (out >= 0).all()


def test_fpn_distribute_collect():
    rois = np.array([[0, 0, 30, 30],      # small -> low level
                     [0, 0, 400, 400]], "float32")  # big -> high level
    r = call("distribute_fpn_proposals", {"FpnRois": rois},
             {"min_level": 2, "max_level": 5, "refer_level": 4,
              "refer_scale": 224})
    levels = r["MultiFpnRois"]
    assert len(levels) == 4
    assert (levels[0][0] != 0).any()      # small roi landed at level 2
    # 400px roi: floor(log2(400/224)) + 4 = 4 -> index 2
    assert (levels[2][1] != 0).any()

    out = call("collect_fpn_proposals",
               {"MultiLevelRois": [rois[:1], rois[1:]],
                "MultiLevelScores": [np.array([0.1], "float32"),
                                     np.array([0.9], "float32")]},
               {"post_nms_topN": 1})["FpnRois"][0]
    np.testing.assert_allclose(out[0], rois[1])


def test_box_decoder_and_assign():
    prior = np.array([[0, 0, 9, 9]], "float32")
    deltas = np.zeros((1, 2, 4), "float32")  # 2 classes, zero deltas
    scores = np.array([[0.1, 0.9]], "float32")
    r = call("box_decoder_and_assign",
             {"PriorBox": prior, "PriorBoxVar": None,
              "TargetBox": deltas.reshape(1, -1), "BoxScore": scores}, {})
    np.testing.assert_allclose(r["OutputAssignBox"][0][0], prior[0],
                               atol=1e-4)


def test_cudnn_lstm_and_inception_fusion_and_id_shards():
    rng = np.random.RandomState(30)
    B, T, D, H = 2, 4, 3, 5
    x = rng.randn(B, T, D).astype("float32")
    w = rng.randn(D * 4 * H + H * 4 * H + 4 * H).astype("float32") * 0.1
    r = call("cudnn_lstm", {"Input": x, "InitH": None, "InitC": None,
                            "W": w, "SeqLen": None},
             {"hidden_size": H})
    assert r["Out"][0].shape == (B, T, H)
    np.testing.assert_allclose(r["last_h"][0][0], r["Out"][0][:, -1],
                               rtol=1e-5)

    xi = rng.randn(1, 2, 6, 6).astype("float32")
    f1 = rng.randn(3, 2, 1, 1).astype("float32")
    f3 = rng.randn(4, 2, 3, 3).astype("float32")
    r = call("conv2d_inception_fusion",
             {"Input": xi, "Filter": [f1, f3],
              "Bias": [np.zeros(3, "float32"), np.zeros(4, "float32")]},
             {})
    assert r["Output"][0].shape == (1, 7, 6, 6)

    ids = np.array([0, 1, 2, 3, 4, 5], "int64")
    r = call("split_ids", {"Ids": ids}, {"num_shards": 2})
    np.testing.assert_array_equal(r["Out"][0], [0, -1, 2, -1, 4, -1])
    emb = [np.full((6, 2), s, "float32") for s in range(2)]
    merged = call("merge_ids", {"Ids": ids, "Rows": [], "X": emb},
                  {})["Out"][0]
    np.testing.assert_allclose(merged[:, 0], [0, 1, 0, 1, 0, 1])

    x = np.arange(12, dtype="float32").reshape(6, 2)
    r = call("split_selected_rows", {"X": x}, {"height_sections": [2, 4]})
    assert r["Out"][0].shape == (2, 2) and r["Out"][1].shape == (4, 2)
