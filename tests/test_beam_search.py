"""Beam search op + seq2seq NMT tests (reference:
unittests/test_beam_search_op.py, test_beam_search_decode_op.py, and the
book test tests/book/test_machine_translation.py)."""

import numpy as np

import paddle_tpu as fluid
from paddle_tpu.executor import Scope, scope_guard
from paddle_tpu.models import machine_translation


class TestBeamSearchStep:
    def _run_step(self, pre_ids, pre_scores, scores, beam_size, end_id,
                  is_accumulated=False):
        B, K, V = scores.shape
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            pi = fluid.layers.data("pi", shape=[B, K], dtype="int32",
                                   append_batch_size=False)
            ps = fluid.layers.data("ps", shape=[B, K], dtype="float32",
                                   append_batch_size=False)
            sc = fluid.layers.data("sc", shape=[B, K, V], dtype="float32",
                                   append_batch_size=False)
            ids, sco, par = fluid.layers.beam_search(
                pi, ps, None, sc, beam_size=beam_size, end_id=end_id,
                is_accumulated=is_accumulated, return_parent_idx=True)
        exe = fluid.Executor(fluid.CPUPlace())
        with scope_guard(Scope()):
            return exe.run(
                main,
                feed={"pi": pre_ids, "ps": pre_scores, "sc": scores},
                fetch_list=[ids, sco, par])

    def test_topk_over_beams(self):
        # B=1, K=2, V=4; beam log-probs chosen so the best two candidates
        # come from different beams
        pre_ids = np.array([[5, 6]], "int32")
        pre_scores = np.array([[-1.0, -2.0]], "float32")
        step = np.array([[[-0.1, -3.0, -4.0, -5.0],
                          [-4.0, -0.2, -6.0, -7.0]]], "float32")
        ids, sco, par = self._run_step(pre_ids, pre_scores, step, 2, end_id=0)
        # candidates: beam0: -1.1 (tok 0), -4.0 (tok 1)...; beam1: -2.2 (tok 1)
        assert ids[0].tolist() == [0, 1]
        np.testing.assert_allclose(sco[0], [-1.1, -2.2], atol=1e-6)
        assert par[0].tolist() == [0, 1]

    def test_finished_beam_frozen(self):
        end_id = 3
        pre_ids = np.array([[3, 7]], "int32")      # beam 0 already finished
        pre_scores = np.array([[-0.5, -1.0]], "float32")
        step = np.full((1, 2, 4), -10.0, "float32")
        step[0, 1, 1] = -0.1
        ids, sco, par = self._run_step(pre_ids, pre_scores, step, 2, end_id)
        # finished beam survives with frozen score; live beam extends
        rows = sorted(zip(ids[0].tolist(), sco[0].tolist(), par[0].tolist()))
        assert (1, -1.1, 1) in [(r[0], round(r[1], 6), r[2]) for r in rows]
        assert (3, -0.5, 0) in [(r[0], round(r[1], 6), r[2]) for r in rows]

    def test_first_step_convention(self):
        # pre_scores [0, -1e9]: all selected beams must come from beam 0
        pre_ids = np.array([[1, 1]], "int32")
        pre_scores = np.array([[0.0, -1e9]], "float32")
        step = np.log(np.array(
            [[[0.1, 0.5, 0.2, 0.2], [0.1, 0.5, 0.2, 0.2]]], "float32"))
        ids, sco, par = self._run_step(pre_ids, pre_scores, step, 2, end_id=0)
        assert par[0].tolist() == [0, 0]
        assert ids[0].tolist() == [1, 2] or ids[0].tolist() == [1, 3]


class TestBeamSearchDecode:
    def test_backtrace(self):
        """Hand-built two-step beam tree: verify parent-chain replay."""
        B, K, T = 1, 2, 3
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            i = fluid.layers.fill_constant([1], "int32", 0)
            ids0 = fluid.layers.assign(np.array([[4, 5]], "int32"))
            sc0 = fluid.layers.assign(np.array([[-1.0, -2.0]], "float32"))
            par0 = fluid.layers.assign(np.array([[0, 0]], "int32"))
            ids_arr = fluid.layers.array_write(ids0, i, capacity=T)
            sc_arr = fluid.layers.array_write(sc0, i, capacity=T)
            par_arr = fluid.layers.array_write(par0, i, capacity=T)
            i1 = fluid.layers.fill_constant([1], "int32", 1)
            # step 1: beam0 ← parent 1 (tok 6), beam1 ← parent 0 (tok 7)
            ids1 = fluid.layers.assign(np.array([[6, 7]], "int32"))
            sc1 = fluid.layers.assign(np.array([[-1.5, -2.5]], "float32"))
            par1 = fluid.layers.assign(np.array([[1, 0]], "int32"))
            fluid.layers.array_write(ids1, i1, array=ids_arr)
            fluid.layers.array_write(sc1, i1, array=sc_arr)
            fluid.layers.array_write(par1, i1, array=par_arr)
            sent, scores = fluid.layers.beam_search_decode(
                ids_arr, sc_arr, par_arr, beam_size=K, end_id=0)
        exe = fluid.Executor(fluid.CPUPlace())
        with scope_guard(Scope()):
            s, sc = exe.run(main, fetch_list=[sent, scores])
        # beam 0 at final step came from parent beam 1: sequence [5, 6]
        assert s[0, 0, :2].tolist() == [5, 6]
        # beam 1 came from parent beam 0: sequence [4, 7]
        assert s[0, 1, :2].tolist() == [4, 7]
        # unwritten step 2 (capacity padding) → end_id
        assert (s[:, :, 2] == 0).all()
        np.testing.assert_allclose(sc[0], [-1.5, -2.5], atol=1e-6)


class TestNMTBook:
    """Train a toy copy-task seq2seq, then beam-decode it (book test
    pattern: train until loss drops, assert decode quality)."""

    def test_train_and_decode(self):
        V, L = 12, 4
        start_id, end_id = 1, 2
        B = 4
        rng = np.random.RandomState(0)

        main, startup, feeds, loss = machine_translation.build_train(V, emb_dim=24, hidden_dim=48, src_len=L,
                        tgt_len=L + 1, lr=5e-3)

        def make_batch(n):
            toks = rng.randint(3, V, size=(n, L))
            tgt_in = np.concatenate(
                [np.full((n, 1), start_id), toks], axis=1)
            tgt_out = np.concatenate(
                [toks, np.full((n, 1), end_id)], axis=1)[..., None]
            return (toks.astype("int64"), tgt_in.astype("int64"),
                    tgt_out.astype("int64"))

        exe = fluid.Executor(fluid.CPUPlace())
        scope = Scope()
        with scope_guard(scope):
            exe.run(startup)
            first = last = None
            for step in range(200):
                s, ti, to = make_batch(16)
                (l,) = exe.run(
                    main, feed={"src": s, "tgt_in": ti, "tgt_out": to},
                    fetch_list=[loss])
                l = float(np.asarray(l).reshape(()))
                if first is None:
                    first = l
                last = l
            assert last < first * 0.25, (first, last)

            # decode in the same scope → shared trained parameters
            imain, istartup, ifeeds, sent, scores = \
                machine_translation.build_infer(
                    V, emb_dim=24, hidden_dim=48, src_len=L, batch_size=B,
                    beam_size=3, max_len=L + 2, start_id=start_id,
                    end_id=end_id)
            s, _, _ = make_batch(B)
            sids, sscores = exe.run(imain, feed={"src": s},
                                    fetch_list=[sent, scores])
        assert sids.shape == (B, 3, L + 2)
        # top beam should reproduce the source tokens then emit end_id
        correct = 0
        for b in range(B):
            got = sids[b, 0, :L].tolist()
            if got == s[b].tolist():
                correct += 1
        assert correct >= B - 1, (sids[:, 0], s)
        # scores sorted: beam 0 is the best-scoring hypothesis
        assert (sscores[:, 0] >= sscores[:, 1] - 1e-6).all()
