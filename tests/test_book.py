"""Book model tests (reference: ``python/paddle/fluid/tests/book/`` —
train a few iterations, assert the loss decreases, save + reload the
inference model).  fit_a_line, word2vec and recommender_system here;
recognize_digits/image_classification/machine_translation live in
test_models.py / test_beam_search.py."""

import numpy as np

import paddle_tpu as fluid
from paddle_tpu import datasets, reader_decorators as rd
from paddle_tpu.executor import Scope, scope_guard


class TestFitALine:
    """book/test_fit_a_line.py: linear regression on uci_housing."""

    def test_train_and_infer(self, tmp_path):
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.layers.data("x", shape=[13], dtype="float32")
            y = fluid.layers.data("y", shape=[1], dtype="float32")
            pred = fluid.layers.fc(x, size=1)
            loss = fluid.layers.reduce_mean(
                fluid.layers.square_error_cost(pred, y))
            fluid.optimizer.Adam(learning_rate=0.1).minimize(loss)

        reader = rd.batch(datasets.uci_housing.train(), 64)
        exe = fluid.Executor(fluid.CPUPlace())
        model_dir = str(tmp_path / "fit_a_line")
        with scope_guard(Scope()):
            exe.run(startup)
            first = last = None
            for epoch in range(100):
                for b in reader():
                    xs = np.stack([s[0] for s in b]).astype("float32")
                    ys = np.stack([s[1] for s in b]).astype("float32")
                    (l,) = exe.run(main, feed={"x": xs, "y": ys},
                                   fetch_list=[loss])
                    l = float(np.asarray(l).reshape(()))
                    first = first if first is not None else l
                    last = l
            assert last < first * 0.01, (first, last)
            fluid.io.save_inference_model(model_dir, ["x"], [pred], exe,
                                          main_program=main)

        # reload and check prediction error is in the trained ballpark
        with scope_guard(Scope()):
            prog, feeds, fetches = fluid.io.load_inference_model(
                model_dir, exe)
            b = next(iter(rd.batch(datasets.uci_housing.test(), 32)()))
            xs = np.stack([s[0] for s in b]).astype("float32")
            ys = np.stack([s[1] for s in b]).astype("float32")
            (p,) = exe.run(prog, feed={feeds[0]: xs}, fetch_list=fetches)
        assert np.mean((p - ys) ** 2) < 2.0


class TestWord2Vec:
    """book/test_word2vec.py: N-gram LM with shared embeddings."""

    def test_train(self):
        V, EMB, N = 40, 16, 4
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            words = [fluid.layers.data("w%d" % i, shape=[1], dtype="int64")
                     for i in range(N)]
            embs = [
                fluid.layers.embedding(
                    w, size=[V, EMB],
                    param_attr=fluid.ParamAttr(name="shared_emb"))
                for w in words
            ]
            embs = [fluid.layers.reshape(e, shape=[-1, EMB]) for e in embs]
            concat = fluid.layers.concat(embs, axis=1)
            hidden = fluid.layers.fc(concat, size=64, act="relu")
            logits = fluid.layers.fc(hidden, size=V)
            target = fluid.layers.data("target", shape=[1], dtype="int64")
            loss = fluid.layers.mean(
                fluid.layers.softmax_with_cross_entropy(logits, target))
            fluid.optimizer.Adam(learning_rate=1e-2).minimize(loss)

        # synthetic corpus with strong 4-gram structure: w_{t+1} = 3w_t+1 mod V
        rng = np.random.RandomState(0)

        def batch(bs=64):
            w0 = rng.randint(0, V, size=(bs, 1))
            seq = [w0]
            for _ in range(N):
                seq.append((3 * seq[-1] + 1) % V)
            return [s.astype("int64") for s in seq]

        exe = fluid.Executor(fluid.CPUPlace())
        with scope_guard(Scope()):
            exe.run(startup)
            first = last = None
            for _ in range(120):
                *ws, tgt = batch()
                feed = {("w%d" % i): w for i, w in enumerate(ws)}
                feed["target"] = tgt
                (l,) = exe.run(main, feed=feed, fetch_list=[loss])
                l = float(np.asarray(l).reshape(()))
                first = first if first is not None else l
                last = l
        assert last < first * 0.2, (first, last)


class TestRecommender:
    """book/test_recommender_system.py: user/item embedding dot-product
    rating model."""

    def test_train(self):
        USERS, ITEMS, EMB = 30, 50, 16
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            uid = fluid.layers.data("uid", shape=[1], dtype="int64")
            iid = fluid.layers.data("iid", shape=[1], dtype="int64")
            rating = fluid.layers.data("rating", shape=[1], dtype="float32")
            uemb = fluid.layers.reshape(
                fluid.layers.embedding(uid, size=[USERS, EMB]),
                shape=[-1, EMB])
            iemb = fluid.layers.reshape(
                fluid.layers.embedding(iid, size=[ITEMS, EMB]),
                shape=[-1, EMB])
            uvec = fluid.layers.fc(uemb, size=EMB, act="relu")
            ivec = fluid.layers.fc(iemb, size=EMB, act="relu")
            sim = fluid.layers.cos_sim(uvec, ivec)
            pred = fluid.layers.scale(sim, scale=5.0)
            loss = fluid.layers.reduce_mean(
                fluid.layers.square_error_cost(pred, rating))
            fluid.optimizer.Adam(learning_rate=5e-3).minimize(loss)

        rng = np.random.RandomState(1)
        affinity = rng.rand(USERS, ITEMS).astype("float32") * 5.0

        def batch(bs=64):
            u = rng.randint(0, USERS, size=(bs, 1))
            i = rng.randint(0, ITEMS, size=(bs, 1))
            r = affinity[u[:, 0], i[:, 0]][:, None]
            return u.astype("int64"), i.astype("int64"), r.astype("float32")

        exe = fluid.Executor(fluid.CPUPlace())
        with scope_guard(Scope()):
            exe.run(startup)
            first = last = None
            for _ in range(200):
                u, i, r = batch()
                (l,) = exe.run(
                    main, feed={"uid": u, "iid": i, "rating": r},
                    fetch_list=[loss])
                l = float(np.asarray(l).reshape(()))
                first = first if first is not None else l
                last = l
        assert last < first * 0.6, (first, last)


class TestLabelSemanticRoles:
    """book/test_label_semantic_roles.py: SRL tagging with word+context
    +predicate embeddings -> CRF loss, viterbi decode + chunk precision
    (reference model uses conll05; padded + lengths here)."""

    def test_train_and_decode(self):
        from paddle_tpu import datasets

        T, NTAG = 12, 59
        wd, vd, ld = datasets.conll05.get_dict()
        WORDS, VERBS = 600, 50  # truncated vocab for the test

        fluid.unique_name.switch()
        main, startup = fluid.Program(), fluid.Program()
        main.random_seed = startup.random_seed = 7
        with fluid.program_guard(main, startup):
            word = fluid.layers.data("word", shape=[T], dtype="int64")
            verb = fluid.layers.data("verb", shape=[T], dtype="int64")
            mark = fluid.layers.data("mark", shape=[T], dtype="int64")
            lens = fluid.layers.data("lens", shape=[], dtype="int64")
            tags = fluid.layers.data("tags", shape=[T], dtype="int64")
            embs = [
                fluid.layers.embedding(word, size=[WORDS, 32]),
                fluid.layers.embedding(verb, size=[VERBS, 16]),
                fluid.layers.embedding(mark, size=[2, 8]),
            ]
            x = fluid.layers.concat(embs, axis=2)
            h = fluid.layers.fc(x, size=64, num_flatten_dims=2, act="tanh")
            emission = fluid.layers.fc(h, size=NTAG, num_flatten_dims=2)
            crf_attr = fluid.ParamAttr(name="srl.crfw")
            nll = fluid.layers.linear_chain_crf(
                emission, tags, param_attr=crf_attr, length=lens)
            loss = fluid.layers.mean(nll)
            test_prog = main.clone(for_test=True)
            fluid.optimizer.Adam(5e-2).minimize(loss)
        with fluid.program_guard(test_prog):
            em = test_prog.global_block().var(emission.name)
            path = fluid.layers.crf_decoding(
                em, crf_attr,
                length=test_prog.global_block().var("lens"))

        rng = np.random.RandomState(0)

        def batch(bs=16):
            n = rng.randint(4, T + 1, (bs,)).astype("int64")
            w = rng.randint(0, WORDS, (bs, T)).astype("int64")
            v = rng.randint(0, VERBS, (bs, T)).astype("int64")
            m = (rng.rand(bs, T) < 0.1).astype("int64")
            t = w % NTAG  # learnable per-word rule
            return {"word": w, "verb": v, "mark": m, "lens": n,
                    "tags": t.astype("int64")}

        exe = fluid.Executor(fluid.CPUPlace())
        with scope_guard(Scope()):
            exe.run(startup)
            first = last = None
            for _ in range(120):
                f = batch()
                (l,) = exe.run(main, feed=f, fetch_list=[loss])
                l = float(np.asarray(l).reshape(()))
                first = first if first is not None else l
                last = l
            assert last < 0.5 * first, (first, last)
            # decode runs and emits valid tags within lengths
            f = batch(4)
            p = exe.run(test_prog, feed=f, fetch_list=[path])[0]
            assert p.shape == (4, T)
            assert (p >= 0).all() and (p < NTAG).all()


class TestUnderstandSentiment:
    """book/test_understand_sentiment.py: the sentiment pipeline (canned
    dataset → reader decorators → feed) with two network bodies — the
    masked mean-pool baseline and the reference's convolution_net
    (notest_understand_sentiment.py:28: two sequence_conv_pool towers,
    tanh, sqrt pooling, multi-input fc)."""

    L = 40

    def _train(self, net_fn, lr):
        """Shared scaffold: build program with ``net_fn(emb, lens) ->
        logits``, train 40 batches, return per-batch accuracies."""
        import random

        from paddle_tpu import datasets, reader_decorators as rd

        # rd.shuffle draws from the global random module; pin it so the
        # batch order (and the accuracy threshold) is independent of
        # whichever tests ran before in the same process
        random.seed(1234)
        L = self.L
        V = datasets.sentiment.VOCAB
        fluid.unique_name.switch()
        main, startup = fluid.Program(), fluid.Program()
        main.random_seed = startup.random_seed = 2
        with fluid.program_guard(main, startup):
            ids = fluid.layers.data("ids", shape=[L], dtype="int64")
            lens = fluid.layers.data("lens", shape=[], dtype="int64")
            label = fluid.layers.data("label", shape=[1], dtype="int64")
            emb = fluid.layers.embedding(ids, size=[V, 32])
            logits = net_fn(emb, lens)
            loss = fluid.layers.mean(
                fluid.layers.softmax_with_cross_entropy(logits, label))
            acc = fluid.layers.accuracy(fluid.layers.softmax(logits),
                                        label)
            fluid.optimizer.Adam(lr).minimize(loss)

        reader = rd.batch(
            rd.shuffle(datasets.sentiment.train(), buf_size=500), 64)

        def to_feed(batch):
            n = len(batch)
            idm = np.zeros((n, L), "int64")
            ln = np.zeros((n,), "int64")
            lb = np.zeros((n, 1), "int64")
            for i, (seq, y) in enumerate(batch):
                k = min(len(seq), L)
                idm[i, :k] = seq[:k]
                ln[i] = k
                lb[i, 0] = y
            return {"ids": idm, "lens": ln, "label": lb}

        exe = fluid.Executor(fluid.CPUPlace())
        with scope_guard(Scope()):
            exe.run(startup)
            accs = []
            for step, b in enumerate(reader()):
                if len(b) < 64 or step >= 40:
                    break
                av = exe.run(main, feed=to_feed(b), fetch_list=[acc])[0]
                accs.append(float(np.asarray(av).reshape(())))
        return accs

    def test_train_reaches_accuracy(self):
        def mean_pool_net(emb, lens):
            mask = fluid.layers.cast(
                fluid.layers.sequence_mask(lens, maxlen=self.L), "float32")
            summed = fluid.layers.reduce_sum(
                fluid.layers.elementwise_mul(
                    emb, fluid.layers.unsqueeze(mask, [2])), dim=[1])
            denom = fluid.layers.unsqueeze(
                fluid.layers.reduce_sum(mask, dim=[1]), [1])
            pooled = fluid.layers.elementwise_div(summed, denom)
            return fluid.layers.fc(pooled, size=2)

        accs = self._train(mean_pool_net, lr=5e-3)
        assert np.mean(accs[-5:]) > 0.8, accs[-5:]

    def test_convolution_net_reaches_accuracy(self):
        def convolution_net(emb, lens):
            conv_3 = fluid.nets.sequence_conv_pool(
                emb, num_filters=32, filter_size=3, act="tanh",
                pool_type="sqrt", seq_len=lens)
            conv_4 = fluid.nets.sequence_conv_pool(
                emb, num_filters=32, filter_size=4, act="tanh",
                pool_type="sqrt", seq_len=lens)
            return fluid.layers.fc([conv_3, conv_4], size=2)

        accs = self._train(convolution_net, lr=2e-3)
        assert np.mean(accs[-5:]) > 0.8, accs[-5:]
