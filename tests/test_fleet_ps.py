"""fleet.parameter_server façade: a CTR script written against the
reference PS fleet API (``incubate/fleet/parameter_server/
distribute_transpiler/__init__.py``) runs unchanged and reproduces the
single-device per-step losses with the table row-sharded on the mesh
(the test_dist_base parity bar)."""

import warnings

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.executor import Scope, scope_guard
from paddle_tpu.incubate.fleet.base.role_maker import (Role,
                                                       UserDefinedRoleMaker)
from paddle_tpu.incubate.fleet.parameter_server.distribute_transpiler import (
    fleet, TranspilerOptimizer)
from paddle_tpu.models import ctr
from paddle_tpu.transpiler import DistributeTranspilerConfig

VOCAB = 4096
N_SLOTS, SLOT_LEN, DENSE = 3, 5, 8


def _build(use_fleet, lr=0.05):
    fluid.unique_name.switch()
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 11
    with fluid.program_guard(main, startup):
        slots = [
            fluid.layers.data("slot%d" % i, shape=[SLOT_LEN], dtype="int64")
            for i in range(N_SLOTS)
        ]
        dense = fluid.layers.data("dense", shape=[DENSE], dtype="float32")
        label = fluid.layers.data("label", shape=[1], dtype="int64")
        # the reference CTR script uses is_sparse embeddings and lets the
        # fleet transpile decide distribution — build the model WITHOUT
        # is_distributed and let the façade mark it
        loss, prob = ctr.wide_deep(
            slots, dense, label, vocab=VOCAB, embed_dim=16,
            hidden=(32, 32), is_distributed=False, is_sparse=True)
        opt = fluid.optimizer.Adam(learning_rate=lr)
        if use_fleet:
            config = DistributeTranspilerConfig()
            config.sync_mode = True
            opt = fleet.distributed_optimizer(opt, config)
        opt.minimize(loss, startup_program=startup)
    return main, startup, loss


def _batches(n_steps, bs=32):
    rng = np.random.RandomState(3)
    out = []
    for _ in range(n_steps):
        slots = [
            rng.randint(0, VOCAB, (bs, SLOT_LEN)).astype("int64")
            for _ in range(N_SLOTS)
        ]
        dense = rng.randn(bs, DENSE).astype("float32")
        label = rng.randint(0, 2, (bs, 1)).astype("int64")
        out.append((slots, dense, label))
    return out


def _train(prog, startup, loss, data_parallel, n_steps=6):
    exe = fluid.Executor(fluid.CPUPlace())
    losses = []
    scope = Scope()
    with scope_guard(scope):
        exe.run(startup)
        run_prog = prog
        if data_parallel:
            run_prog = fluid.CompiledProgram(prog).with_data_parallel(
                loss_name=loss.name)
        for slots, dense, label in _batches(n_steps):
            feed = {"slot%d" % i: s for i, s in enumerate(slots)}
            feed["dense"] = dense
            feed["label"] = label
            (l,) = exe.run(run_prog, feed=feed, fetch_list=[loss])
            losses.append(float(np.asarray(l).reshape(())))
        table = scope.get("deep_emb_0") if scope.has("deep_emb_0") else None
    return losses, table


class TestFleetPS:
    def test_ctr_script_loss_parity(self):
        """The reference-style fleet-PS CTR flow: init → distributed_
        optimizer → minimize → init_worker → train on fleet.main_program,
        8-way mesh, vs the plain single-device run."""
        single_main, single_startup, single_loss = _build(use_fleet=False)
        single, _ = _train(single_main, single_startup, single_loss,
                           data_parallel=False)

        fleet.init(UserDefinedRoleMaker(current_id=0, role=Role.WORKER,
                                        worker_num=1))
        main, startup, loss = _build(use_fleet=True)
        assert not fleet.is_server()
        fleet.init_worker()
        assert fleet.main_program is main
        sharded, table = _train(fleet.main_program, fleet.startup_program
                                or startup, loss, data_parallel=True)
        fleet.stop_worker()

        np.testing.assert_allclose(sharded, single, rtol=3e-4, atol=3e-4)
        assert single[-1] < single[0]
        # the façade marked the sparse table and it really row-sharded
        w = main.global_block().var("deep_emb_0")
        assert getattr(w, "_is_distributed", False)
        assert table is not None and len(table.sharding.device_set) == 8
        assert table.sharding.spec[0] == "data"

    def test_strategy_type_checked(self):
        opt = fluid.optimizer.SGD(learning_rate=0.1)
        with pytest.raises(TypeError):
            TranspilerOptimizer(opt, strategy={"not": "a config"})

    def test_server_calls_warn_not_wedge(self):
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            fleet.init_server()
            fleet.run_server()
        assert len(w) == 2
        assert "no parameter servers" in str(w[0].message)

    def test_pslib_facade(self):
        from paddle_tpu.incubate.fleet.parameter_server.pslib import (
            fleet as ps_fleet, DownpourOptimizer)

        opt = ps_fleet.distributed_optimizer(
            fluid.optimizer.SGD(learning_rate=0.1), strategy={})
        assert isinstance(opt, DownpourOptimizer)
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            ps_fleet.shrink_sparse_table()
        assert "no-op" in str(w[0].message)
