"""Block quantization primitives (ISSUE 15): wire-format round trips,
the documented error model, the zero/denormal guard, bit-exact replay,
and the Pallas-interpret vs XLA-composite parity."""

import numpy as np
import pytest

import jax.numpy as jnp

from paddle_tpu.quant import (block_dequantize, block_quantize,
                              predicted_rms_error, quant_block,
                              quant_enabled, quantization_error)
from paddle_tpu.quant.blockwise import padded_size


def _roundtrip(x, block=None):
    q, s = block_quantize(jnp.asarray(x), block=block)
    back = block_dequantize(q, s, size=np.asarray(x).size)
    return np.asarray(q), np.asarray(s), np.asarray(back)


class TestWireFormat:
    def test_shapes_and_dtypes(self):
        rng = np.random.RandomState(0)
        x = rng.randn(1000).astype("float32")  # odd tail: 1000 % 256 != 0
        q, s, back = _roundtrip(x, block=256)
        assert q.dtype == np.int8
        assert s.dtype == np.float32
        assert q.size == padded_size(1000, 256) == 1024
        assert s.size == 4
        assert back.size == 1000

    def test_odd_tail_blocks_round_trip(self):
        """The zero-padded tail must not disturb the real elements: pad
        quantizes to 0 under the tail block's scale, dequant + trim is
        exact about which elements exist."""
        rng = np.random.RandomState(1)
        for numel in (1000, 257, 255, 129):
            x = rng.randn(numel).astype("float32")
            q, s, back = _roundtrip(x, block=256)
            step = s.max()
            assert np.max(np.abs(back - x)) <= step / 2 + 1e-7
            # pad region of the int8 payload is exactly zero
            assert not q[numel:].any()

    def test_single_element_bucket(self):
        q, s, back = _roundtrip(np.array([3.25], "float32"), block=256)
        # one element is its own absmax: round trips exactly
        assert back[0] == np.float32(3.25)
        assert q[0] == 127

    def test_f32_vs_bf16_inputs(self):
        """bf16 input quantizes through the same f32 math and dequants
        back in the requested dtype."""
        rng = np.random.RandomState(2)
        xf = rng.randn(512).astype("float32")
        xb = jnp.asarray(xf).astype(jnp.bfloat16)
        q, s = block_quantize(xb, block=256)
        back = block_dequantize(q, s, size=512, dtype=jnp.bfloat16)
        assert back.dtype == jnp.bfloat16
        err = np.abs(np.asarray(back, "float32")
                     - np.asarray(xb, "float32"))
        assert err.max() <= np.asarray(s).max()  # step + bf16 rounding

    def test_shape_reshape(self):
        rng = np.random.RandomState(3)
        x = rng.randn(12, 33).astype("float32")
        q, s = block_quantize(jnp.asarray(x))
        back = block_dequantize(q, s, shape=(12, 33))
        assert back.shape == (12, 33)


class TestZeroAndDenormal:
    def test_zero_input_no_nan(self):
        q, s, back = _roundtrip(np.zeros(512, "float32"))
        assert not q.any()
        assert np.isfinite(s).all()
        assert s.min() > 0  # the unit-scale guard
        assert not back.any()

    def test_zero_block_among_live_blocks(self):
        x = np.zeros(512, "float32")
        x[256:] = np.linspace(-1, 1, 256)
        q, s, back = _roundtrip(x, block=256)
        assert np.isfinite(back).all()
        assert not back[:256].any()

    def test_denormal_input_no_nan(self):
        x = np.full(256, 1e-41, "float32")  # subnormal f32
        q, s, back = _roundtrip(x, block=256)
        assert np.isfinite(back).all()
        assert np.isfinite(s).all()


class TestErrorModel:
    def test_max_abs_error_bound(self):
        """Documented bound: per-element abs error <= m/254 (half the
        quantization step) within each block."""
        rng = np.random.RandomState(4)
        x = rng.randn(2048).astype("float32")
        q, s, back = _roundtrip(x, block=256)
        err = np.abs(back - x).reshape(-1, 256)
        bound = (s / 2.0)[:, None]  # s = m/127, so s/2 = m/254
        assert (err <= bound + 1e-7).all()

    def test_measured_rms_tracks_model(self):
        rng = np.random.RandomState(5)
        d = quantization_error(rng.randn(4096).astype("float32"))
        measured = float(d["measured_rms"])
        predicted = float(d["predicted_rms"])
        assert predicted > 0
        # dense gaussian data is the model's home regime
        assert 0.5 <= measured / predicted <= 2.0
        assert float(d["rel_error"]) < 0.02  # ~0.4% typical for randn

    def test_zero_input_rel_error_zero(self):
        d = quantization_error(np.zeros(512, "float32"))
        assert float(d["rel_error"]) == 0.0
        assert float(d["measured_rms"]) == 0.0

    def test_predicted_rms_formula(self):
        s = np.array([0.5, 0.1], "float32")
        expect = np.sqrt(np.mean(s ** 2) / 12.0)
        assert np.isclose(float(predicted_rms_error(s)), expect)


class TestKnobsAndReplay:
    def test_block_env_override(self, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_QUANT_BLOCK", "128")
        assert quant_block() == 128
        q, s = block_quantize(jnp.zeros(200))
        assert np.asarray(s).size == padded_size(200, 128) // 128
        monkeypatch.setenv("PADDLE_TPU_QUANT_BLOCK", "not-a-number")
        assert quant_block() == 256

    def test_kill_switch_flag(self, monkeypatch):
        monkeypatch.delenv("PADDLE_TPU_QUANT", raising=False)
        assert quant_enabled()
        monkeypatch.setenv("PADDLE_TPU_QUANT", "0")
        assert not quant_enabled()

    def test_bit_exact_replay(self):
        """Quantization is a pure function of the input bits: the same
        tensor quantizes to identical bits every time (forward-only op,
        no saved state, exact replay)."""
        rng = np.random.RandomState(6)
        x = jnp.asarray(rng.randn(1024).astype("float32"))
        q1, s1 = block_quantize(x)
        q2, s2 = block_quantize(x)
        assert np.array_equal(np.asarray(q1), np.asarray(q2))
        assert np.array_equal(np.asarray(s1), np.asarray(s2))


class TestPallasParity:
    def test_interpret_matches_xla_composite(self, monkeypatch):
        """PADDLE_TPU_PALLAS=interpret drives the fused kernel through
        the Pallas interpreter on CPU; its bits must match the XLA
        composite fallback (the autotune ``quant`` family swaps grid
        shapes, never values)."""
        from paddle_tpu.ops.pallas.flash_attention import pallas_supported

        if not pallas_supported():
            pytest.skip("pallas unavailable in this jax build")
        rng = np.random.RandomState(7)
        # eligible shape: block % 128 == 0, nblocks % 8 == 0
        x = jnp.asarray(rng.randn(8 * 256).astype("float32"))
        monkeypatch.setenv("PADDLE_TPU_PALLAS", "off")
        q_x, s_x = block_quantize(x, block=256)
        back_x = block_dequantize(q_x, s_x)
        monkeypatch.setenv("PADDLE_TPU_PALLAS", "interpret")
        q_p, s_p = block_quantize(x, block=256)
        back_p = block_dequantize(q_p, s_p)
        assert np.array_equal(np.asarray(q_x), np.asarray(q_p))
        assert np.array_equal(np.asarray(s_x), np.asarray(s_p))
        assert np.array_equal(np.asarray(back_x), np.asarray(back_p))

    def test_ineligible_shape_falls_back(self, monkeypatch):
        """Shapes off the kernel's grid (odd block counts) run the XLA
        composite even in interpret mode — and still round trip."""
        monkeypatch.setenv("PADDLE_TPU_PALLAS", "interpret")
        rng = np.random.RandomState(8)
        x = rng.randn(3 * 256).astype("float32")  # nblocks=3, not %8
        q, s, back = _roundtrip(x, block=256)
        assert np.max(np.abs(back - x)) <= np.asarray(s).max() / 2 + 1e-7
