"""Autotune subsystem (ISSUE 6): cache robustness (corrupt/torn files
fall back to defaults with a warning, never a crash), cache-hit
determinism (a second sweep never re-times), the PADDLE_TPU_AUTOTUNE=0
kill switch (hand-set defaults, bit-exact pre-autotune behavior),
threshold decisions, calibration factors feeding the cost model and the
fusion gates, and the flash_min_t resolution order."""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import autotune


@pytest.fixture
def tuned(tmp_path, monkeypatch):
    """Point the cache at a fresh temp file and reset in-process state."""
    path = str(tmp_path / "autotune.json")
    monkeypatch.setenv("PADDLE_TPU_AUTOTUNE_CACHE", path)
    monkeypatch.delenv("PADDLE_TPU_AUTOTUNE", raising=False)
    autotune.reset()
    yield path
    autotune.reset()


class TestCache:
    def test_round_trip(self, tuned):
        sig = autotune.signature("fam", shape=(8, 128), dtype="float32",
                                 backend="cpu")
        assert autotune.lookup(sig) is None
        autotune.record(sig, {"params": {"block": 64}, "measured_ms": 1.5})
        got = autotune.lookup(sig)
        assert got["params"] == {"block": 64}
        # on-disk: versioned schema, atomic file
        with open(tuned) as f:
            data = json.load(f)
        assert data["schema"] == autotune.SCHEMA_VERSION
        assert sig in data["entries"]

    def test_signature_is_canonical(self):
        a = autotune.signature("f", b=2, a=1)
        b = autotune.signature("f", a=1, b=2)
        assert a == b == "f|a=1|b=2"
        assert autotune.signature("f", shape=(4, 8)) == "f|shape=4x8"

    def test_corrupt_cache_falls_back_with_warning(self, tuned):
        with open(tuned, "w") as f:
            f.write('{"schema": 1, "entries": {"x": ')  # torn write
        with pytest.warns(UserWarning, match="unreadable"):
            assert autotune.lookup("anything") is None
        # a record REPAIRS the file rather than crashing on the merge
        autotune.record("s", {"params": {"k": 1}})
        assert autotune.lookup("s")["params"] == {"k": 1}
        with open(tuned) as f:
            json.load(f)  # valid again

    def test_wrong_schema_is_ignored(self, tuned):
        with open(tuned, "w") as f:
            json.dump({"schema": 999, "entries": {"s": {"params": {}}}}, f)
        with pytest.warns(UserWarning):
            assert autotune.lookup("s") is None

    def test_garbage_bytes_do_not_crash(self, tuned):
        with open(tuned, "wb") as f:
            f.write(b"\x00\xff garbage \x7f")
        with pytest.warns(UserWarning):
            assert autotune.entries() == {}

    def test_cache_hit_across_processes(self, tuned):
        """A second PROCESS sees the same winner — the cache is the file,
        not process state."""
        sig = autotune.signature("xproc", k=1, backend="cpu")
        autotune.record(sig, {"params": {"block": 32}})
        out = subprocess.run(
            [sys.executable, "-c",
             "from paddle_tpu import autotune; "
             "print(autotune.lookup(%r)['params']['block'])" % sig],
            capture_output=True, text=True, timeout=120,
            env={**os.environ, "JAX_PLATFORMS": "cpu",
                 "PADDLE_TPU_AUTOTUNE_CACHE": tuned},
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
        assert out.returncode == 0, out.stderr[-500:]
        assert out.stdout.strip() == "32"

    def test_kill_switch_disables_reads_and_writes(self, tuned,
                                                   monkeypatch):
        sig = autotune.signature("fam", k=1)
        autotune.record(sig, {"params": {"block": 64}})
        monkeypatch.setenv("PADDLE_TPU_AUTOTUNE", "0")
        assert autotune.lookup(sig) is None
        assert autotune.entries() == {}
        autotune.record("other", {"params": {}})  # silently dropped
        monkeypatch.delenv("PADDLE_TPU_AUTOTUNE")
        assert autotune.lookup("other") is None
        assert autotune.lookup(sig) is not None


class TestSweep:
    def test_sweep_times_and_caches_winner(self, tuned):
        import jax.numpy as jnp

        calls = []

        def runner(params):
            calls.append(params["k"])
            return jnp.zeros(()) + params["k"]

        cands = [{"k": 1}, {"k": 2}, {"k": 3}]
        e1 = autotune.sweep("swp", {"shape": (4,)}, cands, runner,
                            repeats=1, warmup=0)
        assert e1["params"]["k"] in (1, 2, 3)
        assert not e1["cached"]
        n_after_first = len(calls)
        assert n_after_first >= 3
        # second run: pure cache hit, runner NEVER invoked again
        e2 = autotune.sweep("swp", {"shape": (4,)}, cands, runner,
                            repeats=1, warmup=0)
        assert e2["cached"] is True
        assert e2["params"] == e1["params"]
        assert len(calls) == n_after_first

    def test_sweep_deterministic_across_reload(self, tuned):
        import jax.numpy as jnp

        e1 = autotune.sweep("det", {}, [{"k": 7}],
                            lambda p: jnp.zeros(()), repeats=1, warmup=0)
        autotune.reset()  # simulate a fresh process: reload from disk
        e2 = autotune.sweep("det", {}, [{"k": 7}],
                            lambda p: jnp.zeros(()), repeats=1, warmup=0)
        assert e2["cached"] and e2["params"] == e1["params"]

    def test_sweep_disabled_returns_first_candidate(self, tuned,
                                                    monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_AUTOTUNE", "0")
        e = autotune.sweep("off", {}, [{"k": 9}, {"k": 10}],
                           lambda p: (_ for _ in ()).throw(
                               AssertionError("must not time")))
        assert e["params"] == {"k": 9} and e.get("disabled")

    def test_sweep_records_calibration(self, tuned):
        import jax.numpy as jnp

        e = autotune.sweep("cal", {"s": 1}, [{"k": 1}],
                           lambda p: jnp.zeros(()),
                           baseline=lambda: jnp.zeros(()),
                           predicted_gain=2.0, repeats=1, warmup=0)
        assert "calibration" in e and e["calibration"] > 0
        sig = autotune.sweep_signature("cal", {"s": 1})
        assert autotune.calibration_factor(sig) == pytest.approx(
            e["calibration"])
        assert sig in autotune.calibrations()

    @pytest.mark.slow
    def test_silicon_block_sweep_smoke(self, tuned):
        """The real thing at toy scale: sweep fused-LN block rows with
        actual kernel executions (interpret mode).  Marked slow — the
        tier-1 run stays CPU-fast; the hw watcher runs it on chip."""
        import jax.numpy as jnp

        from paddle_tpu.ops.pallas.fused_ln import fused_dropout_add_ln

        x = jnp.ones((64, 128))
        res = jnp.zeros((64, 128))
        g = jnp.ones(128)
        b = jnp.zeros(128)

        def runner(params):
            os.environ["PADDLE_TPU_FUSED_LN_BLOCK_ROWS"] = \
                str(params["block_rows"])
            try:
                return fused_dropout_add_ln(x, res, g, b)
            finally:
                os.environ.pop("PADDLE_TPU_FUSED_LN_BLOCK_ROWS", None)

        e = autotune.sweep("fused_ln", {"rows": 64, "d": 128},
                           [{"block_rows": 8}, {"block_rows": 64}],
                           runner, repeats=1)
        assert e["params"]["block_rows"] in (8, 64)
        e2 = autotune.sweep("fused_ln", {"rows": 64, "d": 128},
                            [{"block_rows": 8}, {"block_rows": 64}],
                            runner, repeats=1)
        assert e2["cached"]


class TestThresholdDecision:
    def test_decide_threshold_golden(self):
        rows = {128: (2.0, 1.0), 256: (1.5, 1.4), 512: (1.0, 1.5),
                1024: (1.0, 2.1)}
        assert autotune.decide_threshold(rows) == 512

    def test_decide_threshold_no_clean_win(self):
        rows = {128: (2.0, 1.0), 512: (1.0, 1.5), 1024: (3.0, 2.0)}
        assert autotune.decide_threshold(rows) is None

    def test_flash_min_t_resolution_order(self, tuned, monkeypatch):
        from paddle_tpu.ops.pallas.flash_attention import flash_min_t

        monkeypatch.delenv("PADDLE_TPU_FLASH_MIN_T", raising=False)
        assert flash_min_t() == 512            # hand-set default
        autotune.record_flash_min_t(256, rows={256: (1.0, 1.5)})
        assert flash_min_t() == 256            # cached measured decision
        monkeypatch.setenv("PADDLE_TPU_FLASH_MIN_T", "1024")
        assert flash_min_t() == 1024           # env override wins
        monkeypatch.delenv("PADDLE_TPU_FLASH_MIN_T")
        monkeypatch.setenv("PADDLE_TPU_AUTOTUNE", "0")
        assert flash_min_t() == 512            # kill switch -> default


class TestKillSwitchBitExact:
    def test_autotune_off_restores_pre_autotune_train_path(
            self, tuned, monkeypatch):
        """A poisoned cache entry (absurd block rows for the conv-BN
        epilogue) must have NO effect with PADDLE_TPU_AUTOTUNE=0: the
        losses match a run that never had a cache bit-exactly."""
        from paddle_tpu.executor import Scope, scope_guard

        def build():
            fluid.unique_name.switch()
            main, startup = fluid.Program(), fluid.Program()
            with fluid.program_guard(main, startup):
                img = fluid.layers.data(name="img", shape=[8, 16, 16],
                                        dtype="float32")
                label = fluid.layers.data(name="label", shape=[1],
                                          dtype="int64")
                c = fluid.layers.conv2d(img, num_filters=8,
                                        filter_size=3, padding=1,
                                        bias_attr=False)
                h = fluid.layers.batch_norm(c, act="relu")
                pool = fluid.layers.pool2d(h, pool_size=16,
                                           pool_type="avg")
                pred = fluid.layers.fc(pool, size=10, act="softmax")
                loss = fluid.layers.reduce_mean(
                    fluid.layers.cross_entropy(input=pred, label=label))
                fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
            return main, startup, loss

        rng = np.random.RandomState(0)
        feed = {"img": rng.randn(4, 8, 16, 16).astype("float32"),
                "label": rng.randint(0, 10, (4, 1)).astype("int64")}

        def run_steps():
            main, startup, loss = build()
            exe = fluid.Executor()
            with scope_guard(Scope()):
                exe.run(startup)
                return [float(np.asarray(
                    exe.run(main, feed=feed, fetch_list=[loss])[0])
                    .reshape(())) for _ in range(3)]

        baseline = run_steps()
        # poison the cache with a factor that would flip the fusion gate
        # and absurd block params
        sig = autotune.sweep_signature(
            "conv_bn_act", {"shape": (-1, 16, 16, 8),
                            "dtype": "float32", "act": "relu"})
        autotune.record(sig, {"params": {"block_rows": 7},
                              "calibration": 1e-9})
        monkeypatch.setenv("PADDLE_TPU_AUTOTUNE", "0")
        killed = run_steps()
        assert killed == baseline


class TestCostModelExposure:
    def test_bench_json_exposes_calibration_factors(self, tuned):
        autotune.record(
            autotune.signature("conv_bn_act", shape=(1, 2),
                               backend="cpu"),
            {"params": {}, "calibration": 1.7})
        fluid.unique_name.switch()
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.layers.data(name="x", shape=[4], dtype="float32")
            y = fluid.layers.fc(x, size=2)
        report = main.analyze(targets=[y.name])
        lines = [json.loads(l) for l in
                 report.cost.bench_json().splitlines()]
        cal = [l for l in lines
               if l["metric"] == "autotune_calibration_factors"]
        assert len(cal) == 1
        assert cal[0]["value"] == 1
        assert list(cal[0]["factors"].values()) == [1.7]

    def test_analyze_program_cli_bench_json(self, tuned, tmp_path):
        """analyze_program --bench-json carries the factors end-to-end
        (the CLI is what perf PRs cite)."""
        from paddle_tpu.proto import save_program

        autotune.record(
            autotune.signature("embedding_gather", rows=10, dim=128,
                               backend="cpu"),
            {"params": {}, "calibration": 2.5})
        fluid.unique_name.switch()
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.layers.data(name="x", shape=[4], dtype="float32")
            fluid.layers.fc(x, size=2)
        pjson = str(tmp_path / "prog.json")
        save_program(main, pjson)
        bench = str(tmp_path / "bench.txt")
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        out = subprocess.run(
            [sys.executable, "-m", "paddle_tpu.tools.analyze_program",
             "--program-json", pjson, "--bench-json", bench],
            capture_output=True, text=True, timeout=240,
            env={**os.environ, "JAX_PLATFORMS": "cpu",
                 "PYTHONPATH": repo + os.pathsep
                 + os.environ.get("PYTHONPATH", "")},
            cwd=repo)
        assert out.returncode == 0, (out.stdout + out.stderr)[-800:]
        with open(bench) as f:
            body = f.read()
        assert "autotune_calibration_factors" in body
        line = next(json.loads(l) for l in body.splitlines()
                    if "autotune_calibration_factors" in l)
        assert line["factors"][autotune.signature(
            "embedding_gather", rows=10, dim=128, backend="cpu")] == 2.5
