"""Elastic scale-UP (ISSUE 17): the join protocol (write-once request /
admit / ready files), the leader's warm-up admission state machine —
including the pinned guarantee that a joiner dying mid-warm-up never
stalls the fleet — epoch-scoped GC of protocol files, and upward
reshard round-trips (N -> N+1 / N+2) held to the same bit-exact
gather-then-scatter standard as the downward ones.

The full kill-relaunch-regrow drill lives in ``tools/chaos --elastic
--rejoin`` (subprocess cluster); these tests exercise the pieces
hermetically.
"""

import json
import os
import time

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.executor import Scope, scope_guard
from paddle_tpu.models import ctr
from paddle_tpu.resilience import checkpoint, elastic, reshard
from paddle_tpu.resilience.checkpoint import TopologyMismatchError
from paddle_tpu.resilience.watchdog import HeartbeatWriter


def _beat(dirname, rank):
    """One manual heartbeat (no thread, no done marker)."""
    HeartbeatWriter(dirname, rank, interval=60.0).beat()


# ---------------------------------------------------------------------------
# join protocol files
# ---------------------------------------------------------------------------

class TestJoinProtocol:
    def test_request_join_is_write_once(self, tmp_path):
        d = str(tmp_path)
        first = elastic.request_join(d, 5, 3)
        second = elastic.request_join(d, 5, 3)
        # the repost reads the winner's record — never clobbers it
        assert first == second and second["rank"] == 5
        assert second["epoch"] == 3

    def test_pending_joins_requires_a_fresh_heartbeat(self, tmp_path):
        d = str(tmp_path)
        elastic.request_join(d, 5, 0)
        elastic.request_join(d, 6, 0)   # posted, then died: no beat
        _beat(d, 5)
        assert elastic.pending_joins(d, 0) == [5]
        # the same joiner gone silent drops out of the next round
        assert elastic.pending_joins(d, 0, stale_timeout=5.0,
                                     now=time.time() + 100.0) == []
        # requests against another epoch are not this epoch's pending
        assert elastic.pending_joins(d, 1) == []

    def test_latest_epoch(self, tmp_path):
        d = str(tmp_path)
        assert elastic.latest_epoch(d) == (None, None)
        for epoch in (0, 3):
            elastic._write_once(
                elastic._member_path(d, epoch),
                {"epoch": epoch, "members": [0, 1], "world": 2})
        epoch, rec = elastic.latest_epoch(d)
        assert epoch == 3 and rec["members"] == [0, 1]
        # a newer record mid-publish: epoch visible, record not yet
        with open(elastic._member_path(d, 7), "w") as f:
            f.write("{torn")
        assert elastic.latest_epoch(d) == (7, None)

    def test_join_kill_switch(self, monkeypatch):
        monkeypatch.delenv("PADDLE_TPU_ELASTIC_JOIN", raising=False)
        assert elastic.join_enabled()
        monkeypatch.setenv("PADDLE_TPU_ELASTIC_JOIN", "0")
        assert not elastic.join_enabled()


# ---------------------------------------------------------------------------
# epoch-scoped GC (satellite: the stale-file leak fix)
# ---------------------------------------------------------------------------

class TestEpochGC:
    def _populate(self, d, epochs):
        names = []
        for e in epochs:
            for path in (elastic._member_path(d, e),
                         elastic._join_path(d, e, 7),
                         elastic._admit_path(d, e),
                         elastic._ready_path(d, e, 7)):
                with open(path, "w") as f:
                    json.dump({"epoch": e}, f)
                names.append(os.path.basename(path))
            gname = elastic._grad_fname(e, 4, 0)
            with open(os.path.join(d, gname), "wb") as f:
                f.write(b"x")
            names.append(gname)
        return names

    def test_three_epoch_run_leaves_two(self, tmp_path):
        d = str(tmp_path)
        self._populate(d, (0, 1, 2))
        removed = elastic.gc_epoch_files(d, 2)
        # the current AND previous epoch survive; epoch 0 is reclaimed
        left = {elastic._protocol_epoch(n) for n in os.listdir(d)}
        assert left == {1, 2}
        assert all(elastic._protocol_epoch(n) == 0 for n in removed)
        assert len(removed) == 5  # one per family at epoch 0

    def test_gc_is_idempotent_and_returns_names(self, tmp_path):
        d = str(tmp_path)
        self._populate(d, (0, 1, 2, 3))
        first = elastic.gc_epoch_files(d, 3)
        assert sorted(first) == first and len(first) == 10
        assert elastic.gc_epoch_files(d, 3) == []

    def test_hb_files_of_nonmembers_reclaimed_past_grace(self,
                                                         tmp_path):
        d = str(tmp_path)
        _beat(d, 0)   # member: always kept
        _beat(d, 7)   # long-gone ex-member
        _beat(d, 9)   # pending joiner, still beating
        old = time.time() - 1000.0
        os.utime(os.path.join(d, "hb-7"), (old, old))
        removed = elastic.gc_epoch_files(d, 5, members=[0],
                                         hb_grace=60.0)
        assert removed == ["hb-7"]
        assert os.path.exists(os.path.join(d, "hb-0"))
        assert os.path.exists(os.path.join(d, "hb-9"))
        # without the grace argument heartbeats are never touched
        os.utime(os.path.join(d, "hb-9"), (old, old))
        assert elastic.gc_epoch_files(d, 5) == []

    def test_adopting_an_epoch_garbage_collects_behind_it(self,
                                                          tmp_path):
        tr = elastic.ElasticTrainer(None, None, None, rank=0, world=1,
                                    workdir=str(tmp_path))
        self._populate(tr.hb_dir, (0, 1, 2))
        tr._adopt_membership(elastic.Membership(
            epoch=2, members=[0], world=1, lost=[], writer=0))
        left = {elastic._protocol_epoch(n)
                for n in os.listdir(tr.hb_dir)}
        left.discard(None)  # hb files of the adopting rank
        assert left == {1, 2}


# ---------------------------------------------------------------------------
# the leader's admission state machine
# ---------------------------------------------------------------------------

class TestAdmission:
    def _leader(self, tmp_path, **kw):
        kw.setdefault("stale_timeout", 0.2)
        kw.setdefault("hb_interval", 0.05)
        kw.setdefault("warmup_timeout", 30.0)
        return elastic.ElasticTrainer(None, None, None, rank=0,
                                      world=1, workdir=str(tmp_path),
                                      **kw)

    def test_admission_round_finalizes_with_start_step(self, tmp_path):
        tr = self._leader(tmp_path)
        tr.step = 4
        _beat(tr.hb_dir, 5)
        elastic.request_join(tr.hb_dir, 5, 0)
        tr._maybe_admit()
        # phase 1: write-once admit record naming members + joiners
        adm = json.load(open(elastic._admit_path(tr.hb_dir, 1)))
        assert adm["members"] == [0] and adm["joiners"] == [5]
        assert tr._pending_member is None  # not finalized yet
        # the joiner finishes warm-up and acks ready
        elastic._write_once(elastic._ready_path(tr.hb_dir, 1, 5),
                            {"rank": 5})
        tr.step = 6
        tr._maybe_admit()
        rec = tr._pending_member
        assert rec is not None
        assert rec["members"] == [0, 5] and rec["reason"] == "grow"
        assert rec["joined"] == [5]
        # two boundaries out: the lockstep exchange makes it race-free
        assert rec["start_step"] == 8

    def test_joiner_dying_midwarmup_never_stalls_the_fleet(self,
                                                           tmp_path):
        """Acceptance pin: an admitted joiner that dies before its
        ready ack is evicted by heartbeat staleness and admission rolls
        forward — the fleet keeps stepping, transitions to an epoch
        bump only, and the NEXT joiner is admitted normally."""
        tr = self._leader(tmp_path)
        _beat(tr.hb_dir, 5)
        elastic.request_join(tr.hb_dir, 5, 0)
        tr._maybe_admit()
        assert tr._admission is not None
        # the fleet keeps stepping at the old epoch while warm-up runs
        for _ in range(3):
            tr.step += 1
            tr._maybe_admit()
            assert tr.epoch == 0 and tr._pending_member is None
        # the joiner dies: heartbeat goes stale, no ready ack ever
        time.sleep(0.5)
        tr.step += 1
        tr._maybe_admit()
        rec = tr._pending_member
        assert rec is not None
        assert rec["members"] == [0] and rec["joined"] == []
        # the transition is an epoch bump only — re-plan/restore would
        # be a stall (and would crash this programless trainer)
        def _boom(*_a, **_k):
            raise AssertionError("no-grow transition must not re-plan")
        tr._plan = _boom
        tr._restore = _boom
        tr._checkpoint_now = _boom
        tr.step = int(rec["start_step"])
        tr._maybe_transition()
        assert tr.epoch == 1 and tr.members == [0]
        assert tr._pending_member is None and tr._admission is None
        # ...and admission rolls forward: the next joiner gets in
        _beat(tr.hb_dir, 6)
        elastic.request_join(tr.hb_dir, 6, 1)
        tr._maybe_admit()
        adm = json.load(open(elastic._admit_path(tr.hb_dir, 2)))
        assert adm["joiners"] == [6]

    def test_warmup_budget_exhaustion_evicts_a_wedged_joiner(self,
                                                             tmp_path):
        tr = self._leader(tmp_path, warmup_timeout=0.05)
        _beat(tr.hb_dir, 5)
        elastic.request_join(tr.hb_dir, 5, 0)
        tr._maybe_admit()
        time.sleep(0.1)
        _beat(tr.hb_dir, 5)  # alive but wedged: fresh beat, no ready
        tr._maybe_admit()
        rec = tr._pending_member
        assert rec is not None and rec["members"] == [0]

    def test_no_admission_without_headroom(self, tmp_path):
        tr = self._leader(tmp_path)
        tr._total_steps, tr.step = 10, 6
        _beat(tr.hb_dir, 5)
        elastic.request_join(tr.hb_dir, 5, 0)
        tr._maybe_admit()   # step + 4 >= total: too late to warm up
        assert tr._admission is None
        assert not os.path.exists(elastic._admit_path(tr.hb_dir, 1))

    def test_kill_switch_freezes_admission(self, tmp_path,
                                           monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_ELASTIC_JOIN", "0")
        tr = self._leader(tmp_path)
        _beat(tr.hb_dir, 5)
        elastic.request_join(tr.hb_dir, 5, 0)
        tr._maybe_admit()
        assert tr._admission is None
        assert not os.path.exists(elastic._admit_path(tr.hb_dir, 1))


# ---------------------------------------------------------------------------
# upward reshard round-trips: save at N, restore at N+1 / N+2
# ---------------------------------------------------------------------------

VOCAB = 64
N_SLOTS, SLOT_LEN, DENSE = 2, 3, 4


def _build_sharded(lr=0.05):
    fluid.unique_name.switch()
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 11
    with fluid.program_guard(main, startup):
        slots = [
            fluid.layers.data("slot%d" % i, shape=[SLOT_LEN],
                              dtype="int64")
            for i in range(N_SLOTS)
        ]
        dense = fluid.layers.data("dense", shape=[DENSE],
                                  dtype="float32")
        label = fluid.layers.data("label", shape=[1], dtype="int64")
        loss, _prob = ctr.wide_deep(
            slots, dense, label, vocab=VOCAB, embed_dim=8,
            hidden=(8,), is_distributed=True)
        fluid.optimizer.Adam(learning_rate=lr).minimize(loss)
    return main, startup, loss


def _ctr_feed(rng, bs=16):
    feed = {
        "slot%d" % i: rng.randint(0, VOCAB, (bs, SLOT_LEN))
        .astype("int64") for i in range(N_SLOTS)
    }
    feed["dense"] = rng.randn(bs, DENSE).astype("float32")
    feed["label"] = rng.randint(0, 2, (bs, 1)).astype("int64")
    return feed


def _gathered_shards(path):
    """Gather reference: reassemble every ``<var>.shards`` dir by
    concatenating the shard files in row order, independent of the
    reshard code under test."""
    full = {}
    for root, dirs, _files in os.walk(path):
        for d in list(dirs):
            if not d.endswith(".shards"):
                continue
            sdir = os.path.join(root, d)
            parts = []
            for fname in os.listdir(sdir):
                if not fname.startswith("shard-"):
                    continue
                start = int(fname[len("shard-"):].split("_", 1)[0])
                parts.append((start,
                              np.load(os.path.join(sdir, fname))))
            parts.sort(key=lambda p: p[0])
            full[d[:-len(".shards")]] = np.concatenate(
                [a for _s, a in parts], axis=0)
    return full


class TestUpwardReshard:
    def _save_at_8(self, tmp_path):
        root = str(tmp_path / "ckpt")
        main, startup, loss = _build_sharded()
        exe = fluid.Executor(fluid.CPUPlace())
        rng = np.random.RandomState(13)
        with scope_guard(Scope()):
            exe.run(startup)
            prog = fluid.CompiledProgram(main).with_data_parallel(
                loss_name=loss.name)
            for _ in range(2):
                exe.run(prog, feed=_ctr_feed(rng), fetch_list=[])
            path = checkpoint.save_checkpoint(
                exe, root, main_program=main, step=2,
                state={"step": 2},
                topology={"world": 8, "zero1": True})
        return root, path, main, startup, exe

    def test_restore_grown_bit_exact(self, tmp_path):
        root, path, main, startup, exe = self._save_at_8(tmp_path)
        before = _gathered_shards(path)
        # the is_distributed table and its Adam moments (ZeRO-1 rows)
        assert any("emb" in n for n in before)
        assert sum("moment" in n for n in before) >= 2

        for new_world in (9, 10):   # N+1, then N+2 chained on top
            report = reshard.reshard_checkpoint(
                path, {"world": new_world, "zero1": True})
            assert sorted(e["var"] for e in report) == sorted(before)
            manifest = checkpoint.verify_checkpoint(path)
            assert manifest["topology"]["world"] == new_world
            after = _gathered_shards(path)
            for name, ref in before.items():
                # gather-then-scatter: bit-identical through chained
                # upward reshards, sliced to the grown world's rows
                assert after[name].dtype == ref.dtype
                np.testing.assert_array_equal(after[name], ref)
                bounds = [b for b in reshard.shard_bounds(
                    ref.shape[0], new_world) if b[0] != b[1]]
                entry = [e for e in report if e["var"] == name][0]
                assert entry["new_files"] == len(bounds)

        # the grown version restores on a fresh scope
        with scope_guard(Scope()):
            exe.run(startup)
            info = checkpoint.try_load_latest_checkpoint(
                exe, root, main_program=main,
                expected_topology={"world": 10, "zero1": True})
            assert info is not None and info.step == 2
        # ... and the pre-reshard topology is now rejected
        with scope_guard(Scope()):
            exe.run(startup)
            with pytest.raises(TopologyMismatchError):
                checkpoint.try_load_latest_checkpoint(
                    exe, root, main_program=main,
                    expected_topology={"world": 8, "zero1": True})

    def test_gate_clears_after_upward_reshard(self, tmp_path):
        """A grown world hits the topology gate as a TYPED error until
        the reshard runs — then the same load succeeds."""
        root, path, main, startup, exe = self._save_at_8(tmp_path)
        grown = {"world": 9, "zero1": True}
        with scope_guard(Scope()):
            exe.run(startup)
            with pytest.raises(TopologyMismatchError) as ei:
                checkpoint.try_load_latest_checkpoint(
                    exe, root, main_program=main,
                    expected_topology=grown)
        assert ei.value.expected["world"] == 9
        reshard.reshard_checkpoint(path, grown)
        with scope_guard(Scope()):
            exe.run(startup)
            info = checkpoint.try_load_latest_checkpoint(
                exe, root, main_program=main,
                expected_topology=grown)
            assert info is not None and info.step == 2
