"""Expert-parallel Switch MoE: sharded all-to-all routing must equal the
per-shard dense reference (same gating/capacity math, no collectives),
gradients flow, over-capacity tokens drop, and the aux loss is sane."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from paddle_tpu.jax_compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from paddle_tpu.parallel import moe_ffn, init_moe_params

N = 4          # expert-parallel degree
E = 8          # global experts
B, T, D, F = 8, 16, 32, 64


@pytest.fixture(scope="module")
def mesh():
    return Mesh(np.array(jax.devices()[:N]), ("expert",))


@pytest.fixture(scope="module")
def params():
    return init_moe_params(jax.random.PRNGKey(0), D, F, E)


def _reference(x, params, capacity_factor):
    """Dense per-shard replay of the routing math: no collectives,
    global expert weights visible."""
    gate_w, w1, b1, w2, b2 = (np.asarray(p, np.float64) for p in params)
    xs = np.asarray(x, np.float64)
    out = np.zeros_like(xs)
    shard = B // N
    for s in range(N):
        xl = xs[s * shard:(s + 1) * shard].reshape(-1, D)   # local tokens
        t = xl.shape[0]
        cap = max(1, int(capacity_factor * t / E))
        logits = xl @ gate_w
        g = np.exp(logits - logits.max(-1, keepdims=True))
        g = g / g.sum(-1, keepdims=True)
        eidx = g.argmax(-1)
        counts = {}
        y = np.zeros_like(xl)
        for i in range(t):
            e = int(eidx[i])
            slot = counts.get(e, 0)
            counts[e] = slot + 1
            if slot >= cap:
                continue  # dropped
            a = xl[i] @ w1[e] + b1[e]
            a = 0.5 * a * (1.0 + np.tanh(
                np.sqrt(2.0 / np.pi) * (a + 0.044715 * a ** 3)))  # gelu
            y[i] = (a @ w2[e] + b2[e]) * g[i, e]
        out[s * shard:(s + 1) * shard] = y.reshape(shard, T, D)
    return out


class TestMoE:
    def test_matches_dense_reference(self, mesh, params):
        rng = np.random.RandomState(0)
        x = jnp.asarray(rng.randn(B, T, D).astype("float32"))
        y, aux = jax.jit(lambda x, p: moe_ffn(
            x, p, mesh, "expert", capacity_factor=2.0))(x, params)
        ref = _reference(x, params, capacity_factor=2.0)
        np.testing.assert_allclose(np.asarray(y), ref, atol=2e-4,
                                   rtol=2e-4)
        assert np.isfinite(float(aux))
        # balanced-ish init: aux near 1 (perfect balance == 1 for switch)
        assert 0.5 < float(aux) < 4.0

    def test_capacity_drops_tokens(self, mesh, params):
        rng = np.random.RandomState(1)
        x = jnp.asarray(rng.randn(B, T, D).astype("float32"))
        y_small, _ = jax.jit(lambda x, p: moe_ffn(
            x, p, mesh, "expert", capacity_factor=0.25))(x, params)
        y_big, _ = jax.jit(lambda x, p: moe_ffn(
            x, p, mesh, "expert", capacity_factor=4.0))(x, params)
        # tight capacity zeroes some token outputs that loose capacity keeps
        small_zeros = (np.abs(np.asarray(y_small)).sum(-1) == 0).sum()
        big_zeros = (np.abs(np.asarray(y_big)).sum(-1) == 0).sum()
        assert small_zeros > big_zeros

    def test_grads_flow(self, mesh, params):
        rng = np.random.RandomState(2)
        x = jnp.asarray(rng.randn(B, T, D).astype("float32"))

        def loss(p, x):
            y, aux = moe_ffn(x, p, mesh, "expert", capacity_factor=2.0)
            return jnp.mean(y ** 2) + 0.01 * aux

        g = jax.jit(jax.grad(loss))(params, x)
        for leaf, name in zip(g, ("gate_w", "w1", "b1", "w2", "b2")):
            arr = np.asarray(leaf)
            assert np.isfinite(arr).all(), name
        # expert weights receive signal
        assert np.abs(np.asarray(g[1])).sum() > 0

    def test_divisibility_guards(self, mesh, params):
        x = jnp.zeros((6, T, D))  # 6 % 4 != 0
        with pytest.raises(ValueError, match="divisible"):
            moe_ffn(x, params, mesh, "expert")
