"""Async dispatch pipeline (ISSUE 4): lazy fetch handles, the
single-sync-point return_numpy path, device-resident double-buffered
feeds, the streamed predictor, and their interaction with the NaN
step-guard — all bit-exact against the synchronous paths (the async
plumbing must never change a numeric result, only when the host waits).
"""

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import pipeline as pl
from paddle_tpu.executor import FetchHandle, Scope, scope_guard
from paddle_tpu.inference import (AnalysisConfig, create_paddle_predictor)

BATCHES = 6
BS = 16


def build_mlp():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[8], dtype="float32")
        y = fluid.layers.data("y", shape=[1], dtype="int64")
        h = fluid.layers.fc(x, size=16, act="relu")
        pred = fluid.layers.fc(h, size=4, act="softmax")
        loss = fluid.layers.reduce_mean(
            fluid.layers.cross_entropy(input=pred, label=y))
        test_prog = main.clone(for_test=True)
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    return main, startup, test_prog, loss


def make_batches(n=BATCHES, bs=BS, seed=0):
    rng = np.random.RandomState(seed)
    return [{"x": rng.randn(bs, 8).astype("float32"),
             "y": rng.randint(0, 4, (bs, 1)).astype("int64")}
            for _ in range(n)]


def run_sync(main, startup, loss, batches):
    """Reference path: blocking numpy fetch per step, plain feeds."""
    exe = fluid.Executor(fluid.CPUPlace())
    scope = Scope()
    with scope_guard(scope):
        exe.run(startup)
        losses = [exe.run(main, feed=b, fetch_list=[loss])[0]
                  for b in batches]
    return losses, scope


def scope_params(scope):
    return {n: np.asarray(scope.get(n)) for n in sorted(scope.vars)
            if scope.get(n) is not None}


class TestAsyncTrainBitExact:
    @pytest.mark.parametrize("depth", [1, 2, 4])
    def test_async_loop_matches_sync(self, depth):
        """Device-pipelined feeds + lazy fetch handles at depth 1/2/4
        produce bit-identical losses AND parameters (same compiled
        step, same inputs — the async path only changes when the host
        blocks)."""
        main, startup, _, loss = build_mlp()
        batches = make_batches()
        ref_losses, ref_scope = run_sync(main, startup, loss, batches)

        exe = fluid.Executor(fluid.CPUPlace())
        scope = Scope()
        with scope_guard(scope):
            exe.run(startup)
            handles = []
            for feed in pl.DeviceFeedPipeline(iter(batches), depth=depth):
                (h,) = exe.run(main, feed=feed, fetch_list=[loss],
                               return_numpy=False)
                handles.append(h)
            got = pl.materialize(handles)
        for a, b in zip(ref_losses, got):
            np.testing.assert_array_equal(a, b)
        ref_params = scope_params(ref_scope)
        got_params = scope_params(scope)
        assert set(ref_params) == set(got_params)
        for n, v in ref_params.items():
            np.testing.assert_array_equal(v, got_params[n], err_msg=n)

    def test_return_numpy_true_single_sync(self):
        """The return_numpy=True path issues ONE batched sync after the
        whole step is dispatched — not one per fetch value."""
        main, startup, _, loss = build_mlp()
        (batch,) = make_batches(1)
        exe = fluid.Executor(fluid.CPUPlace())
        with scope_guard(Scope()):
            exe.run(startup)
            exe.run(main, feed=batch, fetch_list=[loss])  # warm the jit
            pl.reset_sync_stats()
            outs = exe.run(main, feed=batch, fetch_list=[loss, loss, loss])
        assert pl.sync_stats()["syncs"] == 1
        assert all(isinstance(o, np.ndarray) for o in outs)


class TestFetchHandleLaziness:
    def test_no_sync_until_materialized(self):
        main, startup, _, loss = build_mlp()
        (batch,) = make_batches(1)
        exe = fluid.Executor(fluid.CPUPlace())
        with scope_guard(Scope()):
            exe.run(startup)
            pl.reset_sync_stats()
            (h,) = exe.run(main, feed=batch, fetch_list=[loss],
                           return_numpy=False)
            assert isinstance(h, FetchHandle)
            assert not h.synced
            # shape/dtype/repr/block_until_ready never sync
            assert h.shape == (1,)
            assert "in-flight" in repr(h) or "synced" in repr(h)
            h.block_until_ready()
            assert pl.sync_stats()["syncs"] == 0
            v = np.asarray(h)
        assert h.synced
        assert pl.sync_stats()["syncs"] == 1
        assert np.isfinite(v).all()
        # cached: a second read is free
        np.testing.assert_array_equal(v, h.numpy())
        assert pl.sync_stats()["syncs"] == 1

    def test_fetch_handle_feeds_next_run(self):
        """A previous run's un-synced FetchHandle can be fed straight
        into another program — chaining stays on device (the raw-device-
        array contract of the pre-handle return_numpy=False path)."""
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.layers.data("x", shape=[8], dtype="float32")
            out = fluid.layers.scale(x, scale=2.0)
        p2 = fluid.Program()
        with fluid.program_guard(p2, fluid.Program()):
            x2 = fluid.layers.data("x", shape=[8], dtype="float32")
            out2 = fluid.layers.scale(x2, scale=3.0)
        xv = np.arange(16, dtype="float32").reshape(2, 8)
        exe = fluid.Executor(fluid.CPUPlace())
        with scope_guard(Scope()):
            (h,) = exe.run(main, feed={"x": xv}, fetch_list=[out],
                           return_numpy=False)
            (r,) = exe.run(p2, feed={"x": h}, fetch_list=[out2])
        np.testing.assert_array_equal(r, xv * 6.0)

    def test_materialize_batches_many_handles_in_one_sync(self):
        main, startup, _, loss = build_mlp()
        batches = make_batches(4)
        exe = fluid.Executor(fluid.CPUPlace())
        with scope_guard(Scope()):
            exe.run(startup)
            handles = [exe.run(main, feed=b, fetch_list=[loss],
                               return_numpy=False)[0] for b in batches]
            pl.reset_sync_stats()
            vals = pl.materialize(handles)
        assert pl.sync_stats()["syncs"] == 1
        assert len(vals) == 4 and all(isinstance(v, np.ndarray)
                                      for v in vals)


class TestAsyncInference:
    def _export_predictor(self, tmp_path):
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.layers.data("x", shape=[6], dtype="float32")
            out = fluid.layers.fc(x, size=3, act="softmax")
        exe = fluid.Executor(fluid.CPUPlace())
        d = str(tmp_path / "m")
        with scope_guard(Scope()):
            exe.run(startup)
            fluid.io.save_inference_model(d, ["x"], [out], exe,
                                          main_program=main)
        return create_paddle_predictor(AnalysisConfig(d))

    def test_run_async_bit_exact(self, tmp_path):
        pred = self._export_predictor(tmp_path)
        xv = np.random.RandomState(0).randn(4, 6).astype("float32")
        (ref,) = pred.run([xv])
        handles = pred.run_async([xv])
        assert isinstance(handles[0], FetchHandle)
        assert not handles[0].synced
        np.testing.assert_array_equal(ref, np.asarray(handles[0]))

    @pytest.mark.parametrize("k", [1, 2, 4])
    def test_run_batches_streams_in_order(self, tmp_path, k):
        pred = self._export_predictor(tmp_path)
        rng = np.random.RandomState(1)
        batches = [[rng.randn(4, 6).astype("float32")] for _ in range(5)]
        refs = [pred.run(b)[0] for b in batches]
        outs = list(pred.run_batches(batches, max_in_flight=k))
        assert len(outs) == len(batches)
        for r, o in zip(refs, outs):
            np.testing.assert_array_equal(r, o[0])

    def test_run_batches_lazy_mode(self, tmp_path):
        pred = self._export_predictor(tmp_path)
        rng = np.random.RandomState(2)
        batches = [[rng.randn(4, 6).astype("float32")] for _ in range(3)]
        outs = list(pred.run_batches(batches, max_in_flight=2,
                                     return_numpy=False))
        assert all(isinstance(o[0], FetchHandle) for o in outs)
        vals = pl.materialize([o[0] for o in outs])
        refs = [pred.run(b)[0] for b in batches]
        for r, v in zip(refs, vals):
            np.testing.assert_array_equal(r, v)


class TestExceptionPropagation:
    def test_prefetch_thread_exception_reaches_consumer(self):
        """A reader that dies mid-epoch must raise in the consumer, not
        hang the queue (the buffered-decorator contract, across the
        device-staging thread)."""
        batches = make_batches(3)

        def bad_source():
            yield batches[0]
            yield batches[1]
            raise ValueError("reader exploded")

        seen = []
        with pytest.raises(ValueError, match="reader exploded"):
            for feed in pl.DeviceFeedPipeline(bad_source):
                seen.append(feed)
        assert len(seen) == 2

    def test_failed_in_flight_step_raises_without_corrupting(self):
        """A bad batch raises at ITS dispatch; handles from earlier
        in-flight steps still materialize."""
        main, startup, _, loss = build_mlp()
        (good,) = make_batches(1)
        bad = {"x": good["x"][:, :5], "y": good["y"]}  # wrong feature dim
        exe = fluid.Executor(fluid.CPUPlace())
        with scope_guard(Scope()):
            exe.run(startup)
            (h,) = exe.run(main, feed=good, fetch_list=[loss],
                           return_numpy=False)
            with pytest.raises(ValueError, match="declares"):
                exe.run(main, feed=bad, fetch_list=[loss],
                        return_numpy=False)
            assert np.isfinite(np.asarray(h)).all()


class TestNanGuardInteraction:
    def test_guard_skips_nan_step_in_async_loop(self, monkeypatch):
        """The resilience step-guard still works under async dispatch:
        its scalar finite flag is the ONE per-step sync, a NaN batch's
        update is skipped bit-exactly, and the loop's fetch handles
        stay materializable."""
        from paddle_tpu.resilience import guard

        monkeypatch.delenv("PADDLE_TPU_NAN_GUARD", raising=False)
        main, startup, _, loss = build_mlp()
        main._nan_guard = True
        batches = make_batches(3)
        nan_batch = {"x": np.full((BS, 8), np.nan, "float32"),
                     "y": batches[0]["y"]}
        exe = fluid.Executor(fluid.CPUPlace())
        scope = Scope()
        guard.stats.reset()
        with scope_guard(scope):
            exe.run(startup)
            (h0,) = exe.run(main, feed=batches[0], fetch_list=[loss],
                            return_numpy=False)
            params_before = scope_params(scope)
            with pytest.warns(guard.NonFiniteStepWarning):
                (h1,) = exe.run(main, feed=nan_batch, fetch_list=[loss],
                                return_numpy=False)
            params_after = scope_params(scope)
            (h2,) = exe.run(main, feed=batches[1], fetch_list=[loss],
                            return_numpy=False)
            l0, l1, l2 = pl.materialize([h0, h1, h2])
        assert guard.stats.skipped_steps == 1
        assert np.isfinite(l0).all() and np.isfinite(l2).all()
        assert np.isnan(l1).all()
        for n, v in params_before.items():
            np.testing.assert_array_equal(v, params_after[n], err_msg=n)


class TestDeviceFeeds:
    def test_device_buffered_stages_arrays(self):
        """double_buffer / device_buffered move ndarray leaves to device
        on the prefetch thread; structure and values survive."""
        from paddle_tpu import reader_decorators as rd

        def reader():
            for i in range(3):
                yield (np.full((2, 2), i, "float32"), i)

        items = list(fluid.layers.double_buffer(
            rd.buffered(reader, 2))())
        assert len(items) == 3
        for i, (arr, scalar) in enumerate(items):
            assert not isinstance(arr, np.ndarray)  # device-resident
            np.testing.assert_array_equal(np.asarray(arr),
                                          np.full((2, 2), i, "float32"))
            assert scalar == i

    def test_pyreader_double_buffer_feeds_executor(self):
        main, startup, _, loss = build_mlp()
        batches = make_batches(3)
        reader = fluid.reader.PyReader(feed_list=[], capacity=4,
                                       use_double_buffer=True)
        reader.decorate_batch_generator(lambda: iter(batches))
        exe = fluid.Executor(fluid.CPUPlace())
        with scope_guard(Scope()):
            exe.run(startup)
            losses = []
            for feed in reader:
                assert not isinstance(feed["x"], np.ndarray)
                losses.append(exe.run(main, feed=feed,
                                      fetch_list=[loss])[0])
        assert len(losses) == 3 and all(np.isfinite(l).all()
                                        for l in losses)

    def test_feed_cache_reuses_placement(self):
        """The SAME host array re-fed across steps (a constant mask, a
        bench batch) transfers once: the executor's placement cache
        returns the identical device array."""
        main, startup, _, loss = build_mlp()
        (batch,) = make_batches(1)
        exe = fluid.Executor(fluid.CPUPlace())
        with scope_guard(Scope()):
            exe.run(startup)
            exe.run(main, feed=batch, fetch_list=[loss])
            dev1 = exe._feed_cache.get("x", batch["x"])
            assert dev1 is not None
            exe.run(main, feed=batch, fetch_list=[loss])
            assert exe._feed_cache.get("x", batch["x"]) is dev1
            # a DIFFERENT array with equal contents also hits: the
            # cache keys on (name, shape, dtype, content) — serving
            # traffic re-sends constants as fresh objects every request
            # (equality is verified in full, not just fingerprinted)
            assert exe._feed_cache.get("x", batch["x"].copy()) is dev1
            # an IN-PLACE mutation of the cached buffer must not serve
            # stale data: the content fingerprint turns it into a miss
            batch["x"][:] = batch["x"] + 1.0
            assert exe._feed_cache.get("x", batch["x"]) is None
            (l2,) = exe.run(main, feed=batch, fetch_list=[loss])
            assert np.isfinite(l2).all()

    def test_materialize_releases_device_buffer(self):
        """A synced handle drops its device reference — windowed loops
        hold device memory O(un-synced window), not O(steps)."""
        main, startup, _, loss = build_mlp()
        (batch,) = make_batches(1)
        exe = fluid.Executor(fluid.CPUPlace())
        with scope_guard(Scope()):
            exe.run(startup)
            (h,) = exe.run(main, feed=batch, fetch_list=[loss],
                           return_numpy=False)
            assert not isinstance(h.device_value, np.ndarray)
            v = h.numpy()
            assert h._dev is None  # device buffer released
            assert isinstance(h.device_value, np.ndarray)
            np.testing.assert_array_equal(v, h.numpy())  # still cached
            assert h.shape == (1,)  # metadata survives the release

    def test_abandoned_iteration_unblocks_worker(self):
        """Breaking out of the loop early must release the prefetch
        thread (it parks in a bounded-queue put) and its staged
        batches, not leak them for the process lifetime."""
        import threading
        import time

        produced = []

        def source():
            for i in range(100):
                produced.append(i)
                yield {"x": np.zeros((2, 2), "float32")}

        pipe = pl.DeviceFeedPipeline(source, depth=2)
        for _ in pipe:
            break  # abandon with the worker mid-stream
        deadline = time.time() + 5.0
        while time.time() < deadline:
            if not any(t.name == "paddle_tpu-device-feed" and t.is_alive()
                       for t in threading.enumerate()):
                break
            time.sleep(0.05)
        assert not any(t.name == "paddle_tpu-device-feed" and t.is_alive()
                       for t in threading.enumerate())
        assert len(produced) < 100  # stopped early, not fully drained

    def test_pipeline_depth_env(self, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_PIPELINE_DEPTH", "4")
        assert pl.pipeline_depth() == 4
        monkeypatch.setenv("PADDLE_TPU_PIPELINE_DEPTH", "0")
        assert pl.pipeline_depth() == 1  # floor
        monkeypatch.delenv("PADDLE_TPU_PIPELINE_DEPTH")
        assert pl.pipeline_depth() == 2  # default


class TestMetricsBatchedSync:
    def test_metrics_accept_device_values(self):
        import jax.numpy as jnp

        from paddle_tpu import metrics

        m = metrics.Precision()
        m.update(jnp.asarray([1.0, 0.0, 1.0, 1.0]),
                 jnp.asarray([1, 0, 0, 1]))
        assert m.eval() == pytest.approx(2.0 / 3.0)
        r = metrics.Recall()
        r.update(np.array([1.0, 0.0]), np.array([1, 1]))  # numpy still ok
        assert r.eval() == pytest.approx(0.5)


class TestHostSyncLint:
    def test_save_in_training_program_flagged(self):
        main, startup, _, loss = build_mlp()
        param = next(n for n in main.global_block().vars
                     if n.startswith("fc_") and n.endswith(".w_0"))
        main.global_block().append_op(
            type="save", inputs={"X": [param]}, outputs={},
            attrs={"file_path": "/tmp/x.npy"})
        diags = main.lint(targets=[loss.name])
        hits = [d for d in diags
                if d.check == "executor-host-sync-in-loop"]
        assert hits, [d.check for d in diags]
        assert "per-step host sync" in hits[0].message

    def test_save_in_while_body_flagged(self):
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            i = fluid.layers.fill_constant([1], "float32", 0.0)
            limit = fluid.layers.fill_constant([1], "float32", 4.0)
            cond = fluid.layers.less_than(i, limit)
            w = fluid.layers.While(cond)
            with w.block():
                fluid.layers.increment(i, value=1.0, in_place=True)
                fluid.default_main_program().current_block().append_op(
                    type="save", inputs={"X": [i.name]}, outputs={},
                    attrs={"file_path": "/tmp/x.npy"})
                fluid.layers.less_than(i, limit, cond=cond)
        diags = main.lint()
        hits = [d for d in diags
                if d.check == "executor-host-sync-in-loop"]
        assert hits
        assert "loop iteration" in hits[0].message

    def test_clean_inference_program_not_flagged(self):
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.layers.data("x", shape=[4], dtype="float32")
            out = fluid.layers.fc(x, size=2)
        diags = main.lint(targets=[out.name])
        assert not [d for d in diags
                    if d.check == "executor-host-sync-in-loop"]


class TestCostDispatchOverhead:
    def test_host_sync_points_and_bench_json(self, monkeypatch):
        import json

        monkeypatch.setenv("PADDLE_TPU_SYNC_LATENCY_MS", "2.5")
        main, startup, _, loss = build_mlp()
        param = next(n for n in main.global_block().vars
                     if n.startswith("fc_") and n.endswith(".w_0"))
        main.global_block().append_op(
            type="save", inputs={"X": [param]}, outputs={},
            attrs={"file_path": "/tmp/x.npy"})
        rep = main.analyze(targets=[loss.name])
        # one save op + one fetch materialization
        assert rep.cost.host_sync_points == 2
        assert rep.cost.dispatch_overhead_ms == pytest.approx(5.0)
        lines = [json.loads(l) for l in rep.cost.bench_json().splitlines()]
        metrics = {l["metric"]: l["value"] for l in lines}
        assert metrics["static_host_sync_points"] == 2
        assert metrics["static_dispatch_overhead_ms"] == pytest.approx(5.0)


class TestDatasetRuntimeContract:
    def test_train_from_dataset_returns_numpy(self, tmp_path):
        """run_from_dataset drives the device pipeline + fetch handles
        internally but still returns numpy per step (and stays
        bit-exact across print windows)."""
        from paddle_tpu.dataset import DatasetFactory

        f = tmp_path / "part-0"
        rng = np.random.RandomState(0)
        lines = []
        for _ in range(24):
            label = rng.randint(0, 2)
            feat = " ".join("%.4f" % v for v in rng.randn(4))
            lines.append("1 %d 4 %s" % (label, feat))
        f.write_text("\n".join(lines) + "\n")

        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            label = fluid.layers.data("label", shape=[1], dtype="int64")
            dense = fluid.layers.data("dense", shape=[4],
                                      dtype="float32")
            logit = fluid.layers.fc(dense, size=1)
            loss = fluid.layers.mean(
                fluid.layers.sigmoid_cross_entropy_with_logits(
                    logit, fluid.layers.cast(label, "float32")))
            fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)

        ds = DatasetFactory().create_dataset("InMemoryDataset")
        ds.set_use_var([label, dense])
        ds.set_batch_size(8)
        ds.set_filelist([str(f)])
        ds.load_into_memory()

        exe = fluid.Executor(fluid.CPUPlace())
        with scope_guard(Scope()):
            exe.run(startup)
            results = exe.train_from_dataset(
                program=main, dataset=ds, fetch_list=[loss],
                print_period=2)
        assert len(results) == 3  # 24 / 8
        for r in results:
            assert isinstance(r[0], np.ndarray)
            assert np.isfinite(r[0]).all()
