"""Gradient accumulation (multi_batch_merge) + synchronized batch norm.

Reference targets: ``paddle/fluid/framework/ir/multi_batch_merge_pass.cc``
(graph repeated per microbatch, optimizer once on merged grads) and
``operators/sync_batch_norm_op.cu`` + ``ir/sync_batch_norm_pass.cc``
(cross-device stats).  TPU lowering: accumulation is a lax.scan over
microbatch slices; sync BN needs NO pass — under jit+GSPMD the batch-mean
of a batch-sharded tensor IS the global mean (the collective is emitted by
the partitioner), so DP batch-norm stats are always synchronized.  The
oracle for both is per-step loss parity with the plain single-shot run.
"""

import numpy as np

import paddle_tpu as fluid
from paddle_tpu.executor import Scope, scope_guard


def _mlp_model(lr=0.1):
    fluid.unique_name.switch()
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 9
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[12], dtype="float32")
        y = fluid.layers.data("y", shape=[1], dtype="int64")
        h = fluid.layers.fc(x, size=24, act="relu")
        logits = fluid.layers.fc(h, size=3)
        loss = fluid.layers.mean(
            fluid.layers.softmax_with_cross_entropy(logits, y))
        fluid.optimizer.SGD(learning_rate=lr).minimize(loss)
    return main, startup, loss


def _bn_model(lr=0.05):
    fluid.unique_name.switch()
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 13
    with fluid.program_guard(main, startup):
        img = fluid.layers.data("img", shape=[3, 8, 8], dtype="float32")
        y = fluid.layers.data("y", shape=[1], dtype="int64")
        c = fluid.layers.conv2d(img, num_filters=8, filter_size=3,
                                padding=1, bias_attr=False)
        c = fluid.layers.batch_norm(c, act="relu")
        p = fluid.layers.pool2d(c, pool_size=8, pool_type="avg")
        logits = fluid.layers.fc(p, size=4)
        loss = fluid.layers.mean(
            fluid.layers.softmax_with_cross_entropy(logits, y))
        fluid.optimizer.SGD(learning_rate=lr).minimize(loss)
    return main, startup, loss


def _mlp_batches(n, bs=32):
    rng = np.random.RandomState(1)
    W = rng.randn(12, 3)
    out = []
    for _ in range(n):
        xv = rng.randn(bs, 12).astype("float32")
        yv = np.argmax(xv @ W, axis=1)[:, None].astype("int64")
        out.append({"x": xv, "y": yv})
    return out


def _bn_batches(n, bs=32):
    rng = np.random.RandomState(2)
    out = []
    for _ in range(n):
        img = rng.randn(bs, 3, 8, 8).astype("float32")
        yv = rng.randint(0, 4, (bs, 1)).astype("int64")
        out.append({"img": img, "y": yv})
    return out


def _train(build_model, batches, data_parallel=False, accum=1):
    main, startup, loss = build_model()
    exe = fluid.Executor(fluid.CPUPlace())
    losses = []
    with scope_guard(Scope()):
        exe.run(startup)
        prog = main
        if data_parallel or accum > 1:
            bs = fluid.BuildStrategy()
            bs.batch_merge_repeat = accum
            prog = fluid.CompiledProgram(main, build_strategy=bs)
            if data_parallel:
                prog = prog.with_data_parallel(loss_name=loss.name,
                                               build_strategy=bs)
        for feed in batches:
            (l,) = exe.run(prog, feed=feed, fetch_list=[loss])
            losses.append(float(np.asarray(l).reshape(())))
    return losses


class TestGradAccumulation:
    def test_accumulation_matches_single_shot(self):
        """k=4 microbatch accumulation on a mean loss is EXACTLY the
        full-batch gradient, so per-step losses must match the plain run
        (fp reassociation tolerance only)."""
        batches = _mlp_batches(6)
        plain = _train(_mlp_model, batches)
        accum = _train(_mlp_model, batches, accum=4)
        np.testing.assert_allclose(accum, plain, rtol=2e-4, atol=2e-4)
        assert plain[-1] < plain[0]

    def test_accumulation_with_data_parallel(self):
        batches = _mlp_batches(6)
        plain = _train(_mlp_model, batches)
        both = _train(_mlp_model, batches, data_parallel=True, accum=2)
        np.testing.assert_allclose(both, plain, rtol=3e-4, atol=3e-4)

    def test_indivisible_batch_raises(self):
        import pytest

        batches = [{"x": np.zeros((10, 12), "float32"),
                    "y": np.zeros((10, 1), "int64")}]
        with pytest.raises(Exception, match="divisible"):
            _train(_mlp_model, batches, accum=4)


class TestSyncBatchNorm:
    def test_dp_batch_norm_stats_are_global(self):
        """8-way DP losses must match single-device: possible only if BN
        statistics are computed over the GLOBAL batch (per-device stats
        would use 32/8=4-sample means and diverge immediately)."""
        batches = _bn_batches(6)
        single = _train(_bn_model, batches)
        dp = _train(_bn_model, batches, data_parallel=True)
        np.testing.assert_allclose(dp, single, rtol=3e-4, atol=3e-4)
        assert single[-1] < single[0]


def test_batch_norm_single_pass_variance_numerics():
    """The BN training stats use the single-pass E[x^2]-E[x]^2 form
    (one activation sweep — +12% ResNet-50 on v5e).  Pin its numerics:
    matches numpy's two-pass variance on ordinary activations, and the
    >=0 clamp keeps constant channels finite (cancellation would
    otherwise produce a small negative under rsqrt)."""
    import numpy as np

    import paddle_tpu as fluid
    from paddle_tpu.executor import Scope, scope_guard

    rng = np.random.RandomState(0)
    x = rng.randn(8, 4, 6, 6).astype("float32") * 3.0 + 5.0
    x[:, 2] = 7.25  # a CONSTANT channel: true var 0
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        xin = fluid.layers.data("x", shape=[4, 6, 6], dtype="float32")
        y = fluid.layers.batch_norm(xin)
    exe = fluid.Executor(fluid.CPUPlace())
    with scope_guard(Scope()):
        exe.run(startup)
        (out,) = exe.run(main, feed={"x": x}, fetch_list=[y])
    out = np.asarray(out)
    assert np.isfinite(out).all()
    # normal channels: matches the reference two-pass normalization
    for c in (0, 1, 3):
        ch = x[:, c]
        ref = (ch - ch.mean()) / np.sqrt(ch.var() + 1e-5)
        np.testing.assert_allclose(out[:, c], ref, atol=2e-4, rtol=2e-4)
    # constant channel: var clamps to ~0 -> output ~(x-mean)*rsqrt(eps)=0
    np.testing.assert_allclose(out[:, 2], 0.0, atol=1e-2)
