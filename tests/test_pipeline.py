"""GPipe pipeline parallelism on the virtual CPU mesh: outputs and grads
must match the sequential stage application (the reference's pipeline
correctness bar: PipelineTrainer results equal single-device results)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from paddle_tpu.parallel import gpipe, gpipe_stage_params

N_STAGES, M, MB, D = 4, 8, 2, 16


def stage_fn(params, x):
    w1, b1, w2, b2 = params
    h = jnp.tanh(x @ w1 + b1)
    return x + h @ w2 + b2


@pytest.fixture(scope="module")
def setup():
    mesh = Mesh(np.array(jax.devices()[:N_STAGES]), ("pipe",))
    rng = np.random.RandomState(0)
    per_stage = [
        (
            jnp.asarray(rng.randn(D, 4 * D).astype("float32") * 0.1),
            jnp.zeros((4 * D,), "float32"),
            jnp.asarray(rng.randn(4 * D, D).astype("float32") * 0.1),
            jnp.zeros((D,), "float32"),
        )
        for _ in range(N_STAGES)
    ]
    stacked = gpipe_stage_params(per_stage)
    x = jnp.asarray(rng.randn(M, MB, D).astype("float32"))
    return mesh, stacked, x


def _sequential(stacked, x):
    def apply_all(x_mb):
        for i in range(N_STAGES):
            params = jax.tree_util.tree_map(lambda p: p[i], stacked)
            x_mb = stage_fn(params, x_mb)
        return x_mb

    return jax.vmap(apply_all)(x)


def test_gpipe_forward_matches_sequential(setup):
    mesh, stacked, x = setup
    y = gpipe(stage_fn, stacked, x, mesh, "pipe", M)
    np.testing.assert_allclose(y, _sequential(stacked, x), atol=1e-5)


def test_gpipe_grads_match_sequential(setup):
    mesh, stacked, x = setup
    g1 = jax.grad(
        lambda s, x: jnp.sum(gpipe(stage_fn, s, x, mesh, "pipe", M) ** 2)
    )(stacked, x)
    g2 = jax.grad(lambda s, x: jnp.sum(_sequential(s, x) ** 2))(stacked, x)
    for a, b in zip(
        jax.tree_util.tree_leaves(g1), jax.tree_util.tree_leaves(g2)
    ):
        np.testing.assert_allclose(a, b, atol=1e-4, rtol=1e-4)


def test_gpipe_under_jit(setup):
    mesh, stacked, x = setup
    y = jax.jit(
        lambda s, x: gpipe(stage_fn, s, x, mesh, "pipe", M)
    )(stacked, x)
    np.testing.assert_allclose(y, _sequential(stacked, x), atol=1e-5)


def test_gpipe_shape_validation(setup):
    mesh, stacked, x = setup
    with pytest.raises(ValueError, match="num_microbatches"):
        gpipe(stage_fn, stacked, x, mesh, "pipe", M + 1)


def test_pipeline_optimizer_api():
    """Reference-API PipelineOptimizer: minimize works (program remains a
    correct single-device program) and pipeline metadata is recorded for
    the runner, mirroring program._pipeline_opt in the reference."""
    import paddle_tpu as fluid

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[4])
        y = fluid.layers.data("y", shape=[1])
        h = fluid.layers.fc(x, size=8, act="relu")
        out = fluid.layers.fc(h, size=1)
        loss = fluid.layers.reduce_mean(
            fluid.layers.square_error_cost(out, y)
        )
        opt = fluid.optimizer.PipelineOptimizer(
            fluid.optimizer.SGD(learning_rate=0.1), num_microbatches=4
        )
        opt.minimize(loss)
    assert main._pipeline_opt["num_microbatches"] == 4
    exe = fluid.Executor(fluid.TPUPlace())
    exe.run(startup)
    xv = np.random.RandomState(0).randn(8, 4).astype("float32")
    yv = np.zeros((8, 1), "float32")
    l0 = exe.run(main, feed={"x": xv, "y": yv}, fetch_list=[loss])[0]
    for _ in range(5):
        l1 = exe.run(main, feed={"x": xv, "y": yv}, fetch_list=[loss])[0]
    assert float(np.asarray(l1).reshape(-1)[0]) < float(
        np.asarray(l0).reshape(-1)[0]
    )


def test_gpipe_3d_dp_tp_pp():
    """dp2×tp2×pp2 composition: data-sharded microbatches, Megatron
    column→row tensor-sharded stage weights (in-stage psum over the
    model axis), GPipe over the pipe axis — output must equal the
    sequential single-device application, and grads must flow."""
    from jax.sharding import PartitionSpec as P

    dp, tp, pp = 2, 2, 2
    mesh3 = Mesh(np.array(jax.devices()[:8]).reshape(dp, tp, pp),
                 ("data", "model", "pipe"))
    D2, H2, M2, MB2 = 8, 16, 4, 4
    rng = np.random.RandomState(1)
    per_stage = [
        (jnp.asarray(rng.randn(D2, H2).astype("float32") * 0.1),
         jnp.zeros((H2,), "float32"),
         jnp.asarray(rng.randn(H2, D2).astype("float32") * 0.1),
         jnp.zeros((D2,), "float32"))
        for _ in range(pp)
    ]
    stacked = gpipe_stage_params(per_stage)
    x = jnp.asarray(rng.randn(M2, MB2, D2).astype("float32"))

    def stage3(params, xm):
        w1, b1, w2, b2 = params
        h = jnp.tanh(xm @ w1 + b1)
        return xm + jax.lax.psum(h @ w2, "model") + b2

    def stage_seq(params, xm):
        w1, b1, w2, b2 = params
        return xm + jnp.tanh(xm @ w1 + b1) @ w2 + b2

    specs = (P("pipe", None, "model"), P("pipe", "model"),
             P("pipe", "model", None), P("pipe"))
    y = jax.jit(lambda s, xin: gpipe(
        stage3, s, xin, mesh3, "pipe", M2,
        param_specs=specs, x_spec=P(None, "data")))(stacked, x)
    expect = x
    for p in per_stage:
        expect = jnp.stack([stage_seq(p, mb) for mb in expect])
    np.testing.assert_allclose(np.asarray(y), np.asarray(expect),
                               atol=1e-5)
    # grads flow through the 3D composition
    g = jax.jit(jax.grad(lambda s: jnp.sum(gpipe(
        stage3, s, x, mesh3, "pipe", M2,
        param_specs=specs, x_spec=P(None, "data")) ** 2)))(stacked)
    assert all(np.isfinite(np.asarray(leaf)).all()
               for leaf in jax.tree_util.tree_leaves(g))


def test_gpipe_param_specs_validation():
    mesh1 = Mesh(np.array(jax.devices()[:4]), ("pipe",))
    from jax.sharding import PartitionSpec as P

    rng = np.random.RandomState(0)
    per_stage = [(jnp.asarray(rng.randn(4, 4).astype("float32")),)
                 for _ in range(4)]
    stacked = gpipe_stage_params(per_stage)
    x = jnp.zeros((2, 2, 4), "float32")
    with pytest.raises(ValueError, match="param_specs"):
        gpipe(lambda p, xm: xm, stacked, x, mesh1, "pipe", 2,
              param_specs=(P(None, "pipe"),))
