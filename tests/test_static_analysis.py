"""Program verifier + lint framework (paddle_tpu/static_analysis/).

One positive (clean program) and one negative (seeded bug) case per
check, the fc_fuse/DCE pass regressions the verifier now guards, the
three exposure surfaces (Program.lint / verify_pass in the Analyzer /
the lint CLI), and a representative-programs sweep: book-style models,
control flow, and transpiled distributed programs must all verify clean.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.executor import Scope, scope_guard
from paddle_tpu.static_analysis import (
    Diagnostic,
    Severity,
    VerifyError,
    assert_valid,
    register_check,
    verify_program,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _errors(diags):
    return [d for d in diags if d.severity >= Severity.ERROR]


def _fresh_programs():
    fluid.unique_name.switch()
    return fluid.Program(), fluid.Program()


def _mlp_with_loss():
    main, startup = _fresh_programs()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[4], dtype="float32")
        y = fluid.layers.data("y", shape=[1], dtype="int64")
        h = fluid.layers.fc(x, size=8, act="relu")
        out = fluid.layers.fc(h, size=3)
        loss = fluid.layers.mean(
            fluid.layers.softmax_with_cross_entropy(out, y))
    return main, startup, loss


# ---------------------------------------------------------------------------
# per-check positive/negative pairs
# ---------------------------------------------------------------------------

class TestUseBeforeDef:
    def test_clean(self, verify_clean):
        main, _, loss = _mlp_with_loss()
        verify_clean(main, targets=[loss.name])

    def test_flags_dangling_read(self):
        p = fluid.Program()
        b = p.global_block()
        b.create_var(name="a", shape=[2, 2], dtype="float32")
        b.create_var(name="c", shape=[2, 2], dtype="float32")
        b.append_op(type="scale", inputs={"X": ["a"]},
                    outputs={"Out": ["c"]}, attrs={"scale": 2.0})
        errs = _errors(verify_program(p, targets=["c"]))
        assert [d.check for d in errs] == ["use-before-def"]
        d = errs[0]
        # structured coordinates: check id, severity, op index/type, vars
        assert d.severity is Severity.ERROR
        assert (d.block_idx, d.op_idx, d.op_type) == (0, 0, "scale")
        assert d.var_names == ("a",)
        assert d.hint

    def test_flags_undeclared_var(self):
        p = fluid.Program()
        b = p.global_block()
        b.create_var(name="c", shape=[2], dtype="float32")
        b.append_op(type="scale", inputs={"X": ["ghost"]},
                    outputs={"Out": ["c"]}, attrs={"scale": 1.0})
        errs = _errors(verify_program(p, targets=["c"]))
        assert errs and errs[0].check == "use-before-def"
        assert "not declared" in errs[0].message

    def test_sub_block_use_of_late_parent_def(self):
        """A var the sub-block reads but the parent defines only AFTER
        the control-flow op is a use-before-def, not a false pass."""
        main, startup = _fresh_programs()
        with fluid.program_guard(main, startup):
            x = fluid.layers.data("x", shape=[2, 4], dtype="float32",
                                  append_batch_size=False)
            pred = fluid.layers.fill_constant([1], "bool", True)
            scale = fluid.layers.scale(x, scale=3.0)
            out = fluid.layers.cond(
                pred, lambda: fluid.layers.scale(scale, scale=1.0),
                lambda: fluid.layers.scale(x, scale=-1.0))
        block = main.global_block()
        # move the producer of `scale` after the conditional blocks
        prod = next(op for op in block.ops
                    if scale.name in op.output_arg_names)
        block.ops.remove(prod)
        block.ops.append(prod)
        errs = _errors(verify_program(main, targets=[out.name]))
        assert any(d.check == "use-before-def"
                   and scale.name in d.var_names for d in errs)


class TestDoubleWrite:
    def test_in_place_update_is_clean(self, verify_clean):
        """sgd's ParamOut==Param read-modify-write must not be flagged."""
        main, startup, loss = _mlp_with_loss()
        with fluid.program_guard(main, startup):
            fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
        verify_clean(main, targets=[loss.name])

    def test_flags_blind_overwrite_of_persistable(self):
        p = fluid.Program()
        b = p.global_block()
        b.create_var(name="x", shape=[2], dtype="float32", is_data=True)
        b.create_var(name="w", shape=[2], dtype="float32", persistable=True)
        for s in (1.0, 2.0):
            b.append_op(type="scale", inputs={"X": ["x"]},
                        outputs={"Out": ["w"]}, attrs={"scale": s})
        errs = _errors(verify_program(p))
        assert [d.check for d in errs] == ["double-write"]
        assert "donation" in errs[0].message

    def test_dead_write_to_temp_is_warning(self):
        p = fluid.Program()
        b = p.global_block()
        b.create_var(name="x", shape=[2], dtype="float32", is_data=True)
        b.create_var(name="t", shape=[2], dtype="float32")
        for s in (1.0, 2.0):
            b.append_op(type="scale", inputs={"X": ["x"]},
                        outputs={"Out": ["t"]}, attrs={"scale": s})
        diags = verify_program(p, targets=["t"])
        dw = [d for d in diags if d.check == "double-write"]
        assert dw and dw[0].severity is Severity.WARNING

    def test_sub_block_closure_read_counts_as_read(self, verify_clean):
        """write t → branch body reads t by closure only (no slot on the
        conditional_block op) → write t again: the closure read makes the
        second write a legitimate refresh, not a dead first write."""
        main, startup = _fresh_programs()
        with fluid.program_guard(main, startup):
            x = fluid.layers.data("x", shape=[2, 4], dtype="float32",
                                  append_batch_size=False)
            t = fluid.layers.scale(x, scale=2.0)        # write #1
            pred = fluid.layers.fill_constant([1], "bool", True)
            out = fluid.layers.cond(
                pred, lambda: fluid.layers.scale(t, scale=1.0),
                lambda: fluid.layers.scale(x, scale=-1.0))
            block = main.global_block()
            block.append_op(type="assign", inputs={"X": [out.name]},
                            outputs={"Out": [t.name]})  # write #2
        diags = verify_clean(main, targets=[out.name, t.name])
        assert not [d for d in diags if d.check == "double-write"]

    def test_conditional_merge_is_clean(self, verify_clean):
        """Both branches of cond() assign the merge var — CF ops merge,
        they don't blindly overwrite."""
        main, startup = _fresh_programs()
        with fluid.program_guard(main, startup):
            x = fluid.layers.data("x", shape=[2, 4], dtype="float32",
                                  append_batch_size=False)
            pred = fluid.layers.fill_constant([1], "bool", True)
            out = fluid.layers.cond(
                pred, lambda: fluid.layers.scale(x, scale=1.0),
                lambda: fluid.layers.scale(x, scale=-1.0))
        verify_clean(main, targets=[out.name])


class TestShapeDtypeDrift:
    def test_clean_after_append_time_inference(self, verify_clean):
        main, _, loss = _mlp_with_loss()
        verify_clean(main, targets=[loss.name])

    def test_flags_dtype_drift(self):
        p = fluid.Program()
        b = p.global_block()
        b.create_var(name="x", shape=[2, 2], dtype="float32", is_data=True)
        b.create_var(name="y", shape=[2, 2], dtype="float32")
        b.append_op(type="scale", inputs={"X": ["x"]},
                    outputs={"Out": ["y"]}, attrs={"scale": 1.0})
        b.vars["y"].dtype = "int64"  # a rewrite forgot to re-infer
        errs = _errors(verify_program(p, targets=["y"]))
        assert any(d.check == "shape-dtype-drift" for d in errs)

    def test_shape_drift_is_warning(self):
        p = fluid.Program()
        b = p.global_block()
        b.create_var(name="x", shape=[2, 3], dtype="float32", is_data=True)
        b.create_var(name="y", shape=[2, 3], dtype="float32")
        b.append_op(type="scale", inputs={"X": ["x"]},
                    outputs={"Out": ["y"]}, attrs={"scale": 1.0})
        b.vars["y"].shape = (2, 7)
        diags = verify_program(p, targets=["y"])
        drift = [d for d in diags if d.check == "shape-dtype-drift"]
        assert drift and drift[0].severity is Severity.WARNING


class TestOrphanedFetch:
    def test_clean(self, verify_clean):
        main, _, loss = _mlp_with_loss()
        verify_clean(main, targets=[loss.name])

    def test_flags_unproduced_and_missing_targets(self):
        p = fluid.Program()
        b = p.global_block()
        b.create_var(name="x", shape=[2], dtype="float32", is_data=True)
        b.create_var(name="orphan", shape=[2], dtype="float32")
        errs = _errors(verify_program(p, targets=["orphan", "missing"]))
        kinds = sorted(d.check for d in errs)
        assert kinds == ["orphaned-fetch", "orphaned-fetch"]


class TestSubBlockIndex:
    @pytest.mark.parametrize("bad_idx", [99, "1"], ids=["oob", "non-int"])
    def test_flags_bad_index_without_crashing(self, bad_idx):
        """Out-of-range AND non-int sub_block attrs must come back as
        diagnostics from every walker — not TypeError/RecursionError."""
        from paddle_tpu.analysis import (dead_code_elimination_pass,
                                         fc_fuse_pass)

        p = fluid.Program()
        b = p.global_block()
        b.create_var(name="x", shape=[2], dtype="float32", is_data=True)
        b.append_op(type="conditional_block", inputs={"Cond": ["x"]},
                    outputs={}, attrs={"sub_block": bad_idx})
        errs = _errors(verify_program(p, targets=["x"]))
        assert any(d.check == "sub-block-index" for d in errs)
        fc_fuse_pass(p, targets=["x"])
        dead_code_elimination_pass(p, targets=["x"])

    def test_sub_block_cycle_diagnosed_not_recursion_error(self):
        """A sub_block-attr cycle (block 1 ↔ block 2) must produce
        diagnostics, not crash the verifier or the rewrite passes."""
        from paddle_tpu.analysis import dead_code_elimination_pass
        from paddle_tpu.static_analysis import sub_block_reads_recursive

        p = fluid.Program()
        b1 = p._create_block(parent_idx=0)
        b2 = p._create_block(parent_idx=1)
        p.current_block_idx = 0
        b1.append_op(type="while", inputs={}, outputs={},
                     attrs={"sub_block": 2})
        b2.append_op(type="while", inputs={}, outputs={},
                     attrs={"sub_block": 1})
        g = p.global_block()
        g.create_var(name="x", shape=[2], dtype="float32", is_data=True)
        g.append_op(type="while", inputs={"X": ["x"]}, outputs={},
                    attrs={"sub_block": 1})
        diags = verify_program(p, targets=["x"])  # must not recurse forever
        assert isinstance(diags, list)
        # the liveness helper used by fc_fuse/DCE must also terminate
        assert isinstance(sub_block_reads_recursive(p, b1), list)
        dead_code_elimination_pass(p, targets=["x"])

    def test_self_referential_sub_block_flagged(self):
        p = fluid.Program()
        b1 = p._create_block(parent_idx=0)
        p.current_block_idx = 0
        b1.append_op(type="while", inputs={}, outputs={},
                     attrs={"sub_block": 1})
        g = p.global_block()
        g.append_op(type="while", inputs={}, outputs={},
                    attrs={"sub_block": 1})
        errs = _errors(verify_program(p))
        assert any(d.check == "sub-block-index" for d in errs)


class TestCollectiveRing:
    def test_transpiled_programs_clean(self, verify_clean):
        main, startup, loss = _mlp_with_loss()
        with fluid.program_guard(main, startup):
            fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
        cfg = fluid.DistributeTranspilerConfig()
        cfg.mode = "collective"
        t = fluid.DistributeTranspiler(cfg)
        t.transpile(0, program=main, startup_program=startup, trainers=2)
        assert any(op.type == "c_allreduce_sum"
                   for op in main.global_block().ops)
        verify_clean(main, targets=[loss.name])
        verify_clean(startup)

    def test_flags_missing_ring_id(self):
        p = fluid.Program()
        b = p.global_block()
        b.create_var(name="g", shape=[2], dtype="float32", is_data=True)
        b.append_op(type="c_allreduce_sum", inputs={"X": ["g"]},
                    outputs={"Out": ["g"]}, attrs={})
        errs = _errors(verify_program(p, targets=["g"]))
        assert [d.check for d in errs] == ["collective-ring"]

    def test_flags_peerless_send_recv(self):
        p = fluid.Program()
        b = p.global_block()
        b.create_var(name="g", shape=[2], dtype="float32", is_data=True)
        b.append_op(type="send_v2", inputs={"X": ["g"]}, outputs={},
                    attrs={"ring_id": 0})
        errs = _errors(verify_program(p, targets=["g"]))
        assert [d.check for d in errs] == ["collective-ring"]
        assert "peer" in errs[0].message

    def test_asymmetric_pipeline_stage_peers_are_clean(self):
        """A middle pipeline stage recvs from rank-1 and sends to rank+1;
        peer asymmetry within one rank's program is legal."""
        p = fluid.Program()
        b = p.global_block()
        b.create_var(name="g", shape=[2], dtype="float32", is_data=True)
        b.create_var(name="h", shape=[2], dtype="float32")
        b.append_op(type="recv_v2", inputs={}, outputs={"Out": ["h"]},
                    attrs={"ring_id": 0, "peer": 0})
        b.append_op(type="send_v2", inputs={"X": ["h"]}, outputs={},
                    attrs={"ring_id": 0, "peer": 2})
        diags = verify_program(p, targets=["h"])
        assert not [d for d in diags if d.check == "collective-ring"]

    def test_warns_unpaired_comm_init(self):
        p = fluid.Program()
        b = p.global_block()
        b.create_var(name="id0", shape=[1], dtype="int32", persistable=True)
        b.append_op(type="c_gen_nccl_id", outputs={"Out": ["id0"]},
                    attrs={"ring_id": 3})
        diags = verify_program(p)
        ring = [d for d in diags if d.check == "collective-ring"]
        assert ring and ring[0].severity is Severity.WARNING

    def test_mixed_type_ring_ids_diagnosed_not_crashed(self):
        """int and str ring ids in one malformed program must not blow
        up the sort that orders the unpaired-ring warnings."""
        p = fluid.Program()
        b = p.global_block()
        for name, ring in (("id0", "0"), ("id1", 1)):
            b.create_var(name=name, shape=[1], dtype="int32",
                         persistable=True)
            b.append_op(type="c_gen_nccl_id", outputs={"Out": [name]},
                        attrs={"ring_id": ring})
        diags = verify_program(p)
        ring = [d for d in diags if d.check == "collective-ring"]
        assert len(ring) == 2


class TestUnreferencedOp:
    def test_advisory_on_dead_op(self):
        main, startup = _fresh_programs()
        with fluid.program_guard(main, startup):
            x = fluid.layers.data("x", shape=[4], dtype="float32")
            dead = fluid.layers.scale(x, scale=2.0)
            out = fluid.layers.scale(x, scale=3.0)
        diags = verify_program(main, targets=[out.name])
        assert not _errors(diags)
        unref = [d for d in diags if d.check == "unreferenced-op"]
        assert unref and unref[0].severity is Severity.INFO
        assert dead.name in unref[0].var_names


# ---------------------------------------------------------------------------
# satellite regressions: fc_fuse_pass + DCE control-flow liveness
# ---------------------------------------------------------------------------

class TestFcFusePassFixed:
    def test_chained_pairs_fuse_with_numeric_parity(self, verify_clean):
        from paddle_tpu.analysis import Analyzer, PassBuilder

        rng = np.random.RandomState(7)
        main, startup = _fresh_programs()
        with fluid.program_guard(main, startup):
            x = fluid.layers.data("x", shape=[4], dtype="float32")
            h1 = fluid.layers.fc(x, size=8)
            h2 = fluid.layers.fc(h1, size=8)
            out = fluid.layers.fc(h2, size=3)
        exe = fluid.Executor(fluid.CPUPlace())
        scope = Scope()
        with scope_guard(scope):
            exe.run(startup)
            xv = rng.randn(5, 4).astype("float32")
            before = exe.run(main, feed={"x": xv}, fetch_list=[out])[0]
            Analyzer(PassBuilder(["fc_fuse_pass"])).run(
                main, scope=scope, targets=[out.name])
            after = exe.run(main, feed={"x": xv}, fetch_list=[out])[0]
        types = [op.type for op in main.global_block().ops]
        assert types.count("fc") == 3 and "mul" not in types
        verify_clean(main, targets=[out.name])
        np.testing.assert_allclose(after, before, rtol=1e-5, atol=1e-6)

    def test_add_before_mul_order_is_skipped_not_corrupted(self,
                                                          verify_clean):
        """Adversarial op order (add precedes its mul): the old
        ``ops[i] = fc; del ops[j]`` assumed j > i and corrupted the
        block; the fixed pass skips the pair and the program still
        verifies."""
        from paddle_tpu.analysis import fc_fuse_pass

        main, startup = _fresh_programs()
        with fluid.program_guard(main, startup):
            x = fluid.layers.data("x", shape=[4], dtype="float32")
            h = fluid.layers.fc(x, size=8)
            out = fluid.layers.scale(h, scale=1.0)
        block = main.global_block()
        mul_i = next(i for i, op in enumerate(block.ops)
                     if op.type == "mul")
        add_i = next(i for i, op in enumerate(block.ops)
                     if op.type == "elementwise_add")
        assert add_i > mul_i
        block.ops[mul_i], block.ops[add_i] = (block.ops[add_i],
                                              block.ops[mul_i])
        n_before = len(block.ops)
        fc_fuse_pass(main, targets=[out.name])
        # pair skipped: nothing fused, nothing corrupted, op count intact
        assert len(block.ops) == n_before
        types = [op.type for op in block.ops]
        assert "mul" in types and "elementwise_add" in types

    def test_verifier_flags_broken_fuse_output(self):
        """Simulate the OLD bug's effect — fuse removed the mul but left
        the add reading its output: use-before-def, structured."""
        main, startup = _fresh_programs()
        with fluid.program_guard(main, startup):
            x = fluid.layers.data("x", shape=[4], dtype="float32")
            h = fluid.layers.fc(x, size=8)
        block = main.global_block()
        mul_out = next(op.outputs["Out"][0] for op in block.ops
                       if op.type == "mul")
        block.ops = [op for op in block.ops if op.type != "mul"]
        errs = _errors(verify_program(main, targets=[h.name]))
        assert any(d.check == "use-before-def"
                   and mul_out in d.var_names for d in errs)

    def test_mul_feeding_sub_block_not_fused_away(self, verify_clean):
        """A mul output captured by a conditional_block's closure has no
        visible consumer on any input slot — the fixed pass must count
        sub-block reads as consumers and leave the pair alone unless the
        add is that single consumer."""
        main, startup = _fresh_programs()
        with fluid.program_guard(main, startup):
            x = fluid.layers.data("x", shape=[2, 4], dtype="float32",
                                  append_batch_size=False)
            h = fluid.layers.fc(x, size=4)   # mul + add
            pred = fluid.layers.fill_constant([1], "bool", True)
            block = main.global_block()
            mul_out_name = next(op.outputs["Out"][0] for op in block.ops
                                if op.type == "mul")
            mul_out = block.var(mul_out_name)
            out = fluid.layers.cond(
                pred, lambda: fluid.layers.scale(mul_out, scale=1.0),
                lambda: fluid.layers.scale(x, scale=-1.0))
        from paddle_tpu.analysis import fc_fuse_pass

        fc_fuse_pass(main, targets=[out.name, h.name])
        types = [op.type for op in main.global_block().ops]
        # two consumers now (add + sub-block closure): must not fuse
        assert "mul" in types
        verify_clean(main, targets=[out.name, h.name])


class TestDcePassControlFlow:
    def _cond_program(self):
        main, startup = _fresh_programs()
        with fluid.program_guard(main, startup):
            x = fluid.layers.data("x", shape=[2, 4], dtype="float32",
                                  append_batch_size=False)
            pred = fluid.layers.fill_constant([1], "bool", True)
            scale = fluid.layers.scale(x, scale=3.0)  # read only in branch
            out = fluid.layers.cond(
                pred, lambda: fluid.layers.scale(scale, scale=1.0),
                lambda: fluid.layers.scale(x, scale=-1.0))
        return main, startup, scale, out

    def test_keeps_producers_of_sub_block_reads(self, verify_clean):
        from paddle_tpu.analysis import dead_code_elimination_pass

        main, startup, scale, out = self._cond_program()
        dead_code_elimination_pass(main, targets=[out.name])
        assert any(scale.name in op.output_arg_names
                   for op in main.global_block().ops), \
            "DCE removed the producer of a sub-block-only read"
        verify_clean(main, targets=[out.name])
        exe = fluid.Executor(fluid.CPUPlace())
        with scope_guard(Scope()):
            exe.run(startup)
            r = exe.run(main, feed={"x": np.ones((2, 4), "float32")},
                        fetch_list=[out], verify=True)
        np.testing.assert_allclose(r[0], np.full((2, 4), 3.0, "float32"))

    def test_still_prunes_actually_dead_ops(self):
        from paddle_tpu.analysis import dead_code_elimination_pass

        main, startup, scale, out = self._cond_program()
        with fluid.program_guard(main, startup):
            x_var = main.global_block().var("x")
            fluid.layers.scale(x_var, scale=9.0)  # genuinely dead
        n = len(main.global_block().ops)
        dead_code_elimination_pass(main, targets=[out.name])
        assert len(main.global_block().ops) == n - 1


# ---------------------------------------------------------------------------
# exposure surfaces
# ---------------------------------------------------------------------------

class TestSurfaces:
    def test_program_lint_returns_diagnostics(self):
        main, _, loss = _mlp_with_loss()
        diags = main.lint(targets=[loss.name])
        assert isinstance(diags, list)
        assert not _errors(diags)

    def test_assert_valid_raises_with_structured_payload(self):
        p = fluid.Program()
        b = p.global_block()
        b.create_var(name="c", shape=[2], dtype="float32")
        b.append_op(type="scale", inputs={"X": ["ghost"]},
                    outputs={"Out": ["c"]}, attrs={"scale": 1.0})
        with pytest.raises(VerifyError) as ei:
            assert_valid(p)
        assert ei.value.diagnostics
        assert ei.value.diagnostics[0].check == "use-before-def"

    def test_analyzer_verifies_around_every_pass(self):
        """A pass that breaks the program is caught by the bracketing
        verify with the pass named in the error."""
        from paddle_tpu.analysis import (Analyzer, PassBuilder,
                                         register_pass, _PASSES)

        @register_pass("_test_breaking_pass")
        def _breaking(program, scope=None, targets=None):
            block = program.global_block()
            block.ops = [op for op in block.ops if op.type != "mul"]
            return program

        try:
            main, _, loss = _mlp_with_loss()
            with pytest.raises(VerifyError) as ei:
                Analyzer(PassBuilder(["_test_breaking_pass"])).run(
                    main, targets=[loss.name], verify=True)
            assert "_test_breaking_pass" in str(ei.value)
        finally:
            _PASSES.pop("_test_breaking_pass", None)

    def test_analyzer_default_passes_preserve_numerics(self):
        """Acceptance: the default pipeline under verification changes
        nothing numerically (same guarantee as before, now checked)."""
        from paddle_tpu.analysis import Analyzer

        rng = np.random.RandomState(3)
        main, startup = _fresh_programs()
        with fluid.program_guard(main, startup):
            x = fluid.layers.data("x", shape=[4], dtype="float32")
            h = fluid.layers.fc(x, size=8, act="relu")
            out = fluid.layers.fc(h, size=3)
        exe = fluid.Executor(fluid.CPUPlace())
        scope = Scope()
        with scope_guard(scope):
            exe.run(startup)
            xv = rng.randn(3, 4).astype("float32")
            before = exe.run(main, feed={"x": xv}, fetch_list=[out])[0]
            Analyzer().run(main, scope=scope, targets=[out.name],
                           verify=True)
            after = exe.run(main, feed={"x": xv}, fetch_list=[out])[0]
        np.testing.assert_allclose(after, before, rtol=1e-5, atol=1e-6)

    def test_executor_run_verify_hook(self):
        main, startup = _fresh_programs()
        with fluid.program_guard(main, startup):
            x = fluid.layers.data("x", shape=[4], dtype="float32")
            out = fluid.layers.scale(x, scale=2.0)
        block = main.global_block()
        prod = block.ops[-1]
        block.ops.remove(prod)
        block.ops.append(
            type(prod)(block, "scale", {"X": [out.name]},
                       {"Out": [out.name + ".2"]}, {"scale": 1.0}))
        exe = fluid.Executor(fluid.CPUPlace())
        with scope_guard(Scope()):
            with pytest.raises(VerifyError):
                exe.run(main, feed={"x": np.ones((1, 4), "float32")},
                        fetch_list=[out.name + ".2"], verify=True)

    def test_register_custom_check(self):
        """README contract: custom checks register like passes."""
        from paddle_tpu.static_analysis import checks as checks_mod

        @register_check("no-print-ops")
        def no_print_ops(ctx):
            for block_idx, op_idx, op in ctx.graph.order:
                if op.type == "print":
                    yield ctx.diag(
                        "no-print-ops", Severity.WARNING,
                        "print op in production program",
                        block_idx=block_idx, op_idx=op_idx, op=op)

        try:
            p = fluid.Program()
            b = p.global_block()
            b.create_var(name="x", shape=[2], dtype="float32", is_data=True)
            b.append_op(type="print", inputs={"In": ["x"]},
                        outputs={"Out": ["x"]}, attrs={})
            diags = verify_program(p, checks=["no-print-ops"])
            assert [d.check for d in diags] == ["no-print-ops"]
        finally:
            checks_mod._CHECKS.pop("no-print-ops", None)

    def test_unknown_check_id_rejected(self):
        with pytest.raises(KeyError):
            verify_program(fluid.Program(), checks=["no-such-check"])


# ---------------------------------------------------------------------------
# lint CLI
# ---------------------------------------------------------------------------

def _save_model(tmp_path, break_it=False):
    main, startup = _fresh_programs()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[4], dtype="float32")
        out = fluid.layers.fc(x, size=2)
    exe = fluid.Executor(fluid.CPUPlace())
    d = str(tmp_path / ("broken" if break_it else "ok"))
    with scope_guard(Scope()):
        exe.run(startup)
        fluid.io.save_inference_model(d, ["x"], [out], exe,
                                      main_program=main)
    if break_it:
        # corrupt the saved program: drop the mul so the add dangles
        from paddle_tpu.proto import load_program, save_program

        prog = load_program(os.path.join(d, "__model__"))
        b = prog.global_block()
        b.ops = [op for op in b.ops if op.type != "mul"]
        save_program(prog, os.path.join(d, "__model__"))
    return d


def _run_cli(*args):
    return subprocess.run(
        [sys.executable, "-m", "paddle_tpu.tools.lint_program", *args],
        capture_output=True, text=True, timeout=240,
        env={**os.environ,
             "PYTHONPATH": REPO + os.pathsep + os.environ.get(
                 "PYTHONPATH", ""),
             "JAX_PLATFORMS": "cpu"},
        cwd=REPO)


class TestLintCli:
    def test_clean_model_exits_zero(self, tmp_path):
        d = _save_model(tmp_path)
        res = _run_cli(d)
        assert res.returncode == 0, res.stdout + res.stderr
        assert "clean" in res.stdout

    def test_broken_model_exits_nonzero_with_diagnostics(self, tmp_path):
        d = _save_model(tmp_path, break_it=True)
        res = _run_cli(d)
        assert res.returncode == 1, res.stdout + res.stderr
        assert "use-before-def" in res.stdout

    def test_unknown_check_id_is_clean_usage_error(self, tmp_path):
        d = _save_model(tmp_path)
        res = _run_cli(d, "--checks", "no-such-check,")
        assert res.returncode == 2
        assert "no-such-check" in res.stderr
        assert "Traceback" not in res.stderr

    def test_drift_check_reports_rejected_metadata(self):
        """An op whose lowering raises on the recorded input metadata
        (instead of returning mismatched structs) still yields an ERROR
        — the strongest malformed-metadata signal must not be swallowed."""
        p = fluid.Program()
        b = p.global_block()
        b.create_var(name="a", shape=[2, 3], dtype="float32", is_data=True)
        b.create_var(name="bm", shape=[5, 7], dtype="float32",
                     is_data=True)
        b.create_var(name="o", shape=[2, 7], dtype="float32")
        # contraction dims 3 vs 5 cannot multiply: eval_shape raises.
        # Built via Operator directly (as a rewriting pass would) —
        # append_op would have refused this op at build time.
        from paddle_tpu.framework import Operator

        b.ops.append(Operator(b, "mul", {"X": ["a"], "Y": ["bm"]},
                              {"Out": ["o"]}, {}))
        errs = _errors(verify_program(p, targets=["o"]))
        assert any(d.check == "shape-dtype-drift"
                   and "rejects" in d.message for d in errs)

    def test_json_output_is_structured(self, tmp_path):
        from paddle_tpu.tools.diag_cli import DIAG_SCHEMA_VERSION

        d = _save_model(tmp_path, break_it=True)
        res = _run_cli(d, "--json")
        assert res.returncode == 1
        payload = json.loads(res.stdout)
        assert payload["schema"] == DIAG_SCHEMA_VERSION
        diags = payload["diagnostics"]
        assert any(f["check"] == "use-before-def" for f in diags)
        f = diags[0]
        assert {"check", "severity", "message", "block_idx", "op_idx",
                "op_type", "var_names", "hint"} <= set(f)


# ---------------------------------------------------------------------------
# representative programs: the whole catalog must pass clean on realistic
# graphs (book models, control flow, transpiled dist programs)
# ---------------------------------------------------------------------------

def _book_mlp():
    main, startup = _fresh_programs()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("img", shape=[784], dtype="float32")
        y = fluid.layers.data("label", shape=[1], dtype="int64")
        h = fluid.layers.fc(x, size=128, act="relu")
        h = fluid.layers.fc(h, size=64, act="relu")
        out = fluid.layers.fc(h, size=10, act="softmax")
        loss = fluid.layers.mean(fluid.layers.cross_entropy(out, y))
        fluid.optimizer.Adam(learning_rate=1e-3).minimize(loss)
    return [(main, [loss.name]), (startup, None)]


def _book_conv_bn():
    main, startup = _fresh_programs()
    with fluid.program_guard(main, startup):
        img = fluid.layers.data("img", shape=[3, 8, 8], dtype="float32")
        c = fluid.layers.conv2d(img, num_filters=4, filter_size=3,
                                padding=1, bias_attr=False)
        c = fluid.layers.batch_norm(c)
        p = fluid.layers.pool2d(c, pool_size=8, pool_type="avg")
        out = fluid.layers.fc(p, size=2)
    return [(main, [out.name]), (startup, None)]


def _control_flow_while_grad():
    main, startup = _fresh_programs()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[2, 4], dtype="float32",
                              append_batch_size=False)
        w = fluid.layers.create_parameter([4, 4], "float32", name="w")
        i = fluid.layers.fill_constant([1], "int64", 0)
        n = fluid.layers.fill_constant([1], "int64", 3)
        acc = fluid.layers.fill_constant([2, 4], "float32", 0.0)
        cond_v = fluid.layers.less_than(i, n)
        wl = fluid.layers.While(cond_v, max_trip_count=8)
        with wl.block():
            h = fluid.layers.mul(x, w)
            fluid.layers.assign(fluid.layers.elementwise_add(acc, h), acc)
            fluid.layers.increment(i)
            fluid.layers.assign(fluid.layers.less_than(i, n), cond_v)
        loss = fluid.layers.mean(acc)
        fluid.optimizer.SGD(learning_rate=0.01).minimize(loss)
    return [(main, [loss.name]), (startup, None)]


def _static_rnn():
    main, startup = _fresh_programs()
    with fluid.program_guard(main, startup):
        seq = fluid.layers.data("seq", shape=[5, 2, 4], dtype="float32",
                                append_batch_size=False)
        rnn = fluid.layers.StaticRNN()
        with rnn.step():
            xt = rnn.step_input(seq)
            mem = rnn.memory(shape=[4], batch_ref=xt, init_value=0.0)
            nh = fluid.layers.elementwise_add(mem, xt)
            rnn.update_memory(mem, nh)
            rnn.step_output(nh)
        out = rnn()
        loss = fluid.layers.mean(out)
    return [(main, [loss.name]), (startup, None)]


def _transpiled_collective():
    main, startup = _fresh_programs()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[4], dtype="float32")
        out = fluid.layers.fc(x, size=2)
        loss = fluid.layers.mean(out)
        fluid.optimizer.SGD(learning_rate=0.01).minimize(loss)
    cfg = fluid.DistributeTranspilerConfig()
    cfg.mode = "collective"
    t = fluid.DistributeTranspiler(cfg)
    t.transpile(0, program=main, startup_program=startup, trainers=2)
    return [(main, [loss.name]), (startup, None)]


@pytest.mark.parametrize("builder", [
    _book_mlp, _book_conv_bn, _control_flow_while_grad, _static_rnn,
    _transpiled_collective,
], ids=["book-mlp", "book-conv-bn", "while-grad", "static-rnn",
        "dist-collective"])
def test_exemplar_programs_lint_clean(builder, verify_clean):
    """Fast tier-1 sweep: the verifier itself is exercised on every run
    against realistic programs — and must stay silent on them."""
    for program, targets in builder():
        verify_clean(program, targets=targets)
