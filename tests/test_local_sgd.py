"""LocalSGD (reference ``transpiler/collective.py:263``): snapshot
params at sync, train locally, allreduce the parameter DELTAS.  Wired
through ``DistributeTranspiler(mode='local_sgd')`` and the fleet
``DistributedStrategy.use_local_sgd`` knob."""

import numpy as np

import paddle_tpu as fluid
from paddle_tpu.executor import Scope, scope_guard


def _build(lr=0.05, seed=9):
    fluid.unique_name.switch()
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = seed
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[8], dtype="float32")
        y = fluid.layers.data("y", shape=[1], dtype="float32")
        h = fluid.layers.fc(x, size=16, act="relu")
        pred = fluid.layers.fc(h, size=1)
        loss = fluid.layers.reduce_mean(
            fluid.layers.square_error_cost(input=pred, label=y))
        fluid.optimizer.SGD(learning_rate=lr).minimize(loss)
    return main, startup, loss


def _batches(n=6, bs=16):
    r = np.random.RandomState(5)
    out = []
    for _ in range(n):
        xb = r.randn(bs, 8).astype("float32")
        out.append({"x": xb,
                    "y": (xb.sum(1, keepdims=True) > 0).astype(
                        "float32")})
    return out


def _train(prog, startup, loss, dp):
    exe = fluid.Executor(fluid.CPUPlace())
    sc = Scope()
    with scope_guard(sc):
        exe.run(startup)
        run = prog
        if dp:
            run = fluid.CompiledProgram(prog).with_data_parallel(
                loss_name=loss.name)
        ls = [float(np.asarray(exe.run(run, feed=f,
                                       fetch_list=[loss])[0])
                    .reshape(-1)[0]) for f in _batches()]
    return ls


def test_transpile_structure_and_training():
    """mode='local_sgd' inserts per-param snapshot/delta/allreduce/
    restore chains after the optimizer, snapshots init in startup, and
    the program still trains (single-process GSPMD: the delta
    allreduce is consistency-preserving)."""
    main, startup, loss = _build()
    t = fluid.DistributeTranspiler()
    t.config.mode = "local_sgd"
    t.transpile(trainer_id=0, program=main, trainers=2,
                startup_program=startup)
    types = [op.type for op in main.global_block().ops]
    # 4 params (2 w + 2 b): each gets sub, allreduce, sub, assign
    assert types.count("c_allreduce_sum") == 4
    assert types.count("assign") >= 4
    snap_inits = [op for op in startup.global_block().ops
                  if op.type == "assign"]
    assert len(snap_inits) == 4
    assert any(n.endswith("@SNAPSHOT")
               for n in main.global_block().vars)
    # allreduce pre-scales by 1/nranks
    ar = next(op for op in main.global_block().ops
              if op.type == "c_allreduce_sum")
    assert abs(ar.attrs["pre_scale"] - 0.5) < 1e-9
    ls = _train(main, startup, loss, dp=True)
    assert all(np.isfinite(ls))
    assert ls[-1] < ls[0], ls


def test_local_sgd_single_process_matches_plain():
    """Under single-process GSPMD the delta-allreduce averages
    identical replicas — training equals the plain program."""
    main, startup, loss = _build()
    plain = _train(main, startup, loss, dp=True)
    main2, startup2, loss2 = _build()
    t = fluid.DistributeTranspiler()
    t.config.mode = "local_sgd"
    t.transpile(trainer_id=0, program=main2, trainers=2,
                startup_program=startup2)
    wrapped = _train(main2, startup2, loss2, dp=True)
    np.testing.assert_allclose(wrapped, plain, rtol=1e-5, atol=1e-6)


def test_fleet_use_local_sgd_knob():
    """The strategy knob routes through CollectiveOptimizer; with 2
    trainers recorded, the local-SGD chain is inserted (worker_num=1 is
    a clean no-op — LocalSGD skips for nranks<=1)."""
    from paddle_tpu.incubate.fleet.collective import (
        CollectiveOptimizer, DistributedStrategy)

    fluid.unique_name.switch()
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[8], dtype="float32")
        y = fluid.layers.data("y", shape=[1], dtype="float32")
        pred = fluid.layers.fc(x, size=1)
        loss = fluid.layers.reduce_mean(
            fluid.layers.square_error_cost(input=pred, label=y))
        main._num_trainers = 2  # topology as a 2-worker fleet records it
        strategy = DistributedStrategy()
        strategy.use_local_sgd = True
        opt = CollectiveOptimizer(
            fluid.optimizer.SGD(learning_rate=0.05), strategy)
        opt.minimize(loss, startup_program=startup)
    types = [op.type for op in main.global_block().ops]
    assert "c_allreduce_sum" in types
    assert any(n.endswith("@SNAPSHOT")
               for n in main.global_block().vars)


class TestLocalSGDDeltaAverageUnderPsum:
    """shard_map 2-worker oracle (the geo-SGD test's pattern): diverged
    workers must land on the delta-average after the LocalSGD tail runs
    with a REAL psum."""

    def test_diverged_workers_average(self):
        import jax.numpy as jnp
        from jax.sharding import Mesh, PartitionSpec as P
        from jax.experimental.shard_map import shard_map
        import jax

        from paddle_tpu.executor import _run_ops_into_env
        from paddle_tpu.ops import registry as op_registry
        from paddle_tpu.transpiler.collective import LocalSGD

        fluid.unique_name.switch()
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            fluid.layers.create_parameter([4], "float32", name="w")
        LocalSGD().transpile(program=main, startup_program=startup,
                             rank=0, nranks=2)
        block = main.global_block()
        mesh = Mesh(np.array(jax.devices()[:2]), ("workers",))

        def per_worker(w, snap):
            ctx = op_registry.LoweringContext(mode="train")
            ctx.collective_axis = "workers"
            env = {"w": w[0], "w@SNAPSHOT": snap[0]}
            _run_ops_into_env(block, env, ctx)
            return env["w"][None], env["w@SNAPSHOT"][None]

        f = shard_map(per_worker, mesh=mesh,
                      in_specs=(P("workers"), P("workers")),
                      out_specs=(P("workers"), P("workers")))
        snap = np.tile(np.arange(4, dtype="float32"), (2, 1))
        # locally-trained params drifted by -1 and -3 from the snapshot
        w = snap - np.array([[1.0], [3.0]], "float32")
        w2, s2 = (np.asarray(v) for v in
                  f(jnp.asarray(w), jnp.asarray(snap)))
        # delta = snap - w = (+1, +3); mean 2 → w = snap - 2 on BOTH
        np.testing.assert_allclose(w2, snap - 2.0)
        # snapshot re-arms to the synced params
        np.testing.assert_allclose(s2, w2)
