"""Inference stack tests (reference: inference/tests/api/*,
unittests/test_inference_model_io.py, test_inference_transpiler.py —
save → load → predict round-trips and pass-preserves-output checks)."""

import numpy as np

import paddle_tpu as fluid
from paddle_tpu.executor import Scope, scope_guard
from paddle_tpu.inference import (
    AnalysisConfig, create_paddle_predictor, fuse_conv_bn,
    InferenceTranspiler)


def _build_convbn_model():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        img = fluid.layers.data("img", shape=[3, 8, 8], dtype="float32")
        conv = fluid.layers.conv2d(img, num_filters=4, filter_size=3,
                                   padding=1)
        bn = fluid.layers.batch_norm(conv, act="relu")
        conv2 = fluid.layers.conv2d(bn, num_filters=4, filter_size=3,
                                    padding=1)
        bn2 = fluid.layers.batch_norm(conv2)
        pool = fluid.layers.pool2d(bn2, pool_size=8, pool_type="avg")
        logits = fluid.layers.fc(pool, size=3)
        label = fluid.layers.data("label", shape=[1], dtype="int64")
        loss = fluid.layers.mean(
            fluid.layers.softmax_with_cross_entropy(logits, label))
        test_prog = main.clone(for_test=True)
        fluid.optimizer.SGD(learning_rate=0.01).minimize(loss)
    return main, startup, test_prog, img, label, logits, loss


class TestFuseConvBn:
    def test_fold_preserves_output(self):
        main, startup, test_prog, img, label, logits, loss = \
            _build_convbn_model()
        exe = fluid.Executor(fluid.CPUPlace())
        rng = np.random.RandomState(0)
        x = rng.rand(2, 3, 8, 8).astype("float32")
        y = rng.randint(0, 3, size=(2, 1)).astype("int64")
        scope = Scope()
        with scope_guard(scope):
            exe.run(startup)
            # a few steps so bn stats are non-trivial
            for _ in range(5):
                exe.run(main, feed={"img": x, "label": y},
                        fetch_list=[loss])
            (before,) = exe.run(test_prog, feed={"img": x, "label": y},
                                fetch_list=[logits])
            n_bn = sum(op.type == "batch_norm"
                       for op in test_prog.global_block().ops)
            assert n_bn == 2
            fused = fuse_conv_bn(test_prog, scope)
            assert fused == 2
            assert not any(op.type == "batch_norm"
                           for op in test_prog.global_block().ops)
            (after,) = exe.run(test_prog, feed={"img": x, "label": y},
                               fetch_list=[logits])
        np.testing.assert_allclose(before, after, rtol=1e-4, atol=1e-5)

    def test_transpiler_surface(self):
        main, startup, test_prog, img, label, logits, loss = \
            _build_convbn_model()
        exe = fluid.Executor(fluid.CPUPlace())
        scope = Scope()
        with scope_guard(scope):
            exe.run(startup)
            InferenceTranspiler().transpile(test_prog, fluid.CPUPlace(),
                                            scope)
        assert not any(op.type == "batch_norm"
                       for op in test_prog.global_block().ops)


class TestAnalysisPredictor:
    def test_save_load_predict(self, tmp_path):
        main, startup, test_prog, img, label, logits, loss = \
            _build_convbn_model()
        exe = fluid.Executor(fluid.CPUPlace())
        rng = np.random.RandomState(1)
        x = rng.rand(2, 3, 8, 8).astype("float32")
        y = rng.randint(0, 3, size=(2, 1)).astype("int64")
        model_dir = str(tmp_path / "model")
        with scope_guard(Scope()):
            exe.run(startup)
            for _ in range(3):
                exe.run(main, feed={"img": x, "label": y},
                        fetch_list=[loss])
            (expect,) = exe.run(test_prog, feed={"img": x, "label": y},
                                fetch_list=[logits])
            fluid.io.save_inference_model(
                model_dir, ["img"], [logits], exe, main_program=test_prog)

        for ir_optim in (False, True):
            config = AnalysisConfig(model_dir)
            config.switch_ir_optim(ir_optim)
            pred = create_paddle_predictor(config)
            assert pred.get_input_names() == ["img"]
            (got,) = pred.run([x])
            np.testing.assert_allclose(got, expect, rtol=1e-4, atol=1e-5)
            has_bn = any(op.type == "batch_norm"
                         for op in pred.program.global_block().ops)
            assert has_bn == (not ir_optim)

    def test_predictors_isolated(self, tmp_path):
        """Two predictors own separate scopes (reference: per-predictor
        sub-scope in analysis_predictor.cc)."""
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.layers.data("x", shape=[4], dtype="float32")
            out = fluid.layers.fc(x, size=2)
        exe = fluid.Executor(fluid.CPUPlace())
        d = str(tmp_path / "m")
        with scope_guard(Scope()):
            exe.run(startup)
            fluid.io.save_inference_model(d, ["x"], [out], exe,
                                          main_program=main)
        p1 = create_paddle_predictor(AnalysisConfig(d))
        p2 = create_paddle_predictor(AnalysisConfig(d))
        xv = np.ones((1, 4), "float32")
        r1 = p1.run([xv])[0]
        # clobber p2's params; p1 must be unaffected
        p2._scope.set(p2.program.all_parameters()[0].name,
                      np.zeros_like(p2._scope.get(
                          p2.program.all_parameters()[0].name)))
        r1b = p1.run([xv])[0]
        np.testing.assert_array_equal(r1, r1b)
