"""Inference stack tests (reference: inference/tests/api/*,
unittests/test_inference_model_io.py, test_inference_transpiler.py —
save → load → predict round-trips and pass-preserves-output checks)."""

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.executor import Scope, scope_guard
from paddle_tpu.inference import (
    AnalysisConfig, create_paddle_predictor, fuse_conv_bn,
    InferenceTranspiler)


def _build_convbn_model():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        img = fluid.layers.data("img", shape=[3, 8, 8], dtype="float32")
        conv = fluid.layers.conv2d(img, num_filters=4, filter_size=3,
                                   padding=1)
        bn = fluid.layers.batch_norm(conv, act="relu")
        conv2 = fluid.layers.conv2d(bn, num_filters=4, filter_size=3,
                                    padding=1)
        bn2 = fluid.layers.batch_norm(conv2)
        pool = fluid.layers.pool2d(bn2, pool_size=8, pool_type="avg")
        logits = fluid.layers.fc(pool, size=3)
        label = fluid.layers.data("label", shape=[1], dtype="int64")
        loss = fluid.layers.mean(
            fluid.layers.softmax_with_cross_entropy(logits, label))
        test_prog = main.clone(for_test=True)
        fluid.optimizer.SGD(learning_rate=0.01).minimize(loss)
    return main, startup, test_prog, img, label, logits, loss


class TestFuseConvBn:
    def test_fold_preserves_output(self):
        main, startup, test_prog, img, label, logits, loss = \
            _build_convbn_model()
        exe = fluid.Executor(fluid.CPUPlace())
        rng = np.random.RandomState(0)
        x = rng.rand(2, 3, 8, 8).astype("float32")
        y = rng.randint(0, 3, size=(2, 1)).astype("int64")
        scope = Scope()
        with scope_guard(scope):
            exe.run(startup)
            # a few steps so bn stats are non-trivial
            for _ in range(5):
                exe.run(main, feed={"img": x, "label": y},
                        fetch_list=[loss])
            (before,) = exe.run(test_prog, feed={"img": x, "label": y},
                                fetch_list=[logits])
            n_bn = sum(op.type == "batch_norm"
                       for op in test_prog.global_block().ops)
            assert n_bn == 2
            fused = fuse_conv_bn(test_prog, scope)
            assert fused == 2
            assert not any(op.type == "batch_norm"
                           for op in test_prog.global_block().ops)
            (after,) = exe.run(test_prog, feed={"img": x, "label": y},
                               fetch_list=[logits])
        np.testing.assert_allclose(before, after, rtol=1e-4, atol=1e-5)

    def test_transpiler_surface(self):
        main, startup, test_prog, img, label, logits, loss = \
            _build_convbn_model()
        exe = fluid.Executor(fluid.CPUPlace())
        scope = Scope()
        with scope_guard(scope):
            exe.run(startup)
            InferenceTranspiler().transpile(test_prog, fluid.CPUPlace(),
                                            scope)
        assert not any(op.type == "batch_norm"
                       for op in test_prog.global_block().ops)


class TestAnalysisPredictor:
    def test_save_load_predict(self, tmp_path):
        main, startup, test_prog, img, label, logits, loss = \
            _build_convbn_model()
        exe = fluid.Executor(fluid.CPUPlace())
        rng = np.random.RandomState(1)
        x = rng.rand(2, 3, 8, 8).astype("float32")
        y = rng.randint(0, 3, size=(2, 1)).astype("int64")
        model_dir = str(tmp_path / "model")
        with scope_guard(Scope()):
            exe.run(startup)
            for _ in range(3):
                exe.run(main, feed={"img": x, "label": y},
                        fetch_list=[loss])
            (expect,) = exe.run(test_prog, feed={"img": x, "label": y},
                                fetch_list=[logits])
            fluid.io.save_inference_model(
                model_dir, ["img"], [logits], exe, main_program=test_prog)

        for ir_optim in (False, True):
            config = AnalysisConfig(model_dir)
            config.switch_ir_optim(ir_optim)
            pred = create_paddle_predictor(config)
            assert pred.get_input_names() == ["img"]
            (got,) = pred.run([x])
            np.testing.assert_allclose(got, expect, rtol=1e-4, atol=1e-5)
            has_bn = any(op.type == "batch_norm"
                         for op in pred.program.global_block().ops)
            assert has_bn == (not ir_optim)

    def test_predictors_isolated(self, tmp_path):
        """Two predictors own separate scopes (reference: per-predictor
        sub-scope in analysis_predictor.cc)."""
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.layers.data("x", shape=[4], dtype="float32")
            out = fluid.layers.fc(x, size=2)
        exe = fluid.Executor(fluid.CPUPlace())
        d = str(tmp_path / "m")
        with scope_guard(Scope()):
            exe.run(startup)
            fluid.io.save_inference_model(d, ["x"], [out], exe,
                                          main_program=main)
        p1 = create_paddle_predictor(AnalysisConfig(d))
        p2 = create_paddle_predictor(AnalysisConfig(d))
        xv = np.ones((1, 4), "float32")
        r1 = p1.run([xv])[0]
        # clobber p2's params; p1 must be unaffected
        p2._scope.set(p2.program.all_parameters()[0].name,
                      np.zeros_like(p2._scope.get(
                          p2.program.all_parameters()[0].name)))
        r1b = p1.run([xv])[0]
        np.testing.assert_array_equal(r1, r1b)


def test_fc_fuse_and_dce_passes():
    """fc_fuse_pass folds mul+add(bias) into one fc op; DCE prunes ops
    off the target path; outputs unchanged (reference
    ir/fc_fuse_pass.cc + analysis memory passes)."""
    from paddle_tpu.analysis import Analyzer, PassBuilder

    rng = np.random.RandomState(0)
    fluid.unique_name.switch()
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[4], dtype="float32")
        h = fluid.layers.fc(x, size=8, act=None)      # mul + add
        dead = fluid.layers.fc(x, size=16)            # not on target path
        out = fluid.layers.fc(h, size=3)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = Scope()
    with scope_guard(scope):
        exe.run(startup)
        xv = rng.randn(5, 4).astype("float32")
        before = exe.run(main, feed={"x": xv}, fetch_list=[out])[0]
        n_ops_before = len(main.global_block().ops)
        Analyzer(PassBuilder(["fc_fuse_pass",
                              "dead_code_elimination_pass"])).run(
            main, scope=scope, targets=[out.name])
        n_ops_after = len(main.global_block().ops)
        after = exe.run(main, feed={"x": xv}, fetch_list=[out])[0]
    assert n_ops_after < n_ops_before
    types = [op.type for op in main.global_block().ops]
    assert "fc" in types and "elementwise_add" not in types
    # the dead fc's mul is gone
    assert types.count("mul") == 0
    np.testing.assert_allclose(after, before, rtol=1e-5, atol=1e-6)


def test_predictor_runs_analysis_pipeline(tmp_path):
    rng = np.random.RandomState(1)
    fluid.unique_name.switch()
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        img = fluid.layers.data("img", shape=[3, 8, 8], dtype="float32")
        c = fluid.layers.conv2d(img, num_filters=4, filter_size=3,
                                padding=1, bias_attr=False)
        c = fluid.layers.batch_norm(c)
        p = fluid.layers.pool2d(c, pool_size=8, pool_type="avg")
        out = fluid.layers.fc(p, size=2)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = Scope()
    with scope_guard(scope):
        exe.run(startup)
        xv = rng.randn(2, 3, 8, 8).astype("float32")
        # oracle must run BN in inference mode (moving stats), like the
        # exported model does
        test_prog = main.clone(for_test=True)
        ref = exe.run(test_prog, feed={"img": xv}, fetch_list=[out])[0]
        fluid.io.save_inference_model(
            str(tmp_path), ["img"], [out], exe, main)
    cfg = fluid.inference.AnalysisConfig(model_dir=str(tmp_path))
    pred = fluid.inference.create_paddle_predictor(cfg)
    got = pred.run([xv])[0]
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-5)
    types = [op.type for op in pred.program.global_block().ops]
    assert "batch_norm" not in types  # folded
    assert "fc" in types              # fused


def test_fc_fuse_preserves_fetched_intermediate():
    """Regression: fusing must not erase a var that is itself a target."""
    from paddle_tpu.analysis import Analyzer, PassBuilder

    fluid.unique_name.switch()
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[4], dtype="float32")
        h = fluid.layers.fc(x, size=8)
    block = main.global_block()
    mul_out = next(op.outputs["Out"][0] for op in block.ops
                   if op.type == "mul")
    exe = fluid.Executor(fluid.CPUPlace())
    scope = Scope()
    with scope_guard(scope):
        exe.run(startup)
        Analyzer(PassBuilder(["fc_fuse_pass"])).run(
            main, scope=scope, targets=[mul_out, h.name])
        xv = np.ones((2, 4), "float32")
        outs = exe.run(main, feed={"x": xv}, fetch_list=[mul_out, h])
    assert all(np.isfinite(o).all() for o in outs)
    types = [op.type for op in main.global_block().ops]
    assert "mul" in types  # fusion skipped, target still produced


def test_gn_resize_model_inference_roundtrip(tmp_path):
    """Round-4 layers survive the inference export: a GN + resize vision
    net saves via save_inference_model, reloads through the
    AnalysisPredictor pipeline, and reproduces its outputs exactly."""
    rng = np.random.RandomState(0)
    fluid.unique_name.switch()
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        img = fluid.layers.data("img", shape=[3, 8, 8], dtype="float32")
        up = fluid.layers.resize_bilinear(img, out_shape=[16, 16])
        conv = fluid.layers.conv2d(up, 4, 3, padding=1)
        gn = fluid.layers.group_norm(conv, groups=2, act="relu")
        pool = fluid.layers.pool2d(gn, 2, global_pooling=True)
        out = fluid.layers.fc(pool, size=3)
    exe = fluid.Executor(fluid.CPUPlace())
    d = str(tmp_path / "gn_model")
    xv = rng.randn(2, 3, 8, 8).astype("float32")
    with scope_guard(Scope()):
        exe.run(startup)
        (direct,) = exe.run(main, feed={"img": xv}, fetch_list=[out])
        fluid.io.save_inference_model(d, ["img"], [out], exe,
                                      main_program=main)
    with scope_guard(Scope()):
        prog, feeds, fetches = fluid.io.load_inference_model(d, exe)
        (loaded,) = exe.run(prog, feed={feeds[0]: xv},
                            fetch_list=fetches)
    np.testing.assert_allclose(loaded, direct, rtol=1e-5)

    cfg = AnalysisConfig(d)
    predictor = create_paddle_predictor(cfg)
    (pred_out,) = predictor.run({"img": xv})
    # predictor may run on the TPU while `direct` came from CPU: same
    # tolerance as test_predictor_runs_analysis_pipeline
    np.testing.assert_allclose(np.asarray(pred_out), direct, rtol=1e-4,
                               atol=1e-5)


def test_predictor_run_return_numpy_false(tmp_path):
    """return_numpy=False returns device arrays without a host sync —
    the serving-style pipelining contract bench.py's inference
    benchmark relies on (block once at the end)."""
    import jax

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[4], dtype="float32")
        out = fluid.layers.fc(x, size=3)
    exe = fluid.Executor(fluid.CPUPlace())
    d = str(tmp_path / "m")
    with scope_guard(Scope()):
        exe.run(startup)
        fluid.io.save_inference_model(d, ["x"], [out], exe,
                                      main_program=main)
    pred = create_paddle_predictor(AnalysisConfig(d))
    xv = np.arange(8, dtype="float32").reshape(2, 4)
    outs = [pred.run([xv], return_numpy=False) for _ in range(3)]
    jax.block_until_ready(outs)
    (ref,) = pred.run([xv])
    for o in outs:
        assert not isinstance(o[0], np.ndarray)
        np.testing.assert_allclose(np.asarray(o[0]), ref, rtol=1e-6)


def test_analysis_config_enable_bf16_after_fold(tmp_path):
    """enable_bf16 rewrites AFTER the analysis passes: conv+bn folding
    must see the clean conv->bn producer chain (a pre-export bf16
    rewrite would cast-sandwich every bn and defeat the fold — the
    bench.py inference-headline bug this switch exists to prevent)."""
    from paddle_tpu.models.resnet import resnet_cifar10

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        img = fluid.layers.data("img", shape=[3, 32, 32],
                                dtype="float32")
        logits = resnet_cifar10(img, 10, 20, is_test=True)
    d = str(tmp_path / "m")
    exe = fluid.Executor(fluid.CPUPlace())
    with scope_guard(Scope()):
        exe.run(startup)
        fluid.io.save_inference_model(d, ["img"], [logits], exe,
                                      main_program=main)
    x = np.random.RandomState(0).randn(2, 3, 32, 32).astype("float32")

    ref_pred = create_paddle_predictor(AnalysisConfig(d))
    (ref,) = ref_pred.run([x])

    cfg = AnalysisConfig(d)
    cfg.enable_bf16()
    pred = create_paddle_predictor(cfg)
    ops = [op.type for op in pred.program.global_block().ops]
    assert ops.count("batch_norm") == 0, "fold defeated by bf16 casts"
    assert ops.count("cast") > 0, "bf16 rewrite missing"
    (got,) = pred.run([x])
    # bf16 numerics, scale-relative: error accumulates over 20 bf16
    # conv layers (near-zero logit elements make elementwise-relative
    # meaningless) — far outside fp32 noise (proves the bf16 graph
    # actually executed), far inside correctness tolerance
    err = np.abs(got.astype("float32") - ref).max() / np.abs(ref).max()
    assert 1e-6 < err < 0.05, err


def test_conv_bn_fold_nhwc(tmp_path):
    """The conv+bn fold handles channels-last: filter scaling is
    layout-independent (OIHW per output channel), only the replacement
    bias-add's broadcast axis differs (last vs 1)."""
    from paddle_tpu.models.resnet import resnet_cifar10

    fluid.unique_name.switch()
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        img = fluid.layers.data("img", shape=[32, 32, 3],
                                dtype="float32")
        logits = resnet_cifar10(img, 10, 8, is_test=True,
                                data_format="NHWC")
    d = str(tmp_path / "m")
    x = np.random.RandomState(0).randn(2, 32, 32, 3).astype("float32")
    exe = fluid.Executor(fluid.CPUPlace())
    with scope_guard(Scope()):
        exe.run(startup)
        (ref,) = exe.run(main, feed={"img": x}, fetch_list=[logits])
        fluid.io.save_inference_model(d, ["img"], [logits], exe,
                                      main_program=main)
    pred = create_paddle_predictor(AnalysisConfig(d))
    ops = [op.type for op in pred.program.global_block().ops]
    assert ops.count("batch_norm") == 0, "NHWC fold did not fire"
    (got,) = pred.run([x])
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-5)


def test_predictor_params_promoted_to_device_once(tmp_path):
    """The analysis passes compute in numpy: ``fuse_conv_bn`` writes
    the FOLDED weights into the predictor scope as host arrays.  The
    executor must promote those to device arrays ON FIRST RUN and
    write the promotion back — otherwise every dispatch re-transfers
    the whole weight set (on the axon tunnel this made ResNet-50
    inference 30x slower than its own training step: r05 hw window 2,
    2.8 s/batch).  A conv+bn model is essential here: a pure-fc export
    reloads as jax arrays and the test would pass vacuously."""
    main, startup, test_prog, img, label, logits, loss = \
        _build_convbn_model()
    exe = fluid.Executor(fluid.CPUPlace())
    path = str(tmp_path / "m")
    with scope_guard(Scope()):
        exe.run(startup)
        fluid.io.save_inference_model(path, ["img"], [logits], exe,
                                      main_program=test_prog)

    cfg = fluid.inference.AnalysisConfig(model_dir=path)
    pred = fluid.inference.create_paddle_predictor(cfg)
    # the conv+bn fold must have left host numpy in the scope — the
    # precondition that makes this test able to catch a regression
    assert any(isinstance(pred._scope.get(n), np.ndarray)
               and pred._scope.get(n).ndim > 0
               for n in pred._scope.local_var_names())
    feed = {"img": np.random.RandomState(0)
            .randn(2, 3, 8, 8).astype("float32")}
    o1 = pred.run(feed)[0]
    numpy_left = [n for n in pred._scope.local_var_names()
                  if isinstance(pred._scope.get(n), np.ndarray)
                  and pred._scope.get(n).ndim > 0]
    # every weight the run read must now live on device (numpy gone)
    assert not numpy_left, numpy_left
    # and the promotion must not change results across runs
    o2 = pred.run(feed)[0]
    np.testing.assert_allclose(np.asarray(o1), np.asarray(o2))


def test_feed_check_survives_inference_model_roundtrip(tmp_path):
    """need_check_feed / feed_hint must round-trip through
    save_inference_model: a loaded serving program feeding a wrong
    inner dim should fail fast with the targeted data-layer ValueError,
    not a jit shape error deep inside the step."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[8], dtype="float32")
        x.feed_hint = "x is the 8-wide feature row"
        out = fluid.layers.fc(x, size=2)
    exe = fluid.Executor(fluid.CPUPlace())
    d = str(tmp_path / "m")
    with scope_guard(Scope()):
        exe.run(startup)
        fluid.io.save_inference_model(d, ["x"], [out], exe,
                                      main_program=main)
        iprog, feeds, fetches = fluid.io.load_inference_model(d, exe)
        v = iprog.global_block().vars[feeds[0]]
        assert v.need_check_feed
        assert v.feed_hint == "x is the 8-wide feature row"
        with pytest.raises(ValueError, match="declares"):
            exe.run(iprog, feed={feeds[0]: np.zeros((4, 5), "float32")},
                    fetch_list=fetches)
