"""Sharded-embedding (PS-replacement) path: is_distributed=True tables
row-shard over the mesh data axis under DP.

Reference parity target: the distributed lookup table
(``transpiler/distribute_transpiler.py:353-376`` slices the table across
pservers; ``operators/distributed/parameter_prefetch.cc`` exchanges ids by
RPC).  TPU-native: GSPMD partitions lookup + scatter-grad over ICI; the
oracle is per-step loss parity vs the single-device run (the
test_dist_base bar)."""

import numpy as np

import paddle_tpu as fluid
from paddle_tpu.executor import Scope, scope_guard
from paddle_tpu.models import ctr

VOCAB = 4096  # divisible by the 8-device mesh
N_SLOTS, SLOT_LEN, DENSE = 3, 5, 8


def _build(is_distributed, lr=0.05):
    fluid.unique_name.switch()
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 11
    with fluid.program_guard(main, startup):
        slots = [
            fluid.layers.data("slot%d" % i, shape=[SLOT_LEN], dtype="int64")
            for i in range(N_SLOTS)
        ]
        dense = fluid.layers.data("dense", shape=[DENSE], dtype="float32")
        label = fluid.layers.data("label", shape=[1], dtype="int64")
        loss, prob = ctr.wide_deep(
            slots, dense, label, vocab=VOCAB, embed_dim=16,
            hidden=(32, 32), is_distributed=is_distributed)
        fluid.optimizer.Adam(learning_rate=lr).minimize(loss)
    return main, startup, loss


def _batches(n_steps, bs=32):
    rng = np.random.RandomState(3)
    out = []
    for _ in range(n_steps):
        slots = [
            rng.randint(0, VOCAB, (bs, SLOT_LEN)).astype("int64")
            for _ in range(N_SLOTS)
        ]
        dense = rng.randn(bs, DENSE).astype("float32")
        label = rng.randint(0, 2, (bs, 1)).astype("int64")
        out.append((slots, dense, label))
    return out


def _run(data_parallel, is_distributed, n_steps=6):
    main, startup, loss = _build(is_distributed)
    exe = fluid.Executor(fluid.CPUPlace())
    losses = []
    scope = Scope()
    with scope_guard(scope):
        exe.run(startup)
        prog = main
        if data_parallel:
            prog = fluid.CompiledProgram(main).with_data_parallel(
                loss_name=loss.name)
        for slots, dense, label in _batches(n_steps):
            feed = {"slot%d" % i: s for i, s in enumerate(slots)}
            feed["dense"] = dense
            feed["label"] = label
            (l,) = exe.run(prog, feed=feed, fetch_list=[loss])
            losses.append(float(np.asarray(l).reshape(())))
        table = scope.get("deep_emb_0")
    return losses, table


class TestShardedEmbedding:
    def test_sharded_table_matches_single_device(self):
        """8-way DP with the table sharded 8 ways reproduces the
        single-device per-step losses, and the table actually lives
        row-sharded across the mesh."""
        single, _ = _run(data_parallel=False, is_distributed=False)
        sharded, table = _run(data_parallel=True, is_distributed=True)
        np.testing.assert_allclose(sharded, single, rtol=3e-4, atol=3e-4)
        assert single[-1] < single[0]
        # the updated table returned to scope is row-sharded over 8 devices
        import jax

        assert len(table.sharding.device_set) == 8
        spec = table.sharding.spec
        assert spec and spec[0] == "data", spec
        # each device holds VOCAB/8 rows
        shard = table.addressable_shards[0]
        assert shard.data.shape == (VOCAB // 8, 16), shard.data.shape

    def test_distributed_param_marked(self):
        main, startup, _ = _build(is_distributed=True)
        w = main.global_block().var("deep_emb_0")
        assert getattr(w, "_is_distributed", False)
        # adam moments of the table inherit the mark
        dist_accums = [
            v for v in main.global_block().vars.values()
            if getattr(v, "_is_distributed", False)
            and "moment" in v.name and "deep_emb_0" in v.name
        ]
        assert len(dist_accums) == 2, [v.name for v in dist_accums]
