"""incubate.data_generator: the user-subclassed raw-line → MultiSlot
text converter must emit records the dataset pipeline parses back into
the same slots (full round trip through DatasetFactory)."""

import io
import sys

import numpy as np

import paddle_tpu as fluid
from paddle_tpu.incubate.data_generator import (
    DataGenerator, MultiSlotDataGenerator, MultiSlotStringDataGenerator)


class _CTRGen(MultiSlotDataGenerator):
    def generate_sample(self, line):
        def local_iter():
            if line is None:
                return
            toks = line.split()
            yield [("words", [int(t) for t in toks[:-1]]),
                   ("label", [int(toks[-1])])]

        return local_iter


class TestDataGenerator:
    def test_gen_str_and_type_tracking(self):
        g = MultiSlotDataGenerator()
        s = g._gen_str([("words", [1926, 8, 17]), ("label", [1])])
        assert s == "3 1926 8 17 1 1\n"
        assert g._proto_info == [("words", "uint64"), ("label", "uint64")]
        g._gen_str([("words", [1.5, 2]), ("label", [0])])
        assert g._proto_info[0] == ("words", "float")

    def test_string_generator(self):
        g = MultiSlotStringDataGenerator()
        s = g._gen_str([("q", ["11", "22"]), ("y", ["1"])])
        assert s == "2 11 22 1 1\n"

    def test_run_from_stdin_roundtrip(self, tmp_path, monkeypatch):
        raw = "5 6 7 1\n8 9 0\n"
        out = io.StringIO()
        monkeypatch.setattr(sys, "stdin", io.StringIO(raw))
        monkeypatch.setattr(sys, "stdout", out)
        g = _CTRGen()
        g.set_batch(1)
        g.run_from_stdin()
        sys.stdout = sys.__stdout__
        text = out.getvalue()
        assert text == "3 5 6 7 1 1\n2 8 9 1 0\n"

        # the emitted file feeds the dataset pipeline end to end
        data_file = tmp_path / "part-0.txt"
        data_file.write_text(text)
        ds = fluid.DatasetFactory().create_dataset("QueueDataset")
        ds.set_batch_size(2)
        ds.set_filelist([str(data_file)])
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            words = fluid.layers.data("words", shape=[3], dtype="int64")
            label = fluid.layers.data("label", shape=[1], dtype="int64")
        ds.set_use_var([words, label])
        batches = list(ds.batch_iterator())
        assert len(batches) == 1
        feed = batches[0]
        w = np.asarray(feed["words"])
        assert w.shape[0] == 2
        assert set(np.asarray(feed["label"]).reshape(-1)) == {0, 1}

    def test_base_raises(self):
        g = DataGenerator()
        try:
            g._gen_str([])
            assert False
        except NotImplementedError:
            pass
