"""Dygraph (eager) mode: tape autograd, layers, optimizers, checkpoints
(reference tests: unittests/test_imperative_basic.py,
test_imperative_mnist.py, test_imperative_checkpoint.py)."""

import numpy as np

import paddle_tpu as fluid
from paddle_tpu.dygraph import (
    guard, to_variable, Linear, Conv2D, Pool2D, BatchNorm, Embedding,
    Layer, Dropout, save_dygraph, load_dygraph, no_grad,
)


def test_eager_arithmetic_and_backward():
    with guard():
        x = to_variable(np.array([[1.0, 2.0], [3.0, 4.0]], "float32"))
        x.stop_gradient = False
        y = x * x + 2.0
        from paddle_tpu.dygraph.varbase import eager_op

        loss = eager_op("mean", {"X": [y]})[0]
        loss.backward()
        g = x.gradient()
    np.testing.assert_allclose(g, 2 * np.array([[1, 2], [3, 4]]) / 4,
                               rtol=1e-5)


def test_linear_layer_trains_sgd():
    rng = np.random.RandomState(0)
    w_true = rng.randn(4, 1).astype("float32")
    with guard():
        model = Linear(4, 1)
        opt = fluid.optimizer.SGD(learning_rate=0.1)
        losses = []
        for _ in range(100):
            xv = rng.randn(16, 4).astype("float32")
            x = to_variable(xv)
            target = to_variable(xv @ w_true)
            pred = model(x)
            diff = pred - target
            from paddle_tpu.dygraph.varbase import eager_op

            loss = eager_op("mean", {"X": [diff * diff]})[0]
            loss.backward()
            opt.minimize(loss, parameter_list=model.parameters())
            model.clear_gradients()
            losses.append(float(loss.numpy()[0]))
    assert losses[-1] < 1e-2, (losses[0], losses[-1])


class _MNISTNet(Layer):
    def __init__(self):
        super().__init__()
        self.conv = Conv2D(1, 8, 3, padding=1)
        self.pool = Pool2D(2, "max", 2)
        self.bn = BatchNorm(8)
        self.fc = Linear(8 * 14 * 14, 10)
        self.dropout = Dropout(0.2)

    def forward(self, x):
        from paddle_tpu.dygraph.varbase import eager_op

        h = self.conv(x)
        h = self.bn(h)
        h = eager_op("relu", {"X": [h]})[0]
        h = self.pool(h)
        h = eager_op("reshape2", {"X": [h]}, {"shape": [0, -1]})[0]
        h = self.dropout(h)
        return self.fc(h)


def test_conv_net_adam_step_and_eval_mode():
    rng = np.random.RandomState(1)
    with guard():
        model = _MNISTNet()
        opt = fluid.optimizer.Adam(learning_rate=1e-3)
        from paddle_tpu.dygraph.varbase import eager_op

        for step in range(3):
            x = to_variable(rng.rand(4, 1, 28, 28).astype("float32"))
            label = to_variable(rng.randint(0, 10, (4, 1)).astype("int64"))
            logits = model(x)
            outs = eager_op(
                "softmax_with_cross_entropy",
                {"Logits": [logits], "Label": [label]},
            )
            loss = eager_op("mean", {"X": [outs[1]]})[0]
            loss.backward()
            opt.minimize(loss, parameter_list=model.parameters())
            model.clear_gradients()
            assert np.isfinite(loss.numpy()).all()
        # eval mode: dropout off, bn uses running stats → deterministic
        model.eval()
        x = to_variable(rng.rand(2, 1, 28, 28).astype("float32"))
        a = model(x).numpy()
        b = model(x).numpy()
        np.testing.assert_allclose(a, b)


def test_embedding_and_state_dict_roundtrip(tmp_path):
    with guard():
        emb = Embedding([50, 8])
        ids = to_variable(np.array([[1], [3]], "int64"))
        out = emb(ids)
        assert out.shape == (2, 8)  # [N,1] ids squeeze (lookup_table_op.cc)
        state = emb.state_dict()
        save_dygraph(state, str(tmp_path / "model"))
        loaded, _ = load_dygraph(str(tmp_path / "model"))
        emb2 = Embedding([50, 8])
        emb2.set_dict(loaded)
        np.testing.assert_allclose(
            emb2.weight.numpy(), emb.weight.numpy()
        )


def test_no_grad_suspends_tape():
    with guard():
        x = to_variable(np.ones((2, 2), "float32"))
        x.stop_gradient = False
        with no_grad():
            y = x * 3.0
        z = x * 2.0
        from paddle_tpu.dygraph.varbase import eager_op

        loss = eager_op("mean", {"X": [z]})[0]
        loss.backward()
        assert x.gradient() is not None
        np.testing.assert_allclose(x.gradient(), 0.5)


def test_new_dygraph_layers_forward_and_train():
    """Second-wave dygraph layers (reference dygraph/nn.py classes):
    eager forward shapes + a grad step through GroupNorm/PRelu/
    Conv2DTranspose."""
    from paddle_tpu.dygraph import (
        guard, to_variable, Conv3D, Conv2DTranspose, GRUUnit, PRelu,
        BilinearTensorProduct, SequenceConv, RowConv, GroupNorm,
        SpectralNorm, TreeConv, NCE)
    from paddle_tpu.dygraph.varbase import eager_op

    rng = np.random.RandomState(0)
    with guard():
        x3 = to_variable(rng.randn(1, 2, 4, 6, 6).astype("float32"))
        assert Conv3D(2, 3, 3, padding=1)(x3).shape == (1, 3, 4, 6, 6)

        x2 = to_variable(rng.randn(1, 2, 5, 5).astype("float32"))
        ct = Conv2DTranspose(2, 4, 2, stride=2)
        y = ct(x2)
        assert y.shape == (1, 4, 10, 10)

        xg = to_variable(rng.randn(2, 6).astype("float32"))
        hp = to_variable(rng.randn(2, 2).astype("float32"))
        hid, rhp, gate = GRUUnit(6)(xg, hp)
        assert hid.shape == (2, 2)

        xp = to_variable(rng.randn(2, 3).astype("float32"))
        assert PRelu("all")(xp).shape == (2, 3)

        a = to_variable(rng.randn(2, 3).astype("float32"))
        b = to_variable(rng.randn(2, 4).astype("float32"))
        assert BilinearTensorProduct(3, 4, 5)(a, b).shape == (2, 5)

        seq = to_variable(rng.randn(2, 6, 3).astype("float32"))
        assert SequenceConv(num_filters=4, filter_size=3,
                            input_dim=3)(seq).shape == (2, 6, 4)
        assert RowConv(future_ctx_size=2, input_dim=3)(seq).shape \
            == (2, 6, 3)

        xc = to_variable(rng.randn(2, 4, 5, 5).astype("float32"))
        gn = GroupNorm(channels=4, groups=2)
        yg = gn(xc)
        assert yg.shape == (2, 4, 5, 5)

        w = to_variable(rng.randn(6, 4).astype("float32"))
        sn = SpectralNorm(weight_shape=[6, 4], power_iters=20)
        wn = sn(w)
        s = np.linalg.svd(wn.numpy(), compute_uv=False)
        np.testing.assert_allclose(s[0], 1.0, rtol=1e-2)

        nodes = to_variable(rng.randn(1, 5, 4).astype("float32"))
        edges = to_variable(np.array([[[0, 1], [1, 2]]], "int64"))
        assert TreeConv(feature_size=4, output_size=6)(
            nodes, edges).shape == (1, 5, 6)

        feats = to_variable(rng.randn(4, 8).astype("float32"))
        labels = to_variable(rng.randint(0, 10, (4, 1)).astype("int64"))
        cost = NCE(10, dim=8, num_neg_samples=3)(feats, labels)
        assert cost.shape == (4, 1)

        # grads flow through a stack of the new layers
        loss = eager_op("mean", {"X": [gn(xc)]})[0]
        loss.backward()
        assert gn.weight.gradient() is not None
