"""Paged KV-cache serving tests (ISSUE 19): the block-pool allocator
invariants (randomized churn), the paged cache ops against numpy
goldens, the paged flash-decode kernel vs its oracle in interpret mode,
the paged DecodeEngine (bit-exact vs the slot ring, kill switch,
backpressure, resize), disaggregated prefill/decode co-residency under
the scope proof, speculative-decoding exactness, the
``decode-cache-unpaged`` lint, and the kv-pool telemetry + trace leg."""

import os

import numpy as np
import pytest

import paddle_tpu as fluid
import paddle_tpu.observability as obs
import paddle_tpu.observability.metrics as om
from paddle_tpu.observability import tracing as tr
from paddle_tpu.ops.pallas import paged_flash_decode as PFD
from paddle_tpu.serving import (BlockAllocator, DecodeEngine,
                                GenerationConfig, KVPoolExhausted,
                                PredictorServer, SpeculativeDecoder,
                                blocks_needed, build_block_table,
                                ngram_draft, paged_kv_enabled)
from paddle_tpu.static_analysis.verifier import VerifyError
from paddle_tpu.tools import trace as trace_cli
from test_serving_decode import TinyModel


@pytest.fixture(autouse=True)
def _fresh(monkeypatch):
    fluid.unique_name.switch()
    for var in ("PADDLE_TPU_TELEMETRY", "PADDLE_TPU_TELEMETRY_DIR",
                "PADDLE_TPU_TELEMETRY_FLUSH", "PADDLE_TPU_TRACING",
                "PADDLE_TPU_STRICT_SYNC", "PADDLE_TPU_PAGED_KV",
                "PADDLE_TPU_PAGED_BLOCK_LEN",
                "PADDLE_TPU_PAGED_MIN_BYTES"):
        monkeypatch.delenv(var, raising=False)
    obs.reset_telemetry()
    yield
    obs.reset_telemetry()


class PagedTinyModel(TinyModel):
    """TinyModel plus the paged builders — the same deterministic
    next-token chain through paged_kv_cache_prefill/write and
    paged_flash_decode (attention folded in at zero weight, so any
    block-routing corruption still poisons the logits)."""

    def build_prefill_paged(self, prompt, plen, table, caches):
        L = prompt.shape[1]
        pf = fluid.layers.cast(prompt, "float32")
        emb = self._embed(fluid.layers.reshape(pf, [L]), L)
        x = fluid.layers.reshape(emb, [1, 1, L, 4])
        k, v = caches[0]
        fluid.layers.paged_kv_cache_prefill(k, x, plen, table)
        fluid.layers.paged_kv_cache_prefill(v, x, plen, table)
        return self._prefill_logits(pf, plen, L)

    def build_step_paged(self, cur, cursors, tables, caches):
        S = cur.shape[0]
        cf = fluid.layers.cast(cur, "float32")
        emb = self._embed(cf, S)
        x = fluid.layers.reshape(emb, [S, 1, 4])
        k, v = caches[0]
        fluid.layers.paged_kv_cache_write(k, x, cursors, tables,
                                          per_row=True)
        fluid.layers.paged_kv_cache_write(v, x, cursors, tables,
                                          per_row=True)
        att = fluid.layers.paged_flash_decode(x, k, v, cursors, tables,
                                              per_row=True)
        return self._step_logits(cf, att, S)


def _engine(model=None, slots=2, max_new=4, name="pg", **kw):
    return DecodeEngine(
        model if model is not None else PagedTinyModel(), slots=slots,
        prompt_buckets=(8,),
        config=GenerationConfig(max_new_tokens=max_new),
        place=fluid.CPUPlace(), name=name, **kw)


def _chain(prompt, n):
    """TinyModel's greedy continuation: next token = last + 1."""
    return [prompt[-1] + 1 + i for i in range(n)]


# ---------------------------------------------------------------------------
# allocator invariants
# ---------------------------------------------------------------------------


class TestBlockAllocator:
    def test_helpers(self):
        assert blocks_needed(0, 8) == 0
        assert blocks_needed(1, 8) == 1
        assert blocks_needed(8, 8) == 1
        assert blocks_needed(9, 8) == 2
        np.testing.assert_array_equal(build_block_table([4, 2], 4),
                                      [4, 2, -1, -1])
        np.testing.assert_array_equal(build_block_table([], 3),
                                      [-1, -1, -1])

    def test_deterministic_order_and_all_or_nothing(self):
        pool = BlockAllocator(4, 8)
        assert pool.allocate(2) == [0, 1]
        assert pool.allocate(1) == [2]
        assert not pool.can_allocate(2)
        with pytest.raises(KVPoolExhausted):
            pool.allocate(2)  # all-or-nothing: list untouched
        assert pool.num_free == 1
        pool.free([1])
        assert pool.allocate(2) == [1, 3]  # LIFO: 1 came back on top

    def test_double_free_and_foreign_ids_rejected(self):
        pool = BlockAllocator(2, 8)
        got = pool.allocate(1)
        pool.free(got)
        with pytest.raises(ValueError):
            pool.free(got)  # double-free
        with pytest.raises(ValueError):
            pool.free([7])  # never owned by anyone

    def test_randomized_churn_conserves_and_never_double_assigns(self):
        """Satellite 5: a seeded admit/retire schedule — a block id is
        owned by at most one request, and free + live always sums to
        the pool size."""
        rng = np.random.RandomState(1234)
        pool = BlockAllocator(17, 4)
        live = {}  # rid -> blocks
        rid = 0
        for _ in range(500):
            if rng.rand() < 0.55 or not live:
                want = blocks_needed(int(rng.randint(1, 30)), 4)
                if pool.can_allocate(want):
                    got = pool.allocate(want)
                    assert len(set(got)) == len(got)
                    live[rid] = got
                    rid += 1
                else:
                    with pytest.raises(KVPoolExhausted):
                        pool.allocate(want)
            else:
                victim = list(live)[int(rng.randint(len(live)))]
                pool.free(live.pop(victim))
            owned = [b for bs in live.values() for b in bs]
            assert len(set(owned)) == len(owned)  # no double-assign
            assert pool.num_free + len(owned) == pool.num_blocks
        for bs in live.values():
            pool.free(bs)
        assert pool.num_free == pool.num_blocks  # nothing leaked


# ---------------------------------------------------------------------------
# paged cache ops vs numpy goldens
# ---------------------------------------------------------------------------


def _run(main, feed, fetch):
    exe = fluid.Executor(fluid.CPUPlace())
    return exe.run(main, feed=feed, fetch_list=fetch)


class TestPagedOps:
    N, H, BL, D = 6, 2, 4, 3

    def _cache_feed(self, rng):
        return rng.randn(self.N, self.H, self.BL,
                         self.D).astype("float32")

    def test_write_routes_through_table_and_drops_unmapped(self):
        rng = np.random.RandomState(0)
        cache_np = self._cache_feed(rng)
        x_np = rng.randn(3, self.H, self.D).astype("float32")
        # stream 0 at cursor 5 -> table[1]=4, offset 1; stream 1 at
        # cursor 2 -> table[0]=2, offset 2; stream 2 unmapped (-1 row)
        cursors = np.array([5, 2, 0], dtype="int32")
        tables = np.array([[1, 4], [2, -1], [-1, -1]], dtype="int32")
        fluid.unique_name.switch()
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            cache = fluid.layers.data(
                "cache", shape=[self.N, self.H, self.BL, self.D],
                dtype="float32", append_batch_size=False)
            x = fluid.layers.data("x", shape=[3, self.H, self.D],
                                  dtype="float32",
                                  append_batch_size=False)
            cur = fluid.layers.data("cur", shape=[3], dtype="int32",
                                    append_batch_size=False)
            tab = fluid.layers.data("tab", shape=[3, 2], dtype="int32",
                                    append_batch_size=False)
            out = fluid.layers.paged_kv_cache_write(
                cache, x, cur, tab, per_row=True, in_place=False)
        got, = _run(main, {"cache": cache_np, "x": x_np,
                           "cur": cursors, "tab": tables}, [out])
        want = cache_np.copy()
        want[4, :, 1, :] = x_np[0]  # cursor 5 = block idx 1, offset 1
        want[2, :, 2, :] = x_np[1]  # cursor 2 = block idx 0, offset 2
        np.testing.assert_array_equal(got, want)  # -1 row dropped

    def test_prefill_scatters_only_real_rows(self):
        rng = np.random.RandomState(1)
        cache_np = np.zeros((self.N, self.H, self.BL, self.D),
                            dtype="float32")
        L = 6
        x_np = rng.randn(1, self.H, L, self.D).astype("float32")
        tables = np.array([3, 1], dtype="int32")
        fluid.unique_name.switch()
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            cache = fluid.layers.data(
                "cache", shape=[self.N, self.H, self.BL, self.D],
                dtype="float32", append_batch_size=False)
            x = fluid.layers.data("x", shape=[1, self.H, L, self.D],
                                  dtype="float32",
                                  append_batch_size=False)
            ln = fluid.layers.data("ln", shape=[1], dtype="int32",
                                   append_batch_size=False)
            tab = fluid.layers.data("tab", shape=[2], dtype="int32",
                                    append_batch_size=False)
            out = fluid.layers.paged_kv_cache_prefill(
                cache, x, ln, tab, in_place=False)
        got, = _run(main, {"cache": cache_np, "x": x_np,
                           "ln": np.array([5], dtype="int32"),
                           "tab": tables}, [out])
        want = cache_np.copy()
        want[3, :, :, :] = x_np[0, :, 0:4, :]  # rows 0..3 -> block 3
        want[1, :, 0, :] = x_np[0, :, 4, :]    # row 4 -> block 1
        # rows >= plen (the padded tail) must NOT land anywhere
        np.testing.assert_array_equal(got, want)

    def test_gather_matches_ring_layout(self):
        rng = np.random.RandomState(2)
        import jax.numpy as jnp

        cache = rng.randn(5, 2, 4, 3).astype("float32")
        table = np.array([[4, 0, -1], [2, 3, 1]], dtype="int32")
        got = np.asarray(PFD.gather_paged_cache(
            jnp.asarray(cache), jnp.asarray(table)))
        assert got.shape == (2, 2, 12, 3)
        np.testing.assert_array_equal(got[0, :, 0:4], cache[4])
        np.testing.assert_array_equal(got[0, :, 4:8], cache[0])
        np.testing.assert_array_equal(got[1, :, 0:4], cache[2])
        np.testing.assert_array_equal(got[1, :, 4:8], cache[3])
        np.testing.assert_array_equal(got[1, :, 8:12], cache[1])


# ---------------------------------------------------------------------------
# paged kernel vs oracle (interpret mode)
# ---------------------------------------------------------------------------


class TestPagedKernelParity:
    @pytest.mark.parametrize("lens_kind", ["full", "ragged", "shallow"])
    def test_kernel_matches_reference(self, monkeypatch, lens_kind):
        """Interpret-mode paged kernel (block-table-indirect DMA +
        online softmax) vs the gather-then-ring-oracle composite:
        <= 1e-5 with a shuffled pool and part-unmapped tables."""
        import jax.numpy as jnp

        monkeypatch.setenv("PADDLE_TPU_PALLAS", "interpret")
        monkeypatch.setenv("PADDLE_TPU_DECODE_MIN_T", "1")
        rng = np.random.RandomState(0)
        S, H, D, BL, MB, N = 2, 2, 64, 16, 8, 20
        q = jnp.asarray(rng.randn(S, H, D).astype("float32"))
        kc = jnp.asarray(rng.randn(N, H, BL, D).astype("float32"))
        vc = jnp.asarray(rng.randn(N, H, BL, D).astype("float32"))
        perm = rng.permutation(N)
        table = np.full((S, MB), -1, dtype="int32")
        table[0, :MB] = perm[:MB]
        table[1, :3] = perm[MB:MB + 3]  # short allocation, -1 tail
        lens = {"full": [MB * BL, 3 * BL],
                "ragged": [37, 41],
                "shallow": [1, 2]}[lens_kind]
        lens = jnp.asarray(lens, jnp.int32)
        table = jnp.asarray(table)
        from paddle_tpu.ops.pallas.flash_attention import _use_pallas
        assert _use_pallas()[0], "interpret mode must engage the kernel"
        o_kernel = PFD.paged_flash_decode(q, kc, vc, lens, table)
        o_ref = PFD.paged_decode_reference(q, kc, vc, lens, table)
        np.testing.assert_allclose(o_kernel, o_ref, rtol=1e-5,
                                   atol=1e-5)

    def test_block_len_divides_max_len(self):
        assert 64 % PFD.paged_block_len(4, 64) == 0
        assert 48 % PFD.paged_block_len(4, 48) == 0
        assert PFD.paged_block_len(4, 8) <= 8


# ---------------------------------------------------------------------------
# the paged engine
# ---------------------------------------------------------------------------


PROMPTS = [[3, 5, 7], [2], [1, 2, 3, 4]]


def _generate_all(eng, prompts=PROMPTS):
    futs = [eng.submit(p) for p in prompts]
    return [f.result(timeout=60)[0] for f in futs]


class TestPagedEngine:
    def test_paged_matches_ring_bit_exactly(self):
        with _engine(TinyModel(), name="ring") as ring:
            assert not ring.stats()["paged"]
            ring_toks = _generate_all(ring)
        fluid.unique_name.switch()
        with _engine(name="paged") as paged:
            st = paged.stats()
            assert st["paged"] and st["block_len"] >= 1
            assert st["kv_blocks_free"] == st["kv_blocks_total"]
            assert _generate_all(paged) == ring_toks
            # equal HBM by default: the pool is exactly the ring's rows
            assert paged.cache_bytes == ring.cache_bytes

    def test_kill_switch_restores_ring_path(self, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_PAGED_KV", "0")
        assert not paged_kv_enabled()
        with _engine(name="ks") as eng:  # paged-capable model
            assert not eng.stats()["paged"]
            assert _generate_all(eng) == [
                _chain(p, 4) for p in PROMPTS]

    def test_explicit_paged_without_builders_raises(self):
        with pytest.raises(ValueError, match="build_prefill_paged"):
            _engine(TinyModel(), paged=True, auto_start=False)

    def test_block_len_must_divide_depth(self):
        with pytest.raises(ValueError, match="divide"):
            _engine(block_len=5, auto_start=False)  # max_len 32

    def test_pool_backpressure_not_failure(self):
        """Six requests through a pool that only fits four: the
        admission loop waits for retirements instead of failing."""
        with _engine(slots=4, num_blocks=4, block_len=8,
                     name="small") as eng:
            futs = [eng.submit([i + 1]) for i in range(6)]
            for i, f in enumerate(futs):
                assert f.result(timeout=60)[0] == _chain([i + 1], 4)
            st = eng.stats()
            assert st["kv_blocks_free"] == st["kv_blocks_total"]

    def test_oversized_request_rejected_up_front(self):
        with _engine(slots=1, num_blocks=1, block_len=8,
                     name="cap") as eng:
            with pytest.raises(ValueError, match="pool"):
                # bucket 8 + 4 new tokens needs 2 blocks; pool holds 1
                eng.submit([1, 2, 3, 4, 5, 6, 7])

    def test_resize_rebuilds_pool(self):
        with _engine(name="rsz") as eng:
            assert eng.submit([2]).result(timeout=60)[0] == _chain(
                [2], 4)
            eng.resize(3)
            assert eng.stats()["kv_blocks_total"] == 3 * eng.max_blocks
            assert eng.submit([2]).result(timeout=60)[0] == _chain(
                [2], 4)

    def test_churn_matches_ring_and_conserves_pool(self):
        """Seeded admit/generate/retire churn (satellite 5): the paged
        engine's outputs stay bit-identical to the slot ring's, and the
        pool drains back to fully free."""
        rng = np.random.RandomState(7)
        prompts = [list(rng.randint(1, 8, size=rng.randint(1, 6)))
                   for _ in range(12)]
        with _engine(TinyModel(), name="cr") as ring:
            ring_toks = _generate_all(ring, prompts)
        fluid.unique_name.switch()
        with _engine(slots=3, num_blocks=6, block_len=8,
                     name="cp") as paged:
            assert _generate_all(paged, prompts) == ring_toks
            st = paged.stats()
            assert st["kv_blocks_free"] == st["kv_blocks_total"]
            assert st["completed"] == len(prompts)


# ---------------------------------------------------------------------------
# disaggregated prefill/decode
# ---------------------------------------------------------------------------


class TestDisaggregation:
    def test_disagg_requires_paged(self, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_PAGED_KV", "0")
        with pytest.raises(ValueError, match="paged"):
            _engine(disaggregate=True, auto_start=False)

    def test_same_tokens_with_handoff_metrics(self):
        with _engine(name="dz", disaggregate=True) as eng:
            assert eng.stats()["disaggregated"]
            assert _generate_all(eng) == [_chain(p, 4) for p in PROMPTS]
        assert om.counter("serving_kv_handoffs_total",
                          tenant="dz").value == len(PROMPTS)
        assert om.counter("serving_kv_handoff_blocks_total",
                          tenant="dz").value > 0

    def test_server_proves_isolation_and_certifies_both_families(
            self, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_STRICT_SYNC", "1")
        eng = _engine(name="gen", disaggregate=True, auto_start=False)
        server = PredictorServer({"gen": eng})
        try:
            for name, cert in server.certificates.items():
                assert cert.ok, (name,
                                 [str(d) for d in cert.diagnostics])
            assert "gen" in server.certificates
            assert any(n.startswith("gen.prefill")
                       for n in server.certificates)
            # the prefill/decode cache overlap is a DECLARED handoff:
            # downgraded to INFO, never ERROR
            diags = server.placement_diags
            assert all(d.severity < 40 for d in diags)
            assert any(d.check == "scope-handoff" for d in diags)
            toks, _ = server.submit("gen", [3, 5, 7]).result(timeout=60)
            assert toks == _chain([3, 5, 7], 4)
        finally:
            server.close()

    def test_undeclared_overlap_still_rejected(self):
        e1 = _engine(TinyModel(), name="dup", auto_start=False)
        fluid.unique_name.switch()
        e2 = _engine(TinyModel(), name="dup", auto_start=False)
        try:
            with pytest.raises(VerifyError):
                PredictorServer({"a": e1, "b": e2})
        finally:
            e1.close()
            e2.close()


# ---------------------------------------------------------------------------
# speculative decoding
# ---------------------------------------------------------------------------


class TestSpeculative:
    def _spec(self, draft, k=3, max_new=8, name="sp", eos_id=None):
        return SpeculativeDecoder(
            PagedTinyModel(), draft=draft, k=k,
            config=GenerationConfig(max_new_tokens=max_new,
                                    eos_id=eos_id),
            prompt_buckets=(8,), place=fluid.CPUPlace(), name=name)

    def test_ngram_draft_lookup(self):
        # most recent earlier occurrence of the last token wins
        assert ngram_draft([5, 1, 2, 5, 9, 5], 3) == [9, 5, 5]
        assert ngram_draft([1, 2, 3], 2) == [3, 3]  # no match: repeat

    def test_perfect_draft_accepts_everything(self):
        with self._spec(lambda ctx, k: _chain(ctx, k),
                        name="sp1") as dec:
            toks, info = dec.generate([3, 5, 7])
        assert toks == _chain([3, 5, 7], 8)
        assert info["acceptance_rate"] == 1.0
        assert info["rounds"] == 2  # prefill token + 2 x (k+1)
        assert om.gauge("spec_acceptance_rate",
                        tenant="sp1").value == 1.0
        assert om.counter("spec_tokens_proposed_total",
                          tenant="sp1").value == info["proposed"]

    def test_hostile_draft_is_still_exact(self):
        with self._spec(lambda ctx, k: [0] * k, name="sp0") as dec:
            toks, info = dec.generate([3, 5, 7])
        assert toks == _chain([3, 5, 7], 8)  # exactness, not luck
        assert info["acceptance_rate"] == 0.0
        assert info["rounds"] == 7  # one emitted token per round

    def test_draft_model_tenant_is_exact_and_isolated(self):
        from paddle_tpu.static_analysis.concurrency import \
            prove_scope_isolation

        with self._spec(PagedTinyModel(), name="spd") as dec:
            toks, info = dec.generate([3, 5, 7])
            progs = dec.coresident_programs()
            labels = [l for l, _p, _t in progs]
            _fp, diags = prove_scope_isolation(
                [p for _l, p, _t in progs], labels=labels)
            assert not [d for d in diags if d.severity >= 40], \
                [str(d) for d in diags]
        assert any(l.startswith("spd.draft") for l in labels)
        assert toks == _chain([3, 5, 7], 8)
        assert info["acceptance_rate"] == 1.0

    def test_eos_inside_accepted_window_truncates(self):
        with self._spec(lambda ctx, k: _chain(ctx, k), max_new=10,
                        name="spe", eos_id=8) as dec:
            toks, _info = dec.generate([5])
        assert toks == [6, 7, 8]

    def test_greedy_only(self):
        with pytest.raises(ValueError, match="greedy"):
            SpeculativeDecoder(
                PagedTinyModel(),
                config=GenerationConfig(strategy="top_k"),
                prompt_buckets=(8,), place=fluid.CPUPlace())


# ---------------------------------------------------------------------------
# the decode-cache-unpaged lint
# ---------------------------------------------------------------------------


def _ring_step_program(slots=4, heads=8, tmax=512, dh=64):
    fluid.unique_name.switch()
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        cursors = fluid.layers.data("cursors", shape=[slots],
                                    dtype="int32",
                                    append_batch_size=False)
        k = main.global_block().create_var(
            name="kc", shape=[slots, heads, tmax, dh],
            dtype="float32", persistable=True)
        x = fluid.layers.fill_constant([slots, heads, dh], "float32",
                                       1.0)
        fluid.layers.kv_cache_write(k, x, cursors, per_row=True)
        out = fluid.layers.reduce_sum(
            fluid.layers.flash_decode(x, k, k, cursors, per_row=True))
    return main, out


def _unpaged_hits(main, out):
    rep = main.analyze(targets=[out.name])
    return [d for d in rep.diagnostics
            if d.check == "decode-cache-unpaged"]


class TestDecodeCacheUnpagedLint:
    def test_flags_large_ring_cache_with_fragmentation_hint(self):
        from paddle_tpu.static_analysis.diagnostics import Severity

        hits = _unpaged_hits(*_ring_step_program())
        assert len(hits) == 1
        d = hits[0]
        assert d.severity == Severity.INFO  # advisory, never blocking
        assert "slot-ring" in d.message and "block_len" in d.message
        assert "build_prefill_paged" in d.hint

    def test_kill_switch_reason(self, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_PAGED_KV", "0")
        hits = _unpaged_hits(*_ring_step_program())
        assert len(hits) == 1
        assert "kill switch" in hits[0].message

    def test_small_cache_below_floor_is_quiet(self, monkeypatch):
        small = _ring_step_program(slots=1, heads=1, tmax=32, dh=4)
        assert not _unpaged_hits(*small)
        monkeypatch.setenv("PADDLE_TPU_PAGED_MIN_BYTES", "1")
        small = _ring_step_program(slots=1, heads=1, tmax=32, dh=4)
        assert len(_unpaged_hits(*small)) == 1

    def test_paged_program_is_quiet_and_analyzable(self):
        fluid.unique_name.switch()
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            cursors = fluid.layers.data("cursors", shape=[4],
                                        dtype="int32",
                                        append_batch_size=False)
            tables = fluid.layers.data("tables", shape=[4, 32],
                                       dtype="int32",
                                       append_batch_size=False)
            k = main.global_block().create_var(
                name="kp", shape=[128, 8, 16, 64], dtype="float32",
                persistable=True)
            x = fluid.layers.fill_constant([4, 8, 64], "float32", 1.0)
            fluid.layers.paged_kv_cache_write(k, x, cursors, tables,
                                              per_row=True)
            out = fluid.layers.reduce_sum(
                fluid.layers.paged_flash_decode(x, k, k, cursors,
                                                tables))
        rep = main.analyze(targets=[out.name])
        assert not [d for d in rep.diagnostics
                    if d.check == "decode-cache-unpaged"]
        assert not rep.errors, [str(d) for d in rep.diagnostics]


# ---------------------------------------------------------------------------
# telemetry + trace
# ---------------------------------------------------------------------------


class TestPagedTelemetry:
    def test_kv_pool_gauges_track_the_pool(self):
        with _engine(name="tg") as eng:
            eng.submit([3, 5, 7]).result(timeout=60)
            total = eng.stats()["kv_blocks_total"]
        assert om.gauge("kv_blocks_total", tenant="tg").value == total
        assert om.gauge("kv_blocks_free", tenant="tg").value == total
        assert om.gauge("kv_pool_occupancy", tenant="tg").value == 0.0

    def test_kv_handoff_trace_leg(self, tmp_path, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_TELEMETRY_DIR",
                           str(tmp_path / "telemetry"))
        monkeypatch.setenv("PADDLE_TPU_TELEMETRY_FLUSH", "1")
        obs.reset_telemetry()
        with _engine(name="th", disaggregate=True) as eng:
            eng.submit([3]).result(timeout=60)
        tr.get_tracer().flush()
        recs = tr.read_traces(str(tmp_path / "telemetry"))
        by_name = {}
        for r in recs:
            by_name.setdefault(r["name"], []).append(r)
        assert "serving.kv_handoff" in by_name
        root = by_name["serving.request"][0]
        # the handoff hangs off the request root: the third TTFT leg
        # (prefill -> handoff wait -> first decode step)
        assert by_name["serving.kv_handoff"][0]["parent"] == \
            root["span"]
        stats = trace_cli.serving_stats(trace_cli.group_traces(recs))
        assert "kv_handoff_p50_ms" in stats
