"""contrib decoder DSL (reference contrib/decoder/beam_search_decoder.py)
— StateCell + TrainingDecoder train a toy copy-task seq2seq; the SAME
StateCell drives BeamSearchDecoder.decode() and the top beam reproduces
the source (the TestNMTBook oracle, through the DSL instead of
hand-rolled loops)."""

import numpy as np

import paddle_tpu as fluid
from paddle_tpu.executor import Scope, scope_guard

V, L, EMB, H = 12, 4, 24, 48
START, END = 1, 2


def _shared(name):
    return fluid.ParamAttr(name=name)


def _encode(src):
    emb = fluid.layers.embedding(src, size=[V, EMB],
                                 param_attr=_shared("src_emb"))
    flat = fluid.layers.reshape(emb, shape=[-1, L * EMB])  # order-aware
    h0 = fluid.layers.fc(flat, size=H, act="tanh",
                         param_attr=_shared("enc_w"),
                         bias_attr=_shared("enc_b"))
    return h0


def _make_cell(init_h):
    cell = fluid.contrib.StateCell(
        inputs={"x": None},
        states={"h": fluid.contrib.InitState(init=init_h)},
        out_state="h")

    @cell.state_updater
    def updater(c):
        x = c.get_input("x")
        h = c.get_state("h")
        nh = fluid.layers.fc(
            fluid.layers.concat([x, h], axis=1), size=H, act="tanh",
            param_attr=_shared("dec_w"), bias_attr=_shared("dec_b"))
        c.set_state("h", nh)

    return cell


def _build_train():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        src = fluid.layers.data("src", shape=[L], dtype="int64")
        tgt_in = fluid.layers.data("tgt_in", shape=[L + 1], dtype="int64")
        tgt_out = fluid.layers.data("tgt_out", shape=[L + 1, 1],
                                    dtype="int64")
        h0 = _encode(src)
        cell = _make_cell(h0)
        tgt_emb = fluid.layers.embedding(tgt_in, size=[V, EMB],
                                         param_attr=_shared("bsd_emb"))
        decoder = fluid.contrib.TrainingDecoder(cell)
        with decoder.block():
            tok = decoder.step_input(tgt_emb)
            cell.compute_state(inputs={"x": tok})
            out = cell.out_state()
            cell.update_states()
            decoder.output(out)
        states = decoder()                                  # [B, T, H]
        logits = fluid.layers.fc(
            states, size=V, num_flatten_dims=2,
            param_attr=_shared("bsd_out_w"),
            bias_attr=_shared("bsd_out_b"))
        loss = fluid.layers.mean(
            fluid.layers.softmax_with_cross_entropy(logits, tgt_out))
        fluid.optimizer.Adam(5e-3).minimize(loss)
    return main, startup, loss


def _build_infer(B, K, max_len):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        src = fluid.layers.data("src", shape=[B, L], dtype="int64",
                                append_batch_size=False)
        h0 = _encode(src)
        cell = _make_cell(h0)
        init_ids = fluid.layers.fill_constant([B, K], "int32",
                                              float(START))
        zero_col = fluid.layers.fill_constant([B, 1], "float32", 0.0)
        ninf = fluid.layers.fill_constant([B, K - 1], "float32", -1e9)
        init_scores = fluid.layers.concat([zero_col, ninf], axis=1)
        decoder = fluid.contrib.BeamSearchDecoder(
            cell, init_ids, init_scores, target_dict_dim=V, word_dim=EMB,
            max_len=max_len, beam_size=K, end_id=END, name="bsd")
        decoder.decode()
        sent_ids, sent_scores = decoder()
    return main, startup, sent_ids, sent_scores


def test_decoder_dsl_trains_and_beam_decodes():
    rng = np.random.RandomState(0)
    B, K = 4, 3

    def make_batch(n):
        toks = rng.randint(3, V, size=(n, L))
        tgt_in = np.concatenate([np.full((n, 1), START), toks], axis=1)
        tgt_out = np.concatenate(
            [toks, np.full((n, 1), END)], axis=1)[..., None]
        return (toks.astype("int64"), tgt_in.astype("int64"),
                tgt_out.astype("int64"))

    fluid.unique_name.switch()
    main, startup, loss = _build_train()
    exe = fluid.Executor(fluid.CPUPlace())
    with scope_guard(Scope()):
        exe.run(startup)
        first = last = None
        for _ in range(200):
            s, ti, to = make_batch(16)
            (lv,) = exe.run(main,
                            feed={"src": s, "tgt_in": ti, "tgt_out": to},
                            fetch_list=[loss])
            lv = float(np.asarray(lv).reshape(()))
            first = first if first is not None else lv
            last = lv
        assert last < first * 0.25, (first, last)

        imain, istartup, sent, scores = _build_infer(B, K, L + 2)
        s, _, _ = make_batch(B)
        sids, sscores = exe.run(imain, feed={"src": s},
                                fetch_list=[sent, scores])
    assert sids.shape == (B, K, L + 2)
    correct = sum(1 for b in range(B)
                  if sids[b, 0, :L].tolist() == s[b].tolist())
    assert correct >= B - 1, (sids[:, 0], s)
    assert (sscores[:, 0] >= sscores[:, 1] - 1e-6).all()
