"""Bounded-while gradients (masked-scan transpose) + DynamicRNN.

Reference parity targets: ``paddle/fluid/operators/controlflow/while_op.cc``
(while grad registered in C++) and DynamicRNN at
``python/paddle/fluid/layers/control_flow.py:1700``.  TPU lowering: backward
of a bounded `while` re-runs the loop as a lax.scan over max_trip_count
steps with an active mask; DynamicRNN is a masked scan over padded
batch-major sequences.
"""

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.executor import Scope, scope_guard


def _build_pow_loop(max_trip):
    """y = w**3 * x via `while i < 3: y = w*y` with a trainable scalar w=2."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[1, 1], dtype="float32",
                              append_batch_size=False)
        y = fluid.layers.assign(x)
        i = fluid.layers.fill_constant([1], "float32", 0.0)
        limit = fluid.layers.fill_constant([1], "float32", 3.0)
        cond = fluid.layers.less_than(i, limit)
        w = fluid.layers.While(cond, max_trip_count=max_trip)
        with w.block():
            fluid.layers.assign(
                fluid.layers.fc(
                    y, size=1, bias_attr=False,
                    param_attr=fluid.ParamAttr(
                        name="loop.w",
                        initializer=fluid.initializer.Constant(2.0),
                    ),
                ),
                output=y,
            )
            fluid.layers.increment(i, in_place=True)
            fluid.layers.less_than(i, limit, cond=cond)
        loss = fluid.layers.mean(y)
        params_grads = fluid.backward.append_backward(loss)
    return main, startup, loss, params_grads


@pytest.mark.parametrize("max_trip", [3, 8])
def test_while_grad_closed_form(max_trip):
    """d mean(w^3 x)/dw = 3 w^2 x; with max_trip > actual trips the active
    mask must make the extra scan steps no-ops."""
    main, startup, loss, params_grads = _build_pow_loop(max_trip)
    assert len(params_grads) == 1 and params_grads[0][0].name == "loop.w"
    exe = fluid.Executor(fluid.CPUPlace())
    with scope_guard(Scope()):
        exe.run(startup)
        xv = np.array([[0.5]], "float32")
        lv, gv = exe.run(
            main, feed={"x": xv},
            fetch_list=[loss, params_grads[0][1]],
        )
    np.testing.assert_allclose(lv, 8.0 * 0.5, rtol=1e-5)       # w^3 x
    np.testing.assert_allclose(gv, [[3 * 4.0 * 0.5]], rtol=1e-5)  # 3 w^2 x


def test_while_grad_wrt_data_input():
    """dy/dx through the loop = w^3 (grads reach pre-loop producers)."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[1, 1], dtype="float32",
                              append_batch_size=False, stop_gradient=False)
        x2 = fluid.layers.scale(x, 3.0)  # pre-loop producer: dy/dx = 3 w^3
        y = fluid.layers.assign(x2)
        i = fluid.layers.fill_constant([1], "float32", 0.0)
        limit = fluid.layers.fill_constant([1], "float32", 3.0)
        cond = fluid.layers.less_than(i, limit)
        w = fluid.layers.While(cond, max_trip_count=5)
        with w.block():
            fluid.layers.assign(
                fluid.layers.fc(
                    y, size=1, bias_attr=False,
                    param_attr=fluid.ParamAttr(
                        name="loop2.w",
                        initializer=fluid.initializer.Constant(2.0),
                    ),
                ),
                output=y,
            )
            fluid.layers.increment(i, in_place=True)
            fluid.layers.less_than(i, limit, cond=cond)
        loss = fluid.layers.mean(y)
        (gx,) = fluid.backward.gradients(loss, x)
    exe = fluid.Executor(fluid.CPUPlace())
    with scope_guard(Scope()):
        exe.run(startup)
        gv = exe.run(main, feed={"x": np.array([[0.5]], "float32")},
                     fetch_list=[gx])[0]
    np.testing.assert_allclose(gv, [[3 * 8.0]], rtol=1e-5)


def test_while_unbounded_grad_closed_form():
    """No max_trip_count at all (reference while_op.cc:189 default):
    the executor probes the concrete trip count eagerly, then lowers the
    backward as a masked scan of that length."""
    main, startup, loss, params_grads = _build_pow_loop(None)
    assert params_grads[0][0].name == "loop.w"
    exe = fluid.Executor(fluid.CPUPlace())
    with scope_guard(Scope()):
        exe.run(startup)
        xv = np.array([[0.5]], "float32")
        lv, gv = exe.run(main, feed={"x": xv},
                         fetch_list=[loss, params_grads[0][1]])
    np.testing.assert_allclose(lv, 8.0 * 0.5, rtol=1e-5)          # w^3 x
    np.testing.assert_allclose(gv, [[3 * 4.0 * 0.5]], rtol=1e-5)  # 3 w^2 x


def test_while_unbounded_grad_data_dependent_trips():
    """The trip count depends on a FED value: each distinct count keys a
    fresh compile; grads match the closed form for both runs, and the
    numeric finite-difference oracle for the longer one."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[1, 1], dtype="float32",
                              append_batch_size=False)
        limit = fluid.layers.data("limit", shape=[1], dtype="float32",
                                  append_batch_size=False)
        y = fluid.layers.assign(x)
        i = fluid.layers.fill_constant([1], "float32", 0.0)
        cond = fluid.layers.less_than(i, limit)
        w = fluid.layers.While(cond)  # unbounded, runtime-valued limit
        with w.block():
            fluid.layers.assign(
                fluid.layers.fc(
                    y, size=1, bias_attr=False,
                    param_attr=fluid.ParamAttr(
                        name="loop3.w",
                        initializer=fluid.initializer.Constant(2.0),
                    ),
                ),
                output=y,
            )
            fluid.layers.increment(i, in_place=True)
            fluid.layers.less_than(i, limit, cond=cond)
        loss = fluid.layers.mean(y)
        params_grads = fluid.backward.append_backward(loss)
    gvar = params_grads[0][1]
    exe = fluid.Executor(fluid.CPUPlace())
    xv = np.array([[0.5]], "float32")
    with scope_guard(Scope()):
        exe.run(startup)
        for n in (2, 4):
            lv, gv = exe.run(
                main,
                feed={"x": xv, "limit": np.array([float(n)], "float32")},
                fetch_list=[loss, gvar])
            w_ = 2.0
            np.testing.assert_allclose(lv, w_ ** n * 0.5, rtol=1e-5)
            np.testing.assert_allclose(
                gv, [[n * w_ ** (n - 1) * 0.5]], rtol=1e-5)

        # numeric finite-difference oracle at n=4 (op_test.py pattern)
        eps = 1e-3
        scope = fluid.executor.global_scope()
        import jax.numpy as jnp

        for sign, store in ((+1, "hi"), (-1, "lo")):
            scope.set("loop3.w", jnp.asarray([[2.0 + sign * eps]],
                                             jnp.float32))
            val = exe.run(
                main,
                feed={"x": xv, "limit": np.array([4.0], "float32")},
                fetch_list=[loss])[0]
            if store == "hi":
                hi = float(np.asarray(val).reshape(()))
            else:
                lo = float(np.asarray(val).reshape(()))
        np.testing.assert_allclose(float(np.asarray(gv).reshape(())),
                                   (hi - lo) / (2 * eps), rtol=1e-3)

        # zero-trip loop (limit=0): forward passes x through; grad of w
        # is exactly zero (scan of length 0), not an error
        scope.set("loop3.w", jnp.asarray([[2.0]], jnp.float32))
        lv, gv = exe.run(
            main, feed={"x": xv, "limit": np.array([0.0], "float32")},
            fetch_list=[loss, gvar])
        np.testing.assert_allclose(lv, 0.5, rtol=1e-6)
        np.testing.assert_allclose(gv, [[0.0]])


def _np_dynrnn_cumsum(xv, lens):
    B, T, D = xv.shape
    out = np.zeros_like(xv)
    for b in range(B):
        h = np.zeros(D, xv.dtype)
        for t in range(int(lens[b])):
            h = h + xv[b, t]
            out[b, t] = h
    return out


def test_dynamic_rnn_cumsum_and_grad():
    B, T, D = 3, 4, 2
    lens = np.array([4, 2, 3], "int64")
    rng = np.random.RandomState(0)
    xv = rng.randn(B, T, D).astype("float32")

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[B, T, D], dtype="float32",
                              append_batch_size=False, stop_gradient=False)
        sl = fluid.layers.data("sl", shape=[B], dtype="int64",
                               append_batch_size=False)
        drnn = fluid.layers.DynamicRNN()
        with drnn.block():
            xt = drnn.step_input(x, lengths=sl)
            h = drnn.memory(shape=[D], value=0.0)
            nh = fluid.layers.elementwise_add(h, xt)
            drnn.update_memory(h, nh)
            drnn.output(nh)
        out = drnn()  # [B, T, D], zeros past each length
        loss = fluid.layers.reduce_sum(out)
        (gx,) = fluid.backward.gradients(loss, x)
    exe = fluid.Executor(fluid.CPUPlace())
    with scope_guard(Scope()):
        ov, gv = exe.run(main, feed={"x": xv, "sl": lens},
                         fetch_list=[out, gx])
    np.testing.assert_allclose(ov, _np_dynrnn_cumsum(xv, lens), rtol=1e-5)
    # d reduce_sum(out)/dx[b,t] = #steps s in [t, len_b) = len_b - t
    expect = np.zeros((B, T, D), "float32")
    for b in range(B):
        for t in range(int(lens[b])):
            expect[b, t] = lens[b] - t
    np.testing.assert_allclose(gv, expect, rtol=1e-5)


def test_nmt_dynamic_rnn_decoder_trains():
    """Seq2seq trainer whose decoder is a DynamicRNN over padded
    variable-length targets (book machine_translation decoder shape)."""
    from paddle_tpu.models import machine_translation as mt

    V, B, TS, TT = 40, 8, 6, 7
    rng = np.random.RandomState(0)
    main, startup, feeds, loss = mt.build_train_dynamic(
        V, emb_dim=16, hidden_dim=24, src_len=TS, tgt_len=TT, lr=5e-3)
    exe = fluid.Executor(fluid.CPUPlace())
    with scope_guard(Scope()):
        exe.run(startup)
        src = rng.randint(3, V, (B, TS)).astype("int64")
        tgt = rng.randint(3, V, (B, TT)).astype("int64")
        lens = rng.randint(2, TT + 1, (B,)).astype("int64")
        feed = {
            "src": src,
            "tgt_in": tgt,
            "tgt_out": tgt[:, :, None],
            "tgt_lens": lens,
        }
        losses = [
            float(np.asarray(exe.run(main, feed=feed,
                                     fetch_list=[loss])[0]).reshape(()))
            for _ in range(60)
        ]
    assert losses[-1] < 0.5 * losses[0], (losses[0], losses[-1])


def test_dynamic_rnn_with_fc_trains():
    B, T, D, H = 4, 5, 3, 6
    rng = np.random.RandomState(0)
    lens = np.array([5, 3, 4, 2], "int64")
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[B, T, D], dtype="float32",
                              append_batch_size=False)
        sl = fluid.layers.data("sl", shape=[B], dtype="int64",
                               append_batch_size=False)
        yt = fluid.layers.data("yt", shape=[B, H], dtype="float32",
                               append_batch_size=False)
        drnn = fluid.layers.DynamicRNN()
        with drnn.block():
            xt = drnn.step_input(x, lengths=sl)
            mem = drnn.memory(shape=[H], value=0.0)
            nxt = fluid.layers.fc(
                [xt, mem], size=H, act="tanh", bias_attr=False
            )
            drnn.update_memory(mem, nxt)
            drnn.output(nxt)
        out = drnn()  # [B, T, H]
        loss = fluid.layers.mean(
            fluid.layers.square_error_cost(
                fluid.layers.reduce_sum(out, dim=[1]), yt
            )
        )
        _, params_grads = fluid.optimizer.SGD(0.2).minimize(loss)
    assert len(params_grads) == 2, "fc weights inside DynamicRNN got no grads"
    exe = fluid.Executor(fluid.CPUPlace())
    with scope_guard(Scope()):
        exe.run(startup)
        xv = rng.randn(B, T, D).astype("float32")
        yv = (rng.rand(B, H).astype("float32") - 0.5)
        losses = [
            float(exe.run(main, feed={"x": xv, "sl": lens, "yt": yv},
                          fetch_list=[loss])[0][0])
            for _ in range(80)
        ]
    assert losses[-1] < 0.5 * losses[0], (losses[0], losses[-1])
