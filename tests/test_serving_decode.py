"""DecodeEngine serving tests (ISSUE 14): continuous batching over
KV-cache slots, PredictorServer decode-tenant routing + certificates,
decode telemetry counters, and prefill/decode trace attribution."""

import numpy as np
import pytest

import paddle_tpu as fluid
import paddle_tpu.observability as obs
import paddle_tpu.observability.metrics as om
from paddle_tpu.executor import Scope, scope_guard
from paddle_tpu.inference import AnalysisConfig, AnalysisPredictor
from paddle_tpu.observability import tracing as tr
from paddle_tpu.serving import (DecodeEngine, GenerationConfig,
                                PredictorServer, ServerClosedError)
from paddle_tpu.tools import trace as trace_cli


@pytest.fixture(autouse=True)
def _fresh(monkeypatch):
    fluid.unique_name.switch()
    for var in ("PADDLE_TPU_TELEMETRY", "PADDLE_TPU_TELEMETRY_DIR",
                "PADDLE_TPU_TELEMETRY_FLUSH", "PADDLE_TPU_TRACING",
                "PADDLE_TPU_STRICT_SYNC"):
        monkeypatch.delenv(var, raising=False)
    obs.reset_telemetry()
    yield
    obs.reset_telemetry()


V = 16


class TinyModel:
    """Deterministic adapter: next token = cur + 1, with the real
    kv_cache_prefill / kv_cache_write / flash_decode path exercised
    (the attention output is folded in at zero weight so any cache
    corruption would still poison the logits)."""

    def cache_spec(self):
        return 1, 1, 32, 4  # layers, heads, max_len, head_dim

    def _embed(self, ids_f, rows):
        ones = fluid.layers.fill_constant([1, 4], "float32", 1.0)
        x = fluid.layers.reshape(ids_f, [rows, 1])
        return fluid.layers.matmul(x, ones)  # [rows, 4]

    def build_prefill(self, prompt, plen, slot, caches):
        L = prompt.shape[1]
        pf = fluid.layers.cast(prompt, "float32")            # [1, L]
        emb = self._embed(fluid.layers.reshape(pf, [L]), L)  # [L, 4]
        x = fluid.layers.reshape(emb, [1, 1, L, 4])
        k, v = caches[0]
        fluid.layers.kv_cache_prefill(k, x, slot=slot)
        fluid.layers.kv_cache_prefill(v, x, slot=slot)
        return self._prefill_logits(pf, plen, L)

    def _prefill_logits(self, pf, plen, L):
        idx = fluid.layers.increment(fluid.layers.assign(plen),
                                     value=-1, in_place=True)
        oh = fluid.layers.cast(fluid.layers.one_hot(
            fluid.layers.reshape(idx, [1, 1]), L), "float32")
        last = fluid.layers.reduce_sum(
            fluid.layers.elementwise_mul(pf, oh), dim=[1])   # [1]
        nxt = fluid.layers.cast(
            fluid.layers.scale(last, scale=1.0, bias=1.0), "int32")
        return fluid.layers.scale(fluid.layers.cast(
            fluid.layers.one_hot(
                fluid.layers.reshape(nxt, [1, 1]), V), "float32"), 10.0)

    def build_step(self, cur, cursors, caches):
        S = cur.shape[0]
        cf = fluid.layers.cast(cur, "float32")  # [S]
        emb = self._embed(cf, S)                # [S, 4]
        x = fluid.layers.reshape(emb, [S, 1, 4])
        k, v = caches[0]
        fluid.layers.kv_cache_write(k, x, cursors, per_row=True)
        fluid.layers.kv_cache_write(v, x, cursors, per_row=True)
        att = fluid.layers.flash_decode(x, k, v, cursors, per_row=True)
        return self._step_logits(cf, att, S)

    def _step_logits(self, cf, att, S):
        zero = fluid.layers.scale(
            fluid.layers.reduce_sum(att, dim=[1, 2]), 0.0)  # [S]
        nxt = fluid.layers.cast(
            fluid.layers.scale(cf, scale=1.0, bias=1.0), "int32")
        logits = fluid.layers.scale(fluid.layers.cast(
            fluid.layers.one_hot(
                fluid.layers.reshape(nxt, [S, 1]), V), "float32"), 10.0)
        return fluid.layers.elementwise_add(
            logits, fluid.layers.reshape(zero, [S, 1]), axis=0)


def _engine(name="tiny", max_new=4, eos_id=None, auto_start=True):
    return DecodeEngine(
        TinyModel(), slots=2, prompt_buckets=(8,),
        config=GenerationConfig(max_new_tokens=max_new, eos_id=eos_id),
        place=fluid.CPUPlace(), name=name, auto_start=auto_start)


IN_DIM = 6


def _fc_predictor(dirname, seed=0):
    """A classic padded-batch tenant so the decode engine has a
    co-resident to prove isolation against."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[IN_DIM], dtype="float32")
        h = fluid.layers.fc(x, size=8, act="relu")
        out = fluid.layers.fc(h, size=3, act="softmax")
    exe = fluid.Executor(fluid.CPUPlace())
    with scope_guard(Scope()):
        np.random.seed(seed)
        exe.run(startup)
        fluid.io.save_inference_model(str(dirname), ["x"], [out], exe,
                                      main_program=main)
    return AnalysisPredictor(AnalysisConfig(model_dir=str(dirname)))


# ---------------------------------------------------------------------------
# the engine itself: continuous batching over cache slots
# ---------------------------------------------------------------------------
class TestDecodeEngine:
    def test_mid_stream_admission_and_determinism(self):
        """Three requests onto two slots: the third is admitted into a
        freed cache block mid-stream and every token sequence is the
        deterministic cur+1 chain from its own prompt — no cross-slot
        cache bleed."""
        with _engine() as eng:
            r1 = eng.submit([3, 5, 7])
            r2 = eng.submit([2])
            r3 = eng.submit([1, 2, 3, 4])   # queued until a slot frees
            t1, i1 = r1.result(timeout=60)
            t2, i2 = r2.result(timeout=60)
            t3, i3 = r3.result(timeout=60)
            assert t1 == [8, 9, 10, 11]
            assert t2 == [3, 4, 5, 6]
            assert t3 == [5, 6, 7, 8]
            for info in (i1, i2, i3):
                assert info["generated_len"] == 4
                assert info["latency_ms"] >= info["ttft_ms"] >= 0.0
            stats = eng.stats()
        assert stats["submitted"] == stats["completed"] == 3
        assert stats["failed"] == 0
        assert stats["queue_depth"] == 0 and stats["active_slots"] == 0
        # 4 tokens/request: 1 from prefill + 3 from decode steps
        assert stats["tokens"] == 9
        assert stats["decode_steps"] >= 3
        assert stats["slots"] == 2
        assert stats["prompt_buckets"] == [8]

    def test_eos_stops_generation(self):
        with _engine(max_new=10, eos_id=8) as eng:
            toks, info = eng.submit([5]).result(timeout=60)
        assert toks == [6, 7, 8]        # stops AT eos, eos included
        assert info["generated_len"] == 3

    def test_prompt_validation(self):
        with _engine() as eng:
            with pytest.raises(ValueError, match="empty"):
                eng.submit([])
            with pytest.raises(ValueError, match="cache depth"):
                eng.submit(list(range(40)))     # > max_len - 1
            with pytest.raises(ValueError, match="bucket"):
                eng.submit(list(range(10)))     # > largest bucket (8)

    def test_submit_after_close_raises(self):
        eng = _engine()
        eng.close()
        with pytest.raises(RuntimeError, match="closed"):
            eng.submit([1])


# ---------------------------------------------------------------------------
# PredictorServer decode-tenant integration
# ---------------------------------------------------------------------------
class TestServerDecodeTenant:
    def test_routing_certificates_and_stats(self, tmp_path,
                                            monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_STRICT_SYNC", "1")
        pred = _fc_predictor(tmp_path / "fc")
        eng = _engine(name="gen", auto_start=False)
        server = PredictorServer({"fc": pred, "gen": eng},
                                 buckets=(1, 2))
        try:
            # both tenants passed the co-residency proof and carry a
            # zero-sync certificate; the engine's is over its step
            # program — the true hot loop
            assert set(server.certificates) == {"fc", "gen"}
            assert server.certificates["gen"].ok, "\n".join(
                str(d) for d in server.certificates["gen"].diagnostics)
            # server.start() (via auto_start) started the engine
            toks, info = server.submit("gen", [3, 5, 7]).result(
                timeout=60)
            assert toks == [8, 9, 10, 11]
            # the classic padded-batch path is untouched
            x = np.random.RandomState(0).rand(1, IN_DIM).astype(
                "float32")
            out = server.submit("fc", {"x": x}).result(timeout=60)
            assert out[0].shape == (1, 3)
            stats = server.stats()
            assert stats["decode"]["gen"]["completed"] == 1
            with pytest.raises(KeyError, match="gen"):
                server.submit("nope", [1])
        finally:
            server.close()
        with pytest.raises(ServerClosedError):
            server.submit("gen", [1])

    def test_engine_only_server(self):
        eng = _engine(name="solo", auto_start=False)
        server = PredictorServer({"solo": eng})
        try:
            toks, _ = server.submit("solo", [2]).result(timeout=60)
            assert toks == [3, 4, 5, 6]
        finally:
            server.close()


# ---------------------------------------------------------------------------
# telemetry: the monitor-facing decode metrics
# ---------------------------------------------------------------------------
class TestDecodeTelemetry:
    def test_counters_histograms_and_gauge(self):
        with _engine(name="tmet") as eng:
            eng.submit([1]).result(timeout=60)
            eng.submit([2]).result(timeout=60)
        # 3 step tokens per request (first token comes from prefill)
        assert om.counter("serving_decode_tokens_total",
                          tenant="tmet").value == 6
        h = om.histogram("serving_generated_len")
        assert h.count == 2 and h.value == 4.0     # mean generated len
        assert om.histogram("serving_ttft_ms").count == 2
        assert om.gauge("decode_tokens_per_sec").value > 0


# ---------------------------------------------------------------------------
# tracing: prefill vs decode attribution for `tools.trace --serving`
# ---------------------------------------------------------------------------
class TestDecodeTracing:
    def test_request_spans_split_prefill_and_decode(self, tmp_path,
                                                    monkeypatch):
        tdir = tmp_path / "telemetry"
        monkeypatch.setenv("PADDLE_TPU_TELEMETRY_DIR", str(tdir))
        monkeypatch.setenv("PADDLE_TPU_TELEMETRY_FLUSH", "1")
        obs.reset_telemetry()
        with _engine(name="ttr") as eng:
            eng.submit([3]).result(timeout=60)
        tr.get_tracer().flush()
        recs = tr.read_traces(str(tdir))
        by_name = {}
        for r in recs:
            by_name.setdefault(r["name"], []).append(r)
        assert {"serving.request", "serving.prefill",
                "serving.decode_step",
                "serving.decode"} <= set(by_name)
        root = by_name["serving.request"][0]
        # prefill and the retroactive decode span hang off the request
        # root — per-request phase attribution, not just global steps
        assert by_name["serving.prefill"][0]["parent"] == root["span"]
        assert by_name["serving.decode"][0]["parent"] == root["span"]
        assert by_name["serving.decode"][0]["attrs"]["tokens"] == 4
        stats = trace_cli.serving_stats(trace_cli.group_traces(recs))
        assert stats["requests"] == 1
        assert "prefill_p50_ms" in stats
        assert "decode_p50_ms" in stats
