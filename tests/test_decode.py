"""Autoregressive decoding tests (ISSUE 14): the ring-buffer KV cache
ops, the flash-decode kernel vs its XLA oracle (interpret mode on CPU),
the sampling ops, the recompile-free ``decode_loop`` contract (jit-cache
entry count flat across generated lengths + the zero-sync certificate
under ``PADDLE_TPU_STRICT_SYNC=1``), the autotune ``decode`` family's
``PADDLE_TPU_AUTOTUNE=0`` bit-exact fallback, and the
``decode-shape-unbucketed`` lint check."""

import importlib
import os
import sys

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.executor import Scope, scope_guard

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
EXAMPLES = os.path.join(REPO, "examples")
if EXAMPLES not in sys.path:
    sys.path.insert(0, EXAMPLES)

FD = importlib.import_module("paddle_tpu.ops.pallas.flash_decode")


def _run(main, startup, feed, fetch):
    exe = fluid.Executor(fluid.TPUPlace())
    with scope_guard(Scope()):
        exe.run(startup)
        return exe.run(main, feed=feed, fetch_list=fetch)


# ---------------------------------------------------------------------------
# KV-cache ops
# ---------------------------------------------------------------------------


class TestKVCacheOps:
    def test_shared_cursor_write_and_ring_wrap(self):
        import jax.numpy as jnp

        from paddle_tpu.ops.registry import get_op_def

        op = get_op_def("kv_cache_write")
        B, H, T, D = 2, 2, 4, 3
        cache = jnp.zeros((B, H, T, D), jnp.float32)
        x = jnp.ones((B, H, D), jnp.float32)
        out = op.fn(None, {}, cache, x, jnp.asarray([1], jnp.int32))
        assert float(out[:, :, 1, :].min()) == 1.0
        assert float(jnp.abs(out[:, :, 0, :]).max()) == 0.0
        # cursor T+1 wraps to position 1 (ring semantics)
        wrapped = op.fn(None, {}, cache, 2 * x,
                        jnp.asarray([T + 1], jnp.int32))
        assert float(wrapped[:, :, 1, :].min()) == 2.0

    def test_per_row_write_each_slot_its_own_depth(self):
        import jax.numpy as jnp

        from paddle_tpu.ops.registry import get_op_def

        op = get_op_def("kv_cache_write")
        B, H, T, D = 3, 1, 8, 2
        cache = jnp.zeros((B, H, T, D), jnp.float32)
        x = jnp.ones((B, H, D), jnp.float32)
        cursors = jnp.asarray([0, 3, 5], jnp.int32)
        out = np.asarray(op.fn(None, {"per_row": True}, cache, x,
                               cursors))
        for b, pos in enumerate([0, 3, 5]):
            assert out[b, 0, pos].min() == 1.0
            mask = np.ones(T, bool)
            mask[pos] = False
            assert np.abs(out[b, 0, mask]).max() == 0.0

    def test_prefill_slot_routes_one_row(self):
        import jax.numpy as jnp

        from paddle_tpu.ops.registry import get_op_def

        op = get_op_def("kv_cache_prefill")
        S, H, T, D, L = 3, 1, 8, 2, 4
        cache = jnp.zeros((S, H, T, D), jnp.float32)
        x = jnp.ones((1, H, L, D), jnp.float32)
        out = np.asarray(op.fn(None, {}, cache, x,
                               jnp.asarray([1], jnp.int32)))
        assert out[1, 0, :L].min() == 1.0
        assert np.abs(out[0]).max() == 0.0 and np.abs(out[2]).max() == 0.0
        assert np.abs(out[1, 0, L:]).max() == 0.0


# ---------------------------------------------------------------------------
# flash-decode kernel vs XLA oracle
# ---------------------------------------------------------------------------


class TestFlashDecodeKernel:
    @pytest.mark.parametrize("t,lens_kind", [(256, "full"),
                                             (512, "ragged"),
                                             (512, "shallow")])
    def test_kernel_matches_reference(self, monkeypatch, t, lens_kind):
        """Interpret-mode kernel vs the XLA composite: ≤1e-5 relative
        (the documented oracle tolerance), including cursors well short
        of the cache capacity (the masked-block skip path)."""
        import jax.numpy as jnp

        monkeypatch.setenv("PADDLE_TPU_PALLAS", "interpret")
        monkeypatch.setenv("PADDLE_TPU_DECODE_MIN_T", "1")
        rng = np.random.RandomState(0)
        B, H, D = 2, 2, 64
        q = jnp.asarray(rng.randn(B, H, D).astype("float32"))
        k = jnp.asarray(rng.randn(B, H, t, D).astype("float32"))
        v = jnp.asarray(rng.randn(B, H, t, D).astype("float32"))
        lens = {"full": jnp.asarray([t, t], jnp.int32),
                "ragged": jnp.asarray([7, 300], jnp.int32),
                "shallow": jnp.asarray([1, 2], jnp.int32)}[lens_kind]
        use, _ = FD._use_pallas()
        assert use, "interpret mode must engage the kernel path"
        o_kernel = FD.flash_decode(q, k, v, lens)
        o_ref = FD.decode_reference(q, k, v, lens)
        np.testing.assert_allclose(o_kernel, o_ref, rtol=1e-5,
                                   atol=1e-5)

    def test_reference_empty_cache_is_zeros_not_nan(self):
        import jax.numpy as jnp

        rng = np.random.RandomState(1)
        q = jnp.asarray(rng.randn(1, 2, 8).astype("float32"))
        k = jnp.asarray(rng.randn(1, 2, 16, 8).astype("float32"))
        v = jnp.asarray(rng.randn(1, 2, 16, 8).astype("float32"))
        out = np.asarray(FD.decode_reference(q, k, v,
                                             jnp.asarray([0], jnp.int32)))
        assert np.all(np.isfinite(out)) and np.abs(out).max() == 0.0


class TestAutotuneDefaults:
    def test_autotune_off_restores_hand_set_defaults(self, monkeypatch,
                                                     tmp_path):
        """PADDLE_TPU_AUTOTUNE=0 must restore the hand-set 512/256
        bit-exactly even when the cache holds a measured winner."""
        from paddle_tpu import autotune

        monkeypatch.setenv("PADDLE_TPU_AUTOTUNE_CACHE",
                           str(tmp_path / "cache.json"))
        monkeypatch.delenv("PADDLE_TPU_AUTOTUNE", raising=False)
        monkeypatch.delenv("PADDLE_TPU_DECODE_BLOCK_K", raising=False)
        monkeypatch.delenv("PADDLE_TPU_DECODE_MIN_T", raising=False)
        autotune.record_decode_min_t(1024)
        assert FD.decode_min_t() == 1024  # the cache decision wins...
        monkeypatch.setenv("PADDLE_TPU_AUTOTUNE", "0")
        assert FD.decode_min_t() == FD.DEFAULT_MIN_T  # ...until opt-out
        assert FD.decode_block_k(2048, 64) == FD.DEFAULT_BLOCK_K
        # block size still respects divisibility against short caches
        assert 128 % FD.decode_block_k(128, 64) == 0

    def test_env_caps_beat_cache(self, monkeypatch, tmp_path):
        monkeypatch.setenv("PADDLE_TPU_AUTOTUNE_CACHE",
                           str(tmp_path / "cache.json"))
        monkeypatch.setenv("PADDLE_TPU_DECODE_MIN_T", "64")
        assert FD.decode_min_t() == 64


# ---------------------------------------------------------------------------
# sampling ops
# ---------------------------------------------------------------------------


def _sample_once(strategy, logits, step_val, **kw):
    fluid.unique_name.switch()
    main, startup = fluid.Program(), fluid.Program()
    B, V = logits.shape
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[B, V], dtype="float32",
                              append_batch_size=False)
        step = fluid.layers.data("step", shape=[1], dtype="int32",
                                 append_batch_size=False)
        out = fluid.layers.sampling(x, strategy=strategy, step=step,
                                    **kw)
    res = _run(main, startup,
               {"x": logits, "step": np.asarray([step_val], "int32")},
               [out])
    return np.asarray(res[0])


class TestSampling:
    def test_greedy_is_argmax(self):
        rng = np.random.RandomState(0)
        logits = rng.randn(4, 32).astype("float32")
        out = _sample_once("greedy", logits, 0)
        np.testing.assert_array_equal(out, logits.argmax(-1))

    def test_top_k_stays_in_top_k_and_step_decorrelates(self):
        rng = np.random.RandomState(1)
        logits = rng.randn(8, 64).astype("float32")
        k = 5
        topk = np.argsort(-logits, axis=-1)[:, :k]
        draws = {}
        for step in range(3):
            out = _sample_once("top_k", logits, step, k=k,
                               temperature=1.0, seed=7)
            for b in range(len(out)):
                assert out[b] in topk[b]
            draws[step] = out.tolist()
            # replay at the same step is bit-exact
            again = _sample_once("top_k", logits, step, k=k,
                                 temperature=1.0, seed=7)
            assert again.tolist() == draws[step]
        # the step fold must decorrelate: not every step identical
        assert len({tuple(v) for v in draws.values()}) > 1

    def test_top_p_head_token_always_reachable(self):
        # p -> 0 keeps only the head of the nucleus: exactly greedy
        rng = np.random.RandomState(2)
        logits = rng.randn(6, 40).astype("float32")
        out = _sample_once("top_p", logits, 3, p=1e-9, temperature=1.0,
                           seed=3)
        np.testing.assert_array_equal(out, logits.argmax(-1))

    def test_top_p_respects_nucleus(self):
        # one dominant token (mass > p) => nucleus is that token alone
        logits = np.full((3, 16), -10.0, "float32")
        logits[:, 5] = 10.0
        out = _sample_once("top_p", logits, 1, p=0.9, temperature=1.0,
                           seed=0)
        assert out.tolist() == [5, 5, 5]


# ---------------------------------------------------------------------------
# the recompile-free generation contract (gpt_small end to end)
# ---------------------------------------------------------------------------


def _generate(exe, scope, batch, prompt_len, max_new, keep, seed=0):
    import gpt_small

    fluid.unique_name.switch()
    main, startup, feeds, tokens, gen_len = gpt_small.build_program(
        gpt_small.GPT_TINY, batch, prompt_len, max_new)
    # the jit cache is keyed by id(program): keep the programs alive so
    # a later build can't reuse a dead id and alias a cache entry
    keep.append((main, startup, tokens, gen_len))
    rng = np.random.RandomState(seed)
    feed = gpt_small.make_fake_prompt(batch, prompt_len,
                                      gpt_small.GPT_TINY, rng)
    with scope_guard(scope):
        exe.run(startup)
        out = exe.run(main, feed=feed, fetch_list=[tokens, gen_len])
    return main, np.asarray(out[0]), np.asarray(out[1])


class TestDecodeLoopContract:
    def test_jit_cache_flat_across_generated_lengths(self, monkeypatch):
        """The tentpole: the jit cache holds the same number of entries
        whether the loop generates 4 tokens or 16 — no per-step (or
        per-length) recompile — and re-feeding different prompts adds
        nothing."""
        import gpt_small

        monkeypatch.setenv("PADDLE_TPU_STRICT_SYNC", "1")
        exe = fluid.Executor(fluid.TPUPlace())
        keep = []
        base = len(exe._cache)
        _main, toks, _ = _generate(exe, Scope(), 2, 8, 4, keep)
        short = len(exe._cache) - base
        assert toks.shape == (2, 4)
        scope = Scope()
        _main, toks, _ = _generate(exe, scope, 2, 8, 16, keep, seed=1)
        long = len(exe._cache) - base - short
        assert toks.shape == (2, 16)
        assert long == short, (
            "per-generation jit entries grew with generated length: "
            "%d vs %d" % (long, short))
        # warm re-runs with fresh prompts (same program, same scope):
        # zero new entries
        main, _startup, tokens, gen_len = keep[-1]
        warm = len(exe._cache)
        with scope_guard(scope):
            for seed in (4, 5):
                feed = gpt_small.make_fake_prompt(
                    2, 8, gpt_small.GPT_TINY,
                    np.random.RandomState(seed))
                exe.run(main, feed=feed, fetch_list=[tokens, gen_len])
        assert len(exe._cache) == warm

    def test_zero_sync_certificate_over_decode_program(self,
                                                       monkeypatch):
        """The generation program passes the PR-10 zero-sync certificate
        with strict-sync promotion on: the while-op decode loop adds no
        host sync to the hot path."""
        import gpt_small

        from paddle_tpu.static_analysis.concurrency import \
            certify_zero_sync

        monkeypatch.setenv("PADDLE_TPU_STRICT_SYNC", "1")
        fluid.unique_name.switch()
        main, startup, feeds, tokens, gen_len = gpt_small.build_program(
            gpt_small.GPT_TINY, 2, 8, 4)
        main._serving_hot_loop = True
        cert = certify_zero_sync(main,
                                 targets=[tokens.name, gen_len.name],
                                 label="decode")
        assert cert.ok, "\n".join(str(d) for d in cert.diagnostics)

    def test_kv_cache_matches_naive_full_recompute(self):
        """Equivalence oracle: greedy decoding through the ring cache
        produces exactly the naive recompute-everything tokens.  A
        short max_len keeps the naive arm's all-Tmax-per-step graphs
        cheap — bench.py's --child decode runs the Tmax=512 A/B."""
        import gpt_small

        cfg = gpt_small.GPTConfig(max_len=32)
        toks_kv, _glen, _t, _r = gpt_small.run_generate(
            lambda: gpt_small.build_program(cfg, 2, 8, 6), cfg, 2, 8, 6)
        toks_nv, _glen, _t, _r = gpt_small.run_generate(
            lambda: gpt_small.build_naive_program(cfg, 2, 8, 6),
            cfg, 2, 8, 6)
        np.testing.assert_array_equal(toks_kv, toks_nv)

    def test_eos_early_exit_pads_with_eos(self):
        """A vocabulary rigged so the decode loop hits eos row-by-row:
        gen_len counts real tokens, finished rows keep emitting eos
        until every row is done, and positions past the global early
        exit keep the initial zero fill (slice with gen_len)."""
        V, eos = 16, 3
        fluid.unique_name.switch()
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            first = fluid.layers.data("first", shape=[2], dtype="int32",
                                      append_batch_size=False)
            plen = fluid.layers.data("plen", shape=[1], dtype="int32",
                                     append_batch_size=False)

            def step(cur, cursor, i):
                # next = cur + 1 (one-hot logits), so rows march to eos
                nxt = fluid.layers.elementwise_add(
                    cur, fluid.layers.fill_constant([2], "int32", 1))
                oh = fluid.layers.one_hot(
                    fluid.layers.reshape(nxt, [2, 1]), V)
                return fluid.layers.cast(oh, "float32")

            tokens, gen_len = fluid.layers.decode_loop(
                step, first, plen, max_new_tokens=8, eos_id=eos)
        out = _run(main, startup,
                   {"first": np.asarray([0, 2], "int32"),
                    "plen": np.asarray([1], "int32")},
                   [tokens, gen_len])
        toks, glen = np.asarray(out[0]), np.asarray(out[1])
        # row 0: 0,1,2,3(eos) -> 4 real tokens; row 1: 2,3(eos) -> 2
        assert glen.tolist() == [4, 2]
        assert toks[0, :4].tolist() == [0, 1, 2, 3]
        assert toks[1, :2].tolist() == [2, 3]
        # row 1 finished early: it keeps writing eos until row 0
        # finishes at step 4, which is also the loop's early exit —
        # slots past that keep the initial zero fill
        assert toks[1, 2:4].tolist() == [eos, eos]
        assert toks[0, 4:].tolist() == [0] * 4
        assert toks[1, 4:].tolist() == [0] * 4


# ---------------------------------------------------------------------------
# decode-shape-unbucketed lint
# ---------------------------------------------------------------------------


class TestDecodeShapeLint:
    def _naive_concat_loop(self):
        """The anti-pattern: a while loop growing its carried KV by
        concat every step (the reference DecoderBase shape regime)."""
        fluid.unique_name.switch()
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            kv = fluid.layers.data("kv", shape=[2, 4, 8],
                                   dtype="float32",
                                   append_batch_size=False)
            step = fluid.layers.data("x", shape=[2, 1, 8],
                                     dtype="float32",
                                     append_batch_size=False)
            i = fluid.layers.fill_constant([1], "int32", 0)
            limit = fluid.layers.fill_constant([1], "int32", 4)
            cond = fluid.layers.less_than(i, limit)
            w = fluid.layers.While(cond)
            with w.block():
                grown = fluid.layers.concat([kv, step], axis=1)
                fluid.layers.assign(grown, output=kv)
                fluid.layers.increment(i, value=1, in_place=True)
                fluid.layers.less_than(i, limit, cond=cond)
            out = fluid.layers.reduce_sum(kv)
        return main, out

    def test_positive_flags_growing_carry(self):
        main, out = self._naive_concat_loop()
        report = main.analyze(targets=[out.name])
        hits = [d for d in report.diagnostics
                if d.check == "decode-shape-unbucketed"]
        assert hits, "concat-grown loop carry must be flagged"
        assert "ring-buffer" in (hits[0].hint or "")

    def test_negative_gpt_small_is_clean(self):
        import gpt_small

        fluid.unique_name.switch()
        main, startup, feeds, tokens, gen_len = gpt_small.build_program(
            gpt_small.GPT_TINY, 2, 8, 4)
        report = main.analyze(targets=[tokens.name, gen_len.name])
        assert not [d for d in report.diagnostics
                    if d.check == "decode-shape-unbucketed"]
        assert not report.errors
