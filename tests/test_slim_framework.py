"""slim framework layer: GraphWrapper + Compressor strategies
(reference ``contrib/slim/graph/graph_wrapper.py``,
``core/compressor.py``, ``prune/prune_strategy.py``,
``quantization/quantization_strategy.py``,
``distillation/distillation_strategy.py``)."""

import numpy as np

import paddle_tpu as fluid
from paddle_tpu.contrib.slim.core import Compressor, Strategy
from paddle_tpu.contrib.slim.graph import GraphWrapper
from paddle_tpu.executor import Scope, scope_guard

rng = np.random.RandomState(7)


def _convnet():
    fluid.unique_name.switch()
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        img = fluid.layers.data("img", shape=[3, 8, 8], dtype="float32")
        label = fluid.layers.data("label", shape=[1], dtype="int64")
        conv = fluid.layers.conv2d(img, num_filters=4, filter_size=3,
                                   padding=1, act="relu")
        pool = fluid.layers.pool2d(conv, pool_size=8, pool_type="avg")
        logits = fluid.layers.fc(pool, size=3)
        loss = fluid.layers.mean(
            fluid.layers.softmax_with_cross_entropy(logits, label))
    return main, startup, loss


def _reader(n=4, bs=8):
    def gen():
        r = np.random.RandomState(0)
        for _ in range(n):
            yield {"img": r.rand(bs, 3, 8, 8).astype("float32"),
                   "label": r.randint(0, 3, (bs, 1)).astype("int64")}
    return gen


class TestGraphWrapper:
    def test_walks_and_costing(self):
        main, startup, loss = _convnet()
        g = GraphWrapper(main)
        types = [op.type() for op in g.ops()]
        assert "pool2d" in types and "mul" in types
        # producer/consumer walks agree with program order
        pool_op = next(op for op in g.ops() if op.type() == "pool2d")
        pre = {op.type() for op in g.pre_ops(pool_op)}
        nxt = {op.type() for op in g.next_ops(pool_op)}
        assert "relu" in pre
        assert "mul" in nxt or "reshape" in nxt
        # parameters reachable from their ops
        conv_op = next(op for op in g.ops()
                       if op.type() in ("conv2d", "depthwise_conv2d"))
        pnames = [p.name() for p in g.get_param_by_op(conv_op)]
        assert any(".w_0" in n for n in pnames)
        # costing: conv 4 filters of 3x3x3 over 8x8 out + fc 4->3 (+
        # elementwise/activation terms) — exact conv+bias+fc part known
        conv_flops = 2 * 8 * 8 * 4 * (3 * 3 * 3)
        assert g.flops() >= conv_flops
        # params: conv w 4*3*3*3 + b 4 + fc w 4*3 + b 3
        assert g.numel_params() == 4 * 3 * 3 * 3 + 4 + 4 * 3 + 3

    def test_var_wrapper(self):
        main, startup, loss = _convnet()
        g = GraphWrapper(main)
        v = g.var(loss.name)
        assert v.name() == loss.name
        assert [op.type() for op in v.inputs()] == ["mean"]
        assert v.outputs() == []


class TestCompressorStrategies:
    def _run_compressor(self, strategies, epochs=2, optimizer=True):
        main, startup, loss = _convnet()
        scope = Scope()
        with scope_guard(scope):
            exe = fluid.Executor(fluid.CPUPlace())
            comp = Compressor(
                fluid.CPUPlace(), scope, main,
                train_reader=_reader(),
                train_fetch_list=[loss.name],
                train_optimizer=fluid.optimizer.Adam(learning_rate=1e-3)
                if optimizer else None,
                startup_program=startup)
            comp.epoch = epochs
            comp.config(strategies)
            # the compressor runs startup itself, AFTER strategies and
            # optimizer build (reference compressor init ordering)
            ctx = comp.run()
        return ctx, scope, main

    def test_hooks_fire_in_order(self):
        calls = []

        class Probe(Strategy):
            def on_compression_begin(self, context):
                calls.append("cb")

            def on_epoch_begin(self, context):
                calls.append("eb%d" % context["epoch"])

            def on_epoch_end(self, context):
                calls.append("ee%d" % context["epoch"])

            def on_compression_end(self, context):
                calls.append("ce")

        self._run_compressor([Probe()], epochs=2)
        assert calls == ["cb", "eb0", "ee0", "eb1", "ee1", "ce"]

    def test_compressor_builds_optimizer_after_strategies(self):
        """The optimizer is built AFTER on_compression_begin so graph-
        rewriting strategies see the forward-only program (the reference
        graph-then-compile ordering)."""
        seen = {}

        class Probe(Strategy):
            def on_compression_begin(self, context):
                seen["grad_ops_at_begin"] = any(
                    op.type.endswith("_grad")
                    for op in context["program"].global_block().ops)

        ctx, scope, main = self._run_compressor([Probe()])
        assert seen["grad_ops_at_begin"] is False
        assert any(op.type.endswith("_grad")
                   for op in main.global_block().ops)

    def test_uniform_prune_strategy(self):
        from paddle_tpu.contrib.slim.prune.prune_strategy import (
            UniformPruneStrategy)

        s = UniformPruneStrategy(target_ratio=0.5, start_epoch=1,
                                 pruned_params="*.w_0")
        ctx, scope, main = self._run_compressor([s], epochs=2)
        assert s.pruned_idx  # something was pruned
        # lazy pruning zeroed whole filter groups
        for name, idx in s.pruned_idx.items():
            w = np.asarray(scope.get(name))
            assert len(idx) >= 1
            # pruned at epoch-1 BEGIN, then one epoch of training moved
            # them off zero slightly — check the prune actually bit by
            # magnitude ordering instead of exact zeros
            assert w.shape  # still static shapes (mask pruning)

    def test_uniform_prune_zeroes_groups_without_training(self):
        from paddle_tpu.contrib.slim.prune.prune_strategy import (
            UniformPruneStrategy)

        # prune at epoch 0 with NO optimizer: weights stay zeroed
        s = UniformPruneStrategy(target_ratio=0.5, start_epoch=0,
                                 pruned_params="*.w_0")
        ctx, scope, main = self._run_compressor([s], epochs=1,
                                                optimizer=False)
        name, idx = next(iter(s.pruned_idx.items()))
        w = np.asarray(scope.get(name))
        sl = [slice(None)] * w.ndim
        sl[0] = list(idx)
        assert np.all(w[tuple(sl)] == 0.0)

    def test_sensitive_prune_strategy(self):
        from paddle_tpu.contrib.slim.prune.prune_strategy import (
            SensitivePruneStrategy)

        r = np.random.RandomState(1)
        batch = {"img": r.rand(8, 3, 8, 8).astype("float32"),
                 "label": r.randint(0, 3, (8, 1)).astype("int64")}
        main, startup, loss = _convnet()
        eval_prog = main.clone(for_test=True)
        scope = Scope()
        with scope_guard(scope):
            s = SensitivePruneStrategy(
                target_ratio=0.4, start_epoch=0, eval_batch=batch,
                loss_name=loss.name)
            comp = Compressor(
                fluid.CPUPlace(), scope, main, train_reader=_reader(),
                train_fetch_list=[loss.name],
                eval_program=eval_prog,
                train_optimizer=fluid.optimizer.Adam(learning_rate=1e-3),
                startup_program=startup)
            comp.config([s])
            ctx = comp.run()
        assert s.sensitivities  # measured
        assert s.ratios
        # mean assigned ratio tracks the target
        assert abs(np.mean(list(s.ratios.values())) - 0.4) < 0.15
        assert 0 < ctx["achieved_sparsity"] < 1

    def test_quantization_strategy_insert_train_freeze(self):
        from paddle_tpu.contrib.slim.quantization.quantization_strategy \
            import QuantizationStrategy

        s = QuantizationStrategy(start_epoch=0, end_epoch=1)
        ctx, scope, main = self._run_compressor([s], epochs=2)
        assert ctx["quantized_slots"] == 4  # conv In+Filter, mul X+Y
        # gradients flowed THROUGH the fake-quant ops (ordering test)
        types = [op.type for op in main.global_block().ops]
        assert any(t.startswith("fake_quantize_dequantize") for t in types)
        frozen = ctx["quant_frozen_program"]
        ftypes = [op.type for op in frozen.global_block().ops]
        assert ftypes.count("fake_dequantize_max_abs") == 2
        assert not any(t.startswith("fake_quantize_dequantize")
                       for t in ftypes)

    def test_distillation_strategy_trains_distill_program(self):
        """The distillation epochs must actually OPTIMIZE the distill
        loss (via distiller_optimizer), not just swap which program is
        stepped forward-only."""
        from paddle_tpu.contrib.slim.distillation import l2_loss
        from paddle_tpu.contrib.slim.distillation.distillation_strategy \
            import DistillationStrategy

        fluid.unique_name.switch()
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.layers.data("x", shape=[8], dtype="float32")
            student = fluid.layers.fc(x, size=4, name="student")
            teacher = fluid.layers.fc(x, size=4, name="teacher")
            task_loss = fluid.layers.reduce_mean(
                fluid.layers.square(student))
        # the merged distill program: task loss + l2 distiller term
        distill_prog = main.clone()
        with fluid.program_guard(distill_prog, startup):
            s_var = distill_prog.global_block().var(student.name)
            t_var = distill_prog.global_block().var(teacher.name)
            dloss = fluid.layers.elementwise_add(
                distill_prog.global_block().var(task_loss.name),
                l2_loss(t_var, s_var))

        def reader():
            r = np.random.RandomState(0)
            for _ in range(4):
                yield {"x": r.rand(8, 8).astype("float32")}

        stepped = []

        class Spy(Strategy):
            def on_epoch_begin(self, context):
                stepped.append(context["program"])

        s = DistillationStrategy(start_epoch=0, end_epoch=1,
                                 distill_program=distill_prog,
                                 distill_fetch_list=[dloss.name])
        scope = Scope()
        with scope_guard(scope):
            comp = Compressor(
                fluid.CPUPlace(), scope, main, train_reader=reader,
                train_fetch_list=[task_loss.name],
                train_optimizer=fluid.optimizer.SGD(learning_rate=0.1),
                distiller_optimizer=fluid.optimizer.SGD(
                    learning_rate=0.1),
                startup_program=startup)
            comp.epoch = 3
            comp.config([s, Spy()])
            # snapshot the student weight right after the compressor's
            # own init would run — do a manual init to capture w0
            w_name = "student.w_0"
            ctx = comp.run()
            w_after = np.asarray(scope.get(w_name))
        # epochs 0-1 trained the distill program, epoch 2 the original
        assert stepped[0] is distill_prog
        assert stepped[1] is distill_prog
        assert stepped[2] is main
        # the distill program REALLY got optimizer ops and trained
        assert any(op.type.endswith("_grad")
                   for op in distill_prog.global_block().ops)
        assert np.abs(w_after).sum() > 0
        # teacher params untouched by the distill epochs (stop_gradient
        # through the assign in l2_loss)
        # (teacher trains in epoch 2's task program run — so compare
        # the DISTILL program's grad op outputs instead)
        grad_outs = [n for op in distill_prog.global_block().ops
                     if op.type.endswith("_grad")
                     for ns in op.outputs.values() for n in ns]
        assert any("student.w_0" in n for n in grad_outs)
        assert not any("teacher.w_0" in n for n in grad_outs)

    def test_yaml_config_instantiates_strategies(self, tmp_path):
        """Reference-shaped YAML registry: named strategy specs with
        class + kwargs, pruner cross-references, compress_pass epoch."""
        cfg = tmp_path / "compress.yaml"
        cfg.write_text(
            "strategies:\n"
            "  prune_one:\n"
            "    class: UniformPruneStrategy\n"
            "    target_ratio: 0.5\n"
            "    start_epoch: 0\n"
            "    pruner: pruner_1\n"
            "pruners:\n"
            "  pruner_1:\n"
            "    class: StructurePruner\n"
            "compress_pass:\n"
            "  epoch: 2\n"
            "  strategies: [prune_one]\n")
        from paddle_tpu.contrib.slim.prune import StructurePruner
        from paddle_tpu.contrib.slim.prune.prune_strategy import (
            UniformPruneStrategy)

        main, startup, loss = _convnet()
        scope = Scope()
        with scope_guard(scope):
            comp = Compressor(
                fluid.CPUPlace(), scope, main, train_reader=_reader(),
                train_fetch_list=[loss.name],
                train_optimizer=fluid.optimizer.Adam(learning_rate=1e-3),
                startup_program=startup)
            comp.config(str(cfg))
            assert comp.epoch == 2
            assert len(comp.strategies) == 1
            s = comp.strategies[0]
            assert isinstance(s, UniformPruneStrategy)
            assert s.target_ratio == 0.5
            assert isinstance(s.pruner, StructurePruner)
            ctx = comp.run()
        assert s.pruned_idx  # the YAML-built strategy really pruned

    def test_yaml_config_unknown_class_raises(self, tmp_path):
        cfg = tmp_path / "bad.yaml"
        cfg.write_text(
            "compress_pass:\n"
            "  strategies:\n"
            "    - class: NoSuchStrategy\n")
        main, startup, loss = _convnet()
        comp = Compressor(fluid.CPUPlace(), Scope(), main)
        try:
            comp.config(str(cfg))
            raise AssertionError("expected ValueError")
        except ValueError as e:
            assert "NoSuchStrategy" in str(e)

    def test_light_nas_strategy_respects_flops_budget(self):
        """LightNASStrategy: SA search over a width table under a
        GraphWrapper-FLOPs budget — the best candidate must satisfy the
        budget and beat the initial tokens."""
        from paddle_tpu.contrib.slim.nas import SearchSpace
        from paddle_tpu.contrib.slim.nas.light_nas_strategy import (
            LightNASStrategy)

        widths = [4, 8, 16, 32]

        class WidthSpace(SearchSpace):
            def init_tokens(self):
                return [0]

            def range_table(self):
                return [len(widths)]

            def create_net(self, tokens):
                fluid.unique_name.switch()
                main, startup = fluid.Program(), fluid.Program()
                with fluid.program_guard(main, startup):
                    x = fluid.layers.data("x", shape=[8],
                                          dtype="float32")
                    h = fluid.layers.fc(x, size=widths[tokens[0]])
                    out = fluid.layers.fc(h, size=1)
                return startup, main, out

        # reward favors the LARGEST width; the budget excludes 32
        def reward(net):
            _, main, _ = net
            return float(GraphWrapper(main).numel_params())

        # flops budget: width 16 net fits, width 32 does not
        def flops_of(w):
            fluid.unique_name.switch()
            s = WidthSpace()
            return GraphWrapper(s.create_net([widths.index(w)])[1]).flops()

        budget = (flops_of(16) + flops_of(32)) // 2
        s = LightNASStrategy(WidthSpace(), reward, search_steps=30,
                             max_flops=budget)
        ctx = {"epoch": 0}
        s.on_compression_begin(ctx)
        assert s.best_tokens is not None
        best_w = widths[s.best_tokens[0]]
        assert best_w == 16, (best_w, ctx["nas_best_reward"])

    def test_quantization_freeze_does_not_corrupt_training_scope(self):
        """end_epoch < last epoch: epochs after the freeze keep training
        on fp32 weights — the freeze writes int8 codes to a COPIED
        scope, never the live one."""
        from paddle_tpu.contrib.slim.quantization.quantization_strategy \
            import QuantizationStrategy

        s = QuantizationStrategy(start_epoch=0, end_epoch=0)
        ctx, scope, main = self._run_compressor([s], epochs=2)
        frozen = ctx["quant_frozen_program"]
        fscope = ctx["quant_frozen_scope"]
        conv = next(op for op in frozen.global_block().ops
                    if op.type in ("conv2d", "depthwise_conv2d"))
        w_name = conv.inputs["Filter"][0].rsplit(".quant_dequant", 1)[0]
        # frozen scope: int8 codes; training scope: still fp32
        assert np.asarray(fscope.get(w_name)).dtype == np.int8
        live = np.asarray(scope.get(w_name))
        assert live.dtype == np.float32
        assert np.abs(live).max() < 10.0  # weights, not quant codes
