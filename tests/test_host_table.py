"""Host-resident (bigger-than-HBM) embedding tables
(``paddle_tpu/host_table.py``) — the reference's distributed-lookup-table
CTR capability (``parameter_prefetch.cc`` remote prefetch +
``communicator.h:160`` async push) without a pserver.

Oracles:
1. loss parity: a DeepFM-style CTR model using ``host_embedding`` must
   train step-for-step identically to the same model using a normal
   device embedding parameter initialized with the same table (both
   sparse paths reduce duplicate-id grads before SGD);
2. the device step must never see the full table (only the dense slab);
3. checkpoint round-trip in the shared per-shard layout, including
   reshard (different rows_per_shard) on load.
"""

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import host_table
from paddle_tpu.executor import Scope, scope_guard

V, D, B, F = 50000, 16, 8, 3  # vocab deliberately ≫ batch rows touched
STEPS = 6


@pytest.fixture(autouse=True)
def _fresh_tables():
    host_table.reset_tables()
    yield
    host_table.reset_tables()


def _batches():
    rng = np.random.RandomState(3)
    ids = rng.randint(0, V, size=(B, F)).astype("int64")
    ids[:, 1] = ids[:, 0]  # guaranteed duplicate ids per row:
    # exercises the aggregate-before-update sparse semantics
    y = rng.randint(0, 2, size=(B, 1)).astype("float32")
    for _ in range(STEPS):
        yield ids, y  # fixed batch: repeated sparse updates must overfit


def _deep_part(emb3d):
    """Shared deep tower: [B, F, D] -> logit [B, 1]."""
    flat = fluid.layers.flatten(emb3d, axis=1)
    h = fluid.layers.fc(
        flat, size=8, act="relu",
        param_attr=fluid.ParamAttr(
            name="deep.w",
            initializer=fluid.initializer.NumpyArrayInitializer(
                np.random.RandomState(5).uniform(
                    -0.1, 0.1, (F * D, 8)).astype("float32"))),
        bias_attr=fluid.ParamAttr(
            name="deep.b", initializer=fluid.initializer.Constant(0.0)))
    return fluid.layers.fc(
        h, size=1,
        param_attr=fluid.ParamAttr(
            name="head.w",
            initializer=fluid.initializer.NumpyArrayInitializer(
                np.random.RandomState(6).uniform(
                    -0.1, 0.1, (8, 1)).astype("float32"))),
        bias_attr=fluid.ParamAttr(
            name="head.b", initializer=fluid.initializer.Constant(0.0)))


def _train_host():
    fluid.unique_name.switch()
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        ids = fluid.layers.data("ids", shape=[B, F], dtype="int64",
                                append_batch_size=False)
        y = fluid.layers.data("y", shape=[B, 1], dtype="float32",
                              append_batch_size=False)
        slab = fluid.layers.host_embedding(ids, size=[V, D], name="ctr.tbl",
                                           lr=0.1, optimizer="sgd")
        logit = _deep_part(slab)
        loss = fluid.layers.mean(
            fluid.layers.sigmoid_cross_entropy_with_logits(logit, y))
        fluid.optimizer.SGD(0.1).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    losses = []
    with scope_guard(Scope()):
        exe.run(startup)
        for ids_v, y_v in _batches():
            (lv,) = exe.run(main, feed={"ids": ids_v, "y": y_v},
                            fetch_list=[loss])
            losses.append(float(np.asarray(lv).reshape(())))
        host_table.get_table("ctr.tbl").join()
    return losses, main, exe


def _train_device(table0):
    fluid.unique_name.switch()
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        ids = fluid.layers.data("ids", shape=[B, F], dtype="int64",
                                append_batch_size=False)
        y = fluid.layers.data("y", shape=[B, 1], dtype="float32",
                              append_batch_size=False)
        emb = fluid.layers.embedding(
            ids, size=[V, D],
            param_attr=fluid.ParamAttr(
                name="dev.tbl",
                initializer=fluid.initializer.NumpyArrayInitializer(
                    table0)))
        logit = _deep_part(emb)
        loss = fluid.layers.mean(
            fluid.layers.sigmoid_cross_entropy_with_logits(logit, y))
        fluid.optimizer.SGD(0.1).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    losses = []
    with scope_guard(Scope()):
        exe.run(startup)
        for ids_v, y_v in _batches():
            (lv,) = exe.run(main, feed={"ids": ids_v, "y": y_v},
                            fetch_list=[loss])
            losses.append(float(np.asarray(lv).reshape(())))
    return losses


def test_ctr_loss_parity_host_vs_device():
    host_losses, main, _ = _train_host()
    # re-create the table fresh for the oracle (same name+seed → the
    # deterministic step-0 init the host run started from)
    host_table.reset_tables()
    t = host_table.get_or_create("ctr.tbl", V, D, lr=0.1, optimizer="sgd")
    dev_losses = _train_device(t.value.copy())
    np.testing.assert_allclose(host_losses, dev_losses, rtol=1e-5)
    assert host_losses[-1] < host_losses[0]  # it actually learns


def test_device_never_sees_the_table():
    _, main, exe = _train_host()
    # every cached compilation's device inputs: feeds + rw + ro names —
    # none may be table-shaped; only the [B, F, D] slab enters the step
    for compiled in exe._cache.values():
        for n in compiled.rw_names + compiled.ro_names:
            v = main.global_block()._find_var_recursive(n)
            assert v is None or list(v.shape or ()) != [V, D], n
    assert any(
        any("@SLAB@" in n for n in compiled.feed_names)
        for compiled in exe._cache.values())


def test_checkpoint_roundtrip_and_reshard():
    import tempfile

    t = host_table.get_or_create("ck.tbl", 1000, 8, lr=0.1)
    orig = t.value.copy()
    d = tempfile.mkdtemp()
    t.save(d, rows_per_shard=128)  # 8 row-range shards
    t.value[:] = 0.0
    t.load(d)
    np.testing.assert_array_equal(t.value, orig)

    # reshard: save with a different chunking, load back
    t.save(d, rows_per_shard=333)
    t.value[:] = -1.0
    t.load(d)
    np.testing.assert_array_equal(t.value, orig)


def test_deepfm_model_with_host_tables_trains():
    """The real DeepFM model family (models/ctr.py) with host-resident
    slot tables: must train (loss decreases on a fixed batch) through
    the plain Executor path."""
    from paddle_tpu.models import ctr

    fluid.unique_name.switch()
    main, startup, feeds, loss, prob = ctr.build(
        model="deepfm", num_slots=4, slot_len=3, vocab=100000,
        use_host_table=True, host_lr=0.05)
    rng = np.random.RandomState(9)
    feed = {"slot_%d" % i: rng.randint(0, 100000, (8, 3)).astype("int64")
            for i in range(4)}
    feed["label"] = rng.randint(0, 2, (8, 1)).astype("int64")
    exe = fluid.Executor(fluid.CPUPlace())
    losses = []
    with scope_guard(Scope()):
        exe.run(startup)
        for _ in range(8):
            (lv,) = exe.run(main, feed=feed, fetch_list=[loss])
            losses.append(float(np.asarray(lv).reshape(())))
    assert losses[-1] < losses[0], losses


def test_host_table_under_data_parallel():
    """The production CTR shape: DeepFM with host-resident tables under
    CompiledProgram.with_data_parallel on the 8-device mesh — per-step
    loss parity with the plain single-device Executor run (GSPMD shards
    the slab over the data axis; the host push sees the global batch)."""
    from paddle_tpu.models import ctr

    single = ctr.run_deepfm_host_table_steps(
        steps=5, data_parallel=False, vocab=30000)
    dp = ctr.run_deepfm_host_table_steps(
        steps=5, data_parallel=True, vocab=30000)
    np.testing.assert_allclose(dp, single, rtol=1e-4)
    assert dp[-1] < dp[0]


def test_host_table_with_train_from_dataset():
    """The reference CTR deployment shape end to end: MultiSlot files →
    InMemoryDataset → train_from_dataset, with the embedding tables
    HOST-RESIDENT (ids reach the prefetch through the dataset's feed
    dicts — the dist_ctr.py + pserver-table composition, pserver-free)."""
    import os
    import tempfile

    from paddle_tpu.dataset import DatasetFactory

    rng = np.random.RandomState(11)
    tmpd = tempfile.mkdtemp()
    path = os.path.join(tmpd, "part-0")
    with open(path, "w") as f:
        for _ in range(32):
            y = rng.randint(0, 2)
            ids = rng.randint(1, 5000, 3)
            f.write("1 %d 3 %s\n" % (y, " ".join(map(str, ids))))

    fluid.unique_name.switch()
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        label = fluid.layers.data("label", shape=[1], dtype="int64")
        slot = fluid.layers.data("slot", shape=[3], dtype="int64")
        slab = fluid.layers.host_embedding(slot, size=[5000, 8],
                                           name="ds.tbl", lr=0.1)
        pooled = fluid.layers.reduce_sum(slab, dim=1)
        logit = fluid.layers.fc(pooled, size=1)
        loss = fluid.layers.mean(
            fluid.layers.sigmoid_cross_entropy_with_logits(
                logit, fluid.layers.cast(label, "float32")))
        fluid.optimizer.SGD(0.1).minimize(loss)

    dataset = DatasetFactory().create_dataset("InMemoryDataset")
    dataset.set_use_var([label, slot])
    dataset.set_batch_size(8)
    dataset.set_filelist([path])
    dataset.load_into_memory()

    t0 = host_table.get_table("ds.tbl").value.copy()
    exe = fluid.Executor(fluid.CPUPlace())
    with scope_guard(Scope()):
        exe.run(startup)
        results = exe.train_from_dataset(
            program=main, dataset=dataset, fetch_list=[loss],
            print_period=100)
    assert len(results) == 4  # 32 / 8
    assert all(np.isfinite(r[0]).all() for r in results)
    t = host_table.get_table("ds.tbl")
    t.join()
    assert (t.value != t0).any()  # the sparse push actually updated rows


def test_adagrad_accumulator_survives_checkpoint():
    import tempfile

    t = host_table.get_or_create("ada.tbl", 100, 4, lr=0.1,
                                 optimizer="adagrad")
    ids = np.array([1, 1, 7], "int64")
    g = np.ones((3, 4), "float32")
    t.update_async(ids, g)
    t.join()
    acc = t._accum.copy()
    assert acc[1].sum() > 0  # duplicate ids aggregated then squared
    d = tempfile.mkdtemp()
    t.save(d)
    t._accum[:] = 0
    t.value[:] = 0
    t.load(d)
    np.testing.assert_array_equal(t._accum, acc)


def test_get_or_create_rejects_spec_mismatch():
    host_table.get_or_create("m.tbl", 10, 4, lr=0.1)
    with pytest.raises(ValueError, match="already exists"):
        host_table.get_or_create("m.tbl", 20, 4, lr=0.1)


def test_save_load_persistables_includes_host_tables():
    import tempfile

    host_losses, main, exe = _train_host()
    t = host_table.get_table("ctr.tbl")
    trained = t.value.copy()
    d = tempfile.mkdtemp()
    fluid.io.save_persistables(exe, d, main)
    t.value[:] = 0.0
    fluid.io.load_persistables(exe, d, main)
    np.testing.assert_array_equal(t.value, trained)
