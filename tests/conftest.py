"""Test config: run on a virtual 8-device CPU mesh (the reference's
"fake cluster" pattern: test_dist_base.py uses localhost subprocesses; here
XLA's forced host device count gives 8 fake TPU chips — SURVEY.md §4)."""

import os

# must be set before the XLA backend initializes
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
)

# Hermetic autotune cache: the fusion gates consult the per-user cache
# (~/.cache/paddle_tpu/...), and a developer's local sweep recording a
# calibration factor would silently flip gate decisions inside the
# suite.  Point at a per-process temp file (explicit env still wins;
# autotune tests monkeypatch their own paths on top).
import tempfile

os.environ.setdefault(
    "PADDLE_TPU_AUTOTUNE_CACHE",
    os.path.join(tempfile.gettempdir(),
                 "paddle_tpu_autotune_test_%d.json" % os.getpid()))

# Analyzer brackets every rewrite pass with the static_analysis verifier
# (off by default in production, ON in tests): a pass that breaks
# producer/consumer links fails HERE with structured diagnostics instead
# of surfacing as an opaque trace-time JAX error downstream.
os.environ.setdefault("PADDLE_TPU_VERIFY_PASSES", "1")

import pytest

import jax


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: heavyweight tests excluded from the tier-1 "
                   "run (ROADMAP.md runs -m 'not slow')")


@pytest.fixture
def verify_clean():
    """Run ``verify_program`` on a program and assert no ERROR-severity
    findings; returns all diagnostics (advisories included) so tests can
    also assert on warnings.  Usage: ``verify_clean(program, targets=[...])``.
    """
    def _check(program, targets=None):
        from paddle_tpu.static_analysis import assert_valid

        return assert_valid(program, targets=targets)

    return _check


if not os.environ.get("PADDLE_TPU_TESTS_ON_TPU"):
    # the image pins jax_platforms=axon,cpu (real TPU via tunnel); tests
    # run on CPU so they are hermetic and can use the 8-device mesh.
    # PADDLE_TPU_TESTS_ON_TPU=1 leaves the real backend active — the
    # reference's backend-flag rerun pattern (unittests/mkldnn/* reruns
    # the same OpTest classes with use_mkldnn on; SURVEY §4): the op-test
    # files then execute on the chip with bf16-tolerant bounds
    # (tools/hw_when_up.py runs them whenever the tunnel is up).
    jax.config.update("jax_platforms", "cpu")
else:
    import pytest

    def pytest_collection_modifyitems(config, items):
        """TPU rerun covers the OpTest corpus only: non-OpTest tests
        assert CPU-tight tolerances (1e-5/1e-6) that bf16 MXU matmuls
        legitimately miss, and some drive multi-device meshes that the
        single chip doesn't have."""
        from op_test import OpTest

        mark = pytest.mark.skip(
            reason="TPU backend rerun covers OpTest classes only")
        for item in items:
            cls = getattr(item, "cls", None)
            if cls is None or not issubclass(cls, OpTest):
                item.add_marker(mark)
