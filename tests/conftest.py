"""Test config: run on a virtual 8-device CPU mesh (the reference's
"fake cluster" pattern: test_dist_base.py uses localhost subprocesses; here
XLA's forced host device count gives 8 fake TPU chips — SURVEY.md §4)."""

import os

# must be set before the XLA backend initializes
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
)

import jax

# the image pins jax_platforms=axon,cpu (real TPU via tunnel); tests run on
# CPU so they are hermetic and can use the 8-device mesh
jax.config.update("jax_platforms", "cpu")
