"""Per-op device-cost attribution (VERDICT r4 #6).

The executor wraps every op lowering in ``jax.named_scope("pd<i>_<type>")``
so device profiles can be mapped back to Program ops — the device-side
equivalent of the reference's per-op profiler tables
(``platform/profiler.h:166-171``, rendered by ``tools/timeline.py:115``).

Three layers asserted on the CPU backend:
1. the scope tags actually ride the executor lowering into HLO metadata;
2. ``attribute_op_name`` extracts the innermost Program-op tag from the
   scope paths XLA emits;
3. ``device_op_stats`` parses a (synthetic, schema-true) XPlane proto
   into the reference-style total/max/ave table, including the
   unattributed-row fallback.
"""

import re

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as fluid
import paddle_tpu.executor as ex
from paddle_tpu import profiler
from paddle_tpu.executor import Scope, scope_guard


def test_scope_tags_reach_hlo_metadata():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[8], dtype="float32")
        y = fluid.layers.data("y", shape=[1], dtype="float32")
        h = fluid.layers.fc(input=x, size=4, act="relu")
        loss = fluid.layers.reduce_mean(fluid.layers.square(h - y))
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    sc = Scope()
    with scope_guard(sc):
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        feed = {"x": jnp.zeros((4, 8)), "y": jnp.zeros((4, 1))}
        cb = ex._CompiledBlock(main, main.global_block(), list(feed),
                               [loss.name], sc, "train")
        rw = {n: sc.get(n) for n in cb.rw_names}
        ro = {n: sc.get(n) for n in cb.ro_names}
        from paddle_tpu.jax_compat import lowered_as_text

        txt = lowered_as_text(
            cb.jitted.lower(feed, rw, ro, ex.rng_key(0)),
            debug_info=True)
    tags = set(re.findall(r"pd\d+_[a-z0-9_]+", txt))
    types = {t.split("_", 1)[1] for t in tags}
    # forward, backward and optimizer ops all carry tags
    assert "relu" in types
    assert "relu_grad" in types
    assert "sgd" in types
    assert "reduce_mean" in types


def test_attribute_op_name():
    f = profiler.attribute_op_name
    assert f("jit(run)/pd3_conv2d/conv_general_dilated") == ("conv2d", 3)
    # nested scopes: the INNERMOST Program op wins (a while op's body
    # ops are attributed to themselves, not the while)
    assert f("jit(r)/pd2_while/pd5_elementwise_add/add") == (
        "elementwise_add", 5)
    assert f("pd12_softmax_with_cross_entropy") == (
        "softmax_with_cross_entropy", 12)
    assert f("fusion.1234") is None
    assert f("") is None
    assert f(None) is None


def _synthetic_xspace(tmp_path):
    xplane_pb2 = pytest.importorskip(
        "tensorflow.tsl.profiler.protobuf.xplane_pb2")
    space = xplane_pb2.XSpace()
    plane = space.planes.add(name="/device:TPU:0")
    line = plane.lines.add(name="XLA Ops")

    # stat names: real device planes carry the scope path in a
    # string-valued stat (schema varies; the parser scans them all)
    plane.stat_metadata[1].id = 1
    plane.stat_metadata[1].name = "tf_op"
    plane.stat_metadata[2].id = 2
    plane.stat_metadata[2].name = "jit(run)/pd7_sgd/scatter"  # ref target

    def add_event(mid, name, dur_ms, display="", stat_str=None,
                  stat_ref=None):
        md = plane.event_metadata[mid]
        md.id = mid
        md.name = name
        if display:
            md.display_name = display
        ev = line.events.add(metadata_id=mid, offset_ps=0,
                             duration_ps=int(dur_ms * 1e9))
        if stat_str is not None:
            st = ev.stats.add(metadata_id=1)
            st.str_value = stat_str
        if stat_ref is not None:
            st = ev.stats.add(metadata_id=1)
            st.ref_value = stat_ref
        return ev

    # two conv2d events, scope carried two different ways
    add_event(1, "fusion.7", 2.0, display="jit(run)/pd3_conv2d/conv")
    add_event(2, "convolution.9", 4.0,
              stat_str="jit(run)/pd3_conv2d/conv_general_dilated")
    # an sgd event whose scope arrives via a ref_value stat
    add_event(3, "fusion.11", 1.0, stat_ref=2)
    # an unattributed fusion: must stay visible under '~'
    add_event(4, "fusion.99", 8.0)
    # async-start spans: their duration covers the whole in-flight
    # window (overlaps compute) — must collapse into the single
    # ASYNC_OVERLAP_ROW, even when scope-tagged (the tag would bill
    # overlapped time to that op)
    add_event(5, "%copy-start.5 = (bf16[3072]) copy-start(...)", 5.0)
    add_event(6, "%slice-start.7 = ((f32[30522,768])) async-start", 4.0,
              stat_str="jit(run)/pd3_conv2d/slice")
    # a host plane that must be ignored entirely
    host = space.planes.add(name="/host:CPU")
    hl = host.lines.add(name="XLA Ops")
    host.event_metadata[1].id = 1
    host.event_metadata[1].name = "jit(run)/pd3_conv2d/ignored"
    hl.events.add(metadata_id=1, duration_ps=int(99 * 1e9))

    d = tmp_path / "plugins" / "profile" / "run1"
    d.mkdir(parents=True)
    (d / "host.xplane.pb").write_bytes(space.SerializeToString())
    return str(tmp_path)


def test_device_op_stats_synthetic(tmp_path):
    table = profiler.device_op_stats(_synthetic_xspace(tmp_path))
    assert table["conv2d"][0] == 2          # calls
    assert abs(table["conv2d"][1] - 6.0) < 1e-6   # total ms
    assert abs(table["conv2d"][2] - 4.0) < 1e-6   # max ms
    assert abs(table["conv2d"][3] - 2.0) < 1e-6   # min ms
    assert table["sgd"][0] == 1
    assert abs(table["sgd"][1] - 1.0) < 1e-6
    # unattributed row present, host plane excluded
    unattr = sorted(k for k in table if k.startswith("~"))
    assert unattr == [profiler.ASYNC_OVERLAP_ROW, "~fusion.99"]
    assert abs(table["~fusion.99"][1] - 8.0) < 1e-6
    # both async spans (tagged or not) collapse into the overlap row —
    # conv2d's total must NOT include the tagged slice-start's 4ms
    assert table[profiler.ASYNC_OVERLAP_ROW][0] == 2
    assert abs(table[profiler.ASYNC_OVERLAP_ROW][1] - 9.0) < 1e-6
    total = sum(v[1] for n, v in table.items()
                if n != profiler.ASYNC_OVERLAP_ROW)
    assert abs(total - 15.0) < 1e-6


def test_device_op_events_and_timeline_merge(tmp_path):
    """Per-event rows carry attribution + timestamps, and
    tools/timeline.py renders a trace dir into op-named chrome rows
    (the reference timeline's device stream)."""
    import json
    import os
    import sys

    trace_dir = _synthetic_xspace(tmp_path)
    rows = profiler.device_op_events(trace_dir)
    names = [r[0] for r in rows]
    assert names.count("conv2d") == 2
    assert "sgd" in names
    assert "fusion.99" in names  # unattributed keeps its HLO name
    conv = next(r for r in rows if r[0] == "conv2d")
    assert conv[2] > 0  # duration_us

    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "tools"))
    import timeline

    out = tmp_path / "merged.json"
    n = timeline.merge([("dev", trace_dir)], str(out))
    assert n == 1 + len(rows)
    data = json.load(open(out))
    ev_names = [e["name"] for e in data["traceEvents"]
                if e.get("ph") == "X"]
    assert "conv2d" in ev_names and "sgd" in ev_names


def test_stop_profiler_prints_table(tmp_path, capsys, monkeypatch):
    """stop_profiler emits the reference-style sorted per-op report when
    a device trace directory holds attributable rows."""
    monkeypatch.setattr(profiler, "device_op_stats",
                        lambda d: {"conv2d": [2, 6.0, 4.0, 2.0],
                                   "sgd": [1, 1.0, 1.0, 1.0]})
    profiler.start_profiler("CPU")
    with profiler.record_event("step"):
        np.zeros(4).sum()
    # simulate an earlier device trace
    profiler._trace_dir = str(tmp_path)
    profiler._device_trace = True
    profiler.stop_profiler(profile_path=str(tmp_path / "timeline.json"))
    out = capsys.readouterr().out
    assert "Device per-op Report" in out
    conv_line = [l for l in out.splitlines() if l.startswith("conv2d")][0]
    cols = conv_line.split()
    assert cols[1] == "2"              # calls
    assert float(cols[2]) == 6.0       # total
    assert float(cols[5]) == 3.0       # ave = total/calls
