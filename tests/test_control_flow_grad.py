"""Gradients through control flow + review-fix regressions: recurrent_grad
via scan-vjp, cond() two-branch merge, StaticRNN.memory(batch_ref), array
capacity, while-grad diagnostics, dygraph guard nesting/no_grad."""

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.executor import Scope, scope_guard


def test_static_rnn_with_fc_trains():
    """Params used inside the step block must receive grads
    (review finding: backward silently skipped sub-block ops)."""
    T, B, D, H = 5, 4, 3, 6
    rng = np.random.RandomState(0)
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[T, B, D], dtype="float32",
                              append_batch_size=False)
        yt = fluid.layers.data("yt", shape=[B, H], dtype="float32",
                               append_batch_size=False)
        rnn = fluid.layers.StaticRNN()
        with rnn.step():
            xt = rnn.step_input(x)
            mem = rnn.memory(shape=[H], batch_ref=xt)
            nxt = fluid.layers.fc(
                [xt, mem], size=H, act="tanh", bias_attr=False
            )
            rnn.update_memory(mem, nxt)
            rnn.step_output(nxt)
        out = rnn()  # [T, B, H]
        last = fluid.layers.slice(out, axes=[0], starts=[T - 1], ends=[T])
        last = fluid.layers.squeeze(last, axes=[0])
        loss = fluid.layers.mean(fluid.layers.square_error_cost(last, yt))
        _, params_grads = fluid.optimizer.SGD(0.5).minimize(loss)
    assert len(params_grads) == 2, "fc weights inside RNN got no grads"
    exe = fluid.Executor(fluid.CPUPlace())
    with scope_guard(Scope()):
        exe.run(startup)
        xv = rng.randn(T, B, D).astype("float32")
        yv = rng.rand(B, H).astype("float32") * 0.5
        losses = [
            float(exe.run(main, feed={"x": xv, "yt": yv},
                          fetch_list=[loss])[0][0])
            for _ in range(60)
        ]
    assert losses[-1] < 0.3 * losses[0], (losses[0], losses[-1])


def test_while_unbounded_minimize_trains():
    """Round-4: minimize over an unbounded while no longer raises — the
    executor's trip-count probe (two-pass while_op.cc:189 lowering) makes
    the whole pipeline differentiable end to end."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        i = fluid.layers.fill_constant([1], "float32", 0.0)
        acc = fluid.layers.fc(
            fluid.layers.data("x", shape=[1], dtype="float32"), size=1
        )
        limit = fluid.layers.fill_constant([1], "float32", 3.0)
        cond = fluid.layers.less_than(i, limit)
        w = fluid.layers.While(cond)
        with w.block():
            fluid.layers.assign(fluid.layers.scale(acc, 2.0), output=acc)
            fluid.layers.increment(i, in_place=True)
            fluid.layers.less_than(i, limit, cond=cond)
        loss = fluid.layers.mean(fluid.layers.square(acc))
        # loss = (8 w x)^2 → dL/dw = 128 w x^2; lr must stay < 2/128
        fluid.optimizer.SGD(0.005).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    with scope_guard(Scope()):
        exe.run(startup)
        xv = np.array([[1.0]], "float32")
        losses = [float(np.asarray(exe.run(
            main, feed={"x": xv}, fetch_list=[loss])[0]).reshape(()))
            for _ in range(10)]
    assert losses[-1] < losses[0]


def test_cond_two_branches():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[1], dtype="float32",
                              append_batch_size=False)
        zero = fluid.layers.fill_constant([1], "float32", 0.0)
        pred = fluid.layers.greater_than(x, zero)
        out = fluid.layers.cond(
            pred,
            lambda: fluid.layers.fill_constant([1], "float32", 7.0),
            lambda: fluid.layers.fill_constant([1], "float32", -7.0),
        )
    exe = fluid.Executor(fluid.CPUPlace())
    hi = exe.run(main, feed={"x": np.array([2.0], "float32")},
                 fetch_list=[out])[0]
    lo = exe.run(main, feed={"x": np.array([-2.0], "float32")},
                 fetch_list=[out])[0]
    assert float(hi[0]) == 7.0 and float(lo[0]) == -7.0


def test_array_capacity_respected():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        arr = fluid.layers.create_array("float32", capacity=300)
        i = fluid.layers.fill_constant([1], "int32", 0)
        limit = fluid.layers.fill_constant([1], "int32", 200)
        x = fluid.layers.fill_constant([2], "float32", 1.0)
        fluid.layers.array_write(x, i, array=arr)
        cond = fluid.layers.less_than(i, limit)
        w = fluid.layers.While(cond)
        with w.block():
            fluid.layers.increment(i, value=1, in_place=True)
            fluid.layers.array_write(
                fluid.layers.cast(i, "float32") + fluid.layers.fill_constant(
                    [2], "float32", 0.0
                ),
                i, array=arr,
            )
            fluid.layers.less_than(i, limit, cond=cond)
        at150_i = fluid.layers.fill_constant([1], "int32", 150)
        at150 = fluid.layers.array_read(arr, at150_i)
    exe = fluid.Executor(fluid.CPUPlace())
    out = exe.run(main, fetch_list=[at150])[0]
    np.testing.assert_allclose(out, 150.0)


def test_dygraph_nested_guard_and_no_grad():
    from paddle_tpu.dygraph import guard, no_grad, to_variable, enabled
    from paddle_tpu.dygraph.tape import _tape_stack

    depth0 = len(_tape_stack)
    with guard():
        assert enabled()
        with guard():
            assert enabled()
        assert enabled(), "outer guard must survive inner exit"
        with no_grad():
            v = to_variable(np.ones(2, "float32"))  # must not raise
            assert enabled()
    assert len(_tape_stack) == depth0, "tape leaked on the stack"


def test_dygraph_regularization_applied():
    from paddle_tpu.dygraph import guard, to_variable, Linear
    from paddle_tpu.dygraph.varbase import eager_op

    with guard():
        m1 = Linear(2, 1, bias_attr=False)
        m2 = Linear(2, 1, bias_attr=False)
        m2.weight.set_value(m1.weight.numpy())
        x = to_variable(np.ones((4, 2), "float32"))
        for model, opt in (
            (m1, fluid.optimizer.SGD(0.1)),
            (m2, fluid.optimizer.SGD(
                0.1, regularization=fluid.regularizer.L2Decay(1.0))),
        ):
            loss = eager_op("mean", {"X": [model(x)]})[0]
            loss.backward()
            opt.minimize(loss, parameter_list=model.parameters())
            model.clear_gradients()
        # with decay the update must differ (extra -lr*coeff*w term)
        assert not np.allclose(m1.weight.numpy(), m2.weight.numpy())
