"""Op correctness vs numpy oracles + gradient checks (reference:
unittests/test_mul_op.py, test_elementwise_*_op.py, test_softmax_op.py,
test_reduce_op.py, ... — same OpTest pattern)."""

import numpy as np
import pytest

from op_test import OpTest

rng = np.random.RandomState(42)


class TestMul(OpTest):
    op_type = "mul"

    def setup(self):
        x = rng.rand(4, 5).astype("float32")
        y = rng.rand(5, 3).astype("float32")
        self.inputs = {"X": x, "Y": y}
        self.outputs = {"Out": x @ y}

    def test_output(self):
        self.setup()
        self.check_output()

    def test_grad(self):
        self.setup()
        self.check_grad(["in_X", "in_Y"], "Out")


class TestMulFlatten(OpTest):
    op_type = "mul"

    def test_output(self):
        x = rng.rand(2, 3, 4).astype("float32")
        y = rng.rand(12, 5).astype("float32")
        self.inputs = {"X": x, "Y": y}
        self.attrs = {"x_num_col_dims": 1}
        self.outputs = {"Out": x.reshape(2, 12) @ y}
        self.check_output()


class TestMatmulTranspose(OpTest):
    op_type = "matmul"

    def test_output(self):
        x = rng.rand(3, 4).astype("float32")
        y = rng.rand(5, 4).astype("float32")
        self.inputs = {"X": x, "Y": y}
        self.attrs = {"transpose_Y": True}
        self.outputs = {"Out": x @ y.T}
        self.check_output()

    def test_batched(self):
        x = rng.rand(2, 3, 4).astype("float32")
        y = rng.rand(2, 4, 5).astype("float32")
        self.inputs = {"X": x, "Y": y}
        self.attrs = {}
        self.outputs = {"Out": np.matmul(x, y)}
        self.check_output()


class TestElementwiseAddBroadcastAxis(OpTest):
    op_type = "elementwise_add"

    def test_mid_axis_broadcast(self):
        x = rng.rand(2, 3, 4).astype("float32")
        y = rng.rand(3).astype("float32")
        self.inputs = {"X": x, "Y": y}
        self.attrs = {"axis": 1}
        self.outputs = {"Out": x + y[None, :, None]}
        self.check_output()

    def test_grad(self):
        x = rng.rand(2, 3).astype("float32")
        y = rng.rand(3).astype("float32")
        self.inputs = {"X": x, "Y": y}
        self.attrs = {"axis": -1}
        self.outputs = {"Out": x + y}
        self.check_grad(["in_X", "in_Y"], "Out")


class TestElementwiseDivGrad(OpTest):
    op_type = "elementwise_div"

    def test_grad(self):
        x = rng.rand(3, 4).astype("float32") + 0.5
        y = rng.rand(3, 4).astype("float32") + 0.5
        self.inputs = {"X": x, "Y": y}
        self.outputs = {"Out": x / y}
        self.check_grad(["in_X", "in_Y"], "Out", max_relative_error=1e-2)


class TestSoftmax(OpTest):
    op_type = "softmax"

    def test_output_and_grad(self):
        x = rng.rand(5, 7).astype("float32")
        e = np.exp(x - x.max(-1, keepdims=True))
        self.inputs = {"X": x}
        self.outputs = {"Out": e / e.sum(-1, keepdims=True)}
        self.check_output()
        self.check_grad(["in_X"], "Out", max_relative_error=2e-2)


class TestSoftmaxWithCrossEntropy(OpTest):
    op_type = "softmax_with_cross_entropy"

    def test_output(self):
        logits = rng.rand(6, 10).astype("float32") * 4
        labels = rng.randint(0, 10, (6, 1)).astype("int64")
        e = np.exp(logits - logits.max(-1, keepdims=True))
        sm = e / e.sum(-1, keepdims=True)
        loss = -np.log(sm[np.arange(6), labels[:, 0]] + 1e-20)[:, None]
        self.inputs = {"Logits": logits, "Label": labels}
        self.outputs = {"Softmax": sm, "Loss": loss}
        self.check_output(atol=1e-5)


class TestReduce(OpTest):
    op_type = "reduce_sum"

    def test_dim(self):
        x = rng.rand(3, 4, 5).astype("float32")
        self.inputs = {"X": x}
        self.attrs = {"dim": [1]}
        self.outputs = {"Out": x.sum(1)}
        self.check_output()

    def test_keepdim_grad(self):
        x = rng.rand(3, 4).astype("float32")
        self.inputs = {"X": x}
        self.attrs = {"dim": [0], "keep_dim": True}
        self.outputs = {"Out": x.sum(0, keepdims=True)}
        self.check_grad(["in_X"], "Out")

    def test_reduce_all(self):
        x = rng.rand(3, 4).astype("float32")
        self.inputs = {"X": x}
        self.attrs = {"reduce_all": True}
        self.outputs = {"Out": np.asarray([x.sum()])}
        self.check_output()


class TestMean(OpTest):
    op_type = "mean"

    def test_output_and_grad(self):
        x = rng.rand(4, 6).astype("float32")
        self.inputs = {"X": x}
        self.outputs = {"Out": np.asarray([x.mean()])}
        self.check_output()
        self.check_grad(["in_X"], "Out")


class TestSum(OpTest):
    op_type = "sum"

    def test_multi_input(self):
        a = rng.rand(3, 4).astype("float32")
        b = rng.rand(3, 4).astype("float32")
        c = rng.rand(3, 4).astype("float32")
        self.inputs = {"X": [("a", a), ("b", b), ("c", c)]}
        self.outputs = {"Out": a + b + c}
        self.check_output()
        self.check_grad(["a", "b"], "Out")


class TestCumsum(OpTest):
    op_type = "cumsum"

    def test_exclusive_reverse(self):
        x = np.array([[1.0, 2.0, 3.0]], dtype="float32")
        self.inputs = {"X": x}
        self.attrs = {"axis": 1, "exclusive": True, "reverse": True}
        self.outputs = {"Out": np.array([[5.0, 3.0, 0.0]], dtype="float32")}
        self.check_output()


class TestConcatSplit(OpTest):
    op_type = "concat"

    def test_concat(self):
        a = rng.rand(2, 3).astype("float32")
        b = rng.rand(2, 5).astype("float32")
        self.inputs = {"X": [("ca", a), ("cb", b)]}
        self.attrs = {"axis": 1}
        self.outputs = {"Out": np.concatenate([a, b], axis=1)}
        self.check_output()
        self.check_grad(["ca", "cb"], "Out")


class TestTopK(OpTest):
    op_type = "top_k"

    def test_output(self):
        x = np.array([[3.0, 1.0, 2.0], [0.0, 5.0, 4.0]], dtype="float32")
        self.inputs = {"X": x}
        self.attrs = {"k": 2}
        self.outputs = {
            "Out": np.array([[3.0, 2.0], [5.0, 4.0]], dtype="float32"),
            "Indices": np.array([[0, 2], [1, 2]], dtype="int32"),
        }
        self.check_output()


class TestActivations:
    def _check(self, op_type, ref, x=None, grad=True, **attrs):
        class T(OpTest):
            pass

        T.op_type = op_type
        t = T()
        xv = x if x is not None else (rng.rand(3, 4).astype("float32") + 0.1)
        t.inputs = {"X": xv}
        t.attrs = attrs
        t.outputs = {"Out": ref(xv)}
        t.check_output(atol=1e-5, rtol=1e-4)
        if grad:
            t.check_grad(["in_X"], "Out", max_relative_error=1e-2)

    def test_relu(self):
        x = rng.randn(3, 4).astype("float32")
        x[np.abs(x) < 0.1] = 0.5  # keep away from kink for numeric grad
        self._check("relu", lambda v: np.maximum(v, 0), x=x)

    def test_sigmoid(self):
        self._check("sigmoid", lambda v: 1 / (1 + np.exp(-v)))

    def test_tanh(self):
        self._check("tanh", np.tanh)

    def test_exp(self):
        self._check("exp", np.exp)

    def test_sqrt(self):
        self._check("sqrt", np.sqrt)

    def test_square(self):
        self._check("square", np.square)

    def test_gelu(self):
        from scipy.stats import norm  # available via scipy in image

        x = rng.randn(3, 4).astype("float32")
        self._check(
            "gelu", lambda v: v * norm.cdf(v), x=x, grad=False,
        )

    def test_leaky_relu(self):
        x = rng.randn(3, 4).astype("float32")
        x[np.abs(x) < 0.1] = 0.5
        self._check(
            "leaky_relu", lambda v: np.where(v >= 0, v, 0.1 * v), x=x,
            alpha=0.1,
        )


class TestCast(OpTest):
    op_type = "cast"

    def test_output(self):
        x = rng.rand(3, 4).astype("float32") * 10
        self.inputs = {"X": x}
        self.attrs = {"out_dtype": "int32"}
        self.outputs = {"Out": x.astype("int32")}
        self.check_output()


class TestScale(OpTest):
    op_type = "scale"

    def test_bias_order(self):
        x = rng.rand(3).astype("float32")
        self.inputs = {"X": x}
        self.attrs = {"scale": 2.0, "bias": 1.0, "bias_after_scale": False}
        self.outputs = {"Out": (x + 1.0) * 2.0}
        self.check_output()


class TestLookupTable(OpTest):
    op_type = "lookup_table"

    def test_output_and_grad(self):
        w = rng.rand(10, 4).astype("float32")
        ids = np.array([[1], [3], [1], [9]], dtype="int64")
        self.inputs = {"W": w, "Ids": ids}
        self.outputs = {"Out": w[ids[:, 0]]}
        self.check_output()
        self.check_grad(["in_W"], "Out")

    def test_padding_idx(self):
        w = rng.rand(10, 4).astype("float32")
        ids = np.array([[1], [3]], dtype="int64")
        expect = w[ids[:, 0]].copy()
        expect[1] = 0.0
        self.inputs = {"W": w, "Ids": ids}
        self.attrs = {"padding_idx": 3}
        self.outputs = {"Out": expect}
        self.check_output()


class TestGather(OpTest):
    op_type = "gather"

    def test_output(self):
        x = rng.rand(5, 3).astype("float32")
        idx = np.array([0, 2, 4], dtype="int32")
        self.inputs = {"X": x, "Index": idx}
        self.outputs = {"Out": x[idx]}
        self.check_output()

    def test_grad(self):
        # scatter-add transpose incl. a REPEATED index (rows 2x2): the
        # MLM masked-gather head relies on this vjp
        x = rng.rand(6, 4).astype("float32")
        idx = np.array([1, 3, 3, 0], dtype="int32")
        self.inputs = {"X": x, "Index": idx}
        self.outputs = {"Out": x[idx]}
        self.check_grad(["in_X"], "Out")


class TestOneHot(OpTest):
    op_type = "one_hot"

    def test_output(self):
        ids = np.array([[0], [2], [1]], dtype="int64")
        expect = np.zeros((3, 3), "float32")
        expect[np.arange(3), ids[:, 0]] = 1.0
        self.inputs = {"X": ids}
        self.attrs = {"depth": 3}
        self.outputs = {"Out": expect}
        self.check_output()


class TestLayerNorm(OpTest):
    op_type = "layer_norm"

    def test_output(self):
        x = rng.rand(4, 6).astype("float32")
        scale = rng.rand(6).astype("float32")
        bias = rng.rand(6).astype("float32")
        mu = x.mean(-1, keepdims=True)
        var = x.var(-1, keepdims=True)
        y = (x - mu) / np.sqrt(var + 1e-5) * scale + bias
        self.inputs = {"X": x, "Scale": scale, "Bias": bias}
        self.attrs = {"begin_norm_axis": 1, "epsilon": 1e-5}
        self.outputs = {"Y": y}
        self.check_output(atol=1e-4, rtol=1e-4)

    def test_grad(self):
        x = rng.rand(3, 4).astype("float32")
        scale = np.ones(4, "float32")
        bias = np.zeros(4, "float32")
        self.inputs = {"X": x, "Scale": scale, "Bias": bias}
        self.attrs = {"begin_norm_axis": 1}
        self.outputs = {"Y": x}  # unused by check_grad
        self.check_grad(["in_X", "in_Scale"], "Y",
                        max_relative_error=2e-2)


class TestClip(OpTest):
    op_type = "clip"

    def test_output(self):
        x = rng.randn(4, 4).astype("float32")
        self.inputs = {"X": x}
        self.attrs = {"min": -0.5, "max": 0.5}
        self.outputs = {"Out": np.clip(x, -0.5, 0.5)}
        self.check_output()


class TestTranspose(OpTest):
    op_type = "transpose2"

    def test_output(self):
        x = rng.rand(2, 3, 4).astype("float32")
        self.inputs = {"X": x}
        self.attrs = {"axis": [0, 2, 1]}
        self.outputs = {"Out": x.transpose(0, 2, 1)}
        main, startup, feed, _, out_names = self._build_program()
        import paddle_tpu as fluid
        from paddle_tpu.executor import Scope, scope_guard

        exe = fluid.Executor(fluid.CPUPlace())
        with scope_guard(Scope()):
            out = exe.run(main, feed=feed,
                          fetch_list=[out_names["Out"][0]])[0]
        np.testing.assert_allclose(out, x.transpose(0, 2, 1))


class TestReshape(OpTest):
    op_type = "reshape2"

    def test_zero_and_minus_one(self):
        x = rng.rand(2, 3, 4).astype("float32")
        self.inputs = {"X": x}
        self.attrs = {"shape": [0, -1]}
        self.outputs = {"Out": x.reshape(2, 12)}
        main, startup, feed, _, out_names = self._build_program()
        import paddle_tpu as fluid
        from paddle_tpu.executor import Scope, scope_guard

        exe = fluid.Executor(fluid.CPUPlace())
        with scope_guard(Scope()):
            out = exe.run(main, feed=feed,
                          fetch_list=[out_names["Out"][0]])[0]
        np.testing.assert_allclose(out, x.reshape(2, 12))
