"""Shared model for the cluster-parity test (the ``dist_mnist.py`` role
from the reference's test_dist_base harness): a deterministic MLP whose
initial weights are fixed numpy constants, so the 2-process cluster and
the single-process oracle start bit-identical."""

import numpy as np

import paddle_tpu as fluid

GLOBAL_BATCH = 16
STEPS = 5


def _init(name, shape, seed):
    w = np.random.RandomState(seed).uniform(
        -0.1, 0.1, size=shape).astype("float32")
    return fluid.ParamAttr(
        name=name,
        initializer=fluid.initializer.NumpyArrayInitializer(w))


def build_model(optimizer_factory=None):
    """Returns (main, startup, loss, feed_names)."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[8], dtype="float32")
        y = fluid.layers.data("y", shape=[1], dtype="float32")
        h = fluid.layers.fc(x, size=16, act="relu",
                            param_attr=_init("mlp.w0", [8, 16], 1),
                            bias_attr=_init("mlp.b0", [16], 2))
        pred = fluid.layers.fc(h, size=1,
                               param_attr=_init("mlp.w1", [16, 1], 3),
                               bias_attr=_init("mlp.b1", [1], 4))
        loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
        opt = fluid.optimizer.SGD(learning_rate=0.1)
        if optimizer_factory is not None:
            opt = optimizer_factory(opt)
        opt.minimize(loss)
    return main, startup, loss, ["x", "y"]


def make_batches():
    rng = np.random.RandomState(42)
    for _ in range(STEPS):
        xb = rng.randn(GLOBAL_BATCH, 8).astype("float32")
        yb = (xb.sum(axis=1, keepdims=True) * 0.3
              + rng.randn(GLOBAL_BATCH, 1) * 0.01).astype("float32")
        yield xb, yb
