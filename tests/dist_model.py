"""Shared model for the cluster-parity test (the ``dist_mnist.py`` role
from the reference's test_dist_base harness): a deterministic MLP whose
initial weights are fixed numpy constants, so the 2-process cluster and
the single-process oracle start bit-identical."""

import numpy as np

import paddle_tpu as fluid

GLOBAL_BATCH = 16
STEPS = 5


def _init(name, shape, seed):
    w = np.random.RandomState(seed).uniform(
        -0.1, 0.1, size=shape).astype("float32")
    return fluid.ParamAttr(
        name=name,
        initializer=fluid.initializer.NumpyArrayInitializer(w))


def build_model(optimizer_factory=None):
    """Returns (main, startup, loss, feed_names)."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[8], dtype="float32")
        y = fluid.layers.data("y", shape=[1], dtype="float32")
        h = fluid.layers.fc(x, size=16, act="relu",
                            param_attr=_init("mlp.w0", [8, 16], 1),
                            bias_attr=_init("mlp.b0", [16], 2))
        pred = fluid.layers.fc(h, size=1,
                               param_attr=_init("mlp.w1", [16, 1], 3),
                               bias_attr=_init("mlp.b1", [1], 4))
        loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
        opt = fluid.optimizer.SGD(learning_rate=0.1)
        if optimizer_factory is not None:
            opt = optimizer_factory(opt)
        opt.minimize(loss)
    return main, startup, loss, ["x", "y"]


def make_batches():
    rng = np.random.RandomState(42)
    for _ in range(STEPS):
        xb = rng.randn(GLOBAL_BATCH, 8).astype("float32")
        yb = (xb.sum(axis=1, keepdims=True) * 0.3
              + rng.randn(GLOBAL_BATCH, 1) * 0.01).astype("float32")
        yield xb, yb


# ---------------------------------------------------------------------------
# transpiled multi-worker program sets for the static analyzer tests
# (pipeline, DP, MoE) — each builder returns the N per-worker main
# programs plus whatever the analyzer needs to anchor assertions
# ---------------------------------------------------------------------------

def build_pipeline_model():
    """Same MLP, but returning the hidden (cut) var too:
    (main, startup, loss, cut_var_name)."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[8], dtype="float32")
        y = fluid.layers.data("y", shape=[1], dtype="float32")
        h = fluid.layers.fc(x, size=16, act="relu",
                            param_attr=_init("mlp.w0", [8, 16], 1),
                            bias_attr=_init("mlp.b0", [16], 2))
        pred = fluid.layers.fc(h, size=1,
                               param_attr=_init("mlp.w1", [16, 1], 3),
                               bias_attr=_init("mlp.b1", [1], 4))
        loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    return main, startup, loss, h.name


def build_pipeline_workers():
    """2-stage pipeline split of the training MLP: per-stage worker
    programs with send_v2/recv_v2 boundaries (forward activation down,
    activation grad back).  Returns (workers, startups, loss_name)."""
    from paddle_tpu.parallel.pipeline import transpile_pipeline

    fluid.unique_name.switch()
    main, startup, loss, cut = build_pipeline_model()
    workers, startups = transpile_pipeline(main, [cut],
                                           startup_program=startup)
    return workers, startups, loss.name


def build_dp_workers(nranks=2):
    """N-rank GradAllReduce transpile: each rank builds the identical
    model and transpiles for its own rank — the schedules must agree.
    Returns (workers, startups, loss_name)."""
    from paddle_tpu.transpiler.collective import GradAllReduce

    workers, startups = [], []
    loss_name = None
    for rank in range(nranks):
        fluid.unique_name.switch()
        main, startup, loss, _ = build_model()
        GradAllReduce().transpile(program=main, startup_program=startup,
                                  rank=rank, nranks=nranks)
        workers.append(main)
        startups.append(startup)
        loss_name = loss.name
    return workers, startups, loss_name


def build_example_program(which):
    """The planner-acceptance example programs (ISSUE 7): bert_base's
    CI stand-in (BERT_TINY — same op structure, CPU-friendly), the
    resnet trainer and the deepfm CTR trainer, each as
    ``(main, startup, loss_name)``."""
    fluid.unique_name.switch()
    if which == "bert":
        from paddle_tpu.models import bert

        main, startup, _feeds, loss = bert.build_pretrain(
            bert.BERT_TINY, seq_len=32, train=True)
        return main, startup, loss.name
    if which == "resnet":
        from paddle_tpu.models import resnet

        main, startup, _feeds, loss, _acc = resnet.build(
            dataset="cifar10", depth=8)
        return main, startup, loss.name
    if which == "deepfm":
        from paddle_tpu.models import ctr

        main, startup, _feeds, loss, _prob = ctr.build(
            model="deepfm", num_slots=4, slot_len=3, vocab=1000)
        return main, startup, loss.name
    raise ValueError(which)


def build_example_dp_workers(which, nranks=8):
    """Hand-written DP baseline for an example program — the exact
    GradAllReduce journey a user would write, priced by the planner
    tests against ``auto_transpile``'s chosen plan.  Emits rank 0's
    program only (every rank is identical): returns
    ``(worker0, startup0, loss_name)``."""
    main, startup, loss_name = build_example_program(which)
    from paddle_tpu.transpiler.collective import GradAllReduce

    GradAllReduce().transpile(program=main, startup_program=startup,
                              rank=0, nranks=nranks)
    main._num_trainers = nranks
    main._trainer_id = 0
    return main, startup, loss_name


def build_moe_workers(nranks=2):
    """Expert-parallel MLP: hidden acts go through the MoE dispatch
    all_to_all, an expert fc, and the combine all_to_all (ring 2).
    Every rank builds the same program.  Returns
    (workers, startups, out_name)."""
    from paddle_tpu.parallel.moe import moe_combine, moe_dispatch

    workers, startups = [], []
    out_name = None
    for _rank in range(nranks):
        fluid.unique_name.switch()
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.layers.data("x", shape=[8], dtype="float32")
            h = fluid.layers.fc(x, size=16, act="relu",
                                param_attr=_init("moe.w0", [8, 16], 1))
            d = moe_dispatch(h)
            e = fluid.layers.fc(d, size=16, act="relu",
                                param_attr=_init("moe.we", [16, 16], 5))
            c = moe_combine(e)
            out = fluid.layers.fc(c, size=4,
                                  param_attr=_init("moe.w1", [16, 4], 3))
        workers.append(main)
        startups.append(startup)
        out_name = out.name
    return workers, startups, out_name
