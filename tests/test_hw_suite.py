"""Simulated-tunnel-window tests for the hardware capture suite
(``tools/hw_suite.py``).

The axon tunnel gives ~25-minute windows (round 4); these tests prove —
without a TPU — that a window where the backend dies mid-suite still
yields multiple metric artifacts, that the runner resumes at the first
unmeasured item, and that transient tunnel errors are retried in-window
instead of zeroing the step.  (Verdict r4, next-round item #3.)
"""

import json
import os
import sys

import pytest

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tools"))

import hw_suite  # noqa: E402

PY = sys.executable


def _metric_step(name, value, cap=30):
    code = "import json; print(json.dumps({'metric': %r, 'value': %d}))" % (
        name, value)
    return (name, [PY, "-c", code], cap, None)


def _hang_step(name, cap=2):
    """Simulates the backend dying mid-suite: the child blocks forever
    and must be group-killed at its cap."""
    return (name, [PY, "-c", "import time; time.sleep(600)"], cap, None)


def _artifact(tmp, name):
    with open(os.path.join(tmp, name + ".txt")) as f:
        return f.read()


def test_short_window_yields_metrics_despite_midsuite_death(tmp_path):
    """Backend dies at item 3 of 5 (hang → cap kill, probe says down):
    the window still yields >=3 completed metric artifacts — the verdict
    bar for a 10-minute window."""
    out = str(tmp_path)
    steps = [
        _metric_step("bench_a", 1),
        _metric_step("bench_b", 2),
        _metric_step("bench_c", 3),
        _hang_step("bench_dead"),
        _metric_step("bench_e", 5),
    ]
    # probe flips to down once the hang step has burned its cap,
    # mimicking the tunnel dropping mid-suite
    state = {"up": True}

    def probe():
        return state["up"], ""

    def runner(argv, cap, extra):
        rc, out_text = hw_suite.bounded(argv, cap, extra)
        if "time.sleep" in " ".join(argv):
            state["up"] = False
        return rc, out_text

    all_done, ran = hw_suite.run_window(
        steps, out_dir=out, probe=probe, runner=runner, note=lambda m: None)
    assert not all_done
    metrics = []
    for name in ("bench_a", "bench_b", "bench_c"):
        assert hw_suite.is_done(name, out)
        body = _artifact(out, name).splitlines()[1]
        metrics.append(json.loads(body))
    assert len(metrics) >= 3
    # the hang was killed at its cap, not waited out
    assert not hw_suite.is_done("bench_dead", out)
    assert "killed after" in _artifact(out, "bench_dead")
    # the window ended at the dead probe: bench_e never ran
    assert not os.path.exists(os.path.join(out, "bench_e.txt"))


def test_resume_skips_done_items(tmp_path):
    """Second window re-runs ONLY the unfinished tail — completed
    artifacts are never re-burned (resume-at-first-unmeasured-item)."""
    out = str(tmp_path)
    steps = [
        _metric_step("bench_a", 1),
        _hang_step("bench_dead"),
        _metric_step("bench_c", 3),
    ]
    attempts = {}
    hw_suite.run_window(steps, out_dir=out, runner=hw_suite.bounded,
                        note=lambda m: None, attempts=attempts)
    first_mtime = os.path.getmtime(os.path.join(out, "bench_a.txt"))

    ran_names = []

    def counting_runner(argv, cap, extra):
        ran_names.append(argv)
        return hw_suite.bounded(argv, cap, extra)

    # "tunnel back up": second window
    all_done, ran = hw_suite.run_window(
        steps, out_dir=out, runner=counting_runner, note=lambda m: None,
        attempts=attempts)
    assert os.path.getmtime(os.path.join(out, "bench_a.txt")) == first_mtime
    assert all("bench_a" not in " ".join(a) for a in ran_names)
    # bench_c completed in one of the windows
    assert hw_suite.is_done("bench_c", out)


def test_transient_failure_retried_in_window(tmp_path):
    """A step that aborts with a transient tunnel signature is re-run
    immediately (probe still up) and succeeds — one mid-window
    remote_compile abort must not zero the line."""
    out = str(tmp_path)
    flag = os.path.join(out, "flaked")
    code = (
        "import json, os, sys\n"
        "if not os.path.exists(%r):\n"
        "    open(%r, 'w').close()\n"
        "    sys.stderr.write('aborted: response body closed before all "
        "bytes were read\\n')\n"
        "    sys.exit(1)\n"
        "print(json.dumps({'metric': 'flaky', 'value': 7}))\n" % (flag, flag)
    )
    steps = [("bench_flaky", [PY, "-c", code], 30, None)]
    all_done, ran = hw_suite.run_window(
        steps, out_dir=out, probe=lambda: (True, ""),
        note=lambda m: None)
    assert all_done
    assert hw_suite.is_done("bench_flaky", out)


def test_deterministic_failure_not_retried_in_window(tmp_path):
    """A hard (non-transient) failure must not eat the window in
    back-to-back reruns."""
    out = str(tmp_path)
    runs = []

    def runner(argv, cap, extra):
        runs.append(1)
        return 1, "TypeError: deterministic bug"

    steps = [("bench_bug", [PY, "-c", "pass"], 30, None)]
    all_done, ran = hw_suite.run_window(
        steps, out_dir=out, probe=lambda: (True, ""), runner=runner,
        note=lambda m: None)
    assert len(runs) == 1
    assert not all_done


def test_lifetime_attempt_cap(tmp_path):
    """Across windows, a transiently-failing step stops after
    MAX_ATTEMPTS total tries."""
    out = str(tmp_path)
    runs = []

    def runner(argv, cap, extra):
        runs.append(1)
        return 1, "UNAVAILABLE: tunnel burp"

    steps = [("bench_sad", [PY, "-c", "pass"], 30, None)]
    attempts = {}
    for _ in range(4):  # four windows
        hw_suite.run_window(steps, out_dir=out, probe=lambda: (True, ""),
                            runner=runner, note=lambda m: None,
                            attempts=attempts)
    assert len(runs) == hw_suite.MAX_ATTEMPTS


def test_compile_phase_steps_exist():
    """Every checkpointed bench item exposes a .compile phase before its
    measure phase, and the flagship comes first after PRNG validation
    (verdict #1/#2 ordering)."""
    steps = hw_suite.build_steps()
    names = [s[0] for s in steps]
    assert names[0] == "bench_bert_default.compile"
    assert names[1] == "bench_bert_default"
    assert names[2] == "bench_resnet.compile"
    assert names[3] == "bench_resnet"
    assert names[4] == "validate_flash_prng"
    for compile_name in [n for n in names if n.endswith(".compile")]:
        base = compile_name[:-len(".compile")]
        assert base in names
        # the compile phase sets the env knob the measure phase relies on
        idx = names.index(compile_name)
        env = steps[idx][3]
        assert env["PADDLE_BENCH_COMPILE_ONLY"] == "1"


def test_bench_compile_only_smoke(tmp_path):
    """End-to-end: a real bench child under PADDLE_BENCH_COMPILE_ONLY=1
    runs exactly one step and prints the compiled marker (CPU backend)."""
    rc, out = hw_suite.bounded(
        [PY, "bench.py", "--child", "ctr"], 240,
        {"PADDLE_BENCH_COMPILE_ONLY": "1", "PADDLE_BENCH_FORCE_CPU": "1"})
    assert rc == 0, out[-800:]
    assert any(
        json.loads(ln).get("compiled")
        for ln in out.splitlines() if ln.strip().startswith("{")), out[-800:]
