"""bench.py's TPU-down fallback: surface the best clean in-round
watcher capture per metric (the driver-visible flagship for rounds
where the tunnel is dead at bench time — the r02-r04 failure mode)."""

import importlib.util
import json
import os
import time

import pytest


@pytest.fixture(scope="module")
def bench():
    spec = importlib.util.spec_from_file_location(
        "bench_mod", os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "bench.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _write(d, name, rc, ts, lines):
    with open(os.path.join(d, name + ".txt"), "w") as f:
        f.write("[watcher] rc=%s ts=%d\n" % (rc, ts))
        for l in lines:
            f.write(json.dumps(l) + "\n")


def test_best_arm_wins_and_failures_excluded(bench, tmp_path):
    d = str(tmp_path)
    now = int(time.time())
    m = "bert_base_mlm_train_tokens_per_sec_per_chip"
    _write(d, "bench_bert_default", 0, now - 60,
           [{"metric": m, "value": 100.0, "unit": "u", "vs_baseline": 0.5}])
    _write(d, "bench_bert_ipr25", 0, now - 30,
           [{"metric": m, "value": 120.0, "unit": "u ipr25",
             "vs_baseline": 0.6}])
    _write(d, "bench_bert_broken", 1, now - 10,
           [{"metric": m, "value": 999.0, "unit": "u", "vs_baseline": 9.9}])
    out = bench._captured_hw_lines(results_dir=d)
    assert len(out) == 1
    l = out[0]
    assert l["value"] == 120.0 and l["captured_earlier"] is True
    assert "CAPTURED EARLIER" in l["unit"]
    assert l["captured_artifact"] == "bench_bert_ipr25.txt"


def test_in_artifact_ts_beats_checkout_mtime(bench, tmp_path):
    """git checkout resets mtime; freshness must come from the ts=
    header, so a previous round's committed artifact can never replay."""
    d = str(tmp_path)
    m = "resnet50_imagenet_train_images_per_sec_per_chip"
    _write(d, "bench_resnet", 0, int(time.time()) - 3 * 24 * 3600,
           [{"metric": m, "value": 1000.0, "unit": "u",
             "vs_baseline": 0.4}])
    # fresh mtime (as a clone would produce)
    os.utime(os.path.join(d, "bench_resnet.txt"))
    assert bench._captured_hw_lines(results_dir=d) == []


def test_smoke_metrics_excluded_and_ties_prefer_newer(bench, tmp_path):
    d = str(tmp_path)
    now = int(time.time())
    m = "resnet50_imagenet_train_images_per_sec_per_chip"
    _write(d, "a_old", 0, now - 100,
           [{"metric": m, "value": 50.0, "unit": "old", "vs_baseline": 0.2},
            {"metric": "resnet_cifar_smoke_images_per_sec", "value": 5.0,
             "unit": "smoke", "vs_baseline": 1.0}])
    _write(d, "b_new", 0, now - 10,
           [{"metric": m, "value": 50.0, "unit": "new corrected",
             "vs_baseline": 0.2}])
    # mtime order must match write order for the tie-break
    os.utime(os.path.join(d, "a_old.txt"), (now - 100, now - 100))
    os.utime(os.path.join(d, "b_new.txt"), (now - 10, now - 10))
    out = bench._captured_hw_lines(results_dir=d)
    assert len(out) == 1
    assert out[0]["captured_artifact"] == "b_new.txt"


def test_xla_cost_analysis_counts_scan_body_once():
    """The MFU cross-check (bench.py _xla_flops_per_step) treats XLA's
    cost-analysis flops as per-step even under the
    num_iteration_per_run scan wrapper, because XLA counts a
    while/scan body ONCE regardless of trip count.  This pins that
    backend behavior: if a jax upgrade starts multiplying by the trip
    count, the cross-check must go back to dividing (the r05 ipr25
    hardware capture read 25x low under an erroneous /iters)."""
    import jax
    import jax.numpy as jnp

    def step(x):
        return x @ x

    @jax.jit
    def one(x):
        return step(x)

    @jax.jit
    def scan4(x):
        c, _ = jax.lax.scan(lambda c, _: (step(c), None), x, None,
                            length=4)
        return c

    x = jnp.ones((64, 64), jnp.float32)

    def flops(f):
        ca = f.lower(x).compile().cost_analysis()
        if isinstance(ca, list):
            ca = ca[0]
        return float(ca.get("flops", 0.0))

    f1, f4 = flops(one), flops(scan4)
    assert f1 > 0
    assert abs(f4 - f1) / f1 < 0.05, (f1, f4)


def test_dedupe_metrics_one_record_per_metric_last_wins(bench):
    """Satellite (ISSUE 6): the train children print each *_per_chip
    metric twice (measured line first, MFU-enriched re-print after the
    AOT cross-check) — the orchestrator must emit ONE record per metric,
    the LAST (enriched) one, at the first occurrence's position, with
    non-metric lines passing through."""
    plain = {"metric": "resnet50_imagenet_train_images_per_sec_per_chip",
             "value": 2000.0, "unit": "images/sec/chip"}
    enriched = dict(plain, mfu_analytic=0.25, mfu_xla=0.38)
    other = {"metric": "other_metric", "value": 1}
    marker = {"compiled": True}
    out = bench._dedupe_metrics([plain, marker, other, enriched])
    assert out == [enriched, marker, other]
    # a clean single emission is untouched
    assert bench._dedupe_metrics([plain, other]) == [plain, other]
    # duplicate-free input of N metrics stays N records
    assert len([l for l in out if l.get("metric")]) == 2
