"""ISSUE 10: whole-program concurrency analysis — golden race
detections with exact coordinates, the scope-isolation proof, the
zero-sync certificate, the ``run_batches(verify=True)`` gate, the
rewrite brackets, diagnostic determinism, the strict-sync promotion,
the two latent-hazard fixes (thread-local scope stack, fetch-handle
detach), telemetry, and the prog_gen property/cross-check suite.
"""

import json
import os
import subprocess
import sys
import threading

import numpy as np
import pytest

import paddle_tpu as fluid
import prog_gen
from paddle_tpu.executor import Executor, Scope, global_scope, scope_guard
from paddle_tpu.framework import Operator
from paddle_tpu.static_analysis import (
    RACE_CHECK_IDS,
    Severity,
    VerifyError,
    analyze_concurrency,
    assert_no_new_races,
    certify_zero_sync,
    find_inflight_races,
    prove_scope_isolation,
    race_signatures,
    resolve_max_in_flight,
    scope_footprint,
    strict_sync_enabled,
    verify_async_hot_path,
    verify_program,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _fresh_names():
    fluid.unique_name.switch()


def _errors(diags):
    return [d for d in diags if d.severity >= Severity.ERROR]


# ---------------------------------------------------------------------------
# golden race detections (exact coordinates)
# ---------------------------------------------------------------------------

class TestInflightRaces:
    def test_feed_overwrite_flagged_at_depth_2_with_exact_coords(self):
        main, _, out, (bidx, oidx) = prog_gen.gen_feed_overwrite_program()
        diags = find_inflight_races(main, targets=[out],
                                    max_in_flight=2)
        hits = [d for d in diags if d.check == "race-inflight-write"
                and "x" in d.var_names]
        assert hits, diags
        d = hits[0]
        assert (d.block_idx, d.op_idx) == (bidx, oidx)
        assert d.op_type == "scale"
        assert d.severity == Severity.ERROR
        assert "double-buffer" in d.message

    def test_param_fetch_is_donated_buffer_live_read(self):
        main, _, loss, pname = prog_gen.gen_param_fetch_program()
        diags = find_inflight_races(main, targets=[loss, pname],
                                    max_in_flight=2)
        hits = [d for d in diags
                if d.check == "donated-buffer-live-read"]
        assert hits, diags
        d = hits[0]
        assert d.var_names == (pname,)
        assert d.op_type == "sgd"
        # the coords name the exact updating op
        op = main.block(d.block_idx).ops[d.op_idx]
        assert op.type == "sgd"
        assert pname in op.input_arg_names
        assert pname in op.output_arg_names

    def test_sequential_execution_has_no_races(self):
        main, _, out, _ = prog_gen.gen_feed_overwrite_program()
        assert find_inflight_races(main, targets=[out],
                                   max_in_flight=1) == []
        main, _, loss, pname = prog_gen.gen_param_fetch_program()
        assert find_inflight_races(main, targets=[loss, pname],
                                   max_in_flight=1) == []

    def test_plain_lint_stays_unchanged(self):
        """The race checks are registered in the default battery but
        resolve K=1 without an in-flight context — seeded hazards do
        NOT fail a plain lint()."""
        main, _, loss, pname = prog_gen.gen_param_fetch_program()
        diags = main.lint(targets=[loss, pname])
        assert not [d for d in diags if d.check in RACE_CHECK_IDS]

    def test_battery_carries_races_with_in_flight_context(self):
        main, _, loss, pname = prog_gen.gen_param_fetch_program()
        diags = verify_program(main, targets=[loss, pname],
                               max_in_flight=2)
        assert [d for d in diags
                if d.check == "donated-buffer-live-read"]

    def test_race_messages_name_depth_and_api_not_coords(self):
        """Coordinates live in structured fields; messages stay
        coordinate-free so rewrite-bracket signatures survive op
        renumbering."""
        main, _, loss, pname = prog_gen.gen_param_fetch_program()
        for d in find_inflight_races(main, targets=[loss, pname],
                                     max_in_flight=3):
            assert "max_in_flight=3" in d.message
            assert "block" not in d.message

    def test_training_programs_fetching_loss_are_clean(self):
        main, _, loss, _ = prog_gen.gen_param_fetch_program()
        assert find_inflight_races(main, targets=[loss],
                                   max_in_flight=4) == []


class TestMaxInFlightResolution:
    def test_explicit_wins(self):
        p = fluid.Program()
        p._max_in_flight = 8
        assert resolve_max_in_flight(p, explicit=3) == 3

    def test_program_mark_wins_over_env(self, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_MAX_IN_FLIGHT", "5")
        p = fluid.Program()
        p._max_in_flight = 4
        assert resolve_max_in_flight(p) == 4

    def test_env_then_default(self, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_MAX_IN_FLIGHT", "6")
        assert resolve_max_in_flight(fluid.Program()) == 6
        monkeypatch.delenv("PADDLE_TPU_MAX_IN_FLIGHT")
        assert resolve_max_in_flight(fluid.Program(), default=2) == 2

    def test_floor_is_one(self):
        assert resolve_max_in_flight(None, explicit=0) == 1


# ---------------------------------------------------------------------------
# scope isolation
# ---------------------------------------------------------------------------

def _named_mlp(prefix, train=False):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(prefix + "_x", shape=[4], dtype="float32")
        attr = fluid.ParamAttr(name=prefix + ".w")
        h = fluid.layers.fc(x, size=4, param_attr=attr,
                            bias_attr=fluid.ParamAttr(name=prefix + ".b"))
        if train:
            loss = fluid.layers.mean(h)
            fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    return main


class TestScopeIsolation:
    def test_disjoint_programs_prove_isolated(self):
        a, b = _named_mlp("a"), _named_mlp("b")
        prints, diags = prove_scope_isolation([a, b])
        assert diags == []
        assert prints[0].isolated_from(prints[1])

    def test_written_overlap_is_error_naming_pair_and_vars(self):
        a = _named_mlp("m", train=True)   # writes m.w / m.b
        b = _named_mlp("m")               # reads m.w / m.b
        _, diags = prove_scope_isolation([a, b], labels=["train",
                                                         "serve"])
        errs = _errors(diags)
        assert len(errs) == 1
        d = errs[0]
        assert d.check == "scope-overlap"
        assert "train" in d.message and "serve" in d.message
        assert "m.w" in d.var_names and "m.b" in d.var_names

    def test_shared_read_only_state_warns_not_errors(self):
        a, b = _named_mlp("m"), _named_mlp("m")
        _, diags = prove_scope_isolation([a, b])
        assert not _errors(diags)
        assert [d for d in diags if d.severity == Severity.WARNING
                and d.check == "scope-overlap"]

    def test_footprint_excludes_feeds_includes_optimizer_writes(self):
        main = _named_mlp("m", train=True)
        fp = scope_footprint(main)
        assert "m.w" in fp.writes and "m.w" in fp.reads
        assert "m_x" not in fp.reads and "m_x" not in fp.writes

    def test_battery_surface_via_coresident(self):
        a = _named_mlp("m", train=True)
        b = _named_mlp("m")
        diags = verify_program(a, coresident=[("serve-copy", b)])
        hits = [d for d in diags if d.check == "scope-overlap"]
        assert hits and "serve-copy" in hits[0].message


# ---------------------------------------------------------------------------
# zero-sync certificate
# ---------------------------------------------------------------------------

class TestZeroSyncCertificate:
    def test_pure_inference_loop_passes(self):
        main, _, fetch = prog_gen.gen_program(3, train=False)
        cert = certify_zero_sync(main, targets=fetch,
                                 label="async inference loop")
        assert cert.ok
        assert "PASS" in cert.format()

    def test_injected_host_io_fails_naming_the_op(self):
        main, _, fetch = prog_gen.gen_program(3, train=False)
        b = main.global_block()
        b.ops.append(Operator(b, "save", {"X": [fetch[0]]}, {},
                              {"file_path": "/tmp/x"}))
        cert = certify_zero_sync(main, targets=fetch)
        assert not cert.ok
        v = cert.violations[0]
        assert v.op_type == "save"
        assert (v.block_idx, v.op_idx) == (0, len(b.ops) - 1)
        assert "run_host_io_block" in v.api
        assert "FAIL" in cert.format()

    def test_host_table_is_a_program_level_violation(self):
        main, _, fetch = prog_gen.gen_program(4, train=False)
        main._host_tables = ["big_embedding"]
        cert = certify_zero_sync(main, targets=fetch)
        assert not cert.ok
        assert cert.violations[0].where() == "program-level"
        assert "np.asarray" in cert.violations[0].api

    def test_nan_guard_is_allowed_not_violation(self):
        main, _, fetch = prog_gen.gen_program(5, train=True)
        main._nan_guard = True
        cert = certify_zero_sync(main, targets=fetch)
        assert cert.ok
        assert cert.allowed and cert.allowed[0].allowed
        assert "guard" in cert.allowed[0].api

    def test_cli_certify_pass_and_fail_name_the_op(self, tmp_path):
        from paddle_tpu.proto import save_program

        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.layers.data("x", shape=[4], dtype="float32")
            out = fluid.layers.fc(x, size=2)
        clean = str(tmp_path / "clean.json")
        save_program(main, clean)
        b = main.global_block()
        b.ops.append(Operator(b, "save", {"X": [out.name]}, {},
                              {"file_path": "/tmp/x"}))
        synced = str(tmp_path / "synced.json")
        save_program(main, synced)

        def cli(path):
            return subprocess.run(
                [sys.executable, "-m",
                 "paddle_tpu.tools.analyze_program",
                 "--program-json", path, "--certify-zero-sync"],
                capture_output=True, text=True,
                env=dict(os.environ, JAX_PLATFORMS="cpu"), cwd=REPO)

        res = cli(clean)
        assert res.returncode == 0
        assert "zero-sync certificate" in res.stdout
        assert "PASS" in res.stdout
        res = cli(synced)
        assert res.returncode == 1
        assert "FAIL" in res.stdout
        assert "save" in res.stdout
        assert "run_host_io_block" in res.stdout

    def test_certificate_in_analyze_report_and_json(self):
        main, _, fetch = prog_gen.gen_program(6, train=False)
        report = main.analyze(targets=fetch, certify_zero_sync=True)
        assert report.concurrency is not None
        assert report.concurrency.certificate.ok
        blob = report.to_dict()["concurrency"]["certificate"]
        assert blob["ok"] is True


# ---------------------------------------------------------------------------
# strict-sync promotion (satellite 1)
# ---------------------------------------------------------------------------

def _synced_training_program():
    """A program the PR-4 advisory fires on: training with a host-IO
    op in the block (the executor must drain the pipeline per step)."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[4], dtype="float32")
        h = fluid.layers.fc(x, size=4)
        loss = fluid.layers.mean(h)
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    b = main.global_block()
    b.ops.append(Operator(b, "save", {"X": [loss.name]}, {},
                          {"file_path": "/tmp/x"}))
    return main, loss.name


class TestStrictSyncPromotion:
    def test_default_is_info(self, monkeypatch):
        monkeypatch.delenv("PADDLE_TPU_STRICT_SYNC", raising=False)
        main, loss = _synced_training_program()
        diags = [d for d in main.lint(targets=[loss])
                 if d.check == "executor-host-sync-in-loop"]
        assert diags and diags[0].severity == Severity.INFO

    def test_env_promotes_to_error_with_coords_and_api(self, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_STRICT_SYNC", "1")
        main, loss = _synced_training_program()
        diags = [d for d in main.lint(targets=[loss])
                 if d.check == "executor-host-sync-in-loop"]
        assert diags and diags[0].severity == Severity.ERROR
        msg = diags[0].message
        assert "at block" in msg and "op" in msg
        assert "Executor.run's host-IO phase" in msg
        assert "zero-sync certificate" in msg

    def test_serving_hot_loop_mark_promotes(self, monkeypatch):
        monkeypatch.delenv("PADDLE_TPU_STRICT_SYNC", raising=False)
        main, loss = _synced_training_program()
        main._serving_hot_loop = True
        assert strict_sync_enabled(main)
        diags = [d for d in main.lint(targets=[loss])
                 if d.check == "executor-host-sync-in-loop"]
        assert diags and diags[0].severity == Severity.ERROR

    def test_env_zero_does_not_promote(self, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_STRICT_SYNC", "0")
        assert not strict_sync_enabled(fluid.Program())


# ---------------------------------------------------------------------------
# run_batches(verify=True) gate
# ---------------------------------------------------------------------------

def _save_inference_model(tmp_path, hazard=False):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[4], dtype="float32")
        out = fluid.layers.fc(x, size=2)
    exe = Executor()
    with scope_guard(Scope()):
        exe.run(startup)
        d = str(tmp_path / ("hazard" if hazard else "clean"))
        fluid.io.save_inference_model(d, ["x"], [out], exe,
                                      main_program=main)
    return d


class TestRunBatchesGate:
    def test_clean_program_verifies_and_streams(self, tmp_path):
        d = _save_inference_model(tmp_path)
        pred = fluid.inference.create_paddle_predictor(
            fluid.inference.AnalysisConfig(d))
        batches = [[np.ones((2, 4), dtype="float32") * i]
                   for i in range(3)]
        outs = list(pred.run_batches(batches, max_in_flight=2,
                                     verify=True))
        assert len(outs) == 3
        # the gate stamped the serving marks used by strict-sync and
        # depth resolution
        assert pred.program._serving_hot_loop
        assert pred.program._max_in_flight == 2

    def test_injected_sync_fails_at_call_time_naming_the_op(
            self, tmp_path):
        d = _save_inference_model(tmp_path)
        pred = fluid.inference.create_paddle_predictor(
            fluid.inference.AnalysisConfig(d))
        b = pred.program.global_block()
        out_name = pred.get_output_names()[0]
        b.ops.append(Operator(b, "save", {"X": [out_name]}, {},
                              {"file_path": "/tmp/x"}))
        with pytest.raises(VerifyError) as ei:
            # eager wrapper: raises at CALL, not at first next()
            pred.run_batches([[np.ones((2, 4), dtype="float32")]],
                             max_in_flight=2, verify=True)
        assert "sync-in-hot-loop" in str(ei.value)
        assert "save" in str(ei.value)

    def test_bad_depth_raises_at_call_time(self, tmp_path):
        d = _save_inference_model(tmp_path)
        pred = fluid.inference.create_paddle_predictor(
            fluid.inference.AnalysisConfig(d))
        with pytest.raises(ValueError):
            pred.run_batches([], max_in_flight=0)

    def test_verify_async_hot_path_flags_seeded_race(self):
        main, _, loss, pname = prog_gen.gen_param_fetch_program()
        with pytest.raises(VerifyError) as ei:
            verify_async_hot_path(main, targets=[loss, pname],
                                  max_in_flight=2)
        assert "donated-buffer-live-read" in str(ei.value)


# ---------------------------------------------------------------------------
# rewrite brackets (fusion / planner may not introduce races)
# ---------------------------------------------------------------------------

class TestRewriteBrackets:
    def test_signatures_are_coordinate_free(self):
        main, _, loss, pname = prog_gen.gen_param_fetch_program()
        sigs = race_signatures(main, targets=[loss, pname])
        assert ("donated-buffer-live-read", (pname,)) in sigs

    def test_preexisting_race_is_not_blamed_on_rewrite(self):
        main, _, loss, pname = prog_gen.gen_param_fetch_program()
        baseline = race_signatures(main, targets=[loss, pname])
        # unchanged program: nothing new
        assert_no_new_races(main, baseline, "noop rewrite",
                            targets=[loss, pname])

    def test_introduced_race_raises_naming_context(self):
        main, _, loss, pname = prog_gen.gen_param_fetch_program()
        baseline = race_signatures(main, targets=[loss])  # no hazard
        with pytest.raises(VerifyError) as ei:
            assert_no_new_races(main, baseline, "bad-pass",
                                targets=[loss, pname])
        assert "bad-pass" in str(ei.value)

    def test_fusion_resolve_keeps_seeded_program_race_stable(self):
        """The fusion pipeline's bracket diffs at K=2: resolving a
        program that already carries the hazard must not raise (it
        didn't introduce it) — and the fused twin still detects it."""
        from paddle_tpu.static_analysis import fusion

        main, _, loss, pname = prog_gen.gen_param_fetch_program()
        fused, _report = fusion.resolve_fused_program(
            main, targets=[loss, pname])
        diags = find_inflight_races(fused, targets=[loss, pname],
                                    max_in_flight=2)
        assert [d for d in diags
                if d.check == "donated-buffer-live-read"
                and pname in d.var_names]


# ---------------------------------------------------------------------------
# latent hazards fixed: thread-local scope stack, fetch-handle detach
# ---------------------------------------------------------------------------

class TestThreadLocalScopeStack:
    def test_scope_guard_is_thread_private(self):
        """Two predictor threads interleaving scope_guard push/pops must
        each resolve their OWN scope — the process-wide stack let one
        tenant's executor read another's variables."""
        a_in = threading.Event()
        release_a = threading.Event()
        results = {}

        def tenant_a():
            s = Scope()
            with scope_guard(s):
                a_in.set()
                release_a.wait(5)
                results["a"] = global_scope() is s

        def tenant_b():
            a_in.wait(5)
            s = Scope()
            with scope_guard(s):
                results["b"] = global_scope() is s
            release_a.set()

        ta = threading.Thread(target=tenant_a)
        tb = threading.Thread(target=tenant_b)
        ta.start()
        tb.start()
        ta.join(10)
        tb.join(10)
        assert results == {"a": True, "b": True}

    def test_fresh_thread_sees_process_global_scope(self):
        seen = {}

        def probe():
            seen["scope"] = global_scope()

        t = threading.Thread(target=probe)
        t.start()
        t.join(10)
        assert seen["scope"] is global_scope()


class TestFetchHandleDetach:
    def test_fetched_state_handle_does_not_alias_scope_buffer(self):
        """The donated-buffer fix at runtime: a lazy handle for a
        read-write persistable holds a detached device copy, not the
        scope array the next step's donation invalidates."""
        main, startup, loss, pname = prog_gen.gen_param_fetch_program()
        exe = Executor()
        scope = Scope()
        feed = {"x": np.ones((2, 4), dtype="float32"),
                "y": np.zeros((2, 1), dtype="float32")}
        with scope_guard(scope):
            exe.run(startup)
            outs = exe.run(main, feed=feed, fetch_list=[loss, pname],
                           return_numpy=False)
            handle = outs[1]
            assert handle.device_value is not scope.vars[pname]
            np.testing.assert_allclose(np.asarray(handle),
                                       np.asarray(scope.vars[pname]))

    def test_temporary_fetches_stay_zero_copy(self):
        """Only scope state needs the detach copy; temporaries (the
        loss) are not donated scope buffers."""
        from paddle_tpu.pipeline import FetchHandle

        main, startup, loss, _ = prog_gen.gen_param_fetch_program()
        exe = Executor()
        with scope_guard(Scope()):
            exe.run(startup)
            outs = exe.run(main,
                           feed={"x": np.ones((2, 4), dtype="float32"),
                                 "y": np.zeros((2, 1), dtype="float32")},
                           fetch_list=[loss], return_numpy=False)
            assert isinstance(outs[0], FetchHandle)
            assert np.isfinite(float(outs[0]))

    def test_detach_device_passthrough(self):
        from paddle_tpu.pipeline import detach_device

        host = np.arange(4.0)
        assert detach_device(host) is host
        assert detach_device("not-an-array") == "not-an-array"
        import jax.numpy as jnp

        dev = jnp.arange(4.0)
        out = detach_device(dev)
        assert out is not dev
        np.testing.assert_allclose(np.asarray(out), np.asarray(dev))


# ---------------------------------------------------------------------------
# diagnostic determinism + schema (satellite 2)
# ---------------------------------------------------------------------------

class TestDeterminism:
    def test_repeated_runs_are_identical(self):
        main, _, loss, pname = prog_gen.gen_param_fetch_program()
        runs = [verify_program(main, targets=[loss, pname],
                               max_in_flight=2) for _ in range(3)]
        as_tuples = [[(d.check, d.severity, d.message, d.block_idx,
                       d.op_idx) for d in run] for run in runs]
        assert as_tuples[0] == as_tuples[1] == as_tuples[2]

    def test_sorted_by_severity_then_coords(self):
        main, loss = _synced_training_program()
        main._serving_hot_loop = True  # promote INFO → ERROR + cert
        diags = verify_program(main, targets=[loss], max_in_flight=2)
        sevs = [d.severity for d in diags]
        assert sevs == sorted(sevs, reverse=True)
        errs = _errors(diags)
        coords = [(d.block_idx or -1, d.op_idx or -1) for d in errs]
        assert coords == sorted(coords)

    def test_identical_findings_dedupe(self):
        """Two check ids can surface the same (check, message, coords)
        tuple through different walks; the battery reports it once."""
        main, _, loss, pname = prog_gen.gen_param_fetch_program()
        diags = verify_program(main, targets=[loss, pname],
                               max_in_flight=2)
        keys = [(d.check, d.message, d.block_idx, d.op_idx)
                for d in diags]
        assert len(keys) == len(set(keys))

    def test_lint_cli_json_is_schema_stamped(self, tmp_path):
        from paddle_tpu.tools.diag_cli import DIAG_SCHEMA_VERSION

        d = _save_inference_model(tmp_path)
        res = subprocess.run(
            [sys.executable, "-m", "paddle_tpu.tools.lint_program",
             d, "--json"],
            capture_output=True, text=True,
            env=dict(os.environ, JAX_PLATFORMS="cpu"), cwd=REPO)
        payload = json.loads(res.stdout)
        assert payload["schema"] == DIAG_SCHEMA_VERSION
        assert isinstance(payload["diagnostics"], list)


# ---------------------------------------------------------------------------
# telemetry (satellite 6)
# ---------------------------------------------------------------------------

class TestTelemetry:
    @pytest.fixture(autouse=True)
    def _fresh(self, monkeypatch):
        import paddle_tpu.observability as obs

        monkeypatch.delenv("PADDLE_TPU_TELEMETRY", raising=False)
        monkeypatch.delenv("PADDLE_TPU_TELEMETRY_DIR", raising=False)
        obs.reset_telemetry()
        yield
        obs.reset_telemetry()

    def test_counters_and_urgent_journal_event(self, monkeypatch,
                                               tmp_path):
        import paddle_tpu.observability as obs
        from paddle_tpu.observability import journal, metrics

        monkeypatch.setenv("PADDLE_TPU_TELEMETRY_DIR", str(tmp_path))
        obs.reset_telemetry()
        main, _, loss, pname = prog_gen.gen_param_fetch_program()
        analyze_concurrency(main, targets=[loss, pname])
        reg = metrics.registry()
        assert reg.get("concurrency_checks_total").value >= 1
        assert reg.get("races_found_total").value >= 1
        events = journal.get_journal().events("race-detected")
        assert events and events[0]["gate"] == "analyze"
        # urgent kind: flushed to disk immediately, no flush() needed
        on_disk = journal.read_journal(str(tmp_path))
        assert any(e["kind"] == "race-detected" for e in on_disk)

    def test_clean_program_counts_check_but_no_race(self):
        import paddle_tpu.observability as obs
        from paddle_tpu.observability import metrics

        obs.reset_telemetry()
        main, _, fetch = prog_gen.gen_program(7, train=False)
        analyze_concurrency(main, targets=fetch)
        reg = metrics.registry()
        assert reg.get("concurrency_checks_total").value == 1
        assert reg.get("races_found_total") is None

    def test_monitor_incident_sequence_includes_race(self, monkeypatch,
                                                     tmp_path):
        import paddle_tpu.observability as obs
        from paddle_tpu.tools import monitor

        monkeypatch.setenv("PADDLE_TPU_TELEMETRY_DIR", str(tmp_path))
        obs.reset_telemetry()
        main, _, loss, pname = prog_gen.gen_param_fetch_program()
        with pytest.raises(VerifyError):
            verify_async_hot_path(main, targets=[loss, pname],
                                  max_in_flight=2)
        status = monitor.collect_status(str(tmp_path))
        kinds = [s["kind"] for s in status["sequence"]]
        assert "race-detected" in kinds

    def test_disabled_telemetry_is_inert(self, monkeypatch):
        import paddle_tpu.observability as obs
        from paddle_tpu.observability import metrics
        from paddle_tpu.observability.runtime import (
            record_concurrency_check,
        )

        monkeypatch.setenv("PADDLE_TPU_TELEMETRY", "0")
        obs.reset_telemetry()
        record_concurrency_check(3, gate="analyze", tripped=True)
        assert metrics.registry().get("concurrency_checks_total") is None


# ---------------------------------------------------------------------------
# prog_gen property suite + runtime-vs-static cross-checks (satellite 3)
# ---------------------------------------------------------------------------

class TestGeneratedPrograms:
    @pytest.mark.parametrize("seed", range(16))
    def test_analyses_never_crash_and_k1_is_race_free(self, seed):
        main, startup, fetch = prog_gen.gen_program(seed)
        main.lint(targets=fetch)
        report = main.analyze(targets=fetch)
        assert report.cost.total_flops >= 0
        report = main.analyze(targets=fetch, concurrency=True,
                              max_in_flight=1)
        assert report.concurrency.race_free
        startup.lint()

    def test_generator_is_deterministic(self):
        a_main, _, a_fetch = prog_gen.gen_program(11)
        b_main, _, b_fetch = prog_gen.gen_program(11)
        assert a_fetch == b_fetch
        assert [op.type for b in a_main.blocks for op in b.ops] == \
            [op.type for b in b_main.blocks for op in b.ops]

    def test_generated_trainers_clean_at_depth_2_when_fetching_loss(self):
        for seed in range(8):
            main, _, fetch = prog_gen.gen_program(seed, train=True)
            diags = find_inflight_races(main, targets=fetch,
                                        max_in_flight=2)
            assert diags == [], (seed, diags)


class TestRuntimeVsStatic:
    def test_static_flags_exactly_the_op_the_runtime_would_race_on(self):
        """The seeded double-buffer feed overwrite: the static analyzer
        pins the hazard to the exact op the prefetch pipeline would
        race with at depth 2."""
        main, _, out, (bidx, oidx) = prog_gen.gen_feed_overwrite_program()
        report = main.analyze(targets=[out], concurrency=True,
                              max_in_flight=2)
        races = report.concurrency.races
        assert [d for d in races
                if (d.block_idx, d.op_idx) == (bidx, oidx)
                and d.check == "race-inflight-write"]
        # and the report fails overall (races are ERRORs)
        assert not report.ok

    def test_feed_cache_reproduces_the_stale_read_dynamically(
            self, tmp_path, monkeypatch):
        """Dynamic twin of the static warning: sharing one live host
        buffer with the depth-2 feed pipeline and mutating it in place
        (same object, NON-sampled index — the fingerprint samples
        stride-2 from 0) makes batch 2 reuse batch 1's device value:
        the mutation is invisible.  Both fix classes the analyzer
        suggests restore it: fresh arrays per batch, or
        ``PADDLE_TPU_FEED_CACHE=0``."""
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.layers.data("x", shape=[128], dtype="float32")
            out = fluid.layers.reduce_sum(x)
        exe = Executor()
        with scope_guard(Scope()):
            exe.run(startup)
            d = str(tmp_path / "m")
            fluid.io.save_inference_model(d, ["x"], [out], exe,
                                          main_program=main)

        def run_mutating(cache_on, copy_per_batch=False):
            monkeypatch.setenv("PADDLE_TPU_FEED_CACHE",
                               "1" if cache_on else "0")
            pred = fluid.inference.create_paddle_predictor(
                fluid.inference.AnalysisConfig(d))
            buf = np.zeros((1, 128), dtype="float32")

            def batches():
                yield [buf.copy() if copy_per_batch else buf]
                buf[0, 1] = 100.0
                yield [buf.copy() if copy_per_batch else buf]

            return [float(np.asarray(r[0]).sum())
                    for r in pred.run_batches(batches(),
                                              max_in_flight=2)]

        # hazard: same object, mutated content — batch 2 is a stale
        # replay of batch 1's device value (the sum never moves)
        stale = run_mutating(cache_on=True)
        assert stale[1] == stale[0]
        # fix 1: don't share live buffers (fresh array per batch)
        fresh = run_mutating(cache_on=True, copy_per_batch=True)
        assert fresh == [0.0, 100.0]
        # fix 2: kill the cache
        nocache = run_mutating(cache_on=False)
        assert nocache[1] == 100.0

    def test_static_side_of_the_cache_hazard_is_the_feed_rule(self):
        """The same program is statically clean (nothing writes x) —
        the cache hazard is a host-side buffer-sharing bug, which is
        why the analyzer's feed rule only fires when the PROGRAM writes
        a fed slot.  Guards against over-reporting."""
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.layers.data("x", shape=[128], dtype="float32")
            out = fluid.layers.reduce_sum(x)
        assert find_inflight_races(main, targets=[out.name],
                                   max_in_flight=2) == []
