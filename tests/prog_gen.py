"""Seeded random Program generator for the static-analysis property
tests (ISSUE 10 satellite): every generated program is a plausible
feed-forward graph (data -> fc/activation/scale/add chains, optionally
trained with SGD), so "the analyzer never crashes and finds no race at
``max_in_flight=1``" can be asserted across a whole family of programs
instead of a handful of goldens.

Also provides the two *seeded-hazard* builders the runtime-vs-static
cross-checks anchor on:

* :func:`gen_feed_overwrite_program` — an op writes back into the fed
  data buffer (the double-buffer feed overwrite the prefetch pipeline
  turns into a real race at depth 2)
* :func:`gen_param_fetch_program` — a training program that fetches a
  parameter the optimizer updates in place (the donated-buffer hazard)

Deterministic by construction: same seed, same program.
"""

import numpy as np

import paddle_tpu as fluid

__all__ = ["gen_program", "gen_feed_overwrite_program",
           "gen_param_fetch_program"]

_WIDTHS = (4, 8, 16)


def gen_program(seed, max_layers=8, train=None):
    """Build a random feed-forward program.

    Returns ``(main, startup, fetch_names)`` — ``fetch_names`` is what
    a run of the program would fetch (the loss when training, the head
    output otherwise).
    """
    rng = np.random.RandomState(seed)
    fluid.unique_name.switch()
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        width = int(rng.choice(_WIDTHS))
        x = fluid.layers.data("x", shape=[width], dtype="float32")
        h = x
        for _ in range(int(rng.randint(1, max_layers + 1))):
            kind = rng.choice(["fc", "relu", "sigmoid", "scale", "add"])
            if kind == "fc":
                width = int(rng.choice(_WIDTHS))
                act = rng.choice([None, "relu", "sigmoid"])
                h = fluid.layers.fc(h, size=width, act=act)
            elif kind == "relu":
                h = fluid.layers.relu(h)
            elif kind == "sigmoid":
                h = fluid.layers.sigmoid(h)
            elif kind == "scale":
                h = fluid.layers.scale(
                    h, scale=float(rng.uniform(0.5, 1.5)))
            else:
                h = fluid.layers.elementwise_add(h, h)
        if train is None:
            train = bool(rng.randint(2))
        if train:
            loss = fluid.layers.mean(h)
            fluid.optimizer.SGD(
                learning_rate=float(rng.uniform(0.01, 0.2))
            ).minimize(loss)
            fetch = [loss.name]
        else:
            fetch = [h.name]
    return main, startup, fetch


def gen_feed_overwrite_program():
    """The seeded double-buffer hazard: a program whose last op writes
    back INTO the fed data var 'x'.  At ``max_in_flight>=2`` the
    prefetch pipeline may stage batch N+1 into the same slot while the
    in-flight step is still reading/writing batch N's buffer.

    Returns ``(main, startup, out_name, hazard_coords)`` where
    ``hazard_coords`` is ``(block_idx, op_idx)`` of the overwriting op
    — what the golden test pins the diagnostic to.
    """
    from paddle_tpu.framework import Operator

    fluid.unique_name.switch()
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[4], dtype="float32")
        out = fluid.layers.scale(x, scale=2.0)
    b = main.global_block()
    # built via Operator directly (as a rewriting pass would): append_op
    # would be within its rights to refuse a write to a data var
    b.ops.append(Operator(b, "scale", {"X": [out.name]}, {"Out": ["x"]},
                          {"scale": 1.0}))
    return main, startup, out.name, (0, len(b.ops) - 1)


def gen_param_fetch_program():
    """The seeded donated-buffer hazard: an SGD training program that
    fetches a parameter the optimizer writes in place.  With
    ``max_in_flight>=2`` the jitted step donates its read-write
    persistables, so the pending FetchHandle for step N-1 aliases the
    buffer step N invalidates.

    Returns ``(main, startup, loss_name, param_name)``.
    """
    fluid.unique_name.switch()
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[4], dtype="float32")
        y = fluid.layers.data("y", shape=[1], dtype="float32")
        h = fluid.layers.fc(x, size=8, act="relu")
        pred = fluid.layers.fc(h, size=1)
        loss = fluid.layers.mean(
            fluid.layers.square_error_cost(pred, y))
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    params = sorted(
        v.name for v in main.global_block().vars.values()
        if getattr(v, "persistable", False)
        and v.name.endswith(".w_0"))
    return main, startup, loss.name, params[0]
