"""Round-3 API tail: the residual ops from the reference
``REGISTER_OPERATOR`` set (linspace, sequence_erase,
positive_negative_pair, proximal_adagrad/gd, lookup_sparse_table,
in-graph save/load/load_combine), the reader-decorator tail
(PipeReader/Fake/multiprocess_reader), layers.io.load, and the
top-level DataFeedDesc/DistributeTranspiler exports."""

import os

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.executor import Scope, scope_guard
from paddle_tpu.ops.registry import call_op as _call_op, get_op_def, \
    LoweringContext


def call_op(ctx, op_type, ins, attrs):
    return _call_op(get_op_def(op_type), ctx,
                    {k: [v] for k, v in ins.items()}, attrs)


def _ctx():
    return LoweringContext()


class TestTailOps:
    def test_linspace_layer_and_op(self):
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            out = fluid.layers.linspace(2.0, 10.0, 5, "float32")
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        (v,) = exe.run(main, fetch_list=[out])
        np.testing.assert_allclose(v, np.linspace(2, 10, 5), rtol=1e-6)

    def test_sequence_erase(self):
        import jax.numpy as jnp

        X = jnp.asarray([[2, 2, 6, 1, 3, 9, 6, 1, 0, 0],
                         [1, 9, 8, 9, 5, 0, 0, 0, 0, 0]], dtype=jnp.int64)
        lens = jnp.asarray([8, 5], dtype=jnp.int32)
        res = call_op(_ctx(), "sequence_erase",
                      {"X": X, "SeqLen": lens}, {"tokens": [2, 9]})
        out, out_len = res["Out"][0], res["OutLen"][0]
        # row 0: [6,1,3,6,1], row 1: [1,8,5] (reference example semantics)
        np.testing.assert_array_equal(np.asarray(out_len), [5, 3])
        np.testing.assert_array_equal(np.asarray(out[0, :5]),
                                      [6, 1, 3, 6, 1])
        np.testing.assert_array_equal(np.asarray(out[1, :3]), [1, 8, 5])
        assert np.all(np.asarray(out[1, 3:]) == 0)

    def test_positive_negative_pair(self):
        import jax.numpy as jnp

        # query 0: docs (score, label): (3,1),(1,0) → pos pair
        # query 1: (2,0),(5,1),(2,1) → (d0,d1) pos-ordered? s:2vs5 l:0vs1
        #   → (2-5)*(0-1)=3>0 pos; (2,0)vs(2,1): tie → neutral;
        #   (5,1)vs(2,1): equal labels → skipped
        score = jnp.asarray([[3.0], [1.0], [2.0], [5.0], [2.0]])
        label = jnp.asarray([[1.0], [0.0], [0.0], [1.0], [1.0]])
        qid = jnp.asarray([[0], [0], [1], [1], [1]], dtype=jnp.int64)
        res = call_op(_ctx(), "positive_negative_pair",
                      {"Score": score, "Label": label, "QueryID": qid},
                      {"column": -1})
        # reference kernel quirk: the tied pair lands in BOTH neutral
        # and negative (no continue after neu += w)
        assert float(res["PositivePair"][0][0]) == 2.0
        assert float(res["NegativePair"][0][0]) == 1.0
        assert float(res["NeutralPair"][0][0]) == 1.0

    def test_proximal_gd(self):
        import jax.numpy as jnp

        p = jnp.asarray([1.0, -2.0, 0.05])
        g = jnp.asarray([0.1, 0.1, 0.1])
        lr = jnp.asarray([0.5])
        res = call_op(_ctx(), "proximal_gd",
                      {"Param": p, "Grad": g, "LearningRate": lr},
                      {"l1": 0.1, "l2": 0.2})
        prox = np.asarray(p) - 0.5 * np.asarray(g)
        expect = (np.sign(prox) * np.maximum(np.abs(prox) - 0.5 * 0.1, 0)
                  / (1 + 0.5 * 0.2))
        np.testing.assert_allclose(res["ParamOut"][0], expect, rtol=1e-6)

    def test_proximal_adagrad(self):
        import jax.numpy as jnp

        p = jnp.asarray([1.0, -2.0])
        m = jnp.asarray([0.5, 0.5])
        g = jnp.asarray([0.2, -0.4])
        lr = jnp.asarray([0.1])
        res = call_op(_ctx(), "proximal_adagrad",
                      {"Param": p, "Moment": m, "Grad": g,
                       "LearningRate": lr}, {"l1": 0.05, "l2": 0.1})
        m_new = np.asarray(m) + np.asarray(g) ** 2
        alr = 0.1 / np.sqrt(m_new)
        prox = np.asarray(p) - alr * np.asarray(g)
        # shrinkage uses the PLAIN lr (proximal_adagrad_op.h), only the
        # gradient step is adaptive
        expect = (np.sign(prox) * np.maximum(np.abs(prox) - 0.1 * 0.05, 0)
                  / (1 + 0.1 * 0.1))
        np.testing.assert_allclose(res["ParamOut"][0], expect, rtol=1e-6)
        np.testing.assert_allclose(res["MomentOut"][0], m_new, rtol=1e-6)

    def test_lookup_sparse_table(self):
        import jax.numpy as jnp

        W = jnp.arange(12.0).reshape(6, 2)
        ids = jnp.asarray([[1], [4]], dtype=jnp.int64)
        res = call_op(_ctx(), "lookup_sparse_table",
                      {"W": W, "Ids": ids}, {"padding_idx": -1})
        np.testing.assert_allclose(
            np.asarray(res["Out"][0]).reshape(2, 2), [[2, 3], [8, 9]])

    def test_lookup_sparse_table_trains(self):
        """The reference's auto-grown table IS trainable (rows update on
        the pserver); here the dense row-sharded table must receive
        scatter-add gradients like lookup_table does."""
        fluid.unique_name.switch()
        main, startup = fluid.Program(), fluid.Program()
        main.random_seed = startup.random_seed = 5
        with fluid.program_guard(main, startup):
            ids = fluid.layers.data("ids", shape=[3], dtype="int64")
            label = fluid.layers.data("label", shape=[1], dtype="int64")
            blk = main.global_block()
            w = blk.create_parameter(
                name="sp_table", shape=[32, 4], dtype="float32")
            out = blk.create_var(name="sp_out", shape=[-1, 3, 4],
                                 dtype="float32")
            blk.append_op(type="lookup_sparse_table",
                          inputs={"W": [w], "Ids": [ids]},
                          outputs={"Out": [out]},
                          attrs={"padding_idx": -1})
            pooled = fluid.layers.reduce_sum(out, dim=1)
            logits = fluid.layers.fc(pooled, size=2)
            loss = fluid.layers.reduce_mean(
                fluid.layers.softmax_with_cross_entropy(logits, label))
            fluid.optimizer.SGD(learning_rate=0.5).minimize(loss)
        exe = fluid.Executor(fluid.CPUPlace())
        scope = Scope()
        with scope_guard(scope):
            exe.run(startup)
            # raw create_parameter has no startup init op; seed directly
            import jax.numpy as jnp
            scope.set("sp_table", jnp.asarray(
                np.random.RandomState(0).randn(32, 4).astype("float32")))
            before = np.asarray(scope.get("sp_table")).copy()
            feed = {"ids": np.asarray([[1, 2, 3]], "int64"),
                    "label": np.asarray([[1]], "int64")}
            exe.run(main, feed=feed, fetch_list=[])
            after = np.asarray(scope.get("sp_table"))
        # touched rows changed, untouched rows did not (scatter-add grad)
        assert not np.allclose(after[1:4], before[1:4])
        np.testing.assert_allclose(after[5:], before[5:])


class TestInGraphSaveLoad:
    def test_save_load_program_roundtrip(self, tmp_path):
        """A program containing save ops executes (host-IO path), and a
        load program restores the values (reference save_op.cc usage)."""
        import jax.numpy as jnp

        scope = Scope()
        with scope_guard(scope):
            scope.set("w", jnp.asarray([[1.0, 2.0], [3.0, 4.0]]))
            scope.set("b", jnp.asarray([5.0, 6.0]))

            save_prog = fluid.Program()
            blk = save_prog.global_block()
            for n in ("w", "b"):
                v = blk.create_var(name=n, shape=[1], dtype="float32",
                                   persistable=True)
                blk.append_op(type="save", inputs={"X": [v]}, outputs={},
                              attrs={"file_path":
                                     str(tmp_path / ("%s.npy" % n))})
            exe = fluid.Executor(fluid.CPUPlace())
            exe.run(save_prog)
            assert os.path.exists(str(tmp_path / "w.npy"))

            scope.set("w", jnp.zeros((2, 2)))
            scope.set("b", jnp.zeros((2,)))
            load_prog = fluid.Program()
            blk = load_prog.global_block()
            for n in ("w", "b"):
                v = blk.create_var(name=n, shape=[1], dtype="float32",
                                   persistable=True)
                blk.append_op(type="load", inputs={}, outputs={"Out": [v]},
                              attrs={"file_path":
                                     str(tmp_path / ("%s.npy" % n))})
            exe.run(load_prog)
            np.testing.assert_allclose(
                np.asarray(scope.get("w")), [[1, 2], [3, 4]])
            np.testing.assert_allclose(np.asarray(scope.get("b")), [5, 6])

    def test_save_combine_load_combine(self, tmp_path):
        import jax.numpy as jnp

        path = str(tmp_path / "combined")
        scope = Scope()
        with scope_guard(scope):
            scope.set("x1", jnp.asarray([1.0]))
            scope.set("x2", jnp.asarray([[2.0, 3.0]]))
            prog = fluid.Program()
            blk = prog.global_block()
            vs = [blk.create_var(name=n, shape=[1], dtype="float32",
                                 persistable=True) for n in ("x1", "x2")]
            blk.append_op(type="save_combine", inputs={"X": vs}, outputs={},
                          attrs={"file_path": path})
            exe = fluid.Executor(fluid.CPUPlace())
            exe.run(prog)

            scope.set("x1", jnp.zeros((1,)))
            scope.set("x2", jnp.zeros((1, 2)))
            lprog = fluid.Program()
            blk = lprog.global_block()
            vs = [blk.create_var(name=n, shape=[1], dtype="float32",
                                 persistable=True) for n in ("x1", "x2")]
            blk.append_op(type="load_combine", inputs={},
                          outputs={"Out": vs}, attrs={"file_path": path})
            exe.run(lprog)
            np.testing.assert_allclose(np.asarray(scope.get("x1")), [1.0])
            np.testing.assert_allclose(
                np.asarray(scope.get("x2")), [[2.0, 3.0]])

    def test_layers_io_load(self, tmp_path):
        import jax.numpy as jnp

        p = str(tmp_path / "t.npy")
        np.save(p, np.asarray([7.0, 8.0], np.float32))
        scope = Scope()
        with scope_guard(scope):
            prog = fluid.Program()
            with fluid.program_guard(prog):
                out = prog.global_block().create_var(
                    name="t", shape=[2], dtype="float32", persistable=True)
                fluid.layers.load(out, p)
            exe = fluid.Executor(fluid.CPUPlace())
            exe.run(prog)
            np.testing.assert_allclose(np.asarray(scope.get("t")), [7, 8])

    def test_mixed_program_load_compute_save(self, tmp_path):
        """Reference order semantics: loads run before the compute, saves
        after it — a load→compute→save program works in one exe.run."""
        import jax.numpy as jnp

        np.save(str(tmp_path / "win.npy"), np.asarray([2.0, 3.0], "float32"))
        scope = Scope()
        with scope_guard(scope):
            prog = fluid.Program()
            with fluid.program_guard(prog):
                blk = prog.global_block()
                w = blk.create_var(name="w", shape=[2], dtype="float32",
                                   persistable=True)
                fluid.layers.load(w, str(tmp_path / "win.npy"))
                doubled = fluid.layers.scale(w, scale=2.0)
                out = blk.create_var(name="doubled_out", shape=[2],
                                     dtype="float32", persistable=True)
                fluid.layers.assign(doubled, output=out)
                blk.append_op(
                    type="save", inputs={"X": [out]}, outputs={},
                    attrs={"file_path": str(tmp_path / "wout.npy")})
            exe = fluid.Executor(fluid.CPUPlace())
            (v,) = exe.run(prog, fetch_list=["doubled_out"])
        np.testing.assert_allclose(v, [4.0, 6.0])
        np.testing.assert_allclose(
            np.load(str(tmp_path / "wout.npy")), [4.0, 6.0])


class TestReaderTail:
    def test_fake(self):
        def reader():
            for i in range(10):
                yield i

        from paddle_tpu.reader_decorators import Fake

        fake = Fake()(reader, 4)
        assert list(fake()) == [0, 0, 0, 0]
        assert list(fake()) == [0, 0, 0, 0]  # counter resets

    def test_pipe_reader(self):
        from paddle_tpu.reader_decorators import PipeReader

        pr = PipeReader("printf 'a 1\\nb 2\\nc 3\\n'")
        # printf through /bin/sh semantics differ; use echo fallback check
        lines = list(pr.get_line())
        assert len(lines) >= 1

    def test_pipe_reader_plain_lines(self, tmp_path):
        from paddle_tpu.reader_decorators import PipeReader

        p = tmp_path / "f.txt"
        p.write_text("x 1\ny 2\nz 3\n")
        lines = list(PipeReader("cat %s" % p).get_line())
        assert lines == ["x 1", "y 2", "z 3"]

    def test_pipe_reader_gzip(self, tmp_path):
        import gzip

        from paddle_tpu.reader_decorators import PipeReader

        p = tmp_path / "f.gz"
        with gzip.open(p, "wt") as f:
            f.write("g1\ng2\n")
        lines = list(PipeReader("cat %s" % p, file_type="gzip").get_line())
        assert lines == ["g1", "g2"]

    def test_multiprocess_reader_queue_and_pipe(self):
        from paddle_tpu.reader_decorators import multiprocess_reader

        def make(lo, hi):
            def r():
                for i in range(lo, hi):
                    yield [i]
            return r

        for use_pipe in (False, True):
            mr = multiprocess_reader([make(0, 3), make(10, 13)],
                                     use_pipe=use_pipe)
            got = sorted(s[0] for s in mr())
            assert got == [0, 1, 2, 10, 11, 12], (use_pipe, got)


class TestTopLevelExports:
    def test_exports(self):
        assert hasattr(fluid, "DistributeTranspiler")
        assert hasattr(fluid, "DistributeTranspilerConfig")
        assert hasattr(fluid, "DataFeedDesc")
        assert hasattr(fluid, "DatasetFactory")

    def test_data_feed_desc(self, tmp_path):
        proto = tmp_path / "data.proto"
        proto.write_text(
            'name: "MultiSlotDataFeed"\n'
            "batch_size: 2\n"
            "multi_slot_desc {\n"
            "    slots {\n"
            '         name: "words"\n'
            '         type: "uint64"\n'
            "         is_dense: false\n"
            "         is_used: true\n"
            "    }\n"
            "    slots {\n"
            '         name: "label"\n'
            '         type: "uint64"\n'
            "         is_dense: false\n"
            "         is_used: true\n"
            "    }\n"
            "}\n")
        d = fluid.DataFeedDesc(str(proto))
        d.set_batch_size(128)
        d.set_dense_slots(["words"])
        d.set_use_slots(["words"])
        text = d.desc()
        assert "batch_size: 128" in text
        assert 'name: "MultiSlotDataFeed"' in text
        # round-trip: desc() re-parses to the same structure
        p2 = tmp_path / "rt.proto"
        p2.write_text(text)
        d2 = fluid.DataFeedDesc(str(p2))
        slots = d2.proto_desc["multi_slot_desc"][0]["slots"]
        by_name = {s["name"]: s for s in slots}
        assert by_name["words"]["is_dense"] is True
        assert by_name["words"]["is_used"] is True
        assert by_name["label"]["is_used"] is False
