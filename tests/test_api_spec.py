"""API freeze (reference: ``tools/diff_api.py`` fails CI when the public
surface drifts from ``paddle/fluid/API.spec``).  Regenerate with:

    PYTHONPATH=. python tools/print_signatures.py > API.spec
"""

import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class TestApiSpec:
    def test_spec_is_current(self):
        res = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools",
                                          "print_signatures.py")],
            capture_output=True, text=True, timeout=300,
            env={**os.environ, "PYTHONPATH": REPO}, cwd=REPO)
        assert res.returncode == 0, res.stderr[-800:]
        fresh = res.stdout.splitlines()
        with open(os.path.join(REPO, "API.spec")) as f:
            frozen = f.read().splitlines()
        added = sorted(set(fresh) - set(frozen))
        removed = sorted(set(frozen) - set(fresh))
        assert not added and not removed, (
            "public API drifted from API.spec — regenerate it "
            "(added: %s..., removed: %s...)"
            % (added[:5], removed[:5]))

    def test_spec_size_bar(self):
        """Round-3 bar: >= 950 frozen entries (reference: 1031)."""
        with open(os.path.join(REPO, "API.spec")) as f:
            n = sum(1 for line in f if line.strip())
        assert n >= 950, n
