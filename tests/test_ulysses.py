"""Ulysses all-to-all sequence parallelism: output and grads must match
dense attention (same bar as test_ring_attention), heads re-order
correctly through the two all-to-alls, and the head-divisibility guard
fires."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from paddle_tpu.parallel import ulysses_attention
from paddle_tpu.ops.pallas.flash_attention import mha_reference

B, H, T, D = 2, 8, 64, 16
N = 4  # sequence-parallel degree


@pytest.fixture(scope="module")
def mesh():
    return Mesh(np.array(jax.devices()[:N]), ("sp",))


def _rand(rng, *s):
    return jnp.asarray(rng.randn(*s).astype("float32"))


class TestUlysses:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_dense(self, mesh, causal):
        rng = np.random.RandomState(0)
        q, k, v = (_rand(rng, B, H, T, D) for _ in range(3))
        out = jax.jit(lambda q, k, v: ulysses_attention(
            q, k, v, mesh, "sp", causal=causal))(q, k, v)
        ref = mha_reference(q, k, v, causal=causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    def test_with_bias_and_sharded_inputs(self, mesh):
        rng = np.random.RandomState(1)
        q, k, v = (_rand(rng, B, H, T, D) for _ in range(3))
        bias = jnp.where(
            jnp.arange(T)[None, :] < T - 7, 0.0, -1e4
        ) * jnp.ones((B, 1))
        seq_sh = NamedSharding(mesh, P(None, None, "sp", None))
        qs, ks, vs = (jax.device_put(x, seq_sh) for x in (q, k, v))
        out = jax.jit(lambda q, k, v, b: ulysses_attention(
            q, k, v, mesh, "sp", bias=b))(qs, ks, vs, bias)
        ref = mha_reference(q, k, v, bias=bias)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)
        # output keeps the sequence sharding of its inputs
        assert out.sharding.spec[2] == "sp"

    def test_grads_match_dense(self, mesh):
        rng = np.random.RandomState(2)
        q, k, v = (_rand(rng, B, H, T, D) for _ in range(3))

        def loss_sp(q, k, v):
            o = ulysses_attention(q, k, v, mesh, "sp")
            return jnp.sum(o ** 2)

        def loss_ref(q, k, v):
            return jnp.sum(mha_reference(q, k, v) ** 2)

        gs = jax.jit(jax.grad(loss_sp, argnums=(0, 1, 2)))(q, k, v)
        gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b, nm in zip(gs, gr, "qkv"):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=5e-5, rtol=5e-5,
                err_msg="d%s" % nm)

    def test_head_divisibility_guard(self, mesh):
        rng = np.random.RandomState(3)
        q = _rand(rng, B, 2, T, D)  # 2 heads < 4 devices
        with pytest.raises(Exception, match="ring attention"):
            jax.jit(lambda q: ulysses_attention(
                q, q, q, mesh, "sp"))(q)
