"""Fault-tolerant runtime (ISSUE 2): fault injection, retry/backoff,
atomic+versioned checkpoints with auto-resume, the NaN step-guard, the
resilience lint check, and the chaos CLI acceptance scenario.

Cluster-level kill-and-resume lives in test_fault_tolerance.py (slow);
everything here is single-process and fast."""

import os
import subprocess
import sys
import time
import warnings

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.executor import Scope, scope_guard
from paddle_tpu.resilience import (checkpoint, faults, guard, retry,
                                   watchdog)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_resilience_state(monkeypatch):
    """Every test starts with an inert injector, fresh guard stats and
    no resilience env knobs leaking in from outside."""
    for var in ("PADDLE_TPU_FAULT_SPEC", "PADDLE_TPU_NAN_GUARD",
                "PADDLE_TPU_FAULT_STATE_FILE",
                "PADDLE_TPU_NAN_GUARD_MAX_SKIPS"):
        monkeypatch.delenv(var, raising=False)
    faults.set_fault_spec("")
    guard.stats.reset()
    yield
    faults.set_fault_spec("")
    guard.stats.reset()


# ---------------------------------------------------------------------------
# fault spec parsing / firing
# ---------------------------------------------------------------------------
class TestFaultSpec:
    def test_parses_kinds_and_params(self):
        inj = faults.FaultInjector(
            "nan_grad@step=3,target=fc_0.w_0@GRAD;"
            "ckpt_write_fail@step=5,times=2;"
            "worker_kill@step=7,rank=1;"
            "io_fail@target=read,p=0.5,seed=9")
        assert [f.kind for f in inj.faults] == [
            "nan_grad", "ckpt_write_fail", "worker_kill", "io_fail"]
        nan = inj.faults[0]
        assert nan.step == 3 and nan.target == "fc_0.w_0@GRAD"
        assert np.isnan(nan.value)
        assert inj.faults[1].times == 2
        assert inj.faults[2].rank == 1
        assert inj.faults[3].site == "io_read"
        assert len(inj.trace_faults) == 1

    def test_rejects_unknown_kind_and_param(self):
        with pytest.raises(ValueError):
            faults.FaultInjector("frobnicate@step=1")
        with pytest.raises(ValueError):
            faults.FaultInjector("nan_grad@wat=1")

    def test_step_and_times_budget(self):
        f = faults.Fault.parse("ckpt_write_fail@step=5,times=2")
        assert not f.should_fire(4, 0)
        assert f.should_fire(5, 0)
        assert f.should_fire(5, 0)
        assert not f.should_fire(5, 0)  # budget spent

    def test_probabilistic_fire_is_seeded(self):
        f1 = faults.Fault.parse("io_fail@p=0.5,seed=11,times=0")
        f2 = faults.Fault.parse("io_fail@p=0.5,seed=11,times=0")
        draws1 = [f1.should_fire(k, 0) for k in range(20)]
        draws2 = [f2.should_fire(k, 0) for k in range(20)]
        assert draws1 == draws2
        assert any(draws1) and not all(draws1)

    def test_rank_scoping(self):
        f = faults.Fault.parse("worker_kill@step=2,rank=1")
        assert not f.should_fire(2, 0)
        assert f.should_fire(2, 1)

    def test_site_fault_raises_transient(self):
        inj = faults.FaultInjector("compile_fail@times=1")
        with pytest.raises(faults.TransientFault):
            inj.maybe_fire("compile")
        inj.maybe_fire("compile")  # budget spent: no raise

    def test_state_file_spans_restarts(self, tmp_path):
        state = str(tmp_path / "fault_state.json")
        inj = faults.FaultInjector("worker_kill@step=7", state_file=state)
        assert inj.faults[0].should_fire(7, 0)
        inj._persist_state()
        # a "restarted" injector sees the budget already consumed
        inj2 = faults.FaultInjector("worker_kill@step=7",
                                    state_file=state)
        assert inj2.faults[0].exhausted()
        assert not inj2.faults[0].should_fire(7, 0)


# ---------------------------------------------------------------------------
# retry / timeout / backoff
# ---------------------------------------------------------------------------
class TestRetry:
    def test_succeeds_after_transient_failures(self):
        calls = {"n": 0}

        def flaky():
            calls["n"] += 1
            if calls["n"] < 3:
                raise faults.TransientFault("boom")
            return "ok"

        policy = retry.RetryPolicy(max_attempts=4, base_delay=0.001)
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            assert retry.retry_call(flaky, policy=policy) == "ok"
        assert calls["n"] == 3

    def test_non_retryable_propagates_immediately(self):
        calls = {"n": 0}

        def bug():
            calls["n"] += 1
            raise TypeError("a real bug")

        with pytest.raises(TypeError):
            retry.retry_call(bug, policy=retry.RetryPolicy(
                max_attempts=5, base_delay=0.001))
        assert calls["n"] == 1

    def test_exhaustion_raises_with_last_error(self):
        def always():
            raise OSError("disk on fire")

        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            with pytest.raises(retry.RetryExhaustedError) as ei:
                retry.retry_call(always, policy=retry.RetryPolicy(
                    max_attempts=2, base_delay=0.001))
        assert isinstance(ei.value.last_error, OSError)
        assert ei.value.attempts == 2

    def test_backoff_schedule_deterministic_and_bounded(self):
        p = retry.RetryPolicy(max_attempts=5, base_delay=0.1,
                              max_delay=0.3, jitter=0.25, seed=4)
        d1, d2 = list(p.delays()), list(p.delays())
        assert d1 == d2 and len(d1) == 4
        # exponential up to the (jittered) ceiling
        assert all(d <= 0.3 * 1.25 + 1e-9 for d in d1)
        assert d1[1] > d1[0]

    def test_run_with_timeout(self):
        assert retry.run_with_timeout(lambda: 42, 5.0) == 42
        with pytest.raises(TimeoutError):
            retry.run_with_timeout(lambda: time.sleep(10), 0.2,
                                   what="nap")
        with pytest.raises(watchdog.WorkerLostError):
            retry.run_with_timeout(lambda: time.sleep(10), 0.2,
                                   what="barrier",
                                   error_cls=watchdog.WorkerLostError)


# ---------------------------------------------------------------------------
# shared tiny model
# ---------------------------------------------------------------------------
def _build_model(lr=0.1, opt="adam"):
    fluid.unique_name.switch()
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 7
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[4], dtype="float32")
        y = fluid.layers.data("y", shape=[1], dtype="float32")
        h = fluid.layers.fc(x, size=8, act="relu")
        p = fluid.layers.fc(h, size=1)
        loss = fluid.layers.reduce_mean(fluid.layers.square(p - y))
        factory = (fluid.optimizer.Adam if opt == "adam"
                   else fluid.optimizer.SGD)
        factory(learning_rate=lr).minimize(loss)
    return main, startup, loss


def _make_batches(n, bs=16, seed=0):
    rng = np.random.RandomState(seed)
    return [(rng.randn(bs, 4).astype("float32"),
             rng.randn(bs, 1).astype("float32")) for _ in range(n)]


def _persistable_values(program):
    sc = fluid.global_scope()
    out = {}
    for v in program.list_vars():
        if v.persistable and sc.get(v.name) is not None:
            out[v.name] = np.asarray(sc.get(v.name))
    return out


# ---------------------------------------------------------------------------
# atomic io.py (satellite)
# ---------------------------------------------------------------------------
class TestAtomicIO:
    def _save_one(self, tmp_path):
        main, startup, loss = _build_model(opt="sgd")
        exe = fluid.Executor(fluid.CPUPlace())
        with scope_guard(Scope()):
            exe.run(startup)
            fluid.io.save_persistables(exe, str(tmp_path), main)
        return main

    def test_failed_save_leaves_no_torn_output(self, tmp_path,
                                               monkeypatch):
        main = self._save_one(tmp_path)
        before = {}
        for f in os.listdir(str(tmp_path)):
            p = os.path.join(str(tmp_path), f)
            with open(p, "rb") as fh:
                before[f] = fh.read()
        assert before

        def torn_save(f, arr, **kw):
            # write garbage bytes then die: simulates a mid-write crash
            f.write(b"\x93NUMPY-GARBAGE")
            raise OSError("injected torn write")

        monkeypatch.setattr(np, "save", torn_save)
        exe = fluid.Executor(fluid.CPUPlace())
        with scope_guard(Scope()):
            sc = fluid.global_scope()
            sc.set("fc_0.w_0", np.zeros([4, 8], "float32"))
            with pytest.raises(OSError, match="torn write"):
                fluid.io.save_vars(
                    exe, str(tmp_path), main,
                    vars=[main.global_block().var("fc_0.w_0")])
        # no tmp litter, and every pre-existing file is byte-identical
        assert sorted(os.listdir(str(tmp_path))) == sorted(before)
        for f, data in before.items():
            with open(os.path.join(str(tmp_path), f), "rb") as fh:
                assert fh.read() == data, f

    def test_corrupt_npy_load_names_file_and_var(self, tmp_path):
        main = self._save_one(tmp_path)
        victim_var = "fc_0.w_0"
        victim = os.path.join(str(tmp_path), victim_var + ".npy")
        with open(victim, "wb") as f:
            f.write(b"\x93NUMPY truncated")
        exe = fluid.Executor(fluid.CPUPlace())
        with scope_guard(Scope()):
            with pytest.raises(RuntimeError) as ei:
                fluid.io.load_persistables(exe, str(tmp_path), main)
        assert victim_var in str(ei.value)
        assert "corrupt" in str(ei.value) or "unreadable" in str(ei.value)

    def test_missing_combined_npz_is_clear_error(self, tmp_path):
        main, startup, _ = _build_model(opt="sgd")
        exe = fluid.Executor(fluid.CPUPlace())
        with scope_guard(Scope()):
            exe.run(startup)
            with pytest.raises(RuntimeError) as ei:
                fluid.io.load_persistables(exe, str(tmp_path), main,
                                           filename="nope")
        assert "nope" in str(ei.value)


# ---------------------------------------------------------------------------
# atomic + versioned checkpoints (tentpole)
# ---------------------------------------------------------------------------
class TestVersionedCheckpoint:
    def _train_and_checkpoint(self, root, steps=4, retain=3):
        main, startup, loss = _build_model()
        exe = fluid.Executor(fluid.CPUPlace())
        digests = {}
        with scope_guard(Scope()):
            exe.run(startup)
            for k, (xb, yb) in enumerate(_make_batches(steps)):
                exe.run(main, feed={"x": xb, "y": yb},
                        fetch_list=[loss])
                checkpoint.save_checkpoint(
                    exe, root, main_program=main, step=k,
                    state={"next_step": k + 1}, retain=retain)
                digests[k] = _persistable_values(main)
        return main, startup, loss, digests

    def test_versioning_and_retention(self, tmp_path):
        root = str(tmp_path)
        self._train_and_checkpoint(root, steps=5, retain=3)
        assert [s for s, _ in checkpoint.list_checkpoints(root)] \
            == [4, 3, 2]
        # no staging litter
        assert not [d for d in os.listdir(root)
                    if d.startswith(".tmp-")]

    def test_resume_restores_exact_values_and_state(self, tmp_path):
        root = str(tmp_path)
        main, startup, loss, digests = self._train_and_checkpoint(root)
        exe = fluid.Executor(fluid.CPUPlace())
        with scope_guard(Scope()):
            exe.run(startup)
            info = checkpoint.try_load_latest_checkpoint(
                exe, root, main_program=main)
            assert info.step == 3
            assert info.state == {"next_step": 4}
            restored = _persistable_values(main)
        for name, want in digests[3].items():
            np.testing.assert_array_equal(restored[name], want)

    def test_checksum_tamper_skips_to_older_valid_version(self, tmp_path):
        root = str(tmp_path)
        main, startup, loss, digests = self._train_and_checkpoint(root)
        newest = checkpoint.list_checkpoints(root)[0][1]
        vars_dir = os.path.join(newest, checkpoint.VARS_SUBDIR)
        victim = sorted(f for f in os.listdir(vars_dir)
                        if f.endswith(".npy"))[0]
        with open(os.path.join(vars_dir, victim), "r+b") as f:
            f.seek(8)
            f.write(b"\xde\xad\xbe\xef")
        exe = fluid.Executor(fluid.CPUPlace())
        with scope_guard(Scope()):
            exe.run(startup)
            with warnings.catch_warnings(record=True) as caught:
                warnings.simplefilter("always")
                info = checkpoint.try_load_latest_checkpoint(
                    exe, root, main_program=main)
            assert info.step == 2  # newest (3) was tampered: skipped
            restored = _persistable_values(main)
        assert any("checksum" in str(w.message) or "skipping" in
                   str(w.message) for w in caught)
        for name, want in digests[2].items():
            np.testing.assert_array_equal(restored[name], want)

    def test_manifestless_dir_never_loads(self, tmp_path):
        root = str(tmp_path)
        main, startup, loss, _ = self._train_and_checkpoint(root,
                                                            steps=2)
        # fake a torn version that looks newest but has no manifest
        torn = os.path.join(root, "%s%08d" % (checkpoint.CKPT_PREFIX, 99))
        os.makedirs(os.path.join(torn, checkpoint.VARS_SUBDIR))
        exe = fluid.Executor(fluid.CPUPlace())
        with scope_guard(Scope()):
            exe.run(startup)
            with warnings.catch_warnings():
                warnings.simplefilter("ignore")
                info = checkpoint.try_load_latest_checkpoint(
                    exe, root, main_program=main)
        assert info.step == 1

    def test_all_corrupt_returns_none(self, tmp_path):
        root = str(tmp_path / "empty")
        main, startup, _ = _build_model()
        exe = fluid.Executor(fluid.CPUPlace())
        with scope_guard(Scope()):
            exe.run(startup)
            assert checkpoint.try_load_latest_checkpoint(
                exe, root, main_program=main) is None

    def test_transient_write_failure_is_retried(self, tmp_path):
        faults.set_fault_spec("ckpt_write_fail@times=2")
        root = str(tmp_path)
        main, startup, loss = _build_model()
        exe = fluid.Executor(fluid.CPUPlace())
        with scope_guard(Scope()):
            exe.run(startup)
            with warnings.catch_warnings(record=True) as caught:
                warnings.simplefilter("always")
                path = checkpoint.save_checkpoint(
                    exe, root, main_program=main, step=0,
                    policy=retry.RetryPolicy(max_attempts=4,
                                             base_delay=0.001))
        assert path is not None and os.path.isdir(path)
        assert sum("retrying" in str(w.message) for w in caught) == 2
        checkpoint.verify_checkpoint(path)  # intact despite the faults

    def test_write_retries_exhausted_raises(self, tmp_path):
        faults.set_fault_spec("ckpt_write_fail@times=0")  # unlimited
        main, startup, _ = _build_model()
        exe = fluid.Executor(fluid.CPUPlace())
        with scope_guard(Scope()):
            exe.run(startup)
            with warnings.catch_warnings():
                warnings.simplefilter("ignore")
                with pytest.raises(retry.RetryExhaustedError):
                    checkpoint.save_checkpoint(
                        exe, str(tmp_path), main_program=main, step=0,
                        policy=retry.RetryPolicy(max_attempts=2,
                                                 base_delay=0.001))
        # a failed save leaves neither a version nor staging litter
        assert checkpoint.list_checkpoints(str(tmp_path)) == []


# ---------------------------------------------------------------------------
# NaN/Inf step-guard (tentpole)
# ---------------------------------------------------------------------------
class TestNanGuard:
    def _run(self, batches, spec="", skip=(), guard_on=True,
             monkeypatch=None):
        if guard_on:
            monkeypatch.setenv("PADDLE_TPU_NAN_GUARD", "1")
        faults.set_fault_spec(spec)
        guard.stats.reset()
        main, startup, loss = _build_model()
        exe = fluid.Executor(fluid.CPUPlace())
        losses = []
        with scope_guard(Scope()):
            exe.run(startup)
            for k, (xb, yb) in enumerate(batches):
                if k in skip:
                    continue
                faults.set_step(k)
                with warnings.catch_warnings():
                    warnings.simplefilter("ignore")
                    (lv,) = exe.run(main, feed={"x": xb, "y": yb},
                                    fetch_list=[loss])
                losses.append(float(np.asarray(lv).reshape(())))
            params = _persistable_values(main)
        return losses, params, guard.stats.as_dict()

    def test_nan_grad_step_skipped_and_counted(self, monkeypatch):
        batches = _make_batches(5)
        _, params, stats = self._run(batches, spec="nan_grad@step=2",
                                     monkeypatch=monkeypatch)
        assert stats["skipped_steps"] == 1
        assert stats["last_skipped_step"] == 2
        # trajectory == fault-free run that never applied step 2
        _, oracle, _ = self._run(batches, skip={2},
                                 monkeypatch=monkeypatch)
        for name in params:
            np.testing.assert_array_equal(params[name], oracle[name])

    def test_inf_targeted_grad_also_skips(self, monkeypatch):
        batches = _make_batches(4)
        _, params, stats = self._run(
            batches, spec="inf_grad@step=1,target=fc_1.w_0@GRAD",
            monkeypatch=monkeypatch)
        assert stats["skipped_steps"] == 1
        for v in params.values():
            assert np.isfinite(v).all()

    def test_unguarded_nan_poisons_params(self, monkeypatch):
        # negative control: without the guard the same fault corrupts
        batches = _make_batches(3)
        _, params, _ = self._run(batches, spec="nan_grad@step=1",
                                 guard_on=False, monkeypatch=monkeypatch)
        assert any(not np.isfinite(v).all() for v in params.values())

    def test_consecutive_skip_limit_aborts(self, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_NAN_GUARD", "1")
        monkeypatch.setenv("PADDLE_TPU_NAN_GUARD_MAX_SKIPS", "3")
        faults.set_fault_spec("nan_grad@times=0")  # every step
        guard.stats.reset()
        main, startup, loss = _build_model()
        exe = fluid.Executor(fluid.CPUPlace())
        with scope_guard(Scope()):
            exe.run(startup)
            with warnings.catch_warnings():
                warnings.simplefilter("ignore")
                with pytest.raises(RuntimeError, match="diverged"):
                    for k, (xb, yb) in enumerate(_make_batches(6)):
                        faults.set_step(k)
                        exe.run(main, feed={"x": xb, "y": yb},
                                fetch_list=[loss])
        assert guard.stats.consecutive_skips == 3

    def test_guard_covers_data_parallel_path(self, monkeypatch):
        """SPMDRunner (CompiledProgram.with_data_parallel) carries the
        guard too — the DP trainer is where survival matters most."""
        monkeypatch.setenv("PADDLE_TPU_NAN_GUARD", "1")
        faults.set_fault_spec("")
        guard.stats.reset()
        main, startup, loss = _build_model()
        cp = fluid.CompiledProgram(main).with_data_parallel(
            loss_name=loss.name)
        exe = fluid.Executor(fluid.CPUPlace())
        with scope_guard(Scope()):
            exe.run(startup)
            for xb, yb in _make_batches(2):
                (lv,) = exe.run(cp, feed={"x": xb, "y": yb},
                                fetch_list=[loss])
                assert np.isfinite(np.asarray(lv)).all()
        assert guard.stats.total_steps == 2
        assert guard.stats.skipped_steps == 0


# ---------------------------------------------------------------------------
# resilience lint check (satellite)
# ---------------------------------------------------------------------------
class TestResilienceLint:
    def test_unguarded_training_program_advisory(self):
        from paddle_tpu.static_analysis import Severity, verify_program

        main, startup, loss = _build_model()
        diags = verify_program(main, targets=[loss.name])
        hits = [d for d in diags if d.check == "resilience-finite-guard"]
        assert hits and hits[0].severity is Severity.INFO
        assert "PADDLE_TPU_NAN_GUARD" in hits[0].hint

    def test_guarded_program_is_clean(self):
        from paddle_tpu.static_analysis import verify_program

        main, startup, loss = _build_model()
        main._nan_guard = True
        diags = verify_program(main, targets=[loss.name])
        assert not [d for d in diags
                    if d.check == "resilience-finite-guard"]

    def test_inference_program_is_exempt(self):
        from paddle_tpu.static_analysis import verify_program

        fluid.unique_name.switch()
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.layers.data("x", shape=[4], dtype="float32")
            out = fluid.layers.fc(x, size=2)
        diags = verify_program(main, targets=[out.name])
        assert not [d for d in diags
                    if d.check == "resilience-finite-guard"]


# ---------------------------------------------------------------------------
# watchdog / heartbeats
# ---------------------------------------------------------------------------
class TestWatchdog:
    def test_wait_cluster_detects_dead_worker_quickly(self):
        sleeper = subprocess.Popen(
            [sys.executable, "-c", "import time; time.sleep(60)"])
        dier = subprocess.Popen(
            [sys.executable, "-c", "import sys; sys.exit(5)"])
        t0 = time.time()
        try:
            with pytest.raises(watchdog.WorkerLostError) as ei:
                watchdog.wait_cluster([sleeper, dier], timeout=30,
                                      poll=0.1)
        finally:
            for p in (sleeper, dier):
                if p.poll() is None:
                    p.kill()
                p.wait()
        assert time.time() - t0 < 20  # bounded, nowhere near the hang
        assert 5 in ei.value.returncodes
        assert sleeper.poll() is not None  # survivor was reaped

    def test_wait_cluster_timeout_raises(self):
        p = subprocess.Popen(
            [sys.executable, "-c", "import time; time.sleep(60)"])
        try:
            with pytest.raises(watchdog.WorkerLostError,
                               match="timeout"):
                watchdog.wait_cluster([p], timeout=0.5, poll=0.1)
        finally:
            if p.poll() is None:
                p.kill()
            p.wait()

    def test_wait_cluster_all_ok(self):
        procs = [subprocess.Popen([sys.executable, "-c", "pass"])
                 for _ in range(2)]
        assert watchdog.wait_cluster(procs, timeout=30) == [0, 0]

    def test_heartbeat_staleness(self, tmp_path):
        hb_dir = str(tmp_path)
        writer = watchdog.HeartbeatWriter(hb_dir, rank=1, interval=0.1)
        writer.beat()
        mon = watchdog.HeartbeatMonitor(hb_dir, ranks=[1], timeout=0.5,
                                        boot_grace=0.1)
        assert mon.check() is True
        # age the heartbeat past the timeout: rank declared lost
        stale_t = time.time() - 5.0
        os.utime(os.path.join(hb_dir, "hb-1"), (stale_t, stale_t))
        with pytest.raises(watchdog.WorkerLostError) as ei:
            mon.check()
        assert ei.value.ranks == (1,)

    def test_clean_shutdown_is_not_worker_loss(self, tmp_path):
        """A peer that STOPPED (done marker) is finished, not lost — a
        slower survivor must not be hard-exited for outliving it."""
        hb_dir = str(tmp_path)
        w = watchdog.HeartbeatWriter(hb_dir, rank=1,
                                     interval=0.05).start()
        mon = watchdog.HeartbeatMonitor(hb_dir, ranks=[1], timeout=0.3,
                                        boot_grace=0.1)
        assert mon.check() is True
        w.stop()  # clean shutdown writes hb-1.done
        time.sleep(0.6)  # well past the staleness timeout
        assert mon.check() is True

    def test_heartbeat_writer_keeps_beating(self, tmp_path):
        hb_dir = str(tmp_path)
        with watchdog.HeartbeatWriter(hb_dir, rank=0, interval=0.05):
            mon = watchdog.HeartbeatMonitor(hb_dir, ranks=[0],
                                            timeout=1.0)
            time.sleep(0.3)
            assert mon.check() is True


# ---------------------------------------------------------------------------
# executor-level site faults
# ---------------------------------------------------------------------------
class TestExecutorRetry:
    def test_transient_compile_failure_is_retried(self, monkeypatch):
        faults.set_fault_spec("compile_fail@times=1")
        main, startup, loss = _build_model()
        exe = fluid.Executor(fluid.CPUPlace())
        with scope_guard(Scope()):
            with warnings.catch_warnings(record=True) as caught:
                warnings.simplefilter("always")
                exe.run(startup)
                xb, yb = _make_batches(1)[0]
                (lv,) = exe.run(main, feed={"x": xb, "y": yb},
                                fetch_list=[loss])
        assert np.isfinite(np.asarray(lv)).all()
        assert any("retrying" in str(w.message) for w in caught)


# ---------------------------------------------------------------------------
# the chaos CLI — the ISSUE-2 acceptance scenario end to end
# ---------------------------------------------------------------------------
class TestChaosCLI:
    def test_acceptance_scenario_recovers(self, tmp_path):
        """NaN-grad @3 (skipped), transient ckpt-write failure @5
        (retried), worker kill @7 (restart + auto-resume): final params
        must match the fault-free trajectory bit-for-bit."""
        env = dict(os.environ)
        env.update({"JAX_PLATFORMS": "cpu", "PYTHONPATH": REPO})
        env.pop("PADDLE_TPU_FAULT_SPEC", None)
        res = subprocess.run(
            [sys.executable, "-m", "paddle_tpu.tools.chaos",
             "--steps", "9", "--ckpt-dir", str(tmp_path / "ckpt"),
             "--spec",
             "nan_grad@step=3;ckpt_write_fail@step=5;worker_kill@step=7"],
            capture_output=True, text=True, timeout=300, env=env,
            cwd=REPO)
        assert res.returncode == 0, res.stdout[-3000:] + res.stderr[-800:]
        assert "chaos: PASS" in res.stdout
        assert "skipped steps=[3]" in res.stdout
        assert "resumes=[7]" in res.stdout

    def test_hang_is_bounded_and_recovered(self, tmp_path):
        """An injected hang trips the per-incarnation timeout; the
        restarted worker resumes and finishes."""
        env = dict(os.environ)
        env.update({"JAX_PLATFORMS": "cpu", "PYTHONPATH": REPO})
        env.pop("PADDLE_TPU_FAULT_SPEC", None)
        res = subprocess.run(
            [sys.executable, "-m", "paddle_tpu.tools.chaos",
             "--steps", "5", "--ckpt-dir", str(tmp_path / "ckpt"),
             "--worker-timeout", "15",
             "--spec", "worker_hang@step=2,secs=600"],
            capture_output=True, text=True, timeout=300, env=env,
            cwd=REPO)
        assert res.returncode == 0, res.stdout[-3000:] + res.stderr[-800:]
        assert "rc=timeout" in res.stdout
        assert "chaos: PASS" in res.stdout
