"""Public tensor-parallel API: ParamAttr(shard_spec=...) +
BuildStrategy.tensor_parallel_degree (SURVEY §2.3 TP row — beyond the
reference, which has no TP; Megatron-style column/row parallel via GSPMD).

Oracle: TP=2 x DP=4 on the 8-device mesh reproduces single-device
per-step losses (the test_dist_base parity bar)."""

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.executor import Scope, scope_guard


def _model(lr=0.1, tp=False):
    fluid.unique_name.switch()

    def spec(s):
        return s if tp else None

    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 21
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[12], dtype="float32")
        y = fluid.layers.data("y", shape=[1], dtype="int64")
        # Megatron pair: column-parallel fc1 (+sharded bias), row-parallel
        # fc2 (partial sums all-reduced by GSPMD), replicated head
        h = fluid.layers.fc(
            x, size=32, act="relu",
            param_attr=fluid.ParamAttr(
                name="fc1.w", shard_spec=spec([None, "model"])),
            bias_attr=fluid.ParamAttr(
                name="fc1.b", shard_spec=spec(["model"])),
        )
        h2 = fluid.layers.fc(
            h, size=16, act="relu",
            param_attr=fluid.ParamAttr(
                name="fc2.w", shard_spec=spec(["model", None])),
            bias_attr=fluid.ParamAttr(name="fc2.b"),
        )
        logits = fluid.layers.fc(h2, size=3,
                                 param_attr=fluid.ParamAttr(name="head.w"))
        loss = fluid.layers.mean(
            fluid.layers.softmax_with_cross_entropy(logits, y))
        fluid.optimizer.Adam(learning_rate=lr).minimize(loss)
    return main, startup, loss


def _batches(n, bs=32):
    rng = np.random.RandomState(4)
    W = rng.randn(12, 3)
    out = []
    for _ in range(n):
        xv = rng.randn(bs, 12).astype("float32")
        yv = np.argmax(xv @ W, axis=1)[:, None].astype("int64")
        out.append({"x": xv, "y": yv})
    return out


def _train(tp_degree=1, n_steps=6):
    main, startup, loss = _model(tp=tp_degree > 1)
    exe = fluid.Executor(fluid.CPUPlace())
    losses = []
    scope = Scope()
    with scope_guard(scope):
        exe.run(startup)
        prog = main
        if tp_degree > 1:
            bs = fluid.BuildStrategy()
            bs.tensor_parallel_degree = tp_degree
            prog = fluid.CompiledProgram(main).with_data_parallel(
                loss_name=loss.name, build_strategy=bs)
        for feed in _batches(n_steps):
            (l,) = exe.run(prog, feed=feed, fetch_list=[loss])
            losses.append(float(np.asarray(l).reshape(())))
        w1 = scope.get("fc1.w")
    return losses, w1


class TestTensorParallel:
    def test_tp2_dp4_matches_single(self):
        single, _ = _train(tp_degree=1)
        tp, w1 = _train(tp_degree=2)
        np.testing.assert_allclose(tp, single, rtol=3e-4, atol=3e-4)
        assert single[-1] < single[0]
        # fc1.w really is column-sharded over the model axis
        spec = w1.sharding.spec
        assert tuple(spec) == (None, "model"), spec
        assert w1.addressable_shards[0].data.shape == (12, 16)

    def test_bad_shard_spec_falls_back_replicated(self):
        fluid.unique_name.switch()
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.layers.data("x", shape=[5], dtype="float32")
            # 5 is not divisible by the model axis (2)
            h = fluid.layers.fc(
                x, size=5, bias_attr=False,
                param_attr=fluid.ParamAttr(
                    name="odd.w", shard_spec=[None, "model"]))
            loss = fluid.layers.mean(h)
            fluid.optimizer.SGD(0.1).minimize(loss)
        bs = fluid.BuildStrategy()
        bs.tensor_parallel_degree = 2
        prog = fluid.CompiledProgram(main).with_data_parallel(
            loss_name=loss.name, build_strategy=bs)
        exe = fluid.Executor(fluid.CPUPlace())
        with scope_guard(Scope()):
            exe.run(startup)
            with pytest.warns(UserWarning, match="replicating"):
                (l,) = exe.run(
                    prog,
                    feed={"x": np.ones((8, 5), "float32")},
                    fetch_list=[loss])
            assert np.isfinite(l).all()

    def test_accumulator_inherits_shard_spec(self):
        main, startup, _ = _model(tp=True)
        moments = [
            v for v in main.global_block().vars.values()
            if "fc1.w_adam_moment" in v.name
        ]
        assert len(moments) == 2
        for m in moments:
            assert tuple(m.shard_spec) == (None, "model")
