"""Async-SGD (the reference's ``sync_mode=False`` PS mode:
``communicator.h:160-179`` barrier-free grad push / param pull), redesigned
as staleness-1 delayed gradient exchange (``transpiler/collective.py``
AsyncSGD), plus DC-ASGD delay compensation
(``DistributeTranspilerConfig.enable_dc_asgd``).

Oracles:
1. executor-level GSPMD run must match an exact numpy simulation of
   delayed-gradient SGD: w_{t+1} = w_t - lr * g_{t-1} (g_{-1} = 0).
2. shard_map 2-worker run: the head collective must average the PREVIOUS
   step's per-worker gradients (real psum), while each worker's buffer
   takes its fresh local gradient.
3. DC-ASGD: applied grad = stale + lambda * stale^2 * (w - w_snap),
   verified against the same simulation with compensation.
"""

import numpy as np

import paddle_tpu as fluid
from paddle_tpu.executor import Scope, scope_guard
from paddle_tpu.transpiler.collective import (ASYNC_TOY_W0,
                                              build_toy_async_program)

LR = 0.1
W0 = np.array(ASYNC_TOY_W0, dtype="float32")


def _build(dc_asgd=False, nranks=2):
    main, startup, loss, _w0 = build_toy_async_program(
        dc_asgd=dc_asgd, nranks=nranks, lr=LR)
    return main, startup, loss


def _np_grad(w, x):
    return (w - x) / 2.0  # d/dw mean((w-x)^2)


class TestDelayedGradParityUnderGSPMD:
    """Under GSPMD the collective is identity, so the transpiled program
    must be exactly delayed-gradient SGD."""

    def _run(self, dc_asgd):
        main, startup, loss = _build(dc_asgd=dc_asgd)
        xs = [np.linspace(i, i + 3, 4).astype("float32")
              for i in range(6)]
        scope = Scope()
        with scope_guard(scope):
            exe = fluid.Executor(fluid.TPUPlace())
            exe.run(startup)
            ws = []
            for x in xs:
                exe.run(main, feed={"x": x}, fetch_list=[])
                ws.append(np.array(scope.find_var("w").get_tensor()))
        return xs, ws

    def test_plain_async(self):
        xs, ws = self._run(dc_asgd=False)
        w, buf = W0.copy(), np.zeros(4, "float32")
        for x, w_got in zip(xs, ws):
            g = _np_grad(w, x)
            w = w - LR * buf      # optimizer consumes the STALE grad
            buf = g               # buffer takes the fresh local grad
            np.testing.assert_allclose(w_got, w, rtol=1e-6, atol=1e-6)
        # staleness sanity: the first step must not move the params
        np.testing.assert_allclose(ws[0], W0)
        assert not np.allclose(ws[1], W0)

    def test_dc_asgd_compensation(self):
        xs, ws = self._run(dc_asgd=True)
        lam = 0.04
        w, buf, snap = W0.copy(), np.zeros(4, "float32"), W0.copy()
        for x, w_got in zip(xs, ws):
            stale = buf + lam * buf * buf * (w - snap)
            snap = w.copy()       # snapshot BEFORE this step's update
            g = _np_grad(w, x)
            w = w - LR * stale
            buf = g
            np.testing.assert_allclose(w_got, w, rtol=1e-6, atol=1e-6)


class TestCrossWorkerAverageUnderPsum:
    def test_two_workers(self):
        import jax

        from paddle_tpu.transpiler.collective import async_two_worker_probe

        w0, x_w, buf_w, w_out, buf_out = async_two_worker_probe(
            jax.devices(), lr=LR)

        # both workers applied the MEAN of the buffered grads (psum/2)
        expect_w = w0 - LR * buf_w.mean(axis=0)
        np.testing.assert_allclose(w_out[0], expect_w, rtol=1e-6)
        np.testing.assert_allclose(w_out[1], expect_w, rtol=1e-6)
        # each buffer took its own fresh local gradient
        for r in range(2):
            np.testing.assert_allclose(
                buf_out[r], _np_grad(w0, x_w[r]), rtol=1e-6)


class TestTranspilerWiring:
    def test_sync_mode_false_routes_to_async(self):
        fluid.unique_name.switch()
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            w = fluid.layers.create_parameter([4], "float32", name="w")
            x = fluid.layers.data(name="x", shape=[4],
                                  append_batch_size=False)
            d = fluid.layers.elementwise_sub(w, x)
            loss = fluid.layers.reduce_mean(
                fluid.layers.elementwise_mul(d, d))
            fluid.optimizer.SGD(learning_rate=LR).minimize(loss)
        cfg = fluid.DistributeTranspilerConfig()
        cfg.sync_mode = False
        t = fluid.DistributeTranspiler(config=cfg)
        t.transpile(trainer_id=0, program=main, startup_program=startup,
                    trainers=2)
        types = [op.type for op in main.global_block().ops]
        assert "c_allreduce_sum" in types
        assert any(v.endswith("@ASYNC_BUF")
                   for v in main.global_block().vars)

    def test_fleet_ps_async_routes_to_async(self):
        """The fleet PS façade must transpile sync_mode=False the same
        way DistributeTranspiler does (no silent sync divergence)."""
        from paddle_tpu.incubate.fleet.base.role_maker import (
            Role, UserDefinedRoleMaker)
        from paddle_tpu.incubate.fleet.parameter_server. \
            distribute_transpiler import fleet

        fluid.unique_name.switch()
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            w = fluid.layers.create_parameter([4], "float32", name="w")
            x = fluid.layers.data(name="x", shape=[4],
                                  append_batch_size=False)
            d = fluid.layers.elementwise_sub(w, x)
            loss = fluid.layers.reduce_mean(
                fluid.layers.elementwise_mul(d, d))
            opt = fluid.optimizer.SGD(learning_rate=LR)
            fleet.init(UserDefinedRoleMaker(
                current_id=0, role=Role.WORKER, worker_num=2,
                server_endpoints=["127.0.0.1:0"]))
            cfg = fluid.DistributeTranspilerConfig()
            cfg.sync_mode = False
            opt = fleet.distributed_optimizer(opt, cfg)
            opt.minimize(loss, startup_program=startup)
        assert any(v.endswith("@ASYNC_BUF")
                   for v in fleet.main_program.global_block().vars)
