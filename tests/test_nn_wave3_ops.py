"""Numpy/dynamic-programming oracles for the third-wave surface: CRF,
CTC, edit distance, RNN cells, sampled-softmax family, sequence extras,
3-D conv/pool, CTR helpers."""

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.executor import Scope, scope_guard
from test_nn_extra_ops import run_layer, _data


# ---------------- CRF ----------------

def _np_crf_nll(em, trans, lab, lens):
    """Brute-force CRF NLL oracle (enumerate paths)."""
    import itertools

    B, T, D = em.shape
    w_start, w_end, w = trans[0], trans[1], trans[2:]
    out = np.zeros((B, 1), "float64")
    for b in range(B):
        L = int(lens[b])
        def score(path):
            s = w_start[path[0]] + em[b, 0, path[0]]
            for t in range(1, L):
                s += w[path[t - 1], path[t]] + em[b, t, path[t]]
            return s + w_end[path[-1]]
        logz = np.logaddexp.reduce(
            [score(p) for p in itertools.product(range(D), repeat=L)])
        out[b, 0] = logz - score([lab[b, t] for t in range(L)])
    return out


def test_linear_chain_crf_matches_bruteforce():
    rng = np.random.RandomState(0)
    B, T, D = 3, 4, 3
    em = rng.randn(B, T, D).astype("float32")
    lab = rng.randint(0, D, (B, T)).astype("int64")
    lens = np.array([4, 2, 3], "int64")
    trans = rng.randn(D + 2, D).astype("float32") * 0.5

    def build():
        return fluid.layers.linear_chain_crf(
            _data("em", em, False), _data("lab", lab),
            param_attr=fluid.ParamAttr(
                name="crf.w",
                initializer=fluid.initializer.NumpyArrayInitializer(trans)),
            length=_data("len", lens))

    got = run_layer(build, {"em": em, "lab": lab, "len": lens})
    exp = _np_crf_nll(em, trans, lab, lens)
    np.testing.assert_allclose(got, exp, rtol=1e-4, atol=1e-4)


def test_crf_decoding_matches_bruteforce():
    rng = np.random.RandomState(1)
    B, T, D = 3, 4, 3
    em = rng.randn(B, T, D).astype("float32")
    lens = np.array([4, 3, 2], "int64")
    trans = rng.randn(D + 2, D).astype("float32") * 0.5

    def build():
        attr = fluid.ParamAttr(
            name="crfd.w",
            initializer=fluid.initializer.NumpyArrayInitializer(trans))
        # create the transition param via the crf layer-helper mechanism
        fluid.layers.linear_chain_crf(
            _data("em", em, False),
            _data("lab", np.zeros((B, T), "int64")),
            param_attr=attr, length=_data("len", lens))
        return fluid.layers.crf_decoding(
            _data("em2", em), attr, length=_data("len2", lens))

    got = run_layer(build, {"em": em, "em2": em, "len": lens, "len2": lens,
                            "lab": np.zeros((B, T), "int64")})
    import itertools
    w_start, w_end, w = trans[0], trans[1], trans[2:]
    for b in range(B):
        L = int(lens[b])
        best, best_s = None, -1e30
        for p in itertools.product(range(D), repeat=L):
            s = w_start[p[0]] + em[b, 0, p[0]]
            for t in range(1, L):
                s += w[p[t - 1], p[t]] + em[b, t, p[t]]
            s += w_end[p[-1]]
            if s > best_s:
                best, best_s = p, s
        np.testing.assert_array_equal(got[b, :L], best)
        assert (got[b, L:] == 0).all()


def test_chunk_eval_iob():
    # types: 2; IOB tags: B0=0 I0=1 B1=2 I1=3
    inf = np.array([[0, 1, 2, 3, 0]], "int64")
    lab = np.array([[0, 1, 2, 2, 0]], "int64")
    lens = np.array([5], "int64")
    p, r, f1, ni, nl, nc = run_layer(
        lambda: fluid.layers.chunk_eval(
            _data("i", inf), _data("l", lab), "IOB", 2,
            seq_length=_data("sl", lens)),
        {"i": inf, "l": lab, "sl": lens}, n_out=6)
    # inferred chunks: [0-1]:t0, [2-3]:t1, [4]:t0  -> 3
    # label chunks:    [0-1]:t0, [2]:t1, [3]:t1(B again), [4]:t0 -> 4
    # correct: [0-1] t0 and [4] t0 -> 2
    assert int(ni[0]) == 3 and int(nl[0]) == 4 and int(nc[0]) == 2
    np.testing.assert_allclose(p, 2 / 3, rtol=1e-5)
    np.testing.assert_allclose(r, 2 / 4, rtol=1e-5)


# ---------------- CTC / edit distance ----------------

def test_edit_distance():
    hyp = np.array([[1, 2, 3, 0], [1, 1, 1, 1]], "int64")
    ref = np.array([[1, 3, 3], [2, 2, 2]], "int64")
    hl = np.array([3, 4], "int64")
    rl = np.array([3, 3], "int64")
    out, seq_num = run_layer(
        lambda: fluid.layers.edit_distance(
            _data("h", hyp), _data("r", ref), normalized=False,
            input_length=_data("hl", hl), label_length=_data("rl", rl)),
        {"h": hyp, "r": ref, "hl": hl, "rl": rl}, n_out=2)
    np.testing.assert_allclose(out, [[1.0], [4.0]])
    assert int(seq_num[0]) == 2


def test_ctc_greedy_decoder():
    # probs argmax path: [1,1,0,2,2,0] -> collapse -> [1,2]
    T, C = 6, 3
    path = [1, 1, 0, 2, 2, 0]
    probs = np.zeros((1, T, C), "float32")
    for t, c in enumerate(path):
        probs[0, t, c] = 1.0
    lens = np.array([6], "int64")
    out, out_len = run_layer(
        lambda: fluid.layers.ctc_greedy_decoder(
            _data("p", probs), blank=0, input_length=_data("l", lens)),
        {"p": probs, "l": lens}, n_out=2)
    assert int(out_len[0, 0]) == 2
    np.testing.assert_array_equal(out[0, :2], [1, 2])


def _np_ctc_nll(logits, labels, blank=0):
    """Forward-algorithm CTC oracle for one sequence (log domain)."""
    T, C = logits.shape
    lp = logits - np.log(np.exp(logits - logits.max(1, keepdims=True)).sum(
        1, keepdims=True)) - logits.max(1, keepdims=True) * 0  # log_softmax
    lp = logits - np.logaddexp.reduce(logits, axis=1, keepdims=True)
    L = len(labels)
    ext = [blank]
    for c in labels:
        ext += [c, blank]
    S = len(ext)
    NEG = -1e30
    a = np.full((S,), NEG)
    a[0] = lp[0, ext[0]]
    if S > 1:
        a[1] = lp[0, ext[1]]
    for t in range(1, T):
        na = np.full((S,), NEG)
        for s in range(S):
            best = a[s]
            if s >= 1:
                best = np.logaddexp(best, a[s - 1])
            if s >= 2 and ext[s] != blank and ext[s] != ext[s - 2]:
                best = np.logaddexp(best, a[s - 2])
            na[s] = best + lp[t, ext[s]]
        a = na
    return -np.logaddexp(a[S - 1], a[S - 2])


def test_warpctc_against_dp_oracle():
    rng = np.random.RandomState(2)
    B, T, C, L = 2, 5, 4, 2
    logits = rng.randn(B, T, C).astype("float32")
    labels = np.array([[1, 2], [3, 3]], "int64")
    tl = np.array([5, 4], "int64")
    ll = np.array([2, 2], "int64")
    got = run_layer(
        lambda: fluid.layers.warpctc(
            _data("x", logits, False), _data("y", labels),
            input_length=_data("tl", tl), label_length=_data("ll", ll)),
        {"x": logits, "y": labels, "tl": tl, "ll": ll})
    for b in range(B):
        exp = _np_ctc_nll(logits[b, : tl[b]].astype("float64"),
                          list(labels[b, : ll[b]]))
        np.testing.assert_allclose(got[b, 0], exp, rtol=1e-4)


def test_warpctc_trains():
    rng = np.random.RandomState(3)
    B, T, C, L = 4, 6, 5, 2
    fluid.unique_name.switch()
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[T, 8], dtype="float32",
                              append_batch_size=True)
        y = fluid.layers.data("y", shape=[L], dtype="int64")
        tl = fluid.layers.data("tl", shape=[], dtype="int64")
        ll = fluid.layers.data("ll", shape=[], dtype="int64")
        h = fluid.layers.fc(x, size=C, num_flatten_dims=2)
        loss = fluid.layers.mean(fluid.layers.warpctc(
            h, y, input_length=tl, label_length=ll))
        fluid.optimizer.Adam(5e-2).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    feed = {
        "x": rng.randn(B, T, 8).astype("float32"),
        "y": rng.randint(1, C, (B, L)).astype("int64"),
        "tl": np.full((B,), T, "int64"),
        "ll": np.full((B,), L, "int64"),
    }
    with scope_guard(Scope()):
        exe.run(startup)
        losses = [float(np.asarray(exe.run(main, feed=feed,
                                           fetch_list=[loss])[0]).reshape(()))
                  for _ in range(40)]
    assert losses[-1] < 0.5 * losses[0], (losses[0], losses[-1])


# ---------------- RNN cells ----------------

def test_lstm_unit_formula():
    rng = np.random.RandomState(4)
    B, D = 3, 4
    xh = rng.randn(B, 2 * D).astype("float32")
    c_prev = rng.randn(B, D).astype("float32")

    def build():
        h, c = fluid.layers.lstm_unit(
            _data("x", xh[:, :D], False), _data("h", xh[:, D:], False),
            _data("c", c_prev, False), forget_bias=1.0,
            param_attr=fluid.ParamAttr(
                name="lu.w", initializer=fluid.initializer.Constant(0.1)),
            bias_attr=fluid.ParamAttr(
                name="lu.b", initializer=fluid.initializer.Constant(0.0)))
        return h, c

    h, c = run_layer(build, {"x": xh[:, :D], "h": xh[:, D:], "c": c_prev},
                     n_out=2)

    def sig(v):
        return 1 / (1 + np.exp(-v))

    gates = np.concatenate([xh[:, :D], xh[:, D:]], 1) @ np.full(
        (2 * D, 4 * D), 0.1, "float32")
    i, f, o, g = np.split(gates, 4, axis=1)
    ce = sig(f + 1.0) * c_prev + sig(i) * np.tanh(g)
    he = sig(o) * np.tanh(ce)
    np.testing.assert_allclose(c, ce, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(h, he, rtol=1e-4, atol=1e-5)


def test_gru_unit_formula():
    rng = np.random.RandomState(5)
    B, D = 2, 3
    inp = rng.randn(B, 3 * D).astype("float32")
    hp = rng.randn(B, D).astype("float32")
    w = rng.randn(D, 3 * D).astype("float32") * 0.3

    def build():
        hid, rhp, gate = fluid.layers.gru_unit(
            _data("i", inp, False), _data("h", hp, False), 3 * D,
            param_attr=fluid.ParamAttr(
                name="gu.w",
                initializer=fluid.initializer.NumpyArrayInitializer(w)),
            bias_attr=False)
        return hid

    got = run_layer(build, {"i": inp, "h": hp})

    def sig(v):
        return 1 / (1 + np.exp(-v))

    ur = sig(inp[:, :2 * D] + hp @ w[:, :2 * D])
    u, r = ur[:, :D], ur[:, D:]
    c = np.tanh(inp[:, 2 * D:] + (r * hp) @ w[:, 2 * D:])
    he = u * c + (1 - u) * hp
    np.testing.assert_allclose(got, he, rtol=1e-4, atol=1e-5)


def test_dynamic_lstmp_shapes_and_mask():
    rng = np.random.RandomState(6)
    B, T, D, P = 2, 5, 4, 3
    x = rng.randn(B, T, 4 * D).astype("float32")
    lens = np.array([5, 3], "int64")

    def build():
        proj, cell = fluid.layers.dynamic_lstmp(
            _data("x", x, False), 4 * D, P,
            param_attr=fluid.ParamAttr(name="lp.w"),
            bias_attr=fluid.ParamAttr(name="lp.b"),
            seq_len=_data("sl", lens))
        return proj, cell

    proj, cell = run_layer(build, {"x": x, "sl": lens}, n_out=2)
    assert proj.shape == (B, T, P) and cell.shape == (B, T, D)
    # masked steps carry the last state forward
    np.testing.assert_allclose(proj[1, 3], proj[1, 2], rtol=1e-6)
    np.testing.assert_allclose(proj[1, 4], proj[1, 2], rtol=1e-6)


# ---------------- sampled softmax family ----------------

def test_nce_and_hsigmoid_and_sampled_softmax_train():
    rng = np.random.RandomState(7)
    B, D, N = 8, 6, 16
    fluid.unique_name.switch()
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[D], dtype="float32")
        y = fluid.layers.data("y", shape=[1], dtype="int64")
        h = fluid.layers.fc(x, size=D, act="tanh")
        c_nce = fluid.layers.mean(fluid.layers.nce(
            h, y, num_total_classes=N, num_neg_samples=4))
        c_hs = fluid.layers.mean(fluid.layers.hsigmoid(h, y, N))
        logits = fluid.layers.fc(h, size=N)
        c_ss = fluid.layers.mean(
            fluid.layers.sampled_softmax_with_cross_entropy(
                logits, y, num_samples=5))
        loss = c_nce + c_hs + c_ss
        fluid.optimizer.Adam(5e-2).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    xv = rng.randn(B, D).astype("float32")
    yv = rng.randint(0, N, (B, 1)).astype("int64")
    with scope_guard(Scope()):
        exe.run(startup)
        losses = [float(np.asarray(exe.run(main, feed={"x": xv, "y": yv},
                                           fetch_list=[loss])[0]).reshape(()))
                  for _ in range(60)]
    assert losses[-1] < 0.8 * losses[0], (losses[0], losses[-1])


# ---------------- sequence extras ----------------

def test_sequence_conv_oracle():
    rng = np.random.RandomState(8)
    B, T, D, M = 2, 4, 3, 5
    x = rng.randn(B, T, D).astype("float32")
    lens = np.array([4, 2], "int64")
    w = rng.randn(3 * D, M).astype("float32")

    def build():
        return fluid.layers.sequence_conv(
            _data("x", x, False), M, filter_size=3,
            param_attr=fluid.ParamAttr(
                name="sc.w",
                initializer=fluid.initializer.NumpyArrayInitializer(w)),
            bias_attr=False, seq_len=_data("sl", lens))

    got = run_layer(build, {"x": x, "sl": lens})
    xm = x.copy()
    xm[1, 2:] = 0.0  # beyond length
    exp = np.zeros((B, T, M), "float32")
    for t in range(T):
        ctx_rows = []
        for off in (-1, 0, 1):
            tt = t + off
            ctx_rows.append(xm[:, tt] if 0 <= tt < T
                            else np.zeros((B, D), "float32"))
        exp[:, t] = np.concatenate(ctx_rows, 1) @ w
    np.testing.assert_allclose(got, exp, rtol=1e-4, atol=1e-5)


def test_sequence_reshape_expand_as_scatter():
    rng = np.random.RandomState(9)
    x = rng.randn(2, 4, 6).astype("float32")
    got = run_layer(
        lambda: fluid.layers.sequence_reshape(_data("x", x), 3), {"x": x})
    np.testing.assert_allclose(got, x.reshape(2, 8, 3))

    v = rng.randn(2, 3).astype("float32")
    ref = np.zeros((2, 4, 1), "float32")
    lens = np.array([4, 2], "int64")
    got = run_layer(
        lambda: fluid.layers.sequence_expand_as(
            _data("v", v), _data("r", ref), ref_len=_data("l", lens)),
        {"v": v, "r": ref, "l": lens})
    assert got.shape == (2, 4, 3)
    np.testing.assert_allclose(got[0, 3], v[0])
    np.testing.assert_allclose(got[1, 2:], 0.0)

    base = np.zeros((2, 6), "float32")
    ids = np.array([[0, 2, 2], [5, 0, 0]], "int64")
    upd = np.array([[1., 2., 3.], [4., 5., 6.]], "float32")
    sl = np.array([3, 1], "int64")
    got = run_layer(
        lambda: fluid.layers.sequence_scatter(
            _data("b", base), _data("i", ids), _data("u", upd),
            seq_len=_data("sl", sl)),
        {"b": base, "i": ids, "u": upd, "sl": sl})
    exp = np.zeros((2, 6), "float32")
    exp[0, 0], exp[0, 2] = 1.0, 5.0
    exp[1, 5] = 4.0
    np.testing.assert_allclose(got, exp)


# ---------------- 3-D conv/pool, CTR ----------------

def test_conv3d_pool3d_run_and_shapes():
    rng = np.random.RandomState(10)
    x = rng.randn(2, 3, 4, 6, 6).astype("float32")

    def build():
        c = fluid.layers.conv3d(_data("x", x, False), num_filters=4,
                                filter_size=3, padding=1)
        p = fluid.layers.pool3d(c, pool_size=2, pool_stride=2,
                                pool_type="avg")
        a = fluid.layers.adaptive_pool3d(p, pool_size=1, pool_type="avg")
        return c, p, a

    c, p, a = run_layer(build, {"x": x}, n_out=3)
    assert c.shape == (2, 4, 4, 6, 6)
    assert p.shape == (2, 4, 2, 3, 3)
    assert a.shape == (2, 4, 1, 1, 1)
    got = run_layer(
        lambda: fluid.layers.adaptive_pool2d(
            _data("y", x[:, :, 0]), pool_size=[2, 3], pool_type="avg"),
        {"y": x[:, :, 0]})
    assert got.shape == (2, 3, 2, 3)


def test_conv3d_transpose_shape():
    rng = np.random.RandomState(11)
    x = rng.randn(1, 2, 3, 3, 3).astype("float32")
    got = run_layer(
        lambda: fluid.layers.conv3d_transpose(
            _data("x", x, False), num_filters=4, filter_size=2, stride=2),
        {"x": x})
    assert got.shape == (1, 4, 6, 6, 6)


def test_cvm_and_selected_rows_shims():
    x = np.array([[3.0, 1.0, 0.5, 0.6]], "float32")
    cvm = np.zeros((1, 2), "float32")
    got = run_layer(
        lambda: fluid.layers.continuous_value_model(
            _data("x", x), _data("c", cvm)), {"x": x, "c": cvm})
    np.testing.assert_allclose(got[0, 0], np.log(4.0), rtol=1e-5)
    np.testing.assert_allclose(got[0, 1], np.log(2.0) - np.log(4.0),
                               rtol=1e-5)
    np.testing.assert_allclose(got[0, 2:], x[0, 2:])

    got = run_layer(
        lambda: fluid.layers.get_tensor_from_selected_rows(_data("x", x)),
        {"x": x})
    np.testing.assert_allclose(got, x)
    got = run_layer(
        lambda: fluid.layers.merge_selected_rows(_data("x", x)), {"x": x})
    np.testing.assert_allclose(got, x)


def test_py_func_host_callback():
    x = np.arange(6, dtype="float32").reshape(2, 3)

    def host_fn(a):
        return (np.asarray(a) * 2.0).astype("float32")

    def build():
        xin = _data("x", x)
        out = fluid.default_main_program().current_block().create_var(
            name="pyfunc.out", shape=[2, 3], dtype="float32")
        fluid.layers.py_func(host_fn, xin, out)
        return out

    got = run_layer(build, {"x": x})
    np.testing.assert_allclose(got, x * 2.0)


def test_tree_conv_and_similarity_focus_run():
    rng = np.random.RandomState(12)
    nodes = rng.randn(2, 5, 4).astype("float32")
    edges = np.array([[[0, 1], [0, 2], [1, 3]],
                      [[0, 1], [1, 2], [2, 3]]], "int64")
    got = run_layer(
        lambda: fluid.layers.tree_conv(
            _data("n", nodes, False), _data("e", edges), output_size=6),
        {"n": nodes, "e": edges})
    assert got.shape == (2, 5, 6) and np.isfinite(got).all()

    x = rng.randn(2, 3, 4, 4).astype("float32")
    got = run_layer(
        lambda: fluid.layers.similarity_focus(_data("x", x), 1, [0]),
        {"x": x})
    assert got.shape == x.shape
    assert set(np.unique(got)).issubset({0.0, 1.0})


def test_conv2d_transpose_oracle_asymmetric_channels():
    """Regression: round-1 used spec IOHW which breaks (and would silently
    transpose channels) for C_in != C_out; oracle = explicit scatter."""
    rng = np.random.RandomState(13)
    x = rng.randn(1, 2, 3, 3).astype("float32")
    f = rng.randn(2, 4, 2, 2).astype("float32")

    def build():
        return fluid.layers.conv2d_transpose(
            _data("x", x, False), num_filters=4, filter_size=2, stride=2,
            param_attr=fluid.ParamAttr(
                name="ct.w",
                initializer=fluid.initializer.NumpyArrayInitializer(f)),
            bias_attr=False)

    got = run_layer(build, {"x": x})
    exp = np.zeros((1, 4, 6, 6), "float32")
    for ci in range(2):
        for co in range(4):
            for i in range(3):
                for j in range(3):
                    for ki in range(2):
                        for kj in range(2):
                            exp[0, co, i * 2 + ki, j * 2 + kj] += \
                                x[0, ci, i, j] * f[ci, co, ki, kj]
    np.testing.assert_allclose(got, exp, rtol=1e-4, atol=1e-5)


def test_deformable_conv_zero_offset_equals_conv():
    """With zero offsets and unit mask, deformable conv must equal plain
    conv2d (the reference's own degenerate-case identity)."""
    rng = np.random.RandomState(14)
    x = rng.randn(1, 2, 5, 5).astype("float32")
    f = rng.randn(3, 2, 3, 3).astype("float32")
    offset = np.zeros((1, 2 * 9, 5, 5), "float32")
    mask = np.ones((1, 9, 5, 5), "float32")

    def build_deform():
        return fluid.layers.deformable_conv(
            _data("x", x, False), _data("o", offset), _data("m", mask),
            num_filters=3, filter_size=3, padding=1,
            param_attr=fluid.ParamAttr(
                name="dc.w",
                initializer=fluid.initializer.NumpyArrayInitializer(f)),
            bias_attr=False)

    got = run_layer(build_deform, {"x": x, "o": offset, "m": mask})

    def build_plain():
        return fluid.layers.conv2d(
            _data("x", x, False), num_filters=3, filter_size=3, padding=1,
            param_attr=fluid.ParamAttr(
                name="pc.w",
                initializer=fluid.initializer.NumpyArrayInitializer(f)),
            bias_attr=False)

    exp = run_layer(build_plain, {"x": x})
    np.testing.assert_allclose(got, exp, rtol=1e-4, atol=1e-4)


def test_deformable_roi_pooling_runs():
    rng = np.random.RandomState(15)
    x = rng.randn(1, 4, 6, 6).astype("float32")  # out_c=1, ph=pw=2
    rois = np.array([[0, 0, 0, 5, 5]], "float32")
    trans = np.zeros((1, 2, 2, 2), "float32")
    got = run_layer(
        lambda: fluid.layers.deformable_roi_pooling(
            _data("x", x, False), _data("r", rois), _data("t", trans),
            pooled_height=2, pooled_width=2, sample_per_part=2),
        {"x": x, "r": rois, "t": trans})
    assert got.shape == (1, 1, 2, 2) and np.isfinite(got).all()


def test_deformable_conv_integer_offset_shifts():
    """1x1 kernel with offset (0, +1) samples the pixel to the right —
    catches y/x interleave layout mistakes (offsets are (y,x) pairs)."""
    rng = np.random.RandomState(16)
    x = rng.randn(1, 1, 4, 4).astype("float32")
    f = np.ones((1, 1, 1, 1), "float32")
    offset = np.zeros((1, 2, 4, 4), "float32")
    offset[0, 1] = 1.0  # x-offset = +1
    mask = np.ones((1, 1, 4, 4), "float32")
    got = run_layer(
        lambda: fluid.layers.deformable_conv(
            _data("x", x, False), _data("o", offset), _data("m", mask),
            num_filters=1, filter_size=1,
            param_attr=fluid.ParamAttr(
                name="dcs.w",
                initializer=fluid.initializer.NumpyArrayInitializer(f)),
            bias_attr=False),
        {"x": x, "o": offset, "m": mask})
    exp = np.zeros_like(x)
    exp[:, :, :, :-1] = x[:, :, :, 1:]  # shift left (sample right)
    np.testing.assert_allclose(got, exp, rtol=1e-4, atol=1e-5)


def test_lstm_layer_returns_final_states():
    rng = np.random.RandomState(17)
    B, T, D, H = 2, 5, 3, 4
    x = rng.randn(B, T, D).astype("float32")

    def build():
        out, lh, lc = fluid.layers.lstm(
            _data("x", x, False), None, None, T, H, num_layers=2,
            is_bidirec=True)
        return out, lh, lc

    out, lh, lc = run_layer(build, {"x": x}, n_out=3)
    assert out.shape == (B, T, 2 * H)
    assert lh.shape == (4, B, H) and lc.shape == (4, B, H)
    # forward-direction final hidden of the last layer == out's last step
    np.testing.assert_allclose(lh[2], out[:, -1, :H], rtol=1e-5)


def test_edit_distance_ignored_tokens():
    hyp = np.array([[1, 0, 2, 3]], "int64")
    ref = np.array([[1, 3, 3]], "int64")
    out, _ = run_layer(
        lambda: fluid.layers.edit_distance(
            _data("h", hyp), _data("r", ref), normalized=False,
            ignored_tokens=[0],
            input_length=_data("hl", np.array([4], "int64")),
            label_length=_data("rl", np.array([3], "int64"))),
        {"h": hyp, "r": ref, "hl": np.array([4], "int64"),
         "rl": np.array([3], "int64")}, n_out=2)
    # hyp filtered -> [1,2,3]; distance([1,2,3],[1,3,3]) = 1
    np.testing.assert_allclose(out, [[1.0]])
