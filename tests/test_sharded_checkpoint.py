"""Multi-host-style sharded checkpoint: ``is_distributed`` tables (and
their table-shaped Adam moments) save per-shard with no full-table host
gather, and load resumes training with exact loss continuity.

Reference parity: ``python/paddle/fluid/io.py:294``
``_save_distributed_persistables`` (pserver-sliced vars re-assembled on
save); TPU-native inversion: shards stay shards on disk, reassembly
happens lazily per device region on load."""

import os

import numpy as np

import paddle_tpu as fluid
from paddle_tpu.executor import Scope, scope_guard
from paddle_tpu.models import ctr

VOCAB = 4096
N_SLOTS, SLOT_LEN, DENSE = 2, 5, 8


def _build(lr=0.05):
    fluid.unique_name.switch()
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 11
    with fluid.program_guard(main, startup):
        slots = [
            fluid.layers.data("slot%d" % i, shape=[SLOT_LEN], dtype="int64")
            for i in range(N_SLOTS)
        ]
        dense = fluid.layers.data("dense", shape=[DENSE], dtype="float32")
        label = fluid.layers.data("label", shape=[1], dtype="int64")
        loss, prob = ctr.wide_deep(
            slots, dense, label, vocab=VOCAB, embed_dim=16,
            hidden=(32,), is_distributed=True)
        fluid.optimizer.Adam(learning_rate=lr).minimize(loss)
    return main, startup, loss


def _batches(n_steps, bs=32, seed=3):
    rng = np.random.RandomState(seed)
    out = []
    for _ in range(n_steps):
        feed = {
            "slot%d" % i: rng.randint(0, VOCAB, (bs, SLOT_LEN))
            .astype("int64") for i in range(N_SLOTS)
        }
        feed["dense"] = rng.randn(bs, DENSE).astype("float32")
        feed["label"] = rng.randint(0, 2, (bs, 1)).astype("int64")
        out.append(feed)
    return out


class TestShardedCheckpoint:
    def test_save_load_loss_continuity(self, tmp_path):
        ckpt = str(tmp_path / "ckpt")
        main, startup, loss = _build()
        exe = fluid.Executor(fluid.CPUPlace())
        batches = _batches(8)

        # phase 1: train 4 steps on the 8-way mesh, save, then record the
        # reference losses for steps 5-8 in the same run
        scope = Scope()
        with scope_guard(scope):
            exe.run(startup)
            prog = fluid.CompiledProgram(main).with_data_parallel(
                loss_name=loss.name)
            for feed in batches[:4]:
                exe.run(prog, feed=feed, fetch_list=[])
            fluid.io.save_persistables(exe, ckpt, main)
            expect = [
                float(np.asarray(
                    exe.run(prog, feed=feed, fetch_list=[loss])[0]
                ).reshape(()))
                for feed in batches[4:]
            ]

        # the table and its two moments live as per-shard files — 8 files
        # of VOCAB/8 rows each, never one full array
        shard_dir = os.path.join(ckpt, "deep_emb_0.shards")
        files = sorted(f for f in os.listdir(shard_dir)
                       if f.startswith("shard-"))
        assert len(files) == 8, files
        one = np.load(os.path.join(shard_dir, files[0]))
        assert one.shape == (VOCAB // 8, 16)
        moment_dirs = [d for d in os.listdir(ckpt)
                       if d.endswith(".shards") and "moment" in d
                       and "deep_emb_0" in d]
        assert len(moment_dirs) == 2, moment_dirs
        # dense params stay plain files (replicated, no shard split)
        assert any(f.endswith(".npy") and "fc_" in f
                   for f in os.listdir(ckpt))

        # phase 2: fresh scope, clobbered init, load, resume steps 5-8
        scope2 = Scope()
        with scope_guard(scope2):
            exe.run(startup)
            prog = fluid.CompiledProgram(main).with_data_parallel(
                loss_name=loss.name)
            # one step materializes the sharded layout before load (the
            # multi-host pattern: restore onto the live sharding)
            exe.run(prog, feed=batches[0], fetch_list=[])
            fluid.io.load_persistables(exe, ckpt, main)
            got = [
                float(np.asarray(
                    exe.run(prog, feed=feed, fetch_list=[loss])[0]
                ).reshape(()))
                for feed in batches[4:]
            ]
            table = scope2.get("deep_emb_0")
        np.testing.assert_allclose(got, expect, rtol=1e-5, atol=1e-6)
        # restored table kept its 8-way row sharding (no replication)
        assert len(table.sharding.device_set) == 8
        assert not table.is_fully_replicated

    def test_fresh_scope_load_without_live_sharding(self, tmp_path):
        """Single-device consumer of a sharded checkpoint: assembly
        fallback produces the full table."""
        ckpt = str(tmp_path / "ckpt2")
        main, startup, loss = _build()
        exe = fluid.Executor(fluid.CPUPlace())
        scope = Scope()
        with scope_guard(scope):
            exe.run(startup)
            prog = fluid.CompiledProgram(main).with_data_parallel(
                loss_name=loss.name)
            exe.run(prog, feed=_batches(1)[0], fetch_list=[])
            table_before = np.asarray(scope.get("deep_emb_0"))
            fluid.io.save_persistables(exe, ckpt, main)

        scope2 = Scope()
        with scope_guard(scope2):
            exe.run(startup)
            fluid.io.load_persistables(exe, ckpt, main)
            table_after = np.asarray(scope2.get("deep_emb_0"))
        np.testing.assert_allclose(table_after, table_before)

    def test_stale_shard_files_ignored_and_gaps_raise(self, tmp_path):
        """Load trusts meta.json's file list: stale files from an older
        save with a different layout are ignored, and a shard dir whose
        meta leaves gaps raises instead of zero-filling."""
        import json
        import pytest

        ckpt = str(tmp_path / "ckpt4")
        main, startup, loss = _build()
        exe = fluid.Executor(fluid.CPUPlace())
        scope = Scope()
        with scope_guard(scope):
            exe.run(startup)
            prog = fluid.CompiledProgram(main).with_data_parallel(
                loss_name=loss.name)
            exe.run(prog, feed=_batches(1)[0], fetch_list=[])
            table_before = np.asarray(scope.get("deep_emb_0"))
            fluid.io.save_persistables(exe, ckpt, main)

        shard_dir = os.path.join(ckpt, "deep_emb_0.shards")
        # a stale file from a hypothetical older 1-way save: covers the
        # whole table with garbage; must be ignored (not in meta files)
        np.save(os.path.join(shard_dir, "shard-0_%d-0_16.npy" % VOCAB),
                np.full((VOCAB, 16), 99.0, np.float32))
        scope2 = Scope()
        with scope_guard(scope2):
            exe.run(startup)
            fluid.io.load_persistables(exe, ckpt, main)
            np.testing.assert_allclose(
                np.asarray(scope2.get("deep_emb_0")), table_before)

        # corrupt meta: drop one real shard from the list → gap → raise
        meta_path = os.path.join(shard_dir, "meta.json")
        meta = json.load(open(meta_path))
        meta["files"] = meta["files"][1:]
        json.dump(meta, open(meta_path, "w"))
        scope3 = Scope()
        with scope_guard(scope3):
            exe.run(startup)
            with pytest.raises(RuntimeError, match="does not cover"):
                fluid.io.load_persistables(exe, ckpt, main)

    def test_combined_filename_skips_sharded(self, tmp_path):
        """filename= mode: sharded vars go to shard dirs, not the npz."""
        ckpt = str(tmp_path / "ckpt3")
        main, startup, loss = _build()
        exe = fluid.Executor(fluid.CPUPlace())
        scope = Scope()
        with scope_guard(scope):
            exe.run(startup)
            prog = fluid.CompiledProgram(main).with_data_parallel(
                loss_name=loss.name)
            exe.run(prog, feed=_batches(1)[0], fetch_list=[])
            fluid.io.save_persistables(exe, ckpt, main, filename="all")
            data = np.load(os.path.join(ckpt, "all.npz"))
            assert "deep_emb_0" not in data.files
            assert os.path.isdir(os.path.join(ckpt, "deep_emb_0.shards"))
            table_before = np.asarray(scope.get("deep_emb_0"))

        scope2 = Scope()
        with scope_guard(scope2):
            exe.run(startup)
            fluid.io.load_persistables(exe, ckpt, main, filename="all")
            np.testing.assert_allclose(
                np.asarray(scope2.get("deep_emb_0")), table_before)

    def test_tp_sharded_param_checkpoint(self, tmp_path):
        """Column-sharded (tensor-parallel) params are non-replicated jax
        arrays too — they must shard-save and reshard-on-load through the
        same path as row-sharded tables (2-D bounds)."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        import paddle_tpu.io as fio

        mesh = Mesh(np.array(jax.devices()[:8]).reshape(4, 2),
                    ("data", "model"))
        w = jnp.arange(64 * 32, dtype=jnp.float32).reshape(64, 32)
        sharded = jax.device_put(
            w, NamedSharding(mesh, P(None, "model")))

        ckpt = str(tmp_path / "tp_ckpt")
        os.makedirs(ckpt, exist_ok=True)
        fio._save_sharded(ckpt, "tp_w", sharded)
        shard_dir = os.path.join(ckpt, "tp_w.shards")
        files = [f for f in os.listdir(shard_dir)
                 if f.startswith("shard-")]
        # 2-way model sharding → 2 distinct column shards (replicas over
        # the data axis write once)
        assert len(files) == 2, files
        one = np.load(os.path.join(shard_dir, files[0]))
        assert one.shape == (64, 16)

        # load back onto the live sharding: per-device regions only
        restored = fio._load_sharded(shard_dir, sharded, "tp_w")
        np.testing.assert_allclose(np.asarray(restored), np.asarray(w))
        assert restored.sharding.spec == P(None, "model")
        # and the host-assembly fallback for an unsharded consumer
        full = fio._load_sharded(shard_dir, None, "tp_w")
        np.testing.assert_allclose(np.asarray(full), np.asarray(w))
