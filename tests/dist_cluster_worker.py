"""Subprocess trainer for the multi-process cluster parity test
(reference: ``unittests/test_dist_base.py:317`` runtime_main — trainers
driven by PADDLE_* env vars, printing per-step losses for the parent to
compare against the single-process oracle).

Each of the 2 processes owns 4 virtual CPU devices (a fake 2-host × 4-chip
cluster); the REAL user API is driven end to end:
fleet.init → fleet.distributed_optimizer(...).minimize →
CompiledProgram.with_data_parallel → exe.run with the process-local half
batch.
"""

import os
import sys

os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=4")

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_cpu_collectives_implementation", "gloo")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

import paddle_tpu as fluid  # noqa: E402
from paddle_tpu.incubate.fleet.base import role_maker  # noqa: E402
from paddle_tpu.incubate.fleet.collective import fleet  # noqa: E402
from tests.dist_model import build_model, make_batches  # noqa: E402


def main():
    fleet.init(role_maker.PaddleCloudRoleMaker())
    rank = fleet.worker_index()
    assert fleet.worker_num() == 2
    assert jax.process_count() == 2
    assert jax.device_count() == 8, jax.devices()

    # opt-in liveness watchdog (PADDLE_TPU_HEARTBEAT_DIR): a dead peer
    # turns into a prompt visible exit instead of a gloo hang — see
    # resilience/watchdog.py and dist_resilient_worker.py
    writer = monitor = None
    hb_dir = os.environ.get("PADDLE_TPU_HEARTBEAT_DIR")
    if hb_dir:
        from paddle_tpu.resilience import watchdog

        writer = watchdog.HeartbeatWriter(hb_dir, rank,
                                          interval=0.2).start()
        monitor = watchdog.HeartbeatMonitor(
            hb_dir, [r for r in range(fleet.worker_num()) if r != rank],
            timeout=float(os.environ.get(
                "PADDLE_TPU_HEARTBEAT_TIMEOUT_S", "10")),
            interval=0.2).start()

    main_prog, startup, loss, feeds = build_model(
        optimizer_factory=lambda opt: fleet.distributed_optimizer(opt))

    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    cp = fluid.CompiledProgram(main_prog).with_data_parallel(
        loss_name=loss.name)

    losses = []
    for xb, yb in make_batches():
        # this process feeds its HALF of the global batch
        half = slice(rank * (len(xb) // 2), (rank + 1) * (len(xb) // 2))
        (lv,) = exe.run(cp, feed={feeds[0]: xb[half], feeds[1]: yb[half]},
                        fetch_list=[loss])
        losses.append(float(np.asarray(lv).reshape(())))
    print("CLUSTER_LOSSES rank=%d %s"
          % (rank, ",".join("%.8f" % v for v in losses)))
    print("CLUSTER_OK rank=%d" % rank)
    if monitor is not None:
        monitor.stop()
    if writer is not None:
        writer.stop()


if __name__ == "__main__":
    main()
