"""Real DGC (deep gradient compression) primitive: sparsity-0 equals the
dense mean-allreduce, error feedback preserves convergence on a toy
problem, and the exchanged tensor is actually k-sparse."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from paddle_tpu.jax_compat import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from paddle_tpu.parallel import dgc_exchange, dgc_momentum_step

N = 8


@pytest.fixture(scope="module")
def mesh():
    return Mesh(np.array(jax.devices()[:N]), ("data",))


class TestDGC:
    def test_sparsity_zero_is_dense_mean(self, mesh):
        rng = np.random.RandomState(0)
        g = jnp.asarray(rng.randn(N, 64).astype("float32"))

        def f(g):
            z = jnp.zeros_like(g[0] if g.ndim > 1 else g)
            # momentum_coef=0: exchange reduces to plain mean-allreduce
            ex, r, m = dgc_exchange(g.reshape(64), z.reshape(64),
                                    z.reshape(64), "data", sparsity=0.0,
                                    momentum_coef=0.0)
            return ex

        out = jax.jit(shard_map(f, mesh=mesh, in_specs=P("data"),
                                out_specs=P()))(g.reshape(N * 64))
        np.testing.assert_allclose(np.asarray(out),
                                   np.asarray(g).reshape(N, 64).mean(0),
                                   rtol=1e-6)

    def test_exchanged_is_sparse_and_residual_holds_rest(self, mesh):
        rng = np.random.RandomState(1)
        g = jnp.asarray(rng.randn(N * 128).astype("float32"))

        def f(g):
            z = jnp.zeros_like(g)
            ex, r, m = dgc_exchange(g, z, z, "data", sparsity=0.9,
                                    momentum_coef=0.0)
            return ex, r

        ex, r = jax.jit(shard_map(
            f, mesh=mesh, in_specs=P("data"),
            out_specs=(P(), P("data"))))(g)
        ex = np.asarray(ex)
        r_full = np.asarray(r)
        g_full = np.asarray(g)
        # the DGC guarantee is per-WORKER communication volume: each
        # worker sends only its top ~10% (the union across workers can
        # be denser); sent = grad - residual per shard
        sent = (g_full - r_full).reshape(N, 128)
        k = int(round(128 * 0.1))
        per_worker_nnz = (sent != 0).sum(axis=1)
        assert (per_worker_nnz <= k + 2).all(), per_worker_nnz
        assert (per_worker_nnz >= 1).all()
        # union bound on the exchanged density
        assert (ex != 0).mean() <= (k + 2) * N / 128.0
        # the exchange is exactly the mean of what was sent
        np.testing.assert_allclose(sent.sum(0) / N, ex,
                                   rtol=1e-5, atol=1e-6)

    def test_converges_with_error_feedback(self, mesh):
        """Least squares with 99% sparsity: error feedback must still
        reach near the dense solution."""
        rng = np.random.RandomState(2)
        dim = 256
        w_true = rng.randn(dim).astype("float32")
        X = rng.randn(N * 16, dim).astype("float32")
        y = X @ w_true

        def local_grad(w, Xl, yl):
            e = Xl @ w - yl
            return Xl.T @ e / Xl.shape[0]

        def step(w, state, Xl, yl):
            g = local_grad(w, Xl, yl)
            (w2,), (s2,) = dgc_momentum_step(
                (w,), (g,), (state,), 0.003, "data",
                sparsity=0.99, momentum_coef=0.9)
            return w2, s2

        # per the dgc.py state contract: residual/momentum are per-worker
        # and ride the shard_map boundary SHARDED on the data axis
        sharded = shard_map(
            step, mesh=mesh,
            in_specs=(P(), (P("data"), P("data")), P("data"), P("data")),
            out_specs=(P(), (P("data"), P("data"))), check_vma=False)
        stepj = jax.jit(sharded)

        w = jnp.zeros(dim)
        state = (jnp.zeros(N * dim), jnp.zeros(N * dim))
        Xd = jnp.asarray(X)
        yd = jnp.asarray(y)
        err0 = float(jnp.linalg.norm(Xd @ w - yd))
        for _ in range(600):
            w, state = stepj(w, state, Xd, yd)
        err = float(jnp.linalg.norm(Xd @ w - yd))
        assert err < 0.05 * err0, (err0, err)


    def test_sparse_grad_below_k_still_sent(self, mesh):
        """Fewer nonzeros than k: the nonzero entries must still be
        exchanged (per-element zero guard, not an all-or-nothing one)."""
        g = jnp.zeros(N * 128).at[jnp.arange(N) * 128 + 5].set(2.0)

        def f(g):
            z = jnp.zeros_like(g)
            ex, r, m = dgc_exchange(g, z, z, "data", sparsity=0.5,
                                    momentum_coef=0.0)
            return ex

        ex = np.asarray(jax.jit(shard_map(
            f, mesh=mesh, in_specs=P("data"), out_specs=P()))(g))
        # every worker holds 2.0 at LOCAL index 5 → mean = 2.0
        assert ex[5] == pytest.approx(2.0)
        assert np.count_nonzero(ex) == 1

    def test_nesterov_branch(self, mesh):
        """Nesterov accumulation: sparsity 0 + error feedback cleared
        every step ⇒ matches the closed-form nesterov-momentum update."""
        rng = np.random.RandomState(4)
        g = jnp.asarray(rng.randn(N * 16).astype("float32"))

        def f(g):
            z = jnp.zeros_like(g)
            m0 = jnp.asarray(0.5) * jnp.ones_like(g)
            ex, r, m = dgc_exchange(g, z, m0, "data", sparsity=0.0,
                                    momentum_coef=0.9,
                                    use_nesterov=True)
            return ex, r, m

        ex, r, m = jax.jit(shard_map(
            f, mesh=mesh, in_specs=P("data"),
            out_specs=(P(), P("data"), P("data"))))(g)
        g_np = np.asarray(g).reshape(N, 16)
        m_new = 0.9 * 0.5 + g_np  # per-worker
        acc = 0.9 * m_new + g_np
        np.testing.assert_allclose(np.asarray(ex), acc.mean(0),
                                   rtol=1e-5, atol=1e-6)
        # everything was selected → local state fully cleared
        assert np.all(np.asarray(r) == 0) and np.all(np.asarray(m) == 0)
